"""Pallas DMA embedding-gather kernel: parity + gradient vs jnp.take
(interpret mode on CPU; the kernel engages for real on TPU at the
measured _MIN_ROWS gate)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.gather import embedding_gather, _eligible, _BLOCK


@pytest.fixture(autouse=True)
def _force_kernel(monkeypatch):
    """The N >= _MIN_ROWS gate reflects TPU measurement; these are
    KERNEL parity tests, so lower it to test at small sizes."""
    from paddle_tpu.ops import gather
    monkeypatch.setattr(gather, '_MIN_ROWS', _BLOCK)


def test_gather_parity_and_grad(monkeypatch):
    from paddle_tpu.ops import gather
    calls = []
    real = gather._pallas_gather
    monkeypatch.setattr(gather, '_pallas_gather',
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(640, 128), jnp.float32)
    idx = jnp.asarray(rng.randint(0, 640, (_BLOCK * 2,)), jnp.int32)
    assert _eligible(w, idx)
    out = embedding_gather(w, idx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(w)[idx],
                               rtol=1e-6)
    # gradient: scatter-add with duplicate indices.  The kernel must
    # actually engage under jax.grad (a dtype object in the vjp
    # residuals used to raise at trace time and silently reroute every
    # training step to the jnp.take fallback — ADVICE r4).
    n_fwd_calls = len(calls)
    assert n_fwd_calls > 0
    g = jax.grad(lambda w: (embedding_gather(w, idx) ** 2).sum())(w)
    assert len(calls) > n_fwd_calls, 'kernel path did not run under grad'
    gr = jax.grad(lambda w: (jnp.take(w, idx, axis=0) ** 2).sum())(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-5)


def test_gather_multi_dim_ids_and_fallback():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(64, 128), jnp.float32)
    idx2d = jnp.asarray(rng.randint(0, 64, (2, _BLOCK)), jnp.int32)
    out = embedding_gather(w, idx2d)
    assert out.shape == (2, _BLOCK, 128)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(w)[np.asarray(idx2d)], rtol=1e-6)
    # ineligible (tiny / misaligned) shapes fall back to jnp.take
    small = jnp.asarray([3, 1], jnp.int32)
    np.testing.assert_allclose(np.asarray(embedding_gather(w, small)),
                               np.asarray(w)[[3, 1]], rtol=1e-6)


def test_gather_oob_ids_nan_fill_like_take():
    """Out-of-range ids must NaN-fill (jnp.take's default OOB
    semantics, which check_nan surfaces), not read unchecked HBM
    addresses."""
    rng = np.random.RandomState(2)
    w = jnp.asarray(rng.randn(64, 128), jnp.float32)
    idx = np.asarray(rng.randint(0, 64, (_BLOCK,)), np.int32)
    idx[0], idx[1] = 1000, -5  # OOV fills NaN; -5 wraps to row 59
    out = embedding_gather(w, jnp.asarray(idx))
    ref = jnp.take(w, jnp.asarray(idx), axis=0)
    assert np.isnan(np.asarray(out)[0]).all()
    np.testing.assert_allclose(np.asarray(out)[1], np.asarray(w)[59],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
