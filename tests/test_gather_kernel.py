"""Pallas DMA embedding-gather kernel: parity + gradient vs jnp.take
(interpret mode on CPU; the kernel engages for real on TPU at the
measured _MIN_ROWS gate)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.gather import embedding_gather, _eligible, _BLOCK


@pytest.fixture(autouse=True)
def _force_kernel(monkeypatch):
    """The N >= _MIN_ROWS gate reflects TPU measurement; these are
    KERNEL parity tests, so lower it to test at small sizes."""
    from paddle_tpu.ops import gather
    monkeypatch.setattr(gather, '_MIN_ROWS', _BLOCK)


def test_pallas_gather_kernel_runs_no_fallback_possible():
    """Drive the pallas kernel DIRECTLY in interpret mode — no try/except
    between this test and the kernel, so an API drift (BENCH_r04's dtype
    TypeError, the later pltpu.MemorySpace rename) fails HERE instead of
    silently rerouting production training to jnp.take."""
    from paddle_tpu.ops.gather import _pallas_gather
    rng = np.random.RandomState(7)
    w = jnp.asarray(rng.randn(512, 128), jnp.float32)
    idx = jnp.asarray(rng.randint(0, 512, (_BLOCK,)), jnp.int32)
    out = _pallas_gather(w, idx, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(w)[idx],
                               rtol=1e-6)


def test_embedding_gather_no_silent_fallback(monkeypatch):
    """The full embedding_gather path must run WITHOUT emitting the
    fallback warning (warnings-as-errors): the kernel path either works
    or this test fails — degradation can't hide."""
    import warnings
    rng = np.random.RandomState(3)
    w = jnp.asarray(rng.randn(640, 128), jnp.float32)
    idx = jnp.asarray(rng.randint(0, 640, (_BLOCK,)), jnp.int32)
    with warnings.catch_warnings():
        warnings.simplefilter('error')
        out = embedding_gather(w, idx)
        jax.grad(lambda w: (embedding_gather(w, idx) ** 2).sum())(w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(w)[idx],
                               rtol=1e-6)


def test_strict_kernels_raises_instead_of_falling_back(monkeypatch):
    """PT_STRICT_KERNELS=1 turns a kernel failure into a raise with the
    underlying error; default mode counts kernel.fallbacks."""
    from paddle_tpu.ops import gather
    import paddle_tpu.observability as obs

    def _boom(*a, **k):
        raise ValueError('induced kernel failure')

    monkeypatch.setattr(gather, '_kernel_gather', _boom)
    rng = np.random.RandomState(4)
    w = jnp.asarray(rng.randn(640, 128), jnp.float32)
    idx = jnp.asarray(rng.randint(0, 640, (_BLOCK,)), jnp.int32)
    before = obs.counters().get('kernel.fallbacks') or 0
    with pytest.warns(UserWarning, match='embedding_gather'):
        from paddle_tpu.ops import _fallback
        monkeypatch.setattr(_fallback, '_warned', set())
        out = embedding_gather(w, idx)   # degrades to jnp.take, loudly
    np.testing.assert_allclose(np.asarray(out), np.asarray(w)[idx],
                               rtol=1e-6)
    assert (obs.counters().get('kernel.fallbacks') or 0) == before + 1
    monkeypatch.setenv('PT_STRICT_KERNELS', '1')
    with pytest.raises(RuntimeError, match='PT_STRICT_KERNELS'):
        embedding_gather(w, idx)


def test_gather_parity_and_grad(monkeypatch):
    from paddle_tpu.ops import gather
    calls = []
    real = gather._pallas_gather
    monkeypatch.setattr(gather, '_pallas_gather',
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(640, 128), jnp.float32)
    idx = jnp.asarray(rng.randint(0, 640, (_BLOCK * 2,)), jnp.int32)
    assert _eligible(w, idx)
    out = embedding_gather(w, idx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(w)[idx],
                               rtol=1e-6)
    # gradient: scatter-add with duplicate indices.  The kernel must
    # actually engage under jax.grad (a dtype object in the vjp
    # residuals used to raise at trace time and silently reroute every
    # training step to the jnp.take fallback — ADVICE r4).
    n_fwd_calls = len(calls)
    assert n_fwd_calls > 0
    g = jax.grad(lambda w: (embedding_gather(w, idx) ** 2).sum())(w)
    assert len(calls) > n_fwd_calls, 'kernel path did not run under grad'
    gr = jax.grad(lambda w: (jnp.take(w, idx, axis=0) ** 2).sum())(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-5)


def test_gather_multi_dim_ids_and_fallback():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(64, 128), jnp.float32)
    idx2d = jnp.asarray(rng.randint(0, 64, (2, _BLOCK)), jnp.int32)
    out = embedding_gather(w, idx2d)
    assert out.shape == (2, _BLOCK, 128)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(w)[np.asarray(idx2d)], rtol=1e-6)
    # ineligible (tiny / misaligned) shapes fall back to jnp.take
    small = jnp.asarray([3, 1], jnp.int32)
    np.testing.assert_allclose(np.asarray(embedding_gather(w, small)),
                               np.asarray(w)[[3, 1]], rtol=1e-6)


def test_gather_oob_ids_nan_fill_like_take():
    """Out-of-range ids must NaN-fill (jnp.take's default OOB
    semantics, which check_nan surfaces), not read unchecked HBM
    addresses."""
    rng = np.random.RandomState(2)
    w = jnp.asarray(rng.randn(64, 128), jnp.float32)
    idx = np.asarray(rng.randint(0, 64, (_BLOCK,)), np.int32)
    idx[0], idx[1] = 1000, -5  # OOV fills NaN; -5 wraps to row 59
    out = embedding_gather(w, jnp.asarray(idx))
    ref = jnp.take(w, jnp.asarray(idx), axis=0)
    assert np.isnan(np.asarray(out)[0]).all()
    np.testing.assert_allclose(np.asarray(out)[1], np.asarray(w)[59],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
