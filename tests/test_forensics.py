"""Forensic probe lowering (train/forensics.py + the executor's
ForensicProbes collector): per-op finite probes, fused sub-op
granularity, row-bisection helpers, and investigation guard rails.
The end-to-end trip->report->quarantine->heal path lives in
test_resilience.py; these are the unit seams under it."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.core import passes
from paddle_tpu.testing import faults
from paddle_tpu.train import forensics


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _probe_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            h = fluid.layers.fc(x, 3, act='relu')
            out = fluid.layers.reduce_mean(h)
    return main, startup, out


# ----------------------------------------------------------- probe lowering

def test_probes_flag_first_bad_op_with_source_loc():
    main, startup, out = _probe_program()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        runner = forensics._Runner(exe, main, ('x',), (out.name,))
        ok, probes, _ = runner.step(
            scope, {'x': np.ones((2, 4), 'float32')}, 0)
        meta = runner.collector.meta
        assert meta, 'no probes collected'
        # one [all_finite, nonfinite_count, max_abs] row per probed op
        assert ok and probes.shape == (len(meta), 3)
        assert (probes[:, 0] > 0.5).all()
        # a poisoned feed flips the verdict, and the FIRST false probe is
        # the op that consumed x — same position the analyzer stamped
        block = main.global_block()
        want = next(op for op in block.ops
                    if any('x' in (op.inputs.get(k) or [])
                           for k in op.inputs))
        ok, probes, _ = runner.step(
            scope, {'x': np.full((2, 4), np.nan, 'float32')}, 0)
        assert not ok
        first = min(j for j in range(probes.shape[0])
                    if probes[j, 0] < 0.5)
        m = meta[first]
        assert m['op_type'] == want.type
        assert m['source_loc'], 'probe must carry the op source_loc'
        assert probes[first, 1] > 0          # nonfinite element count


def test_fused_groups_probe_at_sub_op_granularity():
    """The production executor fuses elementwise chains into one op; the
    forensic lowering must still see INSIDE the group — one probe per
    sub-op, named fused:<type>."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        h = fluid.layers.scale(x, scale=2.0, bias=1.0)
        h = fluid.layers.relu(h)
        out = fluid.layers.scale(h, scale=0.5)
    opt, _stats = passes.optimize_program(main, (out.name,))
    assert [op.type for op in opt.global_block().ops] == \
        ['fused_elementwise']
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        runner = forensics._Runner(exe, opt, ('x',), (out.name,))
        ok, probes, _ = runner.step(
            scope, {'x': np.ones((2, 4), 'float32')}, 0)
        types = [m['op_type'] for m in runner.collector.meta]
        assert 'fused:scale' in types and 'fused:relu' in types
        assert ok and probes.shape[0] == len(types)


# ------------------------------------------------------------- row phase

def test_delta_rows_finds_culprits_in_both_halves():
    culprits = {1, 6}

    def clean_without(rows_out):
        return culprits <= set(rows_out)

    got = forensics._delta_rows(list(range(8)), [], clean_without)
    assert sorted(got) == [1, 6]


def test_overflow_row_named_by_substitution_bisection():
    """A row that is FINITE in the feed but overflows inside the step
    (so the feed_scan fast path finds nothing) must still be named, via
    zero-substitution bisection."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.reduce_mean(fluid.layers.square(x))
    feed = np.ones((4, 4), 'float32')
    feed[2] = 1e30                  # finite in the feed, inf after square
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        runner = forensics._Runner(exe, main, ('x',), (out.name,))
        report = forensics.ForensicReport()
        forensics._bisect(runner, scope, [(5, {'x': feed})], 5, report,
                          None, 24)
    assert report.tripped and report.step == 5
    assert report.op_type == 'square'
    assert report.rows == [2] and report.row_method == 'substitution'
    assert report.sample_indices == [5 * 4 + 2]   # step*batch + row
    assert report.probe_launches >= 2


def test_state_borne_poison_yields_state_verdict():
    """When the carried state (a param) is already poisoned, even a fully
    zeroed batch trips — forensics must say 'state', not invent rows."""
    main, startup, out = _probe_program()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w = np.array(np.asarray(scope.get('fc_0.w_0')), copy=True)
        w[0, 0] = np.nan
        scope.set('fc_0.w_0', w)
        runner = forensics._Runner(exe, main, ('x',), (out.name,))
        report = forensics.ForensicReport()
        forensics._bisect(runner, scope,
                          [(0, {'x': np.ones((2, 4), 'float32')})],
                          0, report, None, 8)
    assert report.tripped and report.step == 0
    assert report.rows is None and report.row_method == 'state'


# ------------------------------------------------------------ guard rails

def test_investigate_aborts_on_missing_meta_and_window_gap():
    main, startup, out = _probe_program()
    exe = fluid.Executor()
    ck = type('Ck', (), {})()
    ck.executor = exe
    rec = forensics.LaunchRecord(main, {'x': np.ones((2, 4), 'float32')},
                                 None, [out], 7)
    a0 = obs.counters().get('recovery.forensics_aborted') or 0
    # no restored META: nothing to align the replay window against
    assert forensics.investigate(ck, [rec], meta=None) is None
    # a gap between the checkpoint step and the buffered window would
    # mis-align RNG streams — refuse rather than replay garbage
    assert forensics.investigate(ck, [rec], meta={'step_id': 3}) is None
    assert obs.counters().get('recovery.forensics_aborted') == a0 + 2
