"""contrib.decoder: StateCell / TrainingDecoder / BeamSearchDecoder.

Model: reference contrib/tests/test_beam_search_decoder.py (train a tiny
seq2seq with the StateCell API, then beam-decode with shared weights).
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib import (InitState, StateCell, TrainingDecoder,
                                BeamSearchDecoder)
from paddle_tpu.core.lod import create_lod_tensor

V, E, H = 16, 8, 16
END = 1


def _build_cell(enc_h):
    init = InitState(init=enc_h)
    cell = StateCell(inputs={'x': None}, states={'h': init},
                     out_state='h')

    @cell.state_updater
    def updater(state_cell):
        x = state_cell.get_input('x')
        h = state_cell.get_state('h')
        nh = layers.fc(layers.concat([x, h], axis=1), H, act='tanh',
                       param_attr=fluid.ParamAttr(name='cell_fc.w'),
                       bias_attr=fluid.ParamAttr(name='cell_fc.b'))
        state_cell.set_state('h', nh)
    return cell


def test_training_decoder_trains_and_beam_decoder_decodes():
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            src = fluid.layers.data('src', shape=[1], dtype='int64',
                                    lod_level=1)
            trg = fluid.layers.data('trg', shape=[1], dtype='int64',
                                    lod_level=1)
            lab = fluid.layers.data('lab', shape=[1], dtype='int64',
                                    lod_level=1)
            semb = layers.embedding(
                src, size=[V, E],
                param_attr=fluid.ParamAttr(name='src_emb'))
            enc_h = layers.fc(layers.sequence_pool(semb, 'last'), H,
                              act='tanh',
                              param_attr=fluid.ParamAttr(name='enc.w'))
            cell = _build_cell(enc_h)
            temb = layers.embedding(
                trg, size=[V, E],
                param_attr=fluid.ParamAttr(name='trg_emb'))
            decoder = TrainingDecoder(cell)
            with decoder.block():
                word = decoder.step_input(temb)
                cell.compute_state(inputs={'x': word})
                cell.update_states()
                decoder.output(cell.get_state('h'))
            dec = decoder()
            logits = layers.fc(
                dec, V, param_attr=fluid.ParamAttr(name='dec_fc.w'),
                bias_attr=fluid.ParamAttr(name='dec_fc.b'))
            ce = layers.softmax_with_cross_entropy(logits, lab,
                                                   soft_label=False)
            from paddle_tpu.layers.nn import _copy_lod, _len_var
            _copy_lod(lab, ce)
            per_seq = layers.sequence_pool(ce, 'sum')
            n_tok = layers.cast(layers.reduce_sum(_len_var(lab)),
                                'float32')
            loss = layers.reduce_sum(per_seq) / n_tok
            fluid.optimizer.AdamOptimizer(0.05).minimize(loss)

    def batch(n=8):
        lens = rng.randint(2, 5, size=n)
        srcs, trgs, labs = [], [], []
        for L in lens:
            s = rng.randint(2, V, (L, 1)).astype('int64')
            srcs.append(s)
            trgs.append(np.roll(s, 1, axis=0))
            # toy task: always emit the source's LAST token
            labs.append(np.full((L, 1), s[-1, 0], 'int64'))
        return {'src': create_lod_tensor(srcs),
                'trg': create_lod_tensor(trgs),
                'lab': create_lod_tensor(labs)}

    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(120):
            lv, = exe.run(main, feed=batch(), fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # ---- beam decode with the trained weights (shared by name)
        infer, istartup = fluid.Program(), fluid.Program()
        with fluid.program_guard(infer, istartup):
            with fluid.unique_name.guard():
                src_i = fluid.layers.data('src', shape=[1], dtype='int64',
                                          lod_level=1)
                semb_i = layers.embedding(
                    src_i, size=[V, E],
                    param_attr=fluid.ParamAttr(name='src_emb'))
                enc_i = layers.fc(
                    layers.sequence_pool(semb_i, 'last'), H, act='tanh',
                    param_attr=fluid.ParamAttr(name='enc.w'))
                cell_i = _build_cell(enc_i)
                init_ids = fluid.layers.data('init_ids', shape=[1],
                                             dtype='int64')
                init_scores = fluid.layers.data('init_scores', shape=[1],
                                                dtype='float32')
                bs = BeamSearchDecoder(
                    cell_i, init_ids, init_scores, target_dict_dim=V,
                    word_dim=E, max_len=4, beam_size=2, end_id=END,
                    param_attr=fluid.ParamAttr(name='dec_fc.w'),
                    bias_attr=fluid.ParamAttr(name='dec_fc.b'),
                    emb_param_attr=fluid.ParamAttr(name='trg_emb'))
                bs.decode()
                tr_ids, tr_scores = bs()
        feed = batch(4)
        B = 4
        ids_v, sc_v = exe.run(
            infer,
            feed={'src': feed['src'],
                  'init_ids': np.zeros((B, 1), 'int64'),
                  'init_scores': np.zeros((B, 1), 'float32')},
            fetch_list=[tr_ids, tr_scores])
    ids_v = np.asarray(ids_v)          # [B*beam, max_len]
    assert ids_v.shape == (B * 2, 4)
    # the learned rule: first decoded token == each source's last token
    last_tok = feed['src'].padded[
        np.arange(B), feed['src'].lengths - 1, 0]
    top_beam_first = ids_v[0::2, 0]    # beam 0 of each source
    hits = (top_beam_first == last_tok).mean()
    assert hits >= 0.75, (top_beam_first, last_tok)


def test_state_cell_validations():
    import pytest
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        cell = StateCell(inputs={'x': None},
                         states={'h': InitState(init=x)}, out_state='h')
        with pytest.raises(ValueError, match='state_updater'):
            cell.compute_state({'x': x})

        @cell.state_updater
        def up(c):
            c.set_state('h', c.get_input('x'))
        with pytest.raises(ValueError, match='unknown state'):
            cell.get_state('nope')
        with pytest.raises(ValueError, match='outside a decoder'):
            cell.update_states()
