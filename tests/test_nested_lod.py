"""Nested (2-level) LoD: lengths-of-lengths companions, converters, the
feed->op->fetch round trip, and beam_search_decode's reference-shaped
2-level output.  Model: reference python/paddle/fluid/lod_tensor.py
docstring examples (2-level sentence->word nesting) and
beam_search_decode_op.cc (source->hypothesis->token)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import LoDTensor, create_lod_tensor


def test_two_level_create_from_packed_reference_convention():
    """The reference's documented 2-level example shape: 2 outer groups
    holding [2, 1] inner sequences of word counts [2, 3, 1] -> packed
    data of 6 words, offset LoD [[0, 2, 3], [0, 2, 5, 6]]."""
    packed = np.arange(6).reshape(6, 1).astype('int64')
    t = create_lod_tensor(packed, [[2, 1], [2, 3, 1]], None)
    assert t.lod_level == 2
    assert t.recursive_sequence_lengths() == [[2, 1], [2, 3, 1]]
    assert t.lod() == [[0, 2, 3], [0, 2, 5, 6]]
    assert t.padded.shape == (3, 3, 1)
    # inner rows split at offsets 0,2,5,6
    np.testing.assert_array_equal(t.rows()[0][:, 0], [0, 1])
    np.testing.assert_array_equal(t.rows()[1][:, 0], [2, 3, 4])
    np.testing.assert_array_equal(t.rows()[2][:, 0], [5])
    # nested view groups rows [0,1] under group 0, [2] under group 1
    nested = t.nested_rows()
    assert [len(g) for g in nested] == [2, 1]
    # packed round-trip is exact
    back, lens = t.to_packed()
    np.testing.assert_array_equal(back, packed)
    assert lens == [[2, 1], [2, 3, 1]]


def test_two_level_create_from_nested_list():
    data = [[[1, 2], [3, 4, 5]], [[6]]]
    t = create_lod_tensor(data, [[2, 1], [2, 3, 1]], None)
    assert t.lod_level == 2
    assert t.recursive_sequence_lengths() == [[2, 1], [2, 3, 1]]
    np.testing.assert_array_equal(t.flatten_rows()[:, 0], [1, 2, 3, 4, 5, 6])


def test_one_level_unchanged():
    t = create_lod_tensor(np.arange(5).reshape(5, 1), [[3, 2]], None)
    assert t.lod_level == 1
    assert t.lod() == [[0, 3, 5]]
    # reference list convention: flat list of sequences + 1-level lens
    t2 = create_lod_tensor([[1, 2], [3, 4, 5]], [[2, 3]], None)
    np.testing.assert_array_equal(t2.flatten_rows()[:, 0], [1, 2, 3, 4, 5])


def test_two_level_feed_op_fetch_roundtrip():
    """A 2-level LoDTensor feeds (padded + @LENGTH + @OUTERLEN), a
    masked sequence op consumes the inner lengths, and the outer
    grouping is fetchable to rebuild the 2-level result."""
    x = layers.data('x', shape=[1], dtype='float32', lod_level=2)
    pooled = layers.sequence_pool(x, 'sum')   # sums valid tokens per row
    outer = x.block.var('x@OUTERLEN')
    inner = x.block.var('x@LENGTH')
    t = create_lod_tensor(
        np.array([[1.], [2.], [3.], [4.], [5.], [6.]], 'float32'),
        [[2, 1], [2, 3, 1]], None)
    exe = fluid.Executor()
    pv, ov, iv = exe.run(feed={'x': t}, fetch_list=[pooled, outer, inner])
    np.testing.assert_allclose(pv.ravel(), [3., 12., 6.])  # per-inner sums
    # rebuild the 2-level structure on the host side
    out = LoDTensor(pv.reshape(-1, 1, 1), np.ones(3, np.int32), ov)
    assert [len(g) for g in out.nested_rows()] == [2, 1]
    np.testing.assert_array_equal(iv, [2, 3, 1])


def test_beam_search_decode_two_level_output():
    """Hand-checked backtrace (reference beam_search_decode_op.cc
    semantics): 1 source x 2 beams, 3 steps; hypothesis 0 ends at step 2
    (end token kept -> 3 tokens), hypothesis 1 never ends (3 tokens);
    level-0 fan-out is beam_size per source."""
    T, R = 3, 2
    ids = layers.data('ids', shape=[T, R, 1], dtype='int64',
                      append_batch_size=False, stop_gradient=True)
    scores = layers.data('sc', shape=[T, R, 1], dtype='float32',
                         append_batch_size=False, stop_gradient=True)
    sid, ssc = layers.beam_search_decode(ids, scores, beam_size=2, end_id=0)
    assert sid.lod_level == 2
    lens = sid.block.var(sid.lod_length_name)
    outer = sid.block.var(sid.lod_outer_length_name)
    # step tokens: t0 [5, 7], t1 [9, 8], t2 [0(end), 6]; identity parents
    feed = {'ids': np.array([[[5], [7]], [[9], [8]], [[0], [6]]], 'int64'),
            'sc': np.ones((T, R, 1), 'float32')}
    rid, rlen, router = fluid.Executor().run(
        feed=feed, fetch_list=[sid, lens, outer])
    np.testing.assert_array_equal(rid, [[5, 9, 0], [7, 8, 6]])
    np.testing.assert_array_equal(rlen, [3, 3])   # end token INCLUDED
    np.testing.assert_array_equal(router, [2])    # 1 source x beam 2
    # early end: hypothesis 0 ends at step 0 -> length 1
    feed2 = {'ids': np.array([[[0], [7]], [[0], [8]], [[0], [6]]], 'int64'),
             'sc': np.ones((T, R, 1), 'float32')}
    rid2, rlen2 = fluid.Executor().run(feed=feed2, fetch_list=[sid, lens])
    np.testing.assert_array_equal(rlen2, [1, 3])
    # 2-level reconstruction: source 0 has hyps [[0]] and [7,8,6]
    out = LoDTensor(rid2[:, :, None], rlen2, router)
    nested = out.nested_rows()
    np.testing.assert_array_equal(nested[0][0][:, 0], [0])
    np.testing.assert_array_equal(nested[0][1][:, 0], [7, 8, 6])
