"""Parallel stack tests on the 8-virtual-device CPU mesh (conftest)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.ring_attention import ring_attention


def _full_attention(q, k, v, causal=False):
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k) * (q.shape[-1] ** -0.5)
    if causal:
        T = q.shape[2]
        mask = np.tril(np.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    return jnp.einsum('bhqk,bhkd->bhqd', jax.nn.softmax(s, axis=-1), v)


@pytest.mark.parametrize('causal', [False, True])
def test_ring_attention_matches_full(causal):
    mesh = make_mesh(data=2, seq=4, model=1, pipe=1)
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 32, 8
    q = rng.randn(B, H, T, D).astype('float32')
    k = rng.randn(B, H, T, D).astype('float32')
    v = rng.randn(B, H, T, D).astype('float32')
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=causal)
    ref = _full_attention(jnp.array(q), jnp.array(k), jnp.array(v), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grads_match_full():
    mesh = make_mesh(data=1, seq=4, model=1, pipe=1,
                     devices=jax.devices()[:4])
    rng = np.random.RandomState(1)
    B, H, T, D = 1, 2, 16, 4
    q = rng.randn(B, H, T, D).astype('float32')
    k = rng.randn(B, H, T, D).astype('float32')
    v = rng.randn(B, H, T, D).astype('float32')

    def loss_ring(q, k, v):
        with mesh:
            return ring_attention(q, k, v, mesh, causal=True).sum()

    def loss_full(q, k, v):
        return _full_attention(q, k, v, causal=True).sum()

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(
        jnp.array(q), jnp.array(k), jnp.array(v))
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def _mnist_like_program(seed):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            img = fluid.layers.data('img', shape=[32], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(img, 64, act='relu')
            logits = fluid.layers.fc(h, 10)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(
                    fluid.layers.softmax(logits), lbl))
            fluid.optimizer.SGD(0.5).minimize(loss)
    return main, startup, loss


def test_data_parallel_matches_single_device():
    rng = np.random.RandomState(0)
    feed = {'img': rng.randn(16, 32).astype('float32'),
            'lbl': rng.randint(0, 10, (16, 1)).astype('int64')}

    losses = {}
    for tag, mesh in [('single', None),
                      ('dp8', make_mesh(data=8, model=1, pipe=1, seq=1))]:
        main, startup, loss = _mnist_like_program(seed=3)
        if mesh is not None:
            t = fluid.DistributeTranspiler()
            t.transpile(0, program=main, trainers=8)
        exe = fluid.Executor(mesh=mesh)
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            vals = []
            for _ in range(4):
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                vals.append(float(np.asarray(l).ravel()[0]))
        losses[tag] = vals
    np.testing.assert_allclose(losses['single'], losses['dp8'],
                               rtol=1e-5, atol=1e-6)


def test_tp_annotation_and_run():
    from paddle_tpu.models import transformer as tr
    from paddle_tpu.parallel.tp import shard_program_tp
    mesh = make_mesh(data=2, model=4, pipe=1, seq=1)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = tr.transformer(64, 64, max_len=16, n_layer=1, n_head=4,
                                 d_model=32, d_inner=64, dropout=0.0,
                                 label_smooth_eps=0.0)
            fluid.optimizer.Adam(1e-3).minimize(out['loss'])
    applied = shard_program_tp(main)
    assert len(applied) >= 8  # q/k/v/o + fc1/fc2 (+ proj, emb) weights
    rng = np.random.RandomState(0)
    rows = []
    for _ in range(8):
        s = rng.randint(3, 64, (10,))
        rows.append((s, np.concatenate([[0], s]), np.concatenate([s, [1]])))
    feed = tr.make_batch(rows, 16)
    exe = fluid.Executor(mesh=mesh)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with mesh:
            l0, = exe.run(main, feed=feed, fetch_list=[out['loss']])
            l1, = exe.run(main, feed=feed, fetch_list=[out['loss']])
    assert np.isfinite(l0).all() and float(l1[0]) < float(l0[0])


def test_pipeline_matches_sequential():
    from paddle_tpu.parallel.pipeline import (pipeline_apply,
                                              stack_stage_params)
    mesh = make_mesh(data=2, pipe=4, model=1, seq=1)
    rng = np.random.RandomState(0)
    S, B, D = 4, 8, 16
    params = [{'w': rng.randn(D, D).astype('float32') * 0.3,
               'b': rng.randn(D).astype('float32') * 0.1}
              for _ in range(S)]
    x = rng.randn(B, D).astype('float32')

    def stage_fn(p, h):
        return jnp.tanh(h @ p['w'] + p['b'])

    stacked = stack_stage_params(params)
    with mesh:
        out = pipeline_apply(mesh, stage_fn, stacked, jnp.array(x),
                             n_micro=4, data_axis='data')
    ref = jnp.array(x)
    for p in params:
        ref = stage_fn({'w': jnp.array(p['w']), 'b': jnp.array(p['b'])},
                       ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_differentiable():
    from paddle_tpu.parallel.pipeline import (pipeline_apply,
                                              stack_stage_params)
    mesh = make_mesh(data=1, pipe=4, model=1, seq=1,
                     devices=jax.devices()[:4])
    rng = np.random.RandomState(1)
    S, B, D = 4, 4, 8
    params = [{'w': rng.randn(D, D).astype('float32') * 0.3} for _ in
              range(S)]
    x = jnp.array(rng.randn(B, D).astype('float32'))
    stacked = stack_stage_params(params)

    def stage_fn(p, h):
        return jnp.tanh(h @ p['w'])

    def loss_pipe(w):
        with mesh:
            return pipeline_apply(mesh, stage_fn, w, x, n_micro=2).sum()

    def loss_seq(params):
        h = x
        for p in params:
            h = stage_fn(p, h)
        return h.sum()

    gp = jax.grad(loss_pipe)(stacked)
    gs = stack_stage_params(jax.grad(loss_seq)(
        [{'w': jnp.array(p['w'])} for p in params]))
    np.testing.assert_allclose(np.asarray(gp['w']), np.asarray(gs['w']),
                               atol=2e-5, rtol=2e-5)


def test_compiled_program_data_parallel_matches_plain():
    """CompiledProgram().with_data_parallel() through Executor.run must
    train identically to the plain program (the reference's compiled
    path wraps ParallelExecutor; here it partitions the one executable
    over the mesh)."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data('x', shape=[4], dtype='float32')
                y = fluid.layers.data('y', shape=[1], dtype='float32')
                p = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(
                    name='cp_w',
                    initializer=fluid.initializer.Constant(0.5)))
                loss = fluid.layers.reduce_mean(
                    fluid.layers.square_error_cost(p, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(3)
    feed = {'x': rng.rand(8, 4).astype('float32'),
            'y': rng.rand(8, 1).astype('float32')}

    main, startup, loss = build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        plain, = exe.run(main, feed=feed, fetch_list=[loss])
        w_plain = np.asarray(scope.get('cp_w')).copy()

    main2, startup2, loss2 = build()
    compiled = fluid.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name)
    exe2 = fluid.Executor()
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup2)
        dp, = exe2.run(compiled, feed=feed, fetch_list=[loss2])
        w_dp = np.asarray(scope2.get('cp_w'))
    np.testing.assert_allclose(np.asarray(dp).ravel(),
                               np.asarray(plain).ravel(), rtol=1e-5)
    np.testing.assert_allclose(w_dp, w_plain, rtol=1e-5, atol=1e-7)
