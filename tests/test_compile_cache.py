"""Compilation-persistence subsystem (core/compile_cache.py):

  * fingerprint stability — the same program+launch signature hashes the
    same across processes; any keyed component (fetch set, K, AMP,
    check_nan, feed shapes) changes the key
  * warm start — a second FRESH PROCESS over a shared PT_CACHE_DIR loads
    executables from disk instead of compiling (asserted on both the
    cache-hit counters and the compile-time collapse)
  * the in-process LRU bound (PT_EXEC_CACHE_MAX) + eviction counter
  * corrupt disk entries are misses, never errors
  * the two int64 warn-and-truncate regressions stay silent
"""
import json
import os
import pickle
import subprocess
import sys
import warnings

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.core import compile_cache as cc


def _build(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 8, act='relu')
            logits = fluid.layers.fc(h, 3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


FEEDS = {'x': (((2, 4)), 'float32'), 'lbl': ((2, 1), 'int64')}


def _specs():
    return {n: (tuple(s), d) for n, (s, d) in FEEDS.items()}


# ------------------------------------------------------------- fingerprints

def test_fingerprint_components_change_the_key():
    main, _, loss = _build()
    base = cc.launch_fingerprint(main, _specs(), (loss.name,), None, False)
    # same inputs -> same key (and the per-program hash is memoized)
    assert base == cc.launch_fingerprint(main, _specs(), (loss.name,),
                                         None, False)
    # each keyed component perturbs the hash
    assert base != cc.launch_fingerprint(main, _specs(), (loss.name, 'x'),
                                         None, False)       # fetch set
    assert base != cc.launch_fingerprint(main, _specs(), (loss.name,),
                                         4, False)          # steps=K
    assert base != cc.launch_fingerprint(main, _specs(), (loss.name,),
                                         None, True)        # check_nan
    wide = dict(_specs(), x=((5, 4), 'float32'))
    assert base != cc.launch_fingerprint(main, wide, (loss.name,),
                                         None, False)       # feed shape
    main.set_amp(True)
    assert base != cc.launch_fingerprint(main, _specs(), (loss.name,),
                                         None, False)       # AMP policy


def test_fingerprint_stable_across_processes():
    """The key must be a pure function of program+signature+environment —
    no id()s, no process-local serials — or the disk cache could never
    hit across restarts."""
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import sys; sys.path.insert(0, %r)\n"
        "import paddle_tpu as fluid\n"
        "from paddle_tpu.core import compile_cache as cc\n"
        "main, startup = fluid.Program(), fluid.Program()\n"
        "main.random_seed = 7\n"
        "with fluid.program_guard(main, startup):\n"
        "    with fluid.unique_name.guard():\n"
        "        x = fluid.layers.data('x', shape=[4], dtype='float32')\n"
        "        y = fluid.layers.fc(x, 3)\n"
        "        loss = fluid.layers.reduce_mean(y)\n"
        "print(cc.launch_fingerprint(main, {'x': ((2, 4), 'float32')},\n"
        "                            (loss.name,), None, False))\n"
    ) % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fps = set()
    for _ in range(2):
        r = subprocess.run([sys.executable, '-c', code],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        fps.add(r.stdout.strip().splitlines()[-1])
    assert len(fps) == 1, 'fingerprint differs across processes: %s' % fps


def test_program_fingerprint_tracks_edits():
    main, _, _ = _build()
    fp0 = cc.program_fingerprint(main)
    assert fp0 == cc.program_fingerprint(main)
    with fluid.program_guard(main, fluid.Program()):
        fluid.layers.data('extra', shape=[2], dtype='float32')
    assert cc.program_fingerprint(main) != fp0


# --------------------------------------------------------------- warm start

_WARMSTART_CODE = r"""
import os, sys, time
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['PT_CACHE'] = '1'
sys.path.insert(0, sys.argv[1])
os.environ['PT_CACHE_DIR'] = sys.argv[2]
import json
import numpy as np
import paddle_tpu as fluid
import paddle_tpu.observability as obs

main, startup = fluid.Program(), fluid.Program()
main.random_seed = 7
with fluid.program_guard(main, startup):
    with fluid.unique_name.guard():
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 8, act='relu')
        logits = fluid.layers.fc(h, 3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
exe, scope = fluid.Executor(), fluid.Scope()
feed = {'x': np.ones((2, 4), 'float32'), 'lbl': np.zeros((2, 1), 'int64')}
t0 = time.perf_counter()
with fluid.scope_guard(scope):
    exe.run(startup)
    l1, = exe.run(main, feed=feed, fetch_list=[loss])
    ls, = exe.run_steps(main, feed_list=[feed] * 3, fetch_list=[loss])
wall = time.perf_counter() - t0
c = obs.counters()
print(json.dumps({
    'loss': float(np.asarray(l1).ravel()[0]),
    'losses': np.asarray(ls).ravel().tolist(),
    'wall_s': wall,
    'hits': c.get('compile_cache.disk_hits') or 0,
    'misses': c.get('compile_cache.disk_misses') or 0,
    'compile_s': c.get('executor.compile_s') or 0.0,
    'load_s': c.get('compile_cache.load_s') or 0.0,
}))
"""


def _run_warmstart_proc(cache_dir):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != 'PT_CACHE'}
    r = subprocess.run(
        [sys.executable, '-c', _WARMSTART_CODE, repo, str(cache_dir)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_warm_start_across_fresh_processes(tmp_path):
    """The acceptance contract: run the same program twice in FRESH
    processes over one PT_CACHE_DIR — the second must report disk hits,
    zero actual compiles, and materially lower compile time."""
    cold = _run_warmstart_proc(tmp_path / 'cache')
    warm = _run_warmstart_proc(tmp_path / 'cache')
    assert cold['misses'] >= 3 and cold['hits'] == 0
    assert cold['compile_s'] > 0
    assert warm['hits'] >= 3, warm
    assert warm['misses'] == 0, warm
    # no trace happened, so no compile seconds were recorded at all
    assert warm['compile_s'] == 0.0, warm
    # the loaded executable computes the same numbers
    assert warm['loss'] == cold['loss']
    assert warm['losses'] == cold['losses']
    # "materially lower": deserialization must beat trace+compile by a
    # wide margin (measured ~10x; assert 2x to stay CI-noise-proof)
    assert warm['load_s'] < cold['compile_s'] / 2, (warm, cold)


def test_corrupt_disk_entries_are_misses(tmp_path, monkeypatch):
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    disk = cc.DiskCache(str(tmp_path))
    fp = 'ab' + 'cd' * 31
    # truncated garbage
    path = disk._path(fp)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'wb') as f:
        f.write(b'\x80\x04 this is not a pickle')
    assert disk.load(fp) == (None, None)
    assert not os.path.exists(path), 'corrupt entry must be deleted'
    # wrong format version
    with open(path, 'wb') as f:
        pickle.dump({'format': -1, 'fingerprint': fp, 'tier': 'exec',
                     'payload': None}, f)
    assert disk.load(fp) == (None, None)
    assert not os.path.exists(path)


def test_disk_cache_round_trip_in_process(tmp_path, monkeypatch):
    """PT_CACHE on within one process: a second Executor (fresh L1) must
    resolve from disk without tracing."""
    from paddle_tpu.core import executor as em
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    main, startup, loss = _build()
    feed = {'x': np.ones((2, 4), 'float32'),
            'lbl': np.zeros((2, 1), 'int64')}
    exe1, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe1.run(startup)
        a, = exe1.run(main, feed=feed, fetch_list=[loss])
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup)
        tc = em._TRACE_COUNT[0]
        b, = exe2.run(main, feed=feed, fetch_list=[loss])
        assert em._TRACE_COUNT[0] == tc, \
            'second executor must load the AOT executable, not retrace'
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    # the explainer recorded the warm start as a disk_load report
    kinds = [r['kind'] for r in obs.explainer().reports]
    assert 'disk_load' in kinds


# ----------------------------------------------------------------- LRU cap

def test_exec_cache_lru_bound_and_eviction_counter(monkeypatch):
    monkeypatch.setenv('PT_EXEC_CACHE_MAX', '2')
    main, startup, loss = _build()
    exe, scope = fluid.Executor(), fluid.Scope()
    before = obs.counters().get('pt_exec_cache_evictions') or 0
    with fluid.scope_guard(scope):
        exe.run(startup)  # entry 1 (startup program)
        for b in (2, 3, 4, 5):  # distinct feed shapes: distinct entries
            exe.run(main, feed={'x': np.ones((b, 4), 'float32'),
                                'lbl': np.zeros((b, 1), 'int64')},
                    fetch_list=[loss])
    assert len(exe._cache) <= 2
    evictions = (obs.counters().get('pt_exec_cache_evictions') or 0) - before
    assert evictions >= 3, 'LRU bound must evict, and count it'


def test_lru_keeps_recently_used():
    lru = cc.ExecutableLRU(capacity=2)
    lru.put('a', 1)
    lru.put('b', 2)
    assert lru.get('a') == 1      # refresh a
    lru.put('c', 3)               # evicts b, not a
    assert lru.get('a') == 1 and lru.get('b') is None
    assert 'c' in lru and len(lru) == 2


# ------------------------------------------------------- predictor warm start

def test_predictor_warm_starts_from_disk(tmp_path, monkeypatch):
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path / 'cache'))
    from paddle_tpu import inference
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            y = fluid.layers.fc(x, 3, act='softmax')
    exe = fluid.Executor()
    exe.run(startup)
    model_dir = str(tmp_path / 'model')
    fluid.io.save_inference_model(model_dir, ['x'], [y], exe,
                                  main_program=main)
    feed = {'x': np.ones((2, 4), 'float32')}
    r1 = inference.Predictor(model_dir).run(feed)
    hits0 = obs.counters().get('compile_cache.disk_hits') or 0
    r2 = inference.Predictor(model_dir).run(feed)   # fresh L1: disk hit
    hits1 = obs.counters().get('compile_cache.disk_hits') or 0
    assert hits1 == hits0 + 1
    np.testing.assert_array_equal(np.asarray(r1[0]), np.asarray(r2[0]))


# ------------------------------------------------------------ int64 silence

def test_int64_sites_stay_silent():
    """fill_constant / astype / cast asked for int64 route through
    core.dtypes.jax_dtype — no warn-and-truncate from jax may fire.

    Covers both BENCH_r05-tail leak sites: the `jnp.full` inside
    fill_constant (ops/tensor.py) and the in-trace `.astype` path (the
    *_batch_size_like random ops went through convert_dtype, whose
    int64 survives to `.astype` inside the trace).  The np.int64 VALUE
    case pins the _fill_value normalization (a 64-bit numpy scalar from
    program serialization must not reach jnp.full raw).  Runs over the
    full PT_OPT x PT_EMIT matrix so const-fold/fusion replay AND the
    direct-emitter paths are pinned silent too."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            c = fluid.layers.fill_constant([2, 2], 'int64', 7)
            c64 = fluid.layers.fill_constant([2], 'int64', np.int64(9))
            c2 = fluid.layers.cast(c, 'int64') + 1  # fold+fuse fodder
            casted = x.astype('int64')
            topv, topi = fluid.layers.topk(x, k=2)
            blk = main.global_block()
            rnd = blk.create_var(dtype='int64', shape=(-1, 4))
            blk.append_op(
                type='uniform_random_batch_size_like',
                inputs={'Input': x}, outputs={'Out': rnd},
                attrs={'shape': [-1, 4], 'dtype': 'int64',
                       'min': 0.0, 'max': 9.0})
    for pt_opt in ('1', '0'):
        for pt_emit in ('1', '0'):
            os.environ['PT_OPT'] = pt_opt
            os.environ['PT_EMIT'] = pt_emit
            try:
                exe, scope = fluid.Executor(), fluid.Scope()
                with warnings.catch_warnings():
                    warnings.simplefilter('error', UserWarning)
                    with fluid.scope_guard(scope):
                        exe.run(startup)
                        cv, c64v, c2v, iv, tv, rv = exe.run(
                            main, feed={'x': np.ones((3, 4), 'float32')},
                            fetch_list=[c, c64, c2, topi, casted, rnd])
            finally:
                os.environ.pop('PT_OPT', None)
                os.environ.pop('PT_EMIT', None)
            assert cv.ravel()[0] == 7 and c2v.ravel()[0] == 8
            assert c64v.ravel()[0] == 9 and c64v.dtype.kind == 'i'
            assert iv.dtype.kind == 'i' and tv.dtype.kind == 'i'
            assert rv.dtype.kind == 'i' and rv.shape == (3, 4)
