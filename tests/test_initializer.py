"""Per-initializer checks (model: reference unittests
test_initializer.py): exact values for deterministic initializers,
distribution statistics for random ones, fan-in/out scaling for
Xavier/MSRA, the upsampling kernel for Bilinear."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _init_param(shape, init, name):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            layers.create_parameter(shape, 'float32', name=name,
                                    default_initializer=init)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        return np.asarray(scope.get(name))


def test_constant_and_numpy_array():
    v = _init_param([3, 4], fluid.initializer.Constant(2.5), 'c_p')
    np.testing.assert_allclose(v, np.full((3, 4), 2.5, 'float32'))
    arr = np.arange(6, dtype='float32').reshape(2, 3)
    v2 = _init_param([2, 3], fluid.initializer.NumpyArrayInitializer(arr),
                     'np_p')
    np.testing.assert_allclose(v2, arr)


def test_uniform_bounds_and_mean():
    v = _init_param([400, 50], fluid.initializer.Uniform(-0.3, 0.7),
                    'u_p')
    assert v.min() >= -0.3 and v.max() <= 0.7
    assert abs(v.mean() - 0.2) < 0.02
    # distinct values (not a constant fill)
    assert np.unique(v).size > 1000


def test_normal_and_truncated_normal_stats():
    v = _init_param([400, 50], fluid.initializer.Normal(1.0, 2.0), 'n_p')
    assert abs(v.mean() - 1.0) < 0.05
    assert abs(v.std() - 2.0) < 0.05
    t = _init_param([400, 50],
                    fluid.initializer.TruncatedNormal(0.0, 1.0), 't_p')
    # truncation at 2 sigma: no outliers, std shrinks below 1
    assert np.abs(t).max() <= 2.0 + 1e-5
    assert 0.7 < t.std() < 1.0


def test_xavier_fan_scaling():
    # uniform Xavier: bound = sqrt(6 / (fan_in + fan_out))
    v = _init_param([100, 200], fluid.initializer.Xavier(), 'x_p')
    bound = np.sqrt(6.0 / 300)
    assert v.max() <= bound + 1e-6 and v.min() >= -bound - 1e-6
    assert v.std() > bound / 3  # actually filled, not zeros


def test_msra_fan_in_scaling():
    v = _init_param([100, 200], fluid.initializer.MSRA(), 'm_p')
    bound = np.sqrt(6.0 / 100)   # fan_in only
    assert v.max() <= bound + 1e-6 and v.min() >= -bound - 1e-6


def test_bilinear_upsample_kernel():
    # [C_out, C_in, k, k] deconv kernel for 2x upsampling: center weight
    # 1 at the kernel center per channel pair on the diagonal
    v = _init_param([2, 2, 4, 4], fluid.initializer.Bilinear(), 'b_p')
    # factor = ceil(4/2) = 2; center = (2*2 - 1 - 2%2... reference
    # formula gives a separable triangle filter; verify separability and
    # symmetry instead of hard-coding the formula
    k = v[0, 0]
    np.testing.assert_allclose(k, k[::-1, ::-1], rtol=1e-6)  # symmetric
    # rows are scalar multiples of each other (separable outer product)
    r = k[0] / max(k[0].max(), 1e-9)
    for i in range(1, 4):
        ri = k[i] / max(k[i].max(), 1e-9)
        np.testing.assert_allclose(ri, r, rtol=1e-5)


def test_regularizer_l2_shrinks_weights_vs_none():
    """L2 decay must shrink weights faster than no regularizer under the
    same data (model: reference test_regularizer.py, program-level)."""
    def run(reg):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = layers.data('x', shape=[4], dtype='float32')
                y = layers.data('y', shape=[1], dtype='float32')
                p = layers.fc(x, 1, param_attr=fluid.ParamAttr(
                    name='rw', regularizer=reg,
                    initializer=fluid.initializer.Constant(1.0)))
                loss = layers.reduce_mean(
                    layers.square_error_cost(p, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        feed = {'x': rng.rand(8, 4).astype('float32'),
                'y': rng.rand(8, 1).astype('float32')}
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(20):
                exe.run(main, feed=feed, fetch_list=[loss])
            return float(np.abs(np.asarray(scope.get('rw'))).sum())

    w_plain = run(None)
    w_l2 = run(fluid.regularizer.L2Decay(0.5))
    assert w_l2 < w_plain


def test_grad_clip_by_global_norm_limits_update():
    """With clip_norm tiny, one SGD step moves weights by at most
    lr * clip_norm in global norm."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = layers.data('x', shape=[4], dtype='float32')
            p = layers.fc(x, 3, bias_attr=False, param_attr=fluid.ParamAttr(
                name='gw', initializer=fluid.initializer.Constant(1.0)))
            loss = layers.reduce_mean(p) * 1000.0  # huge gradients
            fluid.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(clip_norm=0.01))
            fluid.optimizer.SGD(1.0).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.asarray(scope.get('gw')).copy()
        exe.run(main, feed={'x': np.ones((2, 4), 'float32')},
                fetch_list=[loss])
        w1 = np.asarray(scope.get('gw'))
    delta = np.sqrt(((w1 - w0) ** 2).sum())
    assert delta <= 0.01 + 1e-6
    assert delta > 1e-5  # but it did move
