"""Perf-lab contract tests: record schema validation, ledger
round-trip, counter-vs-timing comparison math, backend-mismatch
refusal, provenance completeness, and subprocess scenario isolation
(a hung child times out into a structured ledger record without
killing the round)."""
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from paddle_tpu.observability import perflab as pl
from paddle_tpu.observability.export import SCHEMA

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERFLAB = os.path.join(REPO, 'tools', 'perflab.py')

PROV = {'backend': 'cpu', 'device_kind': 'cpu', 'platform': 'cpu',
        'jax': '0.0', 'jaxlib': '0.0', 'git_sha': 'deadbeef',
        'python': '3.10', 'fallback': None}


def _metrics(scenario, **over):
    """A minimal valid metrics dict for a scenario: 0 for counters,
    1.0 for timings, 0 for info."""
    m = {}
    for key, spec in pl.metric_specs(scenario).items():
        m[key] = 0 if spec[0] == 'counter' else \
            (1.0 if spec[0] == 'timing' else 0)
    m.update(over)
    return m


def _rec(scenario='fused_adam_micro', prov=None, ts=1.0, **over):
    return pl.build_record(scenario, _metrics(scenario, **over),
                           prov=dict(PROV, **(prov or {})), ts=ts)


# ------------------------------------------------------------- schema
def test_every_scenario_has_a_schema_section():
    names = pl.scenario_names()
    # the run-matrix scenarios plus the tool-bridge sections
    for want in ('train_transformer', 'train_resnet', 'decode_stream',
                 'pod_parallel', 'fused_adam_micro', 'bench',
                 'serve_soak', 'pod_soak'):
        assert want in names
    for name in names:
        specs = pl.metric_specs(name)
        assert specs, name
        for key, spec in specs.items():
            assert spec[0] in ('counter', 'timing', 'info'), (name, key)
            if spec[0] in ('counter', 'timing'):
                assert spec[1] in ('lower', 'higher'), (name, key)


def test_build_record_validates_and_round_trips(tmp_path):
    path = str(tmp_path / 'ledger.jsonl')
    recs = [_rec(ts=1.0), _rec('decode_stream', ts=2.0)]
    for r in recs:
        pl.append_record(path, r)
    back = pl.read_ledger(path)
    assert back == recs
    latest = pl.latest_per_scenario(back)
    assert set(latest) == {'fused_adam_micro', 'decode_stream'}


def test_read_ledger_skips_corrupt_lines(tmp_path):
    path = str(tmp_path / 'ledger.jsonl')
    pl.append_record(path, _rec(ts=1.0))
    with open(path, 'a') as f:
        f.write('not json\n\n{"truncated": \n')
    pl.append_record(path, _rec(ts=2.0))
    back = pl.read_ledger(path)
    assert [r['ts'] for r in back] == [1.0, 2.0]


def test_unknown_scenario_and_metric_rejected():
    with pytest.raises(KeyError):
        pl.metric_specs('no_such_scenario')
    with pytest.raises(ValueError, match='unknown metric'):
        pl.build_record('fused_adam_micro',
                        dict(_metrics('fused_adam_micro'), bogus=1),
                        prov=dict(PROV))
    with pytest.raises(ValueError, match='missing metric'):
        m = _metrics('fused_adam_micro')
        del m['retraces']
        pl.build_record('fused_adam_micro', m, prov=dict(PROV))


def test_counter_must_be_int_timing_may_be_null():
    with pytest.raises(ValueError, match='int'):
        _rec(retraces=1.5)
    with pytest.raises(ValueError, match='int'):
        _rec(retraces=True)
    rec = _rec(fused_adam_ms=None)
    assert rec['metrics']['fused_adam_ms'] is None


def test_provenance_completeness_enforced():
    with pytest.raises(ValueError, match='provenance'):
        pl.validate_record(dict(_rec(), provenance=None))
    for key in pl.PROVENANCE_KEYS:
        if key == 'fallback':  # the one legitimately-null key
            continue
        with pytest.raises(ValueError, match=key):
            _rec(prov={key: None})


def test_error_record_validates_without_metrics():
    rec = pl.error_record('train_resnet', 'timeout', stage='warmup',
                          detail='child exceeded 5s budget', ts=3.0)
    pl.validate_record(rec)
    assert rec['error'] == 'timeout' and rec['stage'] == 'warmup'


# ----------------------------------------------------------- compare
def test_counter_regression_is_exact_zero_tolerance():
    base = _rec(ts=1.0)
    cand = _rec(ts=2.0, kernelgen_fallbacks=1)
    rep = pl.compare_records(base, cand)
    assert rep['status'] == 'regression'
    assert any('kernelgen_fallbacks' in r['metric']
               for r in rep['regressions'])
    # a 'higher'-direction counter regresses on a DROP
    b2 = _rec(ts=1.0, kernelgen_ops=4)
    c2 = _rec(ts=2.0, kernelgen_ops=3)
    assert pl.compare_records(b2, c2)['status'] == 'regression'
    # and improves (not regresses) on a rise
    c3 = _rec(ts=2.0, kernelgen_ops=5)
    rep3 = pl.compare_records(b2, c3)
    assert rep3['status'] == 'ok' and rep3['improvements']


def test_timing_is_noise_bounded_not_exact():
    base = pl.build_record(
        'fused_adam_micro', _metrics('fused_adam_micro',
                                     fused_adam_ms=1.0),
        spread={'fused_adam_ms': [1.0, 1.1]}, prov=dict(PROV), ts=1.0)
    # within the default 50% tolerance: ok
    cand = _rec(ts=2.0, fused_adam_ms=1.3)
    assert pl.compare_records(base, cand)['status'] == 'ok'
    # way past it: regression
    cand = _rec(ts=2.0, fused_adam_ms=4.0)
    rep = pl.compare_records(base, cand)
    assert rep['status'] == 'regression'
    assert any('fused_adam_ms' in r['metric'] for r in rep['regressions'])
    # a null timing on either side is skipped, never a regression
    cand = _rec(ts=2.0, fused_adam_ms=None)
    rep = pl.compare_records(base, cand)
    assert rep['status'] == 'ok'
    assert any('fused_adam_ms' in s['metric'] for s in rep['skipped'])


def test_recorded_spread_widens_timing_tolerance():
    base = pl.build_record(
        'fused_adam_micro', _metrics('fused_adam_micro',
                                     fused_adam_ms=1.0),
        spread={'fused_adam_ms': [1.0, 3.0]},  # 67% observed noise
        prov=dict(PROV), ts=1.0)
    cand = _rec(ts=2.0, fused_adam_ms=1.6)  # past 50%, inside spread
    assert pl.compare_records(base, cand)['status'] == 'ok'


def test_cpu_fallback_vs_tpu_baseline_is_refused():
    base = _rec(ts=1.0, prov={'platform': 'tpu', 'backend': 'tpu',
                              'device_kind': 'TPU v4'})
    cand = _rec(ts=2.0, prov={'backend': 'cpu-fallback',
                              'fallback': 'probe timed out after 60s'})
    rep = pl.compare_records(base, cand)
    assert rep['status'] == 'refused'
    assert 'fallback' in rep['reason']


def test_platform_mismatch_is_refused_not_compared():
    base = _rec(ts=1.0, prov={'platform': 'tpu', 'backend': 'tpu'})
    cand = _rec(ts=2.0)  # honest cpu record, no fallback
    rep = pl.compare_records(base, cand)
    assert rep['status'] == 'refused'


def test_timing_skipped_across_device_kinds_counters_still_gate():
    base = _rec(ts=1.0, prov={'device_kind': 'TPU v4',
                              'platform': 'tpu', 'backend': 'tpu'})
    cand = _rec(ts=2.0, prov={'device_kind': 'TPU v5e',
                              'platform': 'tpu', 'backend': 'tpu'},
                fused_adam_ms=99.0, retraces=3)
    rep = pl.compare_records(base, cand)
    assert rep['status'] == 'regression'  # the counter still gates
    assert any('retraces' in r['metric'] for r in rep['regressions'])
    assert any('device kind differs' in s['detail']
               for s in rep['skipped'])


def test_error_candidate_is_a_regression():
    base = _rec(ts=1.0)
    cand = pl.error_record('fused_adam_micro', 'timeout', ts=2.0)
    rep = pl.compare_records(base, cand)
    assert rep['status'] == 'regression'


def test_compare_ledger_rcs(tmp_path):
    base_doc = pl.bless([_rec(ts=1.0), _rec('train_resnet', ts=1.0)])
    # clean: rc 0
    rc, reps = pl.compare_ledger(
        base_doc, [_rec(ts=2.0), _rec('train_resnet', ts=2.0)])
    assert rc == 0 and all(r['status'] == 'ok' for r in reps)
    # regression: rc 1
    rc, _ = pl.compare_ledger(
        base_doc, [_rec(ts=2.0, retraces=1), _rec('train_resnet', ts=2.0)])
    assert rc == 1
    # a scenario missing from the ledger: rc 1
    rc, reps = pl.compare_ledger(base_doc, [_rec(ts=2.0)])
    assert rc == 1
    assert any(r['status'] == 'missing' for r in reps)
    # refusal outranks regression: rc 2
    rc, _ = pl.compare_ledger(
        base_doc,
        [_rec(ts=2.0, prov={'platform': 'tpu', 'backend': 'tpu'}),
         _rec('train_resnet', ts=2.0, retraces=1)])
    assert rc == 2
    # fail_on=None reports but never fails
    rc, _ = pl.compare_ledger(
        base_doc, [_rec(ts=2.0, retraces=1),
                   _rec('train_resnet', ts=2.0)], fail_on=None)
    assert rc == 0


def test_bless_takes_newest_non_error_record():
    doc = pl.bless([_rec(ts=1.0, retraces=0),
                    _rec(ts=2.0, retraces=2),
                    pl.error_record('fused_adam_micro', 'crash', ts=3.0)])
    assert doc['scenarios']['fused_adam_micro']['metrics']['retraces'] == 2
    assert doc['schema'] == pl.BASELINE_SCHEMA
    with pytest.raises(ValueError):
        pl.bless([pl.error_record('fused_adam_micro', 'crash', ts=1.0)])


# ------------------------------------------- subprocess isolation (CLI)
def _register_test_sections():
    """Mirror tools/perflab.py's PERFLAB_TEST_SCENARIOS=1 registration so
    this process can validate the records its CLI children produce."""
    SCHEMA.setdefault('perflab._quick', (
        ('widgets', ('counter', 'lower')),
        ('widget_ms', ('timing', 'lower', 'ms')),
        ('note', ('info',)),
    ))
    SCHEMA.setdefault('perflab._sleep', (('widgets', ('counter',
                                                      'lower')),))


def _run_cli(args, env_over=None, timeout=120):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PERFLAB_TEST_SCENARIOS='1')
    env.update(env_over or {})
    return subprocess.run(
        [sys.executable, PERFLAB] + args, env=env, cwd=REPO,
        capture_output=True, text=True, timeout=timeout)


def test_hung_child_times_out_into_structured_record(tmp_path):
    _register_test_sections()
    """One hung scenario gets killed at its budget and leaves a
    {"error": "timeout"} ledger record with stage attribution — and the
    NEXT scenario in the round still runs."""
    ledger = str(tmp_path / 'ledger.jsonl')
    p = _run_cli(['run', '--scenarios', '_sleep,_quick',
                  '--ledger', ledger, '--budget-s', '10'])
    assert p.returncode == 1, p.stderr  # the round reports the failure
    recs = pl.read_ledger(ledger)
    assert [r['scenario'] for r in recs] == ['_sleep', '_quick']
    assert recs[0]['error'] == 'timeout'
    assert recs[0]['stage'] == 'sleeping'
    assert 'budget' in recs[0]['detail']
    assert 'error' not in recs[1]


def test_quick_scenario_record_has_full_provenance(tmp_path):
    _register_test_sections()
    ledger = str(tmp_path / 'ledger.jsonl')
    p = _run_cli(['run', '--scenarios', '_quick', '--ledger', ledger])
    assert p.returncode == 0, p.stderr
    rec, = pl.read_ledger(ledger)
    pl.validate_record(rec)
    prov = rec['provenance']
    for key in pl.PROVENANCE_KEYS:
        assert key in prov
        if key != 'fallback':
            assert prov[key], key
    assert prov['platform'] == 'cpu'
    assert prov['fallback'] is None  # deliberate CPU run, not a fallback
    # and `check` accepts it
    p = _run_cli(['check', '--ledger', ledger, '--scenarios', '_quick'])
    assert p.returncode == 0, p.stderr


def test_cli_compare_gate_and_refusal(tmp_path):
    _register_test_sections()
    ledger = str(tmp_path / 'ledger.jsonl')
    baseline = str(tmp_path / 'base.json')
    p = _run_cli(['run', '--scenarios', '_quick', '--ledger', ledger])
    assert p.returncode == 0, p.stderr
    p = _run_cli(['bless', '--ledger', ledger, '--out', baseline])
    assert p.returncode == 0, p.stderr
    p = _run_cli(['compare', '--ledger', ledger, '--baseline', baseline,
                  '--fail-on', 'regression'])
    assert p.returncode == 0, p.stdout + p.stderr
    # regress the counter in a fresh ledger record -> exit 1
    rec, = pl.read_ledger(ledger)
    worse = json.loads(json.dumps(rec))
    worse['metrics']['widgets'] = 5
    worse['ts'] += 1
    pl.append_record(ledger, worse)
    p = _run_cli(['compare', '--ledger', ledger, '--baseline', baseline,
                  '--fail-on', 'regression'])
    assert p.returncode == 1, p.stdout + p.stderr
    # cpu-fallback record vs tpu-blessed baseline -> structured refusal
    doc = json.load(open(baseline))
    for r in doc['scenarios'].values():
        r['provenance'].update(platform='tpu', backend='tpu')
    json.dump(doc, open(baseline, 'w'))
    fb = json.loads(json.dumps(rec))
    fb['provenance'].update(backend='cpu-fallback',
                            fallback='probe timed out')
    fb['ts'] += 2
    pl.append_record(ledger, fb)
    p = _run_cli(['compare', '--ledger', ledger, '--baseline', baseline,
                  '--fail-on', 'regression'])
    assert p.returncode == 2, p.stdout + p.stderr
    assert any(json.loads(l).get('status') == 'refused'
               for l in p.stdout.splitlines() if l.startswith('{'))


# --------------------------------------- int64 warn-and-truncate (bench)
def test_fill_constant_int64_overflow_is_silent():
    """The documented warn-and-truncate contract: an overflowing int64
    fill wraps like the reference C++ cast with NO numpy RuntimeWarning
    (which would be fatal under warnings-as-errors CI)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            c = layers.fill_constant(shape=[2], dtype='int64',
                                     value=2 ** 40)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with warnings.catch_warnings():
            warnings.simplefilter('error')
            out, = exe.run(main_prog, fetch_list=[c])
    # int64 stores as int32 (the TPU warn-and-truncate policy); the
    # out-of-range value truncates (wrap or saturate is backend-defined)
    # — the contract under test is that NO warning escaped above
    assert out.dtype == np.int32
    assert int(out[0]) != 2 ** 40


def test_bench_tiny_warmup_is_warning_clean():
    """The bench code path itself (model build + AMP train step) must
    survive warnings-as-errors — the regression the perf lab's CI gate
    runs under."""
    sys.path.insert(0, REPO)
    try:
        import bench
        import paddle_tpu as fluid
        with warnings.catch_warnings():
            warnings.simplefilter('error', UserWarning)
            warnings.simplefilter('error', RuntimeWarning)
            bench._tiny_warmup(fluid, 128)
    finally:
        sys.path.remove(REPO)
