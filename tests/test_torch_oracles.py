"""Cross-checks of the hardest ops against torch (CPU) as an
independent oracle: CTC loss (forward AND gradient), grid_sampler,
affine_grid — conventions like align_corners and blank handling are
where hand-rolled references can silently agree with their own bugs."""
import numpy as np
import torch
import torch.nn.functional as F
import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import get_op


def _impl(op):
    return get_op(op).impl


def test_warpctc_loss_and_grad_vs_torch():
    rng = np.random.RandomState(0)
    B, T, C, L = 3, 8, 5, 3
    logits = rng.randn(B, T, C).astype('float32')
    labels = rng.randint(1, C, (B, L)).astype('int64')   # 0 is blank
    t_lens = np.array([8, 7, 6], 'int32')
    l_lens = np.array([3, 2, 3], 'int32')

    out = _impl('warpctc')(
        None, {'Logits': jnp.asarray(logits), 'Label': jnp.asarray(labels),
               'LogitsLength': jnp.asarray(t_lens),
               'LabelLength': jnp.asarray(l_lens)}, {'blank': 0})['Loss']
    got = np.asarray(out).ravel()

    tl = torch.from_numpy(logits).requires_grad_(True)
    lp = F.log_softmax(tl, dim=-1).transpose(0, 1)      # [T, B, C]
    ref = F.ctc_loss(lp, torch.from_numpy(labels),
                     torch.from_numpy(t_lens.astype('int64')),
                     torch.from_numpy(l_lens.astype('int64')),
                     blank=0, reduction='none', zero_infinity=False)
    np.testing.assert_allclose(got, ref.detach().numpy(), rtol=1e-4,
                               atol=1e-5)

    # gradients wrt logits
    g = jax.grad(lambda lg: jnp.sum(_impl('warpctc')(
        None, {'Logits': lg, 'Label': jnp.asarray(labels),
               'LogitsLength': jnp.asarray(t_lens),
               'LabelLength': jnp.asarray(l_lens)},
        {'blank': 0})['Loss']))(jnp.asarray(logits))
    ref.sum().backward()
    np.testing.assert_allclose(np.asarray(g), tl.grad.numpy(), rtol=1e-3,
                               atol=1e-4)


def test_grid_sampler_vs_torch():
    rng = np.random.RandomState(1)
    x = rng.rand(2, 3, 6, 6).astype('float32')
    grid = rng.uniform(-1, 1, (2, 4, 4, 2)).astype('float32')
    out = _impl('grid_sampler')(
        None, {'X': jnp.asarray(x), 'Grid': jnp.asarray(grid)}, {})['Output']
    # reference grid_sampler: bilinear, align_corners=True semantics
    ref = F.grid_sample(torch.from_numpy(x), torch.from_numpy(grid),
                        mode='bilinear', padding_mode='zeros',
                        align_corners=True).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_affine_grid_vs_torch():
    theta = np.array([[[1.0, 0.2, 0.1],
                       [-0.1, 0.9, -0.3]],
                      [[0.8, 0.0, 0.0],
                       [0.0, 1.1, 0.2]]], 'float32')
    out = _impl('affine_grid')(
        None, {'Theta': jnp.asarray(theta)},
        {'output_shape': [2, 3, 4, 5]})
    got = np.asarray(list(out.values())[0])
    ref = F.affine_grid(torch.from_numpy(theta), (2, 3, 4, 5),
                        align_corners=True).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
