"""Sequence (LoD) stack tests: padded+lengths representation, masked
sequence ops, scan RNNs (model: reference sequence op unittests +
test_dyn_rnn.py)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


def _lod_feed():
    rows = [np.array([[1.], [2.], [3.]], 'float32'),
            np.array([[4.], [5.]], 'float32')]
    return create_lod_tensor(rows)


def test_create_lod_tensor_roundtrip():
    t = _lod_feed()
    assert t.padded.shape == (2, 3, 1)
    assert t.lengths.tolist() == [3, 2]
    np.testing.assert_allclose(t.flatten_rows().reshape(-1),
                               [1, 2, 3, 4, 5])
    # reference packed convention
    t2 = create_lod_tensor(np.arange(5).reshape(5, 1), [[3, 2]], None)
    assert t2.lengths.tolist() == [3, 2]


def test_sequence_pool_masked():
    x = layers.data('x', shape=[1], dtype='float32', lod_level=1)
    pools = [layers.sequence_pool(x, t)
             for t in ('sum', 'average', 'max', 'last', 'first', 'sqrt')]
    exe = fluid.Executor()
    res = exe.run(feed={'x': _lod_feed()}, fetch_list=pools)
    np.testing.assert_allclose(res[0], [[6.], [9.]])          # sum
    np.testing.assert_allclose(res[1], [[2.], [4.5]])          # avg
    np.testing.assert_allclose(res[2], [[3.], [5.]])           # max
    np.testing.assert_allclose(res[3], [[3.], [5.]])           # last
    np.testing.assert_allclose(res[4], [[1.], [4.]])           # first
    np.testing.assert_allclose(res[5], [[6 / np.sqrt(3)],
                                        [9 / np.sqrt(2)]], rtol=1e-6)


def test_sequence_softmax_ignores_pad():
    x = layers.data('x', shape=[1], dtype='float32', lod_level=1)
    sm = layers.sequence_softmax(x)
    exe = fluid.Executor()
    out, = exe.run(feed={'x': _lod_feed()}, fetch_list=[sm])
    assert abs(out[0].sum() - 1.0) < 1e-5
    assert abs(out[1, :2].sum() - 1.0) < 1e-5
    assert out[1, 2, 0] == 0.0  # padded position zeroed


def test_sequence_reverse_and_first_last():
    x = layers.data('x', shape=[1], dtype='float32', lod_level=1)
    rev = layers.sequence_reverse(x)
    exe = fluid.Executor()
    out, = exe.run(feed={'x': _lod_feed()}, fetch_list=[rev])
    np.testing.assert_allclose(out[0, :3, 0], [3, 2, 1])
    np.testing.assert_allclose(out[1, :2, 0], [5, 4])


def test_sequence_expand():
    x = layers.data('x', shape=[2], dtype='float32')
    y = layers.data('y', shape=[1], dtype='float32', lod_level=1)
    ex = layers.sequence_expand(x, y)
    exe = fluid.Executor()
    out, = exe.run(feed={'x': np.array([[1, 2], [3, 4]], 'float32'),
                         'y': _lod_feed()}, fetch_list=[ex])
    assert out.shape == (2, 3, 2)
    np.testing.assert_allclose(out[0, 0], [1, 2])
    np.testing.assert_allclose(out[1, 1], [3, 4])


def test_sequence_mask_layer():
    lens = layers.data('lens', shape=[], dtype='int64')
    m = layers.sequence_mask(lens, maxlen=5, dtype='float32')
    exe = fluid.Executor()
    out, = exe.run(feed={'lens': np.array([3, 5], 'int64')},
                   fetch_list=[m])
    np.testing.assert_allclose(out, [[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]])


def test_dynamic_lstm_masked_equivalence():
    """LSTM over padded batch == LSTM over each row alone (mask check)."""
    dim = 4
    x = layers.data('x', shape=[4 * dim], dtype='float32', lod_level=1)
    h, c = layers.dynamic_lstm(x, size=4 * dim, use_peepholes=False)
    last = layers.sequence_pool(h, 'last')
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    rows = [rng.normal(size=(3, 4 * dim)).astype('float32'),
            rng.normal(size=(2, 4 * dim)).astype('float32')]
    batched, = exe.run(feed={'x': create_lod_tensor(rows)},
                       fetch_list=[last])
    for i, row in enumerate(rows):
        single, = exe.run(feed={'x': create_lod_tensor([row])},
                          fetch_list=[last])
        np.testing.assert_allclose(batched[i], single[0], rtol=1e-4,
                                   atol=1e-5)


def test_dynamic_gru_runs_and_masks():
    dim = 3
    x = layers.data('x', shape=[3 * dim], dtype='float32', lod_level=1)
    h = layers.dynamic_gru(x, size=dim)
    pooled = layers.sequence_pool(h, 'last')
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    rows = [rng.normal(size=(4, 3 * dim)).astype('float32'),
            rng.normal(size=(2, 3 * dim)).astype('float32')]
    out, = exe.run(feed={'x': create_lod_tensor(rows)},
                   fetch_list=[pooled])
    assert out.shape == (2, dim)
    assert np.all(np.isfinite(out))


def test_sequence_conv_and_pad():
    x = layers.data('x', shape=[4], dtype='float32', lod_level=1)
    sc = layers.sequence_conv(x, num_filters=6, filter_size=3)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rows = [np.random.rand(5, 4).astype('float32'),
            np.random.rand(2, 4).astype('float32')]
    out, = exe.run(feed={'x': create_lod_tensor(rows)}, fetch_list=[sc])
    assert out.shape == (2, 5, 6)
    # padded tail rows must be zero (mask applied)
    assert np.abs(out[1, 2:]).max() == 0.0


def test_lstm_trains_sequence_classification():
    """Tiny seq classification learns: first-token class signal."""
    dim = 8
    x = layers.data('x', shape=[4 * dim], dtype='float32', lod_level=1)
    label = layers.data('label', shape=[1], dtype='int64')
    h, _ = layers.dynamic_lstm(x, 4 * dim, use_peepholes=False)
    pooled = layers.sequence_pool(h, 'max')
    pred = layers.fc(pooled, 2, act='softmax')
    loss = layers.mean(layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)

    def make_batch(n=16):
        rows, labels = [], []
        for _ in range(n):
            lab = rng.randint(2)
            T = rng.randint(2, 6)
            r = rng.normal(0, 0.3, (T, 4 * dim)).astype('float32')
            r[:, 0] += (2.0 if lab else -2.0)
            rows.append(r)
            labels.append([lab])
        return create_lod_tensor(rows), np.array(labels, 'int64')

    losses = []
    for i in range(60):
        xv, yv = make_batch()
        l, = exe.run(feed={'x': xv, 'label': yv}, fetch_list=[loss])
        losses.append(float(l[0]))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.8


def test_sequence_expand_as_and_concat():
    x = layers.data('x', shape=[2], dtype='float32')
    y = layers.data('y', shape=[1], dtype='float32', lod_level=1)
    ea = layers.sequence_expand_as(x, y)
    a = layers.data('a', shape=[1], dtype='float32', lod_level=1)
    cc = layers.sequence_concat([a, a])
    # downstream consumer: ragged row 1 ([4,5] ++ [4,5]) must pool as a
    # CONTIGUOUS length-4 sequence — this is where a naive padded-block
    # concat (pad holes between the two segments, stale LoD) breaks
    pooled = layers.sequence_pool(cc, 'sum')
    last = layers.sequence_pool(cc, 'last')
    exe = fluid.Executor()
    feed_y = _lod_feed()
    out, cat, s, lv = exe.run(
        feed={'x': np.array([[1, 2], [3, 4]], 'float32'),
              'y': feed_y, 'a': feed_y},
        fetch_list=[ea, cc, pooled, last])
    # each row of x repeats along y's time axis
    np.testing.assert_allclose(out[0, 0], [1, 2])
    np.testing.assert_allclose(out[0, 2], [1, 2])
    np.testing.assert_allclose(out[1, 1], [3, 4])
    # concat along time: [B, T1+T2, D], rows compacted left
    assert cat.shape == (2, 6, 1)
    np.testing.assert_allclose(cat[0, :, 0], [1, 2, 3, 1, 2, 3])
    np.testing.assert_allclose(cat[1, :, 0], [4, 5, 4, 5, 0, 0])
    np.testing.assert_allclose(s, [[12.], [18.]])
    np.testing.assert_allclose(lv, [[3.], [5.]])


def test_sequence_pad_unpad_roundtrip():
    x = layers.data('x', shape=[1], dtype='float32', lod_level=1)
    pv = layers.assign(np.zeros((1,), 'float32'))
    padded, length = layers.sequence_pad(x, pv)
    back = layers.sequence_unpad(padded, length)
    pooled = layers.sequence_pool(back, 'sum')  # consumes restored LoD
    exe = fluid.Executor()
    p, l, s = exe.run(feed={'x': _lod_feed()},
                      fetch_list=[padded, length, pooled])
    assert p.shape == (2, 3, 1)
    np.testing.assert_array_equal(l, [3, 2])
    np.testing.assert_allclose(s, [[6.], [9.]])


def test_sequence_slice_and_reshape():
    x = layers.data('x', shape=[1], dtype='float32', lod_level=1)
    off = layers.data('off', shape=[1], dtype='int64')
    ln = layers.data('ln', shape=[1], dtype='int64')
    sl = layers.sequence_slice(x, off, ln)
    # downstream consumer pins that sl carries the REQUESTED lengths
    # ([2,1]), not x's ([3,2]): average divides by 2/1, last picks the
    # final VALID token, not a pad slot
    avg = layers.sequence_pool(sl, 'average')
    last = layers.sequence_pool(sl, 'last')
    r = layers.data('r', shape=[2], dtype='float32', lod_level=1)
    rs = layers.sequence_reshape(r, new_dim=1)
    # ragged rows: lengths rescale by D/new_dim (row lens [2,1] -> [4,2])
    rsum = layers.sequence_pool(rs, 'sum')
    rlast = layers.sequence_pool(rs, 'last')
    exe = fluid.Executor()
    rows = [np.array([[1., 10.], [2., 20.]], 'float32'),
            np.array([[3., 30.]], 'float32')]
    sv, av, lv, rv, rsv, rlv = exe.run(
        feed={'x': _lod_feed(),
              'off': np.array([[1], [0]], 'int64'),
              'ln': np.array([[2], [1]], 'int64'),
              'r': create_lod_tensor(rows)},
        fetch_list=[sl, avg, last, rs, rsum, rlast])
    # row0 [1,2,3] offset1 len2 -> [2,3]; row1 [4,5] offset0 len1 -> [4]
    np.testing.assert_allclose(sv[0, :2, 0], [2, 3])
    np.testing.assert_allclose(sv[1, 0, 0], 4)
    np.testing.assert_allclose(av, [[2.5], [4.]])
    np.testing.assert_allclose(lv, [[3.], [4.]])
    # reshape [2 rows, T=2, D=2] -> [2, 4, 1]; row lens [2,1] -> [4,2]
    assert rv.shape == (2, 4, 1)
    np.testing.assert_allclose(rv[0, :, 0], [1, 10, 2, 20])
    np.testing.assert_allclose(rv[1, :2, 0], [3, 30])
    np.testing.assert_allclose(rsv, [[33.], [33.]])
    np.testing.assert_allclose(rlv, [[20.], [30.]])


def test_sequence_enumerate_and_scatter():
    ids = layers.data('ids', shape=[4], dtype='int64')
    en = layers.sequence_enumerate(ids, win_size=2, pad_value=0)
    base = layers.data('base', shape=[5], dtype='float32')
    sidx = layers.data('sidx', shape=[3], dtype='int64')
    upd = layers.data('upd', shape=[3], dtype='float32')
    sc = layers.sequence_scatter(base, sidx, upd)
    exe = fluid.Executor()
    ev, scv = exe.run(
        feed={'ids': np.array([[1, 2, 3, 4]], 'int64'),
              'base': np.ones((1, 5), 'float32'),
              'sidx': np.array([[0, 2, 4]], 'int64'),
              'upd': np.array([[10., 20., 30.]], 'float32')},
        fetch_list=[en, sc])
    np.testing.assert_array_equal(
        ev[0], [[1, 2], [2, 3], [3, 4], [4, 0]])
    np.testing.assert_allclose(scv[0], [11., 1., 21., 1., 31.])


def test_sequence_slice_clamps_past_end():
    """Requests past a row's valid end must clamp: the reference
    enforces offset + length <= seq_len (sequence_slice_op.h); here the
    reported OutLength clamps so padding never leaks in as valid
    tokens (ADVICE r4)."""
    x = layers.data('x', shape=[1], dtype='float32', lod_level=1)
    off = layers.data('off', shape=[1], dtype='int64')
    ln = layers.data('ln', shape=[1], dtype='int64')
    sl = layers.sequence_slice(x, off, ln)
    ssum = layers.sequence_pool(sl, 'sum')
    last = layers.sequence_pool(sl, 'last')
    exe = fluid.Executor()
    # row0 [1,2,3]: offset 2, request 5 -> only 1 token available ([3])
    # row1 [4,5]: offset 1, request 3 -> only 1 token ([5])
    sv, sm, lv = exe.run(
        feed={'x': _lod_feed(),
              'off': np.array([[2], [1]], 'int64'),
              'ln': np.array([[5], [3]], 'int64')},
        fetch_list=[sl, ssum, last])
    np.testing.assert_allclose(sm, [[3.], [5.]])   # no pad counted
    np.testing.assert_allclose(lv, [[3.], [5.]])   # last valid, not pad


def test_sequence_erase_layer_binds_lengths():
    """Public layers.sequence_erase: compacts survivors, and the new
    lengths flow to downstream consumers via lod_length_name."""
    ids = layers.data('ids', shape=[1], dtype='int64', lod_level=1)
    er = layers.sequence_erase(ids, tokens=[0, 2])
    cnt = layers.sequence_pool(er, 'sum')    # sums only valid survivors
    last = layers.sequence_pool(er, 'last')
    exe = fluid.Executor()
    rows = [np.array([[2], [7], [0], [9]], 'int64'),
            np.array([[0], [0]], 'int64')]
    ev, cv, lv = exe.run(feed={'ids': create_lod_tensor(rows)},
                         fetch_list=[er, cnt, last])
    np.testing.assert_array_equal(ev[0, :2, 0], [7, 9])  # compacted
    np.testing.assert_allclose(cv[0], [16.])
    np.testing.assert_allclose(lv[0], [9.])              # last survivor
    np.testing.assert_allclose(cv[1], [0.])              # all erased
