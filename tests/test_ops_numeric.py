"""Per-op numeric checks vs independent numpy references (model:
reference tests/unittests per-op OpTest forward checks) for ops that
previously had build-and-run coverage only (test_layers.py) but no
value assertions."""
import numpy as np
import pytest

from paddle_tpu import layers
from test_layers import _run


def test_activation_family_numeric():
    x = layers.data('x', shape=[6], dtype='float32')
    outs = [layers.brelu(x, t_min=-0.5, t_max=0.8),
            layers.soft_relu(x, threshold=40.0),
            layers.relu6(x),
            layers.pow(x, factor=3.0),
            layers.stanh(x, scale_a=0.67, scale_b=1.7159),
            layers.softshrink(x, alpha=0.4),
            layers.hard_shrink(x, threshold=0.4),
            layers.thresholded_relu(x, threshold=0.3),
            layers.selu(x)]
    xv = np.linspace(-2, 2, 12).reshape(2, 6).astype('float32')
    res = _run(outs, {'x': xv})
    np.testing.assert_allclose(res[0], np.clip(xv, -0.5, 0.8), rtol=1e-6)
    np.testing.assert_allclose(res[1], np.log1p(np.exp(xv)), rtol=1e-5)
    np.testing.assert_allclose(res[2], np.clip(xv, 0, 6), rtol=1e-6)
    np.testing.assert_allclose(res[3], xv ** 3, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(res[4], 1.7159 * np.tanh(0.67 * xv),
                               rtol=1e-5)
    np.testing.assert_allclose(
        res[5], np.sign(xv) * np.maximum(np.abs(xv) - 0.4, 0), rtol=1e-5,
        atol=1e-7)
    np.testing.assert_allclose(res[6], np.where(np.abs(xv) > 0.4, xv, 0),
                               rtol=1e-6)
    np.testing.assert_allclose(res[7], np.where(xv > 0.3, xv, 0),
                               rtol=1e-6)
    # selu defaults (reference selu_op): scale/alpha from Klambauer et al.
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    np.testing.assert_allclose(
        res[8], np.where(xv > 0, scale * xv,
                         scale * alpha * (np.exp(xv) - 1)), rtol=1e-5)


def test_shape_manipulation_numeric():
    x = layers.data('x', shape=[2, 3], dtype='float32')
    outs = [layers.expand(x, expand_times=[1, 2, 1]),
            layers.space_to_depth(
                layers.data('sd', shape=[4, 2, 2], dtype='float32'),
                blocksize=2)]
    xv = np.arange(12).reshape(2, 2, 3).astype('float32')
    sdv = np.arange(32).reshape(2, 4, 2, 2).astype('float32')
    res = _run(outs, {'x': xv, 'sd': sdv})
    np.testing.assert_allclose(res[0], np.tile(xv, (1, 2, 1)), rtol=1e-6)
    # space_to_depth blocksize 2 (reference space_to_depth_op.cc layout):
    # [N, C, H, W] -> [N, bs*bs*C, H/2, W/2], block-offset-major channels
    assert res[1].shape == (2, 16, 1, 1)
    ref_sd = sdv.reshape(2, 4, 1, 2, 1, 2).transpose(
        0, 3, 5, 1, 2, 4).reshape(2, 16, 1, 1)
    np.testing.assert_allclose(res[1], ref_sd)


def test_unstack_multiplex_shuffle_channel():
    x = layers.data('x', shape=[2, 3], dtype='float32')
    parts = layers.unstack(x, axis=1)
    a = layers.data('a', shape=[4], dtype='float32')
    b = layers.data('b', shape=[4], dtype='float32')
    idx = layers.data('idx', shape=[1], dtype='int32')
    mux = layers.multiplex([a, b], idx)
    sc = layers.data('sc', shape=[4, 1, 1], dtype='float32')
    shuf = layers.shuffle_channel(sc, group=2)
    xv = np.arange(12).reshape(2, 2, 3).astype('float32')
    av = np.ones((3, 4), 'float32')
    bv = np.zeros((3, 4), 'float32')
    iv = np.array([[0], [1], [0]], 'int32')
    scv = np.arange(8, dtype='float32').reshape(2, 4, 1, 1)
    res = _run([parts[0], parts[1], mux, shuf],
               {'x': xv, 'a': av, 'b': bv, 'idx': iv, 'sc': scv})
    np.testing.assert_allclose(res[0], xv[:, 0])
    np.testing.assert_allclose(res[1], xv[:, 1])
    np.testing.assert_allclose(res[2], np.stack([av[0], bv[1], av[2]]))
    # shuffle_channel group=2 on C=4: [0,1,2,3] -> [0,2,1,3]
    np.testing.assert_allclose(res[3][:, :, 0, 0],
                               scv[:, [0, 2, 1, 3], 0, 0])


def test_pad_crop_numeric():
    x = layers.data('x', shape=[1, 2, 2], dtype='float32')
    big = layers.data('big', shape=[1, 4, 4], dtype='float32')
    outs = [layers.pad2d(x, paddings=[1, 0, 0, 1], pad_value=9.0),
            layers.pad_constant_like(big, x, pad_value=-1.0),
            layers.crop(big, shape=[1, 1, 2, 2], offsets=[0, 0, 1, 1])]
    xv = np.arange(4, dtype='float32').reshape(1, 1, 2, 2)
    bigv = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
    res = _run(outs, {'x': xv, 'big': bigv})
    ref_pad = np.pad(xv, [(0, 0), (0, 0), (1, 0), (0, 1)],
                     constant_values=9.0)
    np.testing.assert_allclose(res[0], ref_pad)
    ref_pcl = np.pad(xv, [(0, 0), (0, 0), (0, 2), (0, 2)],
                     constant_values=-1.0)
    np.testing.assert_allclose(res[1], ref_pcl)
    np.testing.assert_allclose(res[2], bigv[:, :, 1:3, 1:3])


def test_norm_family_numeric():
    x = layers.data('x', shape=[3, 4], dtype='float32')
    img = layers.data('img', shape=[4, 2, 2], dtype='float32')
    sc = np.array([2.0, -1.0, 0.5, 3.0], 'float32')
    bi = np.array([0.1, 0.2, -0.1, 0.0], 'float32')
    outs = [layers.l2_normalize(x, axis=-1),
            layers.clip_by_norm(x, max_norm=1.0),
            layers.affine_channel(img, scale=layers.assign(sc),
                                  bias=layers.assign(bi)),
            layers.lrn(img, n=3, k=1.0, alpha=1e-2, beta=0.5)]
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 3, 4).astype('float32')
    iv = rng.rand(2, 4, 2, 2).astype('float32')
    res = _run(outs, {'x': xv, 'img': iv})
    np.testing.assert_allclose(
        res[0], xv / np.sqrt((xv * xv).sum(-1, keepdims=True) + 1e-12),
        rtol=1e-5)
    gn = np.sqrt((xv * xv).sum())
    ref_clip = xv * min(1.0, 1.0 / gn)
    np.testing.assert_allclose(res[1], ref_clip, rtol=1e-5)
    np.testing.assert_allclose(
        res[2], iv * sc.reshape(1, 4, 1, 1) + bi.reshape(1, 4, 1, 1),
        rtol=1e-5)
    sq = np.pad(iv * iv, [(0, 0), (1, 1), (0, 0), (0, 0)])
    acc = sum(sq[:, i:i + 4] for i in range(3))
    np.testing.assert_allclose(res[3], iv / (1.0 + 1e-2 * acc) ** 0.5,
                               rtol=1e-5)


def test_add_position_encoding_numeric():
    x = layers.data('x', shape=[4, 6], dtype='float32')
    out = layers.add_position_encoding(x, alpha=0.5, beta=2.0)
    xv = np.random.RandomState(1).randn(2, 4, 6).astype('float32')
    res, = _run([out], {'x': xv})
    T, D = 4, 6
    pe = np.zeros((T, D), 'float32')
    pos = np.arange(T)[:, None].astype('float64')
    # reference add_position_encoding_op: div = 10000^(i / (D/2)),
    # first half sin, second half cos
    div = np.power(10000.0, np.arange(D // 2) / (D // 2))
    pe[:, :D // 2] = np.sin(pos / div)
    pe[:, D // 2:] = np.cos(pos / div)
    np.testing.assert_allclose(res, 0.5 * xv + 2.0 * pe[None], rtol=1e-4,
                               atol=1e-5)


def test_indexing_ops_numeric():
    x = layers.data('x', shape=[5], dtype='float32')
    vals, idxs = layers.topk(x, k=2)
    am = layers.argmax(x, axis=1)
    an = layers.argmin(x, axis=1)
    src = layers.data('src', shape=[4], dtype='float32',
                      append_batch_size=False)
    sidx = layers.data('sidx', shape=[2], dtype='int32',
                       append_batch_size=False)
    upd = layers.data('upd', shape=[2], dtype='float32',
                      append_batch_size=False)
    sc = layers.scatter(src, sidx, upd)
    xv = np.array([[3., 1., 4., 1., 5.], [2., 7., 1., 8., 2.]], 'float32')
    srcv = np.array([0., 10., 20., 30.], 'float32')
    sidxv = np.array([3, 1], 'int32')
    updv = np.array([-1., -2.], 'float32')
    res = _run([vals, idxs, am, an, sc],
               {'x': xv, 'src': srcv, 'sidx': sidxv, 'upd': updv})
    np.testing.assert_allclose(res[0], np.sort(xv, axis=1)[:, -1:-3:-1])
    assert res[1].tolist() == [[4, 2], [3, 1]]
    assert res[2].tolist() == [4, 3]
    assert res[3].tolist() == [1, 2]
    np.testing.assert_allclose(res[4], np.array([0., -2., 20., -1.]))


def test_loss_family_numeric():
    p = layers.data('p', shape=[1], dtype='float32')
    lbl = layers.data('lbl', shape=[1], dtype='float32')
    left = layers.data('left', shape=[1], dtype='float32')
    right = layers.data('right', shape=[1], dtype='float32')
    logits = layers.data('logits', shape=[4], dtype='float32')
    ilbl = layers.data('ilbl', shape=[1], dtype='int64')
    prob = layers.data('prob', shape=[4], dtype='float32')
    outs = [layers.log_loss(p, lbl, epsilon=1e-4),
            layers.rank_loss(lbl, left, right),
            layers.margin_rank_loss(lbl, left, right, margin=0.2),
            layers.huber_loss(p, lbl, delta=1.0),
            layers.bpr_loss(logits, ilbl),
            layers.dice_loss(prob, layers.fill_constant_batch_size_like(
                ilbl, [-1, 1], 'float32', 1.0)),
            layers.teacher_student_sigmoid_loss(p, lbl)]
    rng = np.random.RandomState(2)
    pv = rng.rand(3, 1).astype('float32') * 0.8 + 0.1
    lv = (rng.rand(3, 1) > 0.5).astype('float32')
    lf = rng.randn(3, 1).astype('float32')
    rt = rng.randn(3, 1).astype('float32')
    lg = rng.randn(3, 4).astype('float32')
    il = rng.randint(0, 4, (3, 1)).astype('int64')
    pr = rng.rand(3, 4).astype('float32')
    res = _run(outs, {'p': pv, 'lbl': lv, 'left': lf, 'right': rt,
                      'logits': lg, 'ilbl': il, 'prob': pr})
    np.testing.assert_allclose(
        res[0], -lv * np.log(pv + 1e-4) - (1 - lv) * np.log(1 - pv + 1e-4),
        rtol=1e-5)
    d = lf - rt
    np.testing.assert_allclose(res[1], np.log1p(np.exp(d)) - lv * d,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        res[2], np.maximum(0.0, -lv * (lf - rt) + 0.2), rtol=1e-5,
        atol=1e-7)
    r = lv - pv
    np.testing.assert_allclose(
        res[3], np.where(np.abs(r) <= 1.0, 0.5 * r * r,
                         np.abs(r) - 0.5), rtol=1e-5, atol=1e-7)
    # bpr: mean over non-target classes of -log sigmoid(pos - x_j)
    pos = np.take_along_axis(lg, il.astype(int), axis=1)
    sig = 1 / (1 + np.exp(-(pos - lg)))
    mask = np.ones_like(lg)
    np.put_along_axis(mask, il.astype(int), 0.0, axis=1)
    ref_bpr = (-np.log(sig + 1e-8) * mask).sum(1, keepdims=True) / 3.0
    np.testing.assert_allclose(res[4], ref_bpr, rtol=1e-4)
    ones = np.ones((3, 1), 'float32')
    inter = 2 * (pr * ones).sum(1)
    union = pr.sum(1) + ones.sum(1)
    np.testing.assert_allclose(res[5].ravel(),
                               1 - inter / (union + 1e-5), rtol=1e-5)
    z = pv  # within clip bounds
    np.testing.assert_allclose(
        res[6], np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0) - z * lv,
        rtol=1e-5)


def test_resize_numeric():
    x = layers.data('x', shape=[1, 2, 2], dtype='float32')
    bi = layers.resize_bilinear(x, out_shape=[3, 3])
    ne = layers.resize_nearest(x, out_shape=[4, 4])
    xv = np.array([[[[0., 1.], [2., 3.]]]], 'float32')
    res = _run([bi, ne], {'x': xv})
    # align_corners=True (reference default): src = i*(in-1)/(out-1)
    ref = np.array([[0., .5, 1.], [1., 1.5, 2.], [2., 2.5, 3.]])
    np.testing.assert_allclose(res[0][0, 0], ref, rtol=1e-5, atol=1e-6)
    # nearest 2x upscale: each source pixel repeated 2x2
    ref_ne = np.repeat(np.repeat(xv, 2, axis=2), 2, axis=3)
    np.testing.assert_allclose(res[1], ref_ne)


def test_mean_iou_numeric():
    pred = layers.data('pred', shape=[4], dtype='int64')
    lab = layers.data('lab', shape=[4], dtype='int64')
    miou, wrong, correct = layers.mean_iou(pred, lab, num_classes=3)
    pv = np.array([[0, 1, 2, 1]], 'int64')
    lv = np.array([[0, 1, 1, 1]], 'int64')
    res = _run([miou, wrong, correct], {'pred': pv, 'lab': lv})
    # class0: i=1 u=1; class1: i=2 u=3 (pred has 2, label has 3, inter 2);
    # class2: i=0 u=1
    np.testing.assert_allclose(res[0], [(1 / 1 + 2 / 3 + 0) / 3],
                               rtol=1e-5)
    np.testing.assert_allclose(res[1], [0., 1., 0.])  # label-row misses
    np.testing.assert_allclose(res[2], [1., 2., 0.])  # diagonal hits


def test_random_ops_shapes_and_ranges():
    g = layers.gaussian_random(shape=[64, 8], mean=1.0, std=2.0, seed=7)
    u = layers.uniform_random_batch_size_like(
        layers.data('x', shape=[3], dtype='float32'), shape=[-1, 5],
        min=-1.0, max=1.0)
    sid = layers.sampling_id(layers.softmax(
        layers.data('pp', shape=[4], dtype='float32')), seed=3)
    xv = np.zeros((6, 3), 'float32')
    ppv = np.random.RandomState(3).rand(6, 4).astype('float32')
    res = _run([g, u, sid], {'x': xv, 'pp': ppv})
    assert res[0].shape == (64, 8)
    assert abs(res[0].mean() - 1.0) < 0.8
    assert res[1].shape == (6, 5)
    assert res[1].min() >= -1.0 and res[1].max() <= 1.0
    assert res[2].shape[0] == 6
    assert ((res[2] >= 0) & (res[2] < 4)).all()


def test_hash_deterministic():
    x = layers.data('x', shape=[2], dtype='int64')
    h = layers.hash(x, hash_size=1000)
    xv = np.array([[3, 5], [3, 5], [7, 9]], 'int64')
    res, = _run([h], {'x': xv})
    assert ((res >= 0) & (res < 1000)).all()
    np.testing.assert_array_equal(res[0], res[1])
    assert not np.array_equal(res[0], res[2])


_GRAD_CASES = [
    # (op, ins builder, attrs) — forward vs numpy is covered above /
    # in test_layers; here the VJP is checked against central difference
    ('l2_norm_layer', lambda r: {'X': r.randn(3, 5)}, {}),
    ('lrn', lambda r: {'X': r.rand(2, 4, 3, 3) + 0.5},
     {'n': 3, 'k': 1.0, 'alpha': 0.01, 'beta': 0.75}),
    ('maxout', lambda r: {'X': r.randn(2, 4, 3, 3)}, {'groups': 2}),
    ('selu', lambda r: {'X': r.randn(3, 4)}, {}),
    ('huber_loss', lambda r: {'X': r.randn(4, 1), 'Y': r.randn(4, 1)},
     {'delta': 1.0}),
    ('prelu', lambda r: {'X': r.randn(3, 4), 'Alpha': np.array([0.25])},
     {'mode': 'all'}),
    ('grid_sampler',
     lambda r: {'X': r.rand(1, 2, 4, 4),
                'Grid': r.uniform(-0.9, 0.9, (1, 3, 3, 2))}, {}),
    ('softshrink', lambda r: {'X': r.randn(3, 4) * 2}, {'lambda': 0.3}),
]


@pytest.mark.parametrize('case', _GRAD_CASES, ids=lambda c: c[0])
def test_op_gradients_vs_numeric_diff(case):
    """Model: reference OpTest.check_grad — analytic (jax.vjp) gradient
    of sum(outputs[first]) wrt each float input vs central difference."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    op_type, build, attrs = case
    impl = get_op(op_type).impl
    rng = np.random.RandomState(11)
    ins = {k: np.asarray(v, 'float32') for k, v in build(rng).items()}
    outs = impl(None, {k: jnp.asarray(v) for k, v in ins.items()}, attrs)
    # the primary output, not an auxiliary (lrn also emits MidOut)
    first_out = 'Out' if 'Out' in outs else sorted(outs.keys())[0]

    def f(d):
        out = impl(None, d, attrs)[first_out]
        return jnp.sum(out.astype(jnp.float32))

    grads = jax.grad(lambda d: f({k: jnp.asarray(v) for k, v in
                                  d.items()}))(ins)
    eps = 1e-3
    for name, x in ins.items():
        g = np.asarray(grads[name])
        num = np.zeros_like(x)
        flat = x.ravel()
        nf = num.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            up = float(f({k: jnp.asarray(v) for k, v in ins.items()}))
            flat[i] = orig - eps
            dn = float(f({k: jnp.asarray(v) for k, v in ins.items()}))
            flat[i] = orig
            nf[i] = (up - dn) / (2 * eps)
        np.testing.assert_allclose(
            g, num, rtol=5e-2, atol=5e-3,
            err_msg='%s grad wrt %s' % (op_type, name))


def test_py_func_forward_and_backward():
    """py_func: host callable as an op (pure_callback lowering), with a
    backward_func-driven custom VJP reaching the parameter gradients."""
    import paddle_tpu as fluid

    def double_plus(a):
        return a * 2.0 + 1.0

    def double_plus_bwd(a, out, dout):
        return dout * 2.0

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            d = layers.data('x', shape=[3], dtype='float32')
            w = layers.create_parameter([3, 3], 'float32', name='pyf_w')
            h = layers.matmul(d, w)
            out_var = layers.create_tensor('float32', name='pyf_out')
            out_var.shape = (-1, 3)
            layers.py_func(double_plus, h, out_var,
                           backward_func=double_plus_bwd)
            loss = layers.reduce_mean(out_var)
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    xv = np.ones((2, 3), 'float32')
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.asarray(scope.get('pyf_w')).copy()
        l1, o1 = exe.run(main, feed={'x': xv}, fetch_list=[loss, out_var])
        w1 = np.asarray(scope.get('pyf_w'))
    np.testing.assert_allclose(o1, xv @ w0 * 2.0 + 1.0, rtol=1e-5)
    # dL/dw = x^T @ (dout * 2) with dout = 1/6
    ref_gw = xv.T @ (np.full((2, 3), 2.0 / 6.0, 'float32'))
    np.testing.assert_allclose(w1, w0 - 0.5 * ref_gw, rtol=1e-4)


def test_py_func_no_backward_cuts_gradient():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            d = layers.data('x', shape=[3], dtype='float32')
            w = layers.create_parameter([3, 3], 'float32', name='pyf2_w')
            h = layers.matmul(d, w)
            out_var = layers.create_tensor('float32', name='pyf2_out')
            out_var.shape = (-1, 3)
            layers.py_func(lambda a: a + 1.0, h, out_var)
            loss = layers.reduce_mean(out_var)
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.asarray(scope.get('pyf2_w')).copy()
        exe.run(main, feed={'x': np.ones((2, 3), 'float32')},
                fetch_list=[loss])
        w1 = np.asarray(scope.get('pyf2_w'))
    np.testing.assert_allclose(w1, w0)  # gradient cut: no update


def test_sequence_erase_compacts_and_relengths():
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    ids = jnp.asarray([[3, 5, 3, 7, 0, 0],
                       [5, 5, 5, 1, 2, 9]])
    lens = jnp.asarray([4, 6], jnp.int32)
    outs = get_op('sequence_erase').impl(
        None, {'X': ids, 'Length': lens}, {'tokens': [3, 5]})
    np.testing.assert_array_equal(
        np.asarray(outs['Out']),
        [[7, 0, 0, 0, 0, 0],   # row0 [3,5,3,7]: erase 3s and 5s -> [7]
         [1, 2, 9, 0, 0, 0]])  # row1: erase 5s -> [1, 2, 9]
    np.testing.assert_array_equal(np.asarray(outs['OutLength']), [1, 3])
