"""High-level Trainer/Inferencer + evaluator/average tests."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _reader():
    rng = np.random.RandomState(0)
    w = np.array([[1.5], [-2.0], [0.5]], 'float32')

    def r():
        for _ in range(8):
            batch = []
            for _ in range(16):
                x = rng.rand(3).astype('float32')
                batch.append((x, (x[None, :] @ w)[0]))
            yield batch
    return r


def test_trainer_train_test_save_infer(tmp_path):
    def train_func():
        x = layers.data('x', shape=[3], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name='w'))
        loss = layers.reduce_mean(layers.square(pred - y))
        return loss

    events = {'epochs': 0, 'steps': 0}

    def handler(ev):
        if isinstance(ev, fluid.EndEpochEvent):
            events['epochs'] += 1
        elif isinstance(ev, fluid.EndStepEvent):
            events['steps'] += 1
            events['last_loss'] = float(np.asarray(ev.metrics[0]).reshape(()))

    trainer = fluid.Trainer(train_func,
                            lambda: fluid.optimizer.SGDOptimizer(0.3))
    # batch reader feeds (x, y) rows in feed_order
    trainer.train(3, handler, reader=_reader(), feed_order=['x', 'y'])
    assert events['epochs'] == 3
    assert events['steps'] == 24
    test_loss, = trainer.test(_reader(), feed_order=['x', 'y'])
    assert np.asarray(test_loss).ravel()[0] < 0.5, test_loss

    pdir = str(tmp_path / 'params')
    trainer.save_params(pdir)

    def infer_func():
        x = layers.data('x', shape=[3], dtype='float32')
        return layers.fc(x, 1, param_attr=fluid.ParamAttr(name='w'))

    inferencer = fluid.Inferencer(infer_func, pdir)
    xb = np.eye(3, dtype='float32')
    out, = inferencer.infer({'x': xb})
    assert out.shape == (3, 1)


def test_trainer_checkpoint_resume(tmp_path):
    def train_func():
        x = layers.data('x', shape=[3], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1)
        return layers.reduce_mean(layers.square(pred - y))

    ckpt = fluid.CheckpointConfig(str(tmp_path / 'ck'), step_interval=4)
    t1 = fluid.Trainer(train_func,
                       lambda: fluid.optimizer.SGDOptimizer(0.1),
                       checkpoint_config=ckpt)
    t1.train(2, lambda ev: None, reader=_reader(), feed_order=['x', 'y'])
    # a fresh trainer with the same config resumes from the saved epoch
    t2 = fluid.Trainer(train_func,
                       lambda: fluid.optimizer.SGDOptimizer(0.1),
                       checkpoint_config=fluid.CheckpointConfig(
                           str(tmp_path / 'ck'), step_interval=4))
    assert t2._resume_epoch >= 1


def test_weighted_average():
    from paddle_tpu.average import WeightedAverage
    a = WeightedAverage()
    a.add(2.0, 1.0)
    a.add(4.0, 3.0)
    assert abs(a.eval() - 3.5) < 1e-9
    a.reset()
    a.add(1.0, 1.0)
    assert abs(a.eval() - 1.0) < 1e-9


def test_chunk_evaluator_accumulates():
    from paddle_tpu.evaluator import ChunkEvaluator
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inf = layers.data('inf', shape=[8], dtype='int64')
        lab = layers.data('lab', shape=[8], dtype='int64')
        ev = ChunkEvaluator(inf, lab, 'IOB', 3)
    exe = fluid.Executor()
    exe.run(startup)
    ev.reset(exe)
    inf_np = np.array([[0, 1, 6, 2, 3, 3, 6, 4]], 'int64')
    lab_np = np.array([[0, 1, 6, 2, 3, 6, 6, 4]], 'int64')
    for _ in range(3):
        exe.run(main, feed={'inf': inf_np, 'lab': lab_np},
                fetch_list=[m.name for m in ev.metrics])
    p, r, f1 = ev.eval(exe)
    # per batch: 3 infer/3 label/2 correct, same accumulated ratio
    assert abs(float(p) - 2 / 3) < 1e-6
    assert abs(float(r) - 2 / 3) < 1e-6
    # reset really zeroes
    ev.reset(exe)
    p2, r2, f2 = ev.eval(exe)
    assert float(p2) == 0.0


def test_edit_distance_evaluator():
    from paddle_tpu.evaluator import EditDistance
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        hyp = layers.data('hyp', shape=[4], dtype='int64', lod_level=1)
        ref = layers.data('ref', shape=[4], dtype='int64', lod_level=1)
        ev = EditDistance(hyp, ref)
    exe = fluid.Executor()
    exe.run(startup)
    ev.reset(exe)
    hyp_np = np.array([[1, 2, 3, 4], [1, 1, 1, 1]], 'int64')
    ref_np = np.array([[1, 2, 3, 4], [2, 2, 2, 2]], 'int64')
    exe.run(main, feed={'hyp': hyp_np, 'ref': ref_np},
            fetch_list=[m.name for m in ev.metrics])
    avg, err_rate = ev.eval(exe)
    assert abs(float(avg) - 2.0) < 1e-6    # (0 + 4)/2
    assert abs(float(err_rate) - 0.5) < 1e-6


def test_detection_map_evaluator():
    from paddle_tpu.evaluator import DetectionMAP
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        d = layers.data('d', shape=[3, 6], dtype='float32')
        g = layers.data('g', shape=[2, 6], dtype='float32')
        ev = DetectionMAP(d, g, None, class_num=3, overlap_threshold=0.5)
    exe = fluid.Executor()
    exe.run(startup)
    ev.reset(exe)
    det = np.array([[[1, .9, 0, 0, 1, 1],
                     [1, .8, 5, 5, 6, 6],
                     [2, .7, 2, 2, 3, 3]]], 'float32')
    gt = np.array([[[1, 0, 0, 1, 1, 0],
                    [2, 2, 2, 3, 3, 0]]], 'float32')
    exe.run(main, feed={'d': det, 'g': gt},
            fetch_list=[m.name for m in ev.metrics])
    assert abs(float(ev.eval(exe)) - 1.0) < 1e-5


def test_contrib_utils():
    from paddle_tpu.contrib import memory_usage, op_freq_statistic
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[3], dtype='float32')
        y = layers.fc(x, 4)
        layers.fc(y, 4)
    gb, unit = memory_usage(main, batch_size=32)
    assert gb > 0 and unit == 'GB'
    uni, adj = op_freq_statistic(main)
    assert uni.get('mul', 0) + uni.get('matmul', 0) >= 2 or uni
