"""Reader decorators, dataset generators, metrics classes, and
WeightedAverage (model: reference reader/decorator tests +
test_metrics.py + per-dataset sanity)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import reader as rd


def _r(seq):
    def gen():
        for s in seq:
            yield s
    return gen


def test_map_shuffle_chain_compose_buffered_firstn():
    doubled = rd.map_readers(lambda a: a * 2, _r([1, 2, 3]))
    assert list(doubled()) == [2, 4, 6]
    ch = rd.chain(_r([1, 2]), _r([3]))
    assert list(ch()) == [1, 2, 3]
    comp = rd.compose(_r([1, 2]), _r([10, 20]))
    assert list(comp()) == [(1, 10), (2, 20)]
    buf = rd.buffered(_r(list(range(10))), 3)
    assert list(buf()) == list(range(10))
    fn = rd.firstn(_r(list(range(100))), 5)
    assert list(fn()) == [0, 1, 2, 3, 4]
    sh = rd.shuffle(_r(list(range(50))), buf_size=10)
    got = list(sh())
    assert sorted(got) == list(range(50))
    assert got != list(range(50))        # actually shuffled
    cached = rd.cache(_r([1, 2, 3]))
    assert list(cached()) == [1, 2, 3]
    assert list(cached()) == [1, 2, 3]   # replayable


def test_xmap_readers_parallel_mapping():
    out = rd.xmap_readers(lambda a: a + 1, _r(list(range(20))),
                          process_num=2, buffer_size=4, order=True)
    assert list(out()) == list(range(1, 21))
    unordered = rd.xmap_readers(lambda a: a + 1, _r(list(range(20))),
                                process_num=2, buffer_size=4)
    assert sorted(unordered()) == list(range(1, 21))


def test_batch_and_drop_last():
    b = fluid.batch(_r(list(range(7))), batch_size=3)
    batches = list(b())
    assert [len(x) for x in batches] == [3, 3, 1]
    b2 = fluid.batch(_r(list(range(7))), batch_size=3, drop_last=True)
    assert [len(x) for x in list(b2())] == [3, 3]


@pytest.mark.parametrize('mod,shape_check', [
    ('mnist', lambda s: np.asarray(s[0]).size == 784 and 0 <= s[1] < 10),
    ('cifar', None),
    ('uci_housing', lambda s: np.asarray(s[0]).size == 13),
    ('imdb', None),
    ('imikolov', None),
    ('movielens', None),
    ('conll05', None),
    ('sentiment', None),
    ('wmt14', None),
    ('wmt16', None),
    ('mq2007', None),
    ('flowers', None),
    ('voc2012', None),
])
def test_dataset_generators_yield(mod, shape_check):
    import importlib
    m = importlib.import_module('paddle_tpu.dataset.%s' % mod)
    if mod == 'cifar':
        it = m.train10()
    elif mod == 'imdb':
        it = m.train(m.word_dict())
    elif mod == 'imikolov':
        it = m.train(m.build_dict(), 5)
    elif mod == 'conll05':
        it = m.test()
    elif mod == 'sentiment':
        it = m.train()
    elif mod == 'wmt14':
        it = m.train(30000)
    elif mod == 'wmt16':
        it = m.train(3000, 3000)
    else:
        it = m.train()
    first = next(iter(it()))
    assert first is not None
    if shape_check:
        assert shape_check(first)


def test_metrics_precision_recall_accuracy():
    from paddle_tpu import metrics
    p = metrics.Precision()
    r = metrics.Recall()
    preds = np.array([1, 1, 0, 1])
    labels = np.array([1, 0, 0, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert abs(p.eval() - 2 / 3) < 1e-9     # tp=2 fp=1
    assert abs(r.eval() - 1.0) < 1e-9       # tp=2 fn=0
    a = metrics.Accuracy()
    a.update(np.array([0.5]), 4)
    a.update(np.array([1.0]), 4)
    assert abs(a.eval() - 0.75) < 1e-9


def test_metrics_auc_class():
    from paddle_tpu import metrics
    auc = metrics.Auc('auc')  # name is positional (reference API)
    preds = np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3], [0.4, 0.6]])
    labels = np.array([[0], [1], [0], [1]])
    auc.update(preds, labels)               # perfect ranking by col 1
    assert auc.eval() > 0.99


def test_weighted_average():
    from paddle_tpu.average import WeightedAverage
    wa = WeightedAverage()
    wa.add(value=2.0, weight=1)
    wa.add(value=4.0, weight=3)
    assert abs(wa.eval() - 3.5) < 1e-9      # (2 + 12) / 4


def test_data_feeder_dense_and_lod_slots():
    """DataFeeder converts row tuples into the executor feed dict:
    dense slots batch+reshape+cast; lod slots become padded+lengths."""
    from paddle_tpu import layers
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            img = layers.data('df_img', shape=[1, 4, 4], dtype='float32')
            lbl = layers.data('df_lbl', shape=[1], dtype='int64')
            seq = layers.data('df_seq', shape=[1], dtype='float32',
                              lod_level=1)
            pooled = layers.sequence_pool(seq, 'sum')
            total = layers.reduce_sum(img) + layers.reduce_sum(pooled)
    feeder = fluid.DataFeeder([img, lbl, seq], program=main)
    rows = [
        (np.ones(16), 3, [1.0, 2.0, 3.0]),       # flat image, ragged seq
        (np.zeros((1, 4, 4)), 7, [4.0]),
    ]
    feed = feeder.feed(rows)
    assert feed['df_img'].shape == (2, 1, 4, 4)
    assert feed['df_img'].dtype == np.float32
    assert feed['df_lbl'].shape == (2, 1)
    assert feed['df_lbl'].dtype == np.int64
    lod = feed['df_seq']
    assert lod.lengths.tolist() == [3, 1]
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        t, p = exe.run(main, feed=feed, fetch_list=[total, pooled])
    np.testing.assert_allclose(np.asarray(p).ravel(), [6.0, 4.0])
    np.testing.assert_allclose(np.asarray(t).ravel()[0], 16.0 + 10.0)
