"""Multi-host runtime: 2 real processes bootstrap jax.distributed via the
PADDLE_TRAINER_* env convention and run a cross-process psum.

Model: the reference's multi-trainer NCCL2 bootstrap tests
(tests/unittests/test_dist_*.py spawn trainer processes); here the
coordination service is jax.distributed and the collective is an XLA
psum over the global mesh.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update('jax_platforms', 'cpu')
from paddle_tpu.parallel import distributed as dist

dist.init_parallel_env()
rank = dist.get_rank()
world = dist.get_world_size()
assert world == 2, world

import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import numpy as np
devs = jax.devices()          # all processes see the global device list
mesh = Mesh(np.asarray(devs), ('x',))

@jax.jit
def allsum(v):
    return shard_map(lambda s: jax.lax.psum(s, 'x'),
                     mesh=mesh, in_specs=P('x'), out_specs=P(None))(v)

n = len(devs)
x = jnp.arange(n, dtype=jnp.float32)
out = np.asarray(jax.device_get(allsum(x)))
expect = float(sum(range(n)))
assert out.shape == () or out.size >= 1
assert abs(float(out.ravel()[0]) - expect) < 1e-6, (out, expect)
print('RANK_OK', rank, world, float(out.ravel()[0]), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_and_collect(timeout=150):
    """Launch the 2-process psum; returns (ok, outs) where ok=False
    means the bootstrap timed out (processes killed)."""
    port = _free_port()
    eps = '127.0.0.1:%d,127.0.0.1:%d' % (port, port + 1)
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            'PADDLE_TRAINER_ID': str(rank),
            'PADDLE_TRAINERS_NUM': '2',
            'PADDLE_TRAINER_ENDPOINTS': eps,
            'PADDLE_CURRENT_ENDPOINT': eps.split(',')[rank],
            'JAX_PLATFORMS': 'cpu',
            'XLA_FLAGS': '--xla_force_host_platform_device_count=2',
        })
        procs.append(subprocess.Popen(
            [sys.executable, '-c', _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False, outs
    return True, list(zip(procs, outs))


# jaxlib refuses cross-process collectives on its CPU backend with this
# exact wording — a BACKEND capability gap, not a bug in our bootstrap
_CPU_BACKEND_LIMIT = "aren't implemented on the CPU backend"


def test_two_process_psum(tmp_path):
    """Flaky-bootstrap failures still FAIL (VERDICT r4 #8: bounded retries
    with fresh ports, no silent escape) — but a jaxlib CPU backend that
    cannot run multi-process collectives AT ALL skips with the backend's
    own error as the reason, so tier-1 separates "can't run here" from
    "broken"."""
    attempts = []
    for attempt in range(3):
        ok, res = _spawn_and_collect()
        if ok:
            break
        attempts.append('attempt %d: bootstrap timed out' % attempt)
    else:
        raise AssertionError(
            'jax.distributed bootstrap timed out on all retries:\n%s'
            % '\n'.join(attempts))
    for rank, (p, out) in enumerate(res):
        if p.returncode != 0 and _CPU_BACKEND_LIMIT in out:
            reason = next((ln.strip() for ln in out.splitlines()
                           if _CPU_BACKEND_LIMIT in ln), _CPU_BACKEND_LIMIT)
            pytest.skip('jaxlib CPU backend cannot run multi-process '
                        'collectives: %s' % reason)
        assert p.returncode == 0, 'rank %d failed:\n%s' % (rank, out)
        assert 'RANK_OK' in out, out
    outs = [out for _, out in res]
    # 2 procs x 2 local devices = 4 global: psum of arange(4) = 6
    assert 'RANK_OK 0 2 6.0' in outs[0], outs[0]
    assert 'RANK_OK 1 2 6.0' in outs[1], outs[1]
