"""Numeric tests for the CTC / CRF / lstmp op family vs plain-numpy
references (model: reference tests/unittests/test_warpctc_op.py,
test_ctc_align_op.py, test_linear_chain_crf_op.py, test_crf_decoding_op.py,
test_lstmp_op.py)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import LoDTensor


# ------------------------------------------------------- numpy references

def np_ctc_nll(logits, labels, blank=0):
    """Brute-force CTC -log p(l|x) by enumerating the alpha recursion in
    float64 (single sequence)."""
    T, C = logits.shape
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ext = [blank]
    for l in labels:
        ext += [l, blank]
    S = len(ext)
    alpha = np.zeros((T, S))
    alpha[0, 0] = probs[0, blank]
    if S > 1:
        alpha[0, 1] = probs[0, ext[1]]
    for t in range(1, T):
        for s in range(S):
            a = alpha[t - 1, s]
            if s - 1 >= 0:
                a += alpha[t - 1, s - 1]
            if s - 2 >= 0 and ext[s] != blank and ext[s] != ext[s - 2]:
                a += alpha[t - 1, s - 2]
            alpha[t, s] = a * probs[t, ext[s]]
    p = alpha[T - 1, S - 1] + (alpha[T - 1, S - 2] if S > 1 else 0.0)
    return -np.log(p)


def np_crf_nll(x, labels, trans):
    """Forward-algorithm NLL for one sequence, float64."""
    start, stop, w = trans[0], trans[1], trans[2:]
    T, C = x.shape
    alpha = start + x[0]
    for t in range(1, T):
        alpha = np.log(np.exp(
            alpha[:, None] + w).sum(0)) + x[t]
    logz = np.log(np.exp(alpha + stop).sum())
    score = start[labels[0]] + x[0, labels[0]]
    for t in range(1, T):
        score += w[labels[t - 1], labels[t]] + x[t, labels[t]]
    score += stop[labels[-1]]
    return logz - score


def np_viterbi(x, trans):
    start, stop, w = trans[0], trans[1], trans[2:]
    T, C = x.shape
    alpha = start + x[0]
    bps = []
    for t in range(1, T):
        scores = alpha[:, None] + w + x[t][None, :]
        bps.append(scores.argmax(0))
        alpha = scores.max(0)
    path = [int((alpha + stop).argmax())]
    for bp in reversed(bps):
        path.append(int(bp[path[-1]]))
    return np.array(path[::-1])


# ----------------------------------------------------------------- tests

def test_warpctc_matches_numpy():
    rng = np.random.RandomState(0)
    B, T, C, L = 3, 8, 5, 3
    logits = rng.randn(B, T, C).astype('float32')
    labels = rng.randint(1, C, (B, L)).astype('int64')
    t_lens = np.array([8, 6, 7], 'int32')
    l_lens = np.array([3, 2, 1], 'int32')

    x = fluid.layers.data('x', shape=[C], dtype='float32', lod_level=1)
    lab = fluid.layers.data('lab', shape=[1], dtype='int64', lod_level=1)
    loss = layers.warpctc(x, lab, blank=0)
    exe = fluid.Executor()
    out, = exe.run(feed={'x': LoDTensor(logits, t_lens),
                         'lab': LoDTensor(labels[..., None], l_lens)},
                   fetch_list=[loss])
    for b in range(B):
        want = np_ctc_nll(logits[b, :t_lens[b]].astype('float64'),
                          labels[b, :l_lens[b]])
        np.testing.assert_allclose(out[b, 0], want, rtol=2e-4)


def test_warpctc_trains():
    rng = np.random.RandomState(1)
    B, T, C, L = 2, 6, 4, 2
    feats = rng.randn(B, T, 3).astype('float32')
    labels = rng.randint(1, C, (B, L)).astype('int64')

    x = fluid.layers.data('x', shape=[T, 3], dtype='float32')
    lab = fluid.layers.data('lab', shape=[L], dtype='int64')
    logits = fluid.layers.fc(x, C, num_flatten_dims=2)
    loss = layers.mean(layers.warpctc(logits, lab, blank=0))
    fluid.optimizer.Adam(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(30):
        lv, = exe.run(feed={'x': feats, 'lab': labels}, fetch_list=[loss])
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[::10]


def test_ctc_greedy_decoder():
    # frames argmax to [b b 1 1 b 2 2 b] -> decoded [1, 2]
    T, C = 8, 4
    path = [0, 0, 1, 1, 0, 2, 2, 0]
    probs = np.full((1, T, C), -5.0, 'float32')
    for t, c in enumerate(path):
        probs[0, t, c] = 5.0
    x = fluid.layers.data('x', shape=[T, C], dtype='float32')
    dec = layers.ctc_greedy_decoder(x, blank=0)
    exe = fluid.Executor()
    out, = exe.run(feed={'x': probs}, fetch_list=[dec])
    assert list(out[0][:2]) == [1, 2]
    assert (out[0][2:] == 0).all()


def test_linear_chain_crf_matches_numpy():
    rng = np.random.RandomState(2)
    B, T, C = 3, 6, 4
    x = rng.randn(B, T, C).astype('float32') * 0.5
    labels = rng.randint(0, C, (B, T)).astype('int64')
    trans = (rng.randn(C + 2, C) * 0.3).astype('float32')
    lens = np.array([6, 4, 5], 'int32')

    xv = fluid.layers.data('x', shape=[C], dtype='float32', lod_level=1)
    lv = fluid.layers.data('lab', shape=[1], dtype='int64', lod_level=1)
    cost = layers.linear_chain_crf(
        xv, lv, param_attr=fluid.ParamAttr(name='crf_w'))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set('crf_w', trans)
    out, = exe.run(feed={'x': LoDTensor(x, lens),
                         'lab': LoDTensor(labels[..., None], lens)},
                   fetch_list=[cost])
    for b in range(B):
        want = np_crf_nll(x[b, :lens[b]].astype('float64'),
                          labels[b, :lens[b]], trans.astype('float64'))
        np.testing.assert_allclose(out[b, 0], want, rtol=2e-4)


def test_crf_decoding_matches_numpy():
    rng = np.random.RandomState(3)
    B, T, C = 2, 5, 3
    x = rng.randn(B, T, C).astype('float32')
    trans = (rng.randn(C + 2, C) * 0.5).astype('float32')
    lens = np.array([5, 3], 'int32')

    xv = fluid.layers.data('x', shape=[C], dtype='float32', lod_level=1)
    path = layers.crf_decoding(xv, param_attr=fluid.ParamAttr(name='crf_d'))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set('crf_d', trans)
    out, = exe.run(feed={'x': LoDTensor(x, lens)}, fetch_list=[path])
    for b in range(B):
        want = np_viterbi(x[b, :lens[b]].astype('float64'),
                          trans.astype('float64'))
        np.testing.assert_array_equal(out[b, :lens[b]], want)
        assert (out[b, lens[b]:] == 0).all()


def test_crf_train_improves_decoding():
    """Sequence labeling end-to-end: emissions + CRF learn a trivial
    tagging rule (tag = feature argmax)."""
    rng = np.random.RandomState(4)
    B, T, C = 8, 5, 3
    feats = rng.randn(B, T, C).astype('float32')
    labels = feats.argmax(-1).astype('int64')

    x = fluid.layers.data('x', shape=[T, C], dtype='float32')
    lab = fluid.layers.data('lab', shape=[T], dtype='int64')
    emission = fluid.layers.fc(x, C, num_flatten_dims=2)
    cost = layers.linear_chain_crf(
        emission, lab, param_attr=fluid.ParamAttr(name='crf_t'))
    loss = layers.mean(cost)
    fluid.optimizer.Adam(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    first = None
    for i in range(40):
        lv, = exe.run(feed={'x': feats, 'lab': labels}, fetch_list=[loss])
        if first is None:
            first = float(lv)
    assert float(lv) < first * 0.5, (first, float(lv))


def test_dynamic_lstmp_shapes_and_projection():
    rng = np.random.RandomState(5)
    B, T, D, P = 2, 7, 6, 3
    x = rng.randn(B, T, 4 * D).astype('float32')
    xv = fluid.layers.data('x', shape=[T, 4 * D], dtype='float32')
    proj, cell = fluid.layers.dynamic_lstmp(xv, size=4 * D, proj_size=P)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    pv, cv = exe.run(feed={'x': x}, fetch_list=[proj, cell])
    assert pv.shape == (B, T, P)
    assert cv.shape == (B, T, D)
    assert np.isfinite(pv).all() and np.isfinite(cv).all()
    # projection output bounded by tanh
    assert np.abs(pv).max() <= 1.0


def test_dynamic_lstmp_matches_numpy_step():
    """One-timestep lstmp against a hand-rolled numpy step (no peepholes)."""
    rng = np.random.RandomState(6)
    B, D, P = 2, 4, 3
    x = rng.randn(B, 1, 4 * D).astype('float32')
    w = rng.randn(P, 4 * D).astype('float32') * 0.3
    pw = rng.randn(D, P).astype('float32') * 0.3
    b = rng.randn(1, 4 * D).astype('float32') * 0.1

    xv = fluid.layers.data('x', shape=[1, 4 * D], dtype='float32')
    proj, cell = fluid.layers.dynamic_lstmp(
        xv, size=4 * D, proj_size=P, use_peepholes=False,
        param_attr=fluid.ParamAttr(name='lstmp_w'),
        bias_attr=fluid.ParamAttr(name='lstmp_b'))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set('lstmp_w', w)
    fluid.global_scope().set('lstmp_w_proj', pw)
    fluid.global_scope().set('lstmp_b', b)
    pv, cv = exe.run(feed={'x': x}, fetch_list=[proj, cell])

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    gates = x[:, 0] + b  # r0 = 0
    i, f, g, o = np.split(gates, 4, axis=-1)
    c = sig(i) * np.tanh(g)
    h = sig(o) * np.tanh(c)
    r = np.tanh(h @ pw)
    np.testing.assert_allclose(pv[:, 0], r, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cv[:, 0], c, rtol=1e-5, atol=1e-5)
