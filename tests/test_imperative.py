"""Imperative (dygraph) mode tests.

Mirrors reference python/paddle/fluid/tests/unittests/test_imperative.py /
test_imperative_optimizer.py usage patterns.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import imperative


def test_sums_backward():
    x = np.ones([2, 2], np.float32)
    with imperative.guard():
        inputs = [imperative.to_variable(x) for _ in range(10)]
        ret = fluid.layers.sums(inputs)
        loss = fluid.layers.reduce_sum(ret)
        loss._backward()
        assert np.allclose(ret._numpy(), x * 10)
        assert np.allclose(inputs[0]._gradient(), x)


def test_layer_forward_and_grad():
    class MyLayer(imperative.Layer):
        def forward(self, inputs):
            x = fluid.layers.relu(inputs)
            x = fluid.layers.elementwise_mul(x, x)
            x = fluid.layers.reduce_sum(x)
            return [x]

    np_inp = np.array([1.0, 2.0, -1.0], dtype=np.float32)
    with imperative.guard():
        var_inp = imperative.to_variable(np_inp)
        outs = MyLayer()(var_inp)
        outs[0]._backward()
        out = outs[0]._numpy()
        grad = var_inp._gradient()
    # forward: sum(relu(x)^2); grad: 2*relu(x)*1[x>0]
    r = np.maximum(np_inp, 0)
    assert np.allclose(out, np.sum(r * r))
    assert np.allclose(grad, 2 * r * (np_inp > 0))


def test_mlp_parameters_tracked():
    class MLP(imperative.Layer):
        def __init__(self):
            super(MLP, self).__init__()
            self._fc1 = imperative.FC(
                3, fluid.ParamAttr(
                    initializer=fluid.initializer.Constant(value=0.1)))
            self._fc2 = imperative.FC(
                4, fluid.ParamAttr(
                    initializer=fluid.initializer.Constant(value=0.1)))

        def forward(self, inputs):
            x = self._fc1(inputs)
            x = self._fc2(x)
            return fluid.layers.reduce_sum(x)

    np_inp = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
    with imperative.guard():
        mlp = MLP()
        out = mlp(imperative.to_variable(np_inp))
        out._backward()
        params = mlp.parameters()
        # fc1 w+b, fc2 w+b
        assert len(params) == 4
        # constant-0.1 weights: value check
        # param_attr initializer applies to weights; bias default-inits to 0
        expected = (np_inp @ np.full((2, 3), 0.1)
                    @ np.full((3, 4), 0.1)).sum()
        assert np.allclose(out._numpy(), expected, rtol=1e-5)
        g = mlp._fc1.parameters()[0]._grad_value
        assert g is not None


def test_param_reuse_across_calls():
    with imperative.guard():
        fc = imperative.FC(2, bias_attr=False)
        x = imperative.to_variable(np.ones((1, 2), np.float32))
        fc(x)
        w_names1 = sorted(p.name for p in fc.parameters())
        w1 = fc.parameters()[0].numpy()
        fc(x)
        w_names2 = sorted(p.name for p in fc.parameters())
        w2 = fc.parameters()[0].numpy()
        assert w_names1 == w_names2
        assert np.array_equal(w1, w2)


def test_eager_sgd_converges():
    rng = np.random.RandomState(0)
    w_true = rng.rand(5, 1).astype('float32')
    with imperative.guard():
        fc = imperative.FC(1, bias_attr=False)
        sgd = fluid.optimizer.SGDOptimizer(learning_rate=0.1)
        losses = []
        for _ in range(40):
            xb = rng.rand(16, 5).astype('float32')
            x = imperative.to_variable(xb)
            y = imperative.to_variable(xb @ w_true)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(fc(x) - y))
            sgd.minimize(loss)
            losses.append(float(np.asarray(loss.numpy()).reshape(())))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_eager_adam_converges():
    rng = np.random.RandomState(1)
    with imperative.guard():
        fc = imperative.FC(1)
        opt = fluid.optimizer.AdamOptimizer(learning_rate=0.05)
        losses = []
        for _ in range(40):
            xb = rng.rand(8, 3).astype('float32')
            x = imperative.to_variable(xb)
            y = imperative.to_variable(xb.sum(1, keepdims=True))
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(fc(x) - y))
            opt.minimize(loss)
            losses.append(float(np.asarray(loss.numpy()).reshape(())))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_conv_pool_batchnorm_forward():
    x = np.random.RandomState(2).rand(2, 3, 8, 8).astype('float32')
    with imperative.guard():
        conv = imperative.Conv2D(3, 4, 3, padding=1, act='relu')
        pool = imperative.Pool2D(2, 'max', 2)
        bn = imperative.BatchNorm(4)
        v = imperative.to_variable(x)
        h = conv(v)
        assert tuple(h.shape) == (2, 4, 8, 8)
        h = pool(h)
        assert tuple(h.shape) == (2, 4, 4, 4)
        h = bn(h)
        out = fluid.layers.reduce_mean(h)
        out.backward()
        assert conv.parameters()[0]._grad_value is not None


def test_embedding_layer():
    with imperative.guard():
        emb = imperative.Embedding((10, 4))
        ids = imperative.to_variable(np.array([[1], [3]], np.int32))
        out = emb(ids)
        assert tuple(np.asarray(out.numpy()).shape)[-1] == 4


def test_pylayer_custom_grad():
    class MyPyLayer(imperative.PyLayer):
        @staticmethod
        def forward(inputs):
            return np.tanh(inputs[0])

        @staticmethod
        def backward(inputs):
            inp, out, dout = inputs
            return np.array(dout) * (1 - np.square(np.array(out)))

    np_inp = np.random.RandomState(3).rand(3, 3).astype('float32')
    with imperative.guard():
        v = imperative.to_variable(np_inp)
        outs = MyPyLayer()(v)
        loss = fluid.layers.reduce_sum(outs[0])
        loss._backward()
        g = v._gradient()
    assert np.allclose(g, 1 - np.tanh(np_inp) ** 2, atol=1e-5)


def test_tape_memory_bounded():
    """backward() prunes the eager graph: block op/var count must not grow
    across iterations."""
    with imperative.guard():
        fc = imperative.FC(2, bias_attr=False)
        sizes = []
        blk = fluid.default_main_program().global_block()
        for _ in range(4):
            x = imperative.to_variable(np.ones((2, 2), np.float32))
            loss = fluid.layers.reduce_sum(fc(x))
            loss.backward()
            sizes.append((len(blk.ops), len(blk.vars)))
        # op and var counts steady after the first iteration's pruning:
        # consumed to_variable leaves are pruned along with tape temporaries
        assert sizes[1] == sizes[2] == sizes[3]


def test_minimize_memory_bounded():
    """Optimizer update ops under no_record must not pile up in the block."""
    with imperative.guard():
        fc = imperative.FC(2, bias_attr=False)
        sgd = fluid.optimizer.SGDOptimizer(0.01)
        blk = fluid.default_main_program().global_block()
        sizes = []
        for _ in range(4):
            x = imperative.to_variable(np.ones((2, 2), np.float32))
            loss = fluid.layers.reduce_sum(fc(x))
            sgd.minimize(loss)
            sizes.append((len(blk.ops), len(blk.vars)))
        assert sizes[1] == sizes[2] == sizes[3], sizes


def test_no_stale_grad_reapplied():
    """A param absent from this step's loss must not be re-updated with the
    previous step's gradient."""
    with imperative.guard():
        fc_a = imperative.FC(1, bias_attr=False)
        fc_b = imperative.FC(1, bias_attr=False)
        sgd = fluid.optimizer.SGDOptimizer(0.5)
        x = imperative.to_variable(np.ones((2, 3), np.float32))
        # step 1: loss touches both branches
        loss = fluid.layers.reduce_sum(fc_a(x)) + \
            fluid.layers.reduce_sum(fc_b(x))
        sgd.minimize(loss)
        w_a1 = fc_a.parameters()[0].numpy()
        # step 2: loss touches only branch B → branch A must stay put
        x = imperative.to_variable(np.ones((2, 3), np.float32))
        loss = fluid.layers.reduce_sum(fc_b(x))
        sgd.minimize(loss)
        assert np.array_equal(fc_a.parameters()[0].numpy(), w_a1)


def test_minimize_no_trainable_params_is_noop():
    with imperative.guard():
        sgd = fluid.optimizer.SGDOptimizer(0.1)
        x = imperative.to_variable(np.ones((2, 2), np.float32))
        loss = fluid.layers.reduce_sum(x * x)
        ops, pgs = sgd.minimize(loss)  # no Parameters involved
        assert pgs == []
        assert np.allclose(x.gradient(), 2 * np.ones((2, 2)))


def test_eval_propagates_to_sublayers():
    class Net(imperative.Layer):
        def __init__(self):
            super(Net, self).__init__()
            self.bn = imperative.BatchNorm(3)

        def forward(self, x):
            return self.bn(x)

    with imperative.guard():
        net = Net()
        net.eval()
        assert net.bn._is_test is True
        net.train()
        assert net.bn._is_test is False


def test_control_flow_rejected():
    with imperative.guard():
        with pytest.raises(NotImplementedError):
            i = fluid.layers.fill_constant([1], 'int32', 0)
            n = fluid.layers.fill_constant([1], 'int32', 4)
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond)
            with w.block():
                fluid.layers.increment(i)


def test_state_dict_roundtrip():
    with imperative.guard():
        fc = imperative.FC(3)
        x = imperative.to_variable(np.ones((1, 2), np.float32))
        out1 = np.asarray(fc(x).numpy())
        state = fc.state_dict()
        # perturb then restore
        for p in fc.parameters():
            p._ivalue = p._ivalue + 1.0
        out2 = np.asarray(fc(x).numpy())
        assert not np.allclose(out1, out2)
        fc.set_dict(state)
        out3 = np.asarray(fc(x).numpy())
        assert np.allclose(out1, out3)


def test_dygraph_matches_static_numerics():
    """The SAME model with the SAME weights and data must produce the
    same loss and the same post-step weights in imperative (dygraph)
    and declarative (program) mode — the consistency contract between
    the two execution paths."""
    rng = np.random.RandomState(7)
    w0 = rng.randn(4, 2).astype('float32') * 0.3
    xb = rng.rand(8, 4).astype('float32')
    yb = rng.rand(8, 2).astype('float32')
    lr = 0.1

    # --- static program mode
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            y = fluid.layers.data('y', shape=[2], dtype='float32')
            p = fluid.layers.fc(x, 2, bias_attr=False,
                                param_attr=fluid.ParamAttr(
                                    name='cmp_w',
                                    initializer=fluid.initializer.
                                    NumpyArrayInitializer(w0)))
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(fluid.layers.elementwise_sub(p, y)))
            fluid.optimizer.SGD(lr).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ls, = exe.run(main, feed={'x': xb, 'y': yb}, fetch_list=[loss])
        w_static = np.asarray(scope.get('cmp_w')).copy()
    loss_static = float(np.asarray(ls).ravel()[0])

    # --- dygraph mode, same weights
    with imperative.guard():
        fc = imperative.FC(2, bias_attr=False,
                           param_attr=fluid.ParamAttr(
                               initializer=fluid.initializer.
                               NumpyArrayInitializer(w0)))
        sgd = fluid.optimizer.SGDOptimizer(learning_rate=lr)
        xv = imperative.to_variable(xb)
        yv = imperative.to_variable(yb)
        out = fc(xv)
        l = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(out, yv)))
        sgd.minimize(l)
        loss_dy = float(np.asarray(l.numpy()).reshape(()))
        w_dy = np.asarray(list(fc.parameters())[0].numpy())

    np.testing.assert_allclose(loss_dy, loss_static, rtol=1e-5)
    np.testing.assert_allclose(w_dy, w_static, rtol=1e-5, atol=1e-6)
