"""LLaMA model family tests: rms_norm/rope ops, GQA, training convergence,
ring-vs-flash equivalence under a seq-sharded mesh, TP annotations."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.models import llama


def _run_single(x_fn, feed, fetch):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = x_fn()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=[out[f] for f in fetch])
    return [np.asarray(r) for r in res]


def test_rms_norm_matches_numpy():
    x = np.random.RandomState(0).randn(2, 5, 8).astype('float32')

    def build():
        xv = layers.data('x', shape=[5, 8], dtype='float32')
        return {'y': layers.rms_norm(xv)}

    y, = _run_single(build, {'x': x}, ['y'])
    expect = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
    assert np.allclose(y, expect, atol=1e-5)


def test_rope_rotation_properties():
    B, H, T, D = 2, 3, 8, 16
    x = np.random.RandomState(1).randn(B, H, T, D).astype('float32')

    def build():
        xv = layers.data('x', shape=[H, T, D], dtype='float32')
        return {'y': layers.rope(xv, theta=10000.0)}

    y, = _run_single(build, {'x': x}, ['y'])
    # norm-preserving per feature pair
    assert np.allclose(np.linalg.norm(y, axis=-1),
                       np.linalg.norm(x, axis=-1), rtol=1e-4)
    # position 0 is unrotated
    assert np.allclose(y[:, :, 0], x[:, :, 0], atol=1e-5)


def test_rope_relative_position_property():
    """dot(rope(q)[t], rope(k)[t+s]) must depend only on the offset s: feed
    the SAME q and k vector at every position and check the band structure.
    Catches rotation-direction sign errors that norm checks cannot."""
    D = 16
    rng = np.random.RandomState(4)
    qv = rng.randn(D).astype('float32')
    kv = rng.randn(D).astype('float32')
    T = 8
    x = np.stack([np.tile(qv, (T, 1)), np.tile(kv, (T, 1))])  # [2, T, D]
    x = x[None]                                               # [1, 2, T, D]

    def build():
        xv = layers.data('x', shape=[2, T, D], dtype='float32')
        return {'y': layers.rope(xv, theta=100.0)}

    y, = _run_single(build, {'x': x}, ['y'])
    yq, yk = y[0, 0], y[0, 1]                                  # [T, D]
    dots = yq @ yk.T                                           # [T, T]
    for s in range(-3, 4):
        band = np.diagonal(dots, offset=s)
        assert np.allclose(band, band[0], atol=1e-3), (s, band)
    # and it genuinely varies with s (not a constant matrix)
    assert abs(np.diagonal(dots, 0)[0] - np.diagonal(dots, 3)[0]) > 1e-4


def test_gqa_attention_equals_repeated_heads():
    """Grouped K/V (Hkv < H) must equal full attention with K/V heads
    explicitly repeated — across ref, flash, and ring paths."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.attention import flash_attention, _ref_attention
    B, H, Hkv, T, D = 2, 4, 2, 16, 8
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(B, H, T, D).astype('float32'))
    k = jnp.asarray(rng.randn(B, Hkv, T, D).astype('float32'))
    v = jnp.asarray(rng.randn(B, Hkv, T, D).astype('float32'))
    k_full = jnp.repeat(k, H // Hkv, axis=1)
    v_full = jnp.repeat(v, H // Hkv, axis=1)
    scale = D ** -0.5

    ref_g = _ref_attention(q, k, v, True, scale)
    ref_f = _ref_attention(q, k_full, v_full, True, scale)
    assert np.allclose(ref_g, ref_f, atol=1e-5)

    fl_g = flash_attention(q, k, v, causal=True)
    assert np.allclose(np.asarray(fl_g), np.asarray(ref_f), atol=1e-4)

    if len(jax.devices()) >= 2:
        from paddle_tpu.parallel.mesh import make_mesh
        from paddle_tpu.parallel.ring_attention import ring_attention
        mesh = make_mesh(data=1, model=1, pipe=1, seq=2,
                         devices=jax.devices()[:2])
        ring = ring_attention(q, k, v, mesh, causal=True)
        assert np.allclose(np.asarray(ring), np.asarray(ref_f), atol=1e-4)


def test_llama_tiny_converges():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = llama.build('tiny', lr=1e-3)
    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for _ in range(25):
        rows = [np.cumsum(np.ones(20, np.int64)) * 3 % 250 + 2
                for _ in range(8)]
        feed = llama.make_batch(rows, 32)
        l, = exe.run(main, feed=feed, fetch_list=[out['loss']])
        losses.append(float(np.asarray(l).reshape(())))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_llama_gqa_shapes():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = llama.llama('tiny')
    # kv projections are Hkv*dh wide, q is H*dh
    blk = main.global_block()
    cfg = out['config']
    d_head = cfg['d_model'] // cfg['n_head']
    wq = blk.var('layer_0_att_q_w')
    wk = blk.var('layer_0_att_k_w')
    assert wq.shape[-1] == cfg['n_head'] * d_head
    assert wk.shape[-1] == cfg['n_kv_head'] * d_head


def test_llama_tp_annotations():
    from jax.sharding import PartitionSpec as P
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        llama.build('tiny')
    applied = llama.shard(main)
    spec = dict(applied)
    assert spec['layer_0_att_q_w'] == P(None, 'model')
    assert spec['layer_0_att_o_w'] == P('model', None)
    assert spec['layer_0_ffn_fc1_w'] == P(None, 'model')
    assert spec['layer_0_ffn_fc3_w'] == P(None, 'model')
    assert spec['layer_0_ffn_fc2_w'] == P('model', None)
    assert spec['tok_emb'] == P('model', None)


def test_llama_ring_equals_flash_on_mesh():
    """The same ring-attention program must produce identical logits on a
    seq-sharded mesh as on a single device (exact attention both ways)."""
    import jax
    from paddle_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 8:
        pytest.skip('needs 8 virtual devices')

    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = llama.llama('tiny', use_ring=True)
    rows = [rng.randint(3, 250, 31) for _ in range(4)]
    feed = llama.make_batch(rows, 32)

    scope = fluid.Scope()
    exe1 = fluid.Executor()
    with fluid.scope_guard(scope):
        exe1.run(startup)
        single, = exe1.run(main, feed=feed, fetch_list=[out['logits']])
        single = np.asarray(single)

        mesh = make_mesh(data=2, model=2, pipe=1, seq=2)
        llama.shard(main)
        exe2 = fluid.Executor(mesh=mesh)
        with mesh:
            sharded, = exe2.run(main, feed=feed,
                                fetch_list=[out['logits']])
        sharded = np.asarray(sharded)
    assert np.allclose(single, sharded, atol=2e-2), (
        np.abs(single - sharded).max())


def test_llama_bf16_builds_and_steps():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = llama.build('tiny', dtype='bfloat16', lr=1e-3)
    exe = fluid.Executor()
    exe.run(startup)
    rows = [np.arange(2, 22) for _ in range(4)]
    feed = llama.make_batch(rows, 32)
    l, = exe.run(main, feed=feed, fetch_list=[out['loss']])
    assert np.isfinite(np.asarray(l)).all()


def test_kv_cache_decoder_continues_pattern():
    """Train on a cyclic +3 pattern; the KV-cache decoder must continue
    it, and its prefill must agree with the teacher-forcing program."""
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = llama.build('tiny', lr=2e-3)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(80):
            starts = rng.randint(0, 250, 8)
            rows = [(2 + (s + 3 * np.arange(25)) % 250) for s in starts]
            exe.run(main, feed=llama.make_batch(rows, 32),
                    fetch_list=[out['loss']])
        dec = llama.make_decoder(scope, 'tiny')
        prompt = (2 + (7 + 3 * np.arange(6)) % 250).reshape(1, 6)
        gen = dec(prompt, 10)
        expect = 2 + (7 + 3 * np.arange(16)) % 250
        assert gen.shape == (1, 16)
        assert (gen[0][6:] == expect[6:]).mean() > 0.8, gen

        # decoder prefill logits == program logits on the same prefix
        feed = llama.make_batch([2 + (7 + 3 * np.arange(17)) % 250], 32)
        prog_logits, = exe.run(main, feed=feed,
                               fetch_list=[out['logits']])
        prog_next = np.asarray(prog_logits)[0, 5].argmax()
        assert prog_next == gen[0][6]


def test_decoder_sampling_temperature():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        llama.build('tiny', lr=1e-3)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        dec = llama.make_decoder(scope, 'tiny', temperature=1.0)
        prompt = np.arange(2, 8).reshape(1, 6)
        a = dec(prompt, 6, seed=1)
        b = dec(prompt, 6, seed=2)
    # untrained model at T=1: different seeds give different samples
    assert not np.array_equal(a, b)
