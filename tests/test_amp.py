"""bf16 auto-mixed-precision (measured split policy).

Pins the executor AMP contract (core/executor.py, measurements in
PERF.md):
- conv-class op outputs STAY bf16 (flow-through: activations half-width
  through CNN BN/relu/residual chains — measured +25% on ResNet-50)
- matmul-class op outputs cast back to f32 (flow-through measured
  slower on the transformer)
- elementwise glue follows bf16 instead of promoting back to f32
- norm statistics / softmax / cross-entropy compute internally in f32,
  so the loss is f32 and finite, and training converges under AMP
Parity: reference contrib mixed-precision era behavior
(float16 lists in contrib docs); bf16 replaces fp16 on TPU (same
exponent range as f32 — no loss scaling needed).
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _run_amp_program(build_fn, feed, fetch, steps=1):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            fetches = build_fn()
    main.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            outs = exe.run(main, feed=feed, fetch_list=fetches,
                           return_numpy=False)
    return outs


def test_amp_matmul_output_cast_back_f32():
    """matmul class: computes in bf16 but the output returns f32 (the
    cast fuses into the GEMM epilogue; measured faster than bf16
    flow-through for transformer-shaped programs)."""
    x = np.random.RandomState(0).rand(4, 8).astype('float32')

    def build():
        d = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(d, 16, bias_attr=False,
                      param_attr=fluid.ParamAttr(name='w_amp'))
        loss = layers.reduce_mean(h)
        return [h, loss]

    h, loss = _run_amp_program(build, {'x': x}, None)
    import jax.numpy as jnp
    assert h.dtype == jnp.float32, h.dtype
    assert np.isfinite(float(np.asarray(loss)))


def test_amp_conv_output_flows_bf16():
    """conv class: output stays bf16, and the downstream BN/relu residual
    chain (elementwise _AMP_MATCH rule) keeps it bf16 instead of
    promoting back to f32."""
    x = np.random.RandomState(1).rand(2, 3, 8, 8).astype('float32')

    def build():
        d = layers.data('img', shape=[3, 8, 8], dtype='float32')
        c = layers.conv2d(d, num_filters=4, filter_size=3, padding=1,
                          bias_attr=False)
        c = layers.batch_norm(c, act='relu')
        c2 = layers.conv2d(c, num_filters=4, filter_size=3, padding=1,
                           bias_attr=False)
        res = layers.elementwise_add(c, c2)
        return [c, res]

    c, res = _run_amp_program(build, {'img': x}, None)
    import jax.numpy as jnp
    assert c.dtype == jnp.bfloat16, c.dtype
    assert res.dtype == jnp.bfloat16, res.dtype


def test_amp_layer_norm_stats_f32():
    """layer_norm on a bf16 input: Y in bf16, but the normalization must
    match an f32 reference to f32-stats accuracy (not bf16-stats)."""
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    rng = np.random.RandomState(2)
    x = (rng.rand(8, 64).astype('float32') * 3 + 100).astype(
        jnp.bfloat16)  # large mean: bf16 stats would be visibly wrong
    outs = get_op('layer_norm').impl(
        None, {'X': jnp.asarray(x)}, {'begin_norm_axis': 1})
    y = np.asarray(outs['Y'], dtype='float32')
    assert outs['Y'].dtype == jnp.bfloat16
    assert outs['Mean'].dtype == jnp.float32
    xf = np.asarray(x, dtype='float32')
    ref = (xf - xf.mean(1, keepdims=True)) / np.sqrt(
        xf.var(1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(y, ref, atol=2e-2)


def test_amp_training_converges():
    """A small conv+BN+fc classifier must still train to low loss under
    AMP — the end-to-end guard for the whole policy."""
    rng = np.random.RandomState(3)
    imgs = rng.rand(16, 1, 8, 8).astype('float32')
    lbls = (imgs.mean(axis=(1, 2, 3)) > 0.5).astype('int64').reshape(-1, 1)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            d = layers.data('img', shape=[1, 8, 8], dtype='float32')
            lb = layers.data('lbl', shape=[1], dtype='int64')
            c = layers.conv2d(d, num_filters=8, filter_size=3, padding=1)
            c = layers.batch_norm(c, act='relu')
            p = layers.pool2d(c, pool_size=8, pool_type='avg',
                              global_pooling=True)
            logits = layers.fc(p, 2)
            loss = layers.reduce_mean(
                layers.softmax_with_cross_entropy(logits, lb))
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    main.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    feed = {'img': imgs, 'lbl': lbls}
    with fluid.scope_guard(scope):
        exe.run(startup)
        first = None
        for i in range(60):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            v = float(np.asarray(lv).ravel()[0])
            if first is None:
                first = v
    assert np.isfinite(v)
    assert v < first * 0.5, (first, v)


def test_amp_softmax_ce_loss_is_f32():
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    rng = np.random.RandomState(4)
    logits = jnp.asarray(rng.randn(4, 10).astype('float32'),
                         dtype=jnp.bfloat16)
    lbl = jnp.asarray(rng.randint(0, 10, (4, 1)))
    outs = get_op('softmax_with_cross_entropy').impl(
        None, {'Logits': logits, 'Label': lbl}, {})
    assert outs['Loss'].dtype == jnp.float32
    # matches f32 computation to bf16-logit rounding only
    lf = np.asarray(logits, dtype='float32')
    ref = -np.take_along_axis(
        lf - np.log(np.exp(lf).sum(-1, keepdims=True)),
        np.asarray(lbl), axis=-1)
    np.testing.assert_allclose(np.asarray(outs['Loss']), ref, atol=1e-3)
