"""flash_attention kernel parity vs composed attention."""
import numpy as np
import pytest

import jax

from paddle_tpu.ops.attention import flash_attention, _ref_attention


@pytest.fixture(autouse=True)
def _force_kernel_path(monkeypatch):
    """flash_attention routes short-T shapes to the composed path
    (measured faster on TPU below T=512 — see ops/attention.py); these
    are KERNEL parity tests, so force the kernel on at any size."""
    from paddle_tpu.ops import attention as att
    monkeypatch.setattr(att, '_FWD_PALLAS_MIN_T', 0)


def _rand(shape, seed):
    return np.random.RandomState(seed).normal(size=shape).astype('float32')


def test_forward_parity():
    q, k, v = (_rand((2, 2, 128, 16), i) for i in range(3))
    out = flash_attention(q, k, v)
    ref = _ref_attention(q, k, v, False, 16 ** -0.5)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_causal_parity():
    q, k, v = (_rand((2, 2, 128, 16), i + 3) for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    ref = _ref_attention(q, k, v, True, 16 ** -0.5)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_causal_decode_shape_end_aligned():
    # Tq=1, Tk=128 (cached decode): last query must see ALL keys
    q = _rand((1, 2, 1, 16), 0)
    k, v = _rand((1, 2, 128, 16), 1), _rand((1, 2, 128, 16), 2)
    out = flash_attention(q, k, v, causal=True, block_q=1)
    ref = _ref_attention(q, k, v, True, 16 ** -0.5)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_k_length_masks_padding():
    q, k, v = (_rand((2, 2, 128, 16), i + 7) for i in range(3))
    k_len = np.array([60, 128], np.int32)
    out = flash_attention(q, k, v, k_len=k_len)
    ref = _ref_attention(q, k, v, False, 16 ** -0.5, k_len)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    # row 0 must be invariant to garbage in the padded K/V tail
    k2, v2 = k.copy(), v.copy()
    k2[0, :, 60:] = 99.0
    v2[0, :, 60:] = -99.0
    out2 = flash_attention(q, k2, v2, k_len=k_len)
    np.testing.assert_allclose(out[0], out2[0], atol=2e-5)


def test_gradient_parity():
    q, k, v = (_rand((1, 2, 128, 16), i + 11) for i in range(3))
    k_len = np.array([100], np.int32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, k_len=k_len).sum()

    def loss_ref(q, k, v):
        return _ref_attention(q, k, v, True, 16 ** -0.5, k_len).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=2e-5)


def test_short_t_routes_to_composed_path(monkeypatch):
    """Default dispatch (no kernel forcing): below _FWD_PALLAS_MIN_T the
    op must lower to the composed path; at/above it, the pallas kernel.
    Also pins the AMP precision contract on the composed route: bf16
    in/out with f32 softmax internals (matches the kernel)."""
    from paddle_tpu.ops import attention as att
    monkeypatch.setattr(att, '_FWD_PALLAS_MIN_T', 512)  # the default
    calls = []
    real_ref = att._ref_attention

    def spy(*a, **kw):
        calls.append(a[0].shape)
        return real_ref(*a, **kw)

    monkeypatch.setattr(att, '_ref_attention', spy)
    import jax.numpy as jnp
    q, k, v = (jnp.asarray(_rand((1, 2, 256, 16), i), jnp.bfloat16)
               for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    assert len(calls) == 1, 'T=256 must route to the composed path'
    assert out.dtype == jnp.bfloat16
    # f32-softmax internals: close to the all-f32 reference within
    # bf16 input-rounding error only
    ref = real_ref(*(x.astype(jnp.float32) for x in (q, k, v)),
                   True, 16 ** -0.5)
    np.testing.assert_allclose(np.asarray(out, dtype='float32'),
                               np.asarray(ref), atol=2e-2)
    calls.clear()
    q2, k2, v2 = (_rand((1, 2, 512, 16), i + 3) for i in range(3))
    flash_attention(q2, k2, v2)  # interpret-mode kernel on CPU
    assert not calls, 'T=512 must route to the pallas kernel'


@pytest.mark.parametrize('cfg', [
    dict(B=2, H=4, Hkv=4, Tq=128, Tk=128, D=32, causal=False, klen=False),
    dict(B=2, H=4, Hkv=4, Tq=128, Tk=128, D=32, causal=True, klen=True),
    dict(B=2, H=8, Hkv=2, Tq=128, Tk=128, D=32, causal=True, klen=False),
    dict(B=2, H=8, Hkv=2, Tq=128, Tk=256, D=32, causal=True, klen=True),
])
@pytest.mark.parametrize('dkv_variant', ['resident', 'streamed'])
def test_pallas_backward_kernels_gradient_parity(cfg, dkv_variant,
                                                 monkeypatch):
    """The pallas dq/dkv kernels normally engage only above the HBM score
    threshold (long-T); force them on so regressions surface here, not on
    a long-sequence TPU run.  Both dK/dV variants are exercised: the
    VMEM-resident register-accumulation one (short Tq) and the q-streaming
    4-D-grid one (long Tq)."""
    from paddle_tpu.ops import attention as att
    monkeypatch.setattr(att, '_BWD_PALLAS_SCORE_BYTES', 0)
    if dkv_variant == 'streamed':
        monkeypatch.setattr(att, '_DKV_RESIDENT_MAX_T', 0)
    # guard against the gates silently vacating this test (it happened:
    # _FWD_PALLAS_MIN_T was added after this test and routed its shapes
    # away from the kernels until the autouse fixture above restored them)
    engaged = {}
    real_bwd = att._flash_backward

    def spy_bwd(*a, **kw):
        engaged['bwd'] = True
        return real_bwd(*a, **kw)

    monkeypatch.setattr(att, '_flash_backward', spy_bwd)
    rng = np.random.RandomState(9)
    B, H, Hkv, Tq, Tk, D = (cfg[k] for k in 'B H Hkv Tq Tk D'.split())
    q = rng.randn(B, H, Tq, D).astype('float32')
    k = rng.randn(B, Hkv, Tk, D).astype('float32')
    v = rng.randn(B, Hkv, Tk, D).astype('float32')
    kl = (np.asarray(rng.randint(Tk // 2, Tk + 1, B), np.int32)
          if cfg['klen'] else None)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=cfg['causal'], k_len=kl,
                                block_q=64, block_k=64) ** 2).sum()

    def loss_ref(q, k, v):
        return (_ref_attention(q, k, v, cfg['causal'], D ** -0.5,
                               kl) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert engaged.get('bwd'), \
        'pallas backward never engaged — a routing gate vacated this test'
    for a, b, n in zip(gf, gr, 'dq dk dv'.split()):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=n)
