"""Multi-step fused execution (Executor.run_steps) + async feed pipeline.

The contract under test: K iterations fused into ONE lax.scan launch are
bitwise-identical on CPU to K sequential exe.run calls — including the
per-step RNG folding (dropout masks) and the check_nan fused flag — and
the lowering cache retraces exactly once per (program, feeds, fetches, K).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import executor as executor_mod


def _train_model(seed=7, dropout=0.5):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            if dropout:
                h = fluid.layers.dropout(h, dropout_prob=dropout)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.Adam(0.01).minimize(loss)
    return main, startup, loss


def _feeds(K, batch=6, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.randn(batch, 8).astype('float32'),
             'lbl': rng.randint(0, 4, (batch, 1)).astype('int64')}
            for _ in range(K)]


def _run_sequential(main, startup, loss, feeds, check_nan=False):
    exe, scope = fluid.Executor(check_nan=check_nan), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = [exe.run(main, feed=f, fetch_list=[loss])[0]
                  for f in feeds]
    return np.concatenate([np.asarray(v).reshape(1, -1) for v in losses]), \
        scope


def test_run_steps_matches_sequential_bitwise():
    K = 4
    main, startup, loss = _train_model()
    feeds = _feeds(K)
    seq_losses, seq_scope = _run_sequential(main, startup, loss, feeds)

    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        stacked, = exe.run_steps(main, feed_list=feeds, fetch_list=[loss])
    assert stacked.shape[0] == K
    # fetches stacked per step, bitwise equal to the sequential fetches
    assert stacked.reshape(K, -1).tobytes() == seq_losses.tobytes()
    # params + optimizer state (Adam moments, beta powers) bitwise equal
    assert set(scope.vars) == set(seq_scope.vars)
    for n in scope.vars:
        a, b = np.asarray(seq_scope.vars[n]), np.asarray(scope.vars[n])
        assert a.tobytes() == b.tobytes(), 'mismatch in %s' % n


def test_run_steps_rng_folds_per_step():
    # all-ones feeds: with dropout, per-step losses must DIFFER (distinct
    # masks per scan step), and match the sequential RNG stream bitwise
    K = 3
    main, startup, loss = _train_model(dropout=0.5)
    f = {'x': np.ones((16, 8), 'float32'),
         'lbl': np.zeros((16, 1), 'int64')}
    feeds = [f] * K
    seq_losses, _ = _run_sequential(main, startup, loss, feeds)

    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        stacked, = exe.run_steps(main, feed_list=feeds, fetch_list=[loss])
    assert stacked.reshape(K, -1).tobytes() == seq_losses.tobytes()
    assert len({v.tobytes() for v in stacked}) == K, \
        'per-step dropout masks must differ inside one launch'


def test_run_steps_prestacked_dict_and_step_count_validation():
    K = 3
    main, startup, loss = _train_model(dropout=0.0)
    feeds = _feeds(K)
    stacked_feed = {k: np.stack([f[k] for f in feeds]) for k in feeds[0]}

    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ValueError, match='steps'):
            exe.run_steps(main, feed_list=stacked_feed, fetch_list=[loss])
        with pytest.raises(ValueError, match='leading dim'):
            exe.run_steps(main, feed_list=stacked_feed, fetch_list=[loss],
                          steps=K + 1)
        out, = exe.run_steps(main, feed_list=stacked_feed,
                             fetch_list=[loss], steps=K)
    assert out.shape[0] == K


def test_run_steps_retraces_once_per_cache_key():
    main, startup, loss = _train_model(dropout=0.0)
    feeds = _feeds(6)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        before = executor_mod._TRACE_COUNT[0]
        exe.run_steps(main, feed_list=feeds[:3], fetch_list=[loss])
        after_first = executor_mod._TRACE_COUNT[0]
        # one scan body trace for the whole 3-step executable
        assert after_first == before + 1
        exe.run_steps(main, feed_list=feeds[3:], fetch_list=[loss])
        assert executor_mod._TRACE_COUNT[0] == after_first, \
            'same (program, feeds, fetches, K) must reuse the executable'
        # a different K is a different executable
        exe.run_steps(main, feed_list=feeds[:2], fetch_list=[loss])
        assert executor_mod._TRACE_COUNT[0] == after_first + 1


def test_run_steps_check_nan_parity_and_raise():
    K = 3
    main, startup, loss = _train_model(dropout=0.3)
    feeds = _feeds(K)
    seq_losses, _ = _run_sequential(main, startup, loss, feeds,
                                    check_nan=True)
    exe, scope = fluid.Executor(check_nan=True), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        stacked, = exe.run_steps(main, feed_list=feeds, fetch_list=[loss])
        assert stacked.reshape(K, -1).tobytes() == seq_losses.tobytes()

    # a nan poisoning ANY step of the launch trips the scan-reduced flag
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        w = fluid.layers.create_parameter([2, 1], 'float32', name='w_ms')
        loss2 = fluid.layers.reduce_mean(
            fluid.layers.sqrt(fluid.layers.matmul(x, w)))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss2)
    exe2, scope2 = fluid.Executor(check_nan=True), fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup2)
        good = {'x': np.array([[1.0, 1.0]], 'float32')}
        bad = {'x': np.array([[-100.0, -100.0]], 'float32')}
        with pytest.raises(RuntimeError, match='w_ms'):
            exe2.run_steps(main2, feed_list=[good, bad, good],
                           fetch_list=[loss2])


def test_run_steps_counter_shared_with_single_runs():
    # run(1) + run_steps(2) consumes the same RNG stream as run(3): the
    # counter advances by K per launch, so mixing paths stays coherent
    K = 3
    main, startup, loss = _train_model(dropout=0.5)
    feeds = _feeds(K)
    seq_losses, _ = _run_sequential(main, startup, loss, feeds)

    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        first, = exe.run(main, feed=feeds[0], fetch_list=[loss])
        rest, = exe.run_steps(main, feed_list=feeds[1:], fetch_list=[loss])
    mixed = np.concatenate([np.asarray(first).reshape(1, -1),
                            np.asarray(rest).reshape(K - 1, -1)])
    assert mixed.tobytes() == seq_losses.tobytes()


def test_run_steps_data_parallel_matches_single_device():
    from paddle_tpu.parallel.mesh import make_mesh
    K = 4
    feeds = _feeds(K, batch=16)
    main, startup, loss = _train_model(seed=3, dropout=0.0)
    seq_losses, _ = _run_sequential(main, startup, loss, feeds)

    main2, startup2, loss2 = _train_model(seed=3, dropout=0.0)
    exe = fluid.Executor(mesh=make_mesh(data=8))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup2)
        stacked, = exe.run_steps(main2, feed_list=feeds,
                                 fetch_list=[loss2])
    np.testing.assert_allclose(stacked.reshape(K, -1), seq_losses,
                               rtol=1e-5, atol=1e-6)


def test_compiled_program_num_iteration_per_drop_scope():
    # ExecutionStrategy.num_iteration_per_drop_scope=K + a list feed
    # routes through run_steps, K iterations per launch, results stacked
    # across ALL steps and bitwise equal to the sequential path
    N, K = 5, 2
    main, startup, loss = _train_model(dropout=0.4)
    feeds = _feeds(N)
    seq_losses, seq_scope = _run_sequential(main, startup, loss, feeds)

    es = fluid.ExecutionStrategy()
    es.num_iteration_per_drop_scope = K
    compiled = fluid.CompiledProgram(main, exec_strategy=es)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out, = exe.run(compiled, feed=feeds, fetch_list=[loss])
    assert out.shape[0] == N
    assert out.reshape(N, -1).tobytes() == seq_losses.tobytes()
    for n in scope.vars:
        assert np.asarray(scope.vars[n]).tobytes() == \
            np.asarray(seq_scope.vars[n]).tobytes(), n


def test_trainer_steps_per_launch_events_and_parity():
    from paddle_tpu import layers

    def reader():
        rng = np.random.RandomState(0)
        w = np.array([[1.5], [-2.0], [0.5]], 'float32')
        for _ in range(7):   # 7 steps: 3 launches of K=3, 3, 1 (tail)
            xb = rng.rand(4, 3).astype('float32')
            yield [(x, (x[None, :] @ w)[0]) for x in xb]

    def train_func():
        x = layers.data('x', shape=[3], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name='w'))
        return layers.reduce_mean(layers.square(pred - y))

    def run(steps_per_launch):
        seen = {'begin': [], 'end': [], 'metrics': []}

        def handler(ev):
            if isinstance(ev, fluid.BeginStepEvent):
                seen['begin'].append(ev.step)
            elif isinstance(ev, fluid.EndStepEvent):
                seen['end'].append(ev.step)
                seen['metrics'].append(
                    np.asarray(ev.metrics[0]).ravel()[0])

        trainer = fluid.Trainer(
            train_func, lambda: fluid.optimizer.SGDOptimizer(0.3))
        trainer.train(1, handler, reader=lambda: reader(),
                      feed_order=['x', 'y'],
                      steps_per_launch=steps_per_launch)
        return seen

    single = run(1)
    fused = run(3)
    # events still fire per STEP, in order, with per-step metric values
    assert fused['begin'] == single['begin'] == list(range(7))
    assert fused['end'] == single['end'] == list(range(7))
    np.testing.assert_array_equal(np.asarray(fused['metrics']),
                                  np.asarray(single['metrics']))


# ---------------------------------------------------------------- feed queue

def test_feed_prefetcher_preserves_order_and_drains():
    from paddle_tpu.data_feeder import FeedPrefetcher
    feeds = ({'x': np.full((2, 3), i, 'float32'),
              'y': np.full((2,), -i, 'int64')} for i in range(10))
    pf = FeedPrefetcher(feeds, steps=4, capacity=2, to_device=False)
    got = list(pf)
    assert [k for _, k in got] == [4, 4, 2]   # partial tail flushed
    seen = []
    for stacked, k in got:
        assert stacked['x'].shape == (k, 2, 3)
        seen.extend(stacked['x'][:, 0, 0].tolist())
    assert seen == list(range(10)), 'prefetch must preserve feed order'
    # a drained prefetcher yields nothing more and close() is idempotent
    assert list(pf) == []
    pf.close()
    pf.close()


def test_feed_prefetcher_device_put_superbatch():
    from paddle_tpu.data_feeder import FeedPrefetcher
    feeds = [{'x': np.full((2,), i, 'float32')} for i in range(4)]
    (stacked, k), = list(FeedPrefetcher(feeds, steps=4))
    assert k == 4
    assert hasattr(stacked['x'], 'devices'), \
        'superbatch must be device-resident'
    np.testing.assert_array_equal(np.asarray(stacked['x'])[:, 0],
                                  [0, 1, 2, 3])


def test_feed_prefetcher_propagates_reader_error():
    from paddle_tpu.data_feeder import FeedPrefetcher

    def gen():
        yield {'x': np.zeros((2,), 'float32')}
        yield {'x': np.ones((2,), 'float32')}
        raise RuntimeError('reader exploded')

    pf = FeedPrefetcher(gen(), steps=2, to_device=False)
    it = iter(pf)
    stacked, k = next(it)
    assert k == 2
    with pytest.raises(RuntimeError, match='reader exploded'):
        next(it)


def test_feed_prefetcher_key_mismatch_is_an_error():
    from paddle_tpu.data_feeder import FeedPrefetcher
    feeds = [{'x': np.zeros(2, 'float32')}, {'y': np.zeros(2, 'float32')}]
    with pytest.raises(ValueError, match='disagree'):
        list(FeedPrefetcher(feeds, steps=2, to_device=False))


def test_feed_prefetcher_feeds_run_steps():
    from paddle_tpu.data_feeder import FeedPrefetcher
    K = 2
    main, startup, loss = _train_model(dropout=0.0)
    feeds = _feeds(4)
    seq_losses, seq_scope = _run_sequential(main, startup, loss, feeds)

    exe, scope = fluid.Executor(), fluid.Scope()
    got = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for superbatch, k in FeedPrefetcher(feeds, steps=K):
            out, = exe.run_steps(main, feed_list=superbatch, steps=k,
                                 fetch_list=[loss])
            got.append(out.reshape(k, -1))
    assert np.concatenate(got).tobytes() == seq_losses.tobytes()
    for n in scope.vars:
        assert np.asarray(scope.vars[n]).tobytes() == \
            np.asarray(seq_scope.vars[n]).tobytes(), n
