"""Direct Program→jaxpr emitter (core/emit): per-rule bitwise parity vs
the kernel reference, whole-program bitwise training parity PT_EMIT=1
vs 0 (run / run_steps / ParallelExecutor, AMP + dropout + Adam, fused
groups, control flow), loud per-program fallback (warn-once counters,
PT_STRICT_EMIT raising, runtime EmitError degradation), launch-report
lowering verdicts, signature-memo sharing, and AOT disk round-trips of
emitted executables."""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.core import emit, registry
from paddle_tpu.core import executor as executor_mod
from paddle_tpu.core.emit import emitter


def _ctx(op_type, amp=False):
    return emitter.EmitCtx(None, None, amp, None, op_type)


# ------------------------------------------- rule-vs-kernel parity sweep
#
# Every registered emit rule must have at least one case here; the sweep
# below fails if a new rule lands without one.  Cases return (ins,
# attrs) with concrete numpy inputs; kernel impl and emit rule must
# agree BITWISE (the rule is a perf overlay, never a second semantics).

def _adam_case(rng, grad_dtype='float32'):
    import jax.numpy as jnp
    g = jnp.asarray(rng.randn(4, 3).astype('float32')).astype(grad_dtype)
    return ({'Param': rng.randn(4, 3).astype('float32'), 'Grad': g,
             'Moment1': rng.randn(4, 3).astype('float32') * 0.1,
             'Moment2': np.abs(rng.randn(4, 3)).astype('float32') * 0.01,
             'Beta1Pow': np.array([0.9 ** 3], 'float32'),
             'Beta2Pow': np.array([0.999 ** 3], 'float32'),
             'LearningRate': np.array([0.01], 'float32')},
            {'beta1': 0.9, 'beta2': 0.999, 'epsilon': 1e-8})


def _fused_case(rng):
    # deterministic fused group (no rng sub-ops: EmitCtx here carries no
    # base key); impl replays, emit dispatches through the kernelgen
    # rule's fallback replay — both must agree bitwise
    def sub(type_, inputs, outputs, attrs=None):
        return {'type': type_, 'inputs': inputs, 'outputs': outputs,
                'input_is_list': {}, 'output_is_list': {},
                'attrs': dict(attrs or {}), 'stop_grad': []}
    subs = [sub('scale', {'X': ['x']}, {'Out': ['t']},
                {'scale': 2.0, 'bias': 0.5, 'bias_after_scale': True}),
            sub('relu', {'X': ['t']}, {'Out': ['y']})]
    return ({'X': [rng.randn(4, 5).astype('float32')]},
            {'sub_ops': subs, 'arg_names': ['x'], 'out_names': ['y']})


def _ew_cases(rng):
    x = rng.randn(4, 5).astype('float32')
    return [
        ({'X': x, 'Y': rng.randn(4, 5).astype('float32')}, {}),     # lax
        ({'X': x, 'Y': rng.randn(5).astype('float32')}, {}),        # jnp
        ({'X': x, 'Y': rng.randn(4, 1).astype('float32')},
         {'axis': 0}),                                              # jnp
    ]


_RULE_CASES = {
    'adam': lambda rng: [_adam_case(rng),
                         # bf16 grads over f32 moments (llama bf16):
                         # the rule must defer to the kernel's jnp
                         # promotion, not feed lax mixed dtypes
                         _adam_case(rng, grad_dtype='bfloat16')],
    'reshape': lambda rng: [
        ({'X': rng.randn(2, 3, 4).astype('float32')}, {'shape': [0, 12]}),
        ({'X': rng.randn(6, 4).astype('float32')}, {'shape': [2, 3, 4]}),
    ],
    'transpose': lambda rng: [
        ({'X': rng.randn(2, 3, 4).astype('float32')},
         {'axis': [2, 0, 1]}),
    ],
    'elementwise_add': _ew_cases,
    'elementwise_sub': _ew_cases,
    'elementwise_mul': _ew_cases,
    'elementwise_div': lambda rng: [
        ({'X': rng.randn(4, 5).astype('float32'),
          'Y': np.abs(rng.randn(4, 5)).astype('float32') + 0.5}, {}),
        ({'X': rng.randn(4, 5).astype('float32'),
          'Y': np.abs(rng.randn(5)).astype('float32') + 0.5}, {}),
    ],
    'fused_elementwise': lambda rng: [_fused_case(rng)],
}


def _rule_ops():
    return [n for n in registry.op_names()
            if registry.get_op(n).emit is not None]


def test_every_emit_rule_has_a_parity_case():
    missing = [n for n in _rule_ops() if n not in _RULE_CASES]
    assert not missing, ('emit rule(s) registered without a bitwise '
                         'parity case in _RULE_CASES: %s' % missing)


@pytest.mark.parametrize('op_type', sorted(_RULE_CASES))
def test_emit_rule_bitwise_matches_kernel(op_type):
    od = registry.get_op(op_type)
    assert od.emit is not None, 'case exists but rule was unregistered'
    rng = np.random.RandomState(0)
    for ins, attrs in _RULE_CASES[op_type](rng):
        want = od.impl(_ctx(op_type), dict(ins), dict(attrs))
        got = od.emit(_ctx(op_type), dict(ins), dict(attrs))
        assert set(want) == set(got)
        for slot in want:
            if want[slot] is None:
                assert got[slot] is None
                continue
            w, g = np.asarray(want[slot]), np.asarray(got[slot])
            assert w.dtype == g.dtype and w.shape == g.shape, slot
            np.testing.assert_array_equal(w, g, err_msg='%s.%s'
                                          % (op_type, slot))


# --------------------------------------- whole-program bitwise parity

def _train_model(seed=7, amp=True):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            h = fluid.layers.dropout(h, dropout_prob=0.4)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.Adam(0.01).minimize(loss)
    if amp:
        main.set_amp(True)
    return main, startup, loss


def _feeds(K, batch=6, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.randn(batch, 8).astype('float32'),
             'lbl': rng.randint(0, 4, (batch, 1)).astype('int64')}
            for _ in range(K)]


def _train(monkeypatch, pt_emit, runner, amp=True):
    monkeypatch.setenv('PT_EMIT', pt_emit)
    main, startup, loss = _train_model(amp=amp)
    losses, scope = runner(main, startup, loss)
    state = {n: np.asarray(v) for n, v in scope.vars.items()}
    return np.asarray(losses), state


def _assert_bitwise(monkeypatch, runner, amp=True):
    l1, s1 = _train(monkeypatch, '1', runner, amp=amp)
    l0, s0 = _train(monkeypatch, '0', runner, amp=amp)
    np.testing.assert_array_equal(l1, l0)
    assert set(s1) == set(s0)
    for n in s1:   # params AND Adam moments/pows, bit for bit
        np.testing.assert_array_equal(s1[n], s0[n], err_msg=n)


def test_bitwise_parity_run(monkeypatch):
    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [np.asarray(exe.run(main, feed=f,
                                         fetch_list=[loss])[0])
                      for f in _feeds(4)]
        return losses, scope
    _assert_bitwise(monkeypatch, runner)


def test_bitwise_parity_run_no_amp(monkeypatch):
    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [np.asarray(exe.run(main, feed=f,
                                         fetch_list=[loss])[0])
                      for f in _feeds(3)]
        return losses, scope
    _assert_bitwise(monkeypatch, runner, amp=False)


def test_bitwise_parity_run_steps(monkeypatch):
    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            stacked, = exe.run_steps(main, feed_list=_feeds(4),
                                     fetch_list=[loss])
        return np.asarray(stacked), scope
    _assert_bitwise(monkeypatch, runner)


def test_bitwise_parity_parallel_executor(monkeypatch):
    from paddle_tpu.parallel.parallel_executor import ParallelExecutor

    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                                  scope=scope)
            losses = [np.asarray(pe.run([loss.name], feed=f)[0])
                      for f in _feeds(2, batch=8)]
        return losses, scope
    _assert_bitwise(monkeypatch, runner)


def _control_flow_outputs(monkeypatch, pt_emit):
    from paddle_tpu import layers
    monkeypatch.setenv('PT_EMIT', pt_emit)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            i = layers.fill_constant(shape=[1], dtype='int64', value=0)
            n = layers.fill_constant(shape=[1], dtype='int64', value=5)
            acc = layers.fill_constant(shape=[1, 4], dtype='float32',
                                       value=0.0)
            cond = layers.less_than(i, n)
            w = layers.While(cond)
            with w.block():
                layers.assign(acc + fluid.layers.scale(x, scale=1.5), acc)
                layers.increment(i, 1)
                layers.less_than(i, n, cond=cond)
            flag = layers.fill_constant(shape=[1], dtype='bool',
                                        value=True)
            ie = layers.IfElse(flag)
            with ie.true_block():
                ie.output(fluid.layers.scale(acc, scale=2.0))
            with ie.false_block():
                ie.output(fluid.layers.scale(acc, scale=-1.0))
            out, = ie()
    exe, scope = fluid.Executor(), fluid.Scope()
    xv = np.arange(4, dtype='float32').reshape(1, 4) + 0.25
    with fluid.scope_guard(scope):
        iv, av, ov = exe.run(main, feed={'x': xv},
                             fetch_list=[i, acc, out])
    return np.asarray(iv), np.asarray(av), np.asarray(ov)


def test_bitwise_parity_control_flow(monkeypatch):
    """While + IfElse sub-blocks: the engine's dmasks cover sub-block
    ops and the executor threads ectx.emit_engine into _run_block."""
    got = _control_flow_outputs(monkeypatch, '1')
    want = _control_flow_outputs(monkeypatch, '0')
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)
    assert got[0][0] == 5


# --------------------------------------------------- signature sharing

def test_rng_stream_shares_one_memo_signature(monkeypatch):
    """Two structurally-identical bias-add+dropout fused groups differ
    only in their RNG streams and var names — streams travel as traced
    arguments and names are alpha-renamed, so both instances must land
    on ONE memoized signature."""
    monkeypatch.setenv('PT_EMIT', '1')
    emit.clear_memo()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            h = fluid.layers.dropout(fluid.layers.fc(x, 8),
                                     dropout_prob=0.3)
            h = fluid.layers.dropout(fluid.layers.fc(h, 8),
                                     dropout_prob=0.3)
            out = fluid.layers.fc(h, 8)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={'x': np.ones((2, 8), 'float32')},
                fetch_list=[out])
    keys = [k for k in emitter._MEMO if k[0] == 'fused_elementwise'
            and any(sub[0] == 'dropout' for sub in k[1][1])]
    assert len(keys) == 1, keys


# ------------------------------------------------- fallback behavior

def _relu_model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            out = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    return main, startup, out


def test_deny_listed_op_falls_back_loudly(monkeypatch):
    monkeypatch.setenv('PT_EMIT', '1')
    monkeypatch.setattr(emitter, 'DENY_OPS', {'relu'})
    emit.reset_fallbacks()
    main, _, out = _relu_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    xv = np.array([[-1.0, 0.0, 1.0, 2.0]], 'float32')
    before = obs.counters().get('emitter.fallbacks') or 0
    with pytest.warns(RuntimeWarning, match='relu'):
        with fluid.scope_guard(scope):
            got, = exe.run(main, feed={'x': xv}, fetch_list=[out])
    np.testing.assert_array_equal(np.asarray(got),
                                  np.maximum(xv * 2.0, 0.0))
    c = obs.counters()
    assert (c.get('emitter.fallbacks') or 0) == before + 1
    assert (c.get('emitter.fallbacks.relu') or 0) >= 1
    rep = obs.explainer().last_report()
    assert rep['lowering'] == 'emit_fallback:relu'
    # warn-once: the same op type degrading again stays quiet
    with warnings.catch_warnings():
        warnings.simplefilter('error')
        emit.note_fallback('relu', 'again')


def test_strict_emit_raises_naming_op(monkeypatch):
    monkeypatch.setenv('PT_EMIT', '1')
    monkeypatch.setenv('PT_STRICT_EMIT', '1')
    monkeypatch.setattr(emitter, 'DENY_OPS', {'relu'})
    main, _, out = _relu_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        with pytest.raises(emit.EmitFallback, match='relu'):
            exe.run(main, feed={'x': np.ones((1, 4), 'float32')},
                    fetch_list=[out])


def test_runtime_emit_error_degrades_to_traced(monkeypatch):
    """A kernel that draws ctx.rng while its op type is missing from
    the emitter RNG set raises EmitError mid-trace; the executor must
    rebuild that program on the traced path and still produce the
    PT_EMIT=0 numbers."""
    def run_once():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 11
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = fluid.layers.data('x', shape=[5], dtype='float32')
                out = fluid.layers.dropout(x, dropout_prob=0.5)
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            got, = exe.run(main, feed={'x': np.ones((3, 5), 'float32')},
                           fetch_list=[out])
        return np.asarray(got)

    monkeypatch.setenv('PT_EMIT', '0')
    want = run_once()

    monkeypatch.setenv('PT_EMIT', '1')
    monkeypatch.setattr(emitter, 'RNG_OPS',
                        emitter.RNG_OPS - {'dropout'})
    emit.clear_memo()
    emit.reset_fallbacks()
    before = obs.counters().get('emitter.fallbacks') or 0
    with pytest.warns(RuntimeWarning, match='dropout'):
        got = run_once()
    np.testing.assert_array_equal(got, want)
    assert (obs.counters().get('emitter.fallbacks') or 0) == before + 1
    rep = obs.explainer().last_report()
    assert rep['lowering'] == 'emit_fallback:dropout'
    emit.clear_memo()   # drop fns traced under the shrunken RNG set


def test_launch_report_carries_emit_verdict(monkeypatch):
    monkeypatch.setenv('PT_EMIT', '1')
    main, _, out = _relu_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(main, feed={'x': np.ones((2, 4), 'float32')},
                fetch_list=[out])
    rep = obs.explainer().last_report()
    assert rep['lowering'] == 'emit'
    assert 'lowering=emit' in obs.explainer().render_report(rep)


def test_retrace_explainer_names_pt_emit_toggle(monkeypatch):
    main, _, out = _relu_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    xv = np.ones((2, 4), 'float32')
    obs.explainer().reset()
    with fluid.scope_guard(scope):
        monkeypatch.setenv('PT_EMIT', '1')
        exe.run(main, feed={'x': xv}, fetch_list=[out])
        monkeypatch.setenv('PT_EMIT', '0')
        exe.run(main, feed={'x': xv}, fetch_list=[out])
    rep = obs.explainer().last_report()
    assert rep['kind'] == 'retrace'
    assert rep['lowering'] == 'trace'
    assert any('PT_EMIT' in d for d in rep['details'])


def test_unsupported_ops_and_capability():
    from paddle_tpu.core.framework import Operator
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.scale(x, scale=2.0)
        blk = main.global_block()
        blk.ops.append(Operator(blk, 'bogus_op', inputs={'X': x},
                                outputs={'Out': out}, attrs={}))
    gaps = emit.unsupported_ops(main)
    assert gaps == [('bogus_op', 'no registered kernel')]
    assert emitter.op_capability('while')[0]          # executor-native
    assert emitter.op_capability('relu') == (True, 'kernel')
    assert emitter.op_capability('adam') == (True, 'rule')


def test_register_emit_guards():
    with pytest.raises(ValueError, match='unregistered'):
        registry.register_emit('never_registered_op')(lambda c, i, a: {})
    with pytest.raises(ValueError, match='already'):
        registry.register_emit('adam')(lambda c, i, a: {})


# ------------------------------------------------- AOT disk round-trip

def test_emitted_executable_disk_round_trip(tmp_path, monkeypatch):
    """PT_EMIT=1 + PT_CACHE=1: a fresh Executor (fresh L1) must serve
    the EMITTED executable from disk without tracing; flipping to
    PT_EMIT=0 must MISS (fingerprints carry the emitter coverage) and
    compile its own traced twin — to the same bits."""
    monkeypatch.setenv('PT_EMIT', '1')
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    main, startup, loss = _train_model(amp=False)
    feed = _feeds(1)[0]

    exe1, scope1 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope1):
        exe1.run(startup)
        a, = exe1.run(main, feed=feed, fetch_list=[loss])

    exe2, scope2 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup)
        tc = executor_mod._TRACE_COUNT[0]
        b, = exe2.run(main, feed=feed, fetch_list=[loss])
        assert executor_mod._TRACE_COUNT[0] == tc, \
            'second executor must load the emitted AOT executable'
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    monkeypatch.setenv('PT_EMIT', '0')
    misses0 = obs.counters().get('compile_cache.disk_misses') or 0
    exe3, scope3 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope3):
        exe3.run(startup)
        c, = exe3.run(main, feed=feed, fetch_list=[loss])
    assert (obs.counters().get('compile_cache.disk_misses') or 0) \
        > misses0, 'traced run must not be served an emitted artifact'
    assert np.asarray(a).tobytes() == np.asarray(c).tobytes()


def test_fallback_program_shares_traced_artifacts(tmp_path, monkeypatch):
    """A program that FALLS BACK fingerprints with extra=None, so its
    traced artifact is shared with PT_EMIT=0 runs: the second process
    posture (fresh L1, PT_EMIT=0) must disk-hit the entry the fallback
    run stored."""
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    monkeypatch.setattr(emitter, 'DENY_OPS', {'relu'})
    emit.reset_fallbacks()
    main, _, out = _relu_model()
    feed = {'x': np.ones((2, 4), 'float32')}

    monkeypatch.setenv('PT_EMIT', '1')
    exe1, scope1 = fluid.Executor(), fluid.Scope()
    with pytest.warns(RuntimeWarning):
        with fluid.scope_guard(scope1):
            a, = exe1.run(main, feed=feed, fetch_list=[out])

    monkeypatch.setenv('PT_EMIT', '0')
    hits0 = obs.counters().get('compile_cache.disk_hits') or 0
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope2):
        b, = exe2.run(main, feed=feed, fetch_list=[out])
    assert (obs.counters().get('compile_cache.disk_hits') or 0) > hits0
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
