"""Second per-op numeric batch: recurrent units, sampled losses,
bilinear/row/patch ops (model: reference unittests test_gru_unit_op /
test_lstm_unit_op / test_nce / test_hsigmoid / test_kldiv_loss_op /
test_row_conv_op / test_im2sequence_op / test_gather_nd_op)."""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import get_op


def _impl(op):
    return get_op(op).impl


def _sig(x):
    return 1 / (1 + np.exp(-x))


def test_lstm_unit_numeric():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 8).astype('float32')   # [B, 4D], D=2
    c_prev = rng.randn(3, 2).astype('float32')
    out = _impl('lstm_unit')(
        None, {'X': jnp.asarray(x), 'C_prev': jnp.asarray(c_prev)},
        {'forget_bias': 1.0})
    i, f, g, o = np.split(x, 4, axis=-1)
    c_ref = _sig(f + 1.0) * c_prev + _sig(i) * np.tanh(g)
    h_ref = _sig(o) * np.tanh(c_ref)
    np.testing.assert_allclose(np.asarray(out['C']), c_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out['H']), h_ref, rtol=1e-5)


def test_gru_unit_numeric():
    rng = np.random.RandomState(1)
    D = 3
    x = rng.randn(2, 3 * D).astype('float32')   # pre-projected input
    h_prev = rng.randn(2, D).astype('float32')
    w = rng.randn(D, 3 * D).astype('float32')
    out = _impl('gru_unit')(
        None, {'Input': jnp.asarray(x), 'HiddenPrev': jnp.asarray(h_prev),
               'Weight': jnp.asarray(w)}, {})
    xu, xr, xc = np.split(x, 3, axis=-1)
    ur = _sig(np.concatenate([xu, xr], -1) + h_prev @ w[:, :2 * D])
    u, r = np.split(ur, 2, axis=-1)
    c = np.tanh(xc + (r * h_prev) @ w[:, 2 * D:])
    h_ref = u * h_prev + (1 - u) * c
    np.testing.assert_allclose(np.asarray(out['Hidden']), h_ref,
                               rtol=1e-4, atol=1e-6)


def test_kldiv_loss_reductions():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 5).astype('float32')          # log-probs input
    t = np.abs(rng.rand(4, 5)).astype('float32')
    raw = t * (np.log(t + 1e-8) - x)
    for red, ref in (('mean', raw.mean()), ('sum', raw.sum()),
                     ('batchmean', raw.sum() / 4)):
        out = _impl('kldiv_loss')(
            None, {'X': jnp.asarray(x), 'Target': jnp.asarray(t)},
            {'reduction': red})['Loss']
        np.testing.assert_allclose(np.asarray(out), [ref], rtol=1e-4)


def test_bilinear_tensor_product_numeric():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3).astype('float32')
    y = rng.randn(2, 4).astype('float32')
    w = rng.randn(5, 3, 4).astype('float32')
    b = rng.randn(1, 5).astype('float32')
    out = _impl('bilinear_tensor_product')(
        None, {'X': jnp.asarray(x), 'Y': jnp.asarray(y),
               'Weight': jnp.asarray(w), 'Bias': jnp.asarray(b)}, {})['Out']
    ref = np.einsum('bi,oij,bj->bo', x, w, y) + b
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_row_conv_lookahead():
    rng = np.random.RandomState(4)
    x = rng.randn(1, 5, 2).astype('float32')
    w = rng.randn(3, 2).astype('float32')     # future context 3
    out = _impl('row_conv')(
        None, {'X': jnp.asarray(x), 'Filter': jnp.asarray(w)}, {})['Out']
    ref = np.zeros_like(x)
    for t in range(5):
        for k in range(3):
            if t + k < 5:
                ref[0, t] += x[0, t + k] * w[k]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_im2sequence_patches():
    x = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
    out = _impl('im2sequence')(
        None, {'X': jnp.asarray(x)},
        {'kernels': [2, 2], 'strides': [2, 2]})['Out']
    o = np.asarray(out)
    assert o.shape == (1, 4, 4)               # 2x2 grid of 2x2 patches
    np.testing.assert_allclose(o[0, 0], [0, 1, 4, 5])
    np.testing.assert_allclose(o[0, 3], [10, 11, 14, 15])


def test_gather_nd_numeric():
    x = np.arange(24, dtype='float32').reshape(2, 3, 4)
    idx = np.array([[0, 2], [1, 0]], 'int32')   # rows of [d0, d1]
    out = _impl('gather_nd')(
        None, {'X': jnp.asarray(x), 'Index': jnp.asarray(idx)}, {})['Out']
    np.testing.assert_allclose(np.asarray(out), x[[0, 1], [2, 0]])


def test_nce_sampled_softmax_loss():
    """NCE: replicate the op's uniform sampling (same key) and verify
    the binary-CE arithmetic over [true, negatives] logits."""

    class Ctx:
        def rng(self):
            return jax.random.key(0)

    rng = np.random.RandomState(5)
    w = rng.randn(16, 4).astype('float32')
    x = rng.randn(2, 4).astype('float32')
    lab = np.array([[3], [7]], 'int64')
    K = 5
    out = _impl('nce')(
        Ctx(), {'Input': jnp.asarray(x), 'Weight': jnp.asarray(w),
                'Label': jnp.asarray(lab)},
        {'num_neg_samples': K, 'num_total_classes': 16})
    cost_key = 'Cost' if 'Cost' in out else sorted(out.keys())[0]
    got = np.asarray(out[cost_key]).reshape(2, -1).sum(-1)
    neg = np.asarray(jax.random.randint(jax.random.key(0), (2, K), 0, 16))
    ids = np.concatenate([lab.astype(np.int64), neg], axis=1)
    logits = np.einsum('bd,bkd->bk', x, w[ids])
    y = np.concatenate([np.ones((2, 1)), np.zeros((2, K))], axis=1)
    bce = np.maximum(logits, 0) - logits * y + np.log1p(
        np.exp(-np.abs(logits)))
    np.testing.assert_allclose(got, bce.sum(-1), rtol=1e-4)


def test_hierarchical_sigmoid_paths():
    """hsigmoid loss must be finite and positive, with finite gradients
    through the binary-tree path selection."""
    rng = np.random.RandomState(6)
    num_classes = 8
    w = rng.randn(num_classes, 4).astype('float32')
    lab = np.array([[2], [5]], 'int64')
    x = rng.randn(2, 4).astype('float32')

    def cost_arr(xv):
        out = _impl('hierarchical_sigmoid')(
            None, {'X': xv, 'W': jnp.asarray(w),
                   'Label': jnp.asarray(lab)},
            {'num_classes': num_classes})
        return out['Cost'] if 'Cost' in out else list(out.values())[0]

    c = np.asarray(cost_arr(jnp.asarray(x)))
    assert np.isfinite(c).all() and (c > 0).all()
    g = jax.grad(lambda xv: jnp.sum(cost_arr(xv)))(jnp.asarray(x))
    assert np.isfinite(np.asarray(g)).all()


def test_data_norm_numeric():
    rng = np.random.RandomState(7)
    x = rng.randn(4, 3).astype('float32')
    sizes = np.full((3,), 10.0, 'float32')
    sums = rng.randn(3).astype('float32') * 10
    sq = (np.abs(rng.randn(3)) * 30 + 50).astype('float32')
    out = _impl('data_norm')(
        None, {'X': jnp.asarray(x), 'BatchSize': jnp.asarray(sizes),
               'BatchSum': jnp.asarray(sums),
               'BatchSquareSum': jnp.asarray(sq)}, {})
    means = sums / 10.0
    scales = 1 / np.sqrt(sq / 10.0 - means ** 2 + 1e-4)
    np.testing.assert_allclose(np.asarray(out['Y']), (x - means) * scales,
                               rtol=1e-4)


def test_auc_streaming_numeric():
    """AUC histogram accumulation: perfect separation -> 1.0; reversed
    scores -> 0.0."""
    nt = 127
    zeros = jnp.zeros((nt + 1,), jnp.float32)

    def auc_of(preds, labels):
        out = _impl('auc')(
            None, {'Predict': jnp.asarray(preds),
                   'Label': jnp.asarray(labels),
                   'StatPos': zeros, 'StatNeg': zeros},
            {'num_thresholds': nt})
        return float(np.asarray(out['AUC']).ravel()[0])

    p = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.9, 0.1]],
                 'float32')
    lab = np.array([[1], [0], [1], [0]], 'int64')
    assert abs(auc_of(p, lab) - 1.0) < 1e-3
    assert abs(auc_of(p, 1 - lab)) < 1e-3


def test_ctc_align_greedy_decode():
    """argmax path -> merge repeats -> drop blanks (blank=0)."""
    # tokens over time: [1, 1, 0, 2, 2, 3] -> [1, 2, 3]
    tok = np.array([[1, 1, 0, 2, 2, 3]], 'int64')
    out = _impl('ctc_align')(
        None, {'X': jnp.asarray(tok[..., None])},
        {'blank': 0, 'merge_repeated': True})
    o = np.asarray(out['Output']).reshape(1, -1)
    ln = np.asarray(out['OutLength']).ravel()
    assert ln[0] == 3
    np.testing.assert_array_equal(o[0, :3], [1, 2, 3])


def test_strided_slice_numeric():
    x = np.arange(24, dtype='float32').reshape(2, 3, 4)
    out = _impl('strided_slice')(
        None, {'Input': jnp.asarray(x)},
        {'axes': [1, 2], 'starts': [0, 1], 'ends': [3, 4],
         'strides': [2, 2]})['Out']
    np.testing.assert_allclose(np.asarray(out), x[:, 0:3:2, 1:4:2])


def test_assign_value_numeric():
    out = _impl('assign_value')(
        None, {}, {'shape': [2, 2], 'values': [1.0, 2.0, 3.0, 4.0],
                   'dtype': 'float32'})['Out']
    np.testing.assert_allclose(np.asarray(out), [[1, 2], [3, 4]])


def test_random_crop_shape_and_content():
    class Ctx:
        def rng(self):
            return jax.random.key(3)

    x = np.arange(64, dtype='float32').reshape(1, 8, 8)
    out = np.asarray(_impl('random_crop')(
        Ctx(), {'X': jnp.asarray(x)}, {'shape': [4, 4]})['Out'])
    assert out.shape == (1, 4, 4)
    # the crop is a contiguous window: rows step by 8, cols by 1
    r0 = out[0, 0, 0]
    expect = r0 + np.arange(4)[:, None] * 8 + np.arange(4)[None, :]
    np.testing.assert_allclose(out[0], expect)


def test_cumsum_attr_combinations():
    """exclusive/reverse attribute grid vs numpy (reference cumsum_op)."""
    x = np.array([[1., 2., 3., 4.]], 'float32')
    cases = {
        (False, False): np.array([[1., 3., 6., 10.]]),
        (True, False): np.array([[0., 1., 3., 6.]]),
        (False, True): np.array([[10., 9., 7., 4.]]),
        (True, True): np.array([[9., 7., 4., 0.]]),
    }
    for (excl, rev), want in cases.items():
        out = _impl('cumsum')(
            None, {'X': jnp.asarray(x)},
            {'axis': -1, 'exclusive': excl, 'reverse': rev})['Out']
        np.testing.assert_allclose(np.asarray(out), want,
                                   err_msg='excl=%s rev=%s' % (excl, rev))


def test_pad2d_reflect_and_edge_modes():
    x = np.arange(9, dtype='float32').reshape(1, 1, 3, 3)
    for mode in ('reflect', 'edge'):
        out = _impl('pad2d')(
            None, {'X': jnp.asarray(x)},
            {'paddings': [1, 1, 1, 1], 'mode': mode})['Out']
        ref = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)], mode=mode)
        np.testing.assert_allclose(np.asarray(out), ref, err_msg=mode)


def test_label_smooth_numeric():
    oh = np.eye(4, dtype='float32')[[1, 3]]
    out = _impl('label_smooth')(
        None, {'X': jnp.asarray(oh)}, {'epsilon': 0.1})
    got = np.asarray(list(out.values())[0])
    ref = oh * 0.9 + 0.1 / 4
    np.testing.assert_allclose(got, ref, rtol=1e-5)
