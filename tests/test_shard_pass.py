"""GSPMD-style shard pass (core/passes/shard.py): spec completion,
explicit collectives, ZeRO-sharded optimizer state, bitwise
sharded-vs-single-device parity, the memplan ZeRO divisor, and the
checkpoint sharding adoption (docs/passes.md, "The shard pass")."""
import re
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.analysis import lint_program
from paddle_tpu.core import passes
from paddle_tpu.core.passes import shard
from paddle_tpu.core.sharding import spec_from_jsonable, normalize_spec
from paddle_tpu.parallel.mesh import make_mesh

COLLECTIVES = set(shard.COLLECTIVE_OPS)


def _mesh2():
    import jax
    return make_mesh(data=2, devices=jax.devices()[:2])


def _build(mesh=True, dropout=False, amp=False, seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        h = fluid.layers.fc(x, size=8, act='relu')
        if dropout:
            h = fluid.layers.dropout(h, dropout_prob=0.3)
        y = fluid.layers.fc(h, size=4)
        loss = fluid.layers.reduce_mean(y * y)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    if amp:
        main.set_amp(True)
    if mesh:
        main.set_mesh_axes({'data': 2})
        x.sharding = (None, None)   # replicated feed => bitwise parity
    return main, startup, loss


def _collective_ops(program):
    return [op for b in program.blocks for op in b.ops
            if op.type in COLLECTIVES]


# ------------------------------------------------------- the rewrite

def test_no_mesh_is_inert():
    main, _, loss = _build(mesh=False)
    opt, stats = passes.optimize_program(main, (loss.name,))
    s = stats['passes']['shard']
    assert not _collective_ops(opt)
    assert s['reshards_inserted'] == s['grad_allreduce'] == \
        s['all_gathers'] == s['zero_params'] == 0


def test_pt_shard_0_disables(monkeypatch):
    monkeypatch.setenv('PT_SHARD', '0')
    main, _, loss = _build()
    opt, stats = passes.optimize_program(main, (loss.name,))
    assert not _collective_ops(opt)
    assert shard.config_token() == ('shard_off',)


def test_config_token_in_pipeline_token(monkeypatch):
    t1 = passes.config_token()
    assert 'shard_on' in t1
    monkeypatch.setenv('PT_SHARD_ZERO', '0')
    t2 = passes.config_token()
    assert t1 != t2 and 'nozero' in t2


def test_explicit_collectives_and_zero_state():
    main, _, loss = _build()
    opt, stats = passes.optimize_program(main, (loss.name,))
    s = stats['passes']['shard']
    # 4 params (2 w + 2 b): each gets exactly one grad_allreduce and,
    # because their only post-backward reader is their own update op,
    # one forward all_gather
    assert s['zero_params'] == 4
    assert s['zero_state_vars'] == 8      # moment1+moment2 per param
    assert s['grad_allreduce'] == 4
    assert s['all_gathers'] == 4
    gblock = opt.global_block()
    ars = [op for op in _collective_ops(opt) if op.type == 'grad_allreduce']
    assert sorted(op.attrs['param'] for op in ars) == \
        sorted(v.name for v in gblock.all_parameters())
    for op in _collective_ops(opt):
        assert isinstance(op.attrs['bytes'], int) and op.attrs['bytes'] > 0
        assert op.attrs['dst_spec'] is not None
    # ZeRO layout landed on the vars: dim 0 split over 'data'
    for p in gblock.all_parameters():
        assert gblock.vars[p.name]._sharding_spec[0] == 'data'


def test_pass_is_idempotent():
    main, _, loss = _build(dropout=True)
    opt, _ = passes.optimize_program(main, (loss.name,))
    opt2, stats2 = passes.optimize_program(opt, (loss.name,))
    s = stats2['passes']['shard']
    assert s['reshards_inserted'] == s['grad_allreduce'] == \
        s['all_gathers'] == s['specs_completed'] == 0
    assert len(_collective_ops(opt2)) == len(_collective_ops(opt))


def test_optimized_program_lints_clean():
    main, _, loss = _build(dropout=True)
    opt, _ = passes.optimize_program(main, (loss.name,))
    res = lint_program(opt, feed_names=('x',), fetch_names=(loss.name,))
    assert not [d for d in res.diagnostics
                if d.code in ('D017', 'D018', 'D019')]


def test_trailing_replication_equivalence_no_reshard():
    # (None,) on the bias vs (None, None) on the activation is the SAME
    # placement: neither the lint nor the pass may reshard it
    main, _, loss = _build()
    opt, stats = passes.optimize_program(main, (loss.name,))
    assert stats['passes']['shard']['reshards_inserted'] == 0


# ---------------------------------------- D018 <-> reshard bytes parity

def test_d018_bytes_equal_reshard_op_bytes():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        h = fluid.layers.fc(x, size=8)
        loss = fluid.layers.reduce_mean(h * h)
    main.set_mesh_axes({'data': 2})
    x.sharding = ('data', None)
    # annotation fights the dataflow layout => one D018 edge on h
    hv = main.global_block().vars[h.name]
    hv.sharding = (None, None)
    res = lint_program(main, feed_names=('x',), fetch_names=(loss.name,))
    d18 = [d for d in res.diagnostics
           if d.code == 'D018' and d.var == h.name]
    assert d18, 'expected an implicit-reshard warning on %s' % h.name
    est = int(re.search(r'~(\d+) bytes/device', d18[0].message).group(1))
    opt, stats = passes.optimize_program(main, (loss.name,))
    reshards = [op for op in _collective_ops(opt) if op.type == 'reshard'
                and (op.outputs.get('Out') or [None])[0] == h.name]
    assert len(reshards) == 1
    assert reshards[0].attrs['bytes'] == est
    assert normalize_spec(spec_from_jsonable(
        reshards[0].attrs['dst_spec'])) == (None, None)
    # and the rewritten program no longer carries the D018
    res2 = lint_program(opt, feed_names=('x',), fetch_names=(loss.name,))
    assert not [d for d in res2.diagnostics if d.code == 'D018']


def test_adjacent_collectives_fuse():
    from paddle_tpu.core.framework import Operator
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.relu(x)
        loss = fluid.layers.reduce_mean(y)
    main.set_mesh_axes({'data': 2})
    block = main.global_block()
    # hand-build a reshard -> reshard chain on the relu output edge
    mid = block.create_var(name='y_mid', dtype=y.dtype, shape=y.shape)
    out = block.create_var(name='y_out', dtype=y.dtype, shape=y.shape)
    r1 = Operator(block, 'reshard', inputs={'X': y.name},
                  outputs={'Out': 'y_mid'},
                  attrs={'src_spec': ['data', None],
                         'dst_spec': [None, None], 'bytes': 16})
    r2 = Operator(block, 'reshard', inputs={'X': 'y_mid'},
                  outputs={'Out': 'y_out'},
                  attrs={'src_spec': [None, None],
                         'dst_spec': ['data', None], 'bytes': 32})
    idx = next(i for i, op in enumerate(block.ops)
               if op.type == 'reduce_mean')
    block.ops[idx:idx] = [r1, r2]
    mid.op, out.op = r1, r2
    block.ops[idx + 2].inputs['X'] = ['y_out']
    main._bump()
    opt, stats = passes.optimize_program(main, (loss.name,))
    assert stats['passes']['shard']['collectives_fused'] >= 1
    chain = [op for op in _collective_ops(opt)]
    assert len(chain) == 1
    assert chain[0].attrs['src_spec'] == ['data', None]
    assert chain[0].attrs['dst_spec'] == ['data', None]


# ----------------------------------------------------- bitwise parity

def _train(mesh, steps=3, use_run_steps=False):
    main, startup, loss = _build(mesh=mesh, dropout=True, amp=True)
    exe = fluid.Executor(mesh=_mesh2() if mesh else None)
    scope = fluid.Scope()
    feeds = [{'x': np.random.RandomState(i).rand(4, 8).astype('float32')}
             for i in range(steps)]
    with fluid.scope_guard(scope):
        exe.run(startup)
        if use_run_steps:
            out = exe.run_steps(main, feed_list=feeds, fetch_list=[loss])
            losses = [float(v) for v in np.asarray(out[0]).reshape(-1)]
        else:
            losses = [np.asarray(exe.run(main, feed=f,
                                         fetch_list=[loss])[0]).item()
                      for f in feeds]
        state = {n: np.asarray(scope.find_var(n).get_tensor())
                 for n in sorted(main.global_block().vars)
                 if main.global_block().vars[n].persistable
                 and scope.find_var(n) is not None}
    return losses, state


def _assert_state_equal(s1, s2):
    assert len(s1) == len(s2)
    for (n1, a), (n2, b) in zip(sorted(s1.items()), sorted(s2.items())):
        assert np.array_equal(a, b), (n1, n2)


@pytest.mark.parametrize('use_run_steps', [False, True])
def test_bitwise_parity_mesh_vs_single_device(use_run_steps):
    # AMP + dropout on, ZeRO-sharded params/moments on the mesh side:
    # losses AND end-of-run param/Adam state must be bitwise equal
    l1, s1 = _train(False, use_run_steps=use_run_steps)
    l2, s2 = _train(True, use_run_steps=use_run_steps)
    assert l1 == l2
    _assert_state_equal(s1, s2)


def test_zero_state_physically_sharded():
    import jax
    main, startup, loss = _build(mesh=True)
    exe, scope = fluid.Executor(mesh=_mesh2()), fluid.Scope()
    feed = {'x': np.random.RandomState(0).rand(4, 8).astype('float32')}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(2):
            exe.run(main, feed=feed, fetch_list=[loss])
        total = dev0 = 0
        for n in main.global_block().vars:
            arr = scope.vars.get(n)
            v = main.global_block().vars[n]
            if arr is None or not v.persistable or \
                    not hasattr(arr, 'addressable_shards'):
                continue
            total += arr.nbytes
            dev0 += sum(s.data.nbytes for s in arr.addressable_shards
                        if s.device == jax.devices()[0])
    # params + moments halve; scalar beta-pows/LR stay replicated
    assert dev0 <= 0.6 * total


# -------------------------------------------------- memplan ZeRO divisor

def test_memplan_divides_by_zero_divisor(monkeypatch):
    from paddle_tpu.analysis.passes.memplan import plan_memory
    main, _, loss = _build(mesh=False)
    p0 = plan_memory(main)
    # fc8(w 8x8 + b 8) + fc4(w 8x4 + b 4), f32
    assert p0.params_bytes == 432
    # 2 moments per param (864) + 8 beta-pow scalars (32) + lr (4)
    assert p0.opt_state_bytes == 900
    main.set_mesh_axes({'data': 2})
    p1 = plan_memory(main)
    assert p1.params_bytes == 216            # all four shard: 432 / 2
    assert p1.opt_state_bytes == 432 + 36    # moments halve, scalars don't
    assert (p1.params_bytes + p1.opt_state_bytes) <= \
        0.6 * (p0.params_bytes + p0.opt_state_bytes)
    monkeypatch.setenv('PT_SHARD', '0')
    p2 = plan_memory(main)
    assert p2.params_bytes == 432 and p2.opt_state_bytes == 900
    monkeypatch.delenv('PT_SHARD')
    # an optimized program (specs applied) plans the same — no double div
    opt, _ = passes.optimize_program(main, (loss.name,))
    p3 = plan_memory(opt)
    assert p3.params_bytes == 216 and p3.opt_state_bytes == 468


# --------------------------------------------- checkpoint spec adoption

def test_restore_adopts_manifest_sharding():
    from paddle_tpu.train.checkpoint import Checkpointer, CheckpointConfig

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            y = fluid.layers.fc(
                x, size=4, param_attr=fluid.ParamAttr(name='ckw'),
                bias_attr=fluid.ParamAttr(name='ckb'))
            loss = fluid.layers.reduce_mean(y * y)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return main, startup, loss

    d = tempfile.mkdtemp()
    cfg = CheckpointConfig(d, step_interval=1, async_write=False,
                           handle_signals=False, sharded=True)
    main, startup, _ = build()
    main.global_block().vars['ckw'].sharding = ('data', None)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        Checkpointer(cfg, exe, main_program=main).save(0, 1, blocking=True)

    main2, startup2, _ = build()
    assert main2.global_block().vars['ckw'].sharding is None
    scope2, exe2 = fluid.Scope(), fluid.Executor()
    with fluid.scope_guard(scope2):
        exe2.run(startup2)
        ck2 = Checkpointer(cfg, exe2, main_program=main2)
        before = obs.metrics.counter('ckpt.sharding_adopted').value
        assert ck2.restore() is not None
        adopted = obs.metrics.counter('ckpt.sharding_adopted').value - before
    assert adopted >= 1
    assert main2.global_block().vars['ckw'].sharding == ('data', None)


# ------------------------------------------- accumulator spec inheritance

def test_accumulators_inherit_param_spec():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.fc(x, size=4,
                            param_attr=fluid.ParamAttr(name='aw'),
                            bias_attr=False)
        loss = fluid.layers.reduce_mean(y * y)
        main.global_block().vars['aw'].sharding = (None, 'model')
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    b = main.global_block()
    moments = [n for n in b.vars if 'aw_moment' in n]
    assert len(moments) == 2
    for n in moments:
        assert b.vars[n].sharding == (None, 'model')
    pows = [n for n in b.vars if 'aw_beta' in n]
    assert pows and all(b.vars[n].sharding is None for n in pows)


# ------------------------------------------------------- observability

def test_perflab_schema_has_shard_keys():
    from paddle_tpu.observability.export import SCHEMA
    keys = dict(SCHEMA['perflab.pod_parallel'])
    assert keys['reshards_inserted'] == ('counter', 'lower')
    assert keys['collective_bytes'] == ('counter', 'lower')
    assert 'hbm_params_bytes_replicated' in keys
    assert 'hbm_params_bytes_sharded' in keys
    assert 'hbm_sharded_ratio' in keys
