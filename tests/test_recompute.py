"""recompute_scope (rematerialization) tests."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build(remat):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        h = x
        if remat:
            with fluid.recompute_scope():
                for i in range(3):
                    h = layers.fc(h, 16, act='relu',
                                  param_attr=fluid.ParamAttr(name='w%d' % i),
                                  bias_attr=False)
        else:
            for i in range(3):
                h = layers.fc(h, 16, act='relu',
                              param_attr=fluid.ParamAttr(name='w%d' % i),
                              bias_attr=False)
        pred = layers.fc(h, 1, param_attr=fluid.ParamAttr(name='wout'),
                         bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    return main, startup, loss


def test_recompute_ops_tagged():
    main, startup, loss = _build(True)
    tagged = [op for op in main.global_block().ops
              if 'recompute_id' in op.attrs]
    assert len(tagged) >= 3  # the three fc mat muls (+activations)
    ids = {op.attrs['recompute_id'] for op in tagged}
    assert len(ids) == 1


def test_recompute_matches_plain_numerics():
    rng = np.random.RandomState(0)
    xb = rng.rand(16, 8).astype('float32')
    yb = xb.sum(1, keepdims=True)
    res = {}
    for remat in (False, True):
        main, startup, loss = _build(remat)
        main.random_seed = 7
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(startup)
            ls = []
            for _ in range(5):
                l, = exe.run(main, feed={'x': xb, 'y': yb},
                             fetch_list=[loss])
                ls.append(float(np.asarray(l).reshape(())))
        res[remat] = ls
    assert np.allclose(res[False], res[True], rtol=1e-5), res
    assert res[True][-1] < res[True][0]  # and it actually trains


def test_recompute_fn_wrapper():
    import jax.numpy as jnp
    f = fluid.recompute(lambda x: jnp.sin(x) ** 2)
    assert np.allclose(np.asarray(f(jnp.float32(0.5))),
                       np.sin(0.5) ** 2, atol=1e-6)
