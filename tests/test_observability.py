"""Telemetry subsystem tests: retrace explainer, Chrome-trace export,
pipeline-stall + prefetcher gauges, no-op-mode overhead, profiler fixes."""
import json
import time

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu import layers


def _build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = layers.data('x', shape=[4], dtype='float32')
            y = layers.fc(x, 3)
            z = layers.reduce_mean(y)
    return main, startup, y, z


def _run(exe, prog, feed, fetch):
    return exe.run(prog, feed=feed, fetch_list=fetch)


def test_retrace_explainer_names_shape_change():
    main, startup, y, _ = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
        before = obs.counters().get('executor.retraces') or 0
        # warm shape: NO retrace
        _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
        assert (obs.counters().get('executor.retraces') or 0) == before
        # changed feed shape mid-loop: counted, and the cause is named
        _run(exe, main, {'x': np.ones((5, 4), 'float32')}, [y])
    assert (obs.counters().get('executor.retraces') or 0) == before + 1
    rep = obs.explainer().last_report()
    assert rep['kind'] == 'retrace'
    assert rep['changed'] == ['feed_shapes']
    assert any('x' in d and '(2, 4)' in d and '(5, 4)' in d
               for d in rep['details']), rep['details']
    # the rendered report is human-readable text naming the component
    assert 'feed_shape:x' in obs.explainer().render_report(rep)


def test_retrace_explainer_names_fetch_set_change():
    main, startup, y, z = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
        _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [z])
    rep = obs.explainer().last_report()
    assert rep['kind'] == 'retrace'
    assert rep['changed'] == ['fetch_set']
    assert any(z.name in d for d in rep['details']), rep['details']


def test_retrace_explainer_fused_steps_change():
    """run -> run_steps on the same program is a retrace whose named cause
    is steps (and the stacked feed shape)."""
    main, startup, y, _ = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    feed = {'x': np.ones((2, 4), 'float32')}
    with fluid.scope_guard(scope):
        exe.run(startup)
        _run(exe, main, feed, [y])
        exe.run_steps(main, feed_list=[feed, feed, feed], fetch_list=[y])
    rep = obs.explainer().last_report()
    assert rep['kind'] == 'retrace'
    assert 'steps' in rep['changed']
    assert any('steps' in d and '3' in d for d in rep['details'])


def test_chrome_trace_json_valid(tmp_path):
    main, startup, y, _ = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
    path = str(tmp_path / 'trace.json')
    obs.export_chrome_trace(path)
    with open(path) as f:
        data = json.load(f)
    evs = data['traceEvents']
    assert evs, 'no events exported'
    ts = [e['ts'] for e in evs]
    assert ts == sorted(ts), 'ts must be monotonic in the exported file'
    for e in evs:
        assert e['ph'] in ('X', 'i'), e
        assert {'name', 'ts', 'pid', 'tid'} <= set(e), e
        if e['ph'] == 'X':
            assert e['dur'] >= 0
    names = {e['name'] for e in evs}
    assert 'executor.dispatch' in names or 'executor.trace_compile' in names
    assert 'executor.fetch_sync' in names


def test_stall_detection_fires_on_launch_gap():
    main, startup, y, _ = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    old = obs.stall_threshold_ms()
    obs.set_stall_threshold_ms(30)
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
            before = obs.counters().get('executor.stall_count') or 0
            time.sleep(0.06)   # the "pipeline" drains
            _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
    finally:
        obs.set_stall_threshold_ms(old)
    assert (obs.counters().get('executor.stall_count') or 0) == before + 1
    stalls = [e for e in obs.recorder().events()
              if e['name'] == 'pipeline.stall']
    assert stalls and stalls[-1]['args']['gap_ms'] > 30
    hist = obs.metrics.histogram('executor.launch_gap_ms').snapshot()
    assert hist['count'] >= 2 and hist['max'] > 30


def test_prefetch_starvation_gauge_fires_under_slow_reader():
    def slow_feeds():
        for _ in range(4):
            time.sleep(0.05)
            yield {'x': np.ones((2, 2), 'float32')}

    before = obs.counters().get('prefetch.starvation_count') or 0
    pf = fluid.FeedPrefetcher(slow_feeds(), steps=2, capacity=2,
                              to_device=False)
    got = list(pf)
    pf.close()
    assert len(got) == 2 and got[0][1] == 2
    c = obs.counters()
    assert (c.get('prefetch.starvation_count') or 0) > before
    assert (c.get('prefetch.starvation_s') or 0) > 0
    assert 'prefetch.queue_depth' in c
    assert (c.get('prefetch.upload_s') or 0) > 0


def test_disabled_mode_does_no_telemetry_work(monkeypatch):
    """With telemetry disabled the executor hot path must not touch the
    subsystem at all: every entry point is patched to raise, and the
    recorder/registry must not grow — i.e. no per-launch telemetry
    allocations beyond the constant `enabled()` branch."""
    main, startup, y, _ = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])  # warm
        events_before = obs.recorder().event_count()
        counters_before = dict(obs.counters())
        obs.disable()
        try:
            def boom(*a, **k):
                raise AssertionError('telemetry invoked while disabled')
            monkeypatch.setattr(obs.stall, 'on_launch_start', boom)
            monkeypatch.setattr(obs.stall, 'on_launch_end', boom)
            monkeypatch.setattr(obs.tracing, 'add_span', boom)
            monkeypatch.setattr(obs.metrics, 'counter', boom)
            monkeypatch.setattr(obs.metrics, 'histogram', boom)
            for _ in range(5):
                _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
        finally:
            obs.enable()
    assert obs.recorder().event_count() == events_before
    assert obs.counters() == counters_before


def test_metrics_registry_basics():
    obs.counter('t.ctr').inc()
    obs.counter('t.ctr').inc(2.5)
    obs.gauge('t.g').set(7)
    h = obs.histogram('t.h')
    for v in (0.5, 3.0, 100.0):
        h.observe(v)
    snap = obs.metrics_snapshot()
    assert snap['counters']['t.ctr'] == 3.5
    assert snap['gauges']['t.g'] == 7
    hs = snap['histograms']['t.h']
    assert hs['count'] == 3 and hs['min'] == 0.5 and hs['max'] == 100.0
    with pytest.raises(TypeError):
        obs.gauge('t.ctr')   # kind mismatch is an error, not a silent alias
    full = obs.snapshot()
    assert 'spans' in full and 'retrace_reports' in full


def test_profiler_restores_trace_dir_and_reset_clears(tmp_path, capsys):
    import paddle_tpu.profiler as prof
    old_dir = prof._trace_dir[0]
    main, startup, y, _ = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    d = str(tmp_path / 'prof')
    with fluid.scope_guard(scope):
        exe.run(startup)
        with prof.profiler('All', sorted_key='total', profile_path=d):
            _run(exe, main, {'x': np.ones((2, 4), 'float32')}, [y])
    # state-leak fix: the scoped profile_path must not stick
    assert prof._trace_dir[0] == old_dir
    out = capsys.readouterr().out
    assert 'Profiling Report' in out
    assert 'executor.' in out   # recorded spans appear in the table
    # our chrome trace landed inside the trace dir alongside the xplane dump
    import os
    assert os.path.exists(os.path.join(d, 'paddle_tpu_trace.json'))
    with open(os.path.join(d, 'paddle_tpu_trace.json')) as f:
        assert json.load(f)['traceEvents']
    # reset_profiler is no longer a silent no-op
    assert obs.recorder().event_count() > 0
    prof.reset_profiler()
    assert obs.recorder().event_count() == 0
    assert obs.counters() == {}
    assert obs.explainer().last_report() is None


def test_trainer_end_step_event_carries_telemetry():
    def train_func():
        x = layers.data('x', shape=[3], dtype='float32')
        yv = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1)
        return layers.reduce_mean(layers.square(pred - yv))

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(4):
            yield [(rng.rand(3).astype('float32'),
                    rng.rand(1).astype('float32')) for _ in range(4)]

    seen = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            seen.append(ev.telemetry)

    trainer = fluid.Trainer(train_func,
                            lambda: fluid.optimizer.SGDOptimizer(0.1))
    trainer.train(1, handler, reader=reader, feed_order=['x', 'y'],
                  steps_per_launch=2)
    assert seen
    assert all(isinstance(t, dict) for t in seen)
    assert all('executor.launches' in t for t in seen)
    # counters are cumulative: later snapshots never go backwards
    launches = [t['executor.launches'] for t in seen]
    assert launches == sorted(launches)
