"""Aux subsystem tests: debugger, inference engine (+AOT export),
checkpoint/resume, recordio conversion, async executor.
"""
import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import native


def _build_linear():
    """y = fc(x), trained program + startup."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(x, 1,
                               param_attr=fluid.ParamAttr(name='w'),
                               bias_attr=fluid.ParamAttr(name='b'))
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        opt = fluid.optimizer.SGDOptimizer(0.1)
        opt.minimize(loss)
    return main, startup, pred, loss


def test_debugger_pprint_and_dot(tmp_path):
    main, startup, pred, loss = _build_linear()
    code = fluid.debugger.program_to_code(main)
    assert 'fc' in code or 'mul' in code
    assert 'w' in code
    dot_path = str(tmp_path / 'g.dot')
    dot = fluid.debugger.draw_block_graphviz(main.global_block(),
                                             path=dot_path)
    assert dot.startswith('digraph')
    assert os.path.exists(dot_path)
    # every op box connects to at least one var
    assert '->' in dot


def test_inference_predictor_and_aot(tmp_path):
    main, startup, pred, loss = _build_linear()
    exe = fluid.Executor()
    scope = fluid.Scope()
    model_dir = str(tmp_path / 'model')
    with fluid.scope_guard(scope):
        exe.run(startup)
        xb = np.random.RandomState(0).rand(8, 4).astype('float32')
        yb = xb.sum(1, keepdims=True)
        exe.run(main, feed={'x': xb, 'y': yb}, fetch_list=[loss])
        fluid.io.save_inference_model(model_dir, ['x'], [pred], exe, main)
        w = np.asarray(scope.vars['w'])
        b = np.asarray(scope.vars['b'])

    predictor = fluid.inference.Predictor(model_dir)
    assert predictor.get_input_names() == ['x']
    out = predictor.run({'x': xb})
    assert np.allclose(out[0], xb @ w + b, atol=1e-5)
    # list-feed form + shape-cache hit
    out2 = predictor.run([xb])
    assert np.allclose(out[0], out2[0])

    # AOT export: serialized computation must reproduce without the program
    aot_dir = str(tmp_path / 'aot')
    fluid.inference.export_serialized(predictor, {'x': xb}, aot_dir)
    run = fluid.inference.load_serialized(aot_dir)
    out3 = run({'x': xb})
    assert np.allclose(out[0], out3[0], atol=1e-5)


def test_checkpointer_save_restore_rotate(tmp_path):
    from paddle_tpu.train import CheckpointConfig, Checkpointer
    main, startup, pred, loss = _build_linear()
    exe = fluid.Executor()
    scope = fluid.Scope()
    ckpt_dir = str(tmp_path / 'ckpt')
    cfg = CheckpointConfig(ckpt_dir, max_num_checkpoints=2, step_interval=1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck = Checkpointer(cfg, exe, main)
        for step in range(4):
            ck.save(epoch_id=0, step_id=step)
        ck.wait()   # saves are async: drain the background writer
        w_saved = np.asarray(scope.vars['w'])
        # rotation: only 2 newest kept
        kept = [d for d in os.listdir(ckpt_dir)
                if d.startswith('checkpoint_')]
        assert len(kept) == 2

        # clobber params, then restore
        scope.vars['w'] = scope.vars['w'] * 0 + 99.0
        meta = Checkpointer(cfg, exe, main).restore()
        assert meta['step_id'] == 3
        assert np.allclose(np.asarray(scope.vars['w']), w_saved)


def test_checkpointer_skips_torn_checkpoint(tmp_path):
    from paddle_tpu.train import CheckpointConfig, Checkpointer
    main, startup, pred, loss = _build_linear()
    exe = fluid.Executor()
    scope = fluid.Scope()
    ckpt_dir = str(tmp_path / 'ckpt')
    cfg = CheckpointConfig(ckpt_dir, max_num_checkpoints=3, step_interval=1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck = Checkpointer(cfg, exe, main)
        ck.save(0, 1)
        w1 = np.asarray(scope.vars['w'])
        d2 = ck.save(0, 2)
        ck.wait()   # saves are async: drain the background writer
        # simulate failure mid-write of the newest: drop its SUCCESS marker
        os.remove(os.path.join(d2, '_SUCCESS'))
        scope.vars['w'] = scope.vars['w'] * 0
        meta = Checkpointer(cfg, exe, main).restore()
        assert meta['step_id'] == 1
        assert np.allclose(np.asarray(scope.vars['w']), w1)


def test_recordio_roundtrip(tmp_path):
    from paddle_tpu import recordio_writer
    path = str(tmp_path / 'data.ptrec')

    def reader():
        for i in range(10):
            yield (np.full((3,), i, np.float32), np.int64(i))

    n = recordio_writer.convert_reader_to_recordio_file(path, reader)
    assert n == 10
    got = list(native.RecordReader(path))
    assert len(got) == 10
    assert np.allclose(got[4][0], 4.0)


def test_recordio_sharded(tmp_path):
    from paddle_tpu import recordio_writer
    base = str(tmp_path / 'shard')

    def reader():
        for i in range(7):
            yield (np.full((2,), i, np.float32),)

    fns = recordio_writer.convert_reader_to_recordio_files(base, 3, reader)
    assert len(fns) == 3  # 3+3+1
    total = sum(1 for fn in fns for _ in native.RecordReader(fn))
    assert total == 7


def test_async_executor_trains(tmp_path):
    from paddle_tpu.async_executor import AsyncExecutor
    rng = np.random.RandomState(0)
    path = str(tmp_path / 'train.ptrec')
    w_true = rng.rand(4, 1).astype('float32')
    with native.RecordWriter(path) as w:
        for _ in range(64):
            xb = rng.rand(4).astype('float32')
            w.write((xb, (xb[None, :] @ w_true)[0]))

    main, startup, pred, loss = _build_linear()
    feed_desc = native.DataFeedDesc([path], batch_size=8,
                                    shuffle_capacity=32)
    feed_desc.add_slot('x', 'float32', (4,))
    feed_desc.add_slot('y', 'float32', (1,))

    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ae = AsyncExecutor()
        first = None
        for epoch in range(6):
            out = ae.run(main, feed_desc, [path], fetch=[loss])
            val = float(np.asarray(out[0]).reshape(()))
            if first is None:
                first = val
        assert val < first, (first, val)


def test_inference_transpiler_folds_conv_bn():
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[3, 8, 8], dtype='float32')
            c = layers.conv2d(x, 6, 3, act=None)
            h = layers.batch_norm(c, act='relu')
            loss = layers.reduce_mean(h)
            fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # move BN stats off their init values
            exe.run(main, feed={'x': rng.rand(4, 3, 8, 8).astype(
                'float32')}, fetch_list=[loss])
        infer = main.clone(for_test=True)
        xt = rng.rand(2, 3, 8, 8).astype('float32')
        before, = exe.run(infer, feed={'x': xt}, fetch_list=[h])
        t = fluid.transpiler.InferenceTranspiler()
        t.transpile(infer, scope=scope)
        types = [op.type for op in infer.global_block().ops]
        assert 'batch_norm' not in types, types
        after, = exe.run(infer, feed={'x': xt}, fetch_list=[h])
    np.testing.assert_allclose(np.asarray(before), np.asarray(after),
                               atol=2e-5)


def test_inference_transpiler_fold_edge_cases():
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    rng = np.random.RandomState(1)

    # (a) conv WITHOUT bias + bn, fetching the bn output directly
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[2, 6, 6], dtype='float32')
            c = layers.conv2d(x, 4, 3, bias_attr=False)
            h = layers.batch_norm(c)       # no act; h fetched directly
            loss = layers.reduce_mean(h)
            fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(2):
            exe.run(main, feed={'x': rng.rand(3, 2, 6, 6).astype(
                'float32')}, fetch_list=[loss])
        infer = main.clone(for_test=True)
        xt = rng.rand(2, 2, 6, 6).astype('float32')
        before, = exe.run(infer, feed={'x': xt}, fetch_list=[h])
        fluid.transpiler.InferenceTranspiler().transpile(infer,
                                                         scope=scope)
        assert 'batch_norm' not in [op.type for op in
                                    infer.global_block().ops]
        after, = exe.run(infer, feed={'x': xt}, fetch_list=[h])
    np.testing.assert_allclose(np.asarray(before), np.asarray(after),
                               atol=2e-5)

    # (b) weight-SHARED convs must not fold (each bn has its own stats)
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[2, 6, 6], dtype='float32')
            w = fluid.ParamAttr(name='shared_w')
            a = layers.batch_norm(layers.conv2d(x, 4, 3, param_attr=w,
                                                bias_attr=False))
            b = layers.batch_norm(layers.conv2d(x, 4, 3, param_attr=w,
                                                bias_attr=False))
            loss = layers.reduce_mean(a + b)
            fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup2)
        for _ in range(2):
            exe.run(main2, feed={'x': rng.rand(3, 2, 6, 6).astype(
                'float32')}, fetch_list=[loss])
        infer2 = main2.clone(for_test=True)
        xt = rng.rand(2, 2, 6, 6).astype('float32')
        before, = exe.run(infer2, feed={'x': xt}, fetch_list=[loss])
        fluid.transpiler.InferenceTranspiler().transpile(infer2,
                                                         scope=scope2)
        # both bns kept — shared filter vetoes the fold
        kinds = [op.type for op in infer2.global_block().ops]
        assert kinds.count('batch_norm') == 2, kinds
        after, = exe.run(infer2, feed={'x': xt}, fetch_list=[loss])
    np.testing.assert_allclose(np.asarray(before), np.asarray(after),
                               rtol=1e-6)


def test_contrib_memory_usage_and_op_freq():
    """contrib.memory_usage_calc + op_frequence over a real program
    (parity: reference contrib utilities)."""
    from paddle_tpu.contrib.memory_usage_calc import memory_usage
    from paddle_tpu.contrib.op_frequence import op_freq_statistic

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[16], dtype='float32')
            h = fluid.layers.fc(x, 32, act='relu')
            h = fluid.layers.fc(h, 8)
            loss = fluid.layers.reduce_mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
    gb, unit = memory_usage(main, batch_size=64)
    assert unit == 'GB' and gb > 0
    # doubling batch grows the (activation-dominated) estimate
    gb2, _ = memory_usage(main, batch_size=128)
    assert gb2 > gb
    with np.testing.assert_raises(ValueError):
        memory_usage(main, batch_size=0)
    uni, adj = op_freq_statistic(main)
    assert uni['mul'] == 2
    assert uni['relu'] == 1
    assert any(k.startswith('mul->') for k in adj)
    # sorted by descending frequency
    counts = list(uni.values())
    assert counts == sorted(counts, reverse=True)


def test_profiler_writes_trace(tmp_path):
    """fluid.profiler context captures a jax trace into the given dir
    (reference profiler.py usage shape)."""
    import paddle_tpu as fluid
    import paddle_tpu.profiler as prof
    import os
    d = str(tmp_path / 'trace')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            out = fluid.layers.fc(x, 4)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with prof.profiler('All', output_file=d):
            exe.run(main, feed={'x': np.ones((2, 4), 'float32')},
                    fetch_list=[out])
    # a plugins/…/xplane.pb (or at least the trace dir tree) must exist
    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert found, 'no trace files written under %s' % d
