"""Optimizer update tests vs numpy reference (model: reference
tests/unittests/test_optimizer.py + per-optimizer op tests)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _one_step(opt, lr=0.1, steps=1):
    """Train y = mean(x*w) one/few steps; return (w_history, grad)."""
    x = fluid.layers.data('x', shape=[4], dtype='float32')
    w = fluid.layers.create_parameter(
        [4], 'float32', name='w_opt',
        default_initializer=fluid.initializer.Constant(1.0))
    y = fluid.layers.elementwise_mul(x, w)
    loss = fluid.layers.mean(y)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.array([[1., 2., 3., 4.]], 'float32')
    ws = [np.array(fluid.global_scope().get('w_opt'))]
    for _ in range(steps):
        exe.run(feed={'x': xv}, fetch_list=[loss])
        ws.append(np.array(fluid.global_scope().get('w_opt')))
    grad = xv[0] / 4.0
    return ws, grad


def test_sgd():
    ws, g = _one_step(fluid.optimizer.SGD(0.1))
    np.testing.assert_allclose(ws[1], ws[0] - 0.1 * g, rtol=1e-5)


def test_momentum():
    ws, g = _one_step(fluid.optimizer.Momentum(0.1, momentum=0.9), steps=2)
    v1 = g
    np.testing.assert_allclose(ws[1], ws[0] - 0.1 * v1, rtol=1e-5)
    v2 = 0.9 * v1 + g
    np.testing.assert_allclose(ws[2], ws[1] - 0.1 * v2, rtol=1e-5)


def test_adam():
    ws, g = _one_step(fluid.optimizer.Adam(0.1), steps=1)
    m1 = 0.1 * g
    m2 = 0.001 * g * g
    lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expect = ws[0] - lr_t * m1 / (np.sqrt(m2) + 1e-8)
    np.testing.assert_allclose(ws[1], expect, rtol=1e-4)


def test_adagrad():
    ws, g = _one_step(fluid.optimizer.Adagrad(0.1))
    expect = ws[0] - 0.1 * g / (np.sqrt(g * g) + 1e-6)
    np.testing.assert_allclose(ws[1], expect, rtol=1e-4)


def test_rmsprop():
    ws, g = _one_step(fluid.optimizer.RMSPropOptimizer(0.1))
    ms = 0.05 * g * g
    expect = ws[0] - 0.1 * g / np.sqrt(ms + 1e-6)
    np.testing.assert_allclose(ws[1], expect, rtol=1e-4)


@pytest.mark.parametrize('opt_ctor', [
    lambda: fluid.optimizer.Adamax(0.01),
    lambda: fluid.optimizer.DecayedAdagrad(0.01),
    lambda: fluid.optimizer.Adadelta(0.01),
    lambda: fluid.optimizer.Ftrl(0.01),
    lambda: fluid.optimizer.LarsMomentum(0.01, momentum=0.9),
])
def test_all_optimizers_step(opt_ctor):
    ws, _ = _one_step(opt_ctor(), steps=2)
    assert not np.allclose(ws[0], ws[2])
    assert np.all(np.isfinite(ws[2]))


def test_regularization_l2():
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    w = fluid.layers.create_parameter(
        [2], 'float32', name='w_reg',
        default_initializer=fluid.initializer.Constant(2.0))
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(x, w))
    opt = fluid.optimizer.SGD(
        0.1, regularization=fluid.regularizer.L2Decay(0.5))
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(feed={'x': np.zeros((1, 2), 'float32')}, fetch_list=[loss])
    w1 = np.array(fluid.global_scope().get('w_reg'))
    # grad = 0 + 0.5 * w -> w = 2 - 0.1*1.0 = 1.9
    np.testing.assert_allclose(w1, [1.9, 1.9], rtol=1e-5)


def test_grad_clip_global_norm():
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    w = fluid.layers.create_parameter(
        [2], 'float32', name='w_clip',
        default_initializer=fluid.initializer.Constant(1.0))
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(x, w) * 100.0)
    fluid.clip.set_gradient_clip(
        fluid.clip.GradientClipByGlobalNorm(clip_norm=1.0))
    fluid.optimizer.SGD(1.0).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(feed={'x': np.ones((1, 2), 'float32')}, fetch_list=[loss])
    w1 = np.array(fluid.global_scope().get('w_clip'))
    # grad norm clipped to 1 -> step length <= 1
    assert np.linalg.norm(1.0 - w1) <= 1.0 + 1e-4


def test_lr_scheduler_decays():
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    w = fluid.layers.create_parameter(
        [2], 'float32', name='w_lr',
        default_initializer=fluid.initializer.Constant(1.0))
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(x, w))
    lr = fluid.layers.exponential_decay(0.1, decay_steps=1, decay_rate=0.5)
    fluid.optimizer.SGD(lr).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    deltas = []
    prev = np.array(fluid.global_scope().get('w_lr'))
    for _ in range(3):
        exe.run(feed={'x': np.ones((1, 2), 'float32')}, fetch_list=[loss])
        cur = np.array(fluid.global_scope().get('w_lr'))
        deltas.append(np.abs(prev - cur).mean())
        prev = cur
    assert deltas[1] == pytest.approx(deltas[0] * 0.5, rel=1e-3)
    assert deltas[2] == pytest.approx(deltas[1] * 0.5, rel=1e-3)


def test_all_lr_schedules_numeric():
    """Every LR schedule's VALUE sequence vs the reference closed form
    (model: reference test_learning_rate_scheduler.py)."""
    import math

    def run_schedule(build, steps=5):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                lr = build()
        exe = fluid.Executor()
        scope = fluid.Scope()
        vals = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(steps):
                v, = exe.run(main, fetch_list=[lr])
                vals.append(float(np.asarray(v).ravel()[0]))
        return vals

    base, dsteps, rate = 0.5, 2, 0.7
    # exponential: base * rate^(step/dsteps); staircase floors the ratio
    got = run_schedule(lambda: fluid.layers.exponential_decay(
        base, dsteps, rate, staircase=False))
    want = [base * rate ** (s / dsteps) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got = run_schedule(lambda: fluid.layers.exponential_decay(
        base, dsteps, rate, staircase=True))
    want = [base * rate ** (s // dsteps) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    got = run_schedule(lambda: fluid.layers.natural_exp_decay(
        base, dsteps, rate, staircase=False))
    want = [base * math.exp(-rate * s / dsteps) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    got = run_schedule(lambda: fluid.layers.inverse_time_decay(
        base, dsteps, rate, staircase=False))
    want = [base / (1 + rate * s / dsteps) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    # polynomial: (base - end) * (1 - step/decay_steps)^power + end
    got = run_schedule(lambda: fluid.layers.polynomial_decay(
        base, decay_steps=4, end_learning_rate=0.1, power=2.0))
    want = [(base - 0.1) * (1 - min(s, 4) / 4) ** 2 + 0.1
            for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    got = run_schedule(lambda: fluid.layers.piecewise_decay(
        boundaries=[2, 4], values=[1.0, 0.5, 0.1]), steps=6)
    want = [1.0, 1.0, 0.5, 0.5, 0.1, 0.1]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    # noam: d^-0.5 * min(step^-0.5, step * warmup^-1.5); step counts from 1
    got = run_schedule(lambda: fluid.layers.noam_decay(64, 3))
    want = [64 ** -0.5 * min((s + 1) ** -0.5, (s + 1) * 3 ** -1.5)
            for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)

    got = run_schedule(lambda: fluid.layers.cosine_decay(
        base, step_each_epoch=2, epochs=4), steps=6)
    want = [base / 2 * (math.cos((s // 2) * math.pi / 4) + 1)
            for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)
