"""Serving runtime (paddle_tpu/serving/): continuous batching, admission
control + deadlines, overflow policies, circuit breaker, graceful drain,
chained signal handlers, Predictor single-flight compiles, and seeded
retry jitter.  Most tests chaos-test the engine with plain-function
backends (no compiles); one end-to-end test goes through a real
Predictor."""
import signal
import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu import serving
from paddle_tpu.core import retry as retry_mod
from paddle_tpu.core import signals as signals_mod
from paddle_tpu.data_feeder import FeedBucketer
from paddle_tpu.serving import ServingConfig, ServingEngine, TokenBucket
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cnt(name):
    return obs.counters().get(name) or 0


class GatedBackend(object):
    """Backend that blocks each call on a gate; records batch shapes."""

    def __init__(self, fail=False):
        self.gate = threading.Semaphore(0)
        self.entered = threading.Semaphore(0)   # one release per call
        self.calls = []
        self.fail = fail

    def __call__(self, feed):
        self.entered.release()
        self.gate.acquire()
        self.calls.append({k: np.asarray(v).shape for k, v in feed.items()})
        if self.fail:
            raise RuntimeError('backend down')
        x = np.asarray(feed['x'])
        return [x * 2.0, np.asarray(x.shape[0])]   # per-row + aggregate


def _echo_backend(feed):
    x = np.asarray(feed['x'])
    return [x * 2.0]


def _feed(rows, cols=3, fill=None):
    a = np.arange(rows * cols, dtype='float32').reshape(rows, cols)
    if fill is not None:
        a = np.full((rows, cols), fill, dtype='float32')
    return {'x': a}


# ------------------------------------------------- coalescing + scatter

def test_coalesce_pad_and_scatter():
    be = GatedBackend()
    eng = ServingEngine(be, bucketer=FeedBucketer(boundaries=[1, 2, 4, 8]),
                        config=ServingConfig(max_queue=16))
    with eng:
        f1 = eng.submit(_feed(1, fill=1.0))
        assert be.entered.acquire(timeout=5)   # dispatcher holds batch 1
        # while the dispatcher is blocked on batch 1, these two queue up
        # and must coalesce into ONE padded superbatch
        f2 = eng.submit(_feed(2, fill=2.0))
        f3 = eng.submit(_feed(1, fill=3.0))
        for _ in range(3):
            be.gate.release()
        r1, r2, r3 = (f.result(5) for f in (f1, f2, f3))
    assert r1.ok and r2.ok and r3.ok
    assert len(be.calls) == 2, be.calls
    # 2+1 rows padded up to the 4-boundary bucket
    assert be.calls[1]['x'] == (4, 3)
    # scatter: each request gets exactly its own rows back
    assert r2.outputs[0].shape == (2, 3)
    assert np.all(r2.outputs[0] == 4.0)
    assert r3.outputs[0].shape == (1, 3)
    assert np.all(r3.outputs[0] == 6.0)
    # outputs without a per-row leading dim are handed over whole
    assert r1.outputs[1].ndim == 0


def test_batch_zero_and_too_large_rejected_clearly():
    bucketer = FeedBucketer(boundaries=[1, 2, 4])
    eng = ServingEngine(_echo_backend, bucketer=bucketer,
                        config=ServingConfig(max_batch_rows=64))
    with eng:
        r0 = eng.submit(_feed(0)).result(1)
        assert r0.status == 'rejected' and r0.reason == 'empty_batch'
        # larger than the largest bucket boundary: refused, NOT truncated
        rbig = eng.submit(_feed(5)).result(1)
        assert rbig.status == 'rejected' and rbig.reason == 'too_large'
        assert 'truncat' in rbig.error
        # mixed leading dims are unbatchable
        rbad = eng.submit({'x': np.ones((2, 3), 'f'),
                           'y': np.ones((3, 3), 'f')}).result(1)
        assert rbad.status == 'rejected' and rbad.reason == 'bad_request'
        ok = eng.submit(_feed(2)).result(5)
        assert ok.ok


def test_bucketer_bucket_count_gauge():
    b = FeedBucketer(boundaries=[1, 2, 4, 8])
    assert b.bucket_count() == 0
    b.bucket_feed(_feed(1))
    b.bucket_feed(_feed(3))
    b.bucket_feed(_feed(4))   # same bucket as rows=3
    assert b.bucket_count() == 2
    snap = obs.metrics_snapshot()
    assert snap['gauges']['bucketer.bucket_count'] == 2


# --------------------------------------------------------- deadlines

def test_expired_deadline_rejected_at_admission():
    eng = ServingEngine(_echo_backend)
    with eng:
        r = eng.submit(_feed(1), timeout_s=0).result(1)
    assert r.status == 'rejected' and r.reason == 'deadline'


def test_queued_past_deadline_dropped_pre_dispatch():
    be = GatedBackend()
    eng = ServingEngine(be, config=ServingConfig(max_queue=16))
    with eng:
        f1 = eng.submit(_feed(1), timeout_s=30)
        assert be.entered.acquire(timeout=5)        # f1 is mid-dispatch
        f2 = eng.submit(_feed(1), timeout_s=0.05)   # expires while queued
        time.sleep(0.12)
        be.gate.release()
        be.gate.release()   # would serve f2's batch if it ever dispatched
        r1 = f1.result(5)
        r2 = f2.result(5)
    assert r1.ok
    assert r2.status == 'deadline_exceeded' and r2.reason == 'queue_wait'
    # the expired request consumed ZERO backend compute
    assert len(be.calls) == 1


# --------------------------------------------------- overflow policies

def test_overflow_reject_policy():
    be = GatedBackend()
    eng = ServingEngine(be, config=ServingConfig(max_queue=1,
                                                 overflow_policy='reject'))
    with eng:
        f1 = eng.submit(_feed(1))          # dispatched, blocked in backend
        assert be.entered.acquire(timeout=5)
        f2 = eng.submit(_feed(1))          # fills the queue
        f3 = eng.submit(_feed(1))          # overflow
        r3 = f3.result(1)
        assert r3.status == 'rejected' and r3.reason == 'full'
        be.gate.release()
        be.gate.release()
        assert f1.result(5).ok and f2.result(5).ok


def test_overflow_shed_oldest_policy():
    be = GatedBackend()
    shed_before = _cnt('serving.shed')
    eng = ServingEngine(be, config=ServingConfig(
        max_queue=1, overflow_policy='shed_oldest'))
    with eng:
        f1 = eng.submit(_feed(1))
        assert be.entered.acquire(timeout=5)
        f2 = eng.submit(_feed(1))          # queued
        f3 = eng.submit(_feed(1))          # displaces f2
        r2 = f2.result(1)
        assert r2.status == 'shed' and r2.reason == 'overflow'
        be.gate.release()
        be.gate.release()
        assert f1.result(5).ok and f3.result(5).ok
    assert _cnt('serving.shed') == shed_before + 1


def test_overflow_block_policy_admits_after_drain():
    be = GatedBackend()
    eng = ServingEngine(be, config=ServingConfig(
        max_queue=1, overflow_policy='block', block_timeout_s=5.0))
    with eng:
        f1 = eng.submit(_feed(1))
        assert be.entered.acquire(timeout=5)
        f2 = eng.submit(_feed(1))          # queue now full
        got = []

        def blocked_submit():
            got.append(eng.submit(_feed(1)))

        t = threading.Thread(target=blocked_submit)
        t.start()
        time.sleep(0.1)
        assert not got or not got[0].done()   # still blocked, not refused
        for _ in range(3):
            be.gate.release()
        t.join(5)
        assert f1.result(5).ok and f2.result(5).ok
        assert got[0].result(5).ok


def test_overflow_block_policy_times_out_to_reject():
    be = GatedBackend()
    eng = ServingEngine(be, config=ServingConfig(
        max_queue=1, overflow_policy='block', block_timeout_s=0.05))
    with eng:
        f1 = eng.submit(_feed(1))
        assert be.entered.acquire(timeout=5)
        eng.submit(_feed(1))
        r3 = eng.submit(_feed(1)).result(1)   # blocks 0.05s, then refused
        assert r3.status == 'rejected' and r3.reason == 'full'
        be.gate.release()
        be.gate.release()
        assert f1.result(5).ok


# ------------------------------------------------------- rate limiting

def test_token_bucket_refill_with_fake_clock():
    now = [0.0]
    tb = TokenBucket(qps=10.0, burst=2.0, clock=lambda: now[0])
    assert tb.try_acquire() and tb.try_acquire()
    assert not tb.try_acquire()
    now[0] += 0.1           # refills exactly one token
    assert tb.try_acquire()
    assert not tb.try_acquire()


def test_rate_limited_submit_rejected():
    eng = ServingEngine(_echo_backend, config=ServingConfig(
        rate_qps=0.001, rate_burst=2))
    with eng:
        assert eng.submit(_feed(1)).result(5).ok
        assert eng.submit(_feed(1)).result(5).ok
        r = eng.submit(_feed(1)).result(1)
    assert r.status == 'rejected' and r.reason == 'rate'


# ----------------------------------------------------- circuit breaker

def test_breaker_trips_on_failures_and_recovers_on_probe():
    trips_before = _cnt('serving.breaker_trips')
    faults.configure('serve_dispatch:at=1:times=3')
    eng = ServingEngine(_echo_backend, config=ServingConfig(
        breaker_failure_threshold=3, breaker_cooldown_s=0.05))
    with eng:
        # sequential submit+wait: each request is its own (failing) batch
        results = [eng.submit(_feed(1)).result(5) for _ in range(3)]
        assert all(r.status == 'error' and r.reason == 'dispatch'
                   for r in results)
        assert eng.breaker.state == 'open'
        assert eng.state == 'degraded'     # READY masked by an open breaker
        time.sleep(0.08)                   # cooldown elapses
        probe = eng.submit(_feed(1)).result(5)
        assert probe.ok
        assert eng.breaker.state == 'closed'
        assert eng.state == 'ready'
    assert _cnt('serving.breaker_trips') == trips_before + 1
    assert eng.breaker.trips == 1 and eng.breaker.recoveries == 1


def test_breaker_open_serves_slow_path_one_request_per_batch():
    faults.configure('serve_dispatch:at=1:times=3')
    be = GatedBackend()
    eng = ServingEngine(be, config=ServingConfig(
        breaker_failure_threshold=3, breaker_cooldown_s=30.0))
    slow_before = _cnt('serving.slow_path_batches')
    with eng:
        for _ in range(3):
            be.gate.release()
        for _ in range(3):
            # sequential: three distinct failing batches trip the breaker
            assert eng.submit(_feed(1)).result(5).status == 'error'
        assert eng.breaker.state == 'open'
        # queue three same-signature requests while blocked: open breaker
        # must dispatch them one per batch, not as one superbatch
        f1 = eng.submit(_feed(1))
        f2 = eng.submit(_feed(1))
        f3 = eng.submit(_feed(1))
        for _ in range(3):
            be.gate.release()
        assert all(f.result(5).ok for f in (f1, f2, f3))
    slow_batches = [c for c in be.calls if c['x'][0] == 1]
    assert len(slow_batches) >= 3
    assert _cnt('serving.slow_path_batches') >= slow_before + 3


def test_compile_storm_trips_breaker():
    faults.configure('compile_storm:at=1:times=3:s=0')
    cold_before = _cnt('serving.cold_compiles')
    eng = ServingEngine(_echo_backend, config=ServingConfig(
        breaker_storm_threshold=3, breaker_cooldown_s=0.05))
    with eng:
        # one request per batch so each injected storm hit is one batch
        for _ in range(3):
            assert eng.submit(_feed(1)).result(5).ok
            time.sleep(0.02)
        assert eng.breaker.state == 'open'
        time.sleep(0.08)
        assert eng.submit(_feed(1)).result(5).ok   # warm probe recovers
        assert eng.breaker.state == 'closed'
    assert _cnt('serving.cold_compiles') >= cold_before + 3


# ------------------------------------------------------------- drain

def test_drain_finishes_queue_then_refuses():
    be = GatedBackend()
    eng = ServingEngine(be, config=ServingConfig(max_queue=16))
    eng.start()
    f1 = eng.submit(_feed(1))
    f2 = eng.submit(_feed(1))
    eng.begin_drain()
    r_late = eng.submit(_feed(1)).result(1)
    assert r_late.status == 'rejected' and r_late.reason == 'draining'
    for _ in range(2):
        be.gate.release()
    assert eng.drain(timeout=5)
    assert f1.result(1).ok and f2.result(1).ok   # in-flight work finished
    assert eng.state == 'stopped'
    assert not eng.ready()


def test_force_stop_sheds_leftovers_with_terminal_replies():
    deadlocks_before = _cnt('serving.deadlocks')

    def slow_backend(feed):
        time.sleep(0.2)
        return [np.asarray(feed['x']) * 2.0]

    eng = ServingEngine(slow_backend, config=ServingConfig(
        max_queue=16, breaker_cooldown_s=30.0))
    eng.start()
    futs = [eng.submit(_feed(1, fill=float(i))) for i in range(6)]
    time.sleep(0.05)             # first batch is mid-backend
    assert eng.stop(timeout=0.01)
    statuses = {f.result(1).status for f in futs}
    assert all(f.done() for f in futs)
    assert statuses <= {'ok', 'shed'}
    assert _cnt('serving.deadlocks') == deadlocks_before


# --------------------------------------------------- signal handling

def _restore_sigterm(prev):
    signal.signal(signal.SIGTERM, prev)


def test_sigterm_drain_chains_and_is_idempotent():
    prev = signal.getsignal(signal.SIGTERM)
    calls = []
    try:
        signal.signal(signal.SIGTERM,
                      lambda s, f: calls.append(s))   # pre-existing handler
        eng = ServingEngine(_echo_backend)
        eng.start()
        assert eng.install_signal_handlers()
        # second install must be a no-op: never chain a handler to itself
        assert eng.install_signal_handlers()
        handler = signal.getsignal(signal.SIGTERM)
        handler(signal.SIGTERM, None)
        assert eng.wait_drained(5)
        assert eng.state == 'stopped'
        # exactly ONE chained invocation of the pre-existing handler
        assert calls == [signal.SIGTERM]
        eng.uninstall_signal_handlers()
        assert signal.getsignal(signal.SIGTERM) is not handler
    finally:
        _restore_sigterm(prev)


def test_install_off_main_thread_warns_once_and_skips():
    prev = signal.getsignal(signal.SIGTERM)
    signals_mod._WARNED_THREAD[0] = False
    results = []
    try:
        eng = ServingEngine(_echo_backend)
        eng.start()

        def worker():
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter('always')
                results.append(eng.install_signal_handlers())
                results.append(eng.install_signal_handlers())
                results.append([str(x.message) for x in w
                                if issubclass(x.category, RuntimeWarning)])

        t = threading.Thread(target=worker)
        t.start()
        t.join(5)
        eng.stop(timeout=5)
        assert results[0] is False and results[1] is False
        assert len(results[2]) == 1          # warned ONCE, not per call
        assert 'main thread' in results[2][0]
        assert signal.getsignal(signal.SIGTERM) is prev   # untouched
    finally:
        signals_mod._WARNED_THREAD[0] = False
        _restore_sigterm(prev)


def test_signals_uninstall_restores_chain_order():
    prev = signal.getsignal(signal.SIGTERM)
    seen = []
    try:
        def make(tag):
            def factory(signum, chained):
                def handler(s, frame):
                    seen.append(tag)
                    signals_mod.chain_previous(chained, s, frame,
                                               redeliver=False)
                return handler
            return factory

        assert signals_mod.install('a', (signal.SIGTERM,), make('a'))
        assert signals_mod.install('b', (signal.SIGTERM,), make('b'))
        signal.getsignal(signal.SIGTERM)(signal.SIGTERM, None)
        assert seen == ['b', 'a']            # newest first, chained down
        signals_mod.uninstall('b')
        del seen[:]
        signal.getsignal(signal.SIGTERM)(signal.SIGTERM, None)
        assert seen == ['a']
        signals_mod.uninstall('a')
        assert signal.getsignal(signal.SIGTERM) is prev
    finally:
        signals_mod.uninstall('a')
        signals_mod.uninstall('b')
        _restore_sigterm(prev)


# ----------------------------------------- Predictor single-flight

def test_predictor_single_flight_one_compile_per_shape(monkeypatch):
    from paddle_tpu.inference import Predictor

    monkeypatch.setenv('PT_CACHE', '1')
    p = Predictor.__new__(Predictor)
    p._compiled = {}
    p._compile_lock = threading.Lock()
    p._inflight = {}
    p._params_in = []
    compiles = []

    def slow_compile(shape_key, feeds):
        time.sleep(0.2)
        compiles.append(shape_key)
        call = lambda *a: shape_key  # noqa: E731
        with p._compile_lock:
            p._compiled[shape_key] = call
        return call

    p._compile_shape = slow_compile
    waits_before = _cnt('predictor.single_flight_waits')
    feeds = {'x': np.ones((2, 3), 'float32')}
    got = []
    threads = [threading.Thread(
        target=lambda: got.append(p._fn_for(feeds)[0]))
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert len(compiles) == 1            # one thread compiled...
    assert len(set(map(id, got))) == 1   # ...everyone got its result
    assert _cnt('predictor.single_flight_waits') >= waits_before + 3
    # warm shape: straight cache hit, no new compile
    assert p._fn_for(feeds)[0] is got[0]
    assert len(compiles) == 1


def test_predictor_single_flight_failure_leaves_cache_cold(monkeypatch):
    from paddle_tpu.inference import Predictor

    monkeypatch.setenv('PT_CACHE', '1')
    p = Predictor.__new__(Predictor)
    p._compiled = {}
    p._compile_lock = threading.Lock()
    p._inflight = {}
    p._params_in = []
    attempts = []

    def flaky_compile(shape_key, feeds):
        attempts.append(shape_key)
        if len(attempts) == 1:
            time.sleep(0.1)
            raise RuntimeError('compile blew up')
        call = lambda *a: 'warm'  # noqa: E731
        with p._compile_lock:
            p._compiled[shape_key] = call
        return call

    p._compile_shape = flaky_compile
    feeds = {'x': np.ones((2, 3), 'float32')}
    outcomes = []

    def call():
        try:
            outcomes.append(p._fn_for(feeds)[0])
        except RuntimeError:
            outcomes.append('raised')

    threads = [threading.Thread(target=call) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    # the owner raised; the waiter re-checked a cold cache and compiled
    assert outcomes.count('raised') == 1
    assert len(attempts) == 2
    assert not p._inflight


# --------------------------------------------------- seeded retry jitter

def test_retry_jitter_deterministic_per_seed():
    def run(jitter, seed):
        sleeps = []
        calls = [0]

        def fn():
            calls[0] += 1
            if calls[0] < 4:
                raise OSError('transient')
            return 'done'

        assert retry_mod.retry_with_backoff(
            fn, attempts=4, base_delay=0.02, max_delay=0.5,
            sleep=sleeps.append, jitter=jitter, seed=seed) == 'done'
        return sleeps

    # jitter off (the default): the exact legacy exponential sequence
    assert run(0, None) == [0.02, 0.04, 0.08]
    a = run(0.5, 42)
    b = run(0.5, 42)
    assert a == b                        # seeded => replayable exactly
    assert a != run(0.5, 43)             # different seed de-syncs
    for base, jit in zip([0.02, 0.04, 0.08], a):
        assert 0.5 * base <= jit <= 1.5 * base


def test_retry_jitter_default_seed_stable_within_process():
    def run():
        sleeps = []
        calls = [0]

        def fn():
            calls[0] += 1
            if calls[0] < 3:
                raise OSError('x')
            return 1

        retry_mod.retry_with_backoff(fn, attempts=3, sleep=sleeps.append,
                                     jitter=0.3, name='cache_read')
        return sleeps

    assert run() == run()   # crc32(name:pid) seed replays in-process


# ------------------------------------------- terminal-reply invariant

def test_every_admitted_request_gets_terminal_reply_under_chaos():
    faults.configure('serve_dispatch:at=3:times=2,'
                     'serve_slow_batch:at=1:times=2:s=0.02,'
                     'queue_overflow:at=6:times=2')
    deadlocks_before = _cnt('serving.deadlocks')
    admitted_before = _cnt('serving.admitted')
    terminal_before = (_cnt('serving.completed') + _cnt('serving.errors') +
                       _cnt('serving.deadline_exceeded') +
                       _cnt('serving.shed'))
    eng = ServingEngine(_echo_backend,
                        bucketer=FeedBucketer(boundaries=[1, 2, 4, 8]),
                        config=ServingConfig(
                            max_queue=4, overflow_policy='shed_oldest',
                            breaker_cooldown_s=0.02))
    eng.start()
    futs = [eng.submit(_feed(1 + (i % 3)), timeout_s=5.0)
            for i in range(24)]
    assert eng.stop(timeout=10)
    assert all(f.done() for f in futs)
    statuses = {f.result(0).status for f in futs}
    assert statuses <= {'ok', 'error', 'shed', 'rejected',
                        'deadline_exceeded'}
    assert _cnt('serving.deadlocks') == deadlocks_before
    admitted = _cnt('serving.admitted') - admitted_before
    terminal = (_cnt('serving.completed') + _cnt('serving.errors') +
                _cnt('serving.deadline_exceeded') + _cnt('serving.shed')
                - terminal_before)
    assert admitted == terminal


# --------------------------------------------------------- end to end

def test_end_to_end_predictor_serving(tmp_path):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            out = fluid.layers.fc(x, 3, act='softmax')
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(str(tmp_path / 'model'), ['x'],
                                      [out], exe, main)
    predictor = fluid.inference.Predictor(str(tmp_path / 'model'))
    eng = ServingEngine.from_predictor(
        predictor, bucketer=FeedBucketer(boundaries=[2, 4]),
        config=ServingConfig(max_queue=16))
    with eng:
        rng = np.random.RandomState(0)
        f1 = eng.submit({'x': rng.rand(1, 4).astype('float32')})
        f2 = eng.submit({'x': rng.rand(2, 4).astype('float32')})
        r1, r2 = f1.result(60), f2.result(60)
    assert r1.ok and r2.ok
    assert r1.outputs[0].shape == (1, 3)
    assert r2.outputs[0].shape == (2, 3)
    # softmax rows sum to 1 — the scatter returned REAL rows, not padding
    np.testing.assert_allclose(r1.outputs[0].sum(axis=1), [1.0], atol=1e-5)
    np.testing.assert_allclose(r2.outputs[0].sum(axis=1), [1.0, 1.0],
                               atol=1e-5)
