"""Conv/pool/matmul attribute-variant numerics vs torch (CPU) as an
independent oracle (model: reference unittests test_conv2d_op.py's
attribute grid: strides/pads/dilations/groups, pool exclusive/ceil)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from paddle_tpu import layers
from test_layers import _run


def _np(x):
    return np.asarray(x, dtype='float32')


@pytest.mark.parametrize('cfg', [
    dict(stride=1, pad=1, dil=1, groups=1),
    dict(stride=2, pad=1, dil=1, groups=1),
    dict(stride=1, pad=2, dil=2, groups=1),
    dict(stride=1, pad=1, dil=1, groups=2),
    dict(stride=1, pad=1, dil=1, groups=4),   # depthwise (C=4)
], ids=lambda c: 's%dp%dd%dg%d' % (c['stride'], c['pad'], c['dil'],
                                   c['groups']))
def test_conv2d_variants_vs_torch(cfg):
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 4, 8, 8).astype('float32')
    x = layers.data('x', shape=[4, 8, 8], dtype='float32')
    out = layers.conv2d(x, num_filters=8, filter_size=3,
                        stride=cfg['stride'], padding=cfg['pad'],
                        dilation=cfg['dil'], groups=cfg['groups'],
                        bias_attr=False, act=None,
                        param_attr=None)
    res, = _run([out], {'x': xv})
    # oracle: torch conv2d driven with the SAME initialized filter,
    # pulled from the scope the program ran in
    import paddle_tpu as fluid
    w = np.asarray(fluid.global_scope().get(
        [p.name for p in
         fluid.default_main_program().global_block().all_parameters()][0]))
    ref = F.conv2d(torch.from_numpy(xv), torch.from_numpy(w), None,
                   stride=cfg['stride'], padding=cfg['pad'],
                   dilation=cfg['dil'], groups=cfg['groups']).numpy()
    np.testing.assert_allclose(res, ref, rtol=1e-4, atol=1e-5)


def test_conv2d_grad_vs_torch():
    """Grouped+dilated conv gradient (input and filter) vs torch
    autograd."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    impl = get_op('conv2d').impl
    rng = np.random.RandomState(1)
    xv = rng.randn(2, 4, 6, 6).astype('float32')
    wv = rng.randn(6, 2, 3, 3).astype('float32')   # groups=2
    attrs = {'strides': [1, 1], 'paddings': [1, 1], 'dilations': [2, 2],
             'groups': 2}

    def loss(x, w):
        return (impl(None, {'Input': x, 'Filter': w}, attrs)['Output']
                ** 2).sum()

    gx, gw = jax.grad(loss, argnums=(0, 1))(jnp.asarray(xv),
                                            jnp.asarray(wv))
    tx = torch.from_numpy(xv).requires_grad_(True)
    tw = torch.from_numpy(wv).requires_grad_(True)
    (F.conv2d(tx, tw, None, stride=1, padding=1, dilation=2,
              groups=2) ** 2).sum().backward()
    np.testing.assert_allclose(_np(gx), tx.grad.numpy(), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(_np(gw), tw.grad.numpy(), rtol=1e-3,
                               atol=1e-4)


def test_conv3d_vs_torch():
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    impl = get_op('conv3d').impl
    rng = np.random.RandomState(2)
    xv = rng.randn(1, 3, 5, 6, 6).astype('float32')
    wv = rng.randn(4, 3, 3, 3, 3).astype('float32')
    out = impl(None, {'Input': jnp.asarray(xv), 'Filter': jnp.asarray(wv)},
               {'strides': [1, 2, 1], 'paddings': [1, 1, 0]})['Output']
    ref = F.conv3d(torch.from_numpy(xv), torch.from_numpy(wv), None,
                   stride=(1, 2, 1), padding=(1, 1, 0)).numpy()
    np.testing.assert_allclose(_np(out), ref, rtol=1e-4, atol=1e-5)


def test_conv2d_transpose_vs_torch():
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    impl = get_op('conv2d_transpose').impl
    rng = np.random.RandomState(3)
    xv = rng.randn(2, 4, 5, 5).astype('float32')
    wv = rng.randn(4, 3, 3, 3).astype('float32')   # [in, out, kh, kw]
    out = impl(None, {'Input': jnp.asarray(xv), 'Filter': jnp.asarray(wv)},
               {'strides': [2, 2], 'paddings': [1, 1]})['Output']
    ref = F.conv_transpose2d(torch.from_numpy(xv), torch.from_numpy(wv),
                             None, stride=2, padding=1).numpy()
    np.testing.assert_allclose(_np(out), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize('cfg', [
    dict(ptype='max', pad=1, exclusive=True, ceil=False),
    dict(ptype='avg', pad=1, exclusive=True, ceil=False),
    dict(ptype='avg', pad=1, exclusive=False, ceil=False),
    dict(ptype='avg', pad=0, exclusive=True, ceil=False),
    dict(ptype='max', pad=0, exclusive=True, ceil=True),
    dict(ptype='max', pad=1, exclusive=True, ceil=True),
], ids=lambda c: '%s_p%d_ex%d_c%d' % (c['ptype'], c['pad'],
                                      c['exclusive'], c['ceil']))
def test_pool2d_variants_vs_torch(cfg):
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    impl = get_op('pool2d').impl
    rng = np.random.RandomState(4)
    xv = rng.randn(2, 3, 8, 8).astype('float32')
    out = impl(None, {'X': jnp.asarray(xv)},
               {'ksize': [3, 3], 'strides': [2, 2],
                'paddings': [cfg['pad'], cfg['pad']],
                'pooling_type': cfg['ptype'],
                'exclusive': cfg['exclusive'],
                'ceil_mode': cfg['ceil']})['Out']
    t = torch.from_numpy(xv)
    if cfg['ptype'] == 'max':
        ref = F.max_pool2d(t, 3, stride=2, padding=cfg['pad'],
                           ceil_mode=cfg['ceil']).numpy()
    else:
        # reference 'exclusive' == torch count_include_pad=False
        ref = F.avg_pool2d(t, 3, stride=2, padding=cfg['pad'],
                           count_include_pad=not cfg['exclusive'],
                           ceil_mode=cfg['ceil']).numpy()
    np.testing.assert_allclose(_np(out), ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('tx,ty', [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_matmul_transpose_variants(tx, ty):
    import jax.numpy as jnp
    from paddle_tpu.core.registry import get_op
    impl = get_op('matmul').impl
    rng = np.random.RandomState(5)
    a = rng.randn(2, 3, 4).astype('float32')
    b = rng.randn(2, 4, 5).astype('float32')
    av = a.transpose(0, 2, 1) if tx else a
    bv = b.transpose(0, 2, 1) if ty else b
    out = impl(None, {'X': jnp.asarray(av), 'Y': jnp.asarray(bv)},
               {'transpose_X': tx, 'transpose_Y': ty})['Out']
    ref = np.matmul(a, b)
    np.testing.assert_allclose(_np(out), ref, rtol=1e-5, atol=1e-6)
