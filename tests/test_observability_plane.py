"""Observability plane (PR 9): request-level tracing, flight recorder,
metrics export (Prometheus + HTTP), device-memory hooks, bounded
histograms, stall attribution under degraded serving, and the shared
telemetry-snapshot schema."""
import json
import os
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu.data_feeder import FeedBucketer
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import flight as obs_flight
from paddle_tpu.observability import memory as obs_memory
from paddle_tpu.observability import trace_context as tc
from paddle_tpu.serving import ServingConfig, ServingEngine
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cnt(name):
    return obs.counters().get(name) or 0


def _echo_backend(feed):
    x = np.asarray(feed['x'])
    return [x * 2.0]


def _feed(rows, cols=3):
    return {'x': np.arange(rows * cols,
                           dtype='float32').reshape(rows, cols)}


# ------------------------------------------------- bounded histograms

def test_histogram_million_observations_bounded_memory_stable_quantiles():
    """Satellite pin: the bounded log-bucket backing store.  A million
    observations spanning six decades must keep O(1) memory (bucket
    count bounded by the VALUE RANGE, not the observation count) and
    still answer p50/p99 within a few percent."""
    h = obs.histogram('t.h_million')
    rng = np.random.RandomState(7)
    vals = np.exp(rng.standard_normal(1_000_000) * 2.0 + 1.0)
    for v in vals.tolist():
        h.observe(v)
    # log buckets with 4 mantissa sub-buckets: ~40 octaves of range
    # would still be < 200 buckets; 1M observations add ZERO
    assert h.bucket_count() < 200
    snap = h.snapshot()
    assert snap['count'] == 1_000_000
    for q in (0.50, 0.99):
        true = float(np.percentile(vals, q * 100))
        est = h.quantile(q)
        assert abs(est - true) / true < 0.05, (q, est, true)
    # Prometheus cumulative buckets are monotone and end at the count
    cum = h.cumulative_buckets()
    counts = [c for _, c in cum]
    assert counts == sorted(counts) and counts[-1] == 1_000_000


def test_histogram_nonpositive_bucket_and_quantile_clamp():
    h = obs.histogram('t.h_edge')
    for v in (0.0, -3.5, 2.0, 4.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap['count'] == 4 and snap['buckets']['le_0'] == 2
    q = h.quantile(0.99)
    assert snap['min'] <= q <= snap['max']
    assert obs.histogram('t.h_never').quantile(0.5) is None


# --------------------------------------------------- trace context

def test_traceparent_roundtrip_and_malformed():
    ctx = tc.TraceContext.new()
    hdr = ctx.to_traceparent()
    back = tc.TraceContext.from_traceparent(hdr)
    assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_span_id == ctx.span_id
    assert child.span_id != ctx.span_id
    for bad in (None, '', 'junk', '00-' + '0' * 32 + '-' + 'a' * 16 + '-01',
                '00-' + 'a' * 32 + '-' + '0' * 16 + '-01'):
        assert tc.TraceContext.from_traceparent(bad) is None
    args = child.span_args(rows=3)
    assert args['trace_id'] == ctx.trace_id and args['rows'] == 3
    assert args['parent_span_id'] == ctx.span_id


def test_ambient_context_stamps_spans():
    ctx = tc.TraceContext.new()
    with tc.use(ctx):
        obs.tracing.add_span('t.ambient', 0.0, 0.001, cat='test')
    evs = [e for e in obs.recorder().events() if e['name'] == 't.ambient']
    assert evs and evs[-1]['args']['trace_id'] == ctx.trace_id


def test_root_span_noop_when_disabled():
    obs.disable()
    try:
        before = obs.recorder().event_count()
        with tc.root_span('t.root_off') as ctx:
            assert ctx is None
            assert tc.current() is None
        assert obs.recorder().event_count() == before
    finally:
        obs.enable()


# --------------------------------------- serving request decomposition

def test_request_trace_decomposes_into_linked_child_spans():
    import time as _time

    def backend(feed):
        _time.sleep(0.004)   # a measurable device window: the >=90%
        return _echo_backend(feed)   # coverage bound is about real time

    eng = ServingEngine(backend,
                        bucketer=FeedBucketer(boundaries=[1, 2, 4, 8]),
                        config=ServingConfig(max_queue=16))
    eng.start()
    futs = [eng.submit(_feed(1 + (i % 3)), timeout_s=5.0) for i in range(6)]
    assert eng.stop(timeout=10)
    events = obs.recorder().events()
    ok = [f for f in futs if f.result(0).status == 'ok']
    assert ok and all(f.traceparent for f in futs)
    verified = 0
    for f in ok:
        tid = f.traceparent.split('-')[1]
        roots = [e for e in events if e['name'] == 'serving.request'
                 and e.get('args', {}).get('trace_id') == tid]
        assert len(roots) == 1, (tid, roots)
        assert roots[0]['args']['status'] == 'ok'
        kids = {e['name']: e for e in events
                if e['name'] in ('serving.queue_wait', 'serving.dispatch',
                                 'serving.device')
                and e.get('args', {}).get('trace_id') == tid}
        assert set(kids) == {'serving.queue_wait', 'serving.dispatch',
                             'serving.device'}
        batch_sid = kids['serving.queue_wait']['args']['batch_span_id']
        batches = [e for e in events if e['name'] == 'serving.batch'
                   and e['args'].get('span_id') == batch_sid]
        assert len(batches) == 1
        assert tid in batches[0]['args']['links']
        covered = sum(k['dur'] for k in kids.values())
        assert covered >= 0.9 * roots[0]['dur'], (covered, roots[0]['dur'])
        verified += 1
    assert verified == len(ok)


def test_chaos_dispatch_failure_one_root_span_status_matches_reply():
    """Satellite pin: under serve_dispatch chaos every request still
    yields EXACTLY one root span, and its status IS the terminal
    reply's status."""
    faults.configure('serve_dispatch:at=1:times=1')
    eng = ServingEngine(_echo_backend,
                        bucketer=FeedBucketer(boundaries=[1, 2, 4, 8]),
                        config=ServingConfig(max_queue=16))
    eng.start()
    futs = [eng.submit(_feed(1), timeout_s=5.0) for i in range(8)]
    assert eng.stop(timeout=10)
    statuses = [f.result(0).status for f in futs]
    assert 'error' in statuses   # the injected batch failure surfaced
    events = obs.recorder().events()
    for f, status in zip(futs, statuses):
        tid = f.traceparent.split('-')[1]
        roots = [e for e in events if e['name'] == 'serving.request'
                 and e.get('args', {}).get('trace_id') == tid]
        assert len(roots) == 1, (tid, status, len(roots))
        assert roots[0]['args']['status'] == status


def test_obs_disabled_new_surfaces_do_zero_work():
    obs.disable()
    try:
        ring_before = len(obs_flight.flight().events())
        events_before = obs.recorder().event_count()
        gauges_before = dict(obs.metrics_snapshot()['gauges'])
        eng = ServingEngine(_echo_backend,
                            bucketer=FeedBucketer(boundaries=[1, 2]),
                            config=ServingConfig(metrics_port=0))
        eng.start()
        fut = eng.submit(_feed(1), timeout_s=5.0)
        assert eng.stop(timeout=10)
        assert fut.result(0).status == 'ok'
        assert fut.traceparent is None          # no trace minted
        assert eng.metrics_port is None         # no HTTP server started
        obs_flight.record('t.should_not_record')
        obs_memory.on_launch()
        assert len(obs_flight.flight().events()) == ring_before
        assert obs.recorder().event_count() == events_before
        assert obs.metrics_snapshot()['gauges'] == gauges_before
    finally:
        obs.enable()


# ----------------------------------------------------- flight recorder

def test_flight_ring_bounded_and_tap_mirrors_trace_events():
    fr = obs_flight.FlightRecorder(max_events=16)
    for i in range(100):
        fr.record('t.ev', i=i)
    assert len(fr.events()) == 16
    assert fr.events()[-1]['i'] == 99
    # the installed global tap mirrors every trace event into the ring
    obs.instant('t.flight_mirror', cat='test')
    names = [e.get('name') for e in obs_flight.flight().events()]
    assert 't.flight_mirror' in names


def test_flight_dump_artifact_and_maybe_dump_gating(tmp_path, monkeypatch):
    monkeypatch.delenv('PT_FLIGHT_DIR', raising=False)
    assert obs_flight.maybe_dump('no_dir_no_dump') is None
    obs_flight.record('t.dumped', detail='x')
    path = obs_flight.dump('unit_test', path=str(tmp_path / 'f.json'))
    art = json.load(open(path))
    assert art['reason'] == 'unit_test' and art['pid'] == os.getpid()
    assert any(e.get('kind') == 't.dumped' for e in art['events'])
    assert 'counters' in art['metrics'] and 'env' in art
    monkeypatch.setenv('PT_FLIGHT_DIR', str(tmp_path))
    p2 = obs_flight.maybe_dump('gated', extra={'k': 1})
    assert p2 and os.path.dirname(p2) == str(tmp_path)
    assert json.load(open(p2))['extra'] == {'k': 1}


def test_flight_dump_budget_cap(monkeypatch):
    monkeypatch.setattr(obs_flight, '_MAX_DUMPS', 2)
    fr = obs_flight.FlightRecorder(max_events=4)
    import tempfile
    d = tempfile.mkdtemp(prefix='pt_flight_cap.')
    assert fr.dump('a', path=os.path.join(d, 'a.json'))
    assert fr.dump('b', path=os.path.join(d, 'b.json'))
    assert fr.dump('c', path=os.path.join(d, 'c.json')) is None


def test_serving_batch_failure_leaves_flight_dump(tmp_path, monkeypatch):
    monkeypatch.setenv('PT_FLIGHT_DIR', str(tmp_path))
    faults.configure('serve_dispatch:at=1:times=1')
    eng = ServingEngine(_echo_backend,
                        bucketer=FeedBucketer(boundaries=[1, 2]),
                        config=ServingConfig(max_queue=8))
    eng.start()
    futs = [eng.submit(_feed(1), timeout_s=5.0) for _ in range(4)]
    assert eng.stop(timeout=10)
    assert any(f.result(0).status == 'error' for f in futs)
    dumps = [fn for fn in os.listdir(str(tmp_path))
             if 'serving_batch_failure' in fn]
    assert dumps
    art = json.load(open(str(tmp_path / dumps[0])))
    evs = art['events']
    assert any(e.get('kind') == 'serving.batch_failure' for e in evs)
    assert any(e.get('name') == 'fault.injected'
               and e.get('args', {}).get('site') == 'serve_dispatch'
               for e in evs)


# -------------------------------------------------- prometheus + HTTP

def test_prometheus_rendering():
    obs.counter('promtest.ctr').inc(3)
    obs.gauge('promtest.g').set(1.5)
    h = obs.histogram('promtest.h')
    for v in (0.5, 1.0, 8.0):
        h.observe(v)
    text = obs.render_prometheus()
    assert 'promtest_ctr_total 3' in text
    assert '# TYPE promtest_ctr_total counter' in text
    assert 'promtest_g 1.5' in text
    assert 'promtest_h_bucket{le="+Inf"} 3' in text
    assert 'promtest_h_count 3' in text
    assert 'promtest_h_sum 9.5' in text


def _get(url):
    with urllib.request.urlopen(url, timeout=5.0) as r:
        return r.status, r.read().decode(), r.headers.get('Content-Type')


def test_metrics_http_server_endpoints():
    obs.counter('httptest.ctr').inc()
    srv = obs_export.start_http_server(port=0)
    try:
        code, body, ctype = _get(srv.url('/metrics'))
        assert code == 200 and 'httptest_ctr_total' in body
        assert ctype == obs_export.PROM_CONTENT_TYPE
        code, body, _ = _get(srv.url('/healthz'))
        assert code == 200 and json.loads(body)['accepting'] is True
        code, body, _ = _get(srv.url('/varz'))
        assert code == 200 and 'counters' in json.loads(body)
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.url('/nope'))
    finally:
        srv.stop()


def test_engine_owns_metrics_server_lifecycle():
    eng = ServingEngine(_echo_backend,
                        bucketer=FeedBucketer(boundaries=[1, 2]),
                        config=ServingConfig(metrics_port=0))
    assert eng.metrics_port is None   # not started before start()
    eng.start()
    try:
        port = eng.metrics_port
        assert isinstance(port, int) and port > 0
        code, _, _ = _get('http://127.0.0.1:%d/healthz' % port)
        assert code == 200
        fut = eng.submit(_feed(1), timeout_s=5.0)
        assert fut.result(5).status == 'ok'
        assert eng.drain(timeout=10)
        # the endpoint must survive the drain so post-drain scrapes can
        # verify the accounting identity...
        code, body, _ = _get('http://127.0.0.1:%d/metrics' % port)
        assert code == 200 and 'serving_admitted_total' in body
        # ...and /healthz now refuses
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get('http://127.0.0.1:%d/healthz' % port)
        assert ei.value.code == 503
    finally:
        eng.stop_metrics_server()
    with pytest.raises(urllib.error.URLError):
        _get('http://127.0.0.1:%d/healthz' % port)


def test_resolve_metrics_port_precedence(monkeypatch):
    monkeypatch.delenv('PT_METRICS_PORT', raising=False)
    assert obs_export.resolve_metrics_port(None) is None
    assert obs_export.resolve_metrics_port(9100) == 9100
    monkeypatch.setenv('PT_METRICS_PORT', '9200')
    assert obs_export.resolve_metrics_port(None) == 9200
    assert obs_export.resolve_metrics_port(0) == 0   # config beats env


# -------------------------------------------------------- memory hooks

def test_memory_hooks_graceful_on_cpu():
    obs_memory._reset_probe()
    obs_memory.on_launch()
    gauges = obs.metrics_snapshot()['gauges']
    # CPU: no memory_stats() -> no HBM gauges, but live buffers always
    assert 'exec.live_buffers' in gauges
    assert gauges['exec.live_buffers'] >= 0
    assert obs_memory.device_memory_stats() is None
    assert obs_memory._STATS_SUPPORTED[0] is False   # cached verdict
    assert obs_memory.host_rss_bytes() > 0


def test_checkpoint_snapshot_host_bytes_accounting(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu.train import CheckpointConfig, Checkpointer
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            fluid.layers.fc(x, 8)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck = Checkpointer(CheckpointConfig(str(tmp_path / 'ckpt'),
                                           handle_signals=False), exe)
        ck.save(0, 0, blocking=True)
    g = obs.metrics_snapshot()['gauges']
    assert g.get('ckpt.snapshot_host_bytes', 0) > 0
    assert _cnt('ckpt.snapshot_bytes_total') >= g['ckpt.snapshot_host_bytes']


# ------------------------------------------ stall attribution (satellite)

def test_stall_suppression_counts_suppressed_not_stall():
    old = obs.stall_threshold_ms()
    obs.set_stall_threshold_ms(50)
    owner = types.SimpleNamespace()
    try:
        stalls0, supp0 = _cnt('executor.stall_count'), \
            _cnt('executor.stall_suppressed')
        obs.on_launch_end(owner, 0.0)
        with obs.stall.suppress('breaker_slow'):
            assert obs.stall.suppressed()
            obs.on_launch_start(owner, 1.0)   # 1000 ms gap, suppressed
        assert not obs.stall.suppressed()
        assert _cnt('executor.stall_count') == stalls0
        assert _cnt('executor.stall_suppressed') == supp0 + 1
        sup = [e for e in obs.recorder().events()
               if e['name'] == 'pipeline.stall_suppressed']
        assert sup and sup[-1]['args']['reason'] == 'breaker_slow'
        # the same gap WITHOUT suppression is a real stall
        obs.on_launch_end(owner, 2.0)
        obs.on_launch_start(owner, 3.0)
        assert _cnt('executor.stall_count') == stalls0 + 1
    finally:
        obs.set_stall_threshold_ms(old)


def test_breaker_slow_path_dispatches_run_suppressed():
    """Satellite pin (fault-injected): while the breaker serves the
    degraded slow path, the dispatch window is marked suppressed so
    backend-side launch gaps don't pollute the stall SLO."""
    faults.configure('serve_dispatch:at=2:times=1')
    seen = []

    def backend(feed):
        seen.append(obs.stall.suppressed())
        x = np.asarray(feed['x'])
        return [x * 2.0]

    eng = ServingEngine(backend,
                        bucketer=FeedBucketer(boundaries=[1, 2]),
                        config=ServingConfig(
                            max_queue=16, breaker_failure_threshold=1,
                            breaker_cooldown_s=30.0))
    eng.start()
    # first wave: dispatch 1 succeeds (normal mode, NOT suppressed),
    # dispatch 2 takes the injected failure and trips the breaker
    assert eng.submit(_feed(1), timeout_s=5.0).result(5).status == 'ok'
    assert eng.submit(_feed(1), timeout_s=5.0).result(5).status == 'error'
    assert eng.breaker.trips >= 1
    # cooldown_s=30 keeps the breaker OPEN: every dispatch from here on
    # is a slow-path batch and must run inside the suppressed window
    futs = [eng.submit(_feed(1), timeout_s=5.0) for _ in range(4)]
    assert eng.stop(timeout=10)
    assert all(f.result(0).status == 'ok' for f in futs)
    assert seen[0] is False           # normal-mode dispatch: not marked
    assert seen[-1] is True           # slow-path dispatch: suppressed
    assert _cnt('executor.stall_suppressed') >= 0


def test_recovery_rollback_clears_stall_window_and_traces():
    from paddle_tpu.train.recovery import RecoveryPolicy
    exe = types.SimpleNamespace(_obs_prev_launch_end=123.0)

    class _Ckpt(object):
        executor = exe

        def restore(self):
            return {'step_id': 7}

    cleared0 = _cnt('executor.stall_windows_cleared')
    pol = RecoveryPolicy(_Ckpt())
    meta = pol.rollback(reason='unit')
    assert meta['step_id'] == 7
    assert exe._obs_prev_launch_end is None
    assert _cnt('executor.stall_windows_cleared') == cleared0 + 1
    roots = [e for e in obs.recorder().events()
             if e['name'] == 'recovery.rollback' and e['ph'] == 'X']
    assert roots and 'trace_id' in roots[-1]['args']


def test_recovery_giveup_dumps_flight(tmp_path, monkeypatch):
    from paddle_tpu.train.recovery import DivergenceError, RecoveryPolicy
    monkeypatch.setenv('PT_FLIGHT_DIR', str(tmp_path))
    exe = types.SimpleNamespace()

    class _Ckpt(object):
        executor = exe

        def restore(self):
            return {'step_id': 1}

    pol = RecoveryPolicy(_Ckpt(), max_retries=1)

    def diverge():
        raise DivergenceError('loss is non-finite')

    assert pol.run(diverge) is None          # first: rollback + skip
    with pytest.raises(DivergenceError):
        pol.run(diverge)                     # second: give up, re-raise
    dumps = [fn for fn in os.listdir(str(tmp_path))
             if 'recovery_giveup' in fn]
    assert dumps
    art = json.load(open(str(tmp_path / dumps[0])))
    assert any(e.get('kind') == 'recovery.giveup' for e in art['events'])


# ------------------------------------------- shared telemetry schema

def test_telemetry_snapshot_strict_extra_validation():
    with pytest.raises(ValueError, match='missing extra keys'):
        obs.telemetry_snapshot('bench')
    with pytest.raises(ValueError, match='unexpected extra keys'):
        obs.telemetry_snapshot('resilience', extra={'nope': 1})


def test_telemetry_snapshot_sections_match_schema():
    tel = obs.telemetry_snapshot(
        'bench', extra={'platform': 'cpu', 'device_kind': 'cpu',
                        'program_op_count_raw': 10,
                        'program_op_count_opt': 7,
                        'fused_adam_ms': 1.5})
    assert list(tel) == obs_export.schema_keys('bench')
    obs.histogram('serving.latency_ms').observe(5.0)
    obs.counter('serving.admitted').inc(0)
    srv = obs.telemetry_snapshot('serving')
    assert list(srv) == obs_export.schema_keys('serving')
    assert srv['p50_ms'] is not None
    res = obs.telemetry_snapshot('resilience')
    assert set(res['counters']) >= {'faults.injected', 'recovery.rollbacks',
                                    'executor.retraces'}


def test_prom_name_sanitization():
    assert obs_export.prom_name('serving.admitted', '_total') == \
        'serving_admitted_total'
    assert obs_export.prom_name('a-b/c d') == 'a_b_c_d'
    assert obs_export.prom_name('1abc') == '_1abc'
