"""Fault-injection harness (paddle_tpu/testing/faults.py): spec parsing,
deterministic firing, metric accounting, and the runtime sites it drives
(retry_with_backoff, compile-cache I/O, prefetcher stall, nan_step)."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.core.retry import retry_with_backoff
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ------------------------------------------------------------------ parsing

def test_spec_parsing():
    armed = faults.configure('ckpt_write:at=2,nan_step:at=5:times=3:row=1,'
                             'prefetch_stall:at=1:s=0.25')
    assert set(armed) == {'ckpt_write', 'nan_step', 'prefetch_stall'}
    assert armed['nan_step'].at == 5 and armed['nan_step'].times == 3
    assert armed['nan_step'].row == 1
    assert armed['prefetch_stall'].sleep_s == 0.25
    assert faults.active('ckpt_write') and not faults.active('cache_read')
    # spec() is the read-only accessor soak gates compare verdicts against
    assert faults.spec('nan_step').row == 1
    assert faults.spec('cache_read') is None


def test_spec_rejects_unknown_field():
    with pytest.raises(ValueError, match='not understood'):
        faults.configure('ckpt_write:frequency=2')


def test_env_parse_is_lazy_and_resettable(monkeypatch):
    monkeypatch.setenv('PT_FAULT', 'cache_read:at=1')
    faults.reset()
    assert faults.any_active() and faults.active('cache_read')
    monkeypatch.delenv('PT_FAULT')
    faults.reset()
    assert not faults.any_active()


# ------------------------------------------------------------------- firing

def test_hit_indexed_fire_is_deterministic():
    faults.configure('cache_read:at=3:times=2')
    fires = [faults.fire('cache_read') for _ in range(6)]
    assert fires == [False, False, True, True, False, False]


def test_step_indexed_fire_and_budget_cap():
    faults.configure('nan_step:at=4')
    assert not faults.fire('nan_step', step=3)
    assert faults.fire('nan_step', step=4)
    # budget spent: a rollback replaying step 4 must not re-fire forever
    assert not faults.fire('nan_step', step=4)


def test_fire_in_window_overlap():
    faults.configure('sigterm:at=5')
    assert not faults.fire_in('sigterm', 0, 4)    # [0,4) misses 5
    assert faults.fire_in('sigterm', 4, 4)        # [4,8) covers 5
    assert not faults.fire_in('sigterm', 4, 4)    # budget spent


def test_forensic_replay_ignores_and_preserves_spent_budget():
    """Inside forensic_replay() the nan_step site re-fires its armed
    window without consuming budget; outside, the one-shot semantics
    are intact — before AND after the replay."""
    before = obs.counters().get('faults.injected.nan_step') or 0
    faults.configure('nan_step:at=4')
    assert faults.fire_in('nan_step', 4, 2)       # production: consumed
    assert not faults.fire_in('nan_step', 4, 2)   # budget spent
    with faults.forensic_replay():
        assert faults.fire_in('nan_step', 4, 2)   # replay re-fires...
        assert faults.fire('nan_step', step=4)    # ...as often as asked
    assert not faults.fire_in('nan_step', 4, 2)   # budget still spent
    # the replay fires were not re-counted as injections
    assert obs.counters().get('faults.injected.nan_step') == before + 1


def test_forensic_replay_only_covers_nan_step():
    faults.configure('cache_read:at=1')
    assert faults.fire('cache_read')
    with faults.forensic_replay():
        # other sites keep their budget semantics during a replay
        assert not faults.fire('cache_read')


def test_fired_faults_count_into_observability():
    faults.configure('io_write:at=1')
    c0 = obs.counters()
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail('io_write')
    c = obs.counters()
    assert c.get('faults.injected') == (c0.get('faults.injected') or 0) + 1
    assert c.get('faults.injected.io_write') == \
        (c0.get('faults.injected.io_write') or 0) + 1


# ------------------------------------------------------------------- retry

def test_retry_recovers_from_transient_failure():
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise OSError('transient')
        return 'ok'

    assert retry_with_backoff(flaky, attempts=3, base_delay=0.001) == 'ok'
    assert calls[0] == 3


def test_retry_gives_up_and_reraises():
    with pytest.raises(OSError, match='persistent'):
        retry_with_backoff(lambda: (_ for _ in ()).throw(
            OSError('persistent')), attempts=2, base_delay=0.001)
    assert (obs.counters().get('retry.giveups') or 0) >= 1


def test_retry_never_retries_give_up_exceptions():
    calls = [0]

    def missing():
        calls[0] += 1
        raise FileNotFoundError('no entry')

    with pytest.raises(FileNotFoundError):
        retry_with_backoff(missing, attempts=5, base_delay=0.001,
                           give_up_on=(FileNotFoundError,))
    assert calls[0] == 1


# ------------------------------------------------------- compile-cache site

def test_cache_write_fault_recovers_via_retry(tmp_path, monkeypatch):
    """One injected cache_write OSError must NOT lose the disk store:
    the shared retry_with_backoff absorbs it on the second attempt."""
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    from paddle_tpu.core.compile_cache import DiskCache
    faults.configure('cache_write:at=1')

    class _Lowered(object):
        @staticmethod
        def as_text():
            return 'module @jit { }'

    cache = DiskCache(str(tmp_path))
    tier = cache.store('ab' * 32, compiled=None, lowered=_Lowered())
    assert tier == 'stablehlo'
    assert (obs.counters().get('retry.attempts.cache_write') or 0) >= 1
    assert cache.load('ab' * 32) == (None, 'stablehlo')


def test_cache_read_fault_recovers_via_retry(tmp_path):
    from paddle_tpu.core.compile_cache import DiskCache

    class _Lowered(object):
        @staticmethod
        def as_text():
            return 'module @jit { }'

    cache = DiskCache(str(tmp_path))
    assert cache.store('cd' * 32, lowered=_Lowered()) == 'stablehlo'
    faults.configure('cache_read:at=1')
    assert cache.load('cd' * 32) == (None, 'stablehlo')
    assert (obs.counters().get('retry.attempts.cache_read') or 0) >= 1


# ----------------------------------------------------------- io.py sites

def test_io_write_and_read_faults_recover_via_retry(tmp_path):
    """One transient OSError on each side of the io.py tensor store must
    be absorbed by retry_with_backoff — the save/load pair still meets."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            fluid.layers.fc(x, 3)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w = np.asarray(scope.get('fc_0.w_0'))
        faults.configure('io_write:at=1,io_read:at=1')
        fluid.io.save_persistables(exe, str(tmp_path), main)
        scope.set('fc_0.w_0', w * 0)
        fluid.io.load_persistables(exe, str(tmp_path), main)
        np.testing.assert_array_equal(np.asarray(scope.get('fc_0.w_0')), w)
    c = obs.counters()
    assert (c.get('retry.attempts.io_write') or 0) >= 1
    assert (c.get('retry.attempts.io_read') or 0) >= 1


# ---------------------------------------------------------- prefetcher site

def test_prefetch_stall_site_fires_and_counts():
    from paddle_tpu.data_feeder import FeedPrefetcher
    before = obs.counters().get('faults.injected.prefetch_stall') or 0
    faults.configure('prefetch_stall:at=1:s=0.01')
    feeds = [{'x': np.full((2, 2), i, np.float32)} for i in range(4)]
    pf = FeedPrefetcher(iter(feeds), steps=2, to_device=False)
    got = [k for _, k in pf]
    pf.close()
    assert got == [2, 2]
    assert obs.counters().get('faults.injected.prefetch_stall') == before + 1


# ------------------------------------------------------------ feed_read site

def test_feed_read_fault_absorbed_by_retry():
    """One injected reader OSError must NOT kill the trainer: the worker
    pulls through retry_with_backoff, which absorbs it and re-reads."""
    from paddle_tpu.data_feeder import FeedPrefetcher
    before = obs.counters().get('retry.attempts.feed_read') or 0
    faults.configure('feed_read:at=2')
    feeds = [{'x': np.full((2, 2), i, np.float32)} for i in range(4)]
    pf = FeedPrefetcher(iter(feeds), steps=2, to_device=False)
    got = [(f, k) for f, k in pf]
    pf.close()
    assert [k for _, k in got] == [2, 2]
    # retried, not reordered: every batch arrived exactly once, in order
    vals = [float(f['x'][j, 0, 0]) for f, _ in got for j in range(2)]
    assert vals == [0.0, 1.0, 2.0, 3.0]
    assert (obs.counters().get('retry.attempts.feed_read') or 0) >= before + 1


def test_feed_read_exhaustion_is_not_a_retry():
    """Reader exhaustion (StopIteration) must drain cleanly through the
    retry wrapper — no attempts, no giveups: an empty stream is not a
    fault."""
    from paddle_tpu.data_feeder import FeedPrefetcher
    faults.configure('feed_read:at=99')   # armed but never reached
    c0 = obs.counters()
    feeds = [{'x': np.zeros((2, 2), np.float32)} for _ in range(3)]
    pf = FeedPrefetcher(iter(feeds), steps=2, to_device=False)
    got = [k for _, k in pf]
    pf.close()
    assert got == [2, 1]                  # partial tail flushed
    c = obs.counters()
    for key in ('retry.attempts.feed_read', 'retry.giveups.feed_read'):
        assert (c.get(key) or 0) == (c0.get(key) or 0)


# --------------------------------------------------- poison_nan row targeting

def test_poison_nan_row_targets_single_row():
    faults.configure('nan_step:at=0:row=1')
    feed = {'x': np.ones((4, 3), np.float32),
            'lbl': np.zeros((4, 1), np.int64)}
    out = faults.poison_nan(feed, 0, 1)
    x = out['x']
    assert np.isnan(x[1]).all()                       # armed row poisoned
    assert np.isfinite(np.delete(x, 1, axis=0)).all()  # others untouched
    np.testing.assert_array_equal(out['lbl'], feed['lbl'])  # ints skipped
    assert np.isfinite(feed['x']).all()               # input not mutated


def test_poison_nan_row_in_stacked_launch():
    """count>1 launches stack steps on axis 0, so the batch is axis 1:
    only (armed step, armed row) goes NaN."""
    faults.configure('nan_step:at=2:row=1')
    feed = {'x': np.ones((4, 3, 2), np.float32)}      # [K=4 steps, B=3, 2]
    out = faults.poison_nan(feed, 0, 4)
    x = out['x']
    assert np.isnan(x[2, 1]).all()
    mask = np.ones(x.shape, bool)
    mask[2, 1] = False
    assert np.isfinite(x[mask]).all()


def test_poison_nan_without_row_poisons_whole_step():
    faults.configure('nan_step:at=1')
    feed = {'x': np.ones((3, 2, 2), np.float32)}      # [K=3 steps, B=2, 2]
    out = faults.poison_nan(feed, 0, 3)
    x = out['x']
    assert np.isnan(x[1]).all()                       # entire armed step
    assert np.isfinite(x[0]).all() and np.isfinite(x[2]).all()


def test_poison_nan_outside_window_is_identity():
    faults.configure('nan_step:at=7:row=0')
    feed = {'x': np.ones((2, 2), np.float32)}
    assert faults.poison_nan(feed, 0, 2) is feed      # window miss: no copy


# ------------------------------------------------------------ executor site

def test_nan_step_fault_trips_check_nan(tmp_path):
    """The nan_step site poisons one step's feeds; the executor's fused
    check_nan verdict must trip exactly at that step, with the steps
    before and after healthy."""
    before = obs.counters().get('faults.injected.nan_step') or 0
    faults.configure('nan_step:at=1')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            y = fluid.layers.fc(x, 3)
            loss = fluid.layers.reduce_mean(y)
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe, scope = fluid.Executor(check_nan=True), fluid.Scope()
    feed = {'x': np.ones((2, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])          # step 0: fine
        with pytest.raises(RuntimeError, match='check_nan'):
            exe.run(main, feed=feed, fetch_list=[loss])      # step 1: poisoned
    assert obs.counters().get('faults.injected.nan_step') == before + 1
