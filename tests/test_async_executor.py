"""Fully-async executor: FetchFuture fetches, deferred nan verdict,
chained launches (docs/async.md).

The contract under test: async mode (as_futures=True + nan_poll>1) is
BITWISE identical to the synchronous path — same losses, same param and
optimizer state, same RNG stream — while never forcing a host sync in
steady state; a deferred verdict trip localizes the divergence to the
last poll window and rolls back cleanly.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.core.async_runtime import FetchFuture
from paddle_tpu.testing import faults


def _train_model(seed=7, dropout=0.5, amp=False):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            if dropout:
                h = fluid.layers.dropout(h, dropout_prob=dropout)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.Adam(0.01).minimize(loss)
    if amp:
        main.set_amp(True)
    return main, startup, loss


def _feeds(n, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.randn(batch, 8).astype('float32'),
             'lbl': rng.randint(0, 4, (batch, 1)).astype('int64')}
            for _ in range(n)]


def _scope_bytes(scope):
    return {n: np.asarray(scope.vars[n]).tobytes() for n in scope.vars}


# ------------------------------------------------------ bitwise parity

def test_run_parity_async_vs_sync():
    """Single-step async (futures + deferred poll) vs sync: losses and
    final param/Adam state bitwise equal — same RNG stream, same math."""
    N = 6
    main, startup, loss = _train_model()
    feeds = _feeds(N)

    exe_s = fluid.Executor(check_nan=True, nan_poll=1)
    scope_s = fluid.Scope()
    with fluid.scope_guard(scope_s):
        exe_s.run(startup)
        sync_losses = [np.asarray(exe_s.run(main, feed=f,
                                            fetch_list=[loss])[0])
                       for f in feeds]

    exe_a = fluid.Executor(check_nan=True, nan_poll=4)
    scope_a = fluid.Scope()
    with fluid.scope_guard(scope_a):
        exe_a.run(startup)
        futs = [exe_a.run(main, feed=f, fetch_list=[loss],
                          as_futures=True)[0] for f in feeds]
        exe_a.poll_nan()   # drain: all verdicts were clean
        async_losses = [np.asarray(f) for f in futs]

    for a, b in zip(sync_losses, async_losses):
        assert a.tobytes() == b.tobytes()
    sb, ab = _scope_bytes(scope_s), _scope_bytes(scope_a)
    assert set(sb) == set(ab)
    for n in sb:
        assert sb[n] == ab[n], 'state mismatch in %s' % n


@pytest.mark.parametrize('nan_poll', [1, 4])
def test_run_steps_parity_async_vs_sync_amp(nan_poll):
    """Fused K-step launches under AMP + dropout: the async fetch mode
    must not perturb the RNG stream or the bf16 master-weight updates."""
    K, launches = 4, 2
    main, startup, loss = _train_model(amp=True)
    feeds = _feeds(K * launches)
    chunks = [feeds[i * K:(i + 1) * K] for i in range(launches)]

    exe_s = fluid.Executor(check_nan=True, nan_poll=1)
    scope_s = fluid.Scope()
    with fluid.scope_guard(scope_s):
        exe_s.run(startup)
        sync_losses = [np.asarray(exe_s.run_steps(
            main, feed_list=c, fetch_list=[loss], steps=K)[0])
            for c in chunks]

    exe_a = fluid.Executor(check_nan=True, nan_poll=nan_poll)
    scope_a = fluid.Scope()
    with fluid.scope_guard(scope_a):
        exe_a.run(startup)
        futs = [exe_a.run_steps(main, feed_list=c, fetch_list=[loss],
                                steps=K, as_futures=True)[0]
                for c in chunks]
        exe_a.poll_nan()
        async_losses = [np.asarray(f) for f in futs]

    for a, b in zip(sync_losses, async_losses):
        assert a.tobytes() == b.tobytes()
    sb, ab = _scope_bytes(scope_s), _scope_bytes(scope_a)
    for n in sb:
        assert sb[n] == ab[n], 'state mismatch in %s' % n


def test_parallel_executor_parity_async():
    """ParallelExecutor over the 8-device mesh: as_futures returns lazy
    handles whose values match the blocking path bitwise."""
    losses = {}
    for tag, as_futures in [('sync', False), ('async', True)]:
        main, startup, loss = _train_model(seed=3, dropout=0.0)
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            pe = fluid.ParallelExecutor(loss_name=loss.name,
                                        main_program=main, scope=scope)
            vals = []
            for f in _feeds(3, batch=16):
                out = pe.run([loss.name], feed=f, as_futures=as_futures)
                vals.append(np.asarray(out[0]))
        losses[tag] = vals
    for a, b in zip(losses['sync'], losses['async']):
        assert a.tobytes() == b.tobytes()
    # nan-verdict duck-type reaches the inner executor
    assert pe.nan_clean() is True
    pe.poll_nan()          # nothing pending: no-op, no raise
    pe.reset_nan_window()


# ------------------------------------------------- deferred nan verdict

def test_deferred_trip_localizes_window():
    """nan_poll=4: a NaN produced on the 2nd launch must NOT raise until
    the 4th (the poll), and the raise names the 4-step window."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            y = fluid.layers.fc(x, 3)
            loss = fluid.layers.reduce_mean(y)
    exe = fluid.Executor(check_nan=True, nan_poll=4)
    scope = fluid.Scope()
    clean = {'x': np.ones((2, 4), np.float32)}
    poison = {'x': np.full((2, 4), np.nan, np.float32)}
    c0 = obs.counters()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.poll_nan()   # drain the startup verdict: window starts at 0
        exe.run(main, feed=clean, fetch_list=[loss])    # 1: fine
        exe.run(main, feed=poison, fetch_list=[loss])   # 2: NaN, deferred
        assert not exe.nan_clean()
        exe.run(main, feed=clean, fetch_list=[loss])    # 3: still deferred
        with pytest.raises(RuntimeError, match='check_nan') as ei:
            exe.run(main, feed=clean, fetch_list=[loss])  # 4: poll trips
        assert ei.value.nan_window_steps == 4
        # window reset by the poll: the next runs are clean again
        assert exe.nan_clean()
        for _ in range(4):
            exe.run(main, feed=clean, fetch_list=[loss])
        assert exe.nan_clean()   # 8th run polled clean
    c1 = obs.counters()
    assert c1.get('nan_poll.trips', 0) - c0.get('nan_poll.trips', 0) == 1
    assert c1.get('nan_poll.polls', 0) - c0.get('nan_poll.polls', 0) >= 2


def test_nan_clean_and_poll_semantics():
    main, startup, loss = _train_model(dropout=0.0)
    exe = fluid.Executor(check_nan=True, nan_poll=3)
    scope = fluid.Scope()
    f = _feeds(1)[0]
    with fluid.scope_guard(scope):
        exe.run(startup)          # push 1
        assert not exe.nan_clean()
        exe.run(main, feed=f, fetch_list=[loss])   # push 2
        assert not exe.nan_clean()
        exe.poll_nan()            # clean forced poll
        assert exe.nan_clean()
        exe.run(main, feed=f, fetch_list=[loss])
        exe.reset_nan_window()    # rollback path: drop without reading
        assert exe.nan_clean()
    # check_nan off: always clean, poll is a no-op
    exe2 = fluid.Executor(check_nan=False, nan_poll=4)
    assert exe2.nan_clean()
    exe2.poll_nan()


def test_deferred_rollback_localizes_to_window(tmp_path):
    """The fault_soak async scenario in-process: nan_step mid-window,
    trip at the NEXT poll, rollback to the last clean-verdict checkpoint,
    run completes with every landed loss finite."""
    from paddle_tpu.train import (CheckpointConfig, Checkpointer,
                                  RecoveryPolicy)
    main, startup, loss = _train_model(seed=17)
    exe = fluid.Executor(check_nan=True, nan_poll=4)
    scope = fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    policy = RecoveryPolicy(ck, max_retries=4)
    feeds = _feeds(16, seed=5)
    K = 2
    c0 = obs.counters()
    losses, pending, skipped = [], [], 0
    try:
        faults.configure('nan_step:at=5')
        with fluid.scope_guard(scope):
            exe.run(startup)
            ck.save(0, -1)
            ck.wait()
            for i in range(0, 16, K):
                out = policy.run(lambda: exe.run_steps(
                    main, feed_list=feeds[i:i + K], steps=K,
                    fetch_list=[loss], as_futures=True))
                if out is None:
                    skipped += K + sum(n for _, n in pending)
                    pending = []
                    continue
                pending.append((out[0], K))
                if exe.nan_clean():
                    for fut, _ in pending:
                        losses.extend(np.asarray(fut).ravel())
                    pending = []
                    ck.maybe_save(0, i + K - 1)
            exe.poll_nan()
            for fut, _ in pending:
                losses.extend(np.asarray(fut).ravel())
            ck.wait()
    finally:
        faults.reset()
    c1 = obs.counters()

    def delta(k):
        return (c1.get(k) or 0) - (c0.get(k) or 0)

    assert delta('recovery.rollbacks') == 1
    assert delta('recovery.deferred_trips') == 1
    assert delta('nan_poll.trips') == 1
    assert delta('faults.injected.nan_step') == 1
    # poisoned launch + the launch that tripped the poll were condemned
    assert skipped == 4
    assert len(losses) == 12
    assert np.all(np.isfinite(losses))


# -------------------------------------------------- zero-sync steady state

def test_chained_launches_never_block_host():
    """Back-to-back as_futures launches: zero host-blocked seconds, zero
    pipeline stalls, until the caller actually reads a future."""
    K = 3
    main, startup, loss = _train_model(dropout=0.0)
    exe = fluid.Executor(check_nan=False)
    scope = fluid.Scope()
    feeds = _feeds(K * 3)
    with fluid.scope_guard(scope):
        exe.run(startup)
        # warmup: compile the K-step executable outside the window
        exe.run_steps(main, feed_list=feeds[:K], steps=K,
                      fetch_list=[loss], as_futures=True)
        c0 = obs.counters()
        f1, = exe.run_steps(main, feed_list=feeds[K:2 * K], steps=K,
                            fetch_list=[loss], as_futures=True)
        f2, = exe.run_steps(main, feed_list=feeds[2 * K:], steps=K,
                            fetch_list=[loss], as_futures=True)
        c1 = obs.counters()
        # the launches chained on the donated device carry: the host
        # never waited on the device between them
        assert (c1.get('executor.host_blocked_s') or 0) == \
            (c0.get('executor.host_blocked_s') or 0)
        assert (c1.get('executor.stall_count') or 0) == \
            (c0.get('executor.stall_count') or 0)
        # first host read: blocks, and the block is metered
        v1, v2 = np.asarray(f1), np.asarray(f2)
        c2 = obs.counters()
        assert (c2.get('executor.host_blocked_s') or 0) > \
            (c1.get('executor.host_blocked_s') or 0)
    assert v1.shape[0] == K and np.all(np.isfinite(v2))


def test_fetch_future_api():
    import jax.numpy as jnp
    c0 = obs.counters().get('executor.host_blocked_s') or 0
    fut = FetchFuture(jnp.arange(6.0).reshape(2, 3))
    assert fut.shape == (2, 3) and len(fut) == 2
    assert 'pending' in repr(fut)
    row = fut[0]                      # lazy device-side slice
    assert isinstance(row, FetchFuture) and row.shape == (3,)
    a = fut.numpy()
    assert fut.numpy() is a           # cached: one sync total
    assert 'synced' in repr(fut)
    np.testing.assert_array_equal(np.asarray(fut), a)
    assert float(row[0]) == 0.0
    assert fut.block() is fut and fut.ready()
    assert fut.device() is not None
    c1 = obs.counters().get('executor.host_blocked_s') or 0
    assert c1 > c0                    # the reads were metered


# ------------------------------------------------------------ prefetcher

def test_prefetcher_upload_wait_not_starvation():
    """A consumer waiting on a pack/upload IN FLIGHT is transfer latency
    (prefetch.upload_wait_s), not reader starvation."""
    from paddle_tpu.data_feeder import FeedPrefetcher
    import time as _time

    class SlowPack(FeedPrefetcher):
        # simulate a 0.15s device upload: widen the pack span over the
        # sleep so the consumer's wait overlaps an upload in flight
        def _pack(self, buf):
            t0 = _time.perf_counter()
            _time.sleep(0.15)
            payload, span = FeedPrefetcher._pack(self, buf)
            return payload, ((t0, span[1]) if span else None)

    feeds = [{'x': np.full((2, 2), i, np.float32)} for i in range(4)]
    c0 = obs.counters()
    pf = SlowPack(iter(feeds), steps=2, to_device=False)
    got = [k for _, k in pf]
    pf.close()
    c1 = obs.counters()
    assert got == [2, 2]
    assert (c1.get('prefetch.upload_waits') or 0) >= \
        (c0.get('prefetch.upload_waits') or 0) + 1
    assert (c1.get('prefetch.upload_wait_s') or 0) - \
        (c0.get('prefetch.upload_wait_s') or 0) > 0.1
    # the wait was attributed to the in-flight upload, not the reader
    assert (c1.get('prefetch.starvation_s') or 0) - \
        (c0.get('prefetch.starvation_s') or 0) < 0.05
    assert obs.counters().get('prefetch.upload_overlap_ratio') is not None
