"""contrib.slim pruners + post-training int8 Calibrator.

Model: reference contrib/slim/unitest/ + contrib/tests (KL calibration of
conv/fc nets; pruning masks by magnitude/ratio).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib import Calibrator
from paddle_tpu.contrib.calibration import kl_scale
from paddle_tpu.contrib.slim import (MagnitudePruner, RatioPruner,
                                     QuantizationTransformPass,
                                     QuantizationFreezePass)


def _train_regressor(seed=0, steps=60):
    rng = np.random.RandomState(seed)
    w_true = rng.rand(8, 1).astype('float32')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = layers.data('x', shape=[8], dtype='float32')
            y = layers.data('y', shape=[1], dtype='float32')
            h = layers.fc(x, 16, act='relu')
            pred = layers.fc(h, 1)
            loss = layers.reduce_mean(layers.square(pred - y))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            xb = rng.rand(32, 8).astype('float32')
            exe.run(main, feed={'x': xb, 'y': xb @ w_true},
                    fetch_list=[loss])
    return main, scope, exe, pred, w_true, rng


# ---------------------------------------------------------------- prune

def test_magnitude_pruner_masks_small_weights():
    main, scope, exe, pred, w_true, rng = _train_regressor()
    with fluid.scope_guard(scope):
        wname = [n for n in scope.vars if n.endswith('.w_0')][0]
        w = np.asarray(scope.vars[wname])
        th = float(np.median(np.abs(w)))
        sparsity = MagnitudePruner(th).apply(main, scope, params=[wname])
        assert wname in sparsity and 0.3 < sparsity[wname] < 0.7
        w2 = np.asarray(scope.vars[wname])
        assert ((np.abs(w) < th) == (w2 == 0)).all()


def test_ratio_pruner_keeps_top_fraction():
    main, scope, exe, pred, w_true, rng = _train_regressor(seed=1)
    with fluid.scope_guard(scope):
        wname = [n for n in scope.vars if n.endswith('.w_0')][0]
        RatioPruner({'*': 0.25}).apply(main, scope, params=[wname])
        w2 = np.asarray(scope.vars[wname])
        kept = (w2 != 0).mean()
        assert 0.2 <= kept <= 0.3, kept


def test_ratio_pruner_graph_mask():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = layers.create_parameter([4, 4], 'float32', name='pw')
        mask = RatioPruner({'*': 0.5}).prune(p)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        rng = np.random.RandomState(2)
        w = rng.randn(4, 4).astype('float32')
        scope.vars['pw'] = scope.vars['pw'] * 0 + w
        mv, = exe.run(main, fetch_list=[mask])
    mv = np.asarray(mv)
    # mask marks the weights to ZERO: the bottom half by magnitude
    assert mv.sum() == 8
    th = np.sort(np.abs(w).ravel())[::-1][7]
    assert (mv.astype(bool) == (np.abs(w) < th)).all()


# ---------------------------------------------------------- calibration

def test_kl_scale_clips_outliers():
    rng = np.random.RandomState(3)
    body = rng.randn(100000).astype('float32')
    outliers = np.array([40.0, -45.0, 60.0], 'float32')
    s = kl_scale([np.concatenate([body, outliers])])
    assert s < 30.0, s                      # clips the heavy tail
    assert s > np.percentile(np.abs(body), 95), s


def test_calibrator_int8_close_to_fp32():
    main, scope, exe, pred, w_true, rng = _train_regressor(seed=4)
    infer = main.clone(for_test=True)
    with fluid.scope_guard(scope):
        calib = Calibrator(infer, scope=scope, algo='KL')
        assert calib._targets, 'no activations found to calibrate'
        for _ in range(8):
            xb = rng.rand(32, 8).astype('float32')
            calib.sample(exe, feed={'x': xb, 'y': xb @ w_true})
        int8_prog = calib.freeze()
        types = [op.type for op in int8_prog.global_block().ops]
        assert 'quantize_dequantize_fixed_scale' in types
        xt = rng.rand(16, 8).astype('float32')
        fp32_pred, = exe.run(infer, feed={'x': xt, 'y': xt @ w_true},
                             fetch_list=[pred])
        int8_pred, = exe.run(int8_prog, feed={'x': xt, 'y': xt @ w_true},
                             fetch_list=[pred])
        packed = calib.save_int8_weights()
    fp32_pred = np.asarray(fp32_pred)
    int8_pred = np.asarray(int8_pred)
    # stated accuracy contract: int8 within 2% relative of fp32 range
    span = fp32_pred.max() - fp32_pred.min() + 1e-6
    rel = np.abs(fp32_pred - int8_pred).max() / span
    assert rel < 0.02, rel
    assert all(q.dtype == np.int8 for q, _ in packed.values())


def test_slim_quantization_passes_roundtrip():
    rng = np.random.RandomState(5)
    w_true = rng.rand(8, 1).astype('float32')
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = layers.data('x', shape=[8], dtype='float32')
            y = layers.data('y', shape=[1], dtype='float32')
            pred = layers.fc(layers.fc(x, 16, act='relu'), 1)
            loss = layers.reduce_mean(layers.square(pred - y))
            QuantizationTransformPass(scope=scope).apply(main, startup)
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert sum(t.startswith('fake_quantize_dequantize')
               for t in types) == 4
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(40):
            xb = rng.rand(32, 8).astype('float32')
            exe.run(main, feed={'x': xb, 'y': xb @ w_true},
                    fetch_list=[loss])
        infer = main.clone(for_test=True)
        QuantizationFreezePass(scope=scope).apply(infer)
        xt = rng.rand(8, 8).astype('float32')
        a, = exe.run(main.clone(for_test=True),
                     feed={'x': xt, 'y': xt @ w_true}, fetch_list=[pred])
        b, = exe.run(infer, feed={'x': xt, 'y': xt @ w_true},
                     fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


# -------------------------------------------------------- contrib.reader

def test_ctr_reader_csv_and_svm(tmp_path):
    from paddle_tpu.contrib.reader.ctr_reader import ctr_reader
    csv = tmp_path / 'a.csv'
    csv.write_text('1 0.5,1.5 7,8\n0 2.0,3.0 9,10\n1 4.0,5.0 11,12\n')
    r = ctr_reader(feed_dict=['label', 'dense', 'sparse'],
                   file_type='plain', file_format='csv',
                   dense_slot_index=[1], sparse_slot_index=[2],
                   capacity=4, thread_num=1, batch_size=2,
                   file_list=[str(csv)], slots=[])
    r.start()
    batches = list(r())
    assert len(batches) == 2
    b0 = batches[0]
    np.testing.assert_array_equal(b0['label'], [[1], [0]])
    np.testing.assert_allclose(b0['dense'], [[0.5, 1.5], [2.0, 3.0]])
    np.testing.assert_array_equal(b0['sparse'], [[7, 8], [9, 10]])
    r.reset()

    svm = tmp_path / 'b.svm'
    svm.write_text('1 3:100 4:200\n0 3:300\n')
    r2 = ctr_reader(feed_dict=['label', 'ids'], file_type='plain',
                    file_format='svm', dense_slot_index=[],
                    sparse_slot_index=[], capacity=2, thread_num=1,
                    batch_size=2, file_list=[str(svm)], slots=[3, 4])
    r2.start()
    (b,) = list(r2())
    np.testing.assert_array_equal(b['label'], [[1], [0]])


def test_ctr_reader_requires_start_and_validates_columns():
    from paddle_tpu.contrib.reader.ctr_reader import ctr_reader
    r = ctr_reader(feed_dict=['a', 'b', 'c', 'd'], file_type='plain',
                   file_format='csv', dense_slot_index=[1],
                   sparse_slot_index=[2], capacity=1, thread_num=1,
                   batch_size=1, file_list=['/nonexistent'], slots=[])
    with pytest.raises(ValueError, match='start'):
        r()


def test_apply_int8_runs_true_int8_kernels():
    main, scope, exe, pred, w_true, rng = _train_regressor(seed=8)
    infer = main.clone(for_test=True)
    with fluid.scope_guard(scope):
        calib = Calibrator(infer, scope=scope, algo='abs_max')
        for _ in range(8):
            xb = rng.rand(32, 8).astype('float32')
            calib.sample(exe, feed={'x': xb, 'y': xb @ w_true})
        int8_prog = calib.apply_int8()
        types = [op.type for op in int8_prog.global_block().ops]
        assert 'mul_int8' in types and 'mul' not in types
        xt = rng.rand(16, 8).astype('float32')
        a, = exe.run(infer, feed={'x': xt, 'y': xt @ w_true},
                     fetch_list=[pred])
        b, = exe.run(int8_prog, feed={'x': xt, 'y': xt @ w_true},
                     fetch_list=[pred])
    a, b = np.asarray(a), np.asarray(b)
    span = a.max() - a.min() + 1e-6
    rel = np.abs(a - b).max() / span
    # true-int8 (both operands quantized) stays within 4% of fp32 range
    assert rel < 0.04, rel


def test_apply_int8_twice_shares_scope_weights():
    main, scope, exe, pred, w_true, rng = _train_regressor(seed=9)
    infer = main.clone(for_test=True)
    with fluid.scope_guard(scope):
        calib = Calibrator(infer, scope=scope, algo='abs_max')
        for _ in range(4):
            xb = rng.rand(32, 8).astype('float32')
            calib.sample(exe, feed={'x': xb, 'y': xb @ w_true})
        p1 = calib.apply_int8()
        p2 = calib.apply_int8()          # fresh clone, shared scope
        xt = rng.rand(8, 8).astype('float32')
        a, = exe.run(p1, feed={'x': xt, 'y': xt @ w_true},
                     fetch_list=[pred])
        b, = exe.run(p2, feed={'x': xt, 'y': xt @ w_true},
                     fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
