"""Native C++ datafeed: ptrec round-trip, shuffle, batching, prefetch.

Model: reference recordio tests + data_feed semantics.
"""
import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.native import fallback
from paddle_tpu.native.datafeed import (BatchReader, RecordReader,
                                        DataFeedDesc, write_records)


def _make_samples(n):
    rs = np.random.RandomState(0)
    return [(rs.randn(3, 4).astype('float32'),
             np.array([i], dtype='int64')) for i in range(n)]


def test_native_lib_builds():
    assert native.native_available(), 'C++ datafeed failed to build'


def test_roundtrip_batches(tmp_path):
    path = str(tmp_path / 'data.ptrec')
    samples = _make_samples(10)
    write_records(path, samples)
    got = list(BatchReader(path, batch_size=2))
    assert len(got) == 5
    assert got[0][0].shape == (2, 3, 4)
    assert got[0][1].shape == (2, 1)
    np.testing.assert_allclose(got[0][0][0], samples[0][0])
    labels = np.concatenate([b[1][:, 0] for b in got])
    assert labels.tolist() == list(range(10))


def test_record_reader_sample_at_a_time(tmp_path):
    path = str(tmp_path / 'data.ptrec')
    samples = _make_samples(4)
    write_records(path, samples)
    got = list(RecordReader(path))
    assert len(got) == 4
    np.testing.assert_allclose(got[2][0], samples[2][0])
    assert got[2][1][0] == 2


def test_shuffle_changes_order_but_not_content(tmp_path):
    path = str(tmp_path / 'data.ptrec')
    write_records(path, _make_samples(64))
    plain = [int(b[1][0, 0]) for b in BatchReader(path, batch_size=1)]
    shuf = [int(b[1][0, 0]) for b in
            BatchReader(path, batch_size=1, shuffle_capacity=32, seed=7)]
    assert sorted(shuf) == plain
    assert shuf != plain


def test_drop_last_and_multifile(tmp_path):
    p1 = str(tmp_path / 'a.ptrec')
    p2 = str(tmp_path / 'b.ptrec')
    write_records(p1, _make_samples(3))
    write_records(p2, _make_samples(4))
    full = list(BatchReader([p1, p2], batch_size=2))
    assert sum(b[0].shape[0] for b in full) == 7
    dropped = list(BatchReader([p1, p2], batch_size=2, drop_last=True))
    assert all(b[0].shape[0] == 2 for b in dropped)
    assert sum(b[0].shape[0] for b in dropped) == 6


def test_fallback_same_format(tmp_path):
    """NumPy fallback reads files written by the C++ writer and vice versa."""
    path = str(tmp_path / 'x.ptrec')
    samples = _make_samples(5)
    write_records(path, samples)  # native (or fallback) writer
    got = list(fallback.read_samples(path))
    assert len(got) == 5
    np.testing.assert_allclose(got[3][0], samples[3][0])
    # and fallback batching agrees with native batching
    nb = list(BatchReader(path, batch_size=2))
    fb = list(fallback.iter_batches([path], 2, 0, 0, False, False))
    assert len(nb) == len(fb)
    for a, b in zip(nb, fb):
        np.testing.assert_allclose(a[0], b[0])


def test_corrupt_file_raises(tmp_path):
    path = str(tmp_path / 'bad.ptrec')
    write_records(path, _make_samples(2))
    with open(path, 'r+b') as f:
        f.seek(20)
        f.write(b'\xff\xff\xff')
    with pytest.raises(IOError):
        list(BatchReader(path, batch_size=1))


def test_datafeed_desc(tmp_path):
    path = str(tmp_path / 'd.ptrec')
    write_records(path, _make_samples(6))
    desc = DataFeedDesc([path], batch_size=3, shuffle_capacity=4, seed=1)
    desc.add_slot('img', 'float32', [3, 4]).add_slot('label', 'int64', [1])
    assert 'img' in desc.desc()
    batches = list(desc.reader())
    assert len(batches) == 2
    assert batches[0][0].shape == (3, 3, 4)


def test_open_files_readers_do_not_alias(tmp_path):
    """Regression: two open_files calls must create distinct graph vars."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    p1 = str(tmp_path / 'tr.ptrec')
    p2 = str(tmp_path / 'te.ptrec')
    write_records(p1, _make_samples(2))
    write_records(p2, _make_samples(2))
    r1 = layers.io.open_files(p1, shapes=[[-1, 3, 4], [-1, 1]],
                              lod_levels=None,
                              dtypes=['float32', 'int64'], batch_size=2)
    r2 = layers.io.open_files(p2, shapes=[[-1, 3, 4], [-1, 1]],
                              lod_levels=None,
                              dtypes=['float32', 'int64'], batch_size=2)
    v1 = layers.io.read_file(r1)
    v2 = layers.io.read_file(r2)
    assert v1[0] is not v2[0]
    assert v1[0].name != v2[0].name


def test_py_reader_training_pipeline():
    """py_reader end-to-end: decorate a paddle reader, start, drive a
    train loop via next_feed until StopIteration, reset and run a second
    epoch (parity: reference py_reader usage pattern)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            reader = layers.io.py_reader(
                capacity=8, shapes=[[-1, 4], [-1, 1]],
                dtypes=['float32', 'int64'], name='pyr')
            x, lbl = layers.io.read_file(reader)
            p = layers.fc(x, 2, act='softmax')
            loss = layers.reduce_mean(layers.cross_entropy(p, lbl))
            fluid.optimizer.SGD(0.1).minimize(loss)

    rng = np.random.RandomState(0)

    def batches():
        for _ in range(5):
            xv = rng.rand(6, 4).astype('float32')
            yv = (xv.sum(1, keepdims=True) > 2).astype('int64')
            yield xv, yv

    reader.decorate_paddle_reader(batches)
    exe = fluid.Executor()
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(2):
            reader.start()
            steps = 0
            while True:
                try:
                    feed = reader.next_feed()
                except StopIteration:
                    break
                lv, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(lv).ravel()[0]))
                steps += 1
            assert steps == 5
            reader.reset()
    assert len(losses) == 10
    assert losses[-1] < losses[0]  # it actually trains
