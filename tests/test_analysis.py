"""Static analyzer (paddle_tpu.analysis): per-pass positive/negative
coverage, the PT_LINT executor hook, and the pt_lint CLI on a saved
model (docs/analysis.md documents codes D001..D014)."""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import LintError, LintWarning, lint_program

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', 'tools'))
import pt_lint  # noqa: E402


def _codes(result):
    return set(result.codes())


def _build_clean():
    """fit_a_line-style clean training program."""
    import paddle_tpu.models.simple as simple
    prog, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, start):
        m = simple.fit_a_line()
    return prog, start, m


# ------------------------------------------------------- def-use (D001)

def test_defuse_clean():
    prog, _, m = _build_clean()
    res = prog.lint(feed_names=['x', 'y'], fetch_list=[m['loss']])
    assert 'D001' not in _codes(res)


def test_defuse_did_you_mean_and_valueerror():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('input_ids', shape=[4], dtype='float32')
        blk = prog.global_block()
        out = blk.create_var(name='out', shape=[-1, 4], dtype='float32')
        # typo'd read: input_idz instead of input_ids
        blk.append_op('scale', inputs={'X': 'input_idz'},
                      outputs={'Out': out}, attrs={'scale': 1.0},
                      infer_shape=False)
    res = prog.lint(feed_names=['input_ids'], fetch_list=['out'])
    d001 = [d for d in res.errors if d.code == 'D001']
    assert len(d001) == 1
    assert 'input_idz' in d001[0].message
    assert 'input_ids' in (d001[0].fixit or '')       # did-you-mean
    assert d001[0].block_path == 'block 0'
    # the historical first-error ValueError contract still holds
    from paddle_tpu.core.validation import validate_def_use
    with pytest.raises(ValueError, match='input_idz'):
        validate_def_use(prog, feed_names=('input_ids',))
    assert x is not None


# ------------------------------------- shape/dtype interpreter (D002-4)

def test_shape_mismatch_reported_at_op():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        blk = prog.global_block()
        w = blk.create_parameter(name='W', shape=[3, 5], dtype='float32')
        bad = blk.create_var(name='bad', shape=[-1, 5], dtype='float32')
        blk.append_op('mul', inputs={'X': x, 'Y': w},
                      outputs={'Out': bad}, attrs={}, infer_shape=False)
    res = prog.lint(feed_names=['x'], fetch_list=['bad'])
    d003 = [d for d in res.errors if d.code == 'D003']
    assert d003, res.render()
    assert d003[0].op_type == 'mul'
    assert 'x' in d003[0].message and 'W' in d003[0].message


def test_declared_shape_conflict():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        blk = prog.global_block()
        # declares [-1, 9] but scale preserves [-1, 4]
        out = blk.create_var(name='out', shape=[-1, 9], dtype='float32')
        blk.append_op('scale', inputs={'X': x}, outputs={'Out': out},
                      attrs={'scale': 2.0}, infer_shape=False)
    res = prog.lint(feed_names=['x'], fetch_list=['out'])
    d003 = [d for d in res.errors if d.code == 'D003']
    assert d003 and d003[0].var == 'out'


def test_unknown_op_d002_with_suggestion():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        blk = prog.global_block()
        out = blk.create_var(name='out', shape=[-1, 4], dtype='float32')
        blk.append_op('sofmax', inputs={'X': x}, outputs={'Out': out},
                      attrs={}, infer_shape=False)
    res = prog.lint(feed_names=['x'], fetch_list=['out'])
    d002 = [d for d in res if d.code == 'D002']
    assert d002 and d002[0].severity == 'warning'
    assert 'softmax' in (d002[0].fixit or '')


def test_models_fully_covered_no_unknown_ops():
    """Acceptance: the shape/dtype pass covers every op type used by the
    bundled model programs — no D002, no shape errors."""
    for name in ('mnist', 'stacked_lstm', 'word2vec'):
        build = pt_lint._zoo_entry(name)
        prog, feeds, fetches = build()
        res = prog.lint(feed_names=feeds, fetch_list=fetches)
        assert 'D002' not in _codes(res), (name, res.render())
        assert not res.errors, (name, res.render())


def test_int64_narrowing_d004():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        blk = prog.global_block()
        out = blk.create_var(name='c', shape=[1], dtype='int64')
        blk.append_op('fill_constant', inputs={}, outputs={'Out': out},
                      attrs={'shape': [1], 'value': 7, 'dtype': 'int64'},
                      infer_shape=False)
    res = prog.lint(fetch_list=['c'])
    d004 = [d for d in res.infos if d.code == 'D004']
    assert d004 and 'int64' in d004[0].message


# --------------------------------------------- liveness (D005 / D006)

def test_dead_op_and_unused_var():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        kept = layers.scale(x, scale=2.0)
        layers.scale(x, scale=3.0)  # dead: never fetched, never read
    res = prog.lint(feed_names=['x'], fetch_list=[kept])
    assert 'D005' in _codes(res)
    assert 'D006' in _codes(res)  # the dead op's output is unused too
    dead = [d for d in res.warnings if d.code == 'D005']
    assert dead[0].op_type == 'scale'


def test_no_dead_ops_in_clean_program():
    prog, _, m = _build_clean()
    res = prog.lint(feed_names=['x', 'y'], fetch_list=[m['loss']])
    assert 'D005' not in _codes(res), res.render()


# ------------------------------------- donation/aliasing (D007-D009)

def _param_writeback_program(read_after=True):
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        blk = prog.global_block()
        w = blk.create_parameter(name='W', shape=[4], dtype='float32')
        post = blk.create_var(name='post', shape=[-1, 4], dtype='float32')
        if not read_after:
            blk.append_op('elementwise_add', inputs={'X': x, 'Y': w},
                          outputs={'Out': post}, attrs={'axis': -1},
                          infer_shape=False)
        blk.append_op('assign', inputs={'X': x}, outputs={'Out': w},
                      attrs={}, infer_shape=False)
        if read_after:
            blk.append_op('elementwise_add', inputs={'X': x, 'Y': w},
                          outputs={'Out': post}, attrs={'axis': -1},
                          infer_shape=False)
    return prog


def test_param_read_after_writeback_d007():
    res = _param_writeback_program(True).lint(feed_names=['x'],
                                              fetch_list=['post'])
    d007 = [d for d in res.warnings if d.code == 'D007']
    assert d007 and d007[0].var == 'W'
    # reading before the writeback is the fine/normal ordering
    res2 = _param_writeback_program(False).lint(feed_names=['x'],
                                                fetch_list=['post'])
    assert 'D007' not in _codes(res2)


def test_feed_shadows_param_d008():
    prog = _param_writeback_program(False)
    res = prog.lint(feed_names=['x', 'W'], fetch_list=['post'])
    d008 = [d for d in res.warnings if d.code == 'D008']
    assert d008 and d008[0].var == 'W'


def test_double_write_d009():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        blk = prog.global_block()
        s = blk.create_var(name='state', shape=[-1, 4], dtype='float32',
                           persistable=True)
        blk.append_op('assign', inputs={'X': x}, outputs={'Out': s},
                      attrs={}, infer_shape=False)
        blk.append_op('scale', inputs={'X': x}, outputs={'Out': s},
                      attrs={'scale': 2.0}, infer_shape=False)
    res = prog.lint(feed_names=['x'], fetch_list=['state'])
    d009 = [d for d in res.warnings if d.code == 'D009']
    assert d009 and d009[0].var == 'state'


# ------------------------------------------- retrace hazards (D010/11)

def test_unbucketed_seq_dim_d010_and_bucketer_coverage():
    def build():
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            ids = layers.data('ids', shape=[-1], dtype='int64')  # [B, T]
            emb = layers.embedding(ids, size=[100, 8])
            loss = layers.mean(layers.reduce_sum(emb, dim=-1))
        return prog, loss
    prog, loss = build()
    res = prog.lint(feed_names=['ids'], fetch_list=[loss])
    seq = [d for d in res.warnings
           if d.code == 'D010' and d.var == 'ids']
    assert seq, res.render()
    # a bucketer declaring ids as a sequence feed covers the hazard
    b = fluid.FeedBucketer(mask_name='m', seq_names=('ids',))
    res2 = prog.lint(feed_names=['ids'], fetch_list=[loss], bucketer=b)
    assert not [d for d in res2.warnings
                if d.code == 'D010' and d.var == 'ids'], res2.render()


def test_array_attr_d011():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        blk = prog.global_block()
        out = blk.create_var(name='v', shape=[4], dtype='float32')
        blk.append_op('assign_value', inputs={}, outputs={'Out': out},
                      attrs={'values': np.zeros(4, np.float32),
                             'shape': [4]},
                      infer_shape=False)
    res = prog.lint(fetch_list=['v'])
    assert [d for d in res.warnings if d.code == 'D011']


# ------------------------------------------ numeric hazards (D012-14)

def test_unclipped_log_d012_and_clipped_clean():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        raw = layers.log(x)
        clipped = layers.log(layers.clip(x, min=1e-6, max=1e6))
        loss = layers.mean(raw + clipped)
    res = prog.lint(feed_names=['x'], fetch_list=[loss])
    d012 = [d for d in res.warnings if d.code == 'D012'
            and d.op_type == 'log']
    assert len(d012) == 1, res.render()   # only the unclipped one


def test_manual_softmax_d013_and_stabilized_clean():
    def build(stabilized):
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            x = layers.data('x', shape=[8], dtype='float32')
            h = x
            if stabilized:
                h = layers.elementwise_sub(
                    x, layers.reduce_max(x, dim=1, keep_dim=True))
            e = layers.exp(h)
            s = layers.reduce_sum(e, dim=1, keep_dim=True)
            sm = layers.elementwise_div(e, s)
            loss = layers.mean(sm)
        return prog, loss
    prog, loss = build(False)
    res = prog.lint(feed_names=['x'], fetch_list=[loss])
    assert [d for d in res.warnings if d.code == 'D013'], res.render()
    prog2, loss2 = build(True)
    res2 = prog2.lint(feed_names=['x'], fetch_list=[loss2])
    assert 'D013' not in _codes(res2), res2.render()


def test_degenerate_lr_decay_d014():
    from paddle_tpu.layers import learning_rate_scheduler as lrs
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        lr = lrs.exponential_decay(0.1, decay_steps=100, decay_rate=1.5)
    res = prog.lint(fetch_list=[lr])
    d014 = [d for d in res.warnings if d.code == 'D014']
    assert d014 and '1.5' in d014[0].message
    # a sane schedule is clean
    prog2 = fluid.Program()
    with fluid.program_guard(prog2, fluid.Program()):
        lr2 = lrs.exponential_decay(0.1, decay_steps=100, decay_rate=0.9)
    assert 'D014' not in _codes(prog2.lint(fetch_list=[lr2]))


# --------------------------------------------- executor PT_LINT hook

def _broken_shape_program():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        blk = prog.global_block()
        w = blk.create_parameter(name='W', shape=[3, 5], dtype='float32')
        bad = blk.create_var(name='bad', shape=[-1, 5], dtype='float32')
        blk.append_op('mul', inputs={'X': x, 'Y': w},
                      outputs={'Out': bad}, attrs={}, infer_shape=False)
    return prog


def test_executor_strict_raises_build_time(monkeypatch):
    monkeypatch.setenv('PT_LINT', 'strict')
    prog = _broken_shape_program()
    exe = fluid.Executor()
    fluid.global_scope().set('W', np.zeros((3, 5), np.float32))
    with pytest.raises(LintError) as ei:
        exe.run(prog, feed={'x': np.zeros((2, 4), np.float32)},
                fetch_list=['bad'])
    assert 'mul' in str(ei.value)        # names the offending op
    assert 'D003' in str(ei.value)


def test_executor_lint_off_reproduces_raw_failure(monkeypatch):
    monkeypatch.setenv('PT_LINT', '0')
    prog = _broken_shape_program()
    exe = fluid.Executor()
    fluid.global_scope().set('W', np.zeros((3, 5), np.float32))
    with pytest.raises(Exception) as ei:
        exe.run(prog, feed={'x': np.zeros((2, 4), np.float32)},
                fetch_list=['bad'])
    assert not isinstance(ei.value, LintError)   # the raw mid-trace error


def test_executor_warn_mode(monkeypatch):
    monkeypatch.setenv('PT_LINT', 'warn')
    prog = _broken_shape_program()
    exe = fluid.Executor()
    fluid.global_scope().set('W', np.zeros((3, 5), np.float32))
    with pytest.warns(LintWarning, match='D003'):
        with pytest.raises(Exception):
            # lint only warns; the trace then fails raw
            exe.run(prog, feed={'x': np.zeros((2, 4), np.float32)},
                    fetch_list=['bad'])


def test_executor_strict_clean_program_still_runs():
    # default mode is strict; a healthy program lowers and runs
    prog, start, m = _build_clean()
    exe = fluid.Executor()
    exe.run(start)
    out = exe.run(prog,
                  feed={'x': np.random.rand(4, 13).astype('float32'),
                        'y': np.random.rand(4, 1).astype('float32')},
                  fetch_list=[m['loss']])
    assert np.isfinite(out[0]).all()
    assert hasattr(prog, '_last_lint')
    assert not prog._last_lint.errors


# ------------------------------------------------- CLI + saved models

def test_cli_saved_model_roundtrip(tmp_path, capsys):
    prog, start, m = _build_clean()
    exe = fluid.Executor()
    exe.run(start)
    with fluid.program_guard(prog, start):
        fluid.save_inference_model(str(tmp_path), ['x'], [m['predict']],
                                   exe, main_program=prog)
    rc = pt_lint.main([str(tmp_path), '--json'])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    (label, res), = out['results'].items()
    assert res['errors'] == 0


def test_cli_fails_on_broken_saved_model(tmp_path, capsys):
    import paddle_tpu.io as fluid_io
    prog = _broken_shape_program()
    desc = fluid_io.program_to_desc(prog)
    desc['feed_names'] = ['x']
    desc['fetch_names'] = ['bad']
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(os.path.join(str(tmp_path), '__model__.json'), 'w') as f:
        json.dump(desc, f)
    rc = pt_lint.main([str(tmp_path)])
    assert rc == 2
    assert 'D003' in capsys.readouterr().out


def test_cli_builtin_gate_passes(capsys):
    rc = pt_lint.main(['--builtin', 'fit_a_line', '--fail-on', 'error'])
    assert rc == 0


# ------------------------------------------------- rendering surfaces

def test_source_loc_round_trips_through_desc():
    import paddle_tpu.io as fluid_io
    prog, _, m = _build_clean()
    ops = prog.global_block().ops
    assert any(op.source_loc for op in ops)
    prog2 = fluid_io.desc_to_program(fluid_io.program_to_desc(prog))
    ops2 = prog2.global_block().ops
    assert any(getattr(op, 'source_loc', None) for op in ops2)


def test_draw_graph_highlights_lint_findings(tmp_path):
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        kept = layers.scale(x, scale=2.0)
        layers.scale(x, scale=3.0)  # dead
    from paddle_tpu.net_drawer import draw_graph
    dot = draw_graph(None, prog, path=str(tmp_path / 'g.dot'),
                     lint=True, feed_names=['x'], fetch_list=[kept])
    assert 'orange' in dot and 'D005' in dot
    assert (tmp_path / 'g.dot').exists()


def test_lint_program_never_raises_on_pass_crash(monkeypatch):
    from paddle_tpu.analysis import engine
    # simulate an analyzer bug: a registered pass that explodes
    engine._ensure_passes_loaded()
    monkeypatch.setattr(engine, '_PASSES',
                        engine._PASSES +
                        [('boom', lambda ctx: 1 / 0)])
    prog, _, m = _build_clean()
    res = lint_program(prog, feed_names=('x', 'y'),
                       fetch_names=(m['loss'].name,))
    d099 = [d for d in res.infos if d.code == 'D099']
    assert d099 and 'boom' in d099[0].message
