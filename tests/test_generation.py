"""Streaming generative decode (paddle_tpu/serving/generation/): slotted
KV cache, chunked/ring prefill parity against a dense reference, bitwise
fused-vs-sequential decode parity (fresh AND restored from the AOT disk
cache), position-keyed sampling determinism, and the GenerationEngine's
token streaming, SLOs, termination, and fault behavior."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.observability as obs
from paddle_tpu.observability import tracing
from paddle_tpu.serving.engine import ServingConfig
from paddle_tpu.serving.generation import (CacheConfig, DecodeRuntime,
                                           GenerationConfig,
                                           GenerationEngine, SamplingParams,
                                           SlotAllocator, dense_reference)
from paddle_tpu.serving.generation.decode import random_weights
from paddle_tpu.ops.sampling import sample_logits, token_key
from paddle_tpu.testing import faults

CFG = dict(vocab=64, d_model=32, n_layer=2, n_head=4, n_kv_head=2,
           d_ffn=64, theta=10000.0, max_len=32)
PROMPT = [1, 5, 9, 2, 7, 3]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()
    # drop this test's serving spans/flows from the global trace ring so
    # later trace-export tests see only their own events
    tracing.reset()


def _runtime(slots=3, chunk=4, mesh=None, seed=0):
    return DecodeRuntime(random_weights(CFG, seed=seed), CFG, slots=slots,
                         prefill_chunk=chunk, mesh=mesh)


def _engine(rt=None, window=4, **gen_kw):
    rt = rt or _runtime()
    return GenerationEngine(rt, config=ServingConfig(),
                            gen_config=GenerationConfig(
                                decode_window=window, **gen_kw)).start()


def _cnt(name):
    return obs.counters().get(name) or 0


# ------------------------------------------------------------- allocator

def test_slot_allocator_lowest_first_and_exhaustion():
    a = SlotAllocator(3)
    assert [a.alloc(), a.alloc(), a.alloc()] == [0, 1, 2]
    assert a.alloc() is None
    assert a.in_use() == 3
    a.free(1)
    assert a.alloc() == 1          # reuses the lowest free slot
    a.free(0)
    a.free(1)
    a.free(2)
    assert a.free_count() == 3


def test_slot_allocator_rejects_bad_frees():
    a = SlotAllocator(2)
    a.alloc()
    with pytest.raises(ValueError, match='out of range'):
        a.free(5)
    a.free(0)
    with pytest.raises(ValueError, match='double free'):
        a.free(0)


def test_cache_config_geometry():
    c = CacheConfig(slots=4, layers=2, kv_heads=2, max_len=32, head_dim=8)
    assert c.page_len == 8 and c.max_pages == 4
    assert c.pages == 4 * 4 + 1              # dense-equivalent + garbage
    assert c.pool_shape == (c.pages, 2, 2, 8, 8)
    assert c.bytes() == c.pages * c.page_bytes()
    assert c.page_bytes() == 2 * 4 * 2 * 2 * 8 * 8
    assert (c.pages_for(0), c.pages_for(1), c.pages_for(8),
            c.pages_for(9)) == (0, 1, 1, 2)
    assert c.dense_slot_bytes() == 2 * 4 * 2 * 2 * 32 * 8
    q = CacheConfig(slots=4, layers=2, kv_heads=2, max_len=32, head_dim=8,
                    page_len=4, quant='int8')
    assert q.store_dtype == 'int8'
    # int8 K+V page + f32 per-row scales
    assert q.page_bytes() == 2 * (2 * 2 * 4 * 8) + 2 * 4 * (2 * 2 * 4)
    with pytest.raises(ValueError):
        CacheConfig(slots=0, layers=1, kv_heads=1, max_len=8, head_dim=4)
    with pytest.raises(ValueError):
        CacheConfig(slots=1, layers=1, kv_heads=1, max_len=8, head_dim=4,
                    page_len=3)              # must divide max_len
    with pytest.raises(ValueError):
        CacheConfig(slots=1, layers=1, kv_heads=1, max_len=8, head_dim=4,
                    quant='int4')


# -------------------------------------------------------------- sampling

def test_sample_logits_greedy_and_topk1_are_argmax():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(16).astype('float32'))
    am = int(jnp.argmax(logits))
    key = token_key(7, 3)
    assert int(sample_logits(logits, key)) == am
    # top_k=1 with any temperature can only pick the argmax
    for seed in range(5):
        got = int(sample_logits(logits, token_key(seed, 0),
                                temperature=2.0, top_k=1))
        assert got == am


def test_sample_logits_topk_respects_support():
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(32).astype('float32'))
    top5 = set(np.argsort(np.asarray(logits))[-5:].tolist())
    for seed in range(20):
        got = int(sample_logits(logits, token_key(seed, 0),
                                temperature=1.5, top_k=5))
        assert got in top5


def test_sampling_is_position_and_seed_keyed():
    rng = np.random.RandomState(2)
    logits = jnp.asarray(rng.randn(64).astype('float32'))

    def draw(seed, pos):
        return int(sample_logits(logits, token_key(seed, pos),
                                 temperature=1.0))

    assert draw(3, 11) == draw(3, 11)           # deterministic
    draws = {draw(3, p) for p in range(40)}
    assert len(draws) > 1                        # position moves the draw
    draws_b = [draw(4, p) for p in range(40)]
    assert [draw(3, p) for p in range(40)] != draws_b  # seed moves it


def test_sample_tokens_op_matches_across_optimizer(monkeypatch):
    import paddle_tpu as fluid
    from paddle_tpu import layers

    def run(opt):
        monkeypatch.setenv('PT_OPT', opt)
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            x = layers.data('x', shape=[8], dtype='float32')
            greedy = layers.sample_tokens(x)
            drawn = layers.sample_tokens(x, temperature=0.8, top_k=3,
                                         seed=7)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(start)
        feed = {'x': np.random.RandomState(0).randn(4, 8).astype('float32')}
        return exe.run(main, feed=feed, fetch_list=[greedy, drawn])

    g0, d0 = run('0')
    g1, d1 = run('1')
    x = np.random.RandomState(0).randn(4, 8).astype('float32')
    assert np.array_equal(np.asarray(g0).ravel(), np.argmax(x, -1))
    assert np.array_equal(g0, g1) and np.array_equal(d0, d1)


# ------------------------------------------------------- prefill parity

def test_chunked_prefill_matches_dense_reference():
    rt = _runtime(chunk=4)
    prompt = np.asarray(PROMPT, np.int32)
    slot = rt.alloc_slot()
    assert rt.ensure_capacity(slot, prompt.size)   # map pages for the slot
    p = SamplingParams()
    logits = None
    for off in range(0, prompt.size, rt.prefill_chunk):
        first, logits = rt.prefill(slot, prompt[off:off + rt.prefill_chunk],
                                   off, p)
    kref, vref, lref = dense_reference(rt.w, CFG, prompt)
    krow, vrow, length = rt.cache_row(slot)
    assert length == prompt.size
    np.testing.assert_allclose(krow[:, :, :prompt.size], kref, atol=1e-5)
    np.testing.assert_allclose(vrow[:, :, :prompt.size], vref, atol=1e-5)
    np.testing.assert_allclose(logits, lref, atol=1e-5)
    assert first == int(np.argmax(lref))


@pytest.mark.skipif(len(jax.devices()) < 4, reason='needs 4 devices')
def test_ring_prefill_matches_dense_reference():
    from paddle_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(data=1, seq=4, model=1, pipe=1,
                     devices=jax.devices()[:4])
    rt = _runtime(slots=2, chunk=4, mesh=mesh)
    prompt = (np.arange(1, 11) % 63).astype(np.int32)   # pads 10 -> 12
    slot = rt.alloc_slot()
    assert rt.ensure_capacity(slot, prompt.size)   # map pages for the slot
    first, logits = rt.prefill_ring(slot, prompt, SamplingParams())
    kref, vref, lref = dense_reference(rt.w, CFG, prompt)
    krow, vrow, length = rt.cache_row(slot)
    assert length == prompt.size
    np.testing.assert_allclose(krow[:, :, :prompt.size], kref, atol=1e-5)
    np.testing.assert_allclose(vrow[:, :, :prompt.size], vref, atol=1e-5)
    np.testing.assert_allclose(logits, lref, atol=1e-5)
    rt.free_slot(slot)
    # the two prefill strategies feed bitwise-identical decode streams
    out_ring = rt.generate(prompt, 6, use_ring=True)
    rt.reset()
    out_chunk = rt.generate(prompt, 6, use_ring=False)
    assert out_ring == out_chunk


# ------------------------------------------------- fused decode parity

@pytest.mark.parametrize('params', [SamplingParams(),
                                    SamplingParams(0.9, 5, 11)],
                         ids=['greedy', 'topk'])
def test_fused_window_bitwise_equals_sequential(params):
    rt = _runtime()
    seq = rt.generate(PROMPT, 8, params, steps_per_window=1)
    rt.reset()
    fused = rt.generate(PROMPT, 8, params, steps_per_window=4)
    assert fused == seq            # bitwise: same ints, any K


def test_decode_parity_through_restored_aot_cache(tmp_path, monkeypatch):
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    params = SamplingParams(0.7, 5, 9)
    w = random_weights(CFG)
    rt1 = DecodeRuntime(w, CFG, slots=2, prefill_chunk=4)
    out1 = rt1.generate(PROMPT, 8, params, steps_per_window=4)
    hits0 = _cnt('compile_cache.disk_hits')
    # a fresh runtime (fresh process stand-in) loads the SAME executables
    # from disk and produces the SAME tokens
    rt2 = DecodeRuntime(w, CFG, slots=2, prefill_chunk=4)
    out2 = rt2.generate(PROMPT, 8, params, steps_per_window=4)
    assert out2 == out1
    assert _cnt('compile_cache.disk_hits') >= hits0 + 2


def test_no_retrace_across_batch_compositions():
    rt = _runtime(slots=3)
    compiles0 = _cnt('generation.compiles')
    rt.generate(PROMPT, 4, steps_per_window=2)
    rt.generate([4, 4], 4, SamplingParams(1.0, 3, 5), steps_per_window=2)
    rt.generate([9] * 7, 4, steps_per_window=2)
    # one prefill executable + one decode executable, total — sampling
    # params and prompt lengths are data, not signatures
    assert _cnt('generation.compiles') - compiles0 == 2


def test_runtime_generate_refuses_overlong():
    rt = _runtime()
    with pytest.raises(ValueError, match='never truncated'):
        rt.generate(list(range(30)), 8)


# ------------------------------------------------------------- engine

def test_engine_streams_and_resolves_max_tokens():
    eng = _engine()
    try:
        s = eng.generate(PROMPT, max_new=8)
        toks = list(s.tokens(timeout=30))
        r = s.result(5)
        assert r.ok and r.reason == 'max_tokens'
        assert toks == list(r.outputs[0]) and len(toks) == 8
        assert s.tokens_so_far() == toks
        # engine stream == direct sequential runtime stream
        ref = _runtime().generate(PROMPT, 8, steps_per_window=1)
        assert toks == ref
    finally:
        eng.stop()
    assert _cnt('serving.deadlocks') == 0


def test_engine_eos_terminates():
    rt = _runtime()
    eos = rt.generate(PROMPT, 1)[0]          # learn the first greedy token
    rt.reset()
    eng = _engine(rt, eos_id=eos)
    try:
        r = eng.generate(PROMPT, max_new=8).result(30)
        assert r.ok and r.reason == 'eos'
        assert len(r.outputs[0]) == 1 and int(r.outputs[0][0]) == eos
    finally:
        eng.stop()


def test_engine_rejects_overlong_prompt_never_truncates():
    eng = _engine()
    try:
        r = eng.generate(list(range(30)), max_new=8).result(1)
        assert r.status == 'rejected' and r.reason == 'too_long'
        assert 'truncated' in r.error and 'max_len=32' in r.error
        assert _cnt('serving.rejected.too_long') >= 1
        # boundary: exactly max_len fits
        ok = eng.generate(list(range(1, 29)), max_new=4).result(30)
        assert ok.ok and len(ok.outputs[0]) == 4
    finally:
        eng.stop()


def test_engine_rejects_empty_prompt_and_bad_max_new():
    eng = _engine()
    try:
        assert eng.generate([], max_new=4).result(1).reason == 'bad_request'
        assert eng.generate([1], max_new=0).result(1).reason == 'bad_request'
    finally:
        eng.stop()


def test_engine_submit_is_closed_off():
    eng = _engine()
    try:
        with pytest.raises(TypeError, match='generate'):
            eng.submit({'x': np.ones((1, 2))})
    finally:
        eng.stop()


def test_engine_seeded_topk_deterministic_across_restarts():
    outs = []
    for _ in range(2):
        eng = _engine(window=3)
        try:
            r = eng.generate(PROMPT, max_new=6, temperature=0.8, top_k=5,
                             seed=42).result(30)
            assert r.ok
            outs.append(list(r.outputs[0]))
        finally:
            eng.stop()
    assert outs[0] == outs[1]


def test_engine_cancel_mid_stream_sheds():
    eng = _engine()
    try:
        s = eng.generate(PROMPT, max_new=24, temperature=0.5, seed=1)
        it = s.tokens(timeout=30)
        next(it)                       # wait for the stream to be live
        s.cancel()
        r = s.result(10)
        assert r.status == 'shed' and r.reason == 'cancelled'
        assert _cnt('generation.cancelled') >= 1
    finally:
        eng.stop()
    assert _cnt('serving.deadlocks') == 0


def test_engine_concurrent_mixed_prefill_decode():
    eng = _engine(_runtime(slots=3))
    mixed0 = _cnt('generation.mixed_dispatches')
    try:
        streams = [eng.generate([i + 1] * (3 + i), max_new=5, seed=i)
                   for i in range(6)]          # 6 requests, 3 slots
        results = [s.result(60) for s in streams]
        assert all(r.ok and len(r.outputs[0]) == 5 for r in results)
        assert _cnt('generation.mixed_dispatches') > mixed0
    finally:
        eng.stop()
    assert _cnt('serving.deadlocks') == 0


def test_engine_ttft_itl_histograms_and_schema():
    eng = _engine()
    try:
        r = eng.generate(PROMPT, max_new=6).result(30)
        assert r.ok
    finally:
        eng.stop()
    assert obs.histogram('serving.ttft_ms').quantile(0.5) is not None
    assert obs.histogram('serving.itl_ms').quantile(0.5) is not None
    tel = obs.telemetry_snapshot('serving')
    for k in ('ttft_p50_ms', 'ttft_p99_ms', 'itl_p50_ms', 'itl_p99_ms',
              'kv_slots_in_use'):
        assert k in tel
    assert tel['kv_slots_in_use'] == 0
    assert any(k.startswith('generation.') for k in tel['counters'])


def test_engine_overall_deadline_mid_stream():
    eng = _engine()
    try:
        s = eng.generate(PROMPT, max_new=26, timeout_s=0.01)
        r = s.result(10)
        assert r.status == 'deadline_exceeded'
    finally:
        eng.stop()
    assert _cnt('serving.deadlocks') == 0


def test_engine_drain_sheds_active_streams():
    eng = _engine(window=1)
    s = eng.generate([1, 2], max_new=26)
    it = s.tokens(timeout=30)
    next(it)                            # actively decoding now
    eng.stop()
    r = s.result(5)
    # either it finished in time or it was shed at shutdown — never silent
    assert r.status in ('ok', 'shed')
    assert _cnt('serving.deadlocks') == 0


def test_engine_decode_step_fault_gives_error_replies_and_frees_slots():
    faults.configure('decode_step:at=1')
    rt = _runtime(slots=2)
    eng = _engine(rt)
    try:
        streams = [eng.generate(PROMPT, max_new=6, seed=i)
                   for i in range(2)]
        results = [s.result(30) for s in streams]
        # the faulted window errors every decoding request; requests that
        # were still prefilling at fire time finish OK afterwards
        assert any(r.status == 'error' and r.reason == 'decode_step'
                   for r in results)
        assert all(r.status in ('ok', 'error') for r in results)
        assert _cnt('faults.injected.decode_step') == 1
        assert rt.free_slots() == rt.slots     # no leaked slots
        # the engine keeps serving after the fault
        r2 = eng.generate(PROMPT, max_new=3).result(30)
        assert r2.ok
    finally:
        eng.stop()
    assert _cnt('serving.deadlocks') == 0


def test_llama_make_streaming_runtime_end_to_end():
    import paddle_tpu as fluid
    from paddle_tpu.models import llama

    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        llama.build('tiny', is_train=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(start)
    scope = fluid.global_scope()
    rt = llama.make_streaming_runtime(scope, 'tiny', slots=2,
                                      prefill_chunk=8)
    eng = GenerationEngine(rt, gen_config=GenerationConfig(
        decode_window=2)).start()
    try:
        r = eng.generate([1, 2, 3, 4], max_new=4).result(60)
        assert r.ok and len(r.outputs[0]) == 4
        assert all(0 <= t < llama.CONFIGS['tiny']['vocab']
                   for t in r.outputs[0])
    finally:
        eng.stop()
    assert _cnt('serving.deadlocks') == 0
