"""io.py save/load edge cases (model: reference test_io_save_load
unittests): predicate-filtered save_vars, params vs persistables
scope, cross-program load, single-file mode, checkpoint step."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build(scale):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = layers.data('x', shape=[3], dtype='float32')
            h = layers.fc(x, 4, param_attr=fluid.ParamAttr(
                name='io_w', initializer=fluid.initializer.Constant(scale)),
                bias_attr=fluid.ParamAttr(
                    name='io_b',
                    initializer=fluid.initializer.Constant(scale / 2)))
            loss = layers.reduce_mean(h)
            fluid.optimizer.Adam(1e-3).minimize(loss)  # adds accumulators
    return main, startup, loss


def test_save_params_vs_persistables_scope(tmp_path):
    main, startup, loss = _build(1.0)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={'x': np.ones((2, 3), 'float32')},
                fetch_list=[loss])
        pdir, adir = str(tmp_path / 'p'), str(tmp_path / 'a')
        fluid.io.save_params(exe, pdir, main)
        fluid.io.save_persistables(exe, adir, main)
    import os
    pkeys = set(np.load(os.path.join(pdir, '__params__.npz')).files)
    akeys = set(np.load(os.path.join(adir, '__params__.npz')).files)
    assert {'io_w', 'io_b'} <= pkeys
    # params-only save excludes optimizer accumulators; persistables has
    # them (adam moments + beta powers + step counters)
    assert not any('moment' in k for k in pkeys)
    assert any('moment' in k for k in akeys)
    assert pkeys < akeys


def test_save_vars_predicate_and_cross_program_load(tmp_path):
    main, startup, loss = _build(3.0)
    exe = fluid.Executor()
    scope = fluid.Scope()
    d = str(tmp_path / 'w_only')
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_vars(exe, d, main,
                           predicate=lambda v: v.name == 'io_w')
    import os
    keys = np.load(os.path.join(d, '__params__.npz')).files
    assert list(keys) == ['io_w']
    # load into a FRESH scope for the same-structure program built anew
    main2, startup2, _ = _build(0.0)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup2)
        fluid.io.load_vars(exe, d, main2,
                           predicate=lambda v: v.name == 'io_w')
        w = np.asarray(scope2.get('io_w'))
        b = np.asarray(scope2.get('io_b'))
    np.testing.assert_allclose(w, 3.0)   # loaded
    np.testing.assert_allclose(b, 0.0)   # untouched by predicate


def test_single_file_save_load(tmp_path):
    main, startup, _ = _build(2.0)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_params(exe, str(tmp_path), main,
                             filename='all_in_one')
    # np.savez appends .npz; load must meet save at the same path
    assert (tmp_path / 'all_in_one.npz').exists()
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        fluid.io.load_params(exe, str(tmp_path), main,
                             filename='all_in_one')
        np.testing.assert_allclose(np.asarray(scope2.get('io_w')), 2.0)


def test_checkpoint_records_step(tmp_path):
    main, startup, loss = _build(1.0)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_checkpoint(exe, str(tmp_path), main, step=42)
        step = fluid.io.load_checkpoint(exe, str(tmp_path), main)
    assert step == 42
