"""Graph-mode control flow: While / conditional_block / runtime tensor
arrays (model: reference tests/unittests/test_while_op.py,
test_conditional_block.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _int_scalar(v, dtype='int64'):
    return layers.fill_constant(shape=[1], dtype=dtype, value=v)


def test_while_counter():
    # the judge's round-1 repro: fill_constant / less_than / While / increment
    i = _int_scalar(0)
    n = _int_scalar(10)
    total = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        layers.assign(total + 1.0, total)
        layers.increment(i, 1)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    iv, tv = exe.run(fetch_list=[i, total])
    assert iv[0] == 10
    np.testing.assert_allclose(tv, [10.0])


def test_while_accumulates_tensor():
    x = fluid.layers.data('x', shape=[4], dtype='float32')
    i = _int_scalar(0)
    n = _int_scalar(5)
    acc = layers.fill_constant(shape=[1, 4], dtype='float32', value=0.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        layers.assign(acc + x, acc)
        layers.increment(i, 1)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    xv = np.arange(4, dtype='float32').reshape(1, 4)
    out, = exe.run(feed={'x': xv}, fetch_list=[acc])
    np.testing.assert_allclose(out, xv * 5, rtol=1e-6)


def test_while_array_write_read():
    i = _int_scalar(0)
    n = _int_scalar(6)
    x = layers.fill_constant(shape=[3], dtype='float32', value=1.0)
    arr = layers.create_array('float32')
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        val = x * layers.cast(i, 'float32')
        layers.array_write(val, i, arr)
        layers.increment(i, 1)
        layers.less_than(i, n, cond=cond)
    ln = layers.array_length(arr)
    r2 = layers.array_read(arr, _int_scalar(2))
    r5 = layers.array_read(arr, _int_scalar(5))
    exe = fluid.Executor()
    lnv, v2, v5 = exe.run(fetch_list=[ln, r2, r5])
    assert lnv[0] == 6
    np.testing.assert_allclose(v2, np.full(3, 2.0), rtol=1e-6)
    np.testing.assert_allclose(v5, np.full(3, 5.0), rtol=1e-6)


def test_nested_while():
    i = _int_scalar(0)
    n = _int_scalar(3)
    total = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        j = _int_scalar(0)
        m = _int_scalar(4)
        icond = layers.less_than(j, m)
        iw = layers.While(icond)
        with iw.block():
            layers.assign(total + 1.0, total)
            layers.increment(j, 1)
            layers.less_than(j, m, cond=icond)
        layers.increment(i, 1)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor()
    tv, = exe.run(fetch_list=[total])
    np.testing.assert_allclose(tv, [12.0])


def test_while_backward():
    # masked-scan lowering is reverse-differentiable: train through a loop
    x = fluid.layers.data('x', shape=[4], dtype='float32')
    wparam = layers.create_parameter([4, 4], 'float32', name='w_loop')
    i = _int_scalar(0)
    n = _int_scalar(3)
    h = layers.assign(x)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        layers.assign(layers.tanh(layers.matmul(h, wparam)), h)
        layers.increment(i, 1)
        layers.less_than(i, n, cond=cond)
    loss = layers.mean(h)
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    w_before = np.asarray(scope.get('w_loop')).copy()
    xv = np.random.RandomState(0).randn(2, 4).astype('float32')
    lv, = exe.run(feed={'x': xv}, fetch_list=[loss])
    w_after = np.asarray(scope.get('w_loop'))
    assert np.isfinite(lv).all()
    assert not np.allclose(w_before, w_after), 'loop params did not update'


def test_conditional_block_taken_and_skipped():
    x = fluid.layers.data('x', shape=[1], dtype='float32')
    out = layers.fill_constant(shape=[1, 1], dtype='float32', value=-1.0)
    zero = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    cond = layers.less_than(zero, x)  # x > 0
    cb = layers.ConditionalBlock([cond])
    with cb.block():
        layers.assign(x * 10.0, out)
    exe = fluid.Executor()
    taken, = exe.run(feed={'x': np.array([[3.0]], 'float32')},
                     fetch_list=[out])
    np.testing.assert_allclose(taken, [[30.0]])
    skipped, = exe.run(feed={'x': np.array([[-3.0]], 'float32')},
                       fetch_list=[out])
    np.testing.assert_allclose(skipped, [[-1.0]])


def test_while_without_cond_update_raises():
    i = _int_scalar(0)
    n = _int_scalar(10)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        layers.increment(i, 1)
    exe = fluid.Executor()
    with pytest.raises(ValueError, match='condition'):
        exe.run(fetch_list=[i])


def test_while_dynamic_bound_uses_while_loop():
    # bound fed at runtime -> no static bound -> lax.while_loop path
    nv = fluid.layers.data('n', shape=[1], dtype='int64')
    i = _int_scalar(0)
    total = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    cond = layers.less_than(i, nv)
    w = layers.While(cond)
    with w.block():
        layers.assign(total + 2.0, total)
        layers.increment(i, 1)
        layers.less_than(i, nv, cond=cond)
    exe = fluid.Executor()
    tv, = exe.run(feed={'n': np.array([[7]], 'int64')}, fetch_list=[total])
    np.testing.assert_allclose(tv, [14.0])
