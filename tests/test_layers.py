"""Broad layer coverage: every layer builds into a program and executes
(model: reference tests/unittests/test_layers.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(fetches, feed):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetches)


def test_activations_and_elementwise():
    x = layers.data('x', shape=[4], dtype='float32')
    outs = [layers.relu(x), layers.sigmoid(x), layers.tanh(x),
            layers.leaky_relu(x), layers.elu(x), layers.softplus(x),
            layers.square(x), layers.abs(x), layers.exp(x),
            layers.swish(x), layers.hard_sigmoid(x),
            layers.elementwise_add(x, x), layers.elementwise_max(x, x),
            layers.scale(x, 2.0), layers.clip(x, -0.5, 0.5)]
    xv = np.linspace(-2, 2, 8).reshape(2, 4).astype('float32')
    res = _run(outs, {'x': xv})
    np.testing.assert_allclose(res[0], np.maximum(xv, 0), rtol=1e-6)
    np.testing.assert_allclose(res[6], xv * xv, rtol=1e-6)
    np.testing.assert_allclose(res[11], 2 * xv, rtol=1e-6)


def test_reductions_and_reshape():
    x = layers.data('x', shape=[2, 3], dtype='float32')
    outs = [layers.reduce_sum(x, dim=1), layers.reduce_mean(x),
            layers.reduce_max(x, dim=2, keep_dim=True),
            layers.reshape(x, [-1, 6]), layers.transpose(x, [0, 2, 1]),
            layers.flatten(x), layers.squeeze(layers.unsqueeze(x, [1]),
                                              [1])]
    xv = np.arange(12).reshape(2, 2, 3).astype('float32')
    res = _run(outs, {'x': xv})
    np.testing.assert_allclose(res[0], xv.sum(1), rtol=1e-6)
    np.testing.assert_allclose(res[3], xv.reshape(2, 6), rtol=1e-6)
    np.testing.assert_allclose(res[6], xv, rtol=1e-6)


def test_concat_split_stack_gather():
    x = layers.data('x', shape=[4], dtype='float32')
    y = layers.data('y', shape=[4], dtype='float32')
    cat = layers.concat([x, y], axis=1)
    parts = layers.split(cat, 2, dim=1)
    st = layers.stack([x, y], axis=1)
    layers.data('idx', shape=[], dtype='int32',
                append_batch_size=False)
    xv = np.ones((2, 4), 'float32')
    yv = np.zeros((2, 4), 'float32')
    res = _run([cat, parts[0], st], {'x': xv, 'y': yv})
    assert res[0].shape == (2, 8)
    np.testing.assert_allclose(res[1], xv)
    assert res[2].shape == (2, 2, 4)


def test_conv_pool_norm_shapes():
    img = layers.data('img', shape=[3, 16, 16], dtype='float32')
    c = layers.conv2d(img, 8, 3, padding=1)
    assert c.shape == (-1, 8, 16, 16)
    ct = layers.conv2d_transpose(c, 3, filter_size=2, stride=2)
    assert ct.shape == (-1, 3, 32, 32)
    p = layers.pool2d(c, 2, pool_stride=2, pool_type='avg')
    assert p.shape == (-1, 8, 8, 8)
    ap = layers.adaptive_pool2d(c, 4, pool_type='avg')
    assert ap.shape == (-1, 8, 4, 4)
    g = layers.group_norm(c, groups=2)
    ln = layers.layer_norm(c)
    res = _run([c, ct, p, ap, g, ln],
               {'img': np.random.rand(2, 3, 16, 16).astype('float32')})
    for r in res:
        assert np.all(np.isfinite(r))


def test_losses():
    logit = layers.data('logit', shape=[5], dtype='float32')
    label = layers.data('label', shape=[1], dtype='int64')
    flabel = layers.data('flabel', shape=[5], dtype='float32')
    sm = layers.softmax(logit)
    ce = layers.cross_entropy(sm, label)
    swce = layers.softmax_with_cross_entropy(logit, label)
    sig = layers.sigmoid_cross_entropy_with_logits(logit, flabel)
    sq = layers.square_error_cost(logit, flabel)
    lv = np.random.RandomState(0).normal(size=(3, 5)).astype('float32')
    lab = np.array([[0], [2], [4]], 'int64')
    flab = np.random.RandomState(1).uniform(size=(3, 5)).astype('float32')
    res = _run([ce, swce, sig, sq],
               {'logit': lv, 'label': lab, 'flabel': flab})
    np.testing.assert_allclose(res[0], res[1], rtol=1e-5)
    expect_sq = (lv - flab) ** 2
    np.testing.assert_allclose(res[3], expect_sq, rtol=1e-5)


def test_embedding_and_one_hot():
    ids = layers.data('ids', shape=[1], dtype='int64')
    emb = layers.embedding(ids, size=[10, 4])
    oh = layers.one_hot(ids, 10)
    res = _run([emb, oh], {'ids': np.array([[1], [3]], 'int64')})
    assert res[0].shape == (2, 4)
    assert res[1].shape == (2, 10)
    assert res[1][0, 1] == 1.0 and res[1][1, 3] == 1.0


def test_topk_argmax_argsort():
    x = layers.data('x', shape=[5], dtype='float32')
    vals, idxs = layers.topk(x, 2)
    am = layers.argmax(x, axis=1)
    srt, sidx = layers.argsort(x, axis=1)
    xv = np.array([[3., 1., 4., 1., 5.]], 'float32')
    res = _run([vals, idxs, am, srt], {'x': xv})
    np.testing.assert_allclose(res[0], [[5., 4.]])
    assert res[2][0] == 4
    np.testing.assert_allclose(res[3][0], np.sort(xv[0]))


def test_dropout_train_vs_test():
    x = layers.data('x', shape=[100], dtype='float32')
    d_train = layers.dropout(x, 0.5)
    d_test = layers.dropout(x, 0.5, is_test=True)
    xv = np.ones((4, 100), 'float32')
    res = _run([d_train, d_test], {'x': xv})
    assert (res[0] == 0).mean() > 0.2          # some dropped
    np.testing.assert_allclose(res[1], xv * 0.5, rtol=1e-6)


def test_batch_norm_moving_stats_update():
    x = layers.data('x', shape=[4], dtype='float32')
    bn = layers.batch_norm(x)
    loss = layers.mean(bn)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    moving = [v for v in fluid.default_main_program().all_parameters()
              if not v.trainable]
    assert len(moving) == 2  # moving mean + variance
    before = {v.name: np.asarray(fluid.global_scope().get(v.name))
              for v in moving}
    xv = np.random.RandomState(0).normal(3.0, 1.0, (64, 4)).astype('float32')
    exe.run(feed={'x': xv}, fetch_list=[loss])
    after = {v.name: np.asarray(fluid.global_scope().get(v.name))
             for v in moving}
    # momentum 0.9: moving mean steps 0 -> ~0.3 toward batch mean 3.0
    assert any(np.abs(after[n] - before[n]).mean() > 0.05 for n in after)


def test_matmul_variants():
    a = layers.data('a', shape=[2, 3], dtype='float32')
    b = layers.data('b', shape=[3, 2], dtype='float32')
    mm = layers.matmul(a, b)
    mt = layers.matmul(a, a, transpose_y=True)
    av = np.random.rand(4, 2, 3).astype('float32')
    bv = np.random.rand(4, 3, 2).astype('float32')
    res = _run([mm, mt], {'a': av, 'b': bv})
    np.testing.assert_allclose(res[0], av @ bv, rtol=1e-5)
    np.testing.assert_allclose(res[1], av @ av.transpose(0, 2, 1),
                               rtol=1e-5)


def test_pad_and_label_smooth():
    x = layers.data('x', shape=[2, 2], dtype='float32')
    p = layers.pad(x, [0, 0, 1, 1, 0, 0], pad_value=9.0)
    oh = layers.data('oh', shape=[4], dtype='float32')
    ls = layers.label_smooth(oh, epsilon=0.1)
    xv = np.ones((1, 2, 2), 'float32')
    ohv = np.eye(4, dtype='float32')[:1].reshape(1, 4)
    res = _run([p, ls], {'x': xv, 'oh': ohv})
    assert res[0].shape == (1, 4, 2)
    np.testing.assert_allclose(res[1][0][0], 0.9 + 0.1 / 4, rtol=1e-5)


def test_where_like_ops_and_compare():
    x = layers.data('x', shape=[3], dtype='float32')
    y = layers.data('y', shape=[3], dtype='float32')
    lt = layers.less_than(x, y)
    eq = layers.equal(x, y)
    land = layers.logical_and(lt, eq)
    xv = np.array([[1., 2., 3.]], 'float32')
    yv = np.array([[3., 2., 1.]], 'float32')
    res = _run([lt, eq, land], {'x': xv, 'y': yv})
    assert res[0].tolist() == [[True, False, False]]
    assert res[1].tolist() == [[False, True, False]]
    assert res[2].tolist() == [[False, False, False]]


def test_nets_helpers():
    img = layers.data('img', shape=[1, 8, 8], dtype='float32')
    cp = fluid.nets.simple_img_conv_pool(img, 4, 3, 2, 2, act='relu')
    g = fluid.nets.glu(layers.fc(cp, 8), dim=-1)
    res = _run([cp, g], {'img': np.random.rand(2, 1, 8, 8)
                         .astype('float32')})
    assert res[0].shape == (2, 4, 3, 3)
    assert res[1].shape == (2, 4)


def test_lr_schedulers_build():
    # each scheduler builds (own program: they share a step-counter var)
    builders = [
        lambda: layers.exponential_decay(0.1, 100, 0.9),
        lambda: layers.natural_exp_decay(0.1, 100, 0.9),
        lambda: layers.inverse_time_decay(0.1, 100, 0.9),
        lambda: layers.polynomial_decay(0.1, 100),
        lambda: layers.piecewise_decay([10, 20], [0.1, 0.05, 0.01]),
        lambda: layers.noam_decay(64, 100),
        lambda: layers.cosine_decay(0.1, 10, 100),
    ]
    for build in builders:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            lr = build()
            exe = fluid.Executor()
            exe.run(startup)
            out, = exe.run(main, fetch_list=[lr])
            assert out.reshape(-1)[0] > 0


def test_uniform_random_and_gaussian():
    u = layers.uniform_random([4, 5], min=-2, max=2)
    g = layers.gaussian_random([4, 5], std=2.0)
    res = _run([u, g], {})
    assert res[0].shape == (4, 5)
    assert np.abs(res[0]).max() <= 2.0
    assert res[1].std() > 0.3


def test_fused_label_smooth_ce_matches_explicit_chain():
    rng = np.random.RandomState(0)
    B, T, V = 3, 5, 17
    eps = 0.1
    logits = fluid.layers.data('lg', shape=[T, V], dtype='float32')
    lbl = fluid.layers.data('lb', shape=[T, 1], dtype='int64')
    fused = layers.softmax_with_cross_entropy(logits, lbl,
                                              label_smooth_eps=eps)
    oh = layers.one_hot(lbl, depth=V)
    soft = layers.label_smooth(oh, epsilon=eps)
    explicit = layers.softmax_with_cross_entropy(logits, soft,
                                                 soft_label=True)
    with pytest.raises(ValueError, match='hard labels'):
        layers.softmax_with_cross_entropy(logits, soft, soft_label=True,
                                          label_smooth_eps=eps)
    lv = rng.randn(B, T, V).astype('float32')
    lb = rng.randint(0, V, (B, T, 1)).astype('int64')
    a, b = _run([fused, explicit], {'lg': lv, 'lb': lb})
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_nets_scaled_dot_product_attention_numeric():
    """nets.scaled_dot_product_attention vs a numpy reference (single
    and multi-head)."""
    rng = np.random.RandomState(0)
    B, T, D, H = 2, 5, 8, 2
    qv = rng.randn(B, T, D).astype('float32')
    kv = rng.randn(B, T, D).astype('float32')
    vv = rng.randn(B, T, D).astype('float32')

    def np_sdpa(q, k, v, heads):
        dh = D // heads
        qh = q.reshape(B, T, heads, dh).transpose(0, 2, 1, 3)
        kh = k.reshape(B, T, heads, dh).transpose(0, 2, 1, 3)
        vh = v.reshape(B, T, heads, dh).transpose(0, 2, 1, 3)
        s = (qh * dh ** -0.5) @ kh.transpose(0, 1, 3, 2)
        e = np.exp(s - s.max(-1, keepdims=True))
        w = e / e.sum(-1, keepdims=True)
        return (w @ vh).transpose(0, 2, 1, 3).reshape(B, T, D)

    q = layers.data('q', shape=[T, D], dtype='float32')
    k = layers.data('k', shape=[T, D], dtype='float32')
    v = layers.data('v', shape=[T, D], dtype='float32')
    outs = [fluid.nets.scaled_dot_product_attention(q, k, v, num_heads=h)
            for h in (1, H)]
    res = _run(outs, {'q': qv, 'k': kv, 'v': vv})
    for got, heads in zip(res, (1, H)):
        np.testing.assert_allclose(got, np_sdpa(qv, kv, vv, heads),
                                   rtol=1e-4, atol=1e-5)


def test_nets_img_conv_group_shapes():
    img = layers.data('icg', shape=[3, 16, 16], dtype='float32')
    out = fluid.nets.img_conv_group(
        img, conv_num_filter=[8, 8], pool_size=2, pool_stride=2,
        conv_padding=1, conv_filter_size=3, conv_act='relu',
        conv_with_batchnorm=True, pool_type='max')
    got, = _run([out], {'icg': np.random.RandomState(1).rand(
        2, 3, 16, 16).astype('float32')})
    assert got.shape == (2, 8, 8, 8)   # the VGG conv_block shape
    assert np.isfinite(got).all()
