"""Book-chapter patterns end to end (model: reference tests/book/
test_fit_a_line.py, test_recommender_system.py,
test_understand_sentiment.py conv variant).

The heavier chapters live elsewhere: recognize_digits / image
classification in test_models.py, machine translation in
test_rnn_blocks.py + test_beam_decoder.py, label semantic roles (CRF)
in test_ctc_crf.py.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


def test_fit_a_line():
    rng = np.random.RandomState(0)
    w_true = rng.rand(13, 1).astype('float32')
    b_true = 0.5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[13], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(0.05).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(300):
            xb = rng.rand(32, 13).astype('float32')
            lv, = exe.run(main, feed={'x': xb,
                                      'y': xb @ w_true + b_true},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0] * 0.02, (losses[0], losses[-1])


def test_recommender_system_dual_tower():
    """usr/mov towers of embeddings -> fc -> cos_sim, scaled to a 0-5
    rating (the book's recommender network shape)."""
    rng = np.random.RandomState(1)
    N_USR, N_JOB, N_MOV, N_CAT = 40, 8, 60, 6
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            uid = layers.data('uid', shape=[1], dtype='int64')
            job = layers.data('job', shape=[1], dtype='int64')
            mid = layers.data('mid', shape=[1], dtype='int64')
            cat = layers.data('cat', shape=[1], dtype='int64')
            score = layers.data('score', shape=[1], dtype='float32')
            usr = layers.concat(
                [layers.embedding(uid, size=[N_USR, 16]),
                 layers.embedding(job, size=[N_JOB, 8])], axis=1)
            usr = layers.fc(usr, 32, act='tanh')
            mov = layers.concat(
                [layers.embedding(mid, size=[N_MOV, 16]),
                 layers.embedding(cat, size=[N_CAT, 8])], axis=1)
            mov = layers.fc(mov, 32, act='tanh')
            sim = layers.cos_sim(usr, mov)
            pred = layers.scale(sim, scale=5.0)
            loss = layers.mean(layers.square_error_cost(pred, score))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)

    # synthetic preference structure: rating depends on (uid+mid) parity
    def batch(n=64):
        u = rng.randint(0, N_USR, (n, 1))
        m = rng.randint(0, N_MOV, (n, 1))
        return {'uid': u.astype('int64'),
                'job': (u % N_JOB).astype('int64'),
                'mid': m.astype('int64'),
                'cat': (m % N_CAT).astype('int64'),
                'score': np.where((u + m) % 2 == 0, 4.5,
                                  0.5).astype('float32')}

    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(150):
            lv, = exe.run(main, feed=batch(), fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_understand_sentiment_conv():
    """The book's sentiment conv net: embedding -> sequence_conv pools
    over ragged reviews -> softmax classifier."""
    rng = np.random.RandomState(2)
    V, C = 100, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            words = layers.data('words', shape=[1], dtype='int64',
                                lod_level=1)
            label = layers.data('label', shape=[1], dtype='int64')
            emb = layers.embedding(words, size=[V, 32])
            conv3 = fluid.nets.sequence_conv_pool(
                input=emb, num_filters=32, filter_size=3, act='tanh',
                pool_type='max')
            conv4 = fluid.nets.sequence_conv_pool(
                input=emb, num_filters=32, filter_size=4, act='tanh',
                pool_type='max')
            pred = layers.fc(layers.concat([conv3, conv4], axis=1), C,
                             act='softmax')
            loss = layers.mean(layers.cross_entropy(pred, label))
            acc = layers.accuracy(pred, label)
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)

    # toy rule: positive iff the review contains token 7
    def batch(n=16):
        rows, labs = [], []
        for _ in range(n):
            L = rng.randint(3, 9)
            r = rng.randint(10, V, (L, 1)).astype('int64')
            if rng.rand() < 0.5:
                r[rng.randint(L), 0] = 7
                labs.append([1])
            else:
                labs.append([0])
            rows.append(r)
        return {'words': create_lod_tensor(rows),
                'label': np.array(labs, 'int64')}

    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        accs = []
        for _ in range(120):
            av, = exe.run(main, feed=batch(), fetch_list=[acc])
            accs.append(float(np.asarray(av).reshape(())))
    assert np.mean(accs[-10:]) > 0.85, np.mean(accs[-10:])
