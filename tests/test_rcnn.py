"""RCNN op family: generate_proposals / rpn_target_assign /
generate_proposal_labels / generate_mask_labels.

Model: reference tests/unittests/test_generate_proposals_op.py,
test_rpn_target_assign_op.py, test_generate_proposal_labels_op.py —
numeric checks against independent numpy implementations of the
fixed-K semantics.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


def _np_iou(a, b):
    xi = np.maximum(a[:, None, 0], b[None, :, 0])
    yi = np.maximum(a[:, None, 1], b[None, :, 1])
    xa = np.minimum(a[:, None, 2], b[None, :, 2])
    ya = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.maximum(xa - xi, 0) * np.maximum(ya - yi, 0)
    ar = lambda x: np.maximum(x[:, 2] - x[:, 0], 0) * \
        np.maximum(x[:, 3] - x[:, 1], 0)
    return inter / np.maximum(ar(a)[:, None] + ar(b)[None] - inter, 1e-10)


def test_generate_proposals_decode_clip_nms():
    rng = np.random.RandomState(0)
    N, A, H, W = 2, 3, 4, 4
    post_n = 8
    scores = rng.rand(N, A, H, W).astype('float32')
    deltas = (rng.rand(N, 4 * A, H, W).astype('float32') - 0.5) * 0.4
    im_info = np.array([[60, 60, 1.0], [60, 60, 1.0]], 'float32')
    # anchors [H, W, A, 4]
    base = np.array([8.0, 16.0, 32.0])
    ys, xs = np.meshgrid(np.arange(H) * 16, np.arange(W) * 16,
                         indexing='ij')
    anchors = np.zeros((H, W, A, 4), 'float32')
    for a, s in enumerate(base):
        anchors[..., a, 0] = xs - s / 2
        anchors[..., a, 1] = ys - s / 2
        anchors[..., a, 2] = xs + s / 2
        anchors[..., a, 3] = ys + s / 2
    variances = np.ones((H, W, A, 4), 'float32')

    sc = fluid.layers.data('sc', shape=[A, H, W], dtype='float32')
    dl = fluid.layers.data('dl', shape=[4 * A, H, W], dtype='float32')
    ii = fluid.layers.data('ii', shape=[3], dtype='float32')
    an = fluid.layers.data('an', shape=[H, W, A, 4], dtype='float32',
                           append_batch_size=False)
    va = fluid.layers.data('va', shape=[H, W, A, 4], dtype='float32',
                           append_batch_size=False)
    rois, probs = layers.generate_proposals(
        sc, dl, ii, an, va, pre_nms_top_n=20, post_nms_top_n=post_n,
        nms_thresh=0.7, min_size=4.0)
    exe = fluid.Executor()
    rv, pv = exe.run(feed={'sc': scores, 'dl': deltas, 'ii': im_info,
                           'an': anchors, 'va': variances},
                     fetch_list=[rois, probs])
    rv, pv = np.asarray(rv), np.asarray(pv)
    assert rv.shape == (N, post_n, 4)
    assert pv.shape == (N, post_n, 1)
    # probs sorted desc within each image, boxes inside the image
    for i in range(N):
        p = pv[i, :, 0]
        valid = p > 0
        assert valid.any()
        assert (np.diff(p[valid]) <= 1e-6).all()
        b = rv[i][valid]
        assert (b[:, 0] >= 0).all() and (b[:, 2] <= 59.0 + 1e-4).all()
        assert (b[:, 2] >= b[:, 0]).all() and (b[:, 3] >= b[:, 1]).all()
        # surviving pairs respect the NMS threshold
        iou = _np_iou(b, b)
        np.fill_diagonal(iou, 0)
        assert (iou <= 0.7 + 1e-5).all()


def test_rpn_target_assign_labels_and_targets():
    rng = np.random.RandomState(1)
    M = 24
    K, Kf = 8, 4
    anchors = np.zeros((M, 4), 'float32')
    anchors[:, 0] = rng.rand(M) * 40
    anchors[:, 1] = rng.rand(M) * 40
    anchors[:, 2] = anchors[:, 0] + 8 + rng.rand(M) * 8
    anchors[:, 3] = anchors[:, 1] + 8 + rng.rand(M) * 8
    # one gt right on top of anchor 5, another overlapping anchor 11
    gts = [np.stack([anchors[5] + 0.5, anchors[11] + 1.0]),
           np.stack([anchors[2] + 0.2])]
    gt_lod = create_lod_tensor([g.astype('float32') for g in gts])

    bp = fluid.layers.data('bp', shape=[M, 4], dtype='float32')
    cl = fluid.layers.data('cl', shape=[M, 1], dtype='float32')
    an = fluid.layers.data('an', shape=[M, 4], dtype='float32',
                           append_batch_size=False)
    av = fluid.layers.data('av', shape=[M, 4], dtype='float32',
                           append_batch_size=False)
    gt = fluid.layers.data('gt', shape=[4], dtype='float32', lod_level=1)
    outs = layers.rpn_target_assign(
        bp, cl, an, av, gt, rpn_batch_size_per_im=K, rpn_fg_fraction=0.5,
        rpn_positive_overlap=0.6, rpn_negative_overlap=0.3)
    pred_scores, pred_loc, tgt_label, tgt_bbox, inside_w = outs
    exe = fluid.Executor()
    rng2 = np.random.RandomState(2)
    feed = {'bp': rng2.rand(2, M, 4).astype('float32'),
            'cl': rng2.rand(2, M, 1).astype('float32'),
            'an': anchors, 'av': np.ones((M, 4), 'float32'),
            'gt': gt_lod}
    ps, pl, tl, tb, iw = [np.asarray(v) for v in exe.run(
        feed=feed, fetch_list=list(outs))]
    assert ps.shape == (2, K, 1) and pl.shape == (2, Kf, 4)
    assert tl.shape == (2, K, 1) and tb.shape == (2, Kf, 4)
    # image 0: anchors 5 and 11 overlap gts strongly -> fg labels first;
    # padding/ignore-zone rows carry label -1
    assert tl[0, 0, 0] == 1 and (tl[0] == 1).sum() >= 2
    assert set(np.unique(tl)) <= {-1, 0, 1}
    # fg rows with weight 1 have finite encoded targets
    assert np.isfinite(tb).all()
    assert set(np.unique(iw)) <= {0.0, 1.0}
    # targets are zeroed where inside weight is zero
    np.testing.assert_allclose(tb * (1 - iw), 0, atol=1e-6)


def test_generate_proposal_labels_classes():
    N, R, G, B, C = 1, 12, 2, 6, 5
    gt_boxes = np.array([[[4, 4, 20, 20], [30, 30, 44, 44]]], 'float32')
    gt_cls = np.array([[[2], [4]]], 'int64')
    # proposals: 0-3 near gt0, 4-7 near gt1, rest far away
    rois = np.zeros((N, R, 4), 'float32')
    for i in range(4):
        rois[0, i] = [4 + i, 4 + i, 20 + i, 20 + i]
        rois[0, 4 + i] = [30 + i, 30 + i, 44 + i, 44 + i]
    for i in range(8, R):
        rois[0, i] = [50 + i, 50 + i, 52 + i, 52 + i]
    rv = fluid.layers.data('rois', shape=[R, 4], dtype='float32')
    gcv = fluid.layers.data('gc', shape=[G, 1], dtype='int64')
    gbv = fluid.layers.data('gb', shape=[G, 4], dtype='float32')
    outs = layers.generate_proposal_labels(
        rv, gcv, None, gbv, batch_size_per_im=B, fg_fraction=0.5,
        fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=C)
    o_rois, o_lab, o_tgt, o_inw, o_outw = outs
    exe = fluid.Executor()
    got = [np.asarray(v) for v in exe.run(
        feed={'rois': rois, 'gc': gt_cls, 'gb': gt_boxes},
        fetch_list=list(outs))]
    o_rois, o_lab, o_tgt, o_inw, o_outw = got
    assert o_rois.shape == (N, B, 4) and o_lab.shape == (N, B, 1)
    assert o_tgt.shape == (N, B, 4 * C)
    labs = o_lab[0, :, 0]
    # fg rows carry the matched gt class (2 or 4), bg rows 0
    fg = labs[labs > 0]
    assert set(fg.tolist()) <= {2, 4} and len(fg) >= 2
    # bbox targets live only in the labeled class slot
    for i, l in enumerate(labs):
        slots = o_inw[0, i].reshape(C, 4).sum(1)
        if l > 0:
            assert slots[l] == 4 and slots.sum() == 4
        else:
            assert slots.sum() == 0


def test_generate_mask_labels_rasterizes_polygon():
    # one roi exactly covering a square polygon -> solid mask
    N, B, G, P, C, R = 1, 2, 1, 4, 3, 8
    rois = np.array([[[10, 10, 26, 26], [0, 0, 8, 8]]], 'float32')
    labels = np.array([[[1], [0]]], 'int32')      # roi1 is bg
    segms = np.array([[[[10, 10], [26, 10], [26, 26], [10, 26]]]],
                     'float32')
    roi_gt = np.array([[[0], [-1]]], 'int32')
    rv = fluid.layers.data('rois', shape=[B, 4], dtype='float32')
    lv = fluid.layers.data('lab', shape=[B, 1], dtype='int32')
    sv = fluid.layers.data('seg', shape=[G, P, 2], dtype='float32')
    gv = fluid.layers.data('rgi', shape=[B, 1], dtype='int32')
    mask_rois, has_mask, mask = layers.generate_mask_labels(
        None, None, None, sv, rv, lv, num_classes=C, resolution=R,
        roi_gt_index=gv)
    exe = fluid.Executor()
    mr, hm, mk = [np.asarray(v) for v in exe.run(
        feed={'rois': rois, 'lab': labels, 'seg': segms, 'rgi': roi_gt},
        fetch_list=[mask_rois, has_mask, mask])]
    assert hm[0, 0, 0] == 1 and hm[0, 1, 0] == 0
    m = mk[0, 0].reshape(C, R, R)
    # class-1 slot: every sampled point is inside the square
    assert (m[1] == 1).all()
    # other class slots are ignore (-1)
    assert (m[0] == -1).all() and (m[2] == -1).all()
    # bg roi contributes nothing
    assert (mk[0, 1] == -1).all()
