"""SSD/YOLO detection-family numerics vs hand/numpy references (model:
reference unittests test_iou_similarity_op / test_box_coder_op /
test_prior_box_op / test_bipartite_match_op / test_multiclass_nms_op /
test_target_assign_op).  The RCNN family has its own file
(test_rcnn.py); this covers the one-stage stack."""
import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.registry import get_op


def _impl(op):
    return get_op(op).impl


def _np_iou(a, b):
    xi = max(a[0], b[0]); yi = max(a[1], b[1])
    xa = min(a[2], b[2]); ya = min(a[3], b[3])
    inter = max(xa - xi, 0) * max(ya - yi, 0)
    area = lambda r: max(r[2] - r[0], 0) * max(r[3] - r[1], 0)
    return inter / max(area(a) + area(b) - inter, 1e-10)


def test_iou_similarity_numeric():
    x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], 'float32')
    y = np.array([[0, 0, 2, 2], [2, 2, 4, 4], [0, 0, 1, 1]], 'float32')
    out = np.asarray(_impl('iou_similarity')(
        None, {'X': jnp.asarray(x), 'Y': jnp.asarray(y)}, {})['Out'])
    ref = np.array([[_np_iou(a, b) for b in y] for a in x])
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_box_coder_encode_decode_roundtrip():
    prior = np.array([[0., 0., 2., 2.], [1., 1., 4., 5.]], 'float32')
    pvar = np.array([[0.1, 0.1, 0.2, 0.2]] * 2, 'float32')
    tb = np.array([[0.5, 0.5, 2.5, 3.5], [0., 1., 3., 4.]], 'float32')
    enc = _impl('box_coder')(
        None, {'PriorBox': jnp.asarray(prior), 'PriorBoxVar': jnp.asarray(pvar),
               'TargetBox': jnp.asarray(tb)},
        {'code_type': 'encode_center_size'})['OutputBox']
    # hand-check one entry: target 0 vs prior 0
    pw = ph = 2.0
    tcx, tcy, tw, th = 1.5, 2.0, 2.0, 3.0
    np.testing.assert_allclose(
        np.asarray(enc)[0, 0],
        [(tcx - 1.0) / pw / 0.1, (tcy - 1.0) / ph / 0.1,
         np.log(tw / pw) / 0.2, np.log(th / ph) / 0.2], rtol=1e-4)
    # decode(encode(t)) == t, taking the diagonal (each target with its
    # own prior's code)
    deltas = np.stack([np.asarray(enc)[i, i] for i in range(2)])
    dec = _impl('box_coder')(
        None, {'PriorBox': jnp.asarray(prior), 'PriorBoxVar': jnp.asarray(pvar),
               'TargetBox': jnp.asarray(deltas[:, None, :].repeat(2, 1))},
        {'code_type': 'decode_center_size'})['OutputBox']
    got = np.stack([np.asarray(dec)[i, i] for i in range(2)])
    np.testing.assert_allclose(got, tb, rtol=1e-4, atol=1e-5)


def test_prior_box_centers_and_sizes():
    feat = jnp.zeros((1, 8, 2, 2))
    img = jnp.zeros((1, 3, 8, 8))
    out = _impl('prior_box')(
        None, {'Input': feat, 'Image': img},
        {'min_sizes': [2.0], 'aspect_ratios': [1.0],
         'variances': [0.1, 0.1, 0.2, 0.2]})
    boxes = np.asarray(out['Boxes'])          # [H, W, P, 4] normalized
    assert boxes.shape == (2, 2, 1, 4)
    # cell (0,0): center = (0+.5)*4 = 2 px -> box [1,1,3,3]/8
    np.testing.assert_allclose(boxes[0, 0, 0],
                               np.array([1, 1, 3, 3]) / 8.0, rtol=1e-5)
    # cell (1,1): center 6 px -> [5,5,7,7]/8
    np.testing.assert_allclose(boxes[1, 1, 0],
                               np.array([5, 5, 7, 7]) / 8.0, rtol=1e-5)
    var = np.asarray(out['Variances'])
    np.testing.assert_allclose(var[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_anchor_generator_values():
    feat = jnp.zeros((1, 8, 2, 2))
    out = _impl('anchor_generator')(
        None, {'Input': feat},
        {'anchor_sizes': [4.0], 'aspect_ratios': [1.0],
         'stride': [4.0, 4.0]})
    anch = np.asarray(out['Anchors'])
    assert anch.shape == (2, 2, 1, 4)
    # cell (0,0): center (2,2), size 4 -> [0,0,4,4]
    np.testing.assert_allclose(anch[0, 0, 0], [0, 0, 4, 4], rtol=1e-5)


def test_bipartite_match_greedy():
    # classic greedy argmax: global max first, rows/cols knocked out
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.8, 0.7, 0.2]], 'float32')
    out = _impl('bipartite_match')(
        None, {'DistMat': jnp.asarray(dist)}, {})
    col2row = np.asarray(out['ColToRowMatchIndices'])[0]
    d = np.asarray(out['ColToRowMatchDist'])[0]
    # 0.9 at (0,0) first; then row1's best remaining is 0.7 at (1,1)
    assert col2row.tolist() == [0, 1, -1]
    np.testing.assert_allclose(d, [0.9, 0.7, 0.0], rtol=1e-6)


def test_target_assign_numeric():
    x = np.arange(12, dtype='float32').reshape(4, 3)  # 4 rows, K=3
    match = np.array([[2, -1, 0]], 'int32')
    out = _impl('target_assign')(
        None, {'X': jnp.asarray(x), 'MatchIndices': jnp.asarray(match)},
        {'mismatch_value': 7.0})
    o = np.asarray(out['Out'])[0]
    w = np.asarray(out['OutWeight'])[0]
    np.testing.assert_allclose(o[0], x[2])
    np.testing.assert_allclose(o[1], [7.0] * 3)   # mismatched
    np.testing.assert_allclose(o[2], x[0])
    np.testing.assert_allclose(w.ravel(), [1.0, 0.0, 1.0])


def test_multiclass_nms_suppresses_overlaps():
    # three boxes: two heavy overlaps (keep the higher score), one far
    boxes = np.array([[[0, 0, 2, 2], [0, 0, 2.1, 2.1],
                       [5, 5, 7, 7]]], 'float32')
    scores = np.array([[[0.9, 0.8, 0.6]]], 'float32')  # [N=1, C=1, M=3]
    out = np.asarray(_impl('multiclass_nms')(
        None, {'BBoxes': jnp.asarray(boxes), 'Scores': jnp.asarray(scores)},
        {'score_threshold': 0.1, 'nms_threshold': 0.5,
         'keep_top_k': 3, 'background_label': -1})['Out'])[0]
    kept = out[out[:, 0] >= 0]
    assert kept.shape[0] == 2                      # overlap suppressed
    np.testing.assert_allclose(kept[0, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(kept[1, 1], 0.6, rtol=1e-5)
    np.testing.assert_allclose(kept[1, 2:], [5, 5, 7, 7], rtol=1e-5)


def test_yolov3_loss_perfect_prediction_near_zero_xywh():
    """Logits constructed to hit the target exactly: the xy/wh terms
    vanish; obj/cls stay finite and positive."""
    H = W = 4
    class_num = 2
    anchors = [16, 16]
    N, na = 1, 1
    gt_box = np.array([[[0.375, 0.625, 0.125, 0.125]]], 'float32')
    gt_label = np.array([[1]], 'int64')
    # responsible cell: gi=1, gj=2 (x*W=1.5, y*H=2.5); tx=ty=0.5
    x = np.zeros((N, na * (5 + class_num), H, W), 'float32')
    pred = x.reshape(N, na, 5 + class_num, H, W)
    pred[0, 0, 0, 2, 1] = 0.0        # sigmoid(0)=0.5 == tx
    pred[0, 0, 1, 2, 1] = 0.0        # ty
    # tw = log(gtw / (aw/input)) with input=32*... downsample 8 ->
    # input_size = 8*4=32; aw = 16/32 = 0.5; tw = log(.125/.5)
    tw = np.log(0.125 / 0.5)
    pred[0, 0, 2, 2, 1] = tw
    pred[0, 0, 3, 2, 1] = tw
    out = _impl('yolov3_loss')(
        None, {'X': jnp.asarray(x), 'GTBox': jnp.asarray(gt_box),
               'GTLabel': jnp.asarray(gt_label)},
        {'anchors': anchors, 'anchor_mask': [0], 'class_num': class_num,
         'downsample_ratio': 8})['Loss']
    val = float(np.asarray(out)[0])
    assert np.isfinite(val) and val > 0
    # perturbing xy away from target must increase the loss
    x2 = x.copy()
    x2.reshape(N, na, 5 + class_num, H, W)[0, 0, 0, 2, 1] = 3.0
    out2 = _impl('yolov3_loss')(
        None, {'X': jnp.asarray(x2), 'GTBox': jnp.asarray(gt_box),
               'GTLabel': jnp.asarray(gt_label)},
        {'anchors': anchors, 'anchor_mask': [0], 'class_num': class_num,
         'downsample_ratio': 8})['Loss']
    assert float(np.asarray(out2)[0]) > val


def test_polygon_box_transform_runs():
    x = np.random.RandomState(0).randn(1, 8, 2, 2).astype('float32')
    out = _impl('polygon_box_transform')(None, {'Input': jnp.asarray(x)},
                                         {})
    o = list(out.values())[0]
    assert np.asarray(o).shape == (1, 8, 2, 2)


def test_multiclass_nms_fixed_shape_and_clean_padding():
    """Padding rows must be fully zeroed (label -1) — no leaked box
    coordinates — and the output must honor [N, keep_top_k, 6] even
    when fewer candidates exist than keep_top_k."""
    boxes = np.array([[[0, 0, 2, 2], [5, 5, 7, 7]]], 'float32')
    scores = np.array([[[0.9, 0.6]]], 'float32')
    out = np.asarray(_impl('multiclass_nms')(
        None, {'BBoxes': jnp.asarray(boxes), 'Scores': jnp.asarray(scores)},
        {'score_threshold': 0.1, 'nms_threshold': 0.5,
         'keep_top_k': 5, 'background_label': -1})['Out'])[0]
    assert out.shape == (5, 6)
    assert (out[:2, 0] == 0).all()
    invalid = out[out[:, 0] < 0]
    assert invalid.shape[0] == 3
    np.testing.assert_allclose(invalid[:, 1:], 0.0)


def test_multiclass_nms_skips_background_class():
    """Reference semantics: the background class (default label 0) emits
    no detections even with near-1.0 scores everywhere."""
    boxes = np.array([[[0, 0, 2, 2], [5, 5, 7, 7]]], 'float32')
    scores = np.array([[[0.99, 0.98],     # class 0 = background
                        [0.30, 0.70]]], 'float32')
    out = np.asarray(_impl('multiclass_nms')(
        None, {'BBoxes': jnp.asarray(boxes), 'Scores': jnp.asarray(scores)},
        {'score_threshold': 0.1, 'nms_threshold': 0.5,
         'keep_top_k': 4})['Out'])[0]
    kept = out[out[:, 0] >= 0]
    assert (kept[:, 0] == 1).all()          # only class 1 rows
    assert kept.shape[0] == 2
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.3, 0.7], rtol=1e-5)
