"""MoE (expert parallel) + sharded embedding tests."""
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel import moe


def _reference_top2(x, params):
    """Loop reference: every token goes to its top-2 experts (no capacity
    drops), gates renormalized."""
    G, S, D = x.shape
    logits = np.einsum('gsd,de->gse', x, params['gate_w'])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    y = np.zeros_like(x)
    for g in range(G):
        for s in range(S):
            p = probs[g, s].copy()
            e1 = int(p.argmax())
            p2 = p.copy()
            p2[e1] = -1
            e2 = int(p2.argmax())
            g1, g2 = p[e1], p[e2]
            tot = g1 + g2
            for e, w in ((e1, g1 / tot), (e2, g2 / tot)):
                h = np.maximum(x[g, s] @ params['wi'][e], 0.0)
                y[g, s] += w * (h @ params['wo'][e])
    return y


def test_moe_matches_reference_no_drops():
    rng = np.random.RandomState(0)
    G, S, D, F, E = 2, 8, 16, 32, 4
    params = {k: np.asarray(v) for k, v in moe.init_moe_params(
        jax.random.key(0), D, F, E).items()}
    x = rng.randn(G, S, D).astype('float32')
    # capacity_factor E => capacity = S: nothing can be dropped
    y, aux = moe.moe_ffn(params, jnp.array(x), capacity_factor=float(E))
    ref = _reference_top2(x, params)
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4, rtol=1e-4)
    assert np.isfinite(float(aux))


def test_moe_sharded_matches_unsharded():
    mesh = make_mesh(data=2, model=4, pipe=1, seq=1)
    rng = np.random.RandomState(1)
    G, S, D, F, E = 4, 8, 8, 16, 4
    params = moe.init_moe_params(jax.random.key(1), D, F, E)
    x = jnp.array(rng.randn(G, S, D).astype('float32'))
    y0, _ = moe.moe_ffn(params, x, capacity_factor=float(E))

    sp = {'gate_w': NamedSharding(mesh, P()),
          'wi': NamedSharding(mesh, P('model', None, None)),
          'wo': NamedSharding(mesh, P('model', None, None))}
    params_s = {k: jax.device_put(v, sp[k]) for k, v in params.items()}
    x_s = jax.device_put(x, NamedSharding(mesh, P('data', None, None)))
    with mesh:
        y1, _ = jax.jit(
            lambda p, x: moe.moe_ffn(p, x, capacity_factor=float(E)))(
                params_s, x_s)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               atol=2e-5, rtol=2e-5)


def test_moe_grads_flow():
    params = moe.init_moe_params(jax.random.key(2), 8, 16, 4)
    x = jax.random.normal(jax.random.key(3), (2, 8, 8))

    def loss(p):
        y, aux = moe.moe_ffn(p, x, capacity_factor=4.0)
        return (y ** 2).mean() + 0.01 * aux

    g = jax.grad(loss)(params)
    for k, v in g.items():
        assert np.isfinite(np.asarray(v)).all(), k
        assert float(jnp.abs(v).max()) > 0, k


def test_sharded_embedding_layer():
    from paddle_tpu.parallel.sharded_embedding import sharded_embedding
    mesh = make_mesh(data=2, model=4, pipe=1, seq=1)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            ids = fluid.layers.data('ids', shape=[6, 1], dtype='int64')
            emb = sharded_embedding(ids, size=[64, 16])
            loss = fluid.layers.mean(emb)
            fluid.optimizer.SGD(0.1).minimize(loss)
    w_name = emb.op.inputs['W'][0]
    assert main._sharding[w_name] == P('model', None)
    exe = fluid.Executor(mesh=mesh)
    rng = np.random.RandomState(0)
    feed = {'ids': rng.randint(0, 64, (8, 6, 1)).astype('int64')}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with mesh:
            l, = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(l).all()
