"""Executor + framework core tests (model: reference
tests/unittests/test_executor_and_mul.py, test_program.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def test_feed_fetch_identity():
    x = fluid.layers.data('x', shape=[4], dtype='float32')
    y = fluid.layers.scale(x, scale=2.0, bias=1.0)
    exe = fluid.Executor()
    xv = np.arange(8, dtype='float32').reshape(2, 4)
    out, = exe.run(feed={'x': xv}, fetch_list=[y])
    np.testing.assert_allclose(out, xv * 2 + 1, rtol=1e-6)


def test_shape_inference_batch_dim():
    x = fluid.layers.data('x', shape=[1, 28, 28], dtype='float32')
    y = fluid.layers.fc(x, 10)
    assert y.shape == (-1, 10)
    c = fluid.layers.conv2d(x, 6, 5)
    assert c.shape == (-1, 6, 24, 24)
    p = fluid.layers.pool2d(c, 2, pool_stride=2)
    assert p.shape == (-1, 6, 12, 12)


def test_program_guard_and_clone():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[3], dtype='float32')
        d = fluid.layers.dropout(fluid.layers.fc(x, 4), 0.5)
        loss = fluid.layers.mean(d)
        fluid.optimizer.SGD(0.1).minimize(loss)
    n_train_ops = len(main.global_block().ops)
    test_prog = main.clone(for_test=True)
    n_test_ops = len(test_prog.global_block().ops)
    assert n_test_ops < n_train_ops
    drop_ops = [op for op in test_prog.global_block().ops
                if op.type == 'dropout']
    assert drop_ops and drop_ops[0].attrs['is_test'] is True


def test_persistable_update_and_scope():
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    w = fluid.layers.create_parameter([2, 2], 'float32', name='w_test',
                                      default_initializer=
                                      fluid.initializer.Constant(1.0))
    y = fluid.layers.mul(x, w)
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(1.0).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    w0 = np.array(fluid.global_scope().get('w_test'))
    np.testing.assert_allclose(w0, np.ones((2, 2)), rtol=1e-6)
    exe.run(feed={'x': np.ones((4, 2), 'float32')}, fetch_list=[loss])
    w1 = np.array(fluid.global_scope().get('w_test'))
    assert not np.allclose(w0, w1)


def test_uninitialized_param_error():
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    y = fluid.layers.fc(x, 2)
    exe = fluid.Executor()
    with pytest.raises(RuntimeError, match='startup'):
        exe.run(feed={'x': np.ones((1, 2), 'float32')}, fetch_list=[y])


def test_math_op_patch():
    x = fluid.layers.data('x', shape=[3], dtype='float32')
    y = (x * 2.0 + 1.0) / 2.0 - 0.5
    z = -y
    exe = fluid.Executor()
    xv = np.array([[1., 2., 3.]], 'float32')
    out, = exe.run(feed={'x': xv}, fetch_list=[z])
    np.testing.assert_allclose(out, -xv, rtol=1e-6)


def test_run_default_program_cache():
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    y = fluid.layers.scale(x, scale=3.0)
    exe = fluid.Executor()
    for i in range(3):
        out, = exe.run(feed={'x': np.full((2, 2), i, 'float32')},
                       fetch_list=[y])
        np.testing.assert_allclose(out, np.full((2, 2), 3.0 * i), rtol=1e-6)


def test_fetch_param_directly():
    fluid.layers.create_parameter([3], 'float32', name='pp',
                                  default_initializer=
                                  fluid.initializer.Constant(2.5))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out, = exe.run(fetch_list=['pp'])
    np.testing.assert_allclose(out, [2.5] * 3, rtol=1e-6)


def test_check_nan_raises_on_nonfinite_fetch():
    import pytest
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    y = fluid.layers.log(x)          # log(0) = -inf, log(-1) = nan
    exe = fluid.Executor(check_nan=True)
    with pytest.raises(RuntimeError, match='non-finite'):
        exe.run(feed={'x': np.array([[0.0, -1.0]], 'float32')},
                fetch_list=[y])
    # finite input passes cleanly through the same executor
    out, = exe.run(feed={'x': np.array([[1.0, 2.0]], 'float32')},
                   fetch_list=[y])
    np.testing.assert_allclose(out, np.log([[1.0, 2.0]]), rtol=1e-6)


def test_check_nan_names_poisoned_param_update():
    import pytest
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        w = fluid.layers.create_parameter([2, 1], 'float32', name='w_nan')
        # sqrt'(u) = 1/(2 sqrt(u)) is nan for u<0 — the nan gradient
        # poisons the updated weight, not just the loss
        loss = fluid.layers.reduce_mean(
            fluid.layers.sqrt(fluid.layers.matmul(x, w)))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(check_nan=True)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        # negative product -> log gives nan -> nan gradient poisons w
        with pytest.raises(RuntimeError, match='w_nan'):
            exe.run(main, feed={'x': np.array([[-1.0, -1.0]], 'float32')},
                    fetch_list=[loss])


def test_def_use_validation_names_op_and_var():
    import pytest
    from paddle_tpu.core.framework import Operator
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        y = fluid.layers.scale(x, scale=2.0)
        blk = main.global_block()
        ghost = blk.create_var(name='never_written', shape=(2,),
                               dtype='float32')
        out = blk.create_var(name='bad_out', shape=(2,), dtype='float32')
        blk.ops.append(Operator(blk, 'scale',
                                inputs={'X': ghost},
                                outputs={'Out': out},
                                attrs={'scale': 1.0}))
    exe = fluid.Executor()
    with pytest.raises(ValueError, match='never_written'):
        exe.run(main, feed={'x': np.zeros((1, 2), 'float32')},
                fetch_list=[y])


def test_clone_for_test_freezes_dropout_and_bn():
    """clone(for_test=True): dropout becomes identity, batch_norm uses
    the running statistics (not batch stats), optimizer ops dropped —
    the reference's train/eval program split."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            h = fluid.layers.dropout(
                x, 0.5, dropout_implementation='upscale_in_train')
            h = fluid.layers.fc(h, 4, bias_attr=False,
                                param_attr=fluid.ParamAttr(
                                    name='cf_w',
                                    initializer=fluid.initializer.
                                    Constant(1.0)))
            h = fluid.layers.batch_norm(h)
            loss = fluid.layers.reduce_mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    # optimizer/backward ops dropped from the clone
    main_types = [op.type for op in main.global_block().ops]
    test_types = [op.type for op in test_prog.global_block().ops]
    assert '__backward__' in main_types and 'sgd' in main_types
    assert '__backward__' not in test_types and 'sgd' not in test_types

    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype('float32')
    with fluid.scope_guard(scope):
        exe.run(startup)
        # eval runs are DETERMINISTIC (dropout off): two runs identical
        a, = exe.run(test_prog, feed={'x': xv}, fetch_list=[loss])
        b, = exe.run(test_prog, feed={'x': xv}, fetch_list=[loss])
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        # train runs are stochastic through dropout
        t1, = exe.run(main, feed={'x': xv}, fetch_list=[loss])
        # BN in the eval clone normalizes with running stats: feeding a
        # SHIFTED batch changes the output mean (batch-stat BN would
        # renormalize it away)
        c, = exe.run(test_prog, feed={'x': xv + 5.0}, fetch_list=[loss])
        assert abs(np.asarray(c).ravel()[0] - np.asarray(a).ravel()[0]) > 1.0
