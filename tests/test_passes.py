"""Program-IR optimizing rewriter (core/passes): per-pass unit tests,
pipeline idempotence, PT_OPT/PT_OPT_SKIP env plumbing, bitwise training
parity vs the unoptimized lowering (run / run_steps / ParallelExecutor),
and saved-model round-trips of optimized programs."""
import json

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core import passes
from paddle_tpu.core.passes import shard


def _op_types(program):
    return [op.type for b in program.blocks for op in b.ops]


def _op_count(program):
    return sum(len(b.ops) for b in program.blocks)


# ------------------------------------------------------------------ dce

def test_dce_removes_dead_chain_keeps_live():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        live = fluid.layers.scale(x, scale=2.0)
        dead = fluid.layers.scale(x, scale=3.0)
        dead2 = fluid.layers.scale(dead, scale=4.0)  # noqa: F841
    opt, stats = passes.optimize_program(main, (live.name,),
                                         skip={'fuse_elementwise'})
    assert stats['passes']['dce']['ops_removed'] == 2
    assert _op_types(opt) == ['scale']


def test_dce_keeps_persistable_writes_and_side_effects():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.scale(x, scale=2.0)
        blk = main.global_block()
        p = blk.create_var(name='pstate', shape=(4,), dtype='float32',
                           persistable=True)
        blk.append_op(type='scale', inputs={'X': x}, outputs={'Out': p},
                      attrs={'scale': 1.0})
        blk.append_op(type='print', inputs={'X': x}, outputs={},
                      attrs={'message': 'hi'})
    opt, stats = passes.optimize_program(main, (out.name,),
                                         skip={'fuse_elementwise'})
    assert stats['passes']['dce']['ops_removed'] == 0
    assert sorted(_op_types(opt)) == ['print', 'scale', 'scale']


def test_dce_kill_on_overwrite():
    """A write fully overwritten before any read is dead (the sharper
    rule the analysis D005 reporter deliberately does not use)."""
    from paddle_tpu.core.framework import Operator
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.scale(x, scale=2.0)
        blk = main.global_block()
        # dead first write: out is rewritten from x before anyone reads it
        blk.ops.insert(1, Operator(blk, 'scale', inputs={'X': x},
                                   outputs={'Out': out},
                                   attrs={'scale': 9.0}))
    opt, stats = passes.optimize_program(main, (out.name,),
                                         skip={'fuse_elementwise'})
    assert stats['passes']['dce']['ops_removed'] == 1


# ----------------------------------------------------------- const fold

def test_const_fold_scale_cast_chain():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        c = fluid.layers.fill_constant([2], 'float32', 3.0)
        s = fluid.layers.scale(c, scale=2.0, bias=1.0)   # 7.0
        out = fluid.layers.cast(s, 'int32')              # 7
    opt, stats = passes.optimize_program(
        main, (out.name,), skip={'fuse_elementwise'})
    assert stats['passes']['const_fold']['ops_folded'] == 2
    # the whole chain is now ONE fill_constant producing the fetch
    assert _op_types(opt) == ['fill_constant']
    op = opt.global_block().ops[0]
    assert op.attrs['value'] == 7 and op.attrs['dtype'] == 'int32'
    assert op.output_names() == [out.name]


def test_const_fold_binary_and_negative():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        a = fluid.layers.fill_constant([2], 'float32', 3.0)
        b = fluid.layers.fill_constant([2], 'float32', 4.0)
        const_sum = a + b                      # foldable -> 7.0
        dyn = x + a                            # NOT foldable (x dynamic)
        out = dyn + const_sum
    opt, stats = passes.optimize_program(
        main, (out.name,), skip={'fuse_elementwise'})
    assert stats['passes']['const_fold']['ops_folded'] == 1
    types = _op_types(opt)
    assert types.count('elementwise_add') == 2  # dyn + out stay
    folded = [op for op in opt.global_block().ops
              if op.type == 'fill_constant' and
              op.attrs.get('value') == 7.0]
    assert len(folded) == 1


# ------------------------------------------------------------------ cse

def test_cse_dedupes_identical_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        a = fluid.layers.scale(x, scale=2.0)
        b = fluid.layers.scale(x, scale=2.0)   # duplicate of a
        c = fluid.layers.scale(x, scale=3.0)   # different attrs: kept
        out = (a + b) + c
    opt, stats = passes.optimize_program(
        main, (out.name,), skip={'fuse_elementwise'})
    assert stats['passes']['cse']['ops_removed'] == 1
    assert _op_types(opt).count('scale') == 2
    # the reader of b's output now reads a's
    add1 = [op for op in opt.global_block().ops
            if op.type == 'elementwise_add'][0]
    assert add1.inputs['X'] == add1.inputs['Y']


def test_cse_skips_rng_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        d1 = fluid.layers.dropout(x, dropout_prob=0.5)
        d2 = fluid.layers.dropout(x, dropout_prob=0.5)
        out = d1 + d2   # two DIFFERENT draws must stay two draws
    opt, stats = passes.optimize_program(
        main, (out.name,), skip={'fuse_elementwise'})
    assert stats['passes']['cse']['ops_removed'] == 0
    assert _op_types(opt).count('dropout') == 2


# ----------------------------------------------------------------- fuse

def test_fuse_chain_and_execution():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        h = fluid.layers.scale(x, scale=2.0, bias=1.0)
        h = fluid.layers.relu(h)
        out = fluid.layers.cast(h, 'float32')
    opt, stats = passes.optimize_program(main, (out.name,))
    assert stats['passes']['fuse_elementwise']['chains'] == 1
    assert stats['passes']['fuse_elementwise']['ops_fused'] == 3
    assert _op_types(opt) == ['fused_elementwise']
    fop = opt.global_block().ops[0]
    assert fop.attrs['out_names'] == [out.name]
    assert [s['type'] for s in fop.attrs['sub_ops']] == \
        ['scale', 'relu', 'cast']
    # source_loc points at the FIRST original op's model line, not here
    assert fop.source_loc is not None
    # and it executes: y = relu(2x+1)
    exe, scope = fluid.Executor(), fluid.Scope()
    xv = np.array([[-3.0, -0.5, 0.0, 2.0]], 'float32')
    with fluid.scope_guard(scope):
        exe.run(startup)
        yv, = exe.run(main, feed={'x': xv}, fetch_list=[out])
    np.testing.assert_array_equal(yv, np.maximum(2 * xv + 1, 0.0))


def test_fuse_escaping_intermediate_stays_fetchable():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        mid = fluid.layers.scale(x, scale=2.0)
        out = fluid.layers.relu(mid)
    opt, stats = passes.optimize_program(main, (mid.name, out.name))
    fop = opt.global_block().ops[0]
    assert sorted(fop.attrs['out_names']) == sorted([mid.name, out.name])
    exe, scope = fluid.Executor(), fluid.Scope()
    xv = np.array([[-1.0, 1.0, -2.0, 2.0]], 'float32')
    with fluid.scope_guard(scope):
        exe.run(startup)
        mv, ov = exe.run(main, feed={'x': xv}, fetch_list=[mid, out])
    np.testing.assert_array_equal(mv, 2 * xv)
    np.testing.assert_array_equal(ov, np.maximum(2 * xv, 0.0))


def test_fuse_parallel_optimizer_run_collapses():
    """Independent per-param updates are a DAG run, not a linear chain —
    they still fuse to one op (the transformer's 158 adam ops)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            h = fluid.layers.fc(x, 8, act='relu')
            loss = fluid.layers.mean(fluid.layers.fc(h, 1))
            fluid.optimizer.Adam(0.01).minimize(loss)
    raw_adams = _op_types(main).count('adam')
    assert raw_adams >= 4
    opt, stats = passes.optimize_program(main, (loss.name,))
    assert _op_types(opt).count('adam') == 0
    assert stats['op_count_opt'] < stats['op_count_raw']
    fused = [op for op in opt.global_block().ops
             if op.type == 'fused_elementwise']
    sub_types = [s['type'] for f in fused for s in f.attrs['sub_ops']]
    assert sub_types.count('adam') == raw_adams


def test_fused_sub_ops_are_jsonable():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    opt, _ = passes.optimize_program(main, (out.name,))
    from paddle_tpu import io as fluid_io
    json.dumps(fluid_io.program_to_desc(opt))  # must not raise


# ---------------------------------------------------------------- canon

def test_canon_narrows_int64_attrs():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        c = fluid.layers.fill_constant([2], 'int64', 5)
    opt, stats = passes.optimize_program(main, (c.name,))
    assert stats['passes']['canon']['attrs_narrowed'] >= 1
    ops = opt.global_block().ops
    (op,) = ops
    attrs = (op.attrs if op.type == 'fill_constant'
             else op.attrs['sub_ops'][0]['attrs'])
    assert attrs['dtype'] == 'int32'


def test_canon_dedupes_cross_block_initializers():
    """A loop-body fill_constant identical to a never-rebound root one
    rewrites to an `assign` of the root var (which traces to nothing) —
    the constant materializes once per program, not once per body.  The
    fuse pass is skipped so the initializers stay visible to canon (with
    fusion on, body constants get swallowed into fused ops instead)."""
    from paddle_tpu import layers
    i = layers.fill_constant(shape=[1], dtype='int64', value=0)
    n = layers.fill_constant(shape=[1], dtype='int64', value=3)
    k = layers.fill_constant(shape=[1], dtype='float32', value=2.5)
    total = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        # identical to k's initializer, inside the loop body
        dup = layers.fill_constant(shape=[1], dtype='float32', value=2.5)
        layers.assign(total + dup, total)
        layers.increment(i, 1)
        layers.less_than(i, n, cond=cond)
    main = fluid.default_main_program()
    opt, stats = passes.optimize_program(
        main, (total.name, k.name), skip={'fuse_elementwise'})
    assert stats['passes']['canon']['initializers_deduped'] == 1
    sub_types = [op.type for op in opt.blocks[1].ops]
    assert 'fill_constant' not in sub_types and 'assign' in sub_types
    # and the loop still computes 3 * 2.5
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        tv, = exe.run(main, fetch_list=[total])
    np.testing.assert_allclose(tv, [7.5], rtol=1e-6)


# ----------------------------------------------------- pipeline plumbing

def test_pipeline_idempotent():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            h = fluid.layers.fc(x, 8, act='relu')
            h = fluid.layers.dropout(h, dropout_prob=0.3)
            loss = fluid.layers.mean(fluid.layers.fc(h, 1))
            fluid.optimizer.Adam(0.01).minimize(loss)
    from paddle_tpu import io as fluid_io
    opt1, _ = passes.optimize_program(main, (loss.name,))
    opt2, stats2 = passes.optimize_program(opt1, (loss.name,))
    assert stats2['op_count_raw'] == stats2['op_count_opt']
    assert json.dumps(fluid_io.program_to_desc(opt1), sort_keys=True,
                      default=str) == \
        json.dumps(fluid_io.program_to_desc(opt2), sort_keys=True,
                   default=str)


def test_pt_opt_kill_switch(monkeypatch):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    monkeypatch.setenv('PT_OPT', '0')
    prog, stats = passes.maybe_optimize(main, (out.name,))
    assert prog is main and stats is None
    assert passes.config_token() == ('off',)


def test_pt_opt_skip_selectivity(monkeypatch):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        dead = fluid.layers.scale(x, scale=9.0)  # noqa: F841
        out = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    monkeypatch.setenv('PT_OPT_SKIP', 'fuse_elementwise')
    opt, stats = passes.maybe_optimize(main, (out.name,))
    assert 'fuse_elementwise' not in stats['passes']
    assert stats['passes']['dce']['ops_removed'] == 1   # dce still ran
    assert 'fused_elementwise' not in _op_types(opt)
    assert passes.config_token() == \
        ('on', 'fuse_elementwise') + shard.config_token()


def test_maybe_optimize_memoizes(monkeypatch):
    monkeypatch.delenv('PT_OPT', raising=False)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    p1, s1 = passes.maybe_optimize(main, (out.name,))
    p2, s2 = passes.maybe_optimize(main, (out.name,))
    assert p1 is p2 and s1 is s2
    main._bump()
    p3, _ = passes.maybe_optimize(main, (out.name,))
    assert p3 is not p1


# ------------------------------------------------------- bitwise parity

def _train_model(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            h = fluid.layers.dropout(h, dropout_prob=0.4)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.Adam(0.01).minimize(loss)
    return main, startup, loss


def _feeds(K, batch=6, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.randn(batch, 8).astype('float32'),
             'lbl': rng.randint(0, 4, (batch, 1)).astype('int64')}
            for _ in range(K)]


def _train(monkeypatch, pt_opt, runner):
    monkeypatch.setenv('PT_OPT', pt_opt)
    main, startup, loss = _train_model()
    losses, scope = runner(main, startup, loss)
    state = {n: np.asarray(v) for n, v in scope.vars.items()}
    return np.asarray(losses), state


def _assert_bitwise(monkeypatch, runner):
    l1, s1 = _train(monkeypatch, '1', runner)
    l0, s0 = _train(monkeypatch, '0', runner)
    np.testing.assert_array_equal(l1, l0)
    assert set(s1) == set(s0)
    for n in s1:   # params AND Adam moments, bit for bit
        np.testing.assert_array_equal(s1[n], s0[n], err_msg=n)


def test_bitwise_parity_run(monkeypatch):
    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [np.asarray(exe.run(main, feed=f,
                                         fetch_list=[loss])[0])
                      for f in _feeds(4)]
        return losses, scope
    _assert_bitwise(monkeypatch, runner)


def test_bitwise_parity_run_steps(monkeypatch):
    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            stacked, = exe.run_steps(main, feed_list=_feeds(4),
                                     fetch_list=[loss])
        return np.asarray(stacked), scope
    _assert_bitwise(monkeypatch, runner)


def test_bitwise_parity_parallel_executor(monkeypatch):
    from paddle_tpu.parallel.parallel_executor import ParallelExecutor

    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                                  scope=scope)
            losses = [np.asarray(pe.run([loss.name], feed=f)[0])
                      for f in _feeds(2, batch=8)]
        return losses, scope
    _assert_bitwise(monkeypatch, runner)


# -------------------------------------------------- saved-model roundtrip

def test_saved_model_roundtrip_of_optimized_program(tmp_path):
    from paddle_tpu import io as fluid_io
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            h = fluid.layers.fc(x, 8, act='relu')
            out = fluid.layers.scale(h, scale=0.5, bias=1.0)
    opt, stats = passes.optimize_program(main, (out.name,))
    assert 'fused_elementwise' in _op_types(opt)

    xv = np.random.RandomState(0).randn(3, 8).astype('float32')
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        want, = exe.run(main, feed={'x': xv}, fetch_list=[out])
        # save the OPTIMIZED program (fused ops serialize through their
        # JSON-able sub_ops attrs) and reload it into a fresh program
        fluid_io.save_inference_model(
            str(tmp_path), ['x'], [opt.global_block().var(out.name)],
            exe, main_program=opt)
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope2):
        prog2, feeds2, fetches2 = fluid_io.load_inference_model(
            str(tmp_path), exe2)
        got, = exe2.run(prog2, feed={'x': xv}, fetch_list=fetches2)
    np.testing.assert_array_equal(want, got)


def test_program_lint_optimize_flag():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        dead = fluid.layers.scale(x, scale=9.0)  # noqa: F841
        out = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    raw = main.lint(feed_names=('x',), fetch_list=[out])
    assert any(d.code == 'D005' for d in raw)       # dead op visible
    opted = main.lint(feed_names=('x',), fetch_list=[out], optimize=True)
    assert not any(d.code == 'D005' for d in opted)  # rewriter removed it
    assert not opted.errors                          # fused program clean


def test_retrace_explainer_names_pt_opt_toggle(monkeypatch):
    import paddle_tpu.observability as obs
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        out = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    exe, scope = fluid.Executor(), fluid.Scope()
    xv = np.ones((2, 4), 'float32')
    obs.explainer().reset()
    with fluid.scope_guard(scope):
        exe.run(startup)
        monkeypatch.setenv('PT_OPT', '1')
        exe.run(main, feed={'x': xv}, fetch_list=[out])
        monkeypatch.setenv('PT_OPT', '0')
        exe.run(main, feed={'x': xv}, fetch_list=[out])
    rep = obs.explainer().last_report()
    assert rep['kind'] == 'retrace'
    assert any('PT_OPT' in d for d in rep['details'])
