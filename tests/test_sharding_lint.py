"""PR-19 sharding-aware analyzer: first-class sharding attrs (IR +
desc round-trips + version bumps), the sharding/memplan/donation lint
passes (D017..D021), the `pt_lint --memplan` surface, and the serving
generation zoo entries (docs/analysis.md)."""
import json
import os
import sys

import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.sharding import (normalize_spec, spec_axes,
                                      spec_divisor, spec_from_jsonable,
                                      spec_to_jsonable)
from paddle_tpu.io import desc_to_program, program_to_desc

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', 'tools'))
import pt_lint  # noqa: E402


def _codes(result):
    return set(result.codes())


def _by_code(result, code):
    return [d for d in result if d.code == code]


# ------------------------------------------------ core/sharding helpers

def test_spec_helpers():
    assert normalize_spec(None) is None
    assert normalize_spec('data') == ('data',)
    assert normalize_spec(['data', None]) == ('data', None)
    assert normalize_spec((('data', 'model'), None)) == \
        (('data', 'model'), None)
    from jax.sharding import PartitionSpec as P
    assert normalize_spec(P('model', None)) == ('model', None)
    with pytest.raises(TypeError):
        normalize_spec([3])
    spec = (('data', 'model'), None, 'seq')
    assert spec_from_jsonable(spec_to_jsonable(spec)) == spec
    assert spec_to_jsonable(None) is None
    assert spec_axes(spec) == {'data', 'model', 'seq'}
    assert spec_divisor(spec, {'data': 4, 'model': 2, 'seq': 2}) == 16
    assert spec_divisor(spec, None) == 1
    assert spec_divisor((None,), {'data': 4}) == 1


# ------------------------------------- first-class attrs + version bumps

def test_variable_sharding_syncs_program_table():
    from jax.sharding import PartitionSpec as P
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[8], dtype='float32')
    v0 = prog._version
    x.sharding = ('data', None)
    assert prog._version > v0
    assert x.sharding == ('data', None)
    assert prog._sharding['x'] == P('data', None)
    # set_sharding delegates to the var when it exists
    prog.set_sharding('x', P(None, 'model'))
    assert x.sharding == (None, 'model')
    # clearing pops the legacy table too
    x.sharding = None
    assert 'x' not in prog._sharding


def test_attr_mutation_bumps_version():
    """Satellite: in-place Operator/Variable attr mutation must bump the
    program version so lint memoization stays sound."""
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.relu(x)
    op = y.op
    v = prog._version
    op.attrs['alpha'] = 1.0            # raw in-place set, not _set_attr
    assert prog._version > v
    v = prog._version
    op.attrs['alpha'] = 1.0            # identical value: no bump
    assert prog._version == v
    op.attrs.setdefault('alpha', 2.0)  # present key: no bump
    assert prog._version == v
    op.attrs.pop('alpha')
    assert prog._version > v
    v = prog._version
    op.attrs.pop('alpha', None)        # absent key: no bump
    assert prog._version == v
    for mutate in (lambda: setattr(x, 'shape', (-1, 9)),
                   lambda: setattr(x, 'persistable', True),
                   lambda: setattr(x, 'stop_gradient', True),
                   lambda: setattr(x, 'dtype', 'float32')):
        v = prog._version
        mutate()
        assert prog._version > v


def test_lint_memo_invalidated_by_inplace_attr_mutation():
    """Regression: Program.lint via apply_lint_policy memoizes on
    _version — an in-place attr edit must invalidate it."""
    from paddle_tpu.analysis import apply_lint_policy
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.relu(x)
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        r1 = apply_lint_policy(prog, feed_names=('x',),
                               fetch_names=(y.name,), mode='warn')
        assert 'D002' not in _codes(r1)
        # break the op in place: unknown type would previously serve
        # the stale memoized clean result
        y.op.type = 'not_a_real_op'
        y.op.attrs['broken'] = 1  # in-place attr bump
        r2 = apply_lint_policy(prog, feed_names=('x',),
                               fetch_names=(y.name,), mode='warn')
    assert r2 is not r1
    assert 'D002' in _codes(r2)


# ------------------------------------------------------ desc round-trip

def _annotated_program():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[4, 8], dtype='float32')
        w = layers.create_parameter([8, 8], 'float32', name='w_rt')
        y = layers.fc(x, size=8, param_attr=fluid.ParamAttr(name='fc_rt'),
                      bias_attr=False)
    x.sharding = (None, 'data', None)
    w.sharding = (None, ('model', 'data'))
    prog.set_mesh_axes({'data': 2, 'model': 4})
    prog.set_device_limit(1 << 30)
    prog.set_kv_plan(slots=2, layers=1, kv_heads=2, max_len=8,
                     head_dim=4)
    return prog, y


def test_desc_roundtrip_sharding_byte_identical():
    prog, _ = _annotated_program()
    d1 = program_to_desc(prog)
    prog2 = desc_to_program(json.loads(json.dumps(d1)))
    d2 = program_to_desc(prog2)
    assert json.dumps(d1, sort_keys=True) == json.dumps(d2,
                                                        sort_keys=True)
    b2 = prog2.global_block()
    assert b2.var('x').sharding == (None, 'data', None)
    assert b2.var('w_rt').sharding == (None, ('model', 'data'))
    from jax.sharding import PartitionSpec as P
    assert prog2._sharding['w_rt'] == P(None, ('model', 'data'))
    assert prog2.mesh_axes() == {'data': 2, 'model': 4}
    assert prog2._device_limit_bytes == 1 << 30
    assert prog2._kv_plan['slots'] == 2


def test_old_desc_without_sharding_loads_clean():
    """A desc written before sharding attrs existed loads with empty
    specs and introduces zero new diagnostics."""
    base = fluid.Program()
    with fluid.program_guard(base, fluid.Program()):
        bx = layers.data('x', shape=[4, 8], dtype='float32')
        layers.create_parameter([8, 8], 'float32', name='w_rt')
        by = layers.fc(bx, size=8,
                       param_attr=fluid.ParamAttr(name='fc_rt'),
                       bias_attr=False)
    desc = program_to_desc(base)
    # simulate the pre-PR-19 on-disk shape: strip the new keys entirely
    for key in ('mesh_axes', 'device_limit_bytes', 'kv_plan'):
        desc.pop(key)
    for bd in desc['blocks']:
        for vd in bd['vars']:
            vd.pop('sharding')
    old = desc_to_program(desc)
    assert all(v.sharding is None for v in old.list_vars())
    assert old._sharding == {}
    assert old.mesh_axes() is None
    ref = base.lint(feed_names=('x',), fetch_list=[by.name])
    got = old.lint(feed_names=('x',), fetch_list=[by.name])
    assert _codes(got) <= _codes(ref)
    assert not _by_code(got, 'D017') and not _by_code(got, 'D018') \
        and not _by_code(got, 'D019') and not _by_code(got, 'D020') \
        and not _by_code(got, 'D021')


def test_clone_carries_sharding_state():
    prog, _ = _annotated_program()
    c = prog.clone()
    assert c.global_block().var('x').sharding == (None, 'data', None)
    assert c.mesh_axes() == {'data': 2, 'model': 4}
    assert c._device_limit_bytes == 1 << 30
    assert c._kv_plan == prog._kv_plan and c._kv_plan is not prog._kv_plan


# ------------------------------------------------- the sharding pass

def _mesh_prog():
    prog = fluid.Program()
    guard = fluid.program_guard(prog, fluid.Program())
    prog.set_mesh_axes({'data': 2, 'model': 2})
    return prog, guard


def test_d019_mesh_axis_typo_and_quiet_without_mesh():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.relu(x)
    x.sharding = (None, 'modle')
    res = prog.lint(feed_names=('x',), fetch_list=[y])
    assert not _by_code(res, 'D019')       # no mesh declared: quiet
    prog.set_mesh_axes({'data': 2, 'model': 2})
    res = prog.lint(feed_names=('x',), fetch_list=[y])
    d = _by_code(res, 'D019')
    assert len(d) == 1 and d[0].severity == 'error'
    assert 'modle' in d[0].message
    assert 'model' in (d[0].fixit or '')   # did-you-mean


def test_d018_reshard_between_inputs_and_declared():
    prog, guard = _mesh_prog()
    with guard:
        a = layers.data('a', shape=[16], dtype='float32')
        b = layers.data('b', shape=[16], dtype='float32')
        s = a + b
        out = layers.reduce_sum(s)
    a.sharding = (None, 'data')
    b.sharding = (None, 'model')
    res = prog.lint(feed_names=('a', 'b'), fetch_list=[out])
    d = _by_code(res, 'D018')
    assert d and d[0].op_type == 'elementwise_add'
    assert 'bytes' in d[0].message and d[0].source_loc
    # declared-vs-delivered: annotate the sum's output differently
    s.sharding = ('data', None)
    res = prog.lint(feed_names=('a', 'b'), fetch_list=[out])
    assert any(s.name == x.var for x in _by_code(res, 'D018'))


def test_d017_conflicting_producers_and_rank_overflow():
    prog, guard = _mesh_prog()
    with guard:
        a = layers.data('a', shape=[16], dtype='float32')
        b = layers.data('b', shape=[16], dtype='float32')
        blk = prog.global_block()
        c = blk.create_var(name='c', dtype='float32')
        c.shape = (-1, 16)
        blk.append_op(type='assign', inputs={'X': a}, outputs={'Out': c})
        blk.append_op(type='assign', inputs={'X': b}, outputs={'Out': c})
        out = layers.reduce_sum(a + b)
    a.sharding = (None, 'data')
    b.sharding = (None, 'model')
    res = prog.lint(feed_names=('a', 'b'), fetch_list=[out, 'c'])
    d = _by_code(res, 'D017')
    assert d and d[0].severity == 'error' and d[0].var == 'c'
    assert d[0].op_index is not None and d[0].source_loc
    # rank overflow form
    a.sharding = ('data', None, 'model')   # rank-2 var, 3 entries
    res = prog.lint(feed_names=('a', 'b'), fetch_list=[out])
    assert any('rank' in x.message for x in _by_code(res, 'D017'))


def test_sharding_propagates_through_backward():
    """Grads inherit their parameter's spec through __backward__, so an
    annotated training program lints without false conflicts."""
    import paddle_tpu.models.simple as simple
    prog, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, start):
        m = simple.fit_a_line()
        opt = fluid.optimizer.SGD(learning_rate=0.01)
        opt.minimize(m['loss'])
    prog.set_mesh_axes({'data': 2, 'model': 2})
    for p in prog.all_parameters():
        if len(p.shape or ()) == 2:
            prog.set_sharding(p.name, (None, 'model'))
    res = prog.lint(feed_names=('x', 'y'), fetch_list=[m['loss']])
    assert not _by_code(res, 'D017') and not _by_code(res, 'D019')


# ---------------------------------------------------- the memplan pass

def test_memplan_accounting_and_d020():
    from paddle_tpu.analysis.passes.memplan import plan_memory
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = layers.data('x', shape=[16], dtype='float32')
        layers.create_parameter([256, 256], 'float32', name='big_w')
        y = layers.relu(x)
    plan = plan_memory(prog, feed_names=('x',), fetch_names=(y.name,))
    assert plan.params_bytes == 256 * 256 * 4
    assert plan.activation_peak_bytes > 0
    assert plan.kv_pool_bytes == 0
    assert plan.to_dict()['total_bytes'] == plan.total_bytes
    # kv plan folds CacheConfig bytes in
    prog.set_kv_plan(slots=2, layers=2, kv_heads=2, max_len=8,
                     head_dim=4)
    from paddle_tpu.serving.generation.kv_cache import CacheConfig
    plan = plan_memory(prog, feed_names=('x',), fetch_names=(y.name,))
    assert plan.kv_pool_bytes == CacheConfig(
        slots=2, layers=2, kv_heads=2, max_len=8, head_dim=4).bytes()
    # sharding divides the parameter contribution
    prog.set_mesh_axes({'model': 4})
    prog.set_sharding('big_w', (None, 'model'))
    sharded = plan_memory(prog, feed_names=('x',),
                          fetch_names=(y.name,))
    assert sharded.params_bytes == plan.params_bytes // 4
    # D020 fires only over the declared limit
    res = prog.lint(feed_names=('x',), fetch_list=[y])
    assert not _by_code(res, 'D020')
    prog.set_device_limit(1024)
    res = prog.lint(feed_names=('x',), fetch_list=[y])
    d = _by_code(res, 'D020')
    assert len(d) == 1 and d[0].severity == 'error'
    assert 'big_w' in d[0].message
    prog.set_device_limit(1 << 40)
    res = prog.lint(feed_names=('x',), fetch_list=[y])
    assert not _by_code(res, 'D020')


# --------------------------------------------------- the donation pass

def test_d021_host_feed_and_fetched_param():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        w = layers.create_parameter([8], 'float32', name='w_d21')
        blk = prog.global_block()
        blk.append_op(type='assign', inputs={'X': w},
                      outputs={'Out': w})
        x = layers.data('x', shape=[8], dtype='float32')
        out = layers.reduce_sum(x + w)
    res = prog.lint(feed_names=('x', 'w_d21'),
                    fetch_list=[out, 'w_d21'])
    d = _by_code(res, 'D021')
    assert len(d) == 2 and all(x.severity == 'warning' for x in d)
    msgs = ' '.join(x.message for x in d)
    assert 'host-owned feed' in msgs and 'fetched' in msgs
    assert all(x.op_index is not None for x in d)
    # neither form present -> quiet
    res = prog.lint(feed_names=('x',), fetch_list=[out])
    assert not _by_code(res, 'D021')


def test_d021_quiet_without_writeback():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        w = layers.create_parameter([8], 'float32', name='w_nd')
        x = layers.data('x', shape=[8], dtype='float32')
        out = layers.reduce_sum(x + w)
    # no writeback -> no donation -> feeding/fetching the param is safe
    res = prog.lint(feed_names=('x', 'w_nd'), fetch_list=[out, 'w_nd'])
    assert not _by_code(res, 'D021')


# ------------------------------------------------- the acceptance program

def test_acceptance_program_reports_all_five_codes():
    """One program with a deliberate sharding conflict, implicit
    reshard, mesh-axis typo, over-budget KV+param footprint, and a
    host-array-into-donating-executable path: exactly D017..D021 fire
    (plus pre-existing codes), each with an op anchor + source_loc."""
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        a = layers.data('a', shape=[16], dtype='float32')
        b = layers.data('b', shape=[16], dtype='float32')
        s = a + b                                     # D018
        blk = prog.global_block()
        c = blk.create_var(name='c', dtype='float32')
        c.shape = (-1, 16)
        blk.append_op(type='assign', inputs={'X': a}, outputs={'Out': c})
        blk.append_op(type='assign', inputs={'X': b}, outputs={'Out': c})
        w = layers.create_parameter([64, 64], 'float32', name='w_acc')
        blk.append_op(type='assign', inputs={'X': w}, outputs={'Out': w})
        t = blk.create_var(name='t', dtype='float32')
        t.shape = (-1, 16)
        blk.append_op(type='assign', inputs={'X': s}, outputs={'Out': t})
        out = layers.reduce_sum(t)
    prog.set_mesh_axes({'data': 2, 'model': 2})
    blk = prog.global_block()
    blk.var('a').sharding = (None, 'data')
    blk.var('b').sharding = (None, 'model')
    blk.var('w_acc').sharding = (None, 'modle')       # D019 typo
    prog.set_kv_plan(slots=8, layers=4, kv_heads=4, max_len=128,
                     head_dim=32)
    prog.set_device_limit(4096)                        # D020
    res = prog.lint(feed_names=('a', 'b', 'w_acc'),
                    fetch_list=[out, 'c'])
    codes = _codes(res)
    assert {'D017', 'D018', 'D019', 'D020', 'D021'} <= codes
    for code in ('D017', 'D018', 'D020', 'D021'):
        d = _by_code(res, code)[0]
        assert d.op_type is not None and d.op_index is not None
        assert d.source_loc, code
    assert _by_code(res, 'D019')[0].var == 'w_acc'
    assert _by_code(res, 'D020')[0].message.count('kv pool')


# ------------------------------------------- zoo + CLI memplan surface

@pytest.mark.parametrize('name', ['llama_prefill', 'llama_decode'])
def test_generation_zoo_entries_lint_clean(name):
    prog, feeds, fetches = pt_lint._zoo_entry(name)()
    assert feeds == ['tokens'] and fetches
    res = prog.lint(feed_names=feeds, fetch_list=fetches)
    assert not res.errors, res.render('error')
    if name == 'llama_decode':
        assert prog._kv_plan is not None
        plan = prog._last_memplan
        assert plan.kv_pool_bytes > 0
    assert name in pt_lint.builtin_names()


def test_pt_lint_memplan_json_shape():
    from paddle_tpu.analysis.diagnostics import (DIAG_JSON_KEYS,
                                                 RESULT_JSON_KEYS)
    from paddle_tpu.analysis.passes.memplan import MEMPLAN_JSON_KEYS
    import contextlib
    import io as _io
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = pt_lint.main(['--builtin', 'llama_decode', '--json',
                           '--memplan'])
    assert rc == 0
    out = json.loads(buf.getvalue())
    res = out['results']['builtin:llama_decode']
    assert set(res) - {'memplan'} == set(RESULT_JSON_KEYS)
    assert set(res['memplan']) == set(MEMPLAN_JSON_KEYS)
    assert res['memplan']['kv_pool_bytes'] > 0
    for d in res['diagnostics']:
        assert set(d) == set(DIAG_JSON_KEYS)
