"""Test config: force CPU backend with 8 virtual devices BEFORE jax import,
so multi-chip sharding paths are exercised without TPU hardware."""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'  # override axon/tpu from the outer env
# cold caches by default: trace-count and retrace-explainer assertions
# depend on every signature actually compiling; warm-start tests opt back
# in with an explicit PT_CACHE_DIR (see tests/test_compile_cache.py)
os.environ.setdefault('PT_CACHE', '0')
# no timed autotune searches inside tests: plan builds use cached/default
# block choices so kernel-execution counts stay deterministic (the
# autotuner's own tests opt back in with PT_AUTOTUNE=1)
os.environ.setdefault('PT_AUTOTUNE', 'cached')
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

# pytest plugins (jaxtyping) import jax before this conftest runs, so the
# env var alone is too late — force the config directly.
jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs + scope + name generator."""
    import paddle_tpu as fluid  # noqa: F401 - warm the package once
    from paddle_tpu.core import framework, unique_name
    from paddle_tpu.core import executor as executor_mod
    main, startup = framework.Program(), framework.Program()
    old_main = framework.switch_main_program(main)
    old_startup = framework.switch_startup_program(startup)
    old_gen = unique_name.switch()
    old_scope = executor_mod._global_scope
    executor_mod._global_scope = executor_mod.Scope()
    yield
    framework.switch_main_program(old_main)
    framework.switch_startup_program(old_startup)
    unique_name.switch(old_gen)
    executor_mod._global_scope = old_scope
