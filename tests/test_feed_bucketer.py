"""FeedBucketer parity + ragged-tail routing.

The contract: a padded-and-masked (bucketed) feed must produce the SAME
loss and the SAME parameter updates as the exact-shape feed — the mask
zeroes every padded row out of the loss and out of every gradient — while
collapsing arbitrary batch/sequence raggedness onto a handful of compile
signatures.  Ragged run_steps tails route through the single-step
executable instead of lowering a per-tail-length scan.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.core import executor as executor_mod
from paddle_tpu.data_feeder import FeedBucketer


def _masked_model(seed=5):
    """Linear regression with the mask threaded through the loss
    reduction: loss = sum(per_example * mask) / sum(mask)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[3], dtype='float32')
            y = fluid.layers.data('y', shape=[1], dtype='float32')
            m = fluid.layers.data('valid', shape=[1], dtype='float32')
            pred = fluid.layers.fc(x, 1)
            per = fluid.layers.square(pred - y)
            loss = fluid.layers.reduce_sum(per * m) / \
                fluid.layers.reduce_sum(m)
            fluid.optimizer.SGDOptimizer(0.2).minimize(loss)
    return main, startup, loss


def _batch(b, seed=0):
    rng = np.random.RandomState(seed)
    return {'x': rng.rand(b, 3).astype('float32'),
            'y': rng.rand(b, 1).astype('float32'),
            'valid': np.ones((b, 1), 'float32')}


def _train(feeds, steps_api=False):
    main, startup, loss = _masked_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        if steps_api:
            losses, = exe.run_steps(main, feed_list=feeds,
                                    fetch_list=[loss])
            losses = [losses[i] for i in range(len(feeds))]
        else:
            losses = [exe.run(main, feed=f, fetch_list=[loss])[0]
                      for f in feeds]
    return np.asarray(losses).ravel(), {
        n: np.asarray(v) for n, v in scope.vars.items()}, exe


def test_bucketed_ragged_batch_matches_exact_loss_and_grads():
    feeds = [_batch(8, 0), _batch(8, 1), _batch(5, 2)]   # ragged tail
    ref_losses, ref_params, _ = _train(feeds)

    b = FeedBucketer(boundaries=[8], mask_name='valid')
    bucketed = [b.bucket_feed({k: v for k, v in f.items()
                               if k != 'valid'})[0] for f in feeds]
    assert all(f['x'].shape == (8, 3) for f in bucketed), \
        'every batch must land on the 8-bucket'
    got_losses, got_params, exe = _train(bucketed)

    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5, atol=1e-6)
    for n in ref_params:
        np.testing.assert_allclose(got_params[n], ref_params[n],
                                   rtol=1e-5, atol=1e-6, err_msg=n)
    # the whole ragged sequence fit ONE compile signature
    assert len(exe._cache) == 2   # startup + train step


def test_bucketed_feeds_through_run_steps():
    """Padded tail inside a fused K-step launch: same losses and params
    as the exact-shape sequential runs."""
    feeds = [_batch(8, 0), _batch(8, 1), _batch(6, 2)]
    ref_losses, ref_params, _ = _train(feeds)

    b = FeedBucketer(boundaries=[8], mask_name='valid')
    bucketed = [b.bucket_feed({k: v for k, v in f.items()
                               if k != 'valid'})[0] for f in feeds]
    got_losses, got_params, _ = _train(bucketed, steps_api=True)

    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5, atol=1e-6)
    for n in ref_params:
        np.testing.assert_allclose(got_params[n], ref_params[n],
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_sequence_tail_bucketing_parity():
    """Padding the time axis beyond the LoD lengths must not change
    length-masked sequence reductions."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            s = fluid.layers.data('s', shape=[2], dtype='float32',
                                  lod_level=1)
            pooled = fluid.layers.sequence_pool(s, 'sum')
    from paddle_tpu.core.lod import create_lod_tensor
    seqs = [np.arange(6, dtype='float32').reshape(3, 2),
            np.arange(10, dtype='float32').reshape(5, 2)]
    lod = create_lod_tensor(seqs)

    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ref, = exe.run(main, feed={'s': lod}, fetch_list=[pooled])
        b = FeedBucketer(boundaries=[4, 8], seq_names=('s',))
        padded_feed, real = b.bucket_feed({'s': lod})
        assert padded_feed['s'].padded.shape == (4, 8, 2)  # B 2->4, T 5->8
        got, = exe.run(main, feed=padded_feed, fetch_list=[pooled])
    # per-row pooled sums on the REAL rows must agree exactly: the time
    # padding sits beyond the true lengths, which sequence ops mask by,
    # and trim() drops the edge-replicated pad rows
    assert real == 2
    got_real, = FeedBucketer.trim([got], real)
    np.testing.assert_allclose(got_real, np.asarray(ref), rtol=1e-6)


def test_run_steps_ragged_tail_splits_instead_of_retracing():
    """After a K-step scan is compiled, a smaller-K launch (the classic
    epoch tail) must NOT lower a new scan: it splits into single-step
    launches, compiling at most the (reusable) single-step executable."""
    main, startup, loss = _masked_model()
    feeds8 = [_batch(8, i) for i in range(8)]
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        before = obs.counters().get('executor.tail_splits') or 0
        exe.run_steps(main, feed_list=feeds8[:4], fetch_list=[loss])
        tc = executor_mod._TRACE_COUNT[0]
        # tail of 3: splits, compiles ONE single-step executable
        exe.run_steps(main, feed_list=feeds8[4:7], fetch_list=[loss])
        assert executor_mod._TRACE_COUNT[0] == tc + 1
        # tail of 1: reuses that same single-step executable — NO trace
        exe.run_steps(main, feed_list=feeds8[7:], fetch_list=[loss])
        assert executor_mod._TRACE_COUNT[0] == tc + 1
    assert (obs.counters().get('executor.tail_splits') or 0) == before + 2


def test_run_steps_tail_split_is_bitwise_identical():
    """Split-tail results must be bitwise the fused-scan / sequential
    results (PR 1's RNG-counter contract extends to the split path)."""
    main, startup, loss = _masked_model()
    feeds = [_batch(4, i) for i in range(5)]

    # sequential reference
    exe1, scope1 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope1):
        exe1.run(startup)
        ref = [np.asarray(exe1.run(main, feed=f, fetch_list=[loss])[0])
               for f in feeds]

    # fused 3 + tail 2 (the tail splits)
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup)
        a, = exe2.run_steps(main, feed_list=feeds[:3], fetch_list=[loss])
        b, = exe2.run_steps(main, feed_list=feeds[3:], fetch_list=[loss])
    got = np.concatenate([np.asarray(a).ravel(), np.asarray(b).ravel()])
    assert got.tobytes() == np.asarray(ref).ravel().tobytes()
    for n in scope1.vars:
        assert np.asarray(scope1.vars[n]).tobytes() == \
            np.asarray(scope2.vars[n]).tobytes(), n


def test_tail_split_disabled_by_env(monkeypatch):
    monkeypatch.setenv('PT_TAIL_SPLIT', '0')
    main, startup, loss = _masked_model()
    feeds = [_batch(4, i) for i in range(5)]
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feed_list=feeds[:3], fetch_list=[loss])
        tc = executor_mod._TRACE_COUNT[0]
        exe.run_steps(main, feed_list=feeds[3:], fetch_list=[loss])
        # kill switch restores the per-tail-length scan lowering
        assert executor_mod._TRACE_COUNT[0] == tc + 1
        assert (obs.counters().get('executor.tail_splits') or 0) == 0 or \
            True  # counter may carry over from other tests; trace is the pin


def test_bucketer_pad_waste_metrics():
    obs.reset()
    b = FeedBucketer(boundaries=[8], mask_name='m')
    b.bucket_feed(_batch(5))
    c = obs.counters()
    assert c.get('bucketer.batches') == 1
    assert c.get('bucketer.rows_real') == 5
    assert c.get('bucketer.rows_pad') == 3
    assert abs(c.get('bucketer.pad_waste') - 3.0 / 8.0) < 1e-9


def test_retrace_explainer_marks_shape_only_retraces_bucketable():
    main, startup, loss = _masked_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_batch(8), fetch_list=[loss])
        exe.run(main, feed=_batch(5), fetch_list=[loss])   # ragged retrace
    rep = obs.explainer().last_report()
    assert rep['kind'] == 'retrace'
    assert any('bucketable' in d for d in rep['details']), rep['details']


def test_bucketer_trim_and_boundary_overflow():
    b = FeedBucketer(boundaries=[4, 8])
    assert b.boundary(3) == 4 and b.boundary(8) == 8 and b.boundary(9) == 16
    fetches = [np.arange(8), np.float32(1.0)]
    trimmed = FeedBucketer.trim(fetches, 5)
    assert trimmed[0].shape == (5,) and trimmed[1] == np.float32(1.0)
    with pytest.raises(ValueError):
        FeedBucketer(boundaries=[0])
