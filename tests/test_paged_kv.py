"""Paged + quantized KV cache, shared-prefix caching, speculative
decode (paddle_tpu/serving/generation/): PagePool refcounting and
eviction, PrefixCache chain keys, paged multi-page parity against the
dense reference, int8 parity budget with greedy stream equality,
prefix-hit and speculative streams pinned BITWISE against cold/plain
decode, and the two kv_oom surfaces (admission backpressure stays
queued; mid-stream exhaustion is a terminal error, never truncation)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu.observability as obs
from paddle_tpu.observability import tracing
from paddle_tpu.serving.engine import ServingConfig
from paddle_tpu.serving.generation import (CacheConfig, DecodeRuntime,
                                           GenerationConfig,
                                           GenerationEngine, PagePool,
                                           PrefixCache, SamplingParams,
                                           default_page_len,
                                           dense_reference)
from paddle_tpu.serving.generation.decode import random_weights
from paddle_tpu.serving.generation.sampling import draft_ngram
from paddle_tpu.testing import faults

CFG = dict(vocab=64, d_model=32, n_layer=2, n_head=4, n_kv_head=2,
           d_ffn=64, theta=10000.0, max_len=32)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()
    tracing.reset()


def _cfg(slots=2, page_len=4, pages=None, quant='none'):
    return CacheConfig(slots=slots, layers=2, kv_heads=2, max_len=32,
                       head_dim=8, page_len=page_len, pages=pages,
                       quant=quant)


def _rt(slots=2, page_len=4, **kw):
    kw.setdefault('prefill_chunk', 4)
    return DecodeRuntime(random_weights(CFG, seed=0), CFG, slots=slots,
                         page_len=page_len, **kw)


def _cnt(name):
    return int(obs.counters().get(name) or 0)


# ----------------------------------------------------------- page pool

def test_default_page_len_largest_divisor_up_to_8():
    assert default_page_len(32) == 8
    assert default_page_len(24) == 8
    assert default_page_len(20) == 5
    assert default_page_len(7) == 7


def test_page_pool_alloc_lowest_first_all_or_nothing():
    pool = PagePool(_cfg(pages=6))        # pages 1..5 allocatable
    assert pool.capacity == 5
    a = pool.alloc(3)
    assert a == [1, 2, 3]                 # page 0 reserved, lowest first
    assert pool.alloc(3) is None          # 2 free < 3: all-or-nothing
    assert pool.in_use() == 3             # the failed alloc leaked nothing
    b = pool.alloc(2)
    assert b == [4, 5]
    pool.release(a)
    pool.release(b)
    assert pool.free_count() == 5
    assert pool.alloc(0) == []


def test_page_pool_refcounts_shared_pages():
    pool = PagePool(_cfg(pages=4))
    pages = pool.alloc(2)
    pool.retain(pages)                    # second holder (prefix share)
    pool.release(pages)
    assert pool.in_use() == 2             # survives the first release
    assert pool.refcount(pages[0]) == 1
    pool.release(pages)
    assert pool.in_use() == 0
    with pytest.raises(ValueError, match='release of free'):
        pool.release(pages)
    with pytest.raises(ValueError, match='retain of unallocated'):
        pool.retain([3])


def test_page_pool_evict_callback_frees_under_pressure():
    pool = PagePool(_cfg(pages=4))        # 3 allocatable
    held = [pool.alloc(1), pool.alloc(1), pool.alloc(1)]

    def evict():
        if held:
            pool.release(held.pop(0))
            return True
        return False

    assert pool.alloc(2) is None          # no evictor: exhausted
    got = pool.alloc(2, evict=evict)      # evictor drains oldest holds
    assert got is not None and len(got) == 2
    assert len(held) == 1                 # exactly as many evictions as needed


def test_page_pool_kv_oom_fault_site_forces_exhaustion():
    assert 'kv_oom' in faults.SITES
    pool = PagePool(_cfg(pages=6))
    faults.configure('kv_oom:at=1:times=1')
    assert pool.alloc(1) is None          # injected exhaustion
    got = pool.alloc(1)                   # budget spent: pool recovers
    assert got == [1]


# --------------------------------------------------------- prefix cache

def test_prefix_cache_chain_match_insert_evict():
    pool = PagePool(_cfg(pages=8))
    pc = PrefixCache(pool, page_len=4)
    prompt = np.arange(1, 13, dtype=np.int32)       # 12 tokens = 3 pages
    pages = pool.alloc(3)
    h0 = _cnt('generation.prefix_inserts')
    assert pc.insert(prompt, pages) == 3            # depths 1, 2, 3
    assert len(pc) == 3
    assert _cnt('generation.prefix_inserts') == h0 + 3
    # a prompt sharing 2 pages + fresh tail hits depth 2, retained for us
    other = np.concatenate([prompt[:8], [60, 61, 62]]).astype(np.int32)
    hits0 = _cnt('generation.prefix_hits')
    got = pc.match(other)
    assert got == pages[:2]
    assert _cnt('generation.prefix_hits') == hits0 + 1
    # holders of page 1: the original alloc, one per chain entry that
    # includes it (depths 1..3), and the match we just took
    assert pool.refcount(pages[0]) == 5
    pool.release(got)
    # a diverging prompt misses entirely
    assert pc.match(np.asarray([9, 9, 9, 9, 9, 9], np.int32)) == []
    # matching never covers the whole prompt: one suffix token must
    # prefill to produce the first-token logits
    assert pc.match(prompt[:4]) == []
    one = pc.match(prompt[:5])
    assert one == pages[:1]
    pool.release(one)
    # FIFO eviction drops the oldest entry; reset drains the rest
    ev0 = _cnt('generation.prefix_evictions')
    assert pc.evict_one()
    assert len(pc) == 2
    assert _cnt('generation.prefix_evictions') == ev0 + 1
    pc.reset()
    assert len(pc) == 0
    pool.release(pages)                   # the original stream's hold
    assert pool.in_use() == 0


# ------------------------------------------------- paged decode parity

def test_multipage_prefill_matches_dense_reference():
    # 10 tokens over page_len=4 spans 3 pages — the gather/scatter must
    # follow the block table, not page 0
    rt = _rt(page_len=4)
    prompt = (np.arange(1, 11) * 3 % 63 + 1).astype(np.int32)
    slot = rt.alloc_slot()
    assert rt.ensure_capacity(slot, prompt.size)
    logits = None
    for off in range(0, prompt.size, rt.prefill_chunk):
        _, logits = rt.prefill(slot, prompt[off:off + rt.prefill_chunk],
                               off, SamplingParams())
    kref, vref, lref = dense_reference(rt.w, CFG, prompt)
    krow, vrow, length = rt.cache_row(slot)
    assert length == prompt.size
    # the slot's pages are non-contiguous in the pool by construction
    assert len(rt.owned[slot]) == 3
    np.testing.assert_allclose(krow[:, :, :prompt.size], kref, atol=1e-5)
    np.testing.assert_allclose(vrow[:, :, :prompt.size], vref, atol=1e-5)
    np.testing.assert_allclose(logits, lref, atol=1e-5)
    rt.free_slot(slot)
    assert rt.pool.in_use() == 0


def test_int8_quant_greedy_stream_equal_and_logit_budget():
    prompt = [1, 5, 9, 2, 7, 3, 11, 4, 8, 2]
    rt32 = _rt(page_len=4, prefix_cache=False)
    rt8 = DecodeRuntime(rt32.w, CFG, slots=2, prefill_chunk=4, page_len=4,
                        kv_quant='int8', prefix_cache=False)
    assert rt8.cache.store_dtype == 'int8'
    assert rt8.cache.page_bytes() < rt32.cache.page_bytes()
    # documented parity budget: final-chunk logits within 2e-2 absolute
    s32, s8 = rt32.alloc_slot(), rt8.alloc_slot()
    assert rt32.ensure_capacity(s32, len(prompt))
    assert rt8.ensure_capacity(s8, len(prompt))
    l32 = l8 = None
    for off in range(0, len(prompt), 4):
        _, l32 = rt32.prefill(s32, prompt[off:off + 4], off,
                              SamplingParams())
        _, l8 = rt8.prefill(s8, prompt[off:off + 4], off, SamplingParams())
    assert float(np.max(np.abs(l32 - l8))) <= 2e-2
    rt32.free_slot(s32)
    rt8.free_slot(s8)
    # and the budget is small enough that GREEDY streams are identical
    assert rt8.generate(prompt, 10) == rt32.generate(prompt, 10)


def test_prefix_hit_stream_bitwise_equals_cold():
    rt = _rt(page_len=4)                  # prefix cache on by default
    assert rt.prefix is not None
    prompt = [7, 3, 11, 2, 9, 1, 4, 6, 13, 5]      # 2 full pages + tail
    cold = rt.generate(prompt, 8)
    inserted = _cnt('generation.prefix_inserts')
    assert inserted >= 2                  # both full pages published
    hits0 = _cnt('generation.prefix_hits')
    warm = rt.generate(prompt, 8)
    assert _cnt('generation.prefix_hits') == hits0 + 1
    assert warm == cold                   # bitwise: a hit never shifts tokens
    # seeded top-k must be equally invisible
    p = SamplingParams(temperature=0.9, top_k=5, seed=11)
    cold_tk = rt.generate(prompt, 8, p)
    warm_tk = rt.generate(prompt, 8, p)
    assert warm_tk == cold_tk
    # cached chains hold pages after every stream retired — that is the
    # cache working, not a leak; reset releases them all
    assert rt.pool.in_use() > 0
    assert rt.allocator.in_use() == 0
    rt.prefix.reset()
    assert rt.pool.in_use() == 0


def test_speculative_stream_bitwise_equals_plain():
    rt = _rt(page_len=4, prefix_cache=False)
    prompt = [1, 5, 9, 2, 7, 3]
    plain = rt.generate(prompt, 14)
    prop0, acc0 = _cnt('generation.spec_proposed'), \
        _cnt('generation.spec_accepted')
    compiles0 = _cnt('generation.compiles')
    spec = rt.generate(prompt, 14, speculative=True)
    assert spec == plain                  # speculation never changes tokens
    assert _cnt('generation.spec_proposed') > prop0
    assert _cnt('generation.spec_accepted') >= acc0
    # seeded top-k sampling replays identically through accept/verify
    p = SamplingParams(temperature=0.9, top_k=5, seed=11)
    assert rt.generate(prompt, 10, p, speculative=True) == \
        rt.generate(prompt, 10, p)
    # the verify executable was the only extra compile
    rt.warmup(steps=4, speculative=True)
    c0 = _cnt('generation.compiles')
    rt.generate(prompt, 8, speculative=True)
    assert _cnt('generation.compiles') == c0


def test_draft_ngram_prompt_lookup():
    # last token 5 occurred before at index 1; propose its continuation
    ctx = np.asarray([3, 5, 8, 13, 5], np.int32)
    np.testing.assert_array_equal(draft_ngram(ctx, 3), [8, 13, 5])
    # no prior occurrence: pad with the last token
    np.testing.assert_array_equal(draft_ngram(np.asarray([1, 2, 3]), 2),
                                  [3, 3])


# ----------------------------------------------------- kv_oom surfaces

def test_admission_never_fits_rejected_and_backpressure_queues():
    # 2 allocatable pages of 4 tokens: one stream fills the pool
    rt = _rt(slots=2, page_len=4, pages=3, prefix_cache=False)
    eng = GenerationEngine(rt, config=ServingConfig(max_queue=16),
                           gen_config=GenerationConfig(
                               decode_window=4)).start()
    try:
        # could never fit even on an idle pool -> terminal kv_oom reject
        res = eng.generate(list(range(1, 10)), max_new=9).result(30)
        assert res.status == 'rejected' and res.reason == 'kv_oom'
        # oversubscribe: page-short streams stay QUEUED and complete
        # once the pool frees — backpressure, not failure
        # prompt + max_new exactly fills the 2-page pool, so each
        # stream FITS alone but two can never run together
        bp0 = _cnt('generation.kv_backpressure')
        streams = [eng.generate([1 + i, 5, 9, 2], max_new=4,
                                timeout_s=60.0) for i in range(4)]
        results = [s.result(60) for s in streams]
        assert all(r.ok for r in results)
        assert _cnt('generation.kv_backpressure') > bp0
    finally:
        eng.stop()
    assert rt.pool.in_use() == 0
    assert rt.free_slots() == rt.slots


def test_midstream_kv_oom_terminal_error_with_flight_dump(tmp_path,
                                                          monkeypatch):
    monkeypatch.setenv('PT_FLIGHT_DIR', str(tmp_path))
    rt = _rt(slots=1, page_len=4, prefix_cache=False)
    eng = GenerationEngine(rt, config=ServingConfig(),
                           gen_config=GenerationConfig(
                               decode_window=4)).start()
    try:
        oom0 = _cnt('generation.kv_oom')
        # alloc #1 claims the admission span; alloc #2 is the
        # mid-stream growth before the second window — inject there
        faults.configure('kv_oom:at=2:times=1')
        s = eng.generate([2, 7], max_new=8, timeout_s=60.0)
        res = s.result(60)
        assert res.status == 'error' and res.reason == 'kv_oom'
        assert len(s.tokens_so_far()) >= 1      # streamed work stays readable
        assert _cnt('generation.kv_oom') == oom0 + 1
    finally:
        eng.stop()
    assert rt.free_slots() == rt.slots and rt.pool.in_use() == 0
    dumps = [fn for fn in os.listdir(str(tmp_path)) if 'kv_oom' in fn]
    assert dumps, 'mid-stream kv_oom left no flight dump'
    art = json.load(open(os.path.join(str(tmp_path), dumps[0])))
    assert 'kv_pool' in art['extra']
    assert art['extra']['kv_pool']['pages_capacity'] == rt.pool.capacity
