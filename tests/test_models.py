"""Model zoo tests: each model builds and trains, loss decreases
(model: reference book/benchmark convergence tests)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _train(out, feed_fn, steps=25, loss_key='loss'):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    losses = []
    for i in range(steps):
        l, = exe.run(feed=feed_fn(i), fetch_list=[out[loss_key]])
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    return losses


def test_fit_a_line_converges():
    from paddle_tpu.models import simple
    import paddle_tpu.dataset.uci_housing as uci
    out = simple.fit_a_line(lr=0.05)
    data = list(uci.train()())

    def feed(i):
        rows = data[(i * 32) % 300:(i * 32) % 300 + 32]
        return {'x': np.stack([r[0] for r in rows]),
                'y': np.stack([r[1] for r in rows])}
    losses = _train(out, feed, steps=40)
    assert losses[-1] < losses[0] * 0.2


def test_mnist_cnn_converges():
    from paddle_tpu.models import mnist as m
    import paddle_tpu.dataset.mnist as md
    out = m.build(lr=0.003)
    data = list(md.train()())[:512]

    def feed(i):
        rows = data[(i * 32) % 480:(i * 32) % 480 + 32]
        return {'pixel': np.stack([r[0].reshape(1, 28, 28) for r in rows]),
                'label': np.array([[r[1]] for r in rows], 'int64')}
    losses = _train(out, feed, steps=25)
    assert losses[-1] < losses[0] * 0.5


def test_word2vec_builds_and_steps():
    from paddle_tpu.models import word2vec
    out = word2vec.build(dict_size=100, embed_size=8, hidden_size=16)
    rng = np.random.RandomState(0)

    def feed(i):
        grams = rng.randint(0, 100, (16, 5))
        d = {'word_%d' % j: grams[:, j:j + 1].astype('int64')
             for j in range(4)}
        d['next_word'] = grams[:, 4:5].astype('int64')
        return d
    losses = _train(out, feed, steps=10)
    assert np.all(np.isfinite(losses))


def test_ctr_deepfm_converges():
    from paddle_tpu.models import ctr
    out = ctr.deepfm(sparse_slots=8, dense_dim=4, vocab_size=100,
                     embed_dim=4, fc_sizes=(16,))
    data = list(ctr.synthetic_reader(
        512, sparse_slots=8, dense_dim=4, vocab_size=100)())

    def feed(i):
        rows = data[(i * 64) % 448:(i * 64) % 448 + 64]
        return {'dense_input': np.stack([r[0] for r in rows]),
                'sparse_input': np.stack([r[1] for r in rows]),
                'label': np.array([r[2] for r in rows], 'int64')}
    losses = _train(out, feed, steps=30)
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_resnet_cifar_builds_and_steps():
    from paddle_tpu.models import resnet
    out = resnet.build(data_shape=(3, 32, 32), class_dim=10, depth=20,
                       lr=0.05, data_set='cifar10')
    rng = np.random.RandomState(0)

    def feed(i):
        return {'data': rng.rand(8, 3, 32, 32).astype('float32'),
                'label': rng.randint(0, 10, (8, 1)).astype('int64')}
    losses = _train(out, feed, steps=4)
    assert np.all(np.isfinite(losses))


def test_transformer_tiny_converges():
    from paddle_tpu.models import transformer as tr
    out = tr.transformer(64, 64, max_len=16, n_layer=1, n_head=2,
                         d_model=32, d_inner=64, dropout=0.0,
                         label_smooth_eps=0.0)
    fluid.optimizer.Adam(3e-3).minimize(out['loss'])
    rng = np.random.RandomState(0)
    fixed_rows = []
    for _ in range(8):
        L = rng.randint(4, 14)
        s = rng.randint(3, 64, (L,))
        fixed_rows.append((s, np.concatenate([[0], s]),
                           np.concatenate([s, [1]])))
    feed_dict = tr.make_batch(fixed_rows, 16)
    losses = _train(out, lambda i: feed_dict, steps=60)
    assert losses[-1] < 0.3 * losses[0]


def test_transformer_flash_matches_composed():
    from paddle_tpu.models import transformer as tr
    rng = np.random.RandomState(0)
    rows = []
    for _ in range(4):
        L = rng.randint(4, 14)
        s = rng.randint(3, 64, (L,))
        rows.append((s, np.concatenate([[0], s]),
                     np.concatenate([s, [1]])))
    feed = tr.make_batch(rows, 16)

    results = []
    for use_flash in (False, True):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 7
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                out = tr.transformer(64, 64, max_len=16, n_layer=1,
                                     n_head=2, d_model=32, d_inner=64,
                                     dropout=0.0, use_flash=use_flash)
                fluid.optimizer.SGD(0.1).minimize(out['loss'])
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            l0, = exe.run(main, feed=feed, fetch_list=[out['loss']])
            l1, = exe.run(main, feed=feed, fetch_list=[out['loss']])
            results.append((float(l0[0]), float(l1[0])))
    # forward AND post-SGD-step losses agree -> gradients agree too
    assert results[0][0] == pytest.approx(results[1][0], rel=2e-3)
    assert results[0][1] == pytest.approx(results[1][1], rel=2e-3)


def test_stacked_lstm_builds_and_steps():
    from paddle_tpu.models import stacked_lstm
    from paddle_tpu.core.lod import create_lod_tensor
    out = stacked_lstm.build(dict_dim=50, emb_dim=8, hid_dim=8,
                             stacked_num=2)
    rng = np.random.RandomState(0)

    def feed(i):
        rows = [rng.randint(0, 50, (rng.randint(3, 8), 1)).astype('int64')
                for _ in range(4)]
        return {'words': create_lod_tensor(rows),
                'label': rng.randint(0, 2, (4, 1)).astype('int64')}
    losses = _train(out, feed, steps=5)
    assert np.all(np.isfinite(losses))


def test_vgg_builds_and_steps():
    from paddle_tpu.models import vgg
    out = vgg.build(data_shape=(3, 32, 32), class_dim=10)
    rng = np.random.RandomState(0)

    def feed(i):
        return {'data': rng.rand(4, 3, 32, 32).astype('float32'),
                'label': rng.randint(0, 10, (4, 1)).astype('int64')}
    losses = _train(out, feed, steps=3)
    assert np.all(np.isfinite(losses))
