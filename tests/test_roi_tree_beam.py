"""ROI pooling family, tree_conv, conv_shift, beam search.

Model: reference tests/unittests/test_roi_pool_op.py, test_psroi_pool_op.py,
test_tree_conv_op.py, test_beam_search_op.py, test_beam_search_decode_op.py.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(fetches, feed):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetches)


def test_roi_pool_and_align_build_run():
    x = layers.data('x', shape=[3, 8, 8], dtype='float32')
    rois = layers.data('rois', shape=[4], dtype='float32',
                       append_batch_size=False, stop_gradient=True)
    rois2 = layers.reshape(rois, [-1, 4])
    p = layers.roi_pool(x, rois2, pooled_height=2, pooled_width=2,
                        spatial_scale=1.0)
    a = layers.roi_align(x, rois2, pooled_height=2, pooled_width=2,
                         spatial_scale=1.0)
    xv = np.arange(2 * 3 * 8 * 8, dtype='float32').reshape(2, 3, 8, 8)
    rv = np.array([[0, 0, 3, 3], [2, 2, 7, 7]], 'float32')
    rp, ra = _run([p, a], {'x': xv, 'rois': rv})
    assert rp.shape == (2, 3, 2, 2)
    assert ra.shape == (2, 3, 2, 2)
    # max pool of roi (0,0,3,3) bottom-right 2x2 block of a 4x4 region:
    # rows 2..3, cols 2..3 of channel 0 image 0 -> max = 3*8+3 = 27
    assert rp[0, 0, 1, 1] == 27.0


def test_psroi_pool_uniform_input():
    oc, ph, pw = 2, 2, 2
    c = oc * ph * pw
    x = layers.data('x', shape=[c, 6, 6], dtype='float32')
    rois = layers.data('rois', shape=[1, 4], dtype='float32',
                       append_batch_size=False, stop_gradient=True)
    out = layers.psroi_pool(x, rois, oc, 1.0, ph, pw)
    # each input channel k holds constant value k -> output bin (i,j) of
    # out-channel csel equals the constant of channel (csel*ph+i)*pw+j
    xv = np.broadcast_to(
        np.arange(c, dtype='float32')[None, :, None, None],
        (1, c, 6, 6)).copy()
    rv = np.array([[0, 0, 5, 5]], 'float32')
    r, = _run([out], {'x': xv, 'rois': rv})
    assert r.shape == (1, oc, ph, pw)
    for csel in range(oc):
        for i in range(ph):
            for j in range(pw):
                assert r[0, csel, i, j] == (csel * ph + i) * pw + j


def test_conv_shift_matches_numpy():
    x = layers.data('x', shape=[5], dtype='float32')
    y = layers.data('y', shape=[3], dtype='float32')
    out = layers.conv_shift(x, y)
    xv = np.random.RandomState(0).randn(2, 5).astype('float32')
    yv = np.random.RandomState(1).randn(2, 3).astype('float32')
    r, = _run([out], {'x': xv, 'y': yv})
    m, n = 5, 3
    half = n // 2
    want = np.zeros_like(xv)
    for b in range(2):
        for i in range(m):
            for j in range(n):
                want[b, i] += xv[b, (i + j - half) % m] * yv[b, j]
    np.testing.assert_allclose(r, want, rtol=1e-5)


def _tree_conv_numpy(nodes, edges, W, max_depth):
    """Direct DFS re-implementation of tree2col.cc for checking."""
    B, N, F = nodes.shape
    _, three, out_size, nf = W.shape[1], W.shape[1], W.shape[2], W.shape[3]
    W2 = W.reshape(3 * W.shape[0], -1)
    out = np.zeros((B, N, W.shape[2], W.shape[3]), nodes.dtype)
    for b in range(B):
        tr = {}
        node_count = 0
        for (u, v) in edges[b]:
            if u == 0 or v == 0:
                break
            tr.setdefault(int(u), []).append(int(v))
            node_count += 1
        node_count += 1
        for root in range(1, node_count + 1):
            # DFS patch: (node, index(1-based), pclen, depth)
            patch = [(root, 1, 1, 0)]
            stack = [(root, 1, 1, 0)]
            visited = {root}
            while stack:
                u, _, _, d = stack[-1]
                advanced = False
                for i, v in enumerate(tr.get(u, [])):
                    if v not in visited and d + 1 < max_depth:
                        visited.add(v)
                        sz = len(tr[u])
                        stack.append((v, i, sz, d + 1))
                        patch.append((v, i + 1, sz, d + 1))
                        advanced = True
                if not advanced:
                    stack.pop()
            row = np.zeros((F, 3), nodes.dtype)
            for (v, idx, pclen, d) in patch:
                eta_t = (max_depth - d) / max_depth
                tmp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
                eta_l = (1 - eta_t) * tmp
                eta_r = (1 - eta_t) * (1 - eta_l)
                f = nodes[b, v - 1]
                row[:, 0] += eta_l * f
                row[:, 1] += eta_r * f
                row[:, 2] += eta_t * f
            out[b, root - 1] = (row.reshape(1, 3 * F) @ W2).reshape(
                W.shape[2], W.shape[3])
    return out


def test_tree_conv_matches_reference_dfs():
    B, N, F, E = 2, 6, 4, 5
    rs = np.random.RandomState(0)
    nodes_np = rs.randn(B, N, F).astype('float32')
    # tree: 1 -> 2,3 ; 2 -> 4,5 ; 3 -> 6 (1-based)
    edges_np = np.tile(np.array(
        [[1, 2], [1, 3], [2, 4], [2, 5], [3, 6]], 'int32'), (B, 1, 1))
    nodes = layers.data('nodes', shape=[N, F], dtype='float32')
    edges = layers.data('edges', shape=[E, 2], dtype='int32',
                        stop_gradient=True)
    out = layers.tree_conv(nodes, edges, output_size=3, num_filters=2,
                           max_depth=2, act=None, bias_attr=False)
    prog = fluid.default_main_program()
    w_name = [p for p in prog.global_block().all_parameters()][0].name
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    r, = exe.run(feed={'nodes': nodes_np, 'edges': edges_np},
                 fetch_list=[out])
    W = np.array(fluid.global_scope().get(w_name))
    want = _tree_conv_numpy(nodes_np, edges_np, W, max_depth=2)
    np.testing.assert_allclose(r, want, rtol=1e-4, atol=1e-5)


def test_beam_search_step_and_decode():
    beam, K, end_id = 2, 2, 0
    pre_ids = layers.data('pre_ids', shape=[2, 1], dtype='int64',
                          append_batch_size=False, stop_gradient=True)
    pre_scores = layers.data('pre_scores', shape=[2, 1], dtype='float32',
                             append_batch_size=False, stop_gradient=True)
    ids = layers.data('ids', shape=[2, 2], dtype='int64',
                      append_batch_size=False, stop_gradient=True)
    scores = layers.data('scores', shape=[2, 2], dtype='float32',
                         append_batch_size=False, stop_gradient=True)
    sid, ssc, par = layers.beam_search(pre_ids, pre_scores, ids, scores,
                                       beam_size=beam, end_id=end_id,
                                       return_parent_idx=True)
    # one source, two beams; beam 0 candidates (5:0.9, 6:0.3),
    # beam 1 candidates (7:0.8, 8:0.6) -> top2 overall: 0.9 (id5,p0), 0.8(7,p1)
    r_ids, r_sc, r_par = _run(
        [sid, ssc, par],
        {'pre_ids': np.array([[1], [2]], 'int64'),
         'pre_scores': np.array([[0.1], [0.2]], 'float32'),
         'ids': np.array([[5, 6], [7, 8]], 'int64'),
         'scores': np.array([[0.9, 0.3], [0.8, 0.6]], 'float32')})
    assert r_ids[:, 0].tolist() == [5, 7]
    np.testing.assert_allclose(r_sc[:, 0], [0.9, 0.8], rtol=1e-6)
    assert r_par.tolist() == [0, 1]


def test_beam_search_finished_beam_propagates_end_id():
    pre_ids = layers.data('pre_ids', shape=[2, 1], dtype='int64',
                          append_batch_size=False, stop_gradient=True)
    pre_scores = layers.data('pre_scores', shape=[2, 1], dtype='float32',
                             append_batch_size=False, stop_gradient=True)
    ids = layers.data('ids', shape=[2, 2], dtype='int64',
                      append_batch_size=False, stop_gradient=True)
    scores = layers.data('scores', shape=[2, 2], dtype='float32',
                         append_batch_size=False, stop_gradient=True)
    sid, ssc = layers.beam_search(pre_ids, pre_scores, ids, scores,
                                  beam_size=2, end_id=0)
    # beam 0 already finished (pre_id==0) with score 5.0 -> must survive as
    # (0, 5.0); beam 1 contributes its best live candidate
    r_ids, r_sc = _run(
        [sid, ssc],
        {'pre_ids': np.array([[0], [2]], 'int64'),
         'pre_scores': np.array([[5.0], [0.2]], 'float32'),
         'ids': np.array([[5, 6], [7, 8]], 'int64'),
         'scores': np.array([[0.9, 0.3], [1.5, 0.6]], 'float32')})
    assert r_ids[0, 0] == 0
    np.testing.assert_allclose(r_sc[0, 0], 5.0)
    assert r_ids[1, 0] == 7


def test_beam_search_decode_backtrace():
    from paddle_tpu.layers import control_flow as cf
    T, R = 3, 2
    ids_arr = cf.create_array('int64')
    sc_arr = cf.create_array('float32')
    par_arr = cf.create_array('int32')
    for t in range(T):
        iv = layers.data('ids%d' % t, shape=[R, 1], dtype='int64',
                         append_batch_size=False, stop_gradient=True)
        sv = layers.data('sc%d' % t, shape=[R, 1], dtype='float32',
                         append_batch_size=False, stop_gradient=True)
        pv = layers.data('par%d' % t, shape=[R], dtype='int32',
                         append_batch_size=False, stop_gradient=True)
        cf.array_write(iv, t, ids_arr)
        cf.array_write(sv, t, sc_arr)
        cf.array_write(pv, t, par_arr)
    sids, sscs = layers.beam_search_decode(ids_arr, sc_arr, beam_size=R,
                                           end_id=0, parents=par_arr)
    # step ids:   t0 [10, 20]  t1 [11, 21]  t2 [12, 22]
    # parents:    t0 [0, 1]    t1 [1, 0]    t2 [0, 1]
    # final row0: t2 token 12, parent 0 -> t1 token 11, parent 1 -> t0 20
    feed = {'ids0': np.array([[10], [20]], 'int64'),
            'ids1': np.array([[11], [21]], 'int64'),
            'ids2': np.array([[12], [22]], 'int64'),
            'sc0': np.zeros((R, 1), 'float32'),
            'sc1': np.zeros((R, 1), 'float32'),
            'sc2': np.zeros((R, 1), 'float32'),
            'par0': np.array([0, 1], 'int32'),
            'par1': np.array([1, 0], 'int32'),
            'par2': np.array([0, 1], 'int32')}
    r_ids, r_sc = _run([sids, sscs], feed)
    assert r_ids.shape == (R, T)
    assert r_ids[0].tolist() == [20, 11, 12]
    assert r_ids[1].tolist() == [10, 21, 22]


def test_roi_perspective_transform_identity_quad():
    x = layers.data('x', shape=[1, 4, 4], dtype='float32')
    rois = layers.data('rois', shape=[1, 8], dtype='float32',
                       append_batch_size=False, stop_gradient=True)
    from paddle_tpu.layers import detection
    out = detection.roi_perspective_transform(x, rois, 4, 4, 1.0)
    xv = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
    # quad == whole image corners (clockwise from top-left)
    rv = np.array([[0, 0, 3, 0, 3, 3, 0, 3]], 'float32')
    r, = _run([out], {'x': xv, 'rois': rv})
    np.testing.assert_allclose(r[0, 0], xv[0, 0], atol=1e-3)


def test_beam_search_dynamic_batch_dim_builds():
    """Regression: dynamic (-1) row count must build (shape-inference
    placeholders are not divisible by beam_size)."""
    pre_ids = layers.data('pre_ids', shape=[1], dtype='int64',
                          stop_gradient=True)
    pre_scores = layers.data('pre_scores', shape=[1], dtype='float32',
                             stop_gradient=True)
    scores = layers.data('scores', shape=[3], dtype='float32',
                         stop_gradient=True)
    sid, ssc = layers.beam_search(pre_ids, pre_scores, None, scores,
                                  beam_size=4, end_id=0)
    r_ids, r_sc = _run(
        [sid, ssc],
        {'pre_ids': np.full((4, 1), 1, 'int64'),
         'pre_scores': np.array([[0.], [-1e9], [-1e9], [-1e9]], 'float32'),
         'scores': np.tile(np.array([[0.5, 2.0, 1.0]], 'float32'), (4, 1))})
    assert r_ids.shape == (4, 1)
    assert r_ids[0, 0] == 1  # argmax candidate of the only live beam


def test_beam_search_decode_without_parents_is_identity():
    from paddle_tpu.layers import control_flow as cf
    ids_arr = cf.create_array('int64')
    sc_arr = cf.create_array('float32')
    for t in range(2):
        iv = layers.data('i%d' % t, shape=[1], dtype='int64',
                         stop_gradient=True)
        sv = layers.data('s%d' % t, shape=[1], dtype='float32',
                         stop_gradient=True)
        cf.array_write(iv, t, ids_arr)
        cf.array_write(sv, t, sc_arr)
    sids, _ = layers.beam_search_decode(ids_arr, sc_arr, beam_size=2,
                                        end_id=0)
    r, = _run([sids], {'i0': np.array([[3], [4]], 'int64'),
                       'i1': np.array([[5], [6]], 'int64'),
                       's0': np.zeros((2, 1), 'float32'),
                       's1': np.zeros((2, 1), 'float32')})
    assert r[0].tolist() == [3, 5]
    assert r[1].tolist() == [4, 6]
