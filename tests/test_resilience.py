"""Fault-tolerant training runtime (train/checkpoint.py, train/recovery.py):
async checkpointing, torn-write scanning, SIGKILL/SIGTERM kill-and-resume
with bitwise loss parity, and divergence rollback."""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.data_feeder import SampleQuarantine
from paddle_tpu.testing import faults
from paddle_tpu.train import (CheckpointConfig, Checkpointer, LaunchRecord,
                              RecoveryPolicy, DivergenceError)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _build_model(seed=11):
    """Tiny classifier with dropout (RNG-dependent) + AMP + Adam (optimizer
    accumulator state) — the full resume surface."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 8, act='relu')
            h = fluid.layers.dropout(h, 0.3)
            logits = fluid.layers.fc(h, 3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    main.set_amp(True)
    return main, startup, loss


def _feed_at(i):
    rng = np.random.RandomState(100 + i)
    return {'x': rng.rand(4, 4).astype('float32'),
            'lbl': rng.randint(0, 3, (4, 1)).astype('int64')}


# ------------------------------------------------------------ async writer

def test_async_save_restore_roundtrip_with_rng_state(tmp_path):
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(3):
            exe.run(main, feed=_feed_at(i), fetch_list=[loss])
        ck.save(0, 2, extra_meta={'note': 'hello'})
        ck.wait()
        w = np.asarray(scope.get('fc_0.w_0'))
        m1 = np.asarray(scope.get('fc_0.w_0_moment1_0'))

    # fresh executor/scope = fresh process stand-in
    main2, startup2, loss2 = _build_model()
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    ck2 = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                       exe2, main2, scope=scope2)
    meta = ck2.restore()
    assert meta['epoch_id'] == 0 and meta['step_id'] == 2
    assert meta['note'] == 'hello'
    # params AND optimizer accumulators restored bit-for-bit
    np.testing.assert_array_equal(np.asarray(scope2.get('fc_0.w_0')), w)
    np.testing.assert_array_equal(
        np.asarray(scope2.get('fc_0.w_0_moment1_0')), m1)
    # RNG/run counters restored: the next launch's counter continues
    assert meta['rng_state'] and exe2._pending_counters


def test_async_saves_do_not_block_and_rotate_valid_only(tmp_path):
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1,
                                       max_num_checkpoints=2),
                      exe, main, scope=scope)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed_at(0), fetch_list=[loss])
        for step in range(5):
            ck.save(0, step)
    ck.wait()
    kept = sorted(d for d in os.listdir(tmp_path)
                  if d.startswith('checkpoint_'))
    assert kept == ['checkpoint_3', 'checkpoint_4']
    assert (obs.counters().get('ckpt.saves') or 0) >= 5


def test_write_failure_is_counted_not_fatal(tmp_path):
    """A torn write (injected ckpt_write fault) must not kill training:
    counted + warned, and the NEXT save succeeds."""
    faults.configure('ckpt_write:at=1')
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck.save(0, 0)          # torn by the fault
        with pytest.warns(UserWarning, match='checkpoint write failed'):
            ck.wait()          # draining surfaces the async failure
        ck.save(0, 1)          # ...and the next save succeeds
        ck.wait()
    meta = Checkpointer(CheckpointConfig(str(tmp_path)), exe, main,
                        scope=scope).restore()
    assert meta['step_id'] == 1
    assert (obs.counters().get('ckpt.write_failures') or 0) >= 1


def test_torn_checkpoint_scan_restores_previous_valid(tmp_path):
    """The satellite contract: an injected mid-write failure leaves a torn
    dir; the restorer deletes it and picks the previous valid serial."""
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed_at(0), fetch_list=[loss])
        ck.save(0, 0)
        ck.wait()
        w0 = np.asarray(scope.get('fc_0.w_0'))
        exe.run(main, feed=_feed_at(1), fetch_list=[loss])
        faults.configure('ckpt_write:at=1')   # tear the SECOND save
        ck.save(0, 1)
        try:
            ck.wait()
        except Exception:
            pass
    # torn leftovers exist before the scan...
    leftovers = [d for d in os.listdir(tmp_path)
                 if d.startswith('.tmp_ckpt_')]
    assert leftovers, 'fault should have left a torn temp dir'
    main2, startup2, loss2 = _build_model()
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    ck2 = Checkpointer(CheckpointConfig(str(tmp_path)), exe2, main2,
                       scope=scope2)
    meta = ck2.restore()
    # ...and are swept by it, with the previous valid serial restored
    assert meta['step_id'] == 0
    np.testing.assert_array_equal(np.asarray(scope2.get('fc_0.w_0')), w0)
    assert not [d for d in os.listdir(tmp_path)
                if d.startswith('.tmp_ckpt_')]
    assert (obs.counters().get('ckpt.torn_deleted') or 0) >= 1


# ------------------------------------------------- retry-routed disk I/O

def test_ckpt_io_transient_blip_absorbed_by_retry(tmp_path):
    """A one-shot ckpt_io OSError is a blip, not a torn write: the
    retried writer absorbs it and the checkpoint still lands."""
    faults.configure('ckpt_io:at=1')
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    c0 = obs.counters()
    w0, r0 = c0.get('ckpt.write_failures') or 0, c0.get('retry.attempts') or 0
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck.save(0, 0)
        ck.wait()
    c = obs.counters()
    assert (c.get('ckpt.write_failures') or 0) == w0, 'blip must be absorbed'
    assert (c.get('retry.attempts') or 0) > r0
    assert (c.get('retry.attempts.ckpt.write') or 0) >= 1
    meta = Checkpointer(CheckpointConfig(str(tmp_path)), exe, main,
                        scope=scope).restore()
    assert meta['step_id'] == 0


def test_ckpt_io_exhausted_retry_budget_fails_the_write(tmp_path):
    """A persistent disk failure burns the whole backoff budget, then
    surfaces exactly like any other write failure: counted + warned."""
    faults.configure('ckpt_io:at=1:times=99')
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    g0 = obs.counters().get('retry.giveups') or 0
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck.save(0, 0)
        with pytest.warns(UserWarning, match='checkpoint write failed'):
            ck.wait()
    c = obs.counters()
    assert (c.get('retry.giveups') or 0) > g0
    assert (c.get('ckpt.write_failures') or 0) >= 1


# --------------------------------------------- ckpt.lock (two processes)

_LOCK_CHILD = r"""
import fcntl, os, sys
fd = os.open(sys.argv[1], os.O_CREAT | os.O_RDWR, 0o644)
fcntl.flock(fd, fcntl.LOCK_EX)
print('locked', flush=True)
sys.stdin.readline()
fcntl.flock(fd, fcntl.LOCK_UN)
print('released', flush=True)
"""


def test_ckpt_lock_excludes_a_second_process(tmp_path):
    """The satellite contract: two Checkpointers sharing one directory
    cannot interleave rotation sweeps — a second PROCESS holding
    ckpt.lock blocks dir_lock() until it releases."""
    child = subprocess.Popen(
        [sys.executable, '-c', _LOCK_CHILD, str(tmp_path / 'ckpt.lock')],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    try:
        assert child.stdout.readline().strip() == 'locked'
        main, startup, loss = _build_model()
        exe, scope = fluid.Executor(), fluid.Scope()
        ck = Checkpointer(CheckpointConfig(str(tmp_path),
                                           lock_timeout_s=0.4),
                          exe, main, scope=scope)
        with pytest.raises(RuntimeError, match='checkpoint lock'):
            with ck.dir_lock():
                pass
        child.stdin.write('\n')
        child.stdin.flush()
        assert child.stdout.readline().strip() == 'released'
        child.wait(timeout=30)
        with ck.dir_lock():
            pass   # free again once the peer released
    finally:
        if child.poll() is None:
            child.kill()


# -------------------------------------------- manifest integrity (sharded)

def test_corrupt_shard_and_manifest_fall_back_to_previous_serial(tmp_path):
    """Flip one byte in a shard payload and one in a MANIFEST.json: both
    serials must be skipped (checksum / parse failure), the previous
    clean serial restored, and every skip counted."""
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1,
                                       sharded=True),
                      exe, main, scope=scope)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed_at(0), fetch_list=[loss])
        ck.save(0, 0)
        ck.wait()
        w0 = np.asarray(scope.get('fc_0.w_0'))
        for i in (1, 2):
            exe.run(main, feed=_feed_at(i), fetch_list=[loss])
            ck.save(0, i)
            ck.wait()

    def flip(path):
        blob = bytearray(path.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        path.write_bytes(bytes(blob))

    flip(tmp_path / 'checkpoint_3' / 'arrays_0.npz')      # newest: payload
    flip(tmp_path / 'checkpoint_2' / 'MANIFEST.json')     # next: manifest
    c0 = obs.counters().get('ckpt.corrupt_skipped') or 0
    main2, startup2, loss2 = _build_model()
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    ck2 = Checkpointer(CheckpointConfig(str(tmp_path), sharded=True),
                       exe2, main2, scope=scope2)
    meta = ck2.restore()
    assert meta['step_id'] == 0, 'must land on the last CLEAN serial'
    np.testing.assert_array_equal(np.asarray(scope2.get('fc_0.w_0')), w0)
    assert (obs.counters().get('ckpt.corrupt_skipped') or 0) == c0 + 2


# --------------------------------------------------------- recovery policy

def test_recovery_rolls_back_and_skips_nan_step(tmp_path):
    faults.configure('nan_step:at=2')
    main, startup, loss = _build_model()
    exe = fluid.Executor(check_nan=True)
    scope = fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    pol = RecoveryPolicy(ck, max_retries=2)
    r0 = obs.counters().get('recovery.rollbacks') or 0
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        skipped = []
        for i in range(5):
            out = pol.run(lambda: exe.run(main, feed=_feed_at(i),
                                          fetch_list=[loss]))
            if out is None:
                skipped.append(i)
                continue
            ck.maybe_save(0, i)
            losses.append(float(np.asarray(out[0]).ravel()[0]))
    assert skipped == [2]
    assert all(np.isfinite(losses)) and len(losses) == 4
    c = obs.counters()
    assert c.get('recovery.rollbacks') == r0 + 1
    assert (c.get('faults.injected.nan_step') or 0) >= 1


def test_recovery_gives_up_after_bounded_retries(tmp_path):
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1),
                      exe, main, scope=scope)
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck.save(0, 0)
        ck.wait()
    pol = RecoveryPolicy(ck, max_retries=2)

    def always_nan():
        raise RuntimeError('check_nan: non-finite values everywhere')

    assert pol.run(always_nan) is None
    assert pol.run(always_nan) is None
    with pytest.raises(RuntimeError, match='check_nan'):
        pol.run(always_nan)   # third consecutive divergence: re-raise
    assert (obs.counters().get('recovery.giveups') or 0) >= 1


def test_recovery_requires_a_checkpoint():
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig('/nonexistent/ckpt'), exe, main,
                      scope=scope)
    pol = RecoveryPolicy(ck, max_retries=3)
    with pytest.raises(RuntimeError, match='no valid checkpoint'):
        pol.run(lambda: (_ for _ in ()).throw(
            RuntimeError('check_nan: boom')))


def test_loss_spike_heuristic():
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig('unused_dir'), exe, main,
                      scope=scope)
    pol = RecoveryPolicy(ck, spike_factor=10.0, min_history=3)
    for v in (1.0, 1.1, 0.9, 1.05):
        pol.check_loss(np.float32(v))
    with pytest.raises(DivergenceError, match='loss spike'):
        pol.check_loss(np.float32(50.0))
    with pytest.raises(DivergenceError, match='non-finite'):
        pol.check_loss(np.float32(np.nan))


def test_non_divergence_errors_propagate_untouched(tmp_path):
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(CheckpointConfig(str(tmp_path)), exe, main,
                      scope=scope)
    pol = RecoveryPolicy(ck)
    with pytest.raises(ValueError, match='a real bug'):
        pol.run(lambda: (_ for _ in ()).throw(ValueError('a real bug')))


# ----------------------------------------------------- prefetcher cursor

def test_prefetcher_skip_steps_fast_forwards():
    from paddle_tpu.data_feeder import FeedPrefetcher
    feeds = [{'x': np.full((2,), i, np.float32)} for i in range(8)]
    pf = FeedPrefetcher(iter(feeds), steps=2, to_device=False, skip_steps=4)
    got = [stacked['x'][:, 0].tolist() for stacked, k in pf]
    pf.close()
    assert got == [[4.0, 5.0], [6.0, 7.0]]
    assert pf.cursor() == {'steps': 8, 'superbatches': 2, 'skipped': 4}


# ------------------------------------------------- kill-and-resume (E2E)

_TRAIN_SCRIPT = r"""
import json, os, signal, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ.setdefault('PT_CACHE', '0')
sys.path.insert(0, sys.argv[1])
mode, ckpt_dir = sys.argv[2], sys.argv[3]
total, kill_at = int(sys.argv[4]), int(sys.argv[5])
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.train import CheckpointConfig, Checkpointer

main, startup = fluid.Program(), fluid.Program()
main.random_seed = 11
with fluid.program_guard(main, startup):
    with fluid.unique_name.guard():
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 8, act='relu')
        h = fluid.layers.dropout(h, 0.3)
        logits = fluid.layers.fc(h, 3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
main.set_amp(True)

def feed_at(i):
    rng = np.random.RandomState(100 + i)
    return {'x': rng.rand(4, 4).astype('float32'),
            'lbl': rng.randint(0, 3, (4, 1)).astype('int64')}

exe, scope = fluid.Executor(), fluid.Scope()
ck = Checkpointer(CheckpointConfig(ckpt_dir, step_interval=1,
                                   max_num_checkpoints=3),
                  exe, main, scope=scope)
ck.install_signal_handlers()
meta = ck.restore()
start = meta['step_id'] + 1 if meta else 0
K = 2
losses = []
with fluid.scope_guard(scope):
    if meta is None:
        exe.run(startup)
    if mode == 'run':
        for i in range(start, total):
            l, = exe.run(main, feed=feed_at(i), fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
            ck.save(0, i)                        # async, every step
            if i == kill_at:
                os.kill(os.getpid(), signal.SIGKILL)   # preemption, hard
    else:
        for s in range(start, total, K):
            feeds = [feed_at(i) for i in range(s, s + K)]
            ls, = exe.run_steps(main, feed_list=feeds, steps=K,
                                fetch_list=[loss])
            losses.extend(float(v) for v in np.asarray(ls).ravel())
            ck.save(0, s + K - 1)
            if s <= kill_at < s + K:
                os.kill(os.getpid(), signal.SIGKILL)
print(json.dumps({'start': start, 'losses': losses}))
"""


def _run_train_proc(mode, ckpt_dir, total=8, kill_at=-1, timeout=240,
                    env_extra=None):
    env = {k: v for k, v in os.environ.items() if k != 'PT_FAULT'}
    env.update(env_extra or {})
    r = subprocess.run(
        [sys.executable, '-c', _TRAIN_SCRIPT, REPO, mode, str(ckpt_dir),
         str(total), str(kill_at)],
        capture_output=True, text=True, timeout=timeout, env=env)
    return r


@pytest.mark.parametrize('mode', ['run', 'run_steps'])
def test_sigkill_and_auto_resume_is_bitwise(tmp_path, mode):
    """The acceptance contract: SIGKILL a training run mid-epoch, restart
    with auto-resume, and the combined loss stream is BITWISE equal to an
    uninterrupted run (CPU, dropout + AMP on) — through both the run and
    run_steps paths."""
    # uninterrupted reference (its own checkpoint dir, same code path)
    full = _run_train_proc(mode, tmp_path / 'full')
    assert full.returncode == 0, full.stderr
    ref = json.loads(full.stdout.strip().splitlines()[-1])
    assert ref['start'] == 0 and len(ref['losses']) == 8

    # killed run: SIGKILL right after step 4's (async) checkpoint submit
    killed = _run_train_proc(mode, tmp_path / 'ck', kill_at=4)
    assert killed.returncode == -signal.SIGKILL, (killed.returncode,
                                                  killed.stderr)

    # resume: picks the newest VALID checkpoint and finishes the epoch
    resumed = _run_train_proc(mode, tmp_path / 'ck')
    assert resumed.returncode == 0, resumed.stderr
    res = json.loads(resumed.stdout.strip().splitlines()[-1])
    assert res['start'] >= 1, 'resume did not find a checkpoint'
    assert res['start'] <= 5, 'resume overshot the kill point'
    # bitwise: the resumed tail equals the uninterrupted run's tail
    assert res['losses'] == ref['losses'][res['start']:], \
        'resumed run diverged from the uninterrupted one'


# ------------------------------------- forensics & sample quarantine (E2E)

def _stack_feeds(i0, k):
    per = [_feed_at(i0 + j) for j in range(k)]
    return {n: np.stack([f[n] for f in per]) for n in per[0]}


def _forensic_reference(qstate, total, k=1):
    """Uninjected run with the quarantine pre-seeded — the bitwise target
    a healed run must match.  Launch shape (single-step vs run_steps
    windows) mirrors the injected run so RNG stream counters line up."""
    faults.configure('')   # disarm: this is the clean-world counterfactual
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(check_nan=True), fluid.Scope()
    q = SampleQuarantine()
    q.restore(qstate)
    losses = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = 0
        while step < total:
            if k == 1:
                feed, _ = q.apply(_feed_at(step), step)
                out = exe.run(main, feed=feed, fetch_list=[loss])
                losses[step] = float(np.asarray(out[0]).ravel()[0])
            else:
                stacked, _ = q.apply(_stack_feeds(step, k), step, k)
                out = exe.run_steps(main, feed_list=stacked, steps=k,
                                    fetch_list=[loss])
                for j, v in enumerate(np.asarray(out[0]).ravel()):
                    losses[step + j] = float(v)
            step += k
    return losses


def test_forensics_names_injected_op_and_row_sync(tmp_path):
    """The tentpole contract, sync verdicts (nan_poll=1): a row-targeted
    nan_step trip must come back as a ForensicReport naming the exact
    step, consuming op, and batch row; the row's sample lands in the
    quarantine; the healed loss stream is BITWISE equal to an uninjected
    run with the same quarantine pre-seeded."""
    faults.configure('nan_step:at=2:row=1')
    main, startup, loss = _build_model()
    exe = fluid.Executor(check_nan=True, nan_poll=1)
    scope = fluid.Scope()
    q = SampleQuarantine()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1,
                                       max_num_checkpoints=3),
                      exe, main, scope=scope, quarantine=q)
    pol = RecoveryPolicy(ck, max_retries=4)
    losses = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck.save(0, -1)
        ck.wait()
        pol.note_checkpoint(-1)
        for i in range(5):
            out = pol.run(lambda: exe.run(main, feed=_feed_at(i),
                                          fetch_list=[loss]),
                          launch=LaunchRecord(main, _feed_at(i), None,
                                              [loss], i))
            if pol.last_replay is not None:       # rung 1 healed the window
                for s0, _n, o in pol.last_replay:
                    losses[s0] = float(np.asarray(o[0]).ravel()[0])
            else:
                assert out is not None, 'forensic heal must not skip-batch'
                losses[i] = float(np.asarray(out[0]).ravel()[0])
            ck.save(0, i)
            ck.wait()
            pol.note_checkpoint(i)
    rep = pol.last_report
    assert rep is not None and rep.tripped, 'no forensic report'
    assert rep.step == 2 and rep.rows == [1]
    assert rep.row_method == 'feed_scan'
    assert rep.op_type and rep.source_loc, 'report must name the op'
    assert 2 * 4 + 1 in q.state()      # default step*batch_size+row mapping
    assert sorted(losses) == list(range(5))
    assert all(np.isfinite(v) for v in losses.values())
    assert (obs.counters().get('recovery.escalation.quarantine') or 0) >= 1
    assert losses == _forensic_reference(q.state(), 5)


def test_forensics_localizes_inside_deferred_window_async(tmp_path):
    """Same contract under deferred verdicts (nan_poll=4, as_futures):
    the trip lands steps AFTER the poisoned launch, so forensics must
    bisect the whole condemned multi-launch window back to one step and
    one row — and the heal must still be bitwise."""
    faults.configure('nan_step:at=2:row=1')
    main, startup, loss = _build_model()
    exe = fluid.Executor(check_nan=True, nan_poll=4)
    scope = fluid.Scope()
    q = SampleQuarantine()
    ck = Checkpointer(CheckpointConfig(str(tmp_path), step_interval=1,
                                       max_num_checkpoints=3),
                      exe, main, scope=scope, quarantine=q)
    pol = RecoveryPolicy(ck, max_retries=4)
    K, total = 2, 8
    losses = {}
    pending = []   # [(loss_future, step0)] not yet past a clean poll

    def flush():
        for f, s0 in pending:
            for j, v in enumerate(np.asarray(f).ravel()):
                losses[s0 + j] = float(v)
        del pending[:]

    def land_replay():
        del pending[:]   # condemned-launch futures: superseded by the heal
        for s0, _n, o in pol.last_replay:
            for j, v in enumerate(np.asarray(o[0]).ravel()):
                losses[s0 + j] = float(v)

    def saved(step_id):
        ck.save(0, step_id)
        ck.wait()
        pol.note_checkpoint(step_id)

    with fluid.scope_guard(scope):
        exe.run(startup)
        ck.save(0, -1)
        ck.wait()
        pol.note_checkpoint(-1)
        step = 0
        while step < total:
            stacked = _stack_feeds(step, K)
            out = pol.run(
                lambda: exe.run_steps(main, feed_list=stacked, steps=K,
                                      fetch_list=[loss], as_futures=True),
                launch=LaunchRecord(main, stacked, K, [loss], step))
            if pol.last_replay is not None:
                land_replay()
                saved(step + K - 1)
            elif out is not None:
                pending.append((out[0], step))
                if exe.nan_clean():   # deferred verdict read AND clean
                    flush()
                    saved(step + K - 1)
            step += K
        if pending:
            def drain():
                exe.poll_nan()
                return []
            tail = pol.run(drain)
            if pol.last_replay is not None:
                land_replay()
            elif tail is not None:
                flush()
    rep = pol.last_report
    assert rep is not None and rep.tripped, 'no forensic report'
    assert rep.step == 2 and rep.rows == [1]
    assert rep.op_type and rep.source_loc
    assert 2 * 4 + 1 in q.state()
    assert sorted(losses) == list(range(total))
    assert all(np.isfinite(v) for v in losses.values())
    assert losses == _forensic_reference(q.state(), total, k=K)


_FORENSIC_SCRIPT = r"""
import json, os, signal, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ.setdefault('PT_CACHE', '0')
sys.path.insert(0, sys.argv[1])
ckpt_dir = sys.argv[2]
total, kill_at = int(sys.argv[3]), int(sys.argv[4])
import numpy as np
import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.data_feeder import SampleQuarantine
from paddle_tpu.train import (CheckpointConfig, Checkpointer, LaunchRecord,
                              RecoveryPolicy)

main, startup = fluid.Program(), fluid.Program()
main.random_seed = 11
with fluid.program_guard(main, startup):
    with fluid.unique_name.guard():
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 8, act='relu')
        logits = fluid.layers.fc(h, 3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.AdamOptimizer(0.01).minimize(loss)

EPOCH, BATCH = 4, 4

def feed_at(i):
    e = i % EPOCH
    rng = np.random.RandomState(100 + e)
    f = {'x': rng.rand(BATCH, 4).astype('float32'),
         'lbl': rng.randint(0, 3, (BATCH, 1)).astype('int64')}
    if e == 1:
        f['x'][2] = np.nan   # a genuinely bad sample, recurs every epoch
    return f

def index_of(step, row, batch):
    # epoch-stable reader index: the same bad sample keeps the same id
    return (int(step) % EPOCH) * batch + int(row)

exe = fluid.Executor(check_nan=True, nan_poll=1)
scope = fluid.Scope()
q = SampleQuarantine(index_of=index_of)
ck = Checkpointer(CheckpointConfig(ckpt_dir, step_interval=1,
                                   max_num_checkpoints=3),
                  exe, main, scope=scope, quarantine=q)
pol = RecoveryPolicy(ck, max_retries=4, sample_index_of=index_of)
meta = ck.restore()
start = meta['step_id'] + 1 if meta else 0
losses = []
with fluid.scope_guard(scope):
    if meta is None:
        exe.run(startup)
        ck.save(0, -1)
        ck.wait()
        pol.note_checkpoint(-1)
    for i in range(start, total):
        feed = q.apply(feed_at(i), i)[0]
        out = pol.run(lambda: exe.run(main, feed=feed, fetch_list=[loss]),
                      launch=LaunchRecord(main, feed, None, [loss], i))
        if pol.last_replay is not None:
            for s0, n, o in pol.last_replay:
                losses.append(float(np.asarray(o[0]).ravel()[0]))
        elif out is not None:
            losses.append(float(np.asarray(out[0]).ravel()[0]))
        ck.save(0, i)
        ck.wait()
        pol.note_checkpoint(i)
        if i == kill_at:
            os.kill(os.getpid(), signal.SIGKILL)
print(json.dumps({'start': start, 'losses': losses,
                  'divergences':
                      obs.counters().get('recovery.divergences') or 0,
                  'quarantine': q.state()}))
"""


def _run_forensic_proc(ckpt_dir, total=12, kill_at=-1, timeout=240):
    env = {k: v for k, v in os.environ.items() if k != 'PT_FAULT'}
    return subprocess.run(
        [sys.executable, '-c', _FORENSIC_SCRIPT, REPO, str(ckpt_dir),
         str(total), str(kill_at)],
        capture_output=True, text=True, timeout=timeout, env=env)


def test_sigkill_resume_restores_quarantine_from_meta(tmp_path):
    """The satellite contract: a genuinely bad sample (NaN row baked into
    the data, recurring every epoch) is quarantined by forensics in epoch
    one; the process is then SIGKILLed.  The resumed process must inherit
    the quarantine from checkpoint META and finish the run WITHOUT ever
    re-tripping on that sample."""
    killed = _run_forensic_proc(tmp_path / 'ck', total=12, kill_at=6)
    assert killed.returncode == -signal.SIGKILL, (killed.returncode,
                                                  killed.stderr)
    resumed = _run_forensic_proc(tmp_path / 'ck', total=12)
    assert resumed.returncode == 0, resumed.stderr
    res = json.loads(resumed.stdout.strip().splitlines()[-1])
    assert res['start'] == 7, res['start']
    # (epoch step 1, row 2) on batch 4 -> stable reader index 6,
    # restored from META — not re-derived by a second forensic run
    assert res['quarantine'] == [6], res['quarantine']
    assert res['divergences'] == 0, \
        'resume re-tripped on an already-quarantined sample'
    assert len(res['losses']) == 5
    assert all(np.isfinite(res['losses']))


def test_sigterm_flushes_final_checkpoint_and_resumes_bitwise(tmp_path):
    """Graceful preemption: the sigterm fault site delivers SIGTERM as
    step 3 is about to launch; the installed handler flushes one final
    checkpoint (scope, RNG counters, and recorded progress all consistent
    at "step 2 complete") before the process dies, and the resumed run
    continues bitwise."""
    full = _run_train_proc('run', tmp_path / 'full')
    ref = json.loads(full.stdout.strip().splitlines()[-1])

    killed = _run_train_proc('run', tmp_path / 'ck',
                             env_extra={'PT_FAULT': 'sigterm:at=3'})
    assert killed.returncode != 0
    resumed = _run_train_proc('run', tmp_path / 'ck')
    assert resumed.returncode == 0, resumed.stderr
    res = json.loads(resumed.stdout.strip().splitlines()[-1])
    # the flush covered steps 0..2, so resume starts exactly at step 3 —
    # no step lost, no step double-trained
    assert res['start'] == 3, res
    assert res['losses'] == ref['losses'][3:]
