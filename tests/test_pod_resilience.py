"""Pod-scale resilience (train/checkpoint.py sharded mode,
parallel/health.py, train/recovery.py): sharded manifest checkpoints with
an all-hosts-or-nothing commit, elastic restore onto a different roster,
the device-health watchdog, device-loss recovery, and the multi-process
kill-and-reshard acceptance contract."""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.parallel.health import (DeviceLossError, HealthConfig,
                                        HealthMonitor, HostDesyncError)
from paddle_tpu.testing import faults
from paddle_tpu.train import CheckpointConfig, Checkpointer, RecoveryPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _build_model(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 8, act='relu')
            h = fluid.layers.dropout(h, 0.3)
            logits = fluid.layers.fc(h, 3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    main.set_amp(True)
    return main, startup, loss


def _feed_at(i):
    rng = np.random.RandomState(100 + i)
    return {'x': rng.rand(4, 4).astype('float32'),
            'lbl': rng.randint(0, 3, (4, 1)).astype('int64')}


def _sharded_cfg(path, **kw):
    kw.setdefault('step_interval', 1)
    kw.setdefault('sharded', True)
    return CheckpointConfig(str(path), **kw)


def _trained_scope(steps=2):
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(steps):
            exe.run(main, feed=_feed_at(i), fetch_list=[loss])
    return main, loss, exe, scope


# ------------------------------------------------- sharded manifest format

def test_sharded_manifest_schema_and_roundtrip(tmp_path):
    main, loss, exe, scope = _trained_scope()
    ck = Checkpointer(_sharded_cfg(tmp_path), exe, main, scope=scope)
    ck.save(0, 1)
    ck.wait()
    ckpt = tmp_path / 'checkpoint_2'   # serial is step-derived: step + 1
    for fname in ('_SUCCESS', 'MANIFEST.json', 'arrays_0.npz',
                  'shard_0.json'):
        assert (ckpt / fname).exists(), fname
    man = json.loads((ckpt / 'MANIFEST.json').read_text())
    assert man['format'] == 'ptckpt-sharded-1'
    assert man['writers'] == [0]
    assert man['meta']['step_id'] == 1 and man['meta']['rng_state']
    assert set(man['files']) == {'arrays_0.npz'}
    rec = man['files']['arrays_0.npz']
    assert rec['host'] == 0 and len(rec['sha256']) == 64 and rec['bytes'] > 0
    for n, arr in man['arrays'].items():
        assert 'shape' in arr and 'dtype' in arr and arr['shards'], n
    w = np.asarray(scope.get('fc_0.w_0'))
    m1 = np.asarray(scope.get('fc_0.w_0_moment1_0'))

    main2, _, _ = _build_model()
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    ck2 = Checkpointer(_sharded_cfg(tmp_path), exe2, main2, scope=scope2)
    meta = ck2.restore()
    assert meta['step_id'] == 1
    np.testing.assert_array_equal(np.asarray(scope2.get('fc_0.w_0')), w)
    np.testing.assert_array_equal(
        np.asarray(scope2.get('fc_0.w_0_moment1_0')), m1)


def test_two_host_commit_is_all_or_nothing_and_elastic(tmp_path):
    """One host's shard alone must never become a restorable checkpoint;
    the full roster commits, and a 1-host restore reassembles the global
    arrays bitwise (counting the reshard)."""
    main, loss, exe, scope = _trained_scope()
    ck0 = Checkpointer(_sharded_cfg(tmp_path, host_id=0, host_count=2),
                       exe, main, scope=scope)
    ck1 = Checkpointer(_sharded_cfg(tmp_path, host_id=1, host_count=2),
                       exe, main, scope=scope)
    ck0.save(0, 0)
    ck0.wait()
    final = tmp_path / 'checkpoint_1'
    assert not final.exists(), 'half a roster must not commit'
    assert (tmp_path / 'checkpoint_1.parts' / 'arrays_0.npz').exists()
    ck1.save(0, 0)
    ck1.wait()
    assert (final / '_SUCCESS').exists()
    assert not (tmp_path / 'checkpoint_1.parts').exists()
    man = json.loads((final / 'MANIFEST.json').read_text())
    assert man['writers'] == [0, 1]
    assert set(man['files']) == {'arrays_0.npz', 'arrays_1.npz'}
    w = np.asarray(scope.get('fc_0.w_0'))
    m1 = np.asarray(scope.get('fc_0.w_0_moment1_0'))

    # elastic restore onto a 1-host roster: global arrays reassembled
    r0 = obs.counters().get('ckpt.reshards') or 0
    main2, _, _ = _build_model()
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(_sharded_cfg(tmp_path), exe2, main2, scope=scope2)
    assert ck.restore()['step_id'] == 0
    np.testing.assert_array_equal(np.asarray(scope2.get('fc_0.w_0')), w)
    np.testing.assert_array_equal(
        np.asarray(scope2.get('fc_0.w_0_moment1_0')), m1)
    assert (obs.counters().get('ckpt.reshards') or 0) == r0 + 1

    # a same-roster restore is NOT a reshard
    main3, _, _ = _build_model()
    exe3, scope3 = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(_sharded_cfg(tmp_path, host_id=0, host_count=2),
                      exe3, main3, scope=scope3)
    assert ck.restore()['step_id'] == 0
    assert (obs.counters().get('ckpt.reshards') or 0) == r0 + 1


def test_partial_roster_is_swept_as_a_unit(tmp_path):
    """A .parts staging dir whose writer died mid-roster is swept whole —
    restore never sees half a pod checkpoint."""
    main, loss, exe, scope = _trained_scope()
    ck0 = Checkpointer(_sharded_cfg(tmp_path, host_id=0, host_count=2),
                       exe, main, scope=scope)
    ck1 = Checkpointer(_sharded_cfg(tmp_path, host_id=1, host_count=2),
                       exe, main, scope=scope)
    for ck in (ck0, ck1):
        ck.save(0, 0)
        ck.wait()
    ck0.save(0, 1)            # host 1 "dies" before contributing
    ck0.wait()
    assert (tmp_path / 'checkpoint_2.parts').exists()

    p0 = obs.counters().get('ckpt.partial_swept') or 0
    main2, _, _ = _build_model()
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(_sharded_cfg(tmp_path, stale_parts_s=0.0),
                      exe2, main2, scope=scope2)
    meta = ck.restore()
    assert meta['step_id'] == 0, 'must fall back to the last FULL serial'
    assert not (tmp_path / 'checkpoint_2.parts').exists()
    assert (obs.counters().get('ckpt.partial_swept') or 0) == p0 + 1


def test_host_desync_fault_drops_the_mixed_serial(tmp_path):
    """The host_desync fault skews one sidecar's step; the finalize guard
    must refuse to commit a serial whose roster disagrees on the step."""
    main, loss, exe, scope = _trained_scope()
    ck0 = Checkpointer(_sharded_cfg(tmp_path, host_id=0, host_count=2),
                       exe, main, scope=scope)
    ck1 = Checkpointer(_sharded_cfg(tmp_path, host_id=1, host_count=2),
                       exe, main, scope=scope)
    for ck in (ck0, ck1):
        ck.save(0, 0)
        ck.wait()
    d0 = obs.counters().get('ckpt.desync_dropped') or 0
    faults.configure('host_desync:at=1')   # step-indexed: fires at step 1
    ck0.save(0, 1)
    ck0.wait()
    ck1.save(0, 1)
    ck1.wait()
    assert not (tmp_path / 'checkpoint_2').exists()
    assert not (tmp_path / 'checkpoint_2.parts').exists()
    c = obs.counters()
    assert c.get('ckpt.desync_dropped') == d0 + 1
    assert (c.get('health.desyncs') or 0) >= 1
    assert (c.get('faults.injected.host_desync') or 0) >= 1

    main2, _, _ = _build_model()
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    ck = Checkpointer(_sharded_cfg(tmp_path), exe2, main2, scope=scope2)
    assert ck.restore()['step_id'] == 0


def test_manifest_records_parallel_executor_mesh(tmp_path):
    from paddle_tpu.parallel.parallel_executor import ParallelExecutor
    main, startup, loss = _build_model()
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                              scope=scope)
        rng = np.random.RandomState(0)   # batch divisible by the 8-dev mesh
        pe.run([loss.name],
               feed={'x': rng.rand(8, 4).astype('float32'),
                     'lbl': rng.randint(0, 3, (8, 1)).astype('int64')})
        ck = Checkpointer(_sharded_cfg(tmp_path), pe, main, scope=scope)
        ck.save(0, 0)
        ck.wait()
    man = json.loads(
        (tmp_path / 'checkpoint_1' / 'MANIFEST.json').read_text())
    assert man['mesh']['axes'], 'mesh axes missing from the manifest'
    assert int(np.prod(man['mesh']['shape'])) == pe.device_count
    assert man['meta']['rng_state'], 'PE must delegate rng_state()'


# --------------------------------------------------- device-health watchdog

def _monitors(tmp_path, now, timeout_s=1.0, desync_steps=100):
    mk = lambda h: HealthMonitor(  # noqa: E731 - local factory
        HealthConfig(str(tmp_path), host_id=h, host_count=2,
                     timeout_s=timeout_s, desync_steps=desync_steps),
        time_fn=lambda: now[0])
    return mk(0), mk(1)


def test_health_staleness_trips_and_is_sticky(tmp_path):
    now = [0.0]
    h0, h1 = _monitors(tmp_path, now)
    assert h1.beat(0) and h0.beat(0)
    h0.check(0)                       # fresh roster: healthy
    now[0] = 5.0
    h0.beat(1)
    t0 = obs.counters().get('health.trips') or 0
    with pytest.raises(DeviceLossError, match='host 1 lost'):
        h0.check(1)
    with pytest.raises(DeviceLossError):
        h0.check(1)                   # sticky: same verdict forever
    c = obs.counters()
    assert c.get('health.trips') == t0 + 1
    assert (c.get('health.lost_hosts') or 0) >= 1


def test_health_tolerates_not_yet_joined_and_done_peers(tmp_path):
    now = [0.0]
    h0, h1 = _monitors(tmp_path, now)
    h0.beat(0)
    h0.check(0)                       # peer never beat: still joining
    h1.beat(3)
    h1.mark_done()
    now[0] = 100.0
    h0.beat(4)
    h0.check(4)                       # done peer is healthy forever


def test_health_desync_trips(tmp_path):
    now = [0.0]
    h0, h1 = _monitors(tmp_path, now, desync_steps=100)
    h1.beat(1000)
    h0.beat(0)
    with pytest.raises(HostDesyncError, match='desynced'):
        h0.check(0)
    assert (obs.counters().get('health.desyncs') or 0) >= 1


def test_health_disappeared_heartbeat_trips(tmp_path):
    now = [0.0]
    h0, h1 = _monitors(tmp_path, now)
    h1.beat(0)
    h0.beat(0)
    h0.check(0)
    os.unlink(h0.path_of(1))
    with pytest.raises(DeviceLossError, match='disappeared'):
        h0.check(0)


def test_device_loss_fault_silences_beats(tmp_path):
    """The injected loss is a SILENT death: beat() refuses from the armed
    step on, and the peer detects it purely from staleness."""
    faults.configure('device_loss:at=2')
    now = [0.0]
    h0, h1 = _monitors(tmp_path, now)
    assert h1.beat(1)
    assert not h1.beat(2)             # fault: goes quiet
    assert not h1.beat(3)             # ...and stays quiet
    h0.beat(2)
    now[0] = 5.0
    h0.beat(3)
    with pytest.raises(DeviceLossError):
        h0.check(3)
    assert (obs.counters().get('faults.injected.device_loss') or 0) >= 1


def test_host_desync_fault_skews_heartbeat(tmp_path):
    faults.configure('host_desync:at=1')
    now = [0.0]
    h0, h1 = _monitors(tmp_path, now, desync_steps=100)
    h1.beat(1)                        # fault: records a far-future step
    h0.beat(1)
    with pytest.raises(HostDesyncError):
        h0.check(1)


# ------------------------------------------------- recovery integration

def test_recovery_device_loss_rolls_back_and_reraises(tmp_path):
    """Device loss is a pod fault, not a divergence: RecoveryPolicy must
    roll back to the last good manifest and RE-RAISE (the supervisor
    restarts the process), never skip-and-continue."""
    main, loss, exe, scope = _trained_scope()
    ck = Checkpointer(_sharded_cfg(tmp_path), exe, main, scope=scope)
    with fluid.scope_guard(scope):
        ck.save(0, 0)
        ck.wait()
        w0 = np.asarray(scope.get('fc_0.w_0'))
        scope.set('fc_0.w_0', w0 + 1.0)   # poisoned in-flight state
        pol = RecoveryPolicy(ck, max_retries=3)
        d0 = obs.counters().get('recovery.device_loss') or 0
        with pytest.raises(DeviceLossError):
            pol.run(lambda: (_ for _ in ()).throw(
                DeviceLossError('host 1 lost')))
        np.testing.assert_array_equal(np.asarray(scope.get('fc_0.w_0')), w0)
    c = obs.counters()
    assert c.get('recovery.device_loss') == d0 + 1
    assert (c.get('recovery.rollbacks') or 0) >= 1


# --------------------------------- kill-and-reshard acceptance (E2E)

_POD_SCRIPT = r"""
import json, os, signal, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ.setdefault('PT_CACHE', '0')
sys.path.insert(0, sys.argv[1])
ckpt_dir = sys.argv[2]
host, hosts = int(sys.argv[3]), int(sys.argv[4])
total, kill_at = int(sys.argv[5]), int(sys.argv[6])
import numpy as np
import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.train import CheckpointConfig, Checkpointer

main, startup = fluid.Program(), fluid.Program()
main.random_seed = 11
with fluid.program_guard(main, startup):
    with fluid.unique_name.guard():
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 8, act='relu')
        h = fluid.layers.dropout(h, 0.3)
        logits = fluid.layers.fc(h, 3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
main.set_amp(True)

def feed_at(i):
    rng = np.random.RandomState(100 + i)
    return {'x': rng.rand(4, 4).astype('float32'),
            'lbl': rng.randint(0, 3, (4, 1)).astype('int64')}

exe, scope = fluid.Executor(), fluid.Scope()
ck = Checkpointer(CheckpointConfig(ckpt_dir, step_interval=1,
                                   max_num_checkpoints=4, sharded=True,
                                   host_id=host, host_count=hosts,
                                   stale_parts_s=0.0),
                  exe, main, scope=scope)
meta = ck.restore()
start = meta['step_id'] + 1 if meta else 0
losses = []
with fluid.scope_guard(scope):
    if meta is None:
        exe.run(startup)
    for i in range(start, total):
        l, = exe.run(main, feed=feed_at(i), fetch_list=[loss])
        losses.append(float(np.asarray(l).ravel()[0]))
        ck.save(0, i)
        if i == kill_at:
            ck.wait()   # this host's shard is durable; now die hard
            os.kill(os.getpid(), signal.SIGKILL)
ck.wait()
print(json.dumps({'start': start, 'losses': losses,
                  'reshards': obs.counters().get('ckpt.reshards') or 0}))
"""


def _pod_proc(ckpt_dir, host, hosts, total=8, kill_at=-1):
    env = {k: v for k, v in os.environ.items() if k != 'PT_FAULT'}
    return subprocess.Popen(
        [sys.executable, '-c', _POD_SCRIPT, REPO, str(ckpt_dir), str(host),
         str(hosts), str(total), str(kill_at)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)


def test_sharded_kill_and_elastic_resume_is_bitwise(tmp_path):
    """The acceptance contract: two lockstep hosts write a 2x-sharded
    checkpoint stream, both are SIGKILLed mid-run, and a SINGLE-host
    process elastically restores the newest manifest and finishes — with
    losses bitwise equal to an uninterrupted single-host run."""
    ref_p = _pod_proc(tmp_path / 'full', 0, 1)
    out, err = ref_p.communicate(timeout=240)
    assert ref_p.returncode == 0, err
    ref = json.loads(out.strip().splitlines()[-1])
    assert ref['start'] == 0 and len(ref['losses']) == 8

    # the pod: both hosts die hard right after step 4's shards are durable
    workers = [_pod_proc(tmp_path / 'pod', h, 2, kill_at=4)
               for h in range(2)]
    for p in workers:
        p.communicate(timeout=240)
        assert p.returncode == -signal.SIGKILL, p.returncode

    # a committed manifest for the kill step exists (serial = step + 1)
    man_path = tmp_path / 'pod' / 'checkpoint_5' / 'MANIFEST.json'
    assert man_path.exists(), os.listdir(tmp_path / 'pod')
    assert json.loads(man_path.read_text())['writers'] == [0, 1]

    # elastic resume on ONE host: reassembles the 2-shard manifest
    res_p = _pod_proc(tmp_path / 'pod', 0, 1)
    out, err = res_p.communicate(timeout=240)
    assert res_p.returncode == 0, err
    res = json.loads(out.strip().splitlines()[-1])
    assert res['start'] == 5, res
    assert res['reshards'] >= 1, 'the 2->1 restore must count a reshard'
    assert res['losses'] == ref['losses'][5:], \
        'elastic resume diverged from the uninterrupted run'
    # no orphaned staging debris after the sweep
    leftovers = [d for d in os.listdir(tmp_path / 'pod')
                 if d.startswith('.tmp_ckpt_') or d.endswith('.parts')]
    assert not leftovers, leftovers
