"""QAT quantize transpiler tests."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib import QuantizeTranspiler


def _build(act_qtype='abs_max'):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        h = layers.fc(x, 16, act='relu',
                      param_attr=fluid.ParamAttr(name='w1'))
        pred = layers.fc(h, 1, param_attr=fluid.ParamAttr(name='w2'))
        loss = layers.reduce_mean(layers.square(pred - y))
        t = QuantizeTranspiler(activation_quantize_type=act_qtype)
        t.training_transpile(main, startup)
        fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    return main, startup, pred, loss, t


def test_qat_inserts_fake_quant_and_converges():
    main, startup, pred, loss, t = _build()
    types = [op.type for op in main.global_block().ops]
    n_fq = sum(1 for t_ in types
               if t_.startswith('fake_quantize_dequantize'))
    assert n_fq == 4  # two muls x (weight + activation)
    # fake-quanted training still converges
    rng = np.random.RandomState(0)
    w_true = rng.rand(8, 1).astype('float32')
    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for _ in range(60):
        xb = rng.rand(32, 8).astype('float32')
        l, = exe.run(main, feed={'x': xb, 'y': xb @ w_true},
                     fetch_list=[loss])
        losses.append(float(np.asarray(l).reshape(())))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_qat_moving_average_scale_state():
    main, startup, pred, loss, t = _build('moving_average_abs_max')
    scope = fluid.Scope()
    exe = fluid.Executor()
    rng = np.random.RandomState(1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            xb = rng.rand(16, 8).astype('float32')
            exe.run(main, feed={'x': xb, 'y': xb.sum(1, keepdims=True)},
                    fetch_list=[loss])
        # the activation scale state was created persistable and updated
        scales = [n for n in scope.vars if '.scale' in n]
        assert scales, 'no activation scale states found'
        assert all(float(np.asarray(scope.vars[n]).reshape(())) > 0
                   for n in scales)


def test_freeze_program_matches_qat_predictions():
    main, startup, pred, loss, t = _build()
    rng = np.random.RandomState(2)
    w_true = rng.rand(8, 1).astype('float32')
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(40):
            xb = rng.rand(32, 8).astype('float32')
            exe.run(main, feed={'x': xb, 'y': xb @ w_true},
                    fetch_list=[loss])
        xt = rng.rand(8, 8).astype('float32')
        # eval clone: running `main` itself would take another Adam step
        # and shift the weights between the two predictions
        eval_prog = main.clone(for_test=True)
        qat_pred, = exe.run(eval_prog, feed={'x': xt, 'y': xt @ w_true},
                            fetch_list=[pred])

        infer = main.clone(for_test=True)
        t.freeze_program(infer, scope=scope)
        blk = infer.global_block()
        fq_ops = [op for op in blk.ops
                  if op.type.startswith('fake_quantize')]
        # reference freeze semantics (quantize_transpiler.py:218): weight
        # fake-quants are folded into the stored tensors; ACTIVATION quants
        # stay live in the inference graph (abs_max recomputes its scale
        # per batch, same as training)
        from paddle_tpu.core.framework import Parameter
        assert len(fq_ops) == 2, [op.type for op in blk.ops]
        for op in fq_ops:
            src = blk._find_var_recursive(op.inputs['X'][0])
            assert not isinstance(src, Parameter), \
                'weight fake-quant survived freeze: %s' % op.inputs['X']
        frozen_pred, = exe.run(infer, feed={'x': xt, 'y': xt @ w_true},
                               fetch_list=[pred])
    # weights were folded to their qdq values and activation quantization
    # is unchanged, so the frozen graph simulates QAT numerics exactly
    assert np.allclose(qat_pred, frozen_pred, atol=1e-5), \
        np.abs(np.asarray(qat_pred) - np.asarray(frozen_pred)).max()


def test_convert_to_int8():
    main, startup, pred, loss, t = _build()
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        packed = t.convert_to_int8(main, scope=scope)
    assert 'w1' in packed and 'w2' in packed
    q, scale = packed['w1']
    assert q.dtype == np.int8 and scale > 0
    # dequantized weights approximate the originals
    w = np.asarray(scope.vars['w1'])
    deq = q.astype('float32') / 127.0 * scale
    assert np.abs(deq - w).max() <= scale / 127.0 + 1e-6
