"""StaticRNN / DynamicRNN / IfElse block builders.

Model: reference tests/unittests/test_recurrent_op.py, test_dyn_rnn.py,
test_ifelse.py and the book MT decoder pattern
(tests/book/test_machine_translation.py / test_rnn_encoder_decoder.py).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


# ------------------------------------------------------------- StaticRNN

def test_static_rnn_matches_manual_loop():
    T, B, D = 5, 3, 4
    x = fluid.layers.data('x', shape=[T, B, D], dtype='float32',
                          append_batch_size=False)
    h0 = fluid.layers.data('h0', shape=[B, D], dtype='float32',
                           append_batch_size=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h = rnn.memory(init=h0)
        nh = layers.scale(h, scale=0.5) + xt
        rnn.update_memory(h, nh)
        rnn.output(nh)
    out = rnn()
    assert tuple(out.shape) == (T, B, D)
    rng = np.random.RandomState(0)
    xv = rng.rand(T, B, D).astype('float32')
    h0v = rng.rand(B, D).astype('float32')
    exe = fluid.Executor()
    got, = exe.run(feed={'x': xv, 'h0': h0v}, fetch_list=[out])
    want = np.zeros((T, B, D), np.float32)
    h = h0v
    for t in range(T):
        h = h * 0.5 + xv[t]
        want[t] = h
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_static_rnn_boot_memory_and_training():
    """memory(shape=, batch_ref=) boot path + gradients flow through the
    scan: a tiny seq regressor trains to a much lower loss."""
    T, B, D, H = 4, 8, 3, 6
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[T, B, D], dtype='float32',
                              append_batch_size=False)
        y = fluid.layers.data('y', shape=[B, 1], dtype='float32',
                              append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h = rnn.memory(shape=[-1, H], batch_ref=xt,
                           init_batch_dim_idx=0, ref_batch_dim_idx=0)
            nh = layers.fc(layers.concat([xt, h], axis=1), H, act='tanh')
            rnn.update_memory(h, nh)
            rnn.output(nh)
        seq = rnn()                      # [T, B, H]
        last = layers.slice(seq, axes=[0], starts=[T - 1], ends=[T])
        last = layers.reshape(last, [B, H])
        pred = layers.fc(last, 1)
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
    rng = np.random.RandomState(1)
    w = rng.rand(D, 1).astype('float32')
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(80):
            xv = rng.rand(T, B, D).astype('float32')
            yv = xv.sum(axis=0) @ w
            lv, = exe.run(main, feed={'x': xv, 'y': yv},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_static_rnn_memory_without_update_carries_through():
    T, B, D = 3, 2, 2
    x = fluid.layers.data('x', shape=[T, B, D], dtype='float32',
                          append_batch_size=False)
    h0 = fluid.layers.data('h0', shape=[B, D], dtype='float32',
                           append_batch_size=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h = rnn.memory(init=h0)          # never updated -> constant
        rnn.output(xt + h)
    rng = np.random.RandomState(2)
    xv = rng.rand(T, B, D).astype('float32')
    h0v = rng.rand(B, D).astype('float32')
    got, = fluid.Executor().run(feed={'x': xv, 'h0': h0v},
                                fetch_list=[rnn()])
    np.testing.assert_allclose(np.asarray(got), xv + h0v[None], rtol=1e-6)


# ------------------------------------------------------------ DynamicRNN

def _ragged_batch(rng, lens, D):
    return create_lod_tensor([rng.rand(l, D).astype('float32')
                              for l in lens])


def test_dynamic_rnn_masks_and_freezes():
    """Running sum over ragged rows: outputs are zero past each row's
    length and the memory freezes at the row's last valid step."""
    D = 3
    lens = [4, 2, 5]
    x = fluid.layers.data('x', shape=[D], dtype='float32', lod_level=1)
    drnn = layers.DynamicRNN()
    with drnn.block():
        xt = drnn.step_input(x)
        acc = drnn.memory(shape=[D], value=0.0)
        nacc = acc + xt
        drnn.update_memory(acc, nacc)
        drnn.output(nacc)
    out = drnn()
    last = layers.sequence_last_step(out)
    rng = np.random.RandomState(3)
    lod = _ragged_batch(rng, lens, D)
    exe = fluid.Executor()
    ov, lv = exe.run(feed={'x': lod}, fetch_list=[out, last])
    ov = np.asarray(ov)
    T = max(lens)
    assert ov.shape == (len(lens), T, D)
    for i, L in enumerate(lens):
        want = np.cumsum(lod.padded[i, :L], axis=0)
        np.testing.assert_allclose(ov[i, :L], want, rtol=1e-5)
        # zero padding past the row's length
        np.testing.assert_allclose(ov[i, L:], 0.0)
        # sequence_last_step picks the row's own last valid step
        np.testing.assert_allclose(np.asarray(lv)[i], want[-1], rtol=1e-5)


def test_dynamic_rnn_mt_decoder_trains_and_decodes():
    """The book machine-translation decoder pattern
    (reference tests/book/test_machine_translation.py:68): encoder last
    state boots the decoder DynamicRNN memory; per-step fc emits word
    scores; trained with cross-entropy, then decoded from a test clone."""
    V, E, H = 20, 8, 12
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            src = fluid.layers.data('src', shape=[1], dtype='int64',
                                    lod_level=1)
            trg = fluid.layers.data('trg', shape=[1], dtype='int64',
                                    lod_level=1)
            lab = fluid.layers.data('lab', shape=[1], dtype='int64',
                                    lod_level=1)
            semb = layers.embedding(src, size=[V, E])
            enc = layers.sequence_pool(semb, 'last')    # [B, E]
            enc_h = layers.fc(enc, H, act='tanh')
            temb = layers.embedding(trg, size=[V, E])   # [B, T, E]
            drnn = layers.DynamicRNN()
            with drnn.block():
                word = drnn.step_input(temb)            # [B, E]
                prev = drnn.memory(init=enc_h)
                h = layers.fc(layers.concat([word, prev], axis=1), H,
                              act='tanh')
                drnn.update_memory(prev, h)
                drnn.output(h)
            dec = drnn()                                # [B, T, H] lod
            # dec carries lod, so fc's lod-aware num_flatten_dims bump
            # makes the default a per-token projection (ref: fc(drnn_out,
            # size=V) on the packed LoD tensor)
            logits = layers.fc(dec, V)
            ce = layers.softmax_with_cross_entropy(logits, lab,
                                                   soft_label=False)
            # mean over VALID positions only — padded steps have zeroed
            # decoder outputs and must not contribute loss.  sequence_pool
            # masks by the lod lengths, no static maxlen needed.
            from paddle_tpu.layers.nn import _copy_lod, _len_var
            _copy_lod(lab, ce)
            per_seq = layers.sequence_pool(ce, 'sum')       # [B, 1]
            n_tok = layers.cast(
                layers.reduce_sum(_len_var(lab)), 'float32')
            loss = layers.reduce_sum(per_seq) / n_tok
            fluid.optimizer.AdamOptimizer(0.05).minimize(loss)
    rng = np.random.RandomState(4)

    def batch():
        lens = rng.randint(2, 6, size=4)
        srcs, trgs, labs = [], [], []
        for L in lens:
            s = rng.randint(2, V, (L, 1)).astype('int64')
            # toy task: emit the source's LAST token at every step — the
            # 'last'-pooled encoder state carries exactly that token, so
            # the decoder must preserve its boot memory through the scan
            srcs.append(s)
            trgs.append(np.roll(s, 1, axis=0))
            labs.append(np.full((L, 1), s[-1, 0], 'int64'))
        return {'src': create_lod_tensor(srcs),
                'trg': create_lod_tensor(trgs),
                'lab': create_lod_tensor(labs)}

    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(120):
            lv, = exe.run(main, feed=batch(), fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # decode from the inference clone: argmax at each step
        infer = main.clone(for_test=True)
        feed = batch()
        lg, = exe.run(infer, feed=feed, fetch_list=[logits])
    lg = np.asarray(lg)
    assert lg.shape[-1] == V
    dec_ids = lg.argmax(-1)
    # decoded tokens should mostly equal each row's target label
    tgt = feed['lab'].padded[:, 0, 0]
    lens = feed['lab'].lengths
    hits = sum((dec_ids[i, :lens[i]] == tgt[i]).mean()
               for i in range(len(lens))) / len(lens)
    assert hits > 0.6, hits


# ---------------------------------------------------------------- IfElse

def test_ifelse_rowwise_merge():
    B, D = 6, 4
    x = fluid.layers.data('x', shape=[B, D], dtype='float32',
                          append_batch_size=False)
    limit = layers.fill_constant(shape=[B, 1], dtype='float32', value=0.5)
    first = layers.slice(x, axes=[1], starts=[0], ends=[1])   # [B, 1]
    cond = layers.less_than(first, limit)
    ie = layers.IfElse(cond)
    with ie.true_block():
        xt = ie.input(x)
        ie.output(layers.scale(xt, scale=2.0))
    with ie.false_block():
        xf = ie.input(x)
        ie.output(xf + 10.0)
    merged, = ie()
    rng = np.random.RandomState(5)
    xv = rng.rand(B, D).astype('float32')
    got, = fluid.Executor().run(feed={'x': xv}, fetch_list=[merged])
    mask = xv[:, :1] < 0.5
    want = np.where(mask, xv * 2.0, xv + 10.0)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_ifelse_fc_branches_train():
    """The reference docstring pattern: different fc stacks per branch,
    merged probabilities trainable end to end."""
    B, D, C = 8, 5, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[B, D], dtype='float32',
                              append_batch_size=False)
        y = fluid.layers.data('y', shape=[B, 1], dtype='int64',
                              append_batch_size=False)
        gate = layers.slice(x, axes=[1], starts=[0], ends=[1])
        half = layers.fill_constant([B, 1], 'float32', 0.5)
        cond = layers.less_than(gate, half)
        ie = layers.IfElse(cond)
        with ie.true_block():
            xt = ie.input(x)
            ie.output(layers.fc(xt, C))
        with ie.false_block():
            xf = ie.input(x)
            ie.output(layers.fc(layers.fc(xf, 16, act='tanh'), C))
        logits, = ie()
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.AdamOptimizer(0.05).minimize(loss)
    rng = np.random.RandomState(6)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(60):
            xv = rng.rand(B, D).astype('float32')
            yv = (xv[:, :1] < 0.5).astype('int64')  # branch-correlated
            lv, = exe.run(main, feed={'x': xv, 'y': yv},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_ifelse_single_branch_zeroes_unselected_rows():
    B = 4
    x = fluid.layers.data('x', shape=[B, 2], dtype='float32',
                          append_batch_size=False)
    first = layers.slice(x, axes=[1], starts=[0], ends=[1])
    half = layers.fill_constant([B, 1], 'float32', 0.5)
    cond = layers.less_than(first, half)
    ie = layers.IfElse(cond)
    with ie.true_block():
        ie.output(ie.input(x) * 3.0)
    outs = ie()
    assert isinstance(outs, list) and len(outs) == 1
    rng = np.random.RandomState(7)
    xv = rng.rand(B, 2).astype('float32')
    got, = fluid.Executor().run(feed={'x': xv}, fetch_list=[outs[0]])
    mask = xv[:, :1] < 0.5
    np.testing.assert_allclose(np.asarray(got),
                               np.where(mask, xv * 3.0, 0.0), rtol=1e-6)


@pytest.mark.parametrize('which', ['all_true', 'all_false'])
def test_ifelse_degenerate_masks(which):
    """Every row takes ONE branch: the select-masking merge must not be
    poisoned by the other (empty) branch — including through gradients
    (NaN/Inf from a degenerate branch would leak via 0*inf)."""
    B, D = 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x0 = fluid.layers.data('x', shape=[B, D], dtype='float32',
                                   append_batch_size=False)
            x = layers.fc(x0, D, bias_attr=False,
                          param_attr=fluid.ParamAttr(
                              name='deg_w', initializer=fluid.initializer.
                              NumpyArrayInitializer(np.eye(D, dtype='float32'))))
            limit = layers.fill_constant([B, 1], 'float32',
                                         2.0 if which == 'all_true'
                                         else -2.0)
            first = layers.slice(x, axes=[1], starts=[0], ends=[1])
            cond = layers.less_than(first, limit)   # rows in [0,1)
            ie = layers.IfElse(cond)
            with ie.true_block():
                xt = ie.input(x)
                ie.output(layers.scale(xt, scale=2.0))
            with ie.false_block():
                xf = ie.input(x)
                # sqrt: NaN gradients for the masked-out branch would
                # poison the merge (and the fc weight grad) if wrong
                ie.output(layers.sqrt(xf))
            merged, = ie()
            loss = layers.reduce_mean(merged)
            fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    xv = np.random.RandomState(0).rand(B, D).astype('float32')
    with fluid.scope_guard(scope):
        exe.run(startup)
        got, lv = exe.run(main, feed={'x': xv},
                          fetch_list=[merged, loss])
        w1 = np.asarray(scope.get('deg_w'))
    want = xv * 2.0 if which == 'all_true' else np.sqrt(xv)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)
    assert np.isfinite(float(np.asarray(lv).ravel()[0]))
    assert np.isfinite(w1).all()  # no NaN grads leaked into the update


def test_switch_default_and_order():
    """Switch: first matching case wins; default fires when none match."""
    def run(lr_val):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                step = layers.fill_constant([1], 'float32', lr_val)
                out = fluid.layers.create_global_var(
                    [1], 0.0, 'float32', persistable=True, name='sw_out')
                with fluid.layers.Switch() as switch:
                    with switch.case(layers.less_than(
                            step, layers.fill_constant([1], 'float32',
                                                       1.0))):
                        layers.assign(layers.fill_constant(
                            [1], 'float32', 111.0), out)
                    with switch.case(layers.less_than(
                            step, layers.fill_constant([1], 'float32',
                                                       2.0))):
                        layers.assign(layers.fill_constant(
                            [1], 'float32', 222.0), out)
                    with switch.default():
                        layers.assign(layers.fill_constant(
                            [1], 'float32', 333.0), out)
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            v, = exe.run(main, fetch_list=['sw_out'])
        return float(np.asarray(v).ravel()[0])

    assert run(0.5) == 111.0    # first case (also matches second)
    assert run(1.5) == 222.0
    assert run(5.0) == 333.0    # default


def test_switch_multi_assign_and_const_values():
    """Every assign in one case body blends with the SAME case mask
    (a per-assign registration would mask the second assign to a no-op),
    and non-Variable values (python lists) materialize correctly."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            step = layers.fill_constant([1], 'float32', 0.5)
            a = fluid.layers.create_global_var([1], 0.0, 'float32',
                                               persistable=True, name='ma')
            b = fluid.layers.create_global_var([2], 0.0, 'float32',
                                               persistable=True, name='mb')
            one = layers.fill_constant([1], 'float32', 1.0)
            with fluid.layers.Switch() as switch:
                with switch.case(layers.less_than(step, one)):
                    layers.assign(layers.fill_constant([1], 'float32',
                                                       11.0), a)
                    layers.assign(np.array([22.0, 33.0], 'float32'), b)
                with switch.default():
                    layers.assign(layers.fill_constant([1], 'float32',
                                                       -1.0), a)
                    layers.assign(np.array([-2.0, -3.0], 'float32'), b)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        av, bv = exe.run(main, fetch_list=['ma', 'mb'])
    np.testing.assert_allclose(np.asarray(av), [11.0])
    np.testing.assert_allclose(np.asarray(bv), [22.0, 33.0])


def test_switch_nested_raises():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        cond = layers.less_than(layers.fill_constant([1], 'float32', 0.0),
                                layers.fill_constant([1], 'float32', 1.0))
        out = fluid.layers.create_global_var([1], 0.0, 'float32',
                                             persistable=True, name='nso')
        with fluid.layers.Switch() as outer:
            with outer.case(cond):
                inner = fluid.layers.Switch()
                with pytest.raises(NotImplementedError):
                    with inner.case(cond):
                        layers.assign(layers.fill_constant(
                            [1], 'float32', 1.0), out)


def test_static_rnn_boot_memory_dynamic_batch():
    """Reference programs built with default append_batch_size=True have
    batch dim -1; StaticRNN.memory(shape=, batch_ref=) must boot via
    fill_constant_batch_size_like (VERDICT r4 #6) — the batch is only
    known at feed time, and different batch sizes run the same program."""
    T, D, H = 3, 2, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        # time-major sequence with an UNKNOWN batch dim
        x = fluid.layers.data('x', shape=[T, -1, D], dtype='float32',
                              append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)       # [-1, D] step slice
            h = rnn.memory(shape=[-1, D], batch_ref=xt,
                           init_batch_dim_idx=0, ref_batch_dim_idx=0,
                           init_value=0.0)
            nh = layers.elementwise_add(h, xt)   # running sum
            rnn.update_memory(h, nh)
            rnn.output(nh)
        seq = rnn()                      # [T, B, D]
    exe = fluid.Executor()
    for B in (2, 5):                     # same program, two batch sizes
        xv = np.arange(T * B * D, dtype='float32').reshape(T, B, D)
        got, = exe.run(main, feed={'x': xv}, fetch_list=[seq])
        np.testing.assert_allclose(got, np.cumsum(xv, axis=0), rtol=1e-6)
