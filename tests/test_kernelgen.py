"""Pallas codegen tier (ops/kernelgen): per-rule bitwise parity vs the
reference replay, the fused-Adam single-kernel contract, loud fallback
semantics (PT_STRICT_KERNELS), emitter/launch-signature integration, AOT
disk-cache round trip, and end-to-end parity through run / run_steps /
ParallelExecutor under AMP + dropout.

Parity contract (docs/kernels.md): a generated kernel is BITWISE equal
to the jitted replay of the same fused group — both lower through XLA,
and impl-passthrough bodies run the identical jnp expressions lane for
lane.  Whole-TRAINING-RUN equality is weaker: XLA fuses broadcast-grad
reductions differently around an opaque pallas call than around an
inlined elementwise chain (1-2 ulp per step), so multi-step e2e checks
use a drift tolerance while the first launch stays at 1e-6.
"""
import os
import sys
import warnings

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault('JAX_PLATFORMS', 'cpu')

import jax                                            # noqa: E402
import jax.numpy as jnp                               # noqa: E402

import paddle_tpu as fluid                            # noqa: E402
import paddle_tpu.observability as obs                # noqa: E402
from paddle_tpu.ops import fused as _fused            # noqa: E402
from paddle_tpu.ops import kernelgen as kg            # noqa: E402
from paddle_tpu.ops.kernelgen import builder          # noqa: E402


# ------------------------------------------------------------- helpers

def _sub(type_, inputs, outputs, attrs=None, stop_grad=()):
    return {'type': type_, 'inputs': inputs, 'outputs': outputs,
            'input_is_list': {}, 'output_is_list': {},
            'attrs': dict(attrs or {}), 'stop_grad': list(stop_grad)}


def _attrs(sub_ops, arg_names, out_names):
    return {'sub_ops': sub_ops, 'arg_names': list(arg_names),
            'out_names': list(out_names)}


class _SeqKeyCtx(object):
    """Replay ctx: hands out per-rng-sub keys in call order (the same
    keys the kernel path receives), no AMP."""
    amp = False
    mesh = None

    def __init__(self, keys):
        self._keys = list(keys)
        self._i = 0

    def sub_ctx(self, sub):
        return self

    def rng(self, n=0):
        k = self._keys[self._i]
        self._i += 1
        return k


def _replay(attrs, xs, keys, amp=False):
    env = dict(zip(attrs['arg_names'], xs))
    # seeded rng subs derive their own key internally; only unseeded
    # ones pull from ctx.rng — hand the ctx exactly those keys
    unseeded = []
    si = 0
    for sub in attrs['sub_ops']:
        if sub['type'] in kg.rng_rule_types():
            if not sub['attrs'].get('seed', 0):
                unseeded.append(keys[si])
            si += 1
    ctx = _SeqKeyCtx(unseeded)
    ctx.amp = amp
    for sub in attrs['sub_ops']:
        _fused._run_sub_op(ctx, sub, env, amp)
    return [env[n] for n in attrs['out_names']]


def _keys(attrs, seed=3):
    base = jax.random.key(seed)
    return kg._keys_for(attrs, lambda si, sub: jax.random.fold_in(base,
                                                                  si))


def _assert_plan_bitwise(attrs, xs, amp=False, expect_kernels=None):
    """plan.fn vs jitted replay, both under jax.jit (the executor always
    jits; eager XLA makes different FMA-contraction choices)."""
    xs = tuple(xs)
    keys = _keys(attrs)
    plan = kg.plan_for(attrs, kg._in_avals(xs), amp)
    if expect_kernels is not None:
        assert plan.n_kernels == expect_kernels, plan.kernel_ops
    kouts = jax.jit(plan.fn)(xs, keys)
    routs = jax.jit(lambda x, k: _replay(attrs, x, k, amp))(xs, keys)
    assert len(kouts) == len(routs)
    for n, ko, ro in zip(attrs['out_names'], kouts, routs):
        ka, ra = np.asarray(ko), np.asarray(ro)
        assert ka.dtype == ra.dtype and ka.shape == ra.shape, n
        np.testing.assert_array_equal(ka, ra, err_msg=n)
    return plan


def _rand(rng, shape, dtype='float32', lo=0.25, hi=0.75):
    return jnp.asarray(
        (rng.rand(*shape) * (hi - lo) + lo).astype(dtype))


# ------------------------------------------- per-rule bitwise sweep

def test_rule_sweep_activation_chain():
    rng = np.random.RandomState(0)
    attrs = _attrs(
        [_sub('scale', {'X': ['x']}, {'Out': ['a']},
              {'scale': 1.7, 'bias': 0.3}),
         _sub('tanh', {'X': ['a']}, {'Out': ['b']}),
         _sub('sigmoid', {'X': ['b']}, {'Out': ['c']}),
         _sub('relu', {'X': ['c']}, {'Out': ['d']})],
        ['x'], ['d'])
    _assert_plan_bitwise(attrs, [_rand(rng, (6, 16))], expect_kernels=1)


def test_rule_sweep_binary_broadcasts():
    rng = np.random.RandomState(1)
    x = _rand(rng, (4, 8))
    bias = _rand(rng, (8,))
    scalar = _rand(rng, (1,))
    attrs = _attrs(
        [_sub('elementwise_add', {'X': ['x'], 'Y': ['b']},
              {'Out': ['s']}, {'axis': -1}),
         _sub('elementwise_mul', {'X': ['s'], 'Y': ['c']},
              {'Out': ['m']}, {'axis': -1}),
         _sub('elementwise_max', {'X': ['m'], 'Y': ['x']},
              {'Out': ['o']}, {'axis': -1})],
        ['x', 'b', 'c'], ['o'])
    _assert_plan_bitwise(attrs, [x, bias, scalar], expect_kernels=1)


def test_rule_sweep_compare_and_logic_bool_outputs():
    rng = np.random.RandomState(2)
    x, y = _rand(rng, (5, 7)), _rand(rng, (5, 7))
    attrs = _attrs(
        [_sub('less_than', {'X': ['x'], 'Y': ['y']}, {'Out': ['lt']},
              {'axis': -1}),
         _sub('greater_equal', {'X': ['x'], 'Y': ['y']},
              {'Out': ['ge']}, {'axis': -1}),
         _sub('logical_or', {'X': ['lt'], 'Y': ['ge']},
              {'Out': ['o']})],
        ['x', 'y'], ['lt', 'o'])
    _assert_plan_bitwise(attrs, [x, y], expect_kernels=1)


def test_rule_sweep_fill_cast_increment():
    rng = np.random.RandomState(3)
    x = _rand(rng, (3, 4))
    attrs = _attrs(
        [_sub('fill_constant', {}, {'Out': ['c']},
              {'shape': [3, 4], 'value': np.int64(2), 'dtype': 'int64'}),
         _sub('cast', {'X': ['c']}, {'Out': ['cf']},
              {'out_dtype': 'float32', 'in_dtype': 'int64'}),
         _sub('elementwise_pow', {'X': ['x'], 'Y': ['cf']},
              {'Out': ['p']}, {'axis': -1}),
         _sub('increment', {'X': ['p']}, {'Out': ['o']}, {'step': 0.5})],
        ['x'], ['o'])
    with warnings.catch_warnings():
        warnings.simplefilter('error', UserWarning)  # int64 stays silent
        _assert_plan_bitwise(attrs, [x], expect_kernels=1)


def test_rule_sweep_label_smooth_logical_shape():
    rng = np.random.RandomState(4)
    x = _rand(rng, (6, 10))
    attrs = _attrs(
        [_sub('label_smooth', {'X': ['x']}, {'Out': ['o']},
              {'epsilon': 0.1})],
        ['x'], ['o'])
    _assert_plan_bitwise(attrs, [x], expect_kernels=1)


def test_rule_sweep_dropout_train_and_test():
    rng = np.random.RandomState(5)
    x = _rand(rng, (8, 12))
    for extra in ({'dropout_prob': 0.4,
                   'dropout_implementation': 'upscale_in_train'},
                  {'dropout_prob': 0.4, 'is_test': True}):
        attrs = _attrs(
            [_sub('scale', {'X': ['x']}, {'Out': ['s']}, {'scale': 2.0}),
             _sub('dropout', {'X': ['s']}, {'Out': ['o'],
                                            'Mask': ['m']}, extra)],
            ['x'], ['o', 'm'])
        _assert_plan_bitwise(attrs, [x], expect_kernels=1)


def test_rule_sweep_seeded_dropout_matches_impl_seed_path():
    rng = np.random.RandomState(6)
    x = _rand(rng, (4, 6))
    attrs = _attrs(
        [_sub('dropout', {'X': ['x']}, {'Out': ['o'], 'Mask': ['m']},
              {'dropout_prob': 0.3, 'seed': 11,
               'dropout_implementation': 'upscale_in_train'})],
        ['x'], ['o', 'm'])
    _assert_plan_bitwise(attrs, [x], expect_kernels=1)


def test_rule_sweep_uniform_random_whole_draw():
    attrs = _attrs(
        [_sub('uniform_random', {}, {'Out': ['u']},
              {'shape': [4, 8], 'min': -1.0, 'max': 1.0,
               'dtype': 'float32'}),
         _sub('abs', {'X': ['u']}, {'Out': ['o']})],
        [], ['o'])
    _assert_plan_bitwise(attrs, [])


def test_rule_sweep_layout_glue_segments():
    """An order-changing transpose splits the group into two kernels
    with an XLA glue step between — still bitwise."""
    rng = np.random.RandomState(7)
    x = _rand(rng, (6, 10))
    attrs = _attrs(
        [_sub('scale', {'X': ['x']}, {'Out': ['a']}, {'scale': 3.0}),
         _sub('transpose', {'X': ['a']}, {'Out': ['t']},
              {'axis': [1, 0]}),
         _sub('relu', {'X': ['t']}, {'Out': ['o']})],
        ['x'], ['o'])
    plan = _assert_plan_bitwise(attrs, [x])
    assert plan.n_kernels == 2 and plan.n_glue >= 1


def test_rule_sweep_flat_preserving_reshapes_stay_fused():
    rng = np.random.RandomState(8)
    x = _rand(rng, (4, 6))
    attrs = _attrs(
        [_sub('scale', {'X': ['x']}, {'Out': ['a']}, {'scale': 0.5}),
         _sub('reshape', {'X': ['a']}, {'Out': ['r']},
              {'shape': [24]}),
         _sub('unsqueeze', {'X': ['r']}, {'Out': ['u']},
              {'axes': [0]}),
         _sub('relu', {'X': ['u']}, {'Out': ['o']})],
        ['x'], ['o'])
    _assert_plan_bitwise(attrs, [x], expect_kernels=1)


def test_rule_sweep_sgd_momentum():
    rng = np.random.RandomState(9)
    p, g, v = (_rand(rng, (3, 5)) for _ in range(3))
    lr = jnp.asarray(np.float32([0.01]))
    attrs = _attrs(
        [_sub('sgd', {'Param': ['p'], 'Grad': ['g'],
                      'LearningRate': ['lr']},
              {'ParamOut': ['p']}, {}, stop_grad=['p'])],
        ['p', 'g', 'lr'], ['p'])
    _assert_plan_bitwise(attrs, [p, g, lr], expect_kernels=1)
    attrs = _attrs(
        [_sub('momentum', {'Param': ['p'], 'Grad': ['g'],
                           'Velocity': ['v'], 'LearningRate': ['lr']},
              {'ParamOut': ['p'], 'VelocityOut': ['v']},
              {'mu': 0.9}, stop_grad=['p', 'v'])],
        ['p', 'g', 'v', 'lr'], ['p', 'v'])
    _assert_plan_bitwise(attrs, [p, g, v, lr], expect_kernels=1)


# ------------------------------------------------ fused-Adam contract

def _adam_group(shapes, rng):
    """One fused group of per-param adam subs sharing lr (the shape the
    fuse pass builds for a whole optimizer step)."""
    subs, args, outs, xs = [], [], [], []
    lrname = 'lr'
    for i, shape in enumerate(shapes):
        names = {k: '%s_%d' % (k, i) for k in
                 ('p', 'g', 'm1', 'm2', 'b1p', 'b2p')}
        subs.append(_sub(
            'adam',
            {'Param': [names['p']], 'Grad': [names['g']],
             'Moment1': [names['m1']], 'Moment2': [names['m2']],
             'Beta1Pow': [names['b1p']], 'Beta2Pow': [names['b2p']],
             'LearningRate': [lrname]},
            {'ParamOut': [names['p']], 'Moment1Out': [names['m1']],
             'Moment2Out': [names['m2']]},
            {'beta1': 0.9, 'beta2': 0.997, 'epsilon': 1e-9},
            stop_grad=[names['p'], names['m1'], names['m2']]))
        for k in ('p', 'g', 'm1', 'm2'):
            args.append(names[k])
            xs.append(_rand(rng, shape))
        for k in ('b1p', 'b2p'):
            args.append(names[k])
            xs.append(jnp.asarray(np.float32([0.9 if k == 'b1p'
                                              else 0.997])))
        outs += [names['p'], names['m1'], names['m2']]
    args.append(lrname)
    xs.append(jnp.asarray(np.float32([0.002])))
    return _attrs(subs, args, outs), xs


def test_fused_adam_one_kernel_multi_group():
    """Mixed param sizes (multi-group kernel) still plan to ONE pallas
    call, donate the param/moment buffers, and match ops/optimizer_ops
    adam bitwise."""
    rng = np.random.RandomState(10)
    attrs, xs = _adam_group([(32, 64), (64,), (16, 16), (1, 8)], rng)
    plan = _assert_plan_bitwise(attrs, xs, expect_kernels=1)
    assert plan.n_donated > 0

    # cross-check against the registered adam impl applied per param.
    # This is a DIFFERENT compiled program, so XLA's FMA-contraction
    # freedom allows 1-2 ulp (bitwise only holds within one program —
    # the replay comparison above); bound it at float32 ulp scale.
    from paddle_tpu.core.registry import get_op
    adam = get_op('adam').impl
    kouts = plan.fn(tuple(xs), ())
    env = dict(zip(attrs['arg_names'], xs))
    ptr = 0
    for i in range(4):
        ins = {'Param': env['p_%d' % i], 'Grad': env['g_%d' % i],
               'Moment1': env['m1_%d' % i], 'Moment2': env['m2_%d' % i],
               'Beta1Pow': env['b1p_%d' % i],
               'Beta2Pow': env['b2p_%d' % i], 'LearningRate': env['lr']}
        want = jax.jit(lambda ins=ins: adam(
            None, ins, {'beta1': 0.9, 'beta2': 0.997,
                        'epsilon': 1e-9}))()
        for slot in ('ParamOut', 'Moment1Out', 'Moment2Out'):
            np.testing.assert_allclose(
                np.asarray(kouts[ptr]), np.asarray(want[slot]),
                rtol=3e-7, atol=1e-9,
                err_msg='param %d %s' % (i, slot))
            ptr += 1


# --------------------------------------- interpret mode + direct kernel

def test_interpret_mode_on_cpu_and_small_blocks(monkeypatch):
    assert builder._interpret()  # CPU backend => interpret kernels
    monkeypatch.setenv('PT_KERNELGEN_BLOCK', '8')
    kg.clear_plan_cache()
    try:
        rng = np.random.RandomState(11)
        x = _rand(rng, (5, 9))  # 45 lanes: ragged multi-tile grid
        attrs = _attrs(
            [_sub('scale', {'X': ['x']}, {'Out': ['a']}, {'scale': 2.0}),
             _sub('sqrt', {'X': ['a']}, {'Out': ['o']})],
            ['x'], ['o'])
        plan = _assert_plan_bitwise(attrs, [x], expect_kernels=1)
        out = plan.fn((x,), ())
        np.testing.assert_array_equal(
            np.asarray(out[0]), np.asarray(jnp.sqrt(x * 2.0)))
    finally:
        kg.clear_plan_cache()


def test_grad_through_generated_kernel_matches_replay():
    rng = np.random.RandomState(12)
    x = _rand(rng, (4, 8))
    attrs = _attrs(
        [_sub('scale', {'X': ['x']}, {'Out': ['a']}, {'scale': 1.3}),
         _sub('tanh', {'X': ['a']}, {'Out': ['o']})],
        ['x'], ['o'])
    plan = kg.plan_for(attrs, kg._in_avals([x]), False)
    gk = jax.jit(jax.grad(lambda v: jnp.sum(plan.fn((v,), ())[0])))(x)
    gr = jax.jit(jax.grad(
        lambda v: jnp.sum(_replay(attrs, (v,), ())[0])))(x)
    np.testing.assert_array_equal(np.asarray(gk), np.asarray(gr))


# ----------------------------------------------- loud fallback contract

def _unsupported_attrs():
    # reduce_sum: registered op, no KERNEL_RULES entry (softmax no
    # longer qualifies — it graduated to a dedicated row kernel)
    return _attrs(
        [_sub('scale', {'X': ['x']}, {'Out': ['a']}, {'scale': 2.0}),
         _sub('reduce_sum', {'X': ['a']}, {'Out': ['o']},
              {'dim': [-1], 'keep_dim': False})],
        ['x'], ['o'])


class _PlainCtx(object):
    amp = False
    mesh = None

    def sub_ctx(self, sub):
        return self

    def rng(self, n=0):
        return jax.random.key(0)


def test_strict_kernels_raises_naming_sub_op(monkeypatch):
    monkeypatch.setenv('PT_KERNELGEN', '1')
    monkeypatch.setenv('PT_STRICT_KERNELS', '1')
    from paddle_tpu.core.registry import get_op
    x = jnp.ones((2, 3), jnp.float32)
    with pytest.raises(RuntimeError) as ei:
        get_op('fused_elementwise').impl(_PlainCtx(), {'X': [x]},
                                         _unsupported_attrs())
    msg = str(ei.value)
    assert 'reduce_sum' in msg and 'PT_STRICT_KERNELS' in msg


def test_fallback_counts_warns_once_and_replays(monkeypatch):
    monkeypatch.setenv('PT_KERNELGEN', '1')
    monkeypatch.delenv('PT_STRICT_KERNELS', raising=False)
    from paddle_tpu.core.registry import get_op
    from paddle_tpu.ops import _fallback
    _fallback._warned.discard('kernelgen')
    x = jnp.full((2, 3), 0.5, jnp.float32)
    before = obs.counters().get('kernelgen.fallbacks') or 0
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        out = get_op('fused_elementwise').impl(
            _PlainCtx(), {'X': [x]}, _unsupported_attrs())
        out2 = get_op('fused_elementwise').impl(
            _PlainCtx(), {'X': [x]}, _unsupported_attrs())
    relevant = [x for x in w if 'kernelgen' in str(x.message)]
    assert len(relevant) == 1, 'fallback must warn exactly once'
    assert 'reduce_sum' in str(relevant[0].message)
    after = obs.counters().get('kernelgen.fallbacks') or 0
    assert after == before + 2
    want = jnp.sum(x * 2.0, axis=-1)
    np.testing.assert_allclose(np.asarray(out['Out'][0]),
                               np.asarray(want), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(out['Out'][0]),
                                  np.asarray(out2['Out'][0]))


def test_unsupported_sub_ops_lists_gaps_once():
    assert kg.unsupported_sub_ops(_unsupported_attrs()) == ['reduce_sum']
    assert kg.unsupported_sub_ops(
        _attrs([_sub('relu', {'X': ['x']}, {'Out': ['o']})],
               ['x'], ['o'])) == []


# ----------------------- dedicated kernels: row + attention kinds

def test_softmax_row_kernel_bitwise_with_grad():
    rng = np.random.RandomState(13)
    x = _rand(rng, (6, 33))  # 33 cols + 6 rows: ragged row-block grid
    attrs = _attrs(
        [_sub('softmax', {'X': ['x']}, {'Out': ['o']}, {'axis': -1})],
        ['x'], ['o'])
    plan = _assert_plan_bitwise(attrs, [x])
    assert plan.n_dsteps == 1
    gk = jax.jit(jax.grad(
        lambda v: jnp.sum(plan.fn((v,), ())[0] ** 2)))(x)
    gr = jax.jit(jax.grad(
        lambda v: jnp.sum(_replay(attrs, (v,), ())[0] ** 2)))(x)
    np.testing.assert_array_equal(np.asarray(gk), np.asarray(gr))


def test_softmax_3d_trailing_axis_and_fused_neighbors():
    rng = np.random.RandomState(14)
    x = _rand(rng, (2, 5, 9))
    attrs = _attrs(
        [_sub('scale', {'X': ['x']}, {'Out': ['a']}, {'scale': 1.7}),
         _sub('softmax', {'X': ['a']}, {'Out': ['s']}, {'axis': -1}),
         _sub('relu', {'X': ['s']}, {'Out': ['o']})],
        ['x'], ['o'])
    plan = _assert_plan_bitwise(attrs, [x])
    assert plan.n_dsteps == 1


def test_layer_norm_row_kernel_three_outputs_and_grads():
    rng = np.random.RandomState(15)
    x = _rand(rng, (6, 10))
    scale, bias = _rand(rng, (10,)), _rand(rng, (10,))
    attrs = _attrs(
        [_sub('layer_norm', {'X': ['x'], 'Scale': ['s'], 'Bias': ['b']},
              {'Y': ['y'], 'Mean': ['m'], 'Variance': ['v']},
              {'begin_norm_axis': 1, 'epsilon': 1e-5},
              stop_grad=['m', 'v'])],
        ['x', 's', 'b'], ['y', 'm', 'v'])
    plan = _assert_plan_bitwise(attrs, [x, scale, bias])
    assert plan.n_dsteps == 1
    # AMP policy reproduced (executor _amp_sub_ins/_amp_sub_outs)
    _assert_plan_bitwise(attrs, [x, scale, bias], amp=True)
    gk = jax.jit(jax.grad(
        lambda a, s, b: jnp.sum(plan.fn((a, s, b), ())[0] ** 2),
        argnums=(0, 1, 2)))(x, scale, bias)
    gr = jax.jit(jax.grad(
        lambda a, s, b: jnp.sum(_replay(attrs, (a, s, b), ())[0] ** 2),
        argnums=(0, 1, 2)))(x, scale, bias)
    for name, a, b in zip(('dx', 'dscale', 'dbias'), gk, gr):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_layer_norm_two_pass_env_still_bitwise(monkeypatch):
    monkeypatch.setenv('PT_TWO_PASS_NORM', '1')
    kg.clear_plan_cache()
    try:
        rng = np.random.RandomState(16)
        x = _rand(rng, (4, 8))
        attrs = _attrs(
            [_sub('layer_norm', {'X': ['x']},
                  {'Y': ['y'], 'Mean': ['m'], 'Variance': ['v']},
                  {'begin_norm_axis': 1, 'epsilon': 1e-5},
                  stop_grad=['m', 'v'])],
            ['x'], ['y', 'm', 'v'])
        _assert_plan_bitwise(attrs, [x])
    finally:
        kg.clear_plan_cache()


def test_flash_attention_plan_matches_replay_with_grads():
    """The dstep passes through ops/attention.flash_attention — same
    custom_vjp as the registered impl, so fwd AND grads are bitwise."""
    rng = np.random.RandomState(17)
    q, k, v = (_rand(rng, (2, 2, 16, 8)) for _ in range(3))
    attrs = _attrs(
        [_sub('flash_attention', {'Q': ['q'], 'K': ['k'], 'V': ['v']},
              {'Out': ['o']}, {'causal': True})],
        ['q', 'k', 'v'], ['o'])
    plan = _assert_plan_bitwise(attrs, [q, k, v])
    assert plan.n_dsteps == 1
    gk = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(plan.fn((a, b, c), ())[0] ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(_replay(attrs, (a, b, c), ())[0] ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip(('dq', 'dk', 'dv'), gk, gr):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


# ------------------------------------------------- tile/block autotuner

def _softmax_attrs():
    return _attrs(
        [_sub('softmax', {'X': ['x']}, {'Out': ['o']}, {'axis': -1})],
        ['x'], ['o'])


def _autotune_counters():
    c = obs.counters()
    return (c.get('kernelgen.autotune_searches') or 0,
            c.get('kernelgen.autotune_cache_hits') or 0)


def test_autotune_searches_once_persists_and_is_deterministic(
        tmp_path, monkeypatch):
    from paddle_tpu.ops.kernelgen import autotune
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    monkeypatch.setenv('PT_AUTOTUNE', '1')
    kg.clear_plan_cache()
    autotune.clear_memory()
    try:
        rng = np.random.RandomState(18)
        x = _rand(rng, (64, 16))  # 64 rows: {8, 32, 64} row candidates
        attrs = _softmax_attrs()
        s0, h0 = _autotune_counters()
        plan1 = _assert_plan_bitwise(attrs, [x])
        s1, _ = _autotune_counters()
        assert s1 > s0, 'cold build must pay a timed search'
        assert plan1.tuned and 'block_rows' in plan1.tuned[0]
        store = os.path.join(str(tmp_path), 'autotune')
        assert os.path.isdir(store) and os.listdir(store), \
            'the winning choice must persist in the AOT cache dir'
        # simulate a fresh process: drop the plan cache and the memo;
        # the disk store answers — zero new searches, identical choice
        kg.clear_plan_cache()
        autotune.clear_memory()
        plan2 = kg.plan_for(attrs, kg._in_avals([x]), False)
        s2, h2 = _autotune_counters()
        assert s2 == s1, 'warm rebuild must not re-search'
        assert h2 > h0, 'warm rebuild must hit the persisted store'
        assert plan2.tuned == plan1.tuned
    finally:
        kg.clear_plan_cache()
        autotune.clear_memory()


def test_autotune_cached_mode_uses_static_default(monkeypatch):
    from paddle_tpu.ops.kernelgen import autotune
    monkeypatch.setenv('PT_AUTOTUNE', 'cached')
    monkeypatch.setenv('PT_CACHE', '0')
    kg.clear_plan_cache()
    autotune.clear_memory()
    try:
        rng = np.random.RandomState(19)
        x = _rand(rng, (64, 16))
        s0, _ = _autotune_counters()
        plan = _assert_plan_bitwise(_softmax_attrs(), [x])
        s1, _ = _autotune_counters()
        assert s1 == s0, 'cached mode must never search'
        assert plan.tuned == [{'block_rows': 64}]  # min(128, rows)
    finally:
        kg.clear_plan_cache()
        autotune.clear_memory()


def test_autotune_off_mode_and_lint_ctx_never_time(monkeypatch):
    from paddle_tpu.ops.kernelgen import autotune
    calls = []

    def timer(cand):
        calls.append(cand)
        return 1.0

    monkeypatch.setenv('PT_AUTOTUNE', '0')
    assert autotune.choose('row', ('sig',), [{'a': 1}, {'a': 2}],
                           timer, {'a': 9}, True) == {'a': 9}
    monkeypatch.setenv('PT_AUTOTUNE', '1')
    monkeypatch.setenv('PT_CACHE', '0')
    autotune.clear_memory()
    assert autotune.choose('row', ('sig',), [{'a': 1}, {'a': 2}],
                           timer, {'a': 9}, False) == {'a': 9}
    assert calls == [], 'allow_search=False (lint ctx) must never time'
    autotune.clear_memory()


# ------------------------------ default-on + interpret misconfiguration

def test_enabled_defaults_on_only_for_tpu_backend(monkeypatch):
    monkeypatch.delenv('PT_KERNELGEN', raising=False)
    assert not kg.enabled(), 'CPU session: tier defaults OFF'
    monkeypatch.setattr(jax, 'default_backend', lambda: 'tpu')
    assert kg.enabled(), 'TPU session: tier defaults ON'
    monkeypatch.setenv('PT_KERNELGEN', '0')
    assert not kg.enabled(), 'explicit 0 wins on TPU'
    monkeypatch.setattr(jax, 'default_backend', lambda: 'cpu')
    monkeypatch.setenv('PT_KERNELGEN', '1')
    assert kg.enabled(), 'explicit 1 wins off TPU'


def test_interpret_forced_off_without_tpu_raises(monkeypatch):
    monkeypatch.setenv('PT_KERNELGEN_INTERPRET', '0')
    with pytest.raises(kg.KernelgenUnsupported) as ei:
        builder._interpret()
    msg = str(ei.value)
    assert 'no TPU' in msg and 'interpret' in msg


# ------------------------------------- config tokens and fingerprints

def test_config_token_and_fingerprint_extra(monkeypatch):
    monkeypatch.setenv('PT_KERNELGEN', '1')
    tok_on = kg.config_token()
    monkeypatch.setenv('PT_KERNELGEN', '0')
    tok_off = kg.config_token()
    assert tok_on != tok_off and tok_on[0] == 'kernelgen'
    fp = kg.fingerprint_extra()
    assert fp[0] == 'kernelgen' and fp[1] == kg.KERNELGEN_VERSION
    assert 'adam' in fp[2] and 'dropout' in fp[2]

    # executor composition: kernelgen OFF leaves old fingerprints
    # untouched; ON composes on both emit and trace paths
    from paddle_tpu.core import executor as em
    monkeypatch.setenv('PT_KERNELGEN', '1')
    assert em._compose_fp_extra(None) == fp
    assert em._compose_fp_extra(('emit', 1)) == (('emit', 1), fp)
    monkeypatch.setenv('PT_KERNELGEN', '0')
    assert em._compose_fp_extra(('emit', 1)) == ('emit', 1)
    assert em._compose_fp_extra(None) is None


# --------------------------------------------- end-to-end through fluid

def _train_model(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            lbl = fluid.layers.data('lbl', shape=[1], dtype='int64')
            h = fluid.layers.fc(x, 16, act='relu')
            h = fluid.layers.dropout(h, dropout_prob=0.4)
            logits = fluid.layers.fc(h, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.Adam(0.01).minimize(loss)
    main.set_amp(True)
    return main, startup, loss


def _feeds(K, batch=6, seed=0):
    rng = np.random.RandomState(seed)
    return [{'x': rng.randn(batch, 8).astype('float32'),
             'lbl': rng.randint(0, 4, (batch, 1)).astype('int64')}
            for _ in range(K)]


def _train(monkeypatch, pt_kg, runner, seed):
    # hermetic vs the shared AOT disk cache and the process-wide emitter
    # memo: either would serve an already-built (kernelgen-built, still
    # correct) callable without re-tracing, and kernelgen.ops only
    # counts fresh builds
    from paddle_tpu.core.emit import emitter
    emitter.clear_memo()
    monkeypatch.setenv('PT_CACHE', '0')
    monkeypatch.setenv('PT_KERNELGEN', pt_kg)
    if pt_kg == '1':
        monkeypatch.setenv('PT_STRICT_KERNELS', '1')
    else:
        monkeypatch.delenv('PT_STRICT_KERNELS', raising=False)
    kg.clear_plan_cache()
    main, startup, loss = _train_model(seed)
    losses, scope = runner(main, startup, loss)
    state = {n: np.asarray(v) for n, v in scope.vars.items()}
    return np.asarray(losses), state


def _assert_parity(monkeypatch, runner, seed):
    """First launch 1e-6, later steps drift-bounded (docstring up top);
    the kernel path must actually engage (kernelgen.ops advances —
    per-test seed keeps the program out of the cross-test lowering
    cache) with zero fallbacks under PT_STRICT_KERNELS=1."""
    before = obs.counters().get('kernelgen.ops') or 0
    l1, s1 = _train(monkeypatch, '1', runner, seed)
    assert (obs.counters().get('kernelgen.ops') or 0) > before
    l0, s0 = _train(monkeypatch, '0', runner, seed)
    l1, l0 = np.ravel(l1), np.ravel(l0)
    assert abs(l1[0] - l0[0]) <= 1e-6, (l1[0], l0[0])
    np.testing.assert_allclose(l1, l0, rtol=5e-3, atol=5e-4)
    assert set(s1) == set(s0)
    for n in s1:
        np.testing.assert_allclose(s1[n], s0[n], rtol=5e-2, atol=5e-3,
                                    err_msg=n)


def test_e2e_parity_run(monkeypatch):
    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [np.asarray(exe.run(main, feed=f,
                                         fetch_list=[loss])[0])
                      for f in _feeds(3)]
        return losses, scope
    _assert_parity(monkeypatch, runner, seed=21)


def test_e2e_parity_run_steps(monkeypatch):
    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            stacked, = exe.run_steps(main, feed_list=_feeds(3),
                                     fetch_list=[loss])
        return np.asarray(stacked), scope
    _assert_parity(monkeypatch, runner, seed=22)


def test_e2e_parity_parallel_executor(monkeypatch):
    from paddle_tpu.parallel.parallel_executor import ParallelExecutor

    def runner(main, startup, loss):
        exe, scope = fluid.Executor(), fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                                  scope=scope)
            losses = [np.asarray(pe.run([loss.name], feed=f)[0])
                      for f in _feeds(2, batch=8)]
        return losses, scope
    _assert_parity(monkeypatch, runner, seed=23)


def test_launch_signature_names_kernelgen_flip(monkeypatch):
    """Flipping PT_KERNELGEN between runs of one program is a NAMED
    retrace cause, not a mystery."""
    monkeypatch.setenv('PT_CACHE', '0')
    monkeypatch.setenv('PT_KERNELGEN', '0')
    main, startup, loss = _train_model()
    feed, = _feeds(1)
    exe, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        monkeypatch.setenv('PT_KERNELGEN', '1')
        monkeypatch.setenv('PT_STRICT_KERNELS', '1')
        exe.run(main, feed=feed, fetch_list=[loss])
    hits = [r for r in obs.explainer().reports
            if any('kernelgen' in d for d in r['details'])]
    assert hits, 'retrace explainer must name the kernelgen component'


def test_aot_disk_cache_round_trip(tmp_path, monkeypatch):
    """PT_KERNELGEN=1 executables round-trip the AOT disk cache: a
    second fresh-L1 executor loads without tracing, bitwise."""
    from paddle_tpu.core import executor as em
    monkeypatch.setenv('PT_CACHE', '1')
    monkeypatch.setenv('PT_CACHE_DIR', str(tmp_path))
    monkeypatch.setenv('PT_KERNELGEN', '1')
    monkeypatch.setenv('PT_STRICT_KERNELS', '1')
    kg.clear_plan_cache()
    main, startup, loss = _train_model()
    feed, = _feeds(1)
    exe1, scope = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope):
        exe1.run(startup)
        a, = exe1.run(main, feed=feed, fetch_list=[loss])
    exe2, scope2 = fluid.Executor(), fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup)
        tc = em._TRACE_COUNT[0]
        b, = exe2.run(main, feed=feed, fetch_list=[loss])
        assert em._TRACE_COUNT[0] == tc, \
            'second executor must load the AOT executable, not retrace'
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_emitter_memo_keys_on_kernelgen_token(monkeypatch):
    """The PR-12 emitter memo must not serve a kernelgen-built callable
    to a kernelgen-off run of the same signature (and vice versa)."""
    from paddle_tpu.core.emit import emitter
    assert emitter._kg_token() == kg.config_token()
    monkeypatch.setenv('PT_KERNELGEN', '1')
    t1 = emitter._kg_token()
    monkeypatch.setenv('PT_KERNELGEN', '0')
    t0 = emitter._kg_token()
    assert t1 != t0


def test_d016_lint_names_uncovered_sub_op():
    from paddle_tpu.analysis import lint_program
    from paddle_tpu.core import passes
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[4], dtype='float32')
            y = fluid.layers.relu(fluid.layers.scale(x, scale=2.0))
    opt, _ = passes.optimize_program(main, (y.name,))
    for op in opt.global_block().ops:
        if op.type == 'fused_elementwise':
            op.attrs['sub_ops'] = list(op.attrs['sub_ops']) + [
                _sub('made_up_op', {}, {})]
    res = lint_program(opt, fetch_names=[y.name])
    d16 = [d for d in res.diagnostics if d.code == 'D016']
    assert d16 and 'made_up_op' in d16[0].message


def test_d016_flags_bare_kernel_tier_op():
    """A softmax the fuse pass could NOT wrap (non-serializable attrs)
    must be flagged as a bare kernel-tier op, naming the escape."""
    from paddle_tpu.analysis import lint_program
    from paddle_tpu.core import passes
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            s = fluid.layers.softmax(fluid.layers.scale(x, scale=2.0))
            y = fluid.layers.relu(fluid.layers.scale(s, scale=3.0))
    for op in main.global_block().ops:
        if op.type == 'softmax':
            op.attrs['opaque'] = object()  # blocks _plain_attrs
    opt, _ = passes.optimize_program(main, (y.name,))
    assert any(op.type == 'softmax' for op in opt.global_block().ops)
    res = lint_program(opt, fetch_names=[y.name])
    d16 = [d for d in res.diagnostics if d.code == 'D016']
    assert d16, 'bare kernel-tier softmax must raise a D016'
    assert 'softmax' in d16[0].message
    assert 'not presented' in d16[0].message
    assert 'serializable' in d16[0].message  # the named escape reason
    assert 'plain' in (d16[0].fixit or '')
