"""append_backward / gradients tests (model: reference
tests/unittests/test_backward.py + per-op grad checks via numeric diff)."""
import numpy as np

import paddle_tpu as fluid


def _numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=['multi_index'])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def test_fc_grad_matches_numeric():
    x = fluid.layers.data('x', shape=[3], dtype='float32')
    y = fluid.layers.fc(x, 2, param_attr='w_fc', bias_attr='b_fc')
    loss = fluid.layers.mean(fluid.layers.square(y))
    pg = fluid.append_backward(loss)
    names = {p.name for p, g in pg}
    assert names == {'w_fc', 'b_fc'}
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.random.RandomState(0).normal(size=(4, 3)).astype('float32')
    gw, = exe.run(feed={'x': xv}, fetch_list=['w_fc@GRAD'])
    w0 = np.array(fluid.global_scope().get('w_fc'))
    b0 = np.array(fluid.global_scope().get('b_fc'))

    def f(w):
        return np.mean(np.square(xv @ w + b0))
    gn = _numeric_grad(f, w0.astype('float64')).astype('float32')
    np.testing.assert_allclose(gw, gn, rtol=1e-2, atol=1e-3)


def test_stop_gradient_blocks_flow():
    x = fluid.layers.data('x', shape=[2], dtype='float32')
    w = fluid.layers.create_parameter(
        [2], 'float32', name='w_sg',
        default_initializer=fluid.initializer.Constant(2.0))
    h = fluid.layers.elementwise_mul(x, w)
    h.stop_gradient = True
    h2 = fluid.layers.scale(h, 3.0)
    loss = fluid.layers.mean(h2)
    fluid.append_backward(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    g, = exe.run(feed={'x': np.ones((1, 2), 'float32')},
                 fetch_list=['w_sg@GRAD'])
    np.testing.assert_allclose(g, np.zeros(2), atol=1e-7)


def test_gradients_wrt_input():
    x = fluid.layers.data('x', shape=[3], dtype='float32')
    x.stop_gradient = False
    y = fluid.layers.mean(fluid.layers.square(x))
    (gx,) = fluid.gradients(y, x)
    exe = fluid.Executor()
    xv = np.array([[1., 2., 3.]], 'float32')
    out, = exe.run(feed={'x': xv}, fetch_list=[gx])
    np.testing.assert_allclose(out, 2 * xv / 3, rtol=1e-5)


def test_backward_through_conv_bn_pool():
    img = fluid.layers.data('img', shape=[3, 8, 8], dtype='float32')
    c = fluid.layers.conv2d(img, 4, 3, act='relu')
    b = fluid.layers.batch_norm(c)
    p = fluid.layers.pool2d(b, 2, pool_stride=2, pool_type='avg')
    loss = fluid.layers.mean(p)
    pg = fluid.append_backward(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    fetches = [g for p_, g in pg]
    outs = exe.run(feed={'img': np.random.RandomState(1).normal(
        size=(2, 3, 8, 8)).astype('float32')}, fetch_list=fetches)
    for o in outs:
        assert np.all(np.isfinite(o))
