"""Headline benchmark: Transformer-base training throughput on one TPU chip.

Mirrors the reference's benchmark/fluid/fluid_benchmark.py harness
(--model machine_translation reports words/sec); here the whole train step
(fwd + vjp bwd + Adam) is ONE XLA executable, run in bf16 AMP with the
fused flash-attention kernel.

Robustness (round-2): the TPU ('axon') backend is probed in a SUBPROCESS
with a hard timeout before any in-process device work — a hung PJRT init
cannot hang the benchmark.  On probe failure the bench falls back to CPU,
prints loud diagnostics to stderr, and records the fallback in the JSON.

Prints ONE JSON line:
  {"metric": ..., "value": tok/s, "unit": "tokens/s", "vs_baseline": ...,
   "mfu": model-flops-utilization vs chip peak, "backend": ..., ...}

vs_baseline denominator: ~5100 tokens/s/GPU, the Fluid-era V100 fp32
transformer-base figure recorded in SURVEY.md §5 (BASELINE.json has no
published numbers).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                'tools'))
import _harness  # noqa: E402 - shared stage/watchdog/probe machinery
from _harness import PROBE_TIMEOUT_S, probe_backend, stage  # noqa: E402,F401

BASELINE_TOKENS_PER_SEC = 5100.0
# Fluid-era V100 fp32 ResNet-50 throughput stand-in (BASELINE.json has no
# published numbers; benchmark/fluid's README-era figure is ~360 img/s)
BASELINE_RESNET_IMAGES_PER_SEC = 360.0
# canonical ResNet-50 224x224 forward cost; training ~= 3x forward
RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.1e9
# peak bf16 FLOP/s by TPU generation (public spec sheets)
_PEAK_BF16 = {
    'v4': 275e12,
    'v5 lite': 197e12, 'v5e': 197e12, 'v5litepod': 197e12,
    'v5p': 459e12, 'v5': 459e12,
    'v6e': 918e12, 'v6 lite': 918e12, 'trillium': 918e12,
}

# the probe / watchdog / stage / JSON-tail machinery lives in
# tools/_harness.py now — one implementation shared with perflab
# children, fault_soak, serve_soak, pod_soak
_emit_error = _harness.emit_error


def peak_flops(device_kind):
    kind = (device_kind or '').lower()
    for key, val in sorted(_PEAK_BF16.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return None


def allreduce_bw_gbps(n_iters=10, nbytes=64 * 1024 * 1024):
    """psum bandwidth across local devices (BASELINE.json headline metric).
    Only meaningful with >1 device; returns None single-chip."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    if len(devs) < 2:
        return None
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    mesh = Mesh(np.array(devs), ('x',))
    n = nbytes // 4 // len(devs) * len(devs)
    x = jnp.ones((n,), jnp.float32)

    @jax.jit
    def ar(v):
        return shard_map(lambda s: jax.lax.psum(s, 'x'),
                         mesh=mesh, in_specs=P('x'), out_specs=P(None))(v)

    ar(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(n_iters):
        out = ar(x)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    # ring allreduce moves 2*(n-1)/n of the buffer per device
    moved = 2 * (len(devs) - 1) / len(devs) * n * 4 * n_iters
    return moved / dt / 1e9


def bench_resnet50(on_tpu, device_kind):
    """ResNet-50 training throughput (BASELINE.json headline metric #1;
    reference harness: benchmark/fluid/fluid_benchmark.py --model resnet
    with --data_set imagenet, model at benchmark/fluid/models/resnet.py)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    # TPU v5 lite, conv flow-through AMP policy: 2233 img/s at B=128 vs
    # 2242 at B=256 (a tie); 128 keeps HBM headroom (PERF.md sweep)
    B = int(os.environ.get('BENCH_RESNET_B', 128 if on_tpu else 2))
    side = 224 if on_tpu else 32
    classes = 1000 if on_tpu else 10
    # same CPU-smoke story as the transformer dims: 25M resnet50 params
    # through the interpret-mode fused-optimizer kernel is minutes/step,
    # so CI drops to the 0.27M-param cifar10 variant
    depth = int(os.environ.get('BENCH_RESNET_DEPTH', '50'))
    data_set = os.environ.get('BENCH_RESNET_SET', 'imagenet')
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            out = resnet.build(data_shape=(3, side, side),
                               class_dim=classes, depth=depth, lr=0.1,
                               data_set=data_set)
    main_prog.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {'data': rng.rand(B, 3, side, side).astype('float32'),
            'label': rng.randint(0, classes, (B, 1)).astype('int64')}
    with fluid.scope_guard(scope):
        t0 = time.perf_counter()
        exe.run(startup)
        import jax
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        for _ in range(3):
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']])
        np.asarray(loss)  # block
        print('BENCH: resnet50 compile+warmup ok (%.1fs)'
              % (time.perf_counter() - t0), file=sys.stderr)
        steps = 20 if on_tpu else 3
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']],
                            return_numpy=False)
        np.asarray(loss)  # block
        dt = time.perf_counter() - t0
    ips = steps * B / dt
    peak = peak_flops(device_kind) if on_tpu else None
    mfu = (round(RESNET50_TRAIN_FLOPS_PER_IMAGE * ips / peak, 4)
           if peak else None)
    return {'resnet50_images_per_sec': round(ips, 1),
            'resnet50_vs_baseline': round(
                ips / BASELINE_RESNET_IMAGES_PER_SEC, 3),
            'resnet50_mfu': mfu, 'resnet50_batch': B}


def bench_fused_adam(fluid):
    """Micro-bench the fused-Adam update path: a tiny 2-layer model whose
    optimizer sub-program fuses into one fused_elementwise group (ONE
    generated Pallas kernel when PT_KERNELGEN=1).  Returns avg ms per
    train step — the ledger row for the kernelgen tier's headline op."""
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            x = fluid.layers.data('fa_x', shape=[64], dtype='float32')
            h = fluid.layers.fc(x, size=64, act='relu')
            y = fluid.layers.fc(h, size=64)
            loss = fluid.layers.reduce_mean(y * y)
            opt = fluid.optimizer.Adam(learning_rate=1e-3)
            opt.minimize(loss)
    exe, scope = fluid.Executor(), fluid.Scope()
    feed = {'fa_x': np.random.RandomState(0)
            .rand(32, 64).astype('float32')}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # compile + warmup
            exe.run(main_prog, feed=feed, fetch_list=[loss])
        steps = 20
        t0 = time.perf_counter()
        for _ in range(steps):
            exe.run(main_prog, feed=feed, fetch_list=[loss],
                    return_numpy=False)
        lv, = exe.run(main_prog, feed=feed, fetch_list=[loss])
        np.asarray(lv)  # block
        dt = time.perf_counter() - t0
    return round(dt / (steps + 1) * 1000.0, 3)


def main():
    # the codegen tier is the bench default: the headline number should
    # measure generated kernels, and kernelgen_ops/kernelgen_fallbacks in
    # the telemetry make a silent degrade visible
    os.environ.setdefault('PT_KERNELGEN', '1')
    stage('probe')
    t_probe = time.perf_counter()
    platform, kind_or_reason = probe_backend()
    probe_s = round(time.perf_counter() - t_probe, 1)
    fallback_reason = None
    if platform != 'tpu' and \
            os.environ.get('BENCH_ALLOW_CPU', '0') not in ('1', 'true'):
        # backend != tpu is a structured FAILURE by default: silently
        # recording CPU numbers as if they were TPU numbers cost two
        # bench rounds (BENCH_r02/r05).  CI smoke runs opt in explicitly
        # with BENCH_ALLOW_CPU=1.
        reason = kind_or_reason if platform is None else \
            "probe reached backend '%s', not tpu" % platform
        print('BENCH: backend is not TPU — %s' % reason, file=sys.stderr)
        print('BENCH: set BENCH_ALLOW_CPU=1 to record CPU numbers '
              'anyway', file=sys.stderr)
        _emit_error('cpu_fallback', reason)
        return 3
    if platform is None:
        fallback_reason = kind_or_reason
        print('BENCH: TPU backend probe FAILED — %s' % fallback_reason,
              file=sys.stderr)
        print('BENCH: BENCH_ALLOW_CPU=1 — falling back to CPU',
              file=sys.stderr)
        device_kind = 'cpu-fallback'
    else:
        device_kind = kind_or_reason
        print('BENCH: backend ok: %s (%s)' % (platform, device_kind),
              file=sys.stderr)

    import jax
    if platform is None:
        jax.config.update('jax_platforms', 'cpu')

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tr

    on_tpu = platform not in (None, 'cpu')
    # transformer-base; dropout off so training uses the fused flash kernel.
    # The model dims are overridable because the kernelgen interpret tier
    # pays per PARAMETER on CPU (the fused-Adam kernel walks every param
    # group through the Pallas interpreter, ~minutes/step at 25M params) —
    # CI smoke must shrink the model itself, not just B/T.
    B = int(os.environ.get('BENCH_B', 32 if on_tpu else 4))
    T = int(os.environ.get('BENCH_T', 256 if on_tpu else 64))
    vocab = int(os.environ.get('BENCH_VOCAB', '32000'))
    n_layer = int(os.environ.get('BENCH_LAYERS', '6'))
    n_head = int(os.environ.get('BENCH_HEADS', '8'))
    d_model = int(os.environ.get('BENCH_DMODEL', '512'))
    d_inner = int(os.environ.get('BENCH_DINNER', '2048'))

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=vocab, trg_vocab=vocab, max_len=T,
                           n_layer=n_layer, n_head=n_head, d_model=d_model,
                           d_inner=d_inner, dropout=0.0, use_flash=True)
    main_prog.set_amp(True)

    # tiny-shape warmup first: a failure or hang surfaces on a 2s compile,
    # not after the full-size 30s one
    t0 = time.perf_counter()
    stage('tiny_warmup')
    _tiny_warmup(fluid, vocab)
    print('BENCH: tiny warmup ok (%.1fs)' % (time.perf_counter() - t0),
          file=sys.stderr)

    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = tr.synthetic_batch(rng, B, T, vocab)
    tokens_per_step = float(np.sum(1.0 - feed['trg_pad']))

    n_params = sum(
        int(np.prod(v.shape)) for v in
        main_prog.global_block().all_parameters() if v.shape)
    # params that only feed lookup_table gathers do 0 matmul FLOPs — count
    # them out of the 6*P model-FLOPs term (the logit projection is a real
    # matmul and keeps its '...proj...' name, so it stays in)
    n_gather_params = sum(
        int(np.prod(v.shape)) for v in
        main_prog.global_block().all_parameters()
        if v.shape and v.name.endswith('_emb'))
    n_matmul_params = n_params - n_gather_params

    with fluid.scope_guard(scope):
        t0 = time.perf_counter()
        stage('startup')
        exe.run(startup)
        print('BENCH: startup ok (%.1fs)' % (time.perf_counter() - t0),
              file=sys.stderr)
        # upload the batch ONCE — steady-state training streams batches
        # asynchronously; re-uploading identical host arrays every step
        # would measure the host link, not the chip
        import jax
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        t0 = time.perf_counter()
        stage('train_warmup')
        for _ in range(3):  # compile + warmup
            loss, = exe.run(main_prog, feed=feed, fetch_list=[out['loss']])
        np.asarray(loss)  # block
        print('BENCH: train-step compile+warmup ok (%.1fs)'
              % (time.perf_counter() - t0), file=sys.stderr)
        stage('measure')
        steps = 30 if on_tpu else 10
        t0 = time.perf_counter()
        for _ in range(steps):
            # async fetch: steps pipeline on device; one sync at the end
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']],
                            return_numpy=False)
        np.asarray(loss)  # block
        dt_single = time.perf_counter() - t0
        tps_single = steps * tokens_per_step / dt_single

        # multi-step fused loop (the headline): K iterations per device
        # launch via run_steps — one lax.scan executable amortizes the
        # ~60 ms synchronous-dispatch cost per launch that PERF.md's
        # round-5 ledger attributes to the device tunnel
        K = max(2, int(os.environ.get('BENCH_STEPS_PER_LAUNCH', '8')))
        import jax.numpy as jnp
        superfeed = {k: jnp.stack([v] * K) for k, v in feed.items()}
        t0 = time.perf_counter()
        losses, = exe.run_steps(main_prog, feed_list=superfeed, steps=K,
                                fetch_list=[out['loss']])
        print('BENCH: %d-step fused compile+warmup ok (%.1fs)'
              % (K, time.perf_counter() - t0), file=sys.stderr)
        launches = max(1, steps // K)
        # telemetry: snapshot AFTER warmup so the measured window is
        # self-labeling — a retrace or pipeline stall during the timed
        # loop lands in the JSON instead of silently polluting the number
        import paddle_tpu.observability as obs
        snap0 = obs.counters()
        t0 = time.perf_counter()
        for _ in range(launches):
            losses, = exe.run_steps(main_prog, feed_list=superfeed,
                                    steps=K, fetch_list=[out['loss']],
                                    return_numpy=False)
        np.asarray(losses)  # block
        dt = time.perf_counter() - t0
        # ragged tail: a partial superbatch (steps=1 < K) must route
        # through the already-compiled single-step executable (tail
        # split) instead of lowering a fresh steps=1 scan — any trace
        # here lands in the retraces-after-warmup check below
        tailfeed = {k: v[:1] for k, v in superfeed.items()}
        exe.run_steps(main_prog, feed_list=tailfeed, steps=1,
                      fetch_list=[out['loss']], return_numpy=False)
        snap1 = obs.counters()

        # sync-mode comparison row: the SAME fused launches but with a
        # host fetch (return_numpy=True) after every one — what the
        # headline number would be if the host serialized the device
        stage('sync_compare')
        t0 = time.perf_counter()
        for _ in range(launches):
            exe.run_steps(main_prog, feed_list=superfeed, steps=K,
                          fetch_list=[out['loss']], return_numpy=True)
        dt_sync = time.perf_counter() - t0
        tps_sync = launches * K * tokens_per_step / dt_sync

        # deferred check_nan overhead: with nan_poll=8 the fused
        # all-finite verdict stays device-resident between polls, so the
        # guard should cost ~nothing vs the unguarded single-step loop
        # (PERF.md's old per-launch bool() sync made it ~4x)
        stage('check_nan')
        exe_nan = fluid.Executor(check_nan=True, nan_poll=8)
        for _ in range(2):  # compile + warmup for the guarded executable
            loss, = exe_nan.run(main_prog, feed=feed,
                                fetch_list=[out['loss']])
        exe_nan.poll_nan()
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, = exe_nan.run(main_prog, feed=feed,
                                fetch_list=[out['loss']],
                                return_numpy=False)
        exe_nan.poll_nan()
        np.asarray(loss)  # block
        dt_nan = time.perf_counter() - t0
        check_nan_overhead_x = dt_nan / dt_single

    tps = launches * K * tokens_per_step / dt

    # PT_OPT rewriter accounting (core/passes): raw vs optimized traced-op
    # counts for the headline program.  maybe_optimize is memoized per
    # (program version, fetch set), so this reads the stats of the exact
    # rewrite the executor lowered — no extra work.
    from paddle_tpu.core import passes as pt_passes
    raw_ops = sum(len(b.ops) for b in main_prog.blocks)
    _, opt_stats = pt_passes.maybe_optimize(main_prog, (out['loss'].name,))
    opt_ops = opt_stats['op_count_opt'] if opt_stats else raw_ops

    # the backend the bench process ACTUALLY ran on (the probe only says
    # what a subprocess saw) — a CPU fallback can't masquerade as TPU
    dev0 = jax.devices()[0]
    # one shared schema (observability/export.py SCHEMA['bench']) builds
    # the telemetry block — serve_soak/fault_soak read their sections from
    # the same table, and ci_smoke validates the key set once.  Warm-start
    # semantics (compile_s_cold = in-process compile seconds, _warm = AOT
    # cache load seconds, ci_smoke asserts the second run collapses) and
    # kernel_fallbacks (a pallas kernel degraded to its composed path)
    # are documented in the schema + docs/observability.md.
    stage('fused_adam')
    try:
        fused_adam_ms = bench_fused_adam(fluid)
        print('BENCH: fused-adam step ok: %.3f ms' % fused_adam_ms,
              file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - ledger row is best-effort
        print('BENCH: fused-adam bench failed: %s' % e, file=sys.stderr)
        fused_adam_ms = None

    telemetry = obs.telemetry_snapshot(
        'bench', baseline=snap0, snapshot=snap1,
        extra={'platform': dev0.platform,
               'device_kind': str(dev0.device_kind),
               'program_op_count_raw': raw_ops,
               'program_op_count_opt': opt_ops,
               'fused_adam_ms': fused_adam_ms})
    if telemetry['kernel_fallbacks']:
        print('BENCH: WARNING — %d kernel fallback(s): a pallas kernel '
              'degraded to its composed path (run PT_STRICT_KERNELS=1 '
              'to get the raw error)' % telemetry['kernel_fallbacks'],
              file=sys.stderr)
    if telemetry['kernelgen_fallbacks']:
        print('BENCH: WARNING — %d kernelgen fallback(s): a fused group '
              'degraded from its generated Pallas kernel to replay (run '
              'PT_STRICT_KERNELS=1 to get the raw error)'
              % telemetry['kernelgen_fallbacks'], file=sys.stderr)
    if telemetry['emitter_fallbacks']:
        print('BENCH: WARNING — %d emitter fallback(s): the direct '
              'Program→jaxpr emitter degraded to traced lowering (run '
              'PT_STRICT_EMIT=1 to get the raw error)'
              % telemetry['emitter_fallbacks'], file=sys.stderr)
    if telemetry['retraces']:
        print('BENCH: WARNING — %d retrace(s) DURING the measured fused '
              'loop; the number below is compile-polluted'
              % telemetry['retraces'], file=sys.stderr)
        rep = obs.explainer().last_report()
        if rep:
            print('BENCH: last retrace cause: %s'
                  % '; '.join(rep['details']), file=sys.stderr)

    # model FLOPs (scaling-book accounting): 6*P per trained token for the
    # MATMUL params (embedding gathers excluded — they do no MXU work),
    # + 12*T*d per token per attention layer for the score / context
    # matmuls (fwd 4*T*d, bwd x2); enc self + dec self + dec cross
    attn_layers = 3 * n_layer
    flops_per_token = 6.0 * n_matmul_params + 12.0 * T * d_model * attn_layers
    model_flops_per_s = flops_per_token * tps
    peak = peak_flops(device_kind) if on_tpu else None
    mfu = round(model_flops_per_s / peak, 4) if peak else None

    ar_bw = None
    try:
        ar_bw = allreduce_bw_gbps()
    except Exception as e:  # noqa: BLE001 - diagnostic-only path
        print('BENCH: allreduce microbench failed: %s' % e, file=sys.stderr)

    stage('resnet50')
    resnet_rec = {}
    try:
        resnet_rec = bench_resnet50(on_tpu, device_kind)
        print('BENCH: resnet50 ok: %.1f img/s' %
              resnet_rec['resnet50_images_per_sec'], file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - second metric is best-effort
        print('BENCH: resnet50 bench failed: %s' % e, file=sys.stderr)
        resnet_rec = {'resnet50_error': str(e)[:200]}

    stage('report')
    rec = {
        'metric': 'transformer_base_tokens_per_sec_per_chip',
        'value': round(tps, 1),
        'unit': 'tokens/s',
        'vs_baseline': round(tps / BASELINE_TOKENS_PER_SEC, 3),
        'mfu': mfu,
        'model_tflops_per_s': round(model_flops_per_s / 1e12, 2),
        'params_m': round(n_params / 1e6, 1),
        'matmul_params_m': round(n_matmul_params / 1e6, 1),
        'backend': device_kind,
        'batch': B, 'seq': T, 'amp': True, 'flash': True,
        'steps_per_launch': K,
        'single_step_tokens_per_sec': round(tps_single, 1),
        'sync_mode_tokens_per_sec': round(tps_sync, 1),
        'check_nan_overhead_x': round(check_nan_overhead_x, 2),
        'telemetry': telemetry,
    }
    rec.update(resnet_rec)
    # probe accounting: how long the backend probe took (budget
    # PROBE_TIMEOUT_S) and, on failure, the hang/crash reason
    rec['probe_s'] = probe_s
    if fallback_reason:
        rec['fallback'] = fallback_reason
    if ar_bw is not None:
        rec['allreduce_gbps'] = round(ar_bw, 1)
    print(json.dumps(rec))

    # feed the perf lab's append-only ledger when asked (PT_PERF_LEDGER):
    # the SAME record contract as a `perflab run` scenario, so bench rows
    # diff against blessed baselines with the same counter/timing rules
    from paddle_tpu.observability import perflab
    perflab.maybe_ledger(
        'bench',
        {'program_op_count_opt': int(opt_ops),
         'retraces': int(telemetry['retraces']),
         'kernel_fallbacks': int(telemetry['kernel_fallbacks']),
         'kernelgen_fallbacks': int(telemetry['kernelgen_fallbacks']),
         'emitter_fallbacks': int(telemetry['emitter_fallbacks']),
         'tokens_per_s': round(tps, 1),
         'mfu': mfu,
         'host_blocked_s': telemetry.get('host_blocked_s'),
         'fused_adam_ms': fused_adam_ms,
         'resnet50_images_per_s':
             resnet_rec.get('resnet50_images_per_sec'),
         'batch': B, 'seq': T},
        config={'steps_per_launch': K, 'vocab': vocab,
                'layers': n_layer, 'd_model': d_model},
        fallback=fallback_reason)


def _tiny_warmup(fluid, vocab):
    """One 2-layer micro train step end-to-end: exercises the same lowering
    path at trivial size so backend trouble shows up fast."""
    from paddle_tpu.models import transformer as tr
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=128, trg_vocab=128, max_len=8,
                           n_layer=1, n_head=2, d_model=32, d_inner=64,
                           dropout=0.0, use_flash=False)
    prog.set_amp(True)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rows = [(np.array([3, 4, 1]), np.array([0, 3, 4]), np.array([3, 4, 1]))]
    feed = tr.make_batch(rows, 8)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(prog, feed=feed, fetch_list=[out['loss']])


if __name__ == '__main__':
    # a crashed bench still leaves a diagnosable artifact: the last
    # line is {"error": ..., "stage": ...} instead of a bare stack
    _harness.main_guard(main, flight_tag='bench.watchdog')
