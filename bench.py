"""Headline benchmark: Transformer-base training throughput on one TPU chip.

Mirrors the reference's benchmark/fluid/fluid_benchmark.py harness
(--model machine_translation reports words/sec); here the whole train step
(fwd + vjp bwd + Adam) is ONE XLA executable.  Prints one JSON line.

vs_baseline denominator: ~5100 tokens/s/GPU, the Fluid-era V100 fp32
transformer-base figure recorded in SURVEY.md §5 (BASELINE.json has no
published numbers).
"""
import json
import sys
import time

import numpy as np

BASELINE_TOKENS_PER_SEC = 5100.0


def main():
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tr

    B, T, vocab = 64, 64, 32000
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        with fluid.unique_name.guard():
            out = tr.build(src_vocab=vocab, trg_vocab=vocab, max_len=T,
                           n_layer=6, n_head=8, d_model=512, d_inner=2048,
                           dropout=0.1, use_flash=False)

    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    rows = []
    for _ in range(B):
        s = rng.randint(3, vocab, (T - 1,))
        rows.append((np.concatenate([s, [1]]), np.concatenate([[0], s]),
                     np.concatenate([s, [1]])))
    feed = tr.make_batch(rows, T)
    tokens_per_step = float(np.sum(1.0 - feed['trg_pad']))

    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):  # compile + warmup
            exe.run(main_prog, feed=feed, fetch_list=[out['loss']])
        steps = 30
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, = exe.run(main_prog, feed=feed,
                            fetch_list=[out['loss']])
        np.asarray(loss)  # block
        dt = time.perf_counter() - t0

    tps = steps * tokens_per_step / dt
    print(json.dumps({
        'metric': 'transformer_base_tokens_per_sec_per_chip',
        'value': round(tps, 1),
        'unit': 'tokens/s',
        'vs_baseline': round(tps / BASELINE_TOKENS_PER_SEC, 3),
    }))


if __name__ == '__main__':
    sys.exit(main())
