// TPU-native host data pipeline.
//
// Parity: reference paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed,
// async_executor feeding), recordio/ (chunked record file format), and the
// reader decorators' shuffle/batch/double-buffer stages — rebuilt as one C++
// pipeline so file parsing, shuffling and batch assembly run on host threads
// off the Python GIL while the TPU step executes.
//
// File format ("ptrec"): little-endian.
//   file   := record*
//   record := u32 magic 0x50545231 ("PTR1") | u32 payload_len | u32 crc32
//             | payload
//   payload:= u16 num_fields | field*
//   field  := u8 dtype_code | u8 ndim | i64 dims[ndim] | raw data
// dtype codes: 0=f32 1=f64 2=i32 3=i64 4=u8 5=i16 6=bool 7=bf16(u16)
//
// The reader owns: a demux thread pool parsing records, a reservoir-style
// shuffle buffer (same semantics as paddle.reader.shuffle: fill N, emit
// random), and a bounded queue of fully-assembled contiguous batches
// (double_buffer equivalent; depth = prefetch).
//
// C ABI only (loaded via ctypes; pybind11 is not available in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50545231u;

uint32_t crc32_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc32_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc32_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

size_t dtype_size(uint8_t code) {
  switch (code) {
    case 0: return 4;   // f32
    case 1: return 8;   // f64
    case 2: return 4;   // i32
    case 3: return 8;   // i64
    case 4: return 1;   // u8
    case 5: return 2;   // i16
    case 6: return 1;   // bool
    case 7: return 2;   // bf16
    default: return 0;
  }
}

struct Field {
  uint8_t dtype;
  std::vector<int64_t> dims;
  std::vector<uint8_t> data;
  size_t numel() const {
    size_t n = 1;
    for (auto d : dims) n *= static_cast<size_t>(d);
    return n;
  }
};

using Sample = std::vector<Field>;

// ---------------------------------------------------------------- writer

struct Writer {
  FILE* f;
  std::string err;
};

bool write_all(FILE* f, const void* p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

// ---------------------------------------------------------------- reader

struct Batch {
  // one contiguous buffer per field, samples stacked on axis 0
  std::vector<Field> fields;
  int64_t batch_size = 0;
};

struct Reader {
  std::vector<std::string> paths;
  int64_t batch_size = 1;
  int64_t shuffle_capacity = 0;  // 0 = no shuffle
  uint64_t seed = 0;
  bool drop_last = false;
  bool loop_forever = false;
  int64_t prefetch = 4;

  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::queue<Batch*> ready;
  Batch* current = nullptr;
  std::atomic<bool> done{false}, stop{false};
  std::string err;

  ~Reader() {
    stop.store(true);
    cv_push.notify_all();
    cv_pop.notify_all();
    if (worker.joinable()) worker.join();
    std::lock_guard<std::mutex> l(mu);
    while (!ready.empty()) { delete ready.front(); ready.pop(); }
    delete current;
  }
};

bool parse_record(const uint8_t* p, size_t len, Sample* out, std::string* err) {
  size_t off = 0;
  if (off + 2 > len) { *err = "truncated record header"; return false; }
  uint16_t nf;
  memcpy(&nf, p + off, 2); off += 2;
  out->resize(nf);
  for (uint16_t i = 0; i < nf; i++) {
    if (off + 2 > len) { *err = "truncated field header"; return false; }
    Field& fld = (*out)[i];
    fld.dtype = p[off++];
    uint8_t ndim = p[off++];
    fld.dims.resize(ndim);
    if (off + 8ull * ndim > len) { *err = "truncated dims"; return false; }
    memcpy(fld.dims.data(), p + off, 8ull * ndim); off += 8ull * ndim;
    size_t nbytes = fld.numel() * dtype_size(fld.dtype);
    if (off + nbytes > len) { *err = "truncated data"; return false; }
    fld.data.assign(p + off, p + off + nbytes);
    off += nbytes;
  }
  return true;
}

// Reads one framed record from f into sample. Returns 1 ok, 0 eof, -1 error.
int read_record(FILE* f, Sample* s, std::string* err) {
  uint32_t hdr[3];
  size_t got = fread(hdr, 1, 12, f);
  if (got == 0) return 0;
  if (got != 12 || hdr[0] != kMagic) { *err = "bad record frame"; return -1; }
  std::vector<uint8_t> payload(hdr[1]);
  if (fread(payload.data(), 1, hdr[1], f) != hdr[1]) {
    *err = "truncated payload"; return -1;
  }
  if (crc32(payload.data(), payload.size()) != hdr[2]) {
    *err = "crc mismatch"; return -1;
  }
  return parse_record(payload.data(), payload.size(), s, err) ? 1 : -1;
}

Batch* assemble(std::vector<Sample>&& samples, std::string* err) {
  auto* b = new Batch();
  b->batch_size = static_cast<int64_t>(samples.size());
  if (samples.empty()) return b;
  size_t nf = samples[0].size();
  b->fields.resize(nf);
  for (size_t i = 0; i < nf; i++) {
    Field& dst = b->fields[i];
    const Field& proto = samples[0][i];
    dst.dtype = proto.dtype;
    dst.dims.clear();
    dst.dims.push_back(b->batch_size);
    for (auto d : proto.dims) dst.dims.push_back(d);
    size_t per = proto.data.size();
    dst.data.resize(per * samples.size());
    for (size_t s = 0; s < samples.size(); s++) {
      const Field& src = samples[s][i];
      if (src.data.size() != per || src.dtype != proto.dtype) {
        *err = "inconsistent sample shapes/dtypes in batch";
        delete b;
        return nullptr;
      }
      memcpy(dst.data.data() + s * per, src.data.data(), per);
    }
  }
  return b;
}

void reader_main(Reader* r) {
  std::mt19937_64 rng(r->seed);
  std::vector<Sample> shuffle_buf;
  std::vector<Sample> pending;

  auto emit = [&](std::vector<Sample>&& batch_samples) -> bool {
    std::string err;
    Batch* b = assemble(std::move(batch_samples), &err);
    if (!b) {
      std::lock_guard<std::mutex> l(r->mu);
      r->err = err;
      return false;
    }
    std::unique_lock<std::mutex> l(r->mu);
    r->cv_push.wait(l, [&] {
      return r->stop.load() ||
             static_cast<int64_t>(r->ready.size()) < r->prefetch;
    });
    if (r->stop.load()) { delete b; return false; }
    r->ready.push(b);
    r->cv_pop.notify_one();
    return true;
  };

  auto push_sample = [&](Sample&& s) -> bool {
    if (r->shuffle_capacity > 0) {
      shuffle_buf.emplace_back(std::move(s));
      if (static_cast<int64_t>(shuffle_buf.size()) < r->shuffle_capacity)
        return true;
      size_t pick = rng() % shuffle_buf.size();
      std::swap(shuffle_buf[pick], shuffle_buf.back());
      pending.emplace_back(std::move(shuffle_buf.back()));
      shuffle_buf.pop_back();
    } else {
      pending.emplace_back(std::move(s));
    }
    if (static_cast<int64_t>(pending.size()) == r->batch_size) {
      bool ok = emit(std::move(pending));
      pending.clear();
      return ok;
    }
    return true;
  };

  do {
    for (const auto& path : r->paths) {
      if (r->stop.load()) break;
      FILE* f = fopen(path.c_str(), "rb");
      if (!f) {
        std::lock_guard<std::mutex> l(r->mu);
        r->err = "cannot open " + path;
        break;
      }
      while (!r->stop.load()) {
        Sample s;
        std::string err;
        int rc = read_record(f, &s, &err);
        if (rc == 0) break;
        if (rc < 0) {
          std::lock_guard<std::mutex> l(r->mu);
          r->err = err + " in " + path;
          break;
        }
        if (!push_sample(std::move(s))) break;
      }
      fclose(f);
    }
  } while (r->loop_forever && !r->stop.load() && r->err.empty());

  // drain shuffle buffer (randomized)
  while (!shuffle_buf.empty() && !r->stop.load()) {
    size_t pick = rng() % shuffle_buf.size();
    std::swap(shuffle_buf[pick], shuffle_buf.back());
    pending.emplace_back(std::move(shuffle_buf.back()));
    shuffle_buf.pop_back();
    if (static_cast<int64_t>(pending.size()) == r->batch_size) {
      if (!emit(std::move(pending))) break;
      pending.clear();
    }
  }
  if (!pending.empty() && !r->drop_last && !r->stop.load())
    emit(std::move(pending));

  r->done.store(true);
  r->cv_pop.notify_all();
}

}  // namespace

extern "C" {

// ---------------- writer ----------------

void* ptrec_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  return w;
}

// fields laid out as parallel arrays; dims flattened with ndims offsets
int ptrec_writer_write(void* handle, int num_fields,
                       const uint8_t* dtypes, const int32_t* ndims,
                       const int64_t* dims_flat,
                       const uint8_t* const* data, const int64_t* nbytes) {
  auto* w = static_cast<Writer*>(handle);
  std::vector<uint8_t> payload;
  uint16_t nf = static_cast<uint16_t>(num_fields);
  payload.insert(payload.end(), reinterpret_cast<uint8_t*>(&nf),
                 reinterpret_cast<uint8_t*>(&nf) + 2);
  int dim_off = 0;
  for (int i = 0; i < num_fields; i++) {
    payload.push_back(dtypes[i]);
    payload.push_back(static_cast<uint8_t>(ndims[i]));
    const uint8_t* dp =
        reinterpret_cast<const uint8_t*>(dims_flat + dim_off);
    payload.insert(payload.end(), dp, dp + 8 * ndims[i]);
    dim_off += ndims[i];
    payload.insert(payload.end(), data[i], data[i] + nbytes[i]);
  }
  uint32_t hdr[3] = {kMagic, static_cast<uint32_t>(payload.size()),
                     crc32(payload.data(), payload.size())};
  if (!write_all(w->f, hdr, 12) ||
      !write_all(w->f, payload.data(), payload.size()))
    return -1;
  return 0;
}

void ptrec_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  fclose(w->f);
  delete w;
}

// ---------------- reader ----------------

void* ptrec_reader_open(const char* const* paths, int num_paths,
                        int64_t batch_size, int64_t shuffle_capacity,
                        uint64_t seed, int drop_last, int loop_forever,
                        int64_t prefetch) {
  auto* r = new Reader();
  for (int i = 0; i < num_paths; i++) r->paths.emplace_back(paths[i]);
  r->batch_size = batch_size;
  r->shuffle_capacity = shuffle_capacity;
  r->seed = seed;
  r->drop_last = drop_last != 0;
  r->loop_forever = loop_forever != 0;
  r->prefetch = prefetch < 1 ? 1 : prefetch;
  r->worker = std::thread(reader_main, r);
  return r;
}

// Blocks until a batch is ready. Returns number of fields, 0 on end of
// data, -1 on error (see ptrec_reader_error).
int ptrec_reader_next(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  std::unique_lock<std::mutex> l(r->mu);
  delete r->current;
  r->current = nullptr;
  r->cv_pop.wait(l, [&] {
    return !r->ready.empty() || r->done.load() || !r->err.empty();
  });
  if (!r->err.empty()) return -1;
  if (r->ready.empty()) return 0;
  r->current = r->ready.front();
  r->ready.pop();
  r->cv_push.notify_one();
  return static_cast<int>(r->current->fields.size());
}

int ptrec_reader_field_dtype(void* handle, int i) {
  return static_cast<Reader*>(handle)->current->fields[i].dtype;
}

int ptrec_reader_field_ndim(void* handle, int i) {
  return static_cast<int>(
      static_cast<Reader*>(handle)->current->fields[i].dims.size());
}

void ptrec_reader_field_dims(void* handle, int i, int64_t* out) {
  const auto& dims = static_cast<Reader*>(handle)->current->fields[i].dims;
  memcpy(out, dims.data(), dims.size() * 8);
}

const uint8_t* ptrec_reader_field_data(void* handle, int i) {
  return static_cast<Reader*>(handle)->current->fields[i].data.data();
}

const char* ptrec_reader_error(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  std::lock_guard<std::mutex> l(r->mu);
  return r->err.c_str();
}

void ptrec_reader_close(void* handle) {
  delete static_cast<Reader*>(handle);
}

}  // extern "C"
