"""Pure-NumPy fallback for the native pipeline (same ptrec format).

Used when no C++ toolchain is available at runtime.  Format doc in
src/datafeed.cc.
"""
import random
import struct
import zlib

import numpy as np

_MAGIC = 0x50545231

_DTYPE_CODES = {
    np.dtype('float32'): 0, np.dtype('float64'): 1, np.dtype('int32'): 2,
    np.dtype('int64'): 3, np.dtype('uint8'): 4, np.dtype('int16'): 5,
    np.dtype('bool'): 6,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}
_CODE_DTYPES[7] = np.dtype('uint16')


class FallbackWriter(object):
    def __init__(self, path):
        self.f = open(path, 'wb')

    def write(self, arrs):
        payload = bytearray(struct.pack('<H', len(arrs)))
        for a in arrs:
            payload += struct.pack('<BB', _DTYPE_CODES[a.dtype], a.ndim)
            payload += struct.pack('<%dq' % a.ndim, *a.shape)
            payload += a.tobytes()
        self.f.write(struct.pack('<III', _MAGIC, len(payload),
                                 zlib.crc32(bytes(payload)) & 0xFFFFFFFF))
        self.f.write(payload)

    def close(self):
        self.f.close()


def read_samples(path):
    with open(path, 'rb') as f:
        while True:
            hdr = f.read(12)
            if not hdr:
                return
            magic, ln, crc = struct.unpack('<III', hdr)
            if magic != _MAGIC:
                raise IOError('bad record frame in %s' % path)
            payload = f.read(ln)
            if len(payload) != ln or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise IOError('corrupt record in %s' % path)
            off = 0
            (nf,) = struct.unpack_from('<H', payload, off)
            off += 2
            fields = []
            for _ in range(nf):
                code, ndim = struct.unpack_from('<BB', payload, off)
                off += 2
                dims = struct.unpack_from('<%dq' % ndim, payload, off)
                off += 8 * ndim
                dt = _CODE_DTYPES[code]
                nbytes = int(np.prod(dims)) * dt.itemsize if ndim else \
                    dt.itemsize
                arr = np.frombuffer(payload, dtype=dt, count=max(
                    nbytes // dt.itemsize, 0), offset=off).reshape(dims)
                off += nbytes
                fields.append(arr.copy())
            yield tuple(fields)


def iter_batches(paths, batch_size, shuffle_capacity, seed, drop_last,
                 loop_forever):
    rng = random.Random(seed)

    def samples():
        while True:
            for p in paths:
                for s in read_samples(p):
                    yield s
            if not loop_forever:
                return

    def shuffled(it):
        if shuffle_capacity <= 0:
            for s in it:
                yield s
            return
        buf = []
        for s in it:
            buf.append(s)
            if len(buf) >= shuffle_capacity:
                i = rng.randrange(len(buf))
                buf[i], buf[-1] = buf[-1], buf[i]
                yield buf.pop()
        while buf:
            i = rng.randrange(len(buf))
            buf[i], buf[-1] = buf[-1], buf[i]
            yield buf.pop()

    pending = []
    for s in shuffled(samples()):
        pending.append(s)
        if len(pending) == batch_size:
            yield tuple(np.stack([p[i] for p in pending])
                        for i in range(len(pending[0])))
            pending = []
    if pending and not drop_last:
        yield tuple(np.stack([p[i] for p in pending])
                    for i in range(len(pending[0])))
