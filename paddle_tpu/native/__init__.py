"""Native (C++) host data pipeline.

Parity: reference paddle/fluid/framework/data_feed.cc + recordio/ +
async_executor feeding.  The on-device executor/allocator of the reference
has no TPU equivalent to build (XLA owns device execution and memory), so
the native layer is where it matters on TPU: the host input pipeline.  File
parsing, shuffle buffering and batch assembly run in C++ threads off the
GIL, overlapping the TPU step (see src/datafeed.cc).

The shared library is compiled on first use with g++ (no pip deps; bound via
ctypes).  If no toolchain is available the pure-NumPy fallback in
`fallback.py` provides identical semantics.
"""
import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'src', 'datafeed.cc')
_LIB_PATH = os.path.join(_HERE, 'libptdatafeed.so')
_lock = threading.Lock()
_lib = None
_build_err = None


def _build():
    cmd = ['g++', '-O2', '-shared', '-fPIC', '-std=c++14', '-pthread',
           _SRC, '-o', _LIB_PATH]
    subprocess.run(cmd, check=True, capture_output=True)


def _bind(lib):
    i8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ptrec_writer_open.restype = ctypes.c_void_p
    lib.ptrec_writer_open.argtypes = [ctypes.c_char_p]
    lib.ptrec_writer_write.restype = ctypes.c_int
    lib.ptrec_writer_write.argtypes = [
        ctypes.c_void_p, ctypes.c_int, i8p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(i8p), ctypes.POINTER(ctypes.c_int64)]
    lib.ptrec_writer_close.argtypes = [ctypes.c_void_p]
    lib.ptrec_reader_open.restype = ctypes.c_void_p
    lib.ptrec_reader_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int64]
    lib.ptrec_reader_next.restype = ctypes.c_int
    lib.ptrec_reader_next.argtypes = [ctypes.c_void_p]
    lib.ptrec_reader_field_dtype.restype = ctypes.c_int
    lib.ptrec_reader_field_dtype.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptrec_reader_field_ndim.restype = ctypes.c_int
    lib.ptrec_reader_field_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptrec_reader_field_dims.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
    lib.ptrec_reader_field_data.restype = i8p
    lib.ptrec_reader_field_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptrec_reader_error.restype = ctypes.c_char_p
    lib.ptrec_reader_error.argtypes = [ctypes.c_void_p]
    lib.ptrec_reader_close.argtypes = [ctypes.c_void_p]
    return lib


def get_lib():
    """Load (building if needed) the native library, or None on failure."""
    global _lib, _build_err
    with _lock:
        if _lib is not None or _build_err is not None:
            return _lib
        try:
            if (not os.path.exists(_LIB_PATH) or
                    os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except Exception as e:  # no toolchain / sandboxed build failure
            _build_err = e
        return _lib


def native_available():
    return get_lib() is not None


from .datafeed import (RecordWriter, RecordReader, BatchReader,  # noqa: E402
                       write_records, DataFeedDesc)

__all__ = ['get_lib', 'native_available', 'RecordWriter', 'RecordReader',
           'BatchReader', 'write_records', 'DataFeedDesc']
