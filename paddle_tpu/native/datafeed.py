"""Python face of the native data pipeline (ctypes bindings + fallback).

Parity: reference python/paddle/fluid/data_feed_desc.py (DataFeedDesc),
recordio python API, and the batch/shuffle/double_buffer reader decorators —
backed by the C++ pipeline in src/datafeed.cc when a toolchain is present,
else by `fallback.py` (same on-disk format, same semantics).
"""
import ctypes
import os

import numpy as np

from . import fallback

_DTYPE_CODES = {
    np.dtype('float32'): 0, np.dtype('float64'): 1, np.dtype('int32'): 2,
    np.dtype('int64'): 3, np.dtype('uint8'): 4, np.dtype('int16'): 5,
    np.dtype('bool'): 6,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}
_CODE_DTYPES[7] = np.dtype('uint16')  # bf16 carried as raw u16


def _lib():
    from . import get_lib
    return get_lib()


class RecordWriter(object):
    """Writes samples (tuples of ndarrays) to a ptrec file."""

    def __init__(self, path):
        self.path = path
        lib = _lib()
        if lib is None:
            self._impl = fallback.FallbackWriter(path)
            self._h = None
        else:
            self._impl = None
            self._h = lib.ptrec_writer_open(path.encode())
            if not self._h:
                raise IOError('cannot open %s for writing' % path)

    def write(self, sample):
        arrs = [np.ascontiguousarray(a) for a in sample]
        if self._impl is not None:
            return self._impl.write(arrs)
        lib = _lib()
        n = len(arrs)
        dtypes = (ctypes.c_uint8 * n)(
            *[_DTYPE_CODES[a.dtype] for a in arrs])
        ndims = (ctypes.c_int32 * n)(*[a.ndim for a in arrs])
        dims_flat = []
        for a in arrs:
            dims_flat.extend(a.shape)
        dims = (ctypes.c_int64 * len(dims_flat))(*dims_flat)
        ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
              for a in arrs])
        nbytes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrs])
        rc = lib.ptrec_writer_write(self._h, n, dtypes, ndims, dims,
                                    ptrs, nbytes)
        if rc != 0:
            raise IOError('write failed on %s' % self.path)

    def close(self):
        if self._impl is not None:
            self._impl.close()
        elif self._h:
            _lib().ptrec_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def write_records(path, samples):
    with RecordWriter(path) as w:
        for s in samples:
            w.write(s)


class BatchReader(object):
    """Iterates batches (tuples of stacked ndarrays) from ptrec files.

    shuffle_capacity > 0 enables the C++ reservoir shuffle buffer;
    prefetch sets the depth of the ready-batch queue (double_buffer).
    """

    def __init__(self, paths, batch_size, shuffle_capacity=0, seed=0,
                 drop_last=False, loop_forever=False, prefetch=4):
        if isinstance(paths, str):
            paths = [paths]
        for p in paths:
            if not os.path.exists(p):
                raise IOError('no such file: %s' % p)
        self._args = (paths, batch_size, shuffle_capacity, seed,
                      drop_last, loop_forever, prefetch)
        self._h = None
        self._fallback = _lib() is None

    def __iter__(self):
        paths, bs, cap, seed, drop, loop, pf = self._args
        if self._fallback:
            for batch in fallback.iter_batches(paths, bs, cap, seed, drop,
                                               loop):
                yield batch
            return
        lib = _lib()
        cpaths = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        h = lib.ptrec_reader_open(cpaths, len(paths), bs, cap, seed,
                                  int(drop), int(loop), pf)
        try:
            while True:
                nf = lib.ptrec_reader_next(h)
                if nf < 0:
                    raise IOError(lib.ptrec_reader_error(h).decode())
                if nf == 0:
                    return
                fields = []
                for i in range(nf):
                    ndim = lib.ptrec_reader_field_ndim(h, i)
                    dims = (ctypes.c_int64 * ndim)()
                    lib.ptrec_reader_field_dims(h, i, dims)
                    shape = tuple(dims)
                    dt = _CODE_DTYPES[lib.ptrec_reader_field_dtype(h, i)]
                    nbytes = int(np.prod(shape)) * dt.itemsize
                    ptr = lib.ptrec_reader_field_data(h, i)
                    buf = ctypes.cast(
                        ptr, ctypes.POINTER(ctypes.c_uint8 * nbytes))
                    # copy out: the C buffer is recycled on the next call
                    fields.append(np.frombuffer(
                        bytearray(buf.contents), dtype=dt).reshape(shape))
                yield tuple(fields)
        finally:
            lib.ptrec_reader_close(h)


class RecordReader(object):
    """Sample-at-a-time reader (batch_size=1, squeezed): recordio parity."""

    def __init__(self, path):
        self.path = path

    def __iter__(self):
        for batch in BatchReader(self.path, batch_size=1):
            yield tuple(f[0] for f in batch)


class DataFeedDesc(object):
    """Feed pipeline description (parity: fluid.DataFeedDesc /
    data_feed.proto).  Declares slot names/types/shapes plus pipeline
    parameters; `reader()` materializes the native BatchReader."""

    def __init__(self, paths=None, batch_size=1, shuffle_capacity=0,
                 seed=0, drop_last=False):
        self.paths = paths or []
        self.batch_size = batch_size
        self.shuffle_capacity = shuffle_capacity
        self.seed = seed
        self.drop_last = drop_last
        self.slots = []  # (name, dtype, shape)

    def add_slot(self, name, dtype, shape):
        self.slots.append((name, np.dtype(dtype), tuple(shape)))
        return self

    def set_batch_size(self, batch_size):
        self.batch_size = batch_size

    def set_use_slots(self, names):
        self.use_slots = list(names)

    def reader(self, **overrides):
        kw = dict(batch_size=self.batch_size,
                  shuffle_capacity=self.shuffle_capacity, seed=self.seed,
                  drop_last=self.drop_last)
        kw.update(overrides)
        return BatchReader(self.paths, **kw)

    def desc(self):
        lines = ['batch_size: %d' % self.batch_size]
        for (name, dtype, shape) in self.slots:
            lines.append('slot { name: "%s" type: "%s" shape: %s }'
                         % (name, dtype.name, list(shape)))
        return '\n'.join(lines)
