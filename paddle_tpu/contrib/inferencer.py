"""High-level Inferencer (parity: reference contrib/inferencer.py)."""
from ..core import framework
from ..core.executor import Executor, Scope, scope_guard
from .. import io as fluid_io

__all__ = ['Inferencer']


class Inferencer(object):
    """infer_func() builds the inference graph and returns the prediction
    Variable(s); params load from `param_path` (a save_params /
    save_persistables dir)."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.scope = Scope()
        self.inference_program = framework.Program()
        startup = framework.Program()
        with framework.program_guard(self.inference_program, startup):
            out = infer_func()
            self.predict_vars = list(out) if isinstance(
                out, (list, tuple)) else [out]
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(startup)
            fluid_io.load_persistables(self.exe, param_path,
                                       self.inference_program)

    def infer(self, inputs, return_numpy=True):
        if not isinstance(inputs, dict):
            raise ValueError('inputs must be a dict of {var_name: ndarray}')
        with scope_guard(self.scope):
            results = self.exe.run(
                self.inference_program, feed=inputs,
                fetch_list=[v.name for v in self.predict_vars],
                return_numpy=return_numpy)
        return results
