"""Quantization-aware training + inference freezing.

Parity: reference contrib/quantize/quantize_transpiler.py
(QuantizeTranspiler: training_transpile, freeze_program, convert_to_int8).

TPU-native: fake-quant/dequant pairs are plain registered ops inserted
before each quantizable op — the straight-through estimator lives in the
op's JAX impl, and XLA fuses the round/clip/scale chain into the matmul it
guards, so QAT costs almost nothing on the MXU.  Freezing folds weight
scales into int8 scope arrays; TPU int8 matmuls feed the MXU directly.
"""
import numpy as np

from ..core import unique_name
from ..core.framework import Operator, Parameter

__all__ = ['QuantizeTranspiler']

_QUANTIZABLE = {'mul', 'matmul', 'conv2d', 'conv2d_transpose'}


def _quantized_var_name(n):
    return '%s.quantized' % n


def _quantized_scale_name(n):
    return '%s.scale' % n


class QuantizeTranspiler(object):
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type='abs_max',
                 weight_quantize_type='abs_max', window_size=10000,
                 moving_rate=0.9):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        if activation_quantize_type not in (
                'abs_max', 'range_abs_max', 'moving_average_abs_max'):
            raise ValueError('unknown activation_quantize_type %s'
                             % activation_quantize_type)
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.moving_rate = moving_rate

    # ------------------------------------------------------------ train
    def training_transpile(self, program=None, startup_program=None):
        """Insert fake-quant/dequant before every quantizable op's inputs
        (weights and activations), in place."""
        from ..core.framework import default_main_program
        program = program or default_main_program()
        for block in program.blocks:
            self._transpile_block(block)
        program._bump()
        return program

    def _transpile_block(self, block):
        new_ops = []
        quantized = {}  # original name -> quantized name (this block)
        for op in block.ops:
            if op.type in _QUANTIZABLE:
                for slot, names in list(op.inputs.items()):
                    qnames = []
                    for n in names:
                        v = block._find_var_recursive(n)
                        if v is None or v.dtype not in ('float32',
                                                        'bfloat16'):
                            qnames.append(n)
                            continue
                        if n not in quantized:
                            is_w = isinstance(v, Parameter)
                            qop, qname = self._make_fake_quant(
                                block, v, is_weight=is_w)
                            new_ops.append(qop)
                            quantized[n] = qname
                        qnames.append(quantized[n])
                    op.inputs[slot] = qnames
            new_ops.append(op)
        block.ops = new_ops

    def _make_fake_quant(self, block, var, is_weight):
        bits = self.weight_bits if is_weight else self.activation_bits
        qname = _quantized_var_name(var.name)
        out = block.create_var(name=qname, shape=var.shape, dtype=var.dtype)
        scale = block.create_var(
            name=unique_name.generate(_quantized_scale_name(var.name)),
            shape=(1,), dtype='float32',
            persistable=not is_weight and self.act_type != 'abs_max',
            stop_gradient=True)
        use_moving = (not is_weight and self.act_type in
                      ('range_abs_max', 'moving_average_abs_max'))
        if use_moving:
            # moving scale state: zero-init, updated in the step itself
            from ..initializer import Constant
            Constant(0.0)(scale)
            op = Operator(
                block, 'fake_quantize_dequantize_moving_average_abs_max',
                inputs={'X': var, 'InScale': scale},
                outputs={'Out': out, 'OutScale': scale},
                attrs={'bit_length': bits,
                       'moving_rate': self.moving_rate})
        else:
            op = Operator(block, 'fake_quantize_dequantize_abs_max',
                          inputs={'X': var},
                          outputs={'Out': out, 'OutScale': scale},
                          attrs={'bit_length': bits})
        return op, qname

    # ----------------------------------------------------------- freeze
    def freeze_program(self, program, place=None, fuse_bn=False,
                       scope=None):
        """Turn a QAT program into an inference program.  Weight
        fake-quants are folded by re-quantizing the trained weights once
        on the host; activation fake-quants are REPLACED by fixed-scale
        quantize/dequantize ops using the trained moving-average scale
        (parity: the reference freeze pass at
        contrib/quantize/quantize_transpiler.py:218 removes only WEIGHT
        fake-quants — storing weights pre-quantized — and keeps activation
        quantization live in the inference graph), so frozen numerics match
        what QAT simulated.  Activation quants with no recorded scale
        (abs_max mode) are kept as-is: their scale is computed per batch at
        inference too, exactly as during training."""
        from ..core.executor import global_scope
        scope = scope or global_scope()
        rmax = float(2 ** (self.weight_bits - 1) - 1)
        for block in program.blocks:
            kept = []
            rewire = {}
            for op in block.ops:
                for slot, names in list(op.inputs.items()):
                    op.inputs[slot] = [rewire.get(n, n) for n in names]
                if op.type.startswith('fake_quantize_dequantize'):
                    src = op.inputs['X'][0]
                    dst = op.outputs['Out'][0]
                    v = block._find_var_recursive(src)
                    if isinstance(v, Parameter) and src in scope:
                        # weight: fold the qdq into the stored tensor
                        w = np.asarray(scope.vars[src])
                        scale = float(np.abs(w).max()) or 1e-8
                        qdq = np.clip(np.round(w / scale * rmax),
                                      -rmax, rmax) / rmax * scale
                        scope.vars[src] = scope.vars[src] * 0 + qdq.astype(
                            w.dtype)
                        rewire[dst] = src
                        continue
                    in_scale = op.inputs.get('InScale', [None])[0]
                    trained = (float(np.asarray(scope.vars[in_scale]).sum())
                               if in_scale and in_scale in scope else 0.0)
                    if trained > 0:
                        # activation: freeze at the trained moving-average
                        # scale
                        op = Operator(
                            block, 'quantize_dequantize_fixed_scale',
                            inputs={'X': op.inputs['X'][0]},
                            outputs={'Out': dst},
                            attrs={'scale': trained,
                                   'bit_length':
                                       op.attrs.get('bit_length', 8)})
                kept.append(op)
            block.ops = kept
        program._bump()
        return program

    def convert_to_int8(self, program, place=None, scope=None):
        """Store quantizable weights as int8 + float scale in the scope
        (deploy-size artifact; ops dequantize on read)."""
        from ..core.executor import global_scope
        scope = scope or global_scope()
        rmax = float(2 ** (self.weight_bits - 1) - 1)
        converted = {}
        block = program.global_block()
        for name, v in block.vars.items():
            if isinstance(v, Parameter) and name in scope:
                w = np.asarray(scope.vars[name])
                scale = float(np.abs(w).max()) or 1e-8
                q = np.clip(np.round(w / scale * rmax),
                            -rmax, rmax).astype(np.int8)
                converted[name] = (q, scale)
        return converted
