"""CTR data reader (csv / svm formats, optionally gzipped).

Parity: reference contrib/reader/ctr_reader.py:53, whose C++ reader op
threads parse click-through-rate logs into a blocking queue.  Here the
parse pipeline is host-side Python (the device pipeline is the jitted
step): files stream through a buffered prefetch
(paddle_tpu.reader.buffered), and the returned Reader yields feed dicts
ready for Executor.run — start()/reset() keep the reference's pass
protocol.

Formats (reference docstring):
  csv:  ``label d1,d2,... s1,s2,...``  (dense floats, sparse int ids)
  svm:  ``label slot:sign slot:sign ...``
"""
import gzip

import numpy as np

from ... import reader as reader_mod

__all__ = ['ctr_reader']


def _open(path, file_type):
    if file_type == 'gzip':
        return gzip.open(path, 'rt')
    return open(path, 'r')


def _parse_csv(line, dense_slot_index, sparse_slot_index):
    parts = line.split()
    label = int(parts[0])
    dense, sparse = [], []
    for idx in dense_slot_index:
        dense.extend(float(v) for v in parts[idx].split(','))
    for idx in sparse_slot_index:
        sparse.extend(int(v) for v in parts[idx].split(','))
    return label, dense, sparse


def _parse_svm(line, slots):
    parts = line.split()
    label = int(parts[0])
    per_slot = {s: [] for s in slots}
    for tok in parts[1:]:
        slot, sign = tok.split(':')
        slot = int(slot)
        if slot in per_slot:
            per_slot[slot].append(int(sign))
    return label, per_slot


class _CtrReader(object):
    def __init__(self, feed_dict, file_type, file_format,
                 dense_slot_index, sparse_slot_index, capacity,
                 batch_size, file_list, slots):
        if file_type not in ('gzip', 'plain'):
            raise ValueError('file_type must be gzip or plain')
        if file_format not in ('csv', 'svm'):
            raise ValueError('file_format must be csv or svm')
        self._feed_names = [getattr(v, 'name', v) for v in feed_dict]
        self._file_type = file_type
        self._file_format = file_format
        self._dense = list(dense_slot_index or [])
        self._sparse = list(sparse_slot_index or [])
        self._capacity = capacity
        self._batch_size = batch_size
        self._file_list = list(file_list)
        self._slots = list(slots or [])
        self._running = False

    def start(self):
        """Begin a pass (the reference protocol: start each pass, reset
        after the EOF)."""
        self._running = True

    def reset(self):
        self._running = False

    def _assert_running(self):
        if not self._running:
            raise ValueError('ctr_reader: call start() before iterating '
                             'a pass (and reset() after it ends)')

    def _rows(self):
        for path in self._file_list:
            with _open(path, self._file_type) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if self._file_format == 'csv':
                        yield _parse_csv(line, self._dense, self._sparse)
                    else:
                        label, per_slot = _parse_svm(line, self._slots)
                        yield (label,
                               [v for s in self._slots
                                for v in per_slot[s]], [])

    def __call__(self):
        self._assert_running()

        def batches():
            buf = []
            for row in self._rows():
                buf.append(row)
                if len(buf) == self._batch_size:
                    yield self._to_feed(buf)
                    buf = []
            if buf:
                yield self._to_feed(buf)
        return reader_mod.buffered(batches, max(1, self._capacity))()

    @staticmethod
    def _pad_ids(seqs):
        width = max(len(s) for s in seqs)
        out = np.zeros((len(seqs), width), np.int64)
        for i, s in enumerate(seqs):
            out[i, :len(s)] = s
        return out

    def _to_feed(self, rows):
        labels = np.array([[r[0]] for r in rows], np.int64)
        cols = [labels]
        if self._file_format == 'csv':
            cols.append(np.array([r[1] for r in rows], np.float32))
            if any(len(r[2]) for r in rows):
                cols.append(self._pad_ids([r[2] for r in rows]))
        else:
            # svm rows are ragged id lists — zero-pad to batch width
            cols.append(self._pad_ids([r[1] for r in rows]))
        if len(cols) != len(self._feed_names):
            raise ValueError(
                'ctr_reader produced %d columns for %d feed vars %s — '
                'check dense/sparse_slot_index against feed_dict'
                % (len(cols), len(self._feed_names), self._feed_names))
        return dict(zip(self._feed_names, cols))


def ctr_reader(feed_dict, file_type, file_format, dense_slot_index,
               sparse_slot_index, capacity, thread_num, batch_size,
               file_list, slots, name=None):
    """Build the CTR reader (reference signature; `thread_num` is
    absorbed by the buffered prefetch — host threads are not the
    bottleneck when the step is one XLA executable)."""
    return _CtrReader(feed_dict, file_type, file_format,
                      dense_slot_index, sparse_slot_index, capacity,
                      batch_size, file_list, slots)
