from . import ctr_reader  # noqa

__all__ = ['ctr_reader']
