"""High-level Trainer (parity: reference contrib/trainer.py — the book
chapters' train loop: events, feed_order readers, checkpointing).

TPU-native: the train step is the Executor's single jitted XLA executable;
the Trainer only owns the epoch/step loop, the event callbacks, and
checkpoint rotation (train/checkpoint.py), which all stay on the host.
"""
import numpy as np

from .. import observability as _obs
from ..core import framework
from ..core.executor import Executor, Scope, scope_guard
from ..data_feeder import DataFeeder
from .. import io as fluid_io
from ..train.checkpoint import Checkpointer
from ..train.checkpoint import CheckpointConfig as _CkptConfig

__all__ = ['Trainer', 'BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
           'EndStepEvent', 'CheckpointConfig']


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent(object):
    """`telemetry` is a per-step snapshot of the observability counters/
    gauges ({name: value}, None when telemetry is disabled) — event
    handlers can watch executor.retraces / executor.stall_count /
    prefetch.starvation_s climb live instead of post-mortem.

    In async-metrics mode (``Trainer.train(async_metrics=M)``) `metrics`
    holds lazy ``FetchFuture`` handles instead of numpy arrays: a handler
    that ignores them costs ZERO host syncs; ``np.asarray(m)`` /
    ``float(m)`` forces (and meters) the read on demand."""

    def __init__(self, epoch_id, step_id, metrics, telemetry=None):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics
        self.telemetry = telemetry


def _telemetry_snapshot():
    return _obs.counters() if _obs.enabled() else None


class CheckpointConfig(_CkptConfig):
    """Same knobs as the reference contrib CheckpointConfig."""


class Trainer(object):
    """train_func() -> loss Variable (or [loss, ...metrics]) builds the
    model inside the trainer's programs; optimizer_func() -> Optimizer."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.scope = Scope()
        self.startup_program = framework.Program()
        self.train_program = framework.Program()
        self.parallel = parallel

        with framework.program_guard(self.train_program,
                                     self.startup_program):
            out = train_func()
            if isinstance(out, (list, tuple)):
                self.loss = out[0]
                self.metrics = list(out)
            else:
                self.loss = out
                self.metrics = [out]
            # test program: forward only, is_test flipped
            self.test_program = self.train_program.clone(for_test=True)
            optimizer = optimizer_func()
            optimizer.minimize(self.loss)

        self.place = place
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            if param_path:
                fluid_io.load_persistables(self.exe, param_path,
                                           self.train_program)
        self.checkpointer = None
        self._resume_epoch = 0
        self._resume_step = -1
        if checkpoint_config:
            self.checkpointer = Checkpointer(checkpoint_config, self.exe,
                                             self.train_program,
                                             scope=self.scope)
            meta = self.checkpointer.restore()
            if meta:
                # resume at STEP granularity: the resume epoch replays
                # only the reader entries after the checkpointed step,
                # with the restored RNG counters keeping the stream
                # bitwise-identical to the uninterrupted run
                self._resume_epoch = meta['epoch_id']
                self._resume_step = meta.get('step_id', -1)
            if self.checkpointer.config.handle_signals:
                # preemption safety: SIGTERM/SIGINT flush one final
                # checkpoint at the last recorded step before exiting
                self.checkpointer.install_signal_handlers()
        self.__stop = False

    def stop(self):
        self.__stop = True

    def _feeder(self, feed_order, program):
        if feed_order is None:
            # reference contrib Trainer derives the feed list from the
            # program's data vars when feed_order is omitted
            block = program.global_block()
            feed_order = [n for n, v in block.vars.items()
                          if v.is_data and not n.endswith('@LENGTH')]
        feed_vars = [program.global_block().var(n) for n in feed_order]
        return DataFeeder(feed_vars, program=program)

    def _resume_skip(self, epoch_id):
        """How many leading reader entries of this epoch a checkpoint
        already covers (0 beyond the resume epoch)."""
        if epoch_id == self._resume_epoch and self._resume_step >= 0:
            return self._resume_step + 1
        return 0

    def train(self, num_epochs, event_handler, reader=None,
              feed_order=None, steps_per_launch=1, recovery=None,
              async_metrics=None):
        """steps_per_launch=K fuses K train iterations into ONE device
        launch (Executor.run_steps — a jitted lax.scan), amortizing the
        per-launch dispatch cost.  Step events still fire per iteration
        with that iteration's metrics (sliced from the stacked fetches);
        BeginStepEvent.fetch_metrics is honored at launch granularity
        (the first step's choice governs its whole launch).

        recovery: a train.RecoveryPolicy — a diverged launch (check_nan
        trip or loss spike) rolls back to the last checkpoint and the
        offending superbatch is skipped instead of killing the run.

        async_metrics=M (fused path only, docs/async.md) makes the
        steady state fetch-free: launches return FetchFuture handles
        (EndStepEvent.metrics are lazy per-step views), per-metric
        running sums accumulate ON DEVICE, and one metered host sync
        every M launches lands their means in ``self.last_metric_means``.
        The loss-spike heuristic is skipped (it would read the loss per
        launch); the deferred check_nan verdict covers divergence.
        Checkpoints stay aligned with clean verdict polls: a save only
        happens when ``exe.nan_clean()`` — so the restore point of a
        deferred trip always predates the condemned window."""
        if steps_per_launch <= 1:
            return self._train_single(num_epochs, event_handler, reader,
                                      feed_order, recovery)
        feeder = self._feeder(feed_order, self.train_program)
        K = int(steps_per_launch)
        use_async = async_metrics is not None and int(async_metrics) >= 1
        sync_every = int(async_metrics) if use_async else 0
        self.last_metric_means = None
        self._metric_sums = None
        self._metric_steps = 0
        self._launches_since_sync = 0
        with scope_guard(self.scope):
            for epoch_id in range(self._resume_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                skip = self._resume_skip(epoch_id)
                buf = []
                step_id = skip
                stopped = False

                def flush(buf, step_id):
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    for i in range(1, len(buf)):
                        event_handler(BeginStepEvent(epoch_id, step_id + i))
                    fetch = [m.name for m in self.metrics] \
                        if begin.fetch_metrics else []
                    def launch():
                        with _obs.trace_context.root_span(
                                'trainer.step', cat='trainer',
                                args={'epoch': epoch_id, 'step': step_id,
                                      'steps': len(buf)}):
                            return self.exe.run_steps(
                                self.train_program, feed_list=buf,
                                fetch_list=fetch, steps=len(buf),
                                as_futures=use_async)
                    if recovery is None:
                        stacked = launch()
                    elif use_async:
                        # the loss-spike heuristic would force a host read
                        # per launch; the deferred check_nan verdict covers
                        # divergence instead
                        stacked = recovery.run(launch, loss_index=None)
                    else:
                        stacked = recovery.run(launch)
                    if stacked is None:
                        # diverged + rolled back: the superbatch is
                        # skipped, its step ids stay consumed; on-device
                        # sums accumulated since the last sync are part of
                        # the condemned window — drop them with it
                        if use_async:
                            self._metric_sums = None
                            self._metric_steps = 0
                            self._launches_since_sync = 0
                        return step_id + len(buf)
                    if use_async and stacked:
                        self._accumulate_metrics(stacked, len(buf))
                        if self._launches_since_sync >= sync_every:
                            self._sync_metrics()
                    telem = _telemetry_snapshot()
                    for i in range(len(buf)):
                        if use_async:
                            # lazy per-step views: a handler that ignores
                            # them costs zero syncs
                            metrics = [m[i] for m in stacked]
                        else:
                            metrics = [np.asarray(m[i]) for m in stacked]
                        if self.checkpointer:
                            if self.exe.nan_clean():
                                self.checkpointer.maybe_save(epoch_id,
                                                             step_id + i)
                            else:
                                # verdicts still pending on device: record
                                # progress but don't persist state the next
                                # poll may condemn
                                self.checkpointer.note_progress(epoch_id,
                                                                step_id + i)
                        event_handler(EndStepEvent(epoch_id, step_id + i,
                                                   metrics, telemetry=telem))
                    return step_id + len(buf)

                for i, data in enumerate(reader()):
                    if i < skip:
                        continue
                    if self.__stop:
                        stopped = True
                        break
                    buf.append(feeder.feed(data))
                    if len(buf) == K:
                        step_id = flush(buf, step_id)
                        buf = []
                if buf and not stopped:
                    step_id = flush(buf, step_id)
                if stopped:
                    if use_async:
                        # force the deferred verdict before persisting:
                        # never checkpoint state a pending poll condemns
                        self.exe.poll_nan()
                    if self.checkpointer:
                        self.checkpointer.save(epoch_id, step_id)
                    return
                if use_async:
                    # epoch boundary: drain the verdict window (through
                    # recovery so a late trip rolls back instead of
                    # killing the run) and land the metric means
                    def drain():
                        self.exe.poll_nan()
                        return []
                    out = drain() if recovery is None \
                        else recovery.run(drain, loss_index=None)
                    if out is None:
                        self._metric_sums = None
                        self._metric_steps = 0
                        self._launches_since_sync = 0
                    self._sync_metrics()
                event_handler(EndEpochEvent(epoch_id))

    def _accumulate_metrics(self, stacked, steps):
        """Fold one launch's stacked fetches into the on-device running
        sums (async-metrics mode) — a pure device op, no host sync."""
        import jax.numpy as jnp
        sums = [jnp.sum(m.device(), axis=0) for m in stacked]
        if self._metric_sums is None:
            self._metric_sums = sums
        else:
            self._metric_sums = [a + s for a, s in
                                 zip(self._metric_sums, sums)]
        self._metric_steps += steps
        self._launches_since_sync += 1

    def _sync_metrics(self):
        """ONE metered host sync for everything accumulated since the
        last one: lands per-metric means in ``self.last_metric_means``."""
        from ..core import async_runtime as _async
        if self._metric_steps:
            with _async.host_block('metric_sync',
                                   steps=self._metric_steps):
                sums = [np.asarray(s) for s in self._metric_sums]
            self.last_metric_means = [s / float(self._metric_steps)
                                      for s in sums]
        self._metric_sums = None
        self._metric_steps = 0
        self._launches_since_sync = 0

    def _train_single(self, num_epochs, event_handler, reader, feed_order,
                      recovery=None):
        feeder = self._feeder(feed_order, self.train_program)
        with scope_guard(self.scope):
            for epoch_id in range(self._resume_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                skip = self._resume_skip(epoch_id)
                for step_id, data in enumerate(reader()):
                    if step_id < skip:
                        continue
                    if self.__stop:
                        if self.checkpointer:
                            self.checkpointer.save(epoch_id, step_id)
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    fetch = [m.name for m in self.metrics] \
                        if begin.fetch_metrics else []
                    def launch():
                        with _obs.trace_context.root_span(
                                'trainer.step', cat='trainer',
                                args={'epoch': epoch_id, 'step': step_id}):
                            return self.exe.run(
                                self.train_program, feed=feeder.feed(data),
                                fetch_list=fetch)
                    metrics = launch() if recovery is None \
                        else recovery.run(launch)
                    if metrics is None:
                        continue   # diverged step rolled back + skipped
                    if self.checkpointer:
                        if self.exe.nan_clean():
                            self.checkpointer.maybe_save(epoch_id, step_id)
                        else:
                            self.checkpointer.note_progress(epoch_id,
                                                            step_id)
                    event_handler(EndStepEvent(
                        epoch_id, step_id, metrics,
                        telemetry=_telemetry_snapshot()))
                event_handler(EndEpochEvent(epoch_id))

    def test(self, reader, feed_order):
        feeder = self._feeder(feed_order, self.test_program)
        accum = None
        count = 0
        with scope_guard(self.scope):
            for data in reader():
                vals = self.exe.run(self.test_program,
                                    feed=feeder.feed(data),
                                    fetch_list=[m.name
                                                for m in self.metrics])
                vals = [np.asarray(v, dtype='float64') for v in vals]
                accum = vals if accum is None else [
                    a + v for a, v in zip(accum, vals)]
                count += 1
        if accum is None:
            return []
        return [a / count for a in accum]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            fluid_io.save_persistables(self.exe, param_path,
                                       self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        with scope_guard(self.scope):
            fluid_io.save_inference_model(
                param_path, feeded_var_names,
                [self.metrics[i] for i in target_var_indexes], self.exe,
                self.test_program)
