"""paddle_tpu.contrib.slim — model compression toolkit.

Parity: reference contrib/slim/ (prune/, quantization/, core/).  The
reference organizes compression as IrGraph passes driven by a config-file
Compressor; here each pass is direct Program surgery (the whole block is
one XLA executable, so there is no separate IR graph layer to rewrite).
"""
from . import prune  # noqa
from .prune import Pruner, MagnitudePruner, RatioPruner, SensitivePruner  # noqa
from . import quantization  # noqa
from .quantization import (QuantizationTransformPass,  # noqa
                           QuantizationFreezePass, ConvertToInt8Pass,
                           TransformForMobilePass)

__all__ = (prune.__all__ + quantization.__all__)
