"""Quantization passes, slim-style API.

Parity: reference contrib/slim/quantization/quantization_pass.py
(QuantizationTransformPass, QuantizationFreezePass, ConvertToInt8Pass,
TransformForMobilePass).  The reference rewrites an IrGraph; under
whole-block XLA lowering the Program IS the graph, so each pass is a thin
driver over the same machinery QuantizeTranspiler uses — one set of
semantics, two public APIs (transpiler-era and slim-era), like the
reference ships.
"""
from ..quantize import QuantizeTranspiler

__all__ = ['QuantizationTransformPass', 'QuantizationFreezePass',
           'ConvertToInt8Pass', 'TransformForMobilePass']


class QuantizationTransformPass(object):
    """Insert fake-quant/dequant pairs for QAT
    (ref quantization_pass.py:28 QuantizationTransformPass.apply)."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8,
                 activation_quantize_type='abs_max',
                 weight_quantize_type='abs_max', window_size=10000,
                 moving_rate=0.9):
        self.scope = scope
        self._t = QuantizeTranspiler(
            weight_bits=weight_bits, activation_bits=activation_bits,
            activation_quantize_type=activation_quantize_type,
            weight_quantize_type=weight_quantize_type,
            window_size=window_size, moving_rate=moving_rate)

    def apply(self, program, startup_program=None):
        return self._t.training_transpile(program, startup_program)


class QuantizationFreezePass(object):
    """Fold trained quant state into an inference program
    (ref QuantizationFreezePass.apply)."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, weight_quantize_type='abs_max'):
        self.scope = scope
        self._t = QuantizeTranspiler(weight_bits=weight_bits,
                                     activation_bits=activation_bits)

    def apply(self, program):
        return self._t.freeze_program(program, scope=self.scope)


class ConvertToInt8Pass(object):
    """Pack weights as int8 + scale scope artifacts
    (ref ConvertToInt8Pass.apply)."""

    def __init__(self, scope=None, place=None, weight_bits=8):
        self.scope = scope
        self._t = QuantizeTranspiler(weight_bits=weight_bits)

    def apply(self, program):
        return self._t.convert_to_int8(program, scope=self.scope)


class TransformForMobilePass(object):
    """The reference pass renames fake ops to mobile 'quantize'/
    'dequantize' kernels for Paddle-Mobile.  There is no mobile runtime
    here; the pass validates and returns the program unchanged."""

    def __init__(self, *a, **kw):
        pass

    def apply(self, program):
        return program
