"""Weight pruning passes.

Parity: reference contrib/slim/prune/pruner.py (Pruner, MagnitudePruner,
RatioPruner).  The reference builds mask subgraphs with layers; the same
graph-building API is kept here, plus `apply`, which masks the scope
weights in place — the actual sparsification step the reference leaves to
its Compressor driver.
"""
import numpy as np

__all__ = ['Pruner', 'MagnitudePruner', 'RatioPruner', 'SensitivePruner']


class Pruner(object):
    """Base class: `prune(param)` returns a zeros-mask Variable
    (graph mode) and `mask_numpy(w)` the equivalent numpy mask."""

    def prune(self, param, **kw):
        raise NotImplementedError

    def mask_numpy(self, w, **kw):
        raise NotImplementedError

    def apply(self, program, scope=None, params=None):
        """Zero masked weights in the scope, in place.  Returns
        {param name: sparsity} for the pruned params."""
        from ...core.executor import global_scope
        from ...core.framework import Parameter
        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        chosen = params
        out = {}
        for name, v in block.vars.items():
            if not isinstance(v, Parameter) or name not in scope:
                continue
            if chosen is not None and name not in chosen:
                continue
            w = np.asarray(scope.vars[name])
            mask = self.mask_numpy(w, name=name)
            pruned = np.where(mask, 0.0, w).astype(w.dtype)
            scope.vars[name] = scope.vars[name] * 0 + pruned
            out[name] = float(mask.mean())
        return out


class MagnitudePruner(Pruner):
    """Zero weights with |w| below a fixed threshold
    (ref slim/prune/pruner.py MagnitudePruner)."""

    def __init__(self, threshold):
        self.threshold = float(threshold)

    def prune(self, param, threshold=None):
        from ... import layers
        th = threshold
        if th is None:
            th = layers.fill_constant([1], 'float32', self.threshold)
        return layers.less_than(layers.abs(param), th)

    def mask_numpy(self, w, name=None, threshold=None):
        return np.abs(w) < (self.threshold if threshold is None
                            else threshold)


class RatioPruner(Pruner):
    """Keep the top `ratio` fraction of weights by magnitude, zero the
    rest (ref RatioPruner; `ratios` maps param name -> keep ratio, '*'
    is the default)."""

    def __init__(self, ratios=None):
        self.ratios = ratios or {}

    def _ratio_for(self, name):
        if name in self.ratios:
            return float(self.ratios[name])
        return float(self.ratios.get('*', 1.0))

    def prune(self, param, ratio=None):
        from ... import layers
        rat = ratio if ratio is not None else self._ratio_for(param.name)
        if rat >= 1.0:
            zeros = layers.fill_constant([1], 'float32', 0.0)
            return layers.less_than(layers.abs(param), zeros)
        k = max(int(rat * int(np.prod(param.shape))), 1)
        flat = layers.reshape(layers.abs(param), [1, -1])
        topk, _ = layers.topk(flat, k=k)
        th = layers.slice(topk, axes=[1], starts=[k - 1], ends=[k])
        th = layers.reshape(th, [1])
        return layers.less_than(layers.abs(param), th)

    def mask_numpy(self, w, name=None, ratio=None):
        rat = ratio if ratio is not None else self._ratio_for(name or '')
        if rat >= 1.0:
            return np.zeros_like(w, dtype=bool)
        k = max(int(rat * w.size), 1)
        th = np.sort(np.abs(w).ravel())[::-1][k - 1]
        return np.abs(w) < th


class SensitivePruner(Pruner):
    """Prune each param to the largest ratio whose loss delta stays under
    `tolerance` (a compact stand-in for the reference Compressor's
    sensitivity analysis in slim/core)."""

    def __init__(self, eval_fn, candidate_ratios=(0.9, 0.7, 0.5, 0.3),
                 tolerance=0.05):
        self.eval_fn = eval_fn
        self.candidates = sorted(candidate_ratios, reverse=True)
        self.tolerance = float(tolerance)
        self.chosen = {}

    def mask_numpy(self, w, name=None, ratio=None):
        rat = self.chosen.get(name, 1.0) if ratio is None else ratio
        return RatioPruner({'*': rat}).mask_numpy(w)

    def search(self, program, scope, params):
        """Pick per-param keep ratios by trial pruning + eval_fn()."""
        base = float(self.eval_fn())
        for name in params:
            orig = np.asarray(scope.vars[name]).copy()
            best = 1.0
            for rat in self.candidates:
                mask = RatioPruner({'*': rat}).mask_numpy(orig)
                scope.vars[name] = scope.vars[name] * 0 + np.where(
                    mask, 0.0, orig).astype(orig.dtype)
                score = float(self.eval_fn())
                if score <= base + self.tolerance:
                    best = rat
                else:
                    break
            scope.vars[name] = scope.vars[name] * 0 + orig
            self.chosen[name] = best
        return dict(self.chosen)
