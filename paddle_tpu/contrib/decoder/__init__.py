from . import beam_search_decoder  # noqa
from .beam_search_decoder import (InitState, StateCell,  # noqa
                                  TrainingDecoder, BeamSearchDecoder)

__all__ = beam_search_decoder.__all__
