"""High-level seq2seq decoder API: StateCell / TrainingDecoder /
BeamSearchDecoder.

Parity: reference contrib/decoder/beam_search_decoder.py:43 (InitState),
:159 (StateCell), :384 (TrainingDecoder), :523 (BeamSearchDecoder).  One
StateCell describes the per-step recurrence; TrainingDecoder runs it over
the gold sequence (teacher forcing), BeamSearchDecoder runs it
autoregressively with beam tracking.

TPU-native lowering: the reference drives decoding with a While op over
LoD tensor arrays whose beam width shrinks as hypotheses finish.  Here
the beam width is STATIC — every source keeps beam_size rows, finished
rows re-select end_id (the dense beam_search op, ops/sequence.py:386) —
and the decode loop is unrolled at build time over max_len steps, so XLA
sees a straight-line graph with shared weights.  TrainingDecoder lowers
through DynamicRNN's single lax.scan.
"""
import contextlib

from ...core.framework import Variable
from ...core.layer_helper import LayerHelper
from ... import layers

__all__ = ['InitState', 'StateCell', 'TrainingDecoder',
           'BeamSearchDecoder']


class _DecoderType(object):
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial value of a StateCell state (ref :43): either an explicit
    `init` Variable or (shape, value) zeros-like boot."""

    def __init__(self, init=None, shape=None, value=0.0,
                 init_boot=None, need_reorder=False, dtype='float32'):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError('InitState needs init= or init_boot= '
                             '(batch reference for the boot fill)')
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=[-1] + list(shape),
                dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell(object):
    """Carrier of decoder inputs/states + the user's updater function
    (ref :159).  The same cell (and weights) serves both decoders."""

    def __init__(self, inputs, states, out_state, name=None):
        self.helper = LayerHelper('state_cell', name=name)
        self._inputs = dict(inputs)          # name -> placeholder/None
        self._init_states = dict(states)     # name -> InitState
        self._state_names = list(states)
        self._out_state = out_state
        self._cur_states = {}
        self._cur_inputs = {}
        self._updater = None
        self._decoder = None

    # -- decoder handshake
    def _enter_decoder(self, decoder):
        if self._decoder is not None:
            raise ValueError('StateCell is already inside a decoder')
        self._decoder = decoder

    def _leave_decoder(self, decoder):
        if self._decoder is not decoder:
            raise ValueError('StateCell is not inside this decoder')
        self._decoder = None

    # -- user API
    def get_state(self, name):
        if name not in self._cur_states:
            raise ValueError('unknown state %r (have %s)'
                             % (name, self._state_names))
        return self._cur_states[name]

    def get_input(self, name):
        if name not in self._cur_inputs:
            raise ValueError('input %r was not fed to compute_state'
                             % name)
        return self._cur_inputs[name]

    def set_state(self, name, value):
        self._cur_states[name] = value

    def state_updater(self, updater):
        self._updater = updater
        return updater

    def compute_state(self, inputs):
        """Run the updater once with `inputs` (dict name -> Variable)."""
        if self._updater is None:
            raise ValueError('no @state_cell.state_updater registered')
        self._cur_inputs = dict(inputs)
        self._updater(self)

    def update_states(self):
        """Commit the updated states to the enclosing decoder (training:
        DynamicRNN memories; beam search: beam-reordered carries)."""
        if self._decoder is None:
            raise ValueError('update_states outside a decoder block')
        self._decoder._commit_states(self)

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder(object):
    """Teacher-forced decoder over the gold target sequence (ref :384);
    lowers through DynamicRNN (one lax.scan)."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper('training_decoder', name=name)
        self._rnn = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._mems = {}

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._rnn

    @property
    def type(self):
        return self._type

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError('decoder.block() can only be entered once')
        self._status = TrainingDecoder.IN_DECODER
        with self._rnn.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    def step_input(self, x):
        self._assert_in_block('step_input')
        ipt = self._rnn.step_input(x)
        if not self._mems:
            # first step_input fixes the batch: bind state memories now
            for name in self._state_cell._state_names:
                init = self._state_cell._init_states[name]
                mem = self._rnn.memory(init=init.value)
                self._mems[name] = mem
                self._state_cell._cur_states[name] = mem
        return ipt

    def static_input(self, x):
        self._assert_in_block('static_input')
        return self._rnn.static_input(x)

    def output(self, *outputs):
        self._assert_in_block('output')
        self._rnn.output(*outputs)

    def _commit_states(self, cell):
        for name, mem in self._mems.items():
            cell_cur = cell._cur_states[name]
            if cell_cur is not mem:
                self._rnn.update_memory(mem, cell_cur)

    def __call__(self, *a, **kw):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError('decoder outputs are available after the '
                             'block closes')
        return self._rnn(*a, **kw)

    def _assert_in_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError('%s must be called inside decoder.block()'
                             % method)


def _expand_to_beam(x, beam):
    """[B, ...] -> [B*beam, ...], each source row repeated beam times
    (the dense analog of the reference's sequence_expand by scores)."""
    if beam == 1:
        return x
    shape = list(x.shape)
    ex = layers.unsqueeze(x, axes=[1])
    ex = layers.expand(ex, [1, beam] + [1] * (len(shape) - 1))
    return layers.reshape(ex, [-1] + shape[1:])


class BeamSearchDecoder(object):
    """Autoregressive beam-search decoder (ref :523).

    decode() unrolls max_len steps at build time: embed the previous
    ids, run the StateCell on all B*beam rows, project to the
    vocabulary, take topk, and run the dense beam_search op; states are
    re-gathered by each step's parent indices.  __call__ returns the
    backtraced (translation_ids, translation_scores), each
    [B*beam, max_len]."""

    def __init__(self, state_cell, init_ids, init_scores,
                 target_dict_dim, word_dim, input_var_dict=None,
                 topk_size=50, sparse_emb=True, max_len=100, beam_size=1,
                 end_id=1, name=None, param_attr=None, bias_attr=None,
                 emb_param_attr=None):
        self._helper = LayerHelper('beam_search_decoder', name=name)
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = input_var_dict or {}
        self._topk_size = min(topk_size, target_dict_dim)
        self._sparse_emb = sparse_emb
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        # param_attr/bias_attr/emb_param_attr: optional NAMED attrs so the
        # decode-time projection/embedding reuse the trained weights (the
        # reference relies on unique_name alignment across separately
        # built programs; explicit names are the robust equivalent)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._emb_param_attr = emb_param_attr
        self._done = False
        self._result = None

    @property
    def state_cell(self):
        return self._state_cell

    def _commit_states(self, cell):
        pass  # decode() re-gathers states by parent index explicitly

    def decode(self):
        cell = self._state_cell
        beam = self._beam_size
        prev_ids = _expand_to_beam(self._init_ids, beam)      # [R, 1]
        # only beam 0 starts live: [init_score, -1e9, ...] per source
        if beam > 1:
            dead = layers.fill_constant_batch_size_like(
                self._init_scores, [-1, beam - 1], 'float32', -1e9)
            sc = layers.concat([self._init_scores, dead], axis=1)
            prev_scores = layers.reshape(sc, [-1, 1])
        else:
            prev_scores = self._init_scores
        for name in cell._state_names:
            cell._cur_states[name] = _expand_to_beam(
                cell._init_states[name].value, beam)
        static_feeds = {k: _expand_to_beam(v, beam)
                        for k, v in self._input_var_dict.items()}

        # every unrolled step must SHARE its weights: pin the param names
        from ...param_attr import ParamAttr
        emb_attr = self._emb_param_attr or ParamAttr(
            name=self._helper.name + '_emb')
        fc_w = self._param_attr or ParamAttr(
            name=self._helper.name + '_fc.w')
        fc_b = self._bias_attr or ParamAttr(
            name=self._helper.name + '_fc.b')

        step_ids, step_scores, step_parents = [], [], []
        for _ in range(self._max_len):
            emb = layers.embedding(
                prev_ids, size=[self._target_dict_dim, self._word_dim],
                dtype='float32', is_sparse=self._sparse_emb,
                param_attr=emb_attr)
            feed = dict(static_feeds)
            for input_name in cell._inputs:
                if input_name not in feed:
                    feed[input_name] = emb
            cell.compute_state(inputs=feed)
            out = cell.out_state()                           # [R, H]
            scores = layers.fc(out, self._target_dict_dim, act='softmax',
                               param_attr=fc_w, bias_attr=fc_b)
            topk_scores, topk_idx = layers.topk(scores, self._topk_size)
            acc = layers.log(topk_scores) + prev_scores      # [R, K]
            sel_ids, sel_scores, parent = layers.beam_search(
                prev_ids, prev_scores, topk_idx, acc, beam,
                end_id=self._end_id, return_parent_idx=True)
            for name in cell._state_names:
                cell._cur_states[name] = layers.gather(
                    cell._cur_states[name], parent)
            step_ids.append(sel_ids)
            step_scores.append(sel_scores)
            step_parents.append(parent)
            prev_ids, prev_scores = sel_ids, sel_scores

        ids_arr = layers.create_array('int64')
        ids_arr.vars = step_ids
        sc_arr = layers.create_array('float32')
        sc_arr.vars = step_scores
        pa_arr = layers.create_array('int32')
        pa_arr.vars = step_parents
        self._result = layers.beam_search_decode(
            ids_arr, sc_arr, beam_size=beam, end_id=self._end_id,
            parents=pa_arr)
        self._done = True
        self._state_cell._leave_decoder(self)
        return self._result

    def __call__(self):
        if not self._done:
            raise ValueError('call decode() before reading the results')
        return self._result
