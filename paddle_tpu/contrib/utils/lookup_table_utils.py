"""Distributed-lookup-table checkpoint helpers.

Parity: reference contrib/utils/lookup_table_utils.py
(load_persistables_for_increment / load_persistables_for_inference /
convert_dist_to_sparse_program), which rebuild pserver-sharded embedding
tables from per-node checkpoint dirs.  The pserver architecture is
obsolete here (SURVEY §2.4): large embeddings are mesh-sharded jax
arrays (parallel/sharded_embedding.py) and checkpoints are whole-table
(train/checkpoint.py), so these entry points load the plain persistables
and, where the reference would re-shard, simply validate shapes."""
import os

from ... import io as io_mod
from ...core.executor import global_scope

__all__ = ['load_persistables_for_increment',
           'load_persistables_for_inference',
           'convert_dist_to_sparse_program']


def _load(executor, dirname, program):
    if not os.path.isdir(dirname):
        raise ValueError('checkpoint dir %s does not exist' % dirname)
    io_mod.load_persistables(executor, dirname, main_program=program)


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var=None,
                                    lookup_table_var_path=None):
    """Resume training from `dirname`.  The reference additionally
    re-loads the pserver-sharded lookup table from its own path; tables
    here are ordinary (possibly mesh-sharded) persistables inside the
    same checkpoint."""
    _load(executor, dirname, program)
    return program


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name=None):
    """Load inference persistables; validates the lookup table exists
    when a name is given."""
    _load(executor, dirname, program)
    if lookup_table_var_name is not None:
        scope = global_scope()
        if lookup_table_var_name not in scope:
            raise ValueError('lookup table %r not found in the loaded '
                             'checkpoint' % lookup_table_var_name)
    return program


def convert_dist_to_sparse_program(program):
    """The reference rewrites dense lookup_table ops to the distributed
    sparse form for pserver serving.  There is no pserver runtime here —
    embeddings stay dense/mesh-sharded — so the program is returned
    unchanged (documented no-op, same call sites keep working)."""
    return program
