from . import hdfs_utils  # noqa
from .hdfs_utils import HDFSClient, multi_download, multi_upload  # noqa
from . import lookup_table_utils  # noqa
from .lookup_table_utils import (  # noqa
    load_persistables_for_increment, load_persistables_for_inference,
    convert_dist_to_sparse_program)

__all__ = ['HDFSClient', 'multi_download', 'multi_upload',
           'load_persistables_for_increment',
           'load_persistables_for_inference',
           'convert_dist_to_sparse_program']
