"""HDFS shell helpers.

Parity: reference contrib/utils/hdfs_utils.py (HDFSClient + parallel
up/download), which shells out to the ``hadoop fs`` CLI.  The same
subprocess protocol is kept; on hosts without a hadoop client every
operation raises a clear EnvironmentError instead of a cryptic exec
failure (TPU pods typically mount GCS/NFS instead of HDFS — point
`hadoop_home` at a client install to use these)."""
import os
import subprocess

__all__ = ['HDFSClient', 'multi_download', 'multi_upload']


class HDFSClient(object):
    def __init__(self, hadoop_home, configs=None):
        self.hadoop_home = hadoop_home
        self.configs = configs or {}
        self._bin = os.path.join(hadoop_home, 'bin', 'hadoop')

    def _cmd(self, *args):
        if not os.path.exists(self._bin):
            raise EnvironmentError(
                'no hadoop client at %s — HDFSClient shells out to the '
                '`hadoop fs` CLI exactly like the reference; install one '
                'or stage data on GCS/NFS instead' % self._bin)
        cmd = [self._bin, 'fs']
        for k, v in self.configs.items():
            cmd += ['-D', '%s=%s' % (k, v)]
        cmd += list(args)
        r = subprocess.run(cmd, capture_output=True, text=True)
        return r.returncode, r.stdout, r.stderr

    def is_exist(self, hdfs_path):
        rc, _, _ = self._cmd('-test', '-e', hdfs_path)
        return rc == 0

    def is_dir(self, hdfs_path):
        rc, _, _ = self._cmd('-test', '-d', hdfs_path)
        return rc == 0

    def delete(self, hdfs_path):
        rc, _, err = self._cmd('-rm', '-r', '-skipTrash', hdfs_path)
        return rc == 0

    def rename(self, src, dst, overwrite=False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        rc, _, _ = self._cmd('-mv', src, dst)
        return rc == 0

    def makedirs(self, hdfs_path):
        rc, _, _ = self._cmd('-mkdir', '-p', hdfs_path)
        return rc == 0

    def ls(self, hdfs_path):
        rc, out, _ = self._cmd('-ls', hdfs_path)
        if rc != 0:
            return []
        return [line.split()[-1] for line in out.splitlines()
                if line and not line.startswith('Found')]

    def lsr(self, hdfs_path):
        rc, out, _ = self._cmd('-lsr', hdfs_path)
        if rc != 0:
            return []
        return [line.split()[-1] for line in out.splitlines() if line]

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        if overwrite and self.is_exist(hdfs_path):
            self.delete(hdfs_path)
        rc, _, _ = self._cmd('-put', local_path, hdfs_path)
        return rc == 0

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False):
        if overwrite and os.path.exists(local_path):
            os.remove(local_path)
        rc, _, _ = self._cmd('-get', hdfs_path, local_path)
        if rc == 0 and unzip and local_path.endswith('.gz'):
            subprocess.run(['gunzip', '-f', local_path])
        return rc == 0


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """Each trainer downloads its 1/trainers shard of the listing
    (reference semantics; sequential — host IO overlaps the device step
    anyway)."""
    entries = client.ls(hdfs_path)
    mine = [e for i, e in enumerate(sorted(entries))
            if i % trainers == trainer_id]
    got = []
    for e in mine:
        dst = os.path.join(local_path, os.path.basename(e))
        if client.download(e, dst):
            got.append(dst)
    return got


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False):
    ups = []
    for root, _, files in os.walk(local_path):
        for f in files:
            src = os.path.join(root, f)
            rel = os.path.relpath(src, local_path)
            dst = '%s/%s' % (hdfs_path.rstrip('/'), rel)
            if client.upload(dst, src, overwrite=overwrite):
                ups.append(dst)
    return ups
