"""Op frequency statistics (parity: reference contrib/op_frequence.py)."""
from collections import Counter, OrderedDict

__all__ = ['op_freq_statistic']


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_op_freq): single-op counts and adjacent
    op-pair counts over the whole program."""
    uni = Counter()
    adj = Counter()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] += 1
            if prev is not None:
                adj['%s->%s' % (prev, op.type)] += 1
            prev = op.type
    uni_sorted = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj_sorted = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni_sorted, adj_sorted
