"""Estimate a program's per-batch activation memory (parity: reference
contrib/memory_usage_calc.py memory_usage)."""
import numpy as np

from ..core.dtypes import convert_dtype

__all__ = ['memory_usage']

_GB = 1 << 30


def memory_usage(program, batch_size):
    """Rough lower bound: sum of var sizes with the batch dim filled in.
    XLA's actual peak is usually lower (buffer reuse, fusion) — this
    mirrors the reference's estimate semantics for capacity planning."""
    if batch_size <= 0:
        raise ValueError('batch_size must be positive')
    total = 0
    for var in program.list_vars():
        if var.shape is None:
            continue
        n = 1
        for d in var.shape:
            n *= batch_size if d in (-1, None) else int(d)
        total += n * np.dtype(convert_dtype(var.dtype)).itemsize
    return total / _GB, 'GB'
