"""paddle_tpu.contrib — high-level Trainer/Inferencer + utilities.

Parity: reference python/paddle/fluid/contrib/ (trainer.py, inferencer.py,
memory_usage_calc.py, op_frequence.py).
"""
from . import trainer
from .trainer import (Trainer, BeginEpochEvent, EndEpochEvent,  # noqa
                      BeginStepEvent, EndStepEvent, CheckpointConfig)
from . import inferencer
from .inferencer import Inferencer  # noqa
from .memory_usage_calc import memory_usage  # noqa
from .op_frequence import op_freq_statistic  # noqa
from . import quantize  # noqa
from .quantize import QuantizeTranspiler  # noqa
from . import calibration  # noqa
from .calibration import Calibrator  # noqa
from . import slim  # noqa
from . import decoder  # noqa
from .decoder import (InitState, StateCell, TrainingDecoder,  # noqa
                      BeamSearchDecoder)
from . import reader  # noqa
from . import utils  # noqa

__all__ = []
__all__ += trainer.__all__
__all__ += inferencer.__all__
__all__ += ['memory_usage', 'op_freq_statistic', 'QuantizeTranspiler',
            'Calibrator', 'slim']
