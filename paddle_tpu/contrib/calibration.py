"""Post-training int8 calibration.

Parity: reference contrib/int8_inference/utility.py `Calibrator` (KL
calibration after the TensorRT 8-bit recipe, gtc 2017 s7310).  The
reference walks conv ops and mutates MKLDNN attrs; here calibration is
backend-neutral program surgery: sample the inputs of quantizable ops over
calibration batches, pick per-tensor scales (KL-divergence search or
abs-max), then insert `quantize_dequantize_fixed_scale` ops so the
deployed program simulates int8 numerics on the MXU, and pack weights to
int8 scope arrays via QuantizeTranspiler.convert_to_int8.
"""
import numpy as np

from ..core.framework import Operator, Parameter

__all__ = ['Calibrator', 'kl_scale']

_QUANTIZABLE = {'mul', 'matmul', 'conv2d', 'conv2d_transpose'}


def kl_scale(samples, bins=2048, dst_bins=255):
    """Optimal symmetric quantization threshold by KL-divergence search
    (vectorized re-derivation of the TensorRT recipe the reference
    implements with Python loops at int8_inference/utility.py:599).

    samples: list of np arrays (calibration activations for ONE tensor).
    Returns the scale (clip threshold): values beyond it saturate.
    """
    x = np.abs(np.concatenate([np.asarray(s).ravel() for s in samples]))
    amax = float(x.max()) if x.size else 0.0
    if amax <= 0:
        return 1e-8
    # robust histogram range: far outliers must not stretch the binning
    # (everything beyond the range saturates into the edge bin below)
    amax = min(amax, 4.0 * float(np.percentile(x, 99.0)) + 1e-12)
    hist, edges = np.histogram(np.minimum(x, amax), bins=bins,
                               range=(0.0, amax))
    hist = hist.astype(np.float64)
    bin_width = edges[1] - edges[0]
    total = hist.sum()
    best_i, best_kl = bins, np.inf
    nonzero = np.nonzero(hist)[0]
    # candidate thresholds keep >=70% of the observed range (the
    # reference's starting_iter guard at utility.py:609 — KL alone
    # over-clips peaked distributions), stepped for speed
    start = max(dst_bins, int(bins * 0.7))
    for i in range(start, bins + 1, 8):
        p = hist[:i].copy()
        # outliers saturate into the last NONZERO bin <= i-1 (the
        # reference skips empty-edge candidates outright, which strands
        # sparse histograms between the body and a far outlier)
        edge_cands = nonzero[nonzero < i]
        if edge_cands.size == 0:
            continue
        p[edge_cands[-1]] += hist[i:].sum()
        # quantize i bins down to dst_bins, then expand back (uniform
        # within each merged group over the nonzero source bins)
        idx = (np.arange(i) * dst_bins // i)
        q_merged = np.bincount(idx, weights=hist[:i], minlength=dst_bins)
        nz = (hist[:i] > 0).astype(np.float64)
        nz_count = np.bincount(idx, weights=nz, minlength=dst_bins)
        q = np.where(nz_count[idx] > 0,
                     q_merged[idx] / np.maximum(nz_count[idx], 1), 0.0)
        q = np.where(hist[:i] > 0, q, 0.0)
        mask = p > 0
        qm = np.where(q > 0, q, 1e-30)
        kl = float(np.sum(p[mask] * (np.log(p[mask] / total) -
                                     np.log(qm[mask] / max(q.sum(),
                                                           1e-30)))))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return (best_i - 0.5) * bin_width


class Calibrator(object):
    """Collect activation statistics on calibration batches and emit an
    int8-simulating inference program.

    Usage::

        calib = Calibrator(program, scope=scope, algo='KL')
        for batch in calibration_data:
            calib.sample(exe, feed=batch)      # runs + records
        int8_prog = calib.freeze()             # calibrated program
        packed = calib.save_int8_weights()     # int8 weight artifact
    """

    def __init__(self, program, scope=None, algo='KL', activation_bits=8,
                 weight_bits=8):
        from ..core.executor import global_scope
        if algo not in ('KL', 'abs_max'):
            raise ValueError('algo must be KL or abs_max, got %r' % algo)
        self.program = program
        self.scope = scope if scope is not None else global_scope()
        self.algo = algo
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self._samples = {}            # var name -> [np arrays]
        self._targets = self._find_activation_inputs()

    def _find_activation_inputs(self):
        """Non-parameter float inputs of quantizable ops."""
        names = []
        block = self.program.global_block()
        for op in block.ops:
            if op.type not in _QUANTIZABLE:
                continue
            for slot_names in op.inputs.values():
                for n in slot_names:
                    v = block._find_var_recursive(n)
                    if v is None or isinstance(v, Parameter):
                        continue
                    if v.dtype in ('float32', 'bfloat16') and \
                            n not in names:
                        names.append(n)
        return names

    def sample(self, exe, feed):
        """Run one calibration batch, recording target activations."""
        vals = exe.run(self.program, feed=feed, fetch_list=self._targets)
        for n, v in zip(self._targets, vals):
            self._samples.setdefault(n, []).append(np.asarray(v))
        return vals

    def scales(self):
        """Per-tensor calibrated scales {var name: scale}."""
        out = {}
        for n, samples in self._samples.items():
            if self.algo == 'KL':
                out[n] = kl_scale(samples)
            else:
                out[n] = max(float(np.abs(s).max()) for s in samples)
        return out

    def freeze(self, program=None):
        """Return a clone of the program with fixed-scale quant/dequant
        ops at each calibrated activation (weights left fp32 in-graph;
        use save_int8_weights for the deploy artifact)."""
        program = program or self.program.clone(for_test=True)
        scales = self.scales()
        for block in program.blocks:
            new_ops = []
            rewired = {}
            for op in block.ops:
                for slot, names in list(op.inputs.items()):
                    op.inputs[slot] = [rewired.get(n, n) for n in names]
                if op.type in _QUANTIZABLE:
                    for slot, names in list(op.inputs.items()):
                        qnames = []
                        for n in names:
                            if n in scales and n not in rewired:
                                qn = n + '.int8calib'
                                block.create_var(
                                    name=qn,
                                    shape=block._find_var_recursive(
                                        n).shape,
                                    dtype='float32')
                                qop = Operator(
                                    block,
                                    'quantize_dequantize_fixed_scale',
                                    inputs={'X': n}, outputs={'Out': qn},
                                    attrs={'scale': float(scales[n]),
                                           'bit_length':
                                               self.activation_bits})
                                new_ops.append(qop)
                                rewired[n] = qn
                            qnames.append(rewired.get(n, n))
                        op.inputs[slot] = qnames
                new_ops.append(op)
            block.ops = new_ops
        program._bump()
        return program

    def save_int8_weights(self):
        """Pack quantizable weights to (int8 array, scale) pairs."""
        from .quantize import QuantizeTranspiler
        t = QuantizeTranspiler(weight_bits=self.weight_bits)
        return t.convert_to_int8(self.program, scope=self.scope)

    def apply_int8(self, program=None):
        """Emit a TRUE-int8 inference program: calibrated mul/conv2d ops
        become mul_int8/conv2d_int8 (int8×int8→int32 on the MXU;
        measured 1.24× over bf16 on v5e plus the 4× weight-memory cut —
        see ops/int8.py), reading int8-packed weights stored in the
        scope under `<param>.int8`.  The reference analog is the MKLDNN
        int8 kernel swap its calibrator performs."""
        import jax.numpy as jnp
        if self.weight_bits != 8:
            raise ValueError(
                'apply_int8 needs weight_bits=8: the int8 kernels assume '
                'the 127-range packing convention (got %d bits)'
                % self.weight_bits)
        program = program or self.program.clone(for_test=True)
        scales = self.scales()
        packed = self.save_int8_weights()
        for block in program.blocks:
            for op in block.ops:
                # matmul is excluded (transpose_x/y attrs don't map onto
                # the flattened-GEMM kernel), as is mul with a flattened
                # weight (y_num_col_dims != 1)
                if op.type not in ('mul', 'conv2d'):
                    continue
                if op.type == 'mul' and \
                        op.attrs.get('y_num_col_dims', 1) != 1:
                    continue
                w_slot = 'Filter' if op.type == 'conv2d' else 'Y'
                x_slot = 'Input' if op.type == 'conv2d' else 'X'
                wname = op.inputs.get(w_slot, [None])[0]
                xname = op.inputs.get(x_slot, [None])[0]
                if wname not in packed or xname not in scales:
                    continue
                q, wscale = packed[wname]
                int8_name = wname + '.int8'
                # the block var must exist in EVERY emitted program (the
                # executor pulls persistables from block.vars); only the
                # scope write is once-per-scope
                block.create_var(name=int8_name, shape=q.shape,
                                 dtype='int8', persistable=True)
                if int8_name not in self.scope:
                    self.scope.vars[int8_name] = jnp.asarray(q)
                op.inputs[w_slot] = [int8_name]
                op.type = op.type + '_int8'
                op.attrs = dict(op.attrs)
                op.attrs['x_scale'] = float(scales[xname])
                op.attrs['w_scale'] = float(wscale)
        program._bump()
        return program
