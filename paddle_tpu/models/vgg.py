"""VGG-16 (parity: reference benchmark/fluid/models/vgg.py)."""
import paddle_tpu as fluid


def vgg16_bn_drop(input, is_train=True):
    def conv_block(inp, num_filter, groups, dropouts):
        return fluid.nets.img_conv_group(
            input=inp, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act='relu', conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type='max')

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = fluid.layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = fluid.layers.fc(input=drop, size=512, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act='relu',
                                 is_test=not is_train)
    drop2 = fluid.layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop2, size=512, act=None)
    return fc2


def build(data_shape=(3, 32, 32), class_dim=10, lr=1e-3, is_train=True):
    images = fluid.layers.data(name='data', shape=list(data_shape),
                               dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    net = vgg16_bn_drop(images, is_train)
    predict = fluid.layers.fc(input=net, size=class_dim, act='softmax')
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    opt = None
    if is_train:
        opt = fluid.optimizer.Adam(learning_rate=lr)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'accuracy': batch_acc,
            'feeds': [images, label], 'predict': predict, 'optimizer': opt}
