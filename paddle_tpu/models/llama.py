"""LLaMA-family LLM built on the fluid layers API.

BASELINE stretch target (SURVEY §2.6): a modern decoder-only LLM expressed
in the same declarative Program/layers API as the fluid-era models, showing
the framework carries current model families, not just 2019-era ones.
Architecture: RMSNorm pre-norm, rotary position embeddings, grouped-query
attention, SwiGLU FFN, no biases — LLaMA-3 layout.

TPU-first mapping:
  * attention runs `layers.ring_attention`: flash-attention pallas kernel on
    one chip, exact ppermute ring over the mesh's 'seq' axis for
    long-context (the SAME program serves both — the op picks its strategy
    from the executor mesh at lowering time)
  * parameter names follow parallel/tp.py's Megatron layout rules, so
    `shard_program_tp(main)` gives column/row-parallel attention + FFN and
    a vocab-sharded embedding over the 'model' axis
  * the whole train step (fwd + vjp bwd + Adam) lowers to ONE XLA
    executable; bf16 via build(dtype='bfloat16') keeps matmuls on the MXU
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.initializer import Normal
from paddle_tpu.param_attr import ParamAttr

# rough LLaMA-3-8B shape plus scaled-down variants for bench/tests
CONFIGS = {
    'llama3_8b': dict(vocab=128256, d_model=4096, n_layer=32, n_head=32,
                      n_kv_head=8, d_ffn=14336, theta=500000.0,
                      max_len=8192),
    'llama_1b': dict(vocab=32000, d_model=2048, n_layer=16, n_head=16,
                     n_kv_head=8, d_ffn=5504, theta=500000.0, max_len=2048),
    'tiny': dict(vocab=256, d_model=64, n_layer=2, n_head=4, n_kv_head=2,
                 d_ffn=128, theta=10000.0, max_len=32),
}


def _linear(x, size, name):
    # all llama projections are bias-free; names end in _w so the tp.py
    # Megatron rules shard them (q/k/v/fc1/fc3 column, o/fc2 row)
    return layers.fc(x, size, num_flatten_dims=2,
                     param_attr=ParamAttr(name=name + '_w'), bias_attr=False)


def _split_heads(x, n_head, max_len, d_head):
    x = layers.reshape(x, [0, max_len, n_head, d_head])
    return layers.transpose(x, perm=[0, 2, 1, 3])        # [B, H, T, Dh]


def attention(x, cfg, name, use_ring=False):
    d_model, H = cfg['d_model'], cfg['n_head']
    Hkv, T = cfg['n_kv_head'], cfg['max_len']
    d_head = d_model // H
    q = _linear(x, H * d_head, name + '_q')
    k = _linear(x, Hkv * d_head, name + '_k')
    v = _linear(x, Hkv * d_head, name + '_v')
    q = _split_heads(q, H, T, d_head)
    k = _split_heads(k, Hkv, T, d_head)
    v = _split_heads(v, Hkv, T, d_head)
    q = layers.rope(q, theta=cfg['theta'])
    k = layers.rope(k, theta=cfg['theta'])
    # K/V stay at Hkv width: both attention paths serve GQA natively, so
    # HBM and ring-hop ICI traffic keep the grouped-head savings
    if use_ring:
        ctxv = layers.ring_attention(q, k, v, causal=True)
    else:
        ctxv = layers.flash_attention(q, k, v, causal=True)
    ctxv = layers.transpose(ctxv, perm=[0, 2, 1, 3])
    ctxv = layers.reshape(ctxv, [0, T, d_model])
    return _linear(ctxv, d_model, name + '_o')


def swiglu_ffn(x, cfg, name):
    gate = _linear(x, cfg['d_ffn'], name + '_fc1')      # column-parallel
    up = _linear(x, cfg['d_ffn'], name + '_fc3')        # column-parallel
    h = layers.elementwise_mul(layers.swish(gate, beta=1.0), up)
    return _linear(h, cfg['d_model'], name + '_fc2')    # row-parallel


def decoder_layer(x, cfg, name, use_ring=False):
    h = layers.rms_norm(x, param_attr=ParamAttr(name=name + '_att_norm'))
    x = layers.elementwise_add(x, attention(h, cfg, name + '_att',
                                            use_ring))
    h = layers.rms_norm(x, param_attr=ParamAttr(name=name + '_ffn_norm'))
    return layers.elementwise_add(x, swiglu_ffn(h, cfg, name + '_ffn'))


def llama(config='tiny', use_ring=False, dtype='float32', **overrides):
    """Build the forward + loss.  Feeds: tokens [B, T, 1] int64 (inputs),
    labels [B, T, 1] int64 (shifted targets), loss_mask [B, T] float32."""
    cfg = dict(CONFIGS[config] if isinstance(config, str) else config)
    cfg.update(overrides)
    T, V, D = cfg['max_len'], cfg['vocab'], cfg['d_model']

    tokens = layers.data('tokens', shape=[T, 1], dtype='int64')
    labels = layers.data('labels', shape=[T, 1], dtype='int64')
    loss_mask = layers.data('loss_mask', shape=[T], dtype='float32')

    x = layers.embedding(
        tokens, size=[V, D],
        param_attr=ParamAttr(name='tok_emb',
                             initializer=Normal(0., 0.02)),
        dtype=dtype)
    for i in range(cfg['n_layer']):
        x = decoder_layer(x, cfg, 'layer_%d' % i, use_ring)
    x = layers.rms_norm(x, param_attr=ParamAttr(name='final_norm'))
    logits = _linear(x, V, 'lm_proj')                    # [B, T, V]
    if dtype != 'float32':
        logits = layers.cast(logits, 'float32')

    per_tok = layers.softmax_with_cross_entropy(logits, labels)  # [B,T,1]
    per_tok = layers.elementwise_mul(
        layers.squeeze(per_tok, axes=[2]), loss_mask)
    sum_cost = layers.reduce_sum(per_tok)
    token_num = layers.reduce_sum(loss_mask)
    loss = layers.elementwise_div(sum_cost, token_num)
    return {'loss': loss, 'logits': logits, 'sum_cost': sum_cost,
            'token_num': token_num,
            'feeds': [tokens, labels, loss_mask], 'config': cfg}


def build(config='tiny', use_ring=False, dtype='float32', lr=3e-4,
          grad_clip=1.0, is_train=True, **overrides):
    out = llama(config, use_ring, dtype, **overrides)
    opt = None
    if is_train:
        if grad_clip:
            fluid.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(grad_clip))
        opt = fluid.optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.95,
                                   epsilon=1e-8)
        opt.minimize(out['loss'])
    out['optimizer'] = opt
    return out


def shard(main_program):
    """Apply Megatron TP layout + extra rules for the SwiGLU third matrix
    and the llama norms (replicated)."""
    import re
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.tp import shard_program_tp
    extra = [
        (re.compile(r'.*_fc3_w$'), lambda nd: P(None, 'model')),
        (re.compile(r'.*tok_emb$'), lambda nd: P('model', None)),
    ]
    return shard_program_tp(main_program, extra_rules=extra)


def make_batch(token_rows, max_len):
    """Pack next-token-prediction batches from rows of token ids."""
    B = len(token_rows)
    toks = np.zeros((B, max_len, 1), 'int64')
    lbls = np.zeros((B, max_len, 1), 'int64')
    mask = np.zeros((B, max_len), 'float32')
    for i, row in enumerate(token_rows):
        row = np.asarray(row)[:max_len + 1]
        n = len(row) - 1
        toks[i, :n, 0] = row[:-1]
        lbls[i, :n, 0] = row[1:]
        mask[i, :n] = 1.0
    return {'tokens': toks, 'labels': lbls, 'loss_mask': mask}


# ----------------------------------------- serving programs (zoo/lint)

def generation_program(config='tiny', mode='decode', temperature=0.0,
                       top_k=0, kv_slots=4, **overrides):
    """The serving-side llama paths as declarative Programs, so the
    static analyzer covers what serving/generation/ actually runs:

      * mode='prefill': full-window forward, fetch [B, T, V] logits —
        the shape of DecodeRuntime's prompt pass
      * mode='decode': forward + last-position slice + `sample_tokens`,
        fetch [B] next token ids — one decode step (the op's
        `(seed, position)` stream keeps replay deterministic)

    decode mode also declares the slotted KV pool on the program
    (`set_kv_plan`, CacheConfig arithmetic) so the memplan pass folds
    the cache bytes a real serving deployment would pin into its
    per-device footprint.  Weights use the training parameter names —
    a trained scope serves directly.
    """
    cfg = dict(CONFIGS[config] if isinstance(config, str) else config)
    cfg.update(overrides)
    T, V, D = cfg['max_len'], cfg['vocab'], cfg['d_model']

    tokens = layers.data('tokens', shape=[T, 1], dtype='int64')
    x = layers.embedding(
        tokens, size=[V, D],
        param_attr=ParamAttr(name='tok_emb',
                             initializer=Normal(0., 0.02)))
    for i in range(cfg['n_layer']):
        x = decoder_layer(x, cfg, 'layer_%d' % i)
    x = layers.rms_norm(x, param_attr=ParamAttr(name='final_norm'))
    logits = _linear(x, V, 'lm_proj')                    # [B, T, V]
    out = {'logits': logits, 'feeds': [tokens], 'config': cfg,
           'fetches': [logits]}
    if mode == 'decode':
        last = layers.slice(logits, axes=[1], starts=[T - 1], ends=[T])
        last = layers.squeeze(last, axes=[1])            # [B, V]
        nxt = layers.sample_tokens(last, temperature=temperature,
                                   top_k=top_k)
        out['next_token'] = nxt
        out['fetches'] = [nxt]
        tokens.block.program.set_kv_plan(
            slots=kv_slots, layers=cfg['n_layer'],
            kv_heads=cfg['n_kv_head'], max_len=T,
            head_dim=D // cfg['n_head'])
    return out


# ----------------------------------------------------------- decoding

def make_decoder(scope, config='tiny', temperature=0.0, **overrides):
    """Build a jitted KV-cache autoregressive decoder over the weights a
    trained llama program left in `scope` (same parameter names).

    The graph program is the training/scoring path; decode is a separate
    pure-JAX path because its structure differs (per-step KV cache, not
    teacher forcing) — the analogue of the reference's beam_search decode
    programs (machine_translation infer program).  Static shapes: the
    cache is [n_layer, B, Hkv, Tmax, Dh], current length carried as a
    scalar; attention masks by position, so every step compiles once.

    Returns generate(prompt_ids [B, Tp] int32, max_new) -> [B, Tp+max_new].
    """
    import jax
    import jax.numpy as jnp

    cfg = dict(CONFIGS[config] if isinstance(config, str) else config)
    cfg.update(overrides)
    L, H, Hkv = cfg['n_layer'], cfg['n_head'], cfg['n_kv_head']
    D, V, theta = cfg['d_model'], cfg['vocab'], cfg['theta']
    Tmax = cfg['max_len']
    dh = D // H

    def g(name):
        return jnp.asarray(scope.vars[name])

    w = {'emb': g('tok_emb'), 'final': g('final_norm'),
         'proj': g('lm_proj_w')}
    for i in range(L):
        p = 'layer_%d' % i
        for s in ('att_q_w', 'att_k_w', 'att_v_w', 'att_o_w', 'att_norm',
                  'ffn_norm', 'ffn_fc1_w', 'ffn_fc2_w', 'ffn_fc3_w'):
            w['%d_%s' % (i, s)] = g('%s_%s' % (p, s))

    def rms(x, scale):
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return x * jax.lax.rsqrt(var + 1e-6) * scale

    def rope_at(x, pos):
        # x: [B, h, T, dh]; pos: [T] absolute positions
        freqs = theta ** (-jnp.arange(0, dh // 2) * 2.0 / dh)
        ang = pos[None, None, :, None] * freqs            # [1,1,T,dh/2]
        cos, sin = jnp.cos(ang), jnp.sin(ang)
        x1, x2 = x[..., 0::2], x[..., 1::2]
        return jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                         axis=-1).reshape(x.shape)

    def attn(x, i, kcache, vcache, pos, cur_len):
        """x: [B, T, D] new positions starting at `pos[0]`; returns output
        plus updated caches."""
        B, T = x.shape[0], x.shape[1]
        q = (x @ w['%d_att_q_w' % i]).reshape(B, T, H, dh)
        k = (x @ w['%d_att_k_w' % i]).reshape(B, T, Hkv, dh)
        v = (x @ w['%d_att_v_w' % i]).reshape(B, T, Hkv, dh)
        q = rope_at(q.transpose(0, 2, 1, 3), pos)
        k = rope_at(k.transpose(0, 2, 1, 3), pos)
        v = v.transpose(0, 2, 1, 3)
        kcache = jax.lax.dynamic_update_slice(
            kcache, k.astype(kcache.dtype), (0, 0, pos[0], 0))
        vcache = jax.lax.dynamic_update_slice(
            vcache, v.astype(vcache.dtype), (0, 0, pos[0], 0))
        # GQA attention of q [B,H,T,dh] against cache [B,Hkv,Tmax,dh]
        qg = q.reshape(B, Hkv, H // Hkv, T, dh)
        s = jnp.einsum('bhgqd,bhkd->bhgqk', qg, kcache) * (dh ** -0.5)
        kpos = jnp.arange(Tmax)
        qpos = pos  # [T]
        mask = (kpos[None, :] <= qpos[:, None]) & \
            (kpos[None, :] < cur_len + T)
        s = jnp.where(mask[None, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum('bhgqk,bhkd->bhgqd', p, vcache)
        o = o.reshape(B, H, T, dh).transpose(0, 2, 1, 3).reshape(B, T, D)
        return o @ w['%d_att_o_w' % i], kcache, vcache

    def block(x, i, kc, vc, pos, cur_len):
        h, kc, vc = attn(rms(x, w['%d_att_norm' % i]), i, kc, vc, pos,
                         cur_len)
        x = x + h
        hh = rms(x, w['%d_ffn_norm' % i])
        gate = jax.nn.silu(hh @ w['%d_ffn_fc1_w' % i])
        up = hh @ w['%d_ffn_fc3_w' % i]
        x = x + (gate * up) @ w['%d_ffn_fc2_w' % i]
        return x, kc, vc

    def forward(tokens, kcaches, vcaches, pos, cur_len):
        x = w['emb'][tokens]                               # [B, T, D]
        new_k, new_v = [], []
        for i in range(L):
            x, kc, vc = block(x, i, kcaches[i], vcaches[i], pos, cur_len)
            new_k.append(kc)
            new_v.append(vc)
        x = rms(x, w['final'])
        return x @ w['proj'], jnp.stack(new_k), jnp.stack(new_v)

    def pick(logits, key):
        if temperature and temperature > 0:
            return jax.random.categorical(key, logits / temperature, -1)
        return jnp.argmax(logits, axis=-1)

    import functools

    @functools.partial(jax.jit, static_argnums=(1,))
    def generate(prompt, max_new, seed=0):
        B, Tp = prompt.shape
        kc = jnp.zeros((L, B, Hkv, Tmax, dh), jnp.float32)
        vc = jnp.zeros_like(kc)
        logits, kc, vc = forward(prompt, kc, vc, jnp.arange(Tp),
                                 jnp.int32(0))
        key = jax.random.key(seed)
        nxt = pick(logits[:, -1], key)

        def step(carry, t):
            kc, vc, tok, key = carry
            key, sub = jax.random.split(key)
            logits, kc, vc = forward(tok[:, None], kc, vc,
                                     jnp.array([0]) + Tp + t,
                                     Tp + t)
            nxt = pick(logits[:, 0], sub)
            return (kc, vc, nxt, key), tok

        # prefill already produced one token; scan emits the rest
        (_, _, last, _), toks = jax.lax.scan(
            step, (kc, vc, nxt, key), jnp.arange(max_new - 1))
        out = jnp.concatenate([toks.T, last[:, None]], axis=1)
        return jnp.concatenate([prompt, out], axis=1)

    def run(prompt_ids, max_new, seed=0):
        import numpy as np
        if max_new <= 0:
            # prefill would still emit one token; zero requested -> no-op
            return np.asarray(prompt_ids)
        prompt = jnp.asarray(np.asarray(prompt_ids), jnp.int32)
        if prompt.shape[1] + max_new > Tmax:
            raise ValueError('prompt+max_new exceeds max_len=%d' % Tmax)
        return np.asarray(generate(prompt, int(max_new), seed))

    return run


# ------------------------------------------------- streaming generation

def generation_weights(scope, config='tiny', **overrides):
    """Pull the decode-side weight dict (host arrays, llama parameter
    names) a trained llama program left in `scope` — the input format of
    serving.generation.DecodeRuntime."""
    from paddle_tpu.serving.generation.decode import weight_names
    cfg = dict(CONFIGS[config] if isinstance(config, str) else config)
    cfg.update(overrides)
    return {n: np.asarray(scope.vars[n]) for n in weight_names(cfg)}


def make_streaming_runtime(scope, config='tiny', slots=4, prefill_chunk=8,
                           mesh=None, **overrides):
    """Build a serving.generation.DecodeRuntime over a trained scope:
    the streaming-decode counterpart of `make_decoder` (same weights,
    but a slotted multi-request KV cache, fused K-token decode windows,
    and chunked/ring prefill — the device half of GenerationEngine).

        rt = llama.make_streaming_runtime(scope, 'tiny', slots=8)
        engine = GenerationEngine(rt).start()
    """
    from paddle_tpu.serving.generation.decode import DecodeRuntime
    cfg = dict(CONFIGS[config] if isinstance(config, str) else config)
    cfg.update(overrides)
    return DecodeRuntime(generation_weights(scope, cfg), cfg, slots=slots,
                         prefill_chunk=prefill_chunk, mesh=mesh)
