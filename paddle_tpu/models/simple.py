"""Small book-chapter models: fit_a_line (linear regression) and
recommender (parity: reference book ch.01 fit_a_line, ch.05 recommender)."""
import paddle_tpu as fluid
from paddle_tpu import layers


def fit_a_line(lr=0.01, is_train=True):
    x = layers.data('x', shape=[13], dtype='float32')
    y = layers.data('y', shape=[1], dtype='float32')
    y_predict = layers.fc(input=x, size=1, act=None)
    cost = layers.square_error_cost(input=y_predict, label=y)
    avg_cost = layers.mean(cost)
    opt = None
    if is_train:
        opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'predict': y_predict, 'feeds': [x, y],
            'optimizer': opt}


def recommender(n_users=6041, n_movies=3953, n_jobs=21, n_ages=7,
                n_cats=18, title_vocab=5175, dim=32, lr=1e-3,
                is_train=True):
    uid = layers.data('user_id', shape=[1], dtype='int64')
    gender = layers.data('gender_id', shape=[1], dtype='int64')
    age = layers.data('age_id', shape=[1], dtype='int64')
    job = layers.data('job_id', shape=[1], dtype='int64')
    mid = layers.data('movie_id', shape=[1], dtype='int64')
    cats = layers.data('category_id', shape=[1], dtype='int64', lod_level=1)
    title = layers.data('movie_title', shape=[1], dtype='int64',
                        lod_level=1)
    score = layers.data('score', shape=[1], dtype='float32')

    usr = layers.fc(layers.embedding(uid, [n_users, dim]), dim)
    g = layers.fc(layers.embedding(gender, [2, dim // 2]), dim // 2)
    a = layers.fc(layers.embedding(age, [n_ages, dim // 2]), dim // 2)
    j = layers.fc(layers.embedding(job, [n_jobs, dim // 2]), dim // 2)
    usr_combined = layers.fc(layers.concat([usr, g, a, j], axis=1), 200,
                             act='tanh')

    mov = layers.fc(layers.embedding(mid, [n_movies, dim]), dim)
    cat = layers.sequence_pool(layers.embedding(cats, [n_cats, dim]),
                               pool_type='sum')
    tit = fluid.nets.sequence_conv_pool(
        input=layers.embedding(title, [title_vocab, dim]),
        num_filters=dim, filter_size=3, act='tanh', pool_type='sum')
    mov_combined = layers.fc(layers.concat([mov, cat, tit], axis=1), 200,
                             act='tanh')

    inference = layers.scale(
        layers.cos_sim(usr_combined, mov_combined), scale=5.0)
    cost = layers.square_error_cost(inference, score)
    avg_cost = layers.mean(cost)
    opt = None
    if is_train:
        opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'predict': inference, 'optimizer': opt,
            'feeds': [uid, gender, age, job, mid, cats, title, score]}
