"""Stacked dynamic-LSTM sentiment LM (parity: reference
benchmark/fluid/models/stacked_dynamic_lstm.py).

Ragged IMDB reviews feed as padded+lengths LoDTensors; each LSTM layer is a
lax.scan recurrence with per-step masking (ops/sequence.py lstm).
"""
import paddle_tpu as fluid


def lstm_net(data, dict_dim, emb_dim=512, hid_dim=512, stacked_num=3,
             class_dim=2):
    emb = fluid.layers.embedding(input=data, size=[dict_dim, emb_dim])
    fc1 = fluid.layers.fc(input=emb, size=hid_dim * 4)
    lstm1, _ = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim * 4,
                                         use_peepholes=False)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim * 4)
        lstm, _ = fluid.layers.dynamic_lstm(
            input=fc, size=hid_dim * 4, is_reverse=(i % 2) == 0,
            use_peepholes=False)
        inputs = [fc, lstm]
    fc_last = fluid.layers.sequence_pool(input=inputs[0], pool_type='max')
    lstm_last = fluid.layers.sequence_pool(input=inputs[1], pool_type='max')
    prediction = fluid.layers.fc(input=[fc_last, lstm_last], size=class_dim,
                                 act='softmax')
    return prediction


def build(dict_dim=5147, emb_dim=512, hid_dim=512, stacked_num=3,
          class_dim=2, lr=0.002, is_train=True):
    data = fluid.layers.data(name='words', shape=[1], dtype='int64',
                             lod_level=1)
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    prediction = lstm_net(data, dict_dim, emb_dim, hid_dim, stacked_num,
                          class_dim)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    batch_acc = fluid.layers.accuracy(input=prediction, label=label)
    opt = None
    if is_train:
        opt = fluid.optimizer.Adam(learning_rate=lr)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'accuracy': batch_acc,
            'feeds': [data, label], 'predict': prediction, 'optimizer': opt}
