"""SE-ResNeXt-50/101/152 (parity: reference
benchmark/fluid/models/se_resnext.py)."""
import paddle_tpu as fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_train=True):
    conv = fluid.layers.conv2d(input=input, num_filters=num_filters,
                               filter_size=filter_size, stride=stride,
                               padding=(filter_size - 1) // 2, groups=groups,
                               act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act,
                                   is_test=not is_train)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = fluid.layers.pool2d(input=input, pool_type='avg',
                               global_pooling=True)
    squeeze = fluid.layers.fc(input=pool,
                              size=num_channels // reduction_ratio,
                              act='relu')
    excitation = fluid.layers.fc(input=squeeze, size=num_channels,
                                 act='sigmoid')
    return fluid.layers.elementwise_mul(x=input, y=excitation, axis=0)


def shortcut(input, ch_out, stride, is_train=True):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        filter_size = 1
        return conv_bn_layer(input, ch_out, filter_size, stride,
                             is_train=is_train)
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio, is_train=True):
    conv0 = conv_bn_layer(input, num_filters, 1, act='relu',
                          is_train=is_train)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, cardinality,
                          act='relu', is_train=is_train)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_train=is_train)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride, is_train=is_train)
    return fluid.layers.elementwise_add(x=short, y=scale, act='relu')


def SE_ResNeXt(input, class_dim, layers=50, is_train=True):
    supported = {50: ([3, 4, 6, 3], 32, 16),
                 101: ([3, 4, 23, 3], 32, 16),
                 152: ([3, 8, 36, 3], 64, 16)}
    depth, cardinality, reduction_ratio = supported[layers]
    num_filters = [128, 256, 512, 1024]
    if layers == 152:
        conv = conv_bn_layer(input, 64, 3, 2, act='relu', is_train=is_train)
        conv = conv_bn_layer(conv, 64, 3, act='relu', is_train=is_train)
        conv = conv_bn_layer(conv, 128, 3, act='relu', is_train=is_train)
    else:
        conv = conv_bn_layer(input, 64, 7, 2, act='relu', is_train=is_train)
    conv = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type='max')
    for block in range(len(depth)):
        for i in range(depth[block]):
            conv = bottleneck_block(
                conv, num_filters[block], 2 if i == 0 and block != 0 else 1,
                cardinality, reduction_ratio, is_train=is_train)
    pool = fluid.layers.pool2d(input=conv, pool_type='avg',
                               global_pooling=True)
    drop = fluid.layers.dropout(x=pool, dropout_prob=0.5,
                                is_test=not is_train)
    return fluid.layers.fc(input=drop, size=class_dim, act='softmax')


def build(data_shape=(3, 224, 224), class_dim=1000, depth=50, lr=0.1,
          is_train=True):
    images = fluid.layers.data(name='data', shape=list(data_shape),
                               dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = SE_ResNeXt(images, class_dim, depth, is_train)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    opt = None
    if is_train:
        opt = fluid.optimizer.Momentum(
            learning_rate=fluid.layers.piecewise_decay(
                boundaries=[1000, 2000], values=[lr, lr * 0.1, lr * 0.01]),
            momentum=0.9,
            regularization=fluid.regularizer.L2Decay(1e-4))
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'accuracy': batch_acc,
            'feeds': [images, label], 'predict': predict, 'optimizer': opt}
