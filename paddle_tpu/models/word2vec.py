"""Word2vec n-gram LM (parity: reference book chapter 04 word2vec, the
imikolov benchmark model)."""
import paddle_tpu as fluid
from paddle_tpu import layers


def build(dict_size=2073, embed_size=32, hidden_size=256, n=5, lr=1e-3,
          is_train=True):
    words = [layers.data('word_%d' % i, shape=[1], dtype='int64')
             for i in range(n - 1)]
    next_word = layers.data('next_word', shape=[1], dtype='int64')
    embs = [layers.embedding(
        w, size=[dict_size, embed_size],
        param_attr=fluid.ParamAttr(name='shared_emb'))
        for w in words]
    concat = layers.concat(embs, axis=1)
    hidden = layers.fc(concat, hidden_size, act='sigmoid')
    predict = layers.fc(hidden, dict_size, act='softmax')
    cost = layers.cross_entropy(input=predict, label=next_word)
    avg_cost = layers.mean(cost)
    opt = None
    if is_train:
        opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'predict': predict,
            'feeds': words + [next_word], 'optimizer': opt}
