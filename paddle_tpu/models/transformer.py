"""Transformer for NMT (parity: reference benchmark transformer /
machine_translation model family; fluid transformer config in
benchmark/fluid/models/machine_translation.py's role).

TPU-first: fixed max_len padded batches + boolean masks (no LoD walk),
pre-norm residual blocks, attention as batched MXU matmuls; the scaled-dot
product can route through the pallas flash-attention kernel
(ops/attention.py) with use_flash=True.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.initializer import Normal


def _linear(x, size, name, bias=True, amp_keep_bf16=False, init=None):
    # Xavier init (the fluid fc default): keeps attention logits at O(1)
    # scale so gradients reach the encoder from step 0
    return layers.fc(x, size, num_flatten_dims=2,
                     param_attr=ParamAttr(name=name + '_w',
                                          initializer=init),
                     bias_attr=ParamAttr(name=name + '_b') if bias else False,
                     amp_keep_bf16=amp_keep_bf16)


def multi_head_attention(q_in, kv_in, mask, d_model, n_head, dropout,
                         is_train, name, use_flash=False, causal=False,
                         kv_lengths=None):
    """mask: [B, 1, Tq, Tk] additive (-1e9 on invalid); kv_lengths int [B]
    (used by the flash path, where pad is a suffix)."""
    d_head = d_model // n_head
    # fused projections: self-attention projects q,k,v as ONE d x 3d
    # GEMM (cross-attention fuses k,v as d x 2d) and splits the result.
    # Measured ~parity end-to-end at B=32/T=256 (+0.2%, PERF.md r5) —
    # XLA was already handling the three small GEMMs well — kept because
    # it reads the activations once and is never slower.
    # amp_keep_bf16 flow-through was ALSO measured for the block
    # interior (q/k/v + scores + weights + context, and separately the
    # ffn hidden): both lose ~0.5% — the f32 [B,H,T,T] residual copies
    # the ledger flagged are cheaper than the extra converts the bf16
    # interior induces around the f32 softmax statistics.  Cast-back
    # stays the block-interior policy; only the logits projection flows
    # (PERF.md r5).
    # the fused [d, 3d] weight pins Xavier fans to the SEPARATE
    # projections' (d, d) so each q/k/v slice keeps the exact init
    # distribution of three unfused fc's (fan_out would otherwise
    # triple and shrink the init std ~1.4x)
    from paddle_tpu.initializer import Xavier
    per_proj = Xavier(fan_in=d_model, fan_out=d_model)
    if q_in is kv_in:
        qkv = _linear(q_in, 3 * d_model, name + '_qkv', bias=False,
                      init=per_proj)
        q, k, v = layers.split(qkv, 3, dim=-1)
    else:
        q = _linear(q_in, d_model, name + '_q', bias=False)
        kv = _linear(kv_in, 2 * d_model, name + '_kv', bias=False,
                     init=per_proj)
        k, v = layers.split(kv, 2, dim=-1)

    def split_heads(x):
        x = layers.reshape(x, [0, 0, n_head, d_head])
        return layers.transpose(x, perm=[0, 2, 1, 3])  # [B, H, T, Dh]

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    # the fused kernel has no attention-weight dropout: use it only when
    # dropout is off (inference / LLM-style training); else compose ops
    if use_flash and dropout and is_train:
        use_flash = False
    if use_flash:
        if mask is not None and kv_lengths is None:
            raise ValueError(
                'use_flash with a padding mask requires kv_lengths '
                '(suffix-padding lengths); got None')
        ctx = layers.flash_attention(q, k, v, causal=causal,
                                     k_lengths=kv_lengths)
    else:
        q = layers.scale(q, scale=d_head ** -0.5)
        scores = layers.matmul(q, k, transpose_y=True)  # [B, H, Tq, Tk]
        if mask is not None:
            scores = layers.elementwise_add(scores, mask)
        weights = layers.softmax(scores)
        if dropout and is_train:
            weights = layers.dropout(
                weights, dropout, is_test=not is_train,
                dropout_implementation='upscale_in_train')
        ctx = layers.matmul(weights, v)  # [B, H, Tq, Dh]
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, d_model])
    return _linear(ctx, d_model, name + '_o', bias=False)


def ffn(x, d_model, d_inner, dropout, is_train, name):
    h = _linear(x, d_inner, name + '_fc1')
    h = layers.relu(h)
    if dropout and is_train:
        h = layers.dropout(h, dropout, is_test=not is_train,
                           dropout_implementation='upscale_in_train')
    return _linear(h, d_model, name + '_fc2')


def _prenorm(x, sub, name):
    ln = layers.layer_norm(x, begin_norm_axis=2,
                           param_attr=ParamAttr(name=name + '_ln_w'),
                           bias_attr=ParamAttr(name=name + '_ln_b'))
    return layers.elementwise_add(x, sub(ln))


def encoder_layer(x, mask, cfg, is_train, name, lengths=None):
    x = _prenorm(x, lambda h: multi_head_attention(
        h, h, mask, cfg['d_model'], cfg['n_head'], cfg['dropout'], is_train,
        name + '_att', cfg.get('use_flash', False),
        kv_lengths=lengths), name + '_att')
    x = _prenorm(x, lambda h: ffn(
        h, cfg['d_model'], cfg['d_inner'], cfg['dropout'], is_train,
        name + '_ffn'), name + '_ffn')
    return x


def decoder_layer(x, enc, self_mask, cross_mask, cfg, is_train, name,
                  src_lengths=None, trg_lengths=None):
    x = _prenorm(x, lambda h: multi_head_attention(
        h, h, self_mask, cfg['d_model'], cfg['n_head'], cfg['dropout'],
        is_train, name + '_satt', cfg.get('use_flash', False), causal=True,
        kv_lengths=trg_lengths), name + '_satt')
    x = _prenorm(x, lambda h: multi_head_attention(
        h, enc, cross_mask, cfg['d_model'], cfg['n_head'], cfg['dropout'],
        is_train, name + '_xatt', cfg.get('use_flash', False),
        kv_lengths=src_lengths), name + '_xatt')
    x = _prenorm(x, lambda h: ffn(
        h, cfg['d_model'], cfg['d_inner'], cfg['dropout'], is_train,
        name + '_ffn'), name + '_ffn')
    return x


def _embed(ids, vocab, d_model, max_len, dropout, is_train, name):
    emb = layers.embedding(
        ids, size=[vocab, d_model],
        param_attr=ParamAttr(name=name + '_emb',
                             initializer=Normal(0., d_model ** -0.5)))
    emb = layers.scale(emb, scale=d_model ** 0.5)
    emb = layers.add_position_encoding(emb, alpha=1.0, beta=1.0)
    if dropout and is_train:
        emb = layers.dropout(emb, dropout, is_test=not is_train,
                             dropout_implementation='upscale_in_train')
    return emb


def _pad_mask(pad_flags, neg=-1e9):
    """pad_flags: [B, T] float 1.0 where PAD.  -> [B, 1, 1, T] additive."""
    m = layers.scale(pad_flags, scale=neg)
    m = layers.unsqueeze(m, axes=[1, 2])
    return m


def _causal_mask_const(max_len):
    tri = np.triu(np.full((max_len, max_len), -1e9, 'float32'), k=1)
    return tri.reshape(1, 1, max_len, max_len)


def transformer(src_vocab, trg_vocab, max_len=64, n_layer=6, n_head=8,
                d_model=512, d_inner=2048, dropout=0.1, is_train=True,
                use_flash=False, label_smooth_eps=0.1):
    """Returns dict with loss/feeds/fetches.  Feeds (all dense, [B, T]):
    src_word, trg_word (shifted-in), lbl_word (shifted-out), plus float
    pad masks src_pad [B, T], trg_pad [B, T]."""
    cfg = {'d_model': d_model, 'n_head': n_head, 'd_inner': d_inner,
           'dropout': dropout, 'use_flash': use_flash}
    src = layers.data('src_word', shape=[max_len, 1], dtype='int64')
    trg = layers.data('trg_word', shape=[max_len, 1], dtype='int64')
    lbl = layers.data('lbl_word', shape=[max_len, 1], dtype='int64')
    src_pad = layers.data('src_pad', shape=[max_len], dtype='float32')
    trg_pad = layers.data('trg_pad', shape=[max_len], dtype='float32')

    src_mask = _pad_mask(src_pad)                       # [B,1,1,Ts]
    cross_mask = src_mask
    ones = layers.fill_constant_batch_size_like(src_pad, [-1, max_len],
                                                'float32', 1.0)
    src_len = layers.cast(layers.reduce_sum(
        layers.elementwise_sub(ones, src_pad), dim=1), 'int32')
    trg_len = layers.cast(layers.reduce_sum(
        layers.elementwise_sub(ones, trg_pad), dim=1), 'int32')
    causal = layers.assign(_causal_mask_const(max_len))  # [1,1,Tt,Tt]
    trg_mask = layers.elementwise_add(_pad_mask(trg_pad), causal)

    enc = _embed(src, src_vocab, d_model, max_len, dropout, is_train,
                 'src')
    for i in range(n_layer):
        enc = encoder_layer(enc, src_mask, cfg, is_train, 'enc_%d' % i,
                            lengths=src_len)
    enc = layers.layer_norm(enc, begin_norm_axis=2,
                            param_attr=ParamAttr(name='enc_post_ln_w'),
                            bias_attr=ParamAttr(name='enc_post_ln_b'))

    dec = _embed(trg, trg_vocab, d_model, max_len, dropout, is_train,
                 'trg')
    for i in range(n_layer):
        dec = decoder_layer(dec, enc, trg_mask, cross_mask, cfg, is_train,
                            'dec_%d' % i, src_lengths=src_len,
                            trg_lengths=trg_len)
    dec = layers.layer_norm(dec, begin_norm_axis=2,
                            param_attr=ParamAttr(name='dec_post_ln_w'),
                            bias_attr=ParamAttr(name='dec_post_ln_b'))

    # the [B, T, V] logits stay bf16 under AMP: their only consumer is
    # the CE, whose reductions are internally f32, and the backward then
    # carries a bf16 dlogits into the two big vocab GEMMs — this buffer
    # is the largest in the model and was measured f32 in the per-HLO
    # ledger (PERF.md r5)
    logits = _linear(dec, trg_vocab, 'proj',            # [B, T, V]
                     amp_keep_bf16=True)
    # fused label smoothing: the one_hot -> label_smooth -> soft-CE chain
    # would materialize two [B, T, V] f32 buffers (>1 GB at bench shapes);
    # the closed form needs only reductions over V
    per_tok = layers.softmax_with_cross_entropy(
        logits, lbl, label_smooth_eps=label_smooth_eps)
    # mask out PAD target positions: weight = 1 - trg_pad
    w = layers.elementwise_sub(
        layers.fill_constant_batch_size_like(trg_pad, [-1, max_len],
                                             'float32', 1.0), trg_pad)
    per_tok = layers.elementwise_mul(layers.squeeze(per_tok, axes=[2]), w)
    sum_cost = layers.reduce_sum(per_tok)
    token_num = layers.reduce_sum(w)
    avg_cost = layers.elementwise_div(sum_cost, token_num)
    return {'loss': avg_cost, 'sum_cost': sum_cost, 'token_num': token_num,
            'feeds': [src, trg, lbl, src_pad, trg_pad], 'logits': logits}


def build(src_vocab=10000, trg_vocab=10000, max_len=64, n_layer=6, n_head=8,
          d_model=512, d_inner=2048, dropout=0.1, lr=2.0,
          warmup_steps=8000, is_train=True, use_flash=False):
    out = transformer(src_vocab, trg_vocab, max_len, n_layer, n_head,
                      d_model, d_inner, dropout, is_train, use_flash)
    opt = None
    if is_train:
        lr_var = layers.noam_decay(d_model, warmup_steps)
        lr_var = layers.scale(lr_var, scale=float(lr))
        opt = fluid.optimizer.Adam(learning_rate=lr_var, beta1=0.9,
                                   beta2=0.997, epsilon=1e-9)
        opt.minimize(out['loss'])
    out['optimizer'] = opt
    return out


def synthetic_batch(rng, batch_size, max_len, vocab=32000):
    """Full-length synthetic (src, trg_in, trg_out) feeds for benchmarks
    (bench.py / tools/) — ONE definition so every harness measures the
    same feed contract."""
    rows = []
    for _ in range(batch_size):
        s = rng.randint(3, vocab, (max_len - 1,))
        rows.append((np.concatenate([s, [1]]), np.concatenate([[0], s]),
                     np.concatenate([s, [1]])))
    return make_batch(rows, max_len)


def make_batch(reader_batch, max_len, rng=None):
    """Convert wmt16-style (src, trg_in, trg_out) rows into dense feeds."""
    B = len(reader_batch)
    src = np.zeros((B, max_len, 1), 'int64')
    trg = np.zeros((B, max_len, 1), 'int64')
    lbl = np.zeros((B, max_len, 1), 'int64')
    src_pad = np.ones((B, max_len), 'float32')
    trg_pad = np.ones((B, max_len), 'float32')
    for i, (s, t, l) in enumerate(reader_batch):
        s = s[:max_len]
        t = t[:max_len]
        l = l[:max_len]
        src[i, :len(s), 0] = s
        trg[i, :len(t), 0] = t
        lbl[i, :len(l), 0] = l
        src_pad[i, :len(s)] = 0.0
        trg_pad[i, :len(t)] = 0.0
    return {'src_word': src, 'trg_word': trg, 'lbl_word': lbl,
            'src_pad': src_pad, 'trg_pad': trg_pad}
