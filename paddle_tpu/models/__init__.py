"""Model zoo (parity: reference benchmark/fluid/models/ + book chapters).

Every model is built from paddle_tpu.layers graph code (same style as the
reference's fluid model code) and exposes:
    build(...) -> dict with 'loss', 'feeds', optional 'accuracy'/'fetches'
plus a reference-style `get_model(args, is_train, main_prog, startup_prog)`
where it makes sense.
"""
from . import mnist  # noqa
from . import resnet  # noqa
from . import vgg  # noqa
from . import se_resnext  # noqa
from . import stacked_lstm  # noqa
from . import transformer  # noqa
from . import ctr  # noqa
from . import word2vec  # noqa
from . import simple  # noqa
from . import llama  # noqa
