"""ResNet-50/101/152 (parity: reference benchmark/fluid/models/resnet.py).

Built NCHW with conv+BN blocks; XLA lays out for MXU.  `dtype='bfloat16'`
runs the conv stack in bf16 with f32 batch-norm statistics — the TPU fast
path used by bench.py.
"""
import numpy as np

import paddle_tpu as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act='relu',
                  is_train=True):
    conv1 = fluid.layers.conv2d(input=input, filter_size=filter_size,
                                num_filters=ch_out, stride=stride,
                                padding=padding, act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv1, act=act, is_test=not is_train)


def shortcut(input, ch_out, stride, is_train=True):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             is_train=is_train)
    return input


def basicblock(input, ch_out, stride, is_train=True):
    short = shortcut(input, ch_out, stride, is_train=is_train)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_train=is_train)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None,
                          is_train=is_train)
    return fluid.layers.elementwise_add(x=short, y=conv2, act='relu')


def bottleneck(input, ch_out, stride, is_train=True):
    short = shortcut(input, ch_out * 4, stride, is_train=is_train)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_train=is_train)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_train=is_train)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_train=is_train)
    return fluid.layers.elementwise_add(x=short, y=conv3, act='relu')


def layer_warp(block_func, input, ch_out, count, stride, is_train=True):
    res_out = block_func(input, ch_out, stride, is_train=is_train)
    for i in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_train=is_train)
    return res_out


def resnet_imagenet(input, class_dim=1000, depth=50, is_train=True):
    cfg = {18: ([2, 2, 2, 1], basicblock),
           34: ([3, 4, 6, 3], basicblock),
           50: ([3, 4, 6, 3], bottleneck),
           101: ([3, 4, 23, 3], bottleneck),
           152: ([3, 8, 36, 3], bottleneck)}
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_train=is_train)
    pool1 = fluid.layers.pool2d(input=conv1, pool_type='max', pool_size=3,
                                pool_stride=2, pool_padding=1)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_train)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_train)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_train)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_train)
    pool2 = fluid.layers.pool2d(input=res4, pool_size=7, pool_type='avg',
                                global_pooling=True)
    out = fluid.layers.fc(input=pool2, size=class_dim, act='softmax')
    return out


def resnet_cifar10(input, class_dim=10, depth=32, is_train=True):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input=input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_train=is_train)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_train)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_train)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_train)
    pool = fluid.layers.pool2d(input=res3, pool_size=8, pool_type='avg',
                               global_pooling=True)
    out = fluid.layers.fc(input=pool, size=class_dim, act='softmax')
    return out


def build(data_shape=(3, 224, 224), class_dim=1000, depth=50, lr=0.1,
          is_train=True, data_set='imagenet'):
    images = fluid.layers.data(name='data', shape=list(data_shape),
                               dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    if data_set == 'cifar10':
        predict = resnet_cifar10(images, class_dim, depth, is_train)
    else:
        predict = resnet_imagenet(images, class_dim, depth, is_train)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    opt = None
    if is_train:
        opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'accuracy': batch_acc,
            'feeds': [images, label], 'predict': predict, 'optimizer': opt}


def bench_program(B=128, side=224, classes=1000, depth=50, lr=0.1,
                  seed=0):
    """The canonical ResNet-50 bench step + synthetic feed, shared by
    bench.py / tools/tune_tpu.py / tools/measure.py so every harness
    profiles the SAME program (r5 review).  Returns
    (main, startup, out, feed)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            out = build(data_shape=(3, side, side), class_dim=classes,
                        depth=depth, lr=lr)
    main.set_amp(True)
    rng = np.random.RandomState(seed)
    feed = {'data': rng.rand(B, 3, side, side).astype('float32'),
            'label': rng.randint(0, classes, (B, 1)).astype('int64')}
    return main, startup, out, feed
