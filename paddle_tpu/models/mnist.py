"""MNIST CNN (parity: reference benchmark/fluid/models/mnist.py
cnn_model/get_model)."""
import paddle_tpu as fluid


def cnn_model(data):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act='relu')
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act='relu')
    predict = fluid.layers.fc(input=conv_pool_2, size=10, act='softmax')
    return predict


def build(batch_size=None, lr=0.001, is_train=True):
    images = fluid.layers.data(name='pixel', shape=[1, 28, 28],
                               dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = cnn_model(images)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)
    opt = None
    if is_train:
        opt = fluid.optimizer.AdamOptimizer(learning_rate=lr)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'accuracy': batch_acc,
            'feeds': [images, label], 'predict': predict, 'optimizer': opt}


def get_model(args, is_train, main_prog, startup_prog):
    """Reference-style entry (benchmark/fluid/models/mnist.py:get_model)."""
    import paddle_tpu.dataset.mnist as mnist_data
    from paddle_tpu.batch import batch as batch_fn
    with fluid.program_guard(main_prog, startup_prog):
        with fluid.unique_name.guard():
            out = build(lr=0.001, is_train=is_train)
    reader = mnist_data.train() if is_train else mnist_data.test()
    batched = batch_fn(reader, args.batch_size if hasattr(
        args, 'batch_size') else 64)
    return (out['loss'], out['optimizer'], [out['accuracy']], batched, None)
