"""CTR models: DeepFM and Wide&Deep (parity: PaddleRec CTR per
BASELINE.json configs; reference pattern = sparse lookup_table +
DistributeTranspiler pserver — here dense embeddings shardable over the
mesh via parallel/sharded_embedding.py).
"""
import paddle_tpu as fluid
from paddle_tpu import layers


def deepfm(sparse_slots=26, dense_dim=13, vocab_size=10000, embed_dim=8,
           fc_sizes=(400, 400, 400), is_train=True):
    dense = layers.data('dense_input', shape=[dense_dim], dtype='float32')
    sparse = layers.data('sparse_input', shape=[sparse_slots],
                         dtype='int64')
    label = layers.data('label', shape=[1], dtype='int64')

    # ---- first order
    emb_1 = layers.embedding(layers.unsqueeze(sparse, axes=[2]),
                             size=[vocab_size, 1])        # [B, S, 1]
    first_sparse = layers.reduce_sum(layers.squeeze(emb_1, axes=[2]), dim=1,
                                     keep_dim=True)
    first_dense = layers.fc(dense, 1)
    first = layers.elementwise_add(first_sparse, first_dense)

    # ---- second order (FM):
    emb_k = layers.embedding(layers.unsqueeze(sparse, axes=[2]),
                             size=[vocab_size, embed_dim])  # [B, S, K]
    sum_sq = layers.square(layers.reduce_sum(emb_k, dim=1))
    sq_sum = layers.reduce_sum(layers.square(emb_k), dim=1)
    second = layers.scale(layers.reduce_sum(
        layers.elementwise_sub(sum_sq, sq_sum), dim=1, keep_dim=True),
        scale=0.5)

    # ---- deep
    deep = layers.reshape(emb_k, [-1, sparse_slots * embed_dim])
    deep = layers.concat([deep, dense], axis=1)
    for s in fc_sizes:
        deep = layers.fc(deep, s, act='relu')
    deep_out = layers.fc(deep, 1)

    logit = layers.elementwise_add(layers.elementwise_add(first, second),
                                   deep_out)
    pred = layers.sigmoid(logit)
    labelf = layers.cast(label, 'float32')
    cost = layers.sigmoid_cross_entropy_with_logits(logit, labelf)
    avg_cost = layers.mean(cost)
    opt = None
    if is_train:
        opt = fluid.optimizer.Adam(learning_rate=1e-3)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'predict': pred,
            'feeds': [dense, sparse, label], 'optimizer': opt}


def wide_deep(sparse_slots=26, dense_dim=13, vocab_size=10000, embed_dim=8,
              fc_sizes=(256, 128, 64), is_train=True):
    dense = layers.data('dense_input', shape=[dense_dim], dtype='float32')
    sparse = layers.data('sparse_input', shape=[sparse_slots],
                         dtype='int64')
    label = layers.data('label', shape=[1], dtype='int64')
    # wide: linear over dense + per-slot 1-dim embeddings
    wide_emb = layers.embedding(layers.unsqueeze(sparse, axes=[2]),
                                size=[vocab_size, 1])
    wide = layers.elementwise_add(
        layers.reduce_sum(layers.squeeze(wide_emb, axes=[2]), dim=1,
                          keep_dim=True),
        layers.fc(dense, 1))
    # deep
    emb = layers.embedding(layers.unsqueeze(sparse, axes=[2]),
                           size=[vocab_size, embed_dim])
    deep = layers.concat(
        [layers.reshape(emb, [-1, sparse_slots * embed_dim]), dense], axis=1)
    for s in fc_sizes:
        deep = layers.fc(deep, s, act='relu')
    deep = layers.fc(deep, 1)
    logit = layers.elementwise_add(wide, deep)
    pred = layers.sigmoid(logit)
    labelf = layers.cast(label, 'float32')
    cost = layers.sigmoid_cross_entropy_with_logits(logit, labelf)
    avg_cost = layers.mean(cost)
    opt = None
    if is_train:
        opt = fluid.optimizer.Adagrad(learning_rate=1e-2)
        opt.minimize(avg_cost)
    return {'loss': avg_cost, 'predict': pred,
            'feeds': [dense, sparse, label], 'optimizer': opt}


def synthetic_reader(n=4096, sparse_slots=26, dense_dim=13,
                     vocab_size=10000, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    w = rng.normal(size=(dense_dim,)).astype('float32')

    def reader():
        for _ in range(n):
            d = rng.normal(size=(dense_dim,)).astype('float32')
            s = rng.randint(0, vocab_size, (sparse_slots,)).astype('int64')
            y = int((d.dot(w) + (s % 7).sum() * 0.05 +
                     rng.normal(0, 0.1)) > 0)
            yield d, s, [y]
    return reader
