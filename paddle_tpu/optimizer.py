"""Optimizers — program-transform semantics, single-executable updates.

Parity: reference python/paddle/fluid/optimizer.py (19 exports).
`minimize(loss)` appends backward + clip + regularization + update ops to the
program exactly like the reference; the Executor fuses everything (forward,
vjp backward, updates) into ONE jitted XLA executable with donated parameter
buffers — no per-parameter kernel launches like the reference's GPU path.
"""
import numpy as np

from .core.framework import (Variable, default_main_program,
                             op_role_guard, OpRole)
from .core import unique_name
from .core.backward import append_backward
from .initializer import Constant
from .regularizer import append_regularization_ops
from .clip import append_gradient_clip_ops

__all__ = [
    'SGD', 'Momentum', 'Adagrad', 'Adam', 'Adamax', 'DecayedAdagrad', 'Ftrl',
    'SGDOptimizer', 'MomentumOptimizer', 'AdagradOptimizer', 'AdamOptimizer',
    'AdamaxOptimizer', 'DecayedAdagradOptimizer', 'RMSPropOptimizer',
    'FtrlOptimizer', 'Adadelta', 'AdadeltaOptimizer', 'ModelAverage',
    'LarsMomentum', 'LarsMomentumOptimizer',
]


class Optimizer(object):
    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = {}
        self.helper = None

    # ----------------------------------------------------------- LR

    def _create_global_learning_rate(self):
        prog = default_main_program()
        lr = self._learning_rate_map.get(prog)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[prog] = self._learning_rate
            return
        from .layers.tensor import create_global_var
        lr_var = create_global_var(
            name=unique_name.generate('learning_rate'),
            shape=[1], value=float(self._learning_rate), dtype='float32',
            persistable=True)
        lr_var.stop_gradient = True
        self._learning_rate_map[prog] = lr_var

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = param.optimize_attr.get('learning_rate', 1.0)
        lr_var = self._global_learning_rate()
        if param_lr == 1.0:
            return lr_var
        block = default_main_program().global_block()
        out = block.create_var(dtype='float32')
        block.append_op(type='scale', inputs={'X': lr_var},
                        outputs={'Out': out},
                        attrs={'scale': float(param_lr), 'bias': 0.0,
                               'bias_after_scale': True})
        return out

    # ----------------------------------------------------- accumulators

    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if (name, param.name) in self._accumulators:
            return self._accumulators[(name, param.name)]
        block = default_main_program().global_block()
        shape = list(shape if shape is not None else param.shape)
        var = block.create_var(
            name=unique_name.generate('%s_%s' % (param.name, name)),
            shape=shape, dtype=dtype or param.dtype, persistable=True,
            stop_gradient=True)
        # same-shaped state inherits the parameter's declared layout:
        # a model-parallel annotation covers its moments without the
        # user re-annotating, and the shard pass's ZeRO tier then splits
        # both identically
        if param.sharding is not None and tuple(shape) == \
                tuple(param.shape or ()):
            var.sharding = param.sharding
        Constant(value=float(fill_value))(var)
        self._accumulators[(name, param.name)] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[(name, param.name)]

    # ------------------------------------------------------- pipeline

    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        block = loss.block.program.global_block()
        with op_role_guard(OpRole.Optimize):
            self._create_global_learning_rate()
            self._create_accumulators(
                block, [p for p, g in parameters_and_grads if g is not None])
            optimize_ops = []
            for param_and_grad in parameters_and_grads:
                if param_and_grad[1] is None:
                    continue
                if param_and_grad[0].trainable:
                    optimize_ops.append(
                        self._append_optimize_op(block, param_and_grad))
            self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from .imperative import base as _imp_base
        if _imp_base.enabled():
            return _imp_base.eager_params_grads(loss, parameter_list,
                                                no_grad_set)
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        if not params_grads:
            return []
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        # any grad var's block gives the program
        block = params_grads[0][0].block
        with op_role_guard(OpRole.Optimize):
            self._create_global_learning_rate()
            self._create_accumulators(
                block, [p for p, g in params_grads if g is not None])
            optimize_ops = []
            for pg in params_grads:
                if pg[1] is None or not pg[0].trainable:
                    continue
                optimize_ops.append(self._append_optimize_op(block, pg))
            self._finish_update(block, params_grads)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .imperative import base as _imp_base
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        if _imp_base.enabled():
            # eager: update ops run immediately on param._ivalue; keep them
            # off the tape so the next backward doesn't differentiate them
            with _imp_base.no_record():
                optimize_ops = self.apply_gradients(params_grads)
        else:
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super(SGDOptimizer, self).__init__(learning_rate, regularization,
                                           name)
        self.type = 'sgd'

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type='sgd',
            inputs={'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': param_and_grad[0]}, attrs={},
            infer_shape=False)


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super(MomentumOptimizer, self).__init__(learning_rate,
                                                regularization, name)
        self.type = 'momentum'
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('velocity', p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator('velocity', param_and_grad[0])
        return block.append_op(
            type='momentum',
            inputs={'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                    'Velocity': velocity,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': param_and_grad[0],
                     'VelocityOut': velocity},
            attrs={'mu': self._momentum,
                   'use_nesterov': self._use_nesterov},
            infer_shape=False)


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super(LarsMomentumOptimizer, self).__init__(learning_rate,
                                                    regularization, name)
        self.type = 'lars_momentum'
        self._momentum = momentum
        self._lars_coeff = float(lars_coeff)
        self._lars_weight_decay = float(lars_weight_decay)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('velocity', p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator('velocity', param_and_grad[0])
        return block.append_op(
            type='lars_momentum',
            inputs={'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                    'Velocity': velocity,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': param_and_grad[0],
                     'VelocityOut': velocity},
            attrs={'mu': self._momentum, 'lars_coeff': self._lars_coeff,
                   'lars_weight_decay': self._lars_weight_decay},
            infer_shape=False)


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super(AdagradOptimizer, self).__init__(learning_rate, regularization,
                                               name)
        self.type = 'adagrad'
        self._epsilon = epsilon
        self._initial_accumulator_value = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment', p,
                                  fill_value=self._initial_accumulator_value)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator('moment', param_and_grad[0])
        return block.append_op(
            type='adagrad',
            inputs={'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                    'Moment': moment,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': param_and_grad[0], 'MomentOut': moment},
            attrs={'epsilon': self._epsilon}, infer_shape=False)


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super(AdamOptimizer, self).__init__(learning_rate, regularization,
                                            name)
        self.type = 'adam'
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment1', p)
            self._add_accumulator('moment2', p)
            self._add_accumulator('beta1_pow_acc', p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator('beta2_pow_acc', p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        m1 = self._get_accumulator('moment1', p)
        m2 = self._get_accumulator('moment2', p)
        b1p = self._get_accumulator('beta1_pow_acc', p)
        b2p = self._get_accumulator('beta2_pow_acc', p)
        return block.append_op(
            type='adam',
            inputs={'Param': p, 'Grad': param_and_grad[1],
                    'LearningRate': self._create_param_lr(param_and_grad),
                    'Moment1': m1, 'Moment2': m2,
                    'Beta1Pow': b1p, 'Beta2Pow': b2p},
            outputs={'ParamOut': p, 'Moment1Out': m1, 'Moment2Out': m2,
                     'Beta1PowOut': b1p, 'Beta2PowOut': b2p},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon},
            infer_shape=False)


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super(AdamaxOptimizer, self).__init__(learning_rate, regularization,
                                              name)
        self.type = 'adamax'
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment', p)
            self._add_accumulator('inf_norm', p)
            self._add_accumulator('beta1_pow_acc', p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        moment = self._get_accumulator('moment', p)
        inf_norm = self._get_accumulator('inf_norm', p)
        b1p = self._get_accumulator('beta1_pow_acc', p)
        op = block.append_op(
            type='adamax',
            inputs={'Param': p, 'Grad': param_and_grad[1],
                    'LearningRate': self._create_param_lr(param_and_grad),
                    'Moment': moment, 'InfNorm': inf_norm, 'Beta1Pow': b1p},
            outputs={'ParamOut': p, 'MomentOut': moment,
                     'InfNormOut': inf_norm},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon},
            infer_shape=False)
        # bump beta1^t
        block.append_op(type='scale', inputs={'X': b1p},
                        outputs={'Out': b1p},
                        attrs={'scale': self._beta1, 'bias': 0.0,
                               'bias_after_scale': True},
                        infer_shape=False)
        return op


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super(DecayedAdagradOptimizer, self).__init__(
            learning_rate, regularization, name)
        self.type = 'decayed_adagrad'
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('moment', p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator('moment', param_and_grad[0])
        return block.append_op(
            type='decayed_adagrad',
            inputs={'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                    'Moment': moment,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': param_and_grad[0], 'MomentOut': moment},
            attrs={'decay': self._decay, 'epsilon': self._epsilon},
            infer_shape=False)


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1.0e-6, rho=0.95,
                 regularization=None, name=None):
        super(AdadeltaOptimizer, self).__init__(learning_rate,
                                                regularization, name)
        self.type = 'adadelta'
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('_avg_squared_grad', p)
            self._add_accumulator('_avg_squared_update', p)

    def _append_optimize_op(self, block, param_and_grad):
        g = self._get_accumulator('_avg_squared_grad', param_and_grad[0])
        u = self._get_accumulator('_avg_squared_update', param_and_grad[0])
        return block.append_op(
            type='adadelta',
            inputs={'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                    'AvgSquaredGrad': g, 'AvgSquaredUpdate': u},
            outputs={'ParamOut': param_and_grad[0], 'AvgSquaredGradOut': g,
                     'AvgSquaredUpdateOut': u},
            attrs={'epsilon': self._epsilon, 'rho': self._rho},
            infer_shape=False)


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1.0e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super(RMSPropOptimizer, self).__init__(learning_rate, regularization,
                                               name)
        self.type = 'rmsprop'
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('momentum', p)
            self._add_accumulator('mean_square', p)
            self._add_accumulator('mean_grad', p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator('momentum', param_and_grad[0])
        mean_square_acc = self._get_accumulator('mean_square',
                                                param_and_grad[0])
        mean_grad_acc = self._get_accumulator('mean_grad', param_and_grad[0])
        outputs = {'ParamOut': param_and_grad[0],
                   'MomentOut': momentum_acc,
                   'MeanSquareOut': mean_square_acc}
        inputs = {'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                  'Moment': momentum_acc, 'MeanSquare': mean_square_acc,
                  'LearningRate': self._create_param_lr(param_and_grad)}
        if self._centered:
            inputs['MeanGrad'] = mean_grad_acc
            outputs['MeanGradOut'] = mean_grad_acc
        return block.append_op(
            type='rmsprop', inputs=inputs, outputs=outputs,
            attrs={'epsilon': self._epsilon, 'decay': self._rho,
                   'momentum': self._momentum, 'centered': self._centered},
            infer_shape=False)


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super(FtrlOptimizer, self).__init__(learning_rate, regularization,
                                            name)
        self.type = 'ftrl'
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator('squared', p)
            self._add_accumulator('linear', p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator('squared', param_and_grad[0])
        lin = self._get_accumulator('linear', param_and_grad[0])
        return block.append_op(
            type='ftrl',
            inputs={'Param': param_and_grad[0], 'Grad': param_and_grad[1],
                    'SquaredAccumulator': sq, 'LinearAccumulator': lin,
                    'LearningRate': self._create_param_lr(param_and_grad)},
            outputs={'ParamOut': param_and_grad[0], 'SquaredAccumOut': sq,
                     'LinearAccumOut': lin},
            attrs={'l1': self._l1, 'l2': self._l2,
                   'lr_power': self._lr_power},
            infer_shape=False)


class ModelAverage(Optimizer):
    """Running parameter average with apply()/restore() context (parity:
    reference ModelAverage).  Accumulation ops run inside the train step;
    apply() swaps averaged params into the scope."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super(ModelAverage, self).__init__(0.0, regularization, name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        self._avg_vars = {}
        prog = default_main_program()
        block = prog.global_block()
        with op_role_guard(OpRole.Optimize):
            for param in prog.global_block().all_parameters():
                if not param.do_model_average:
                    continue
                acc = self._add_accumulator('sum', param)
                cnt = self._add_accumulator('cnt', param, dtype='float32',
                                            fill_value=0.0, shape=[1])
                block.append_op(type='elementwise_add',
                                inputs={'X': acc, 'Y': param},
                                outputs={'Out': acc}, attrs={'axis': -1},
                                infer_shape=False)
                block.append_op(type='increment', inputs={'X': cnt},
                                outputs={'Out': cnt},
                                attrs={'step': 1.0}, infer_shape=False)
                self._avg_vars[param.name] = (acc, cnt)
        self._backup = {}

    def apply(self, executor, need_restore=True):
        import contextlib
        from .core.executor import global_scope

        @contextlib.contextmanager
        def cm():
            scope = global_scope()
            self._backup = {}
            for pname, (acc, cnt) in self._avg_vars.items():
                self._backup[pname] = scope.get(pname)
                n = np.maximum(np.asarray(scope.get(cnt.name)), 1.0)
                scope.set(pname, np.asarray(scope.get(acc.name)) / n)
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)
        return cm()

    def restore(self, executor):
        from .core.executor import global_scope
        scope = global_scope()
        for pname, val in self._backup.items():
            scope.set(pname, val)
        self._backup = {}


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer
RMSProp = RMSPropOptimizer
