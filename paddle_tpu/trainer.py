"""Deprecated location (parity: reference fluid/trainer.py which forwards
to contrib) — use paddle_tpu.contrib.Trainer."""
from .contrib.trainer import (  # noqa: F401
    Trainer, BeginEpochEvent, EndEpochEvent, BeginStepEvent, EndStepEvent,
    CheckpointConfig)

__all__ = []
