"""Explicit collective ops inserted by the shard pass (core/passes/shard).

All three lower through `jax.lax.with_sharding_constraint`: the op's
`dst_spec` attr pins the GSPMD layout at that point of the program, and
XLA's SPMD partitioner emits the matching collective — an all-gather
when the constraint removes sharded axes, a dynamic-slice/all-to-all
when it moves them, and (for a constrained vjp cotangent) a
reduce-scatter.  The three TYPES are semantically distinct IR nodes so
the analyzer, pt_lint, perflab, and a human reading the optimized
program can see WHAT moves where:

  reshard        layout change of a live value (the materialized D018)
  all_gather     shard -> full layout rejoin (ZeRO param gathering)
  grad_allreduce the once-per-parameter gradient reduction point; its
                 dst_spec is the parameter's (possibly ZeRO-sharded)
                 spec, so a replicated dst is a plain all-reduce and a
                 sharded dst collapses all-reduce+scatter into one
                 reduce-scatter

Off-mesh (ctx.mesh is None — single-device executors, build-time shape
inference, const-fold evaluation) every kernel is the identity on the
GLOBAL value, which is exactly what makes sharded-vs-single-device runs
of the SAME optimized program bitwise comparable.

Attrs (all JSON-stable, round-tripping through program_to_desc):
  src_spec / dst_spec  spec_to_jsonable layout (nested lists)
  bytes                estimated per-device bytes moved, computed with
                       the SAME cost model as the D018 lint (arxiv
                       2112.01075) — tests pin the two equal
  param                (grad_allreduce) the parameter this reduction
                       belongs to
"""
from ..core.registry import register
from ..core.sharding import spec_from_jsonable, normalize_spec

__all__ = ['COLLECTIVE_OPS']

COLLECTIVE_OPS = ('reshard', 'all_gather', 'grad_allreduce')


def _constrain(ctx, x, dst_jsonable):
    mesh = getattr(ctx, 'mesh', None)
    if mesh is None:
        return x
    spec = normalize_spec(spec_from_jsonable(dst_jsonable)) or ()
    axes = set(mesh.axis_names)
    rank = len(getattr(x, 'shape', ()) or ())
    # degrade to identity rather than crash on a spec the mesh cannot
    # express (D019 names the bad axis statically; rank overflow is D017)
    entries = []
    for e in spec[:rank]:
        if e is None:
            entries.append(None)
        elif isinstance(e, str):
            entries.append(e if e in axes else None)
        else:
            sub = tuple(a for a in e if a in axes)
            entries.append(sub if len(sub) == len(e) else None)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*entries)))


@register('reshard')
def reshard(ctx, ins, attrs):
    return {'Out': _constrain(ctx, ins['X'], attrs.get('dst_spec'))}


@register('all_gather')
def all_gather(ctx, ins, attrs):
    return {'Out': _constrain(ctx, ins['X'], attrs.get('dst_spec'))}


@register('grad_allreduce')
def grad_allreduce(ctx, ins, attrs):
    return {'Out': _constrain(ctx, ins['X'], attrs.get('dst_spec'))}
