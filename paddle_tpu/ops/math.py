"""Elementwise / activation / reduction / linear-algebra ops.

Parity: reference paddle/fluid/operators/elementwise/*, activation_op.*,
reduce_op.*, matmul_op.*, mul_op.*, scale_op.*, cast_op.*, etc.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


# --------------------------------------------------------------- helpers

def _bcast_y(x, y, axis):
    """Fluid elementwise broadcast: y's shape must be a contiguous
    subsequence of x's; `axis` is where it aligns (-1 = align trailing).
    Reference: operators/elementwise/elementwise_op_function.h."""
    if x.shape == y.shape:
        return y
    if y.ndim == 0:
        return y
    ax = axis if axis >= 0 else x.ndim - y.ndim
    # trim trailing 1s of y (fluid allows y shape [N, 1])
    yshape = list(y.shape)
    while len(yshape) > 1 and yshape[-1] == 1 and \
            ax + len(yshape) > x.ndim:
        yshape = yshape[:-1]
    new_shape = [1] * ax + yshape + [1] * (x.ndim - ax - len(yshape))
    return y.reshape(new_shape)


def _ew(name, fn):
    @register(name)
    def impl(ctx, ins, attrs, fn=fn):
        x, y = ins['X'], ins['Y']
        y = _bcast_y(x, y, attrs.get('axis', -1))
        return {'Out': fn(x, y)}
    return impl


_ew('elementwise_add', lambda x, y: x + y)
_ew('elementwise_sub', lambda x, y: x - y)
_ew('elementwise_mul', lambda x, y: x * y)
_ew('elementwise_div', lambda x, y: x / y)
_ew('elementwise_max', jnp.maximum)
_ew('elementwise_min', jnp.minimum)
_ew('elementwise_pow', jnp.power)
_ew('elementwise_mod', jnp.mod)
_ew('elementwise_floordiv', jnp.floor_divide)


def _cmp(name, fn):
    @register(name)
    def impl(ctx, ins, attrs, fn=fn):
        x, y = ins['X'], ins['Y']
        y = _bcast_y(x, y, attrs.get('axis', -1))
        return {'Out': fn(x, y)}


_cmp('less_than', lambda x, y: x < y)
_cmp('less_equal', lambda x, y: x <= y)
_cmp('greater_than', lambda x, y: x > y)
_cmp('greater_equal', lambda x, y: x >= y)
_cmp('equal', lambda x, y: x == y)
_cmp('not_equal', lambda x, y: x != y)


def _logical(name, fn, binary=True):
    @register(name)
    def impl(ctx, ins, attrs, fn=fn, binary=binary):
        if binary:
            return {'Out': fn(ins['X'], ins['Y'])}
        return {'Out': fn(ins['X'])}


_logical('logical_and', jnp.logical_and)
_logical('logical_or', jnp.logical_or)
_logical('logical_xor', jnp.logical_xor)
_logical('logical_not', jnp.logical_not, binary=False)


# --------------------------------------------------------------- unary

def _unary(name, fn):
    @register(name)
    def impl(ctx, ins, attrs, fn=fn):
        return {'Out': fn(ins['X'])}
    return impl


_unary('sigmoid', jax.nn.sigmoid)
_unary('logsigmoid', jax.nn.log_sigmoid)
_unary('tanh', jnp.tanh)
_unary('tanh_shrink', lambda x: x - jnp.tanh(x))
_unary('exp', jnp.exp)
_unary('log', jnp.log)
_unary('sqrt', jnp.sqrt)
_unary('rsqrt', lax.rsqrt)
_unary('abs', jnp.abs)
_unary('ceil', jnp.ceil)
_unary('floor', jnp.floor)
_unary('cos', jnp.cos)
_unary('sin', jnp.sin)
_unary('round', jnp.round)
_unary('reciprocal', jnp.reciprocal)
_unary('square', jnp.square)
_unary('softplus', jax.nn.softplus)
_unary('softsign', jax.nn.soft_sign)
_unary('relu', jax.nn.relu)
_unary('sign', jnp.sign)
_unary('erf', lax.erf)


@register('relu6')
def relu6(ctx, ins, attrs):
    t = attrs.get('threshold', 6.0)
    return {'Out': jnp.clip(ins['X'], 0.0, t)}


@register('leaky_relu')
def leaky_relu(ctx, ins, attrs):
    a = attrs.get('alpha', 0.02)
    x = ins['X']
    return {'Out': jnp.where(x >= 0, x, a * x)}


@register('elu')
def elu(ctx, ins, attrs):
    a = attrs.get('alpha', 1.0)
    x = ins['X']
    return {'Out': jnp.where(x >= 0, x, a * (jnp.exp(x) - 1.0))}


@register('selu')
def selu(ctx, ins, attrs):
    scale = attrs.get('scale', 1.0507009873554805)
    alpha = attrs.get('alpha', 1.6732632423543772)
    x = ins['X']
    return {'Out': scale * jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1.0))}


@register('brelu')
def brelu(ctx, ins, attrs):
    return {'Out': jnp.clip(ins['X'], attrs.get('t_min', 0.0),
                            attrs.get('t_max', 24.0))}


@register('soft_relu')
def soft_relu(ctx, ins, attrs):
    t = attrs.get('threshold', 40.0)
    x = jnp.clip(ins['X'], -t, t)
    return {'Out': jnp.log1p(jnp.exp(x))}


@register('hard_sigmoid')
def hard_sigmoid(ctx, ins, attrs):
    slope = attrs.get('slope', 0.2)
    offset = attrs.get('offset', 0.5)
    return {'Out': jnp.clip(slope * ins['X'] + offset, 0.0, 1.0)}


@register('swish')
def swish(ctx, ins, attrs):
    beta = attrs.get('beta', 1.0)
    x = ins['X']
    return {'Out': x * jax.nn.sigmoid(beta * x)}


@register('stanh')
def stanh(ctx, ins, attrs):
    a = attrs.get('scale_a', 2.0 / 3.0)
    b = attrs.get('scale_b', 1.7159)
    return {'Out': b * jnp.tanh(a * ins['X'])}


@register('pow')
def pow_op(ctx, ins, attrs):
    return {'Out': jnp.power(ins['X'], attrs.get('factor', 1.0))}


@register('thresholded_relu')
def thresholded_relu(ctx, ins, attrs):
    t = attrs.get('threshold', 1.0)
    x = ins['X']
    return {'Out': jnp.where(x > t, x, 0.0)}


@register('hard_shrink')
def hard_shrink(ctx, ins, attrs):
    t = attrs.get('threshold', 0.5)
    x = ins['X']
    return {'Out': jnp.where(jnp.abs(x) > t, x, 0.0)}


@register('softshrink')
def softshrink(ctx, ins, attrs):
    lam = attrs.get('lambda', 0.5)
    x = ins['X']
    return {'Out': jnp.where(x > lam, x - lam,
                             jnp.where(x < -lam, x + lam, 0.0))}


@register('prelu')
def prelu(ctx, ins, attrs):
    x, alpha = ins['X'], ins['Alpha']
    mode = attrs.get('mode', 'all')
    if mode == 'channel':
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == 'all':
        alpha = alpha.reshape((1,) * x.ndim)
    return {'Out': jnp.where(x >= 0, x, alpha * x)}


@register('scale')
def scale(ctx, ins, attrs):
    s = attrs.get('scale', 1.0)
    b = attrs.get('bias', 0.0)
    x = ins['X']
    if attrs.get('bias_after_scale', True):
        out = x * s + jnp.asarray(b, x.dtype)
    else:
        out = (x + jnp.asarray(b, x.dtype)) * s
    # parity with reference scale_op: dtype is preserved (int stays int)
    return {'Out': out.astype(x.dtype)}


@register('clip')
def clip(ctx, ins, attrs):
    return {'Out': jnp.clip(ins['X'], attrs['min'], attrs['max'])}


@register('clip_by_norm')
def clip_by_norm(ctx, ins, attrs):
    x = ins['X']
    max_norm = attrs['max_norm']
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {'Out': x * scale}


@register('cast')
def cast(ctx, ins, attrs):
    from ..core.dtypes import jax_dtype
    return {'Out': ins['X'].astype(jax_dtype(attrs['out_dtype']))}


@register('cumsum')
def cumsum(ctx, ins, attrs):
    x = ins['X']
    axis = attrs.get('axis', -1)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get('exclusive', False):
        out = out - x
    if attrs.get('reverse', False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get('exclusive', False):
            out = out - x
    return {'Out': out}


# --------------------------------------------------------------- reduce

def _reduce(name, fn):
    @register(name)
    def impl(ctx, ins, attrs, fn=fn):
        x = ins['X']
        dim = attrs.get('dim', [0])
        keep = attrs.get('keep_dim', False)
        if attrs.get('reduce_all', False):
            out = fn(x, axis=None, keepdims=keep)
        else:
            dim = [dim] if isinstance(dim, int) else list(dim)
            dim = tuple(d % x.ndim for d in dim)
            out = fn(x, axis=dim, keepdims=keep)
        if out.ndim == 0:
            out = out.reshape(1)
        return {'Out': out}


_reduce('reduce_sum', jnp.sum)
_reduce('reduce_mean', jnp.mean)
_reduce('reduce_max', jnp.max)
_reduce('reduce_min', jnp.min)
_reduce('reduce_prod', jnp.prod)
_reduce('reduce_all', jnp.all)
_reduce('reduce_any', jnp.any)


@register('mean')
def mean(ctx, ins, attrs):
    # reference mean_op: full reduction, output shape [1]
    return {'Out': jnp.mean(ins['X']).reshape(1)}


@register('sum')
def sum_op(ctx, ins, attrs):
    xs = ins['X']
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {'Out': out}


# --------------------------------------------------------------- matmul

@register('matmul')
def matmul(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']
    tx, ty = attrs.get('transpose_X', False), attrs.get('transpose_Y', False)
    alpha = attrs.get('alpha', 1.0)
    if x.ndim == 1:
        x = x.reshape(1, -1)
    if y.ndim == 1:
        y = y.reshape(-1, 1)
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {'Out': out}


@register('mul')
def mul(ctx, ins, attrs):
    # reference mul_op: flatten both sides to 2-D then GEMM (maps straight
    # onto the MXU)
    x, y = ins['X'], ins['Y']
    xn = attrs.get('x_num_col_dims', 1)
    yn = attrs.get('y_num_col_dims', 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape(int(np.prod(xs[:xn])), -1)
    y2 = y.reshape(int(np.prod(ys[:yn])), -1)
    out = x2 @ y2
    return {'Out': out.reshape(xs[:xn] + ys[yn:])}


@register('bilinear_tensor_product')
def bilinear_tensor_product(ctx, ins, attrs):
    x, y, w = ins['X'], ins['Y'], ins['Weight']
    # w: [out_dim, dx, dy]
    out = jnp.einsum('bi,oij,bj->bo', x, w, y)
    if 'Bias' in ins:
        out = out + ins['Bias']
    return {'Out': out}


@register('cos_sim')
def cos_sim(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']
    xn = jnp.sqrt(jnp.sum(x * x, -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / (xn * yn + 1e-12)
    return {'Out': out, 'XNorm': xn, 'YNorm': yn}


@register('l2_normalize')
def l2_normalize(ctx, ins, attrs):
    x = ins['X']
    axis = attrs.get('axis', -1)
    eps = attrs.get('epsilon', 1e-12)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    norm = jnp.maximum(norm, eps)
    return {'Out': x / norm, 'Norm': norm}


@register('increment')
def increment(ctx, ins, attrs):
    x = ins['X']
    return {'Out': x + jnp.asarray(attrs.get('step', 1.0), x.dtype)}


@register('isfinite')
def isfinite(ctx, ins, attrs):
    return {'Out': jnp.all(jnp.isfinite(ins['X'])).reshape(1)}


@register('has_inf')
def has_inf(ctx, ins, attrs):
    return {'Out': jnp.any(jnp.isinf(ins['X'])).reshape(1)}


@register('has_nan')
def has_nan(ctx, ins, attrs):
    return {'Out': jnp.any(jnp.isnan(ins['X'])).reshape(1)}


@register('maxout')
def maxout(ctx, ins, attrs):
    x = ins['X']  # NCHW
    g = attrs['groups']
    n, c, h, w = x.shape
    return {'Out': x.reshape(n, c // g, g, h, w).max(axis=2)}


@register('fake_quantize_dequantize_abs_max')
def fake_quantize_dequantize_abs_max(ctx, ins, attrs):
    """QAT fake-quant: quantize to `bit_length` ints at abs-max scale and
    dequantize back, with a straight-through gradient.

    Parity: reference operators/fake_quantize_op (+contrib quantize
    transpiler semantics).  On TPU the quant/dequant pair stays in the one
    fused executable; the STE is `x + stop_grad(qdq(x) - x)`."""
    x = ins['X']
    bits = attrs.get('bit_length', 8)
    rmax = float(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(x))
    safe = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / safe * rmax), -rmax, rmax)
    qdq = q / rmax * safe
    out = x + lax.stop_gradient(qdq - x)
    return {'Out': out, 'OutScale': scale.reshape(1)}


@register('fake_quantize_dequantize_moving_average_abs_max')
def fake_quantize_dequantize_moving_average_abs_max(ctx, ins, attrs):
    """Activation fake-quant with a moving-average abs-max scale carried in
    a persistable state var (parity: reference moving_average_abs_max)."""
    x = ins['X']
    state = ins['InScale'].reshape(())
    bits = attrs.get('bit_length', 8)
    rate = attrs.get('moving_rate', 0.9)
    rmax = float(2 ** (bits - 1) - 1)
    cur = jnp.max(jnp.abs(x))
    new_state = jnp.where(state > 0, rate * state + (1 - rate) * cur, cur)
    safe = jnp.maximum(lax.stop_gradient(new_state), 1e-8)
    q = jnp.clip(jnp.round(x / safe * rmax), -rmax, rmax)
    qdq = q / rmax * safe
    out = x + lax.stop_gradient(qdq - x)
    return {'Out': out, 'OutScale': new_state.reshape(1)}


@register('quantize_dequantize_fixed_scale')
def quantize_dequantize_fixed_scale(ctx, ins, attrs):
    """Inference-time quantize/dequantize at a frozen scale (the trained
    moving-average abs-max recorded during QAT).  Emitted by
    contrib.quantize freeze_program so the frozen graph's activation
    numerics match what QAT simulated (ref freeze pass keeps
    quantize/dequantize pairs with recorded scales)."""
    x = ins['X']
    bits = attrs.get('bit_length', 8)
    rmax = float(2 ** (bits - 1) - 1)
    safe = max(float(attrs['scale']), 1e-8)
    q = jnp.clip(jnp.round(x / safe * rmax), -rmax, rmax)
    return {'Out': (q / rmax * safe).astype(x.dtype)}
