"""Shape/layout/indexing ops.

Parity: reference operators: reshape_op, transpose_op, concat_op, split_op,
stack_op, gather_op, scatter_op, slice_op, expand_op, pad_op, one_hot_op,
lookup_table_op, topk_op, argsort/arg_min_max, fill_constant*, assign, etc.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register
from ..core.dtypes import convert_dtype, jax_dtype


@register('reshape')
def reshape(ctx, ins, attrs):
    x = ins['X']
    shape = list(attrs['shape'])
    # fluid semantics: 0 -> copy input dim, -1 -> infer
    out_shape = []
    for i, d in enumerate(shape):
        if d == 0:
            out_shape.append(x.shape[i])
        else:
            out_shape.append(int(d))
    return {'Out': x.reshape(out_shape), 'XShape': None}


@register('squeeze')
def squeeze(ctx, ins, attrs):
    x = ins['X']
    axes = attrs.get('axes', [])
    if not axes:
        return {'Out': jnp.squeeze(x)}
    axes = tuple(a % x.ndim for a in axes)
    return {'Out': jnp.squeeze(x, axis=axes)}


@register('unsqueeze')
def unsqueeze(ctx, ins, attrs):
    x = ins['X']
    for a in sorted(attrs['axes']):
        x = jnp.expand_dims(x, a)
    return {'Out': x}


@register('transpose')
def transpose(ctx, ins, attrs):
    return {'Out': jnp.transpose(ins['X'], attrs['axis']), 'XShape': None}


@register('flatten')
def flatten(ctx, ins, attrs):
    x = ins['X']
    ax = attrs.get('axis', 1)
    lead = int(np.prod(x.shape[:ax])) if ax > 0 else 1
    return {'Out': x.reshape(lead, -1)}


@register('concat')
def concat(ctx, ins, attrs):
    xs = ins['X']
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return {'Out': jnp.concatenate(xs, axis=attrs.get('axis', 0))}


@register('split')
def split(ctx, ins, attrs):
    x = ins['X']
    axis = attrs.get('axis', 0)
    sections = attrs.get('sections', [])
    num = attrs.get('num', 0)
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {'Out': list(outs)}


@register('stack')
def stack(ctx, ins, attrs):
    xs = ins['X']
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return {'Y': jnp.stack(xs, axis=attrs.get('axis', 0))}


@register('unstack')
def unstack(ctx, ins, attrs):
    x = ins['X']
    axis = attrs.get('axis', 0)
    n = x.shape[axis]
    return {'Y': [jnp.squeeze(a, axis) for a in jnp.split(x, n, axis)]}


@register('expand')
def expand(ctx, ins, attrs):
    x = ins['X']
    times = attrs['expand_times']
    return {'Out': jnp.tile(x, times)}


@register('slice')
def slice_op(ctx, ins, attrs):
    x = ins['Input']
    axes = attrs['axes']
    starts = attrs['starts']
    ends = attrs['ends']
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = s + dim if s < 0 else s
        e = e + dim if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {'Out': x[tuple(idx)]}


@register('strided_slice')
def strided_slice(ctx, ins, attrs):
    x = ins['Input']
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs['axes'], attrs['starts'], attrs['ends'],
                           attrs['strides']):
        idx[a] = slice(s, e, st)
    return {'Out': x[tuple(idx)]}


@register('gather')
def gather(ctx, ins, attrs):
    index = ins['Index']
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    return {'Out': jnp.take(ins['X'], index, axis=0)}


@register('scatter')
def scatter(ctx, ins, attrs):
    x, ids, updates = ins['X'], ins['Ids'], ins['Updates']
    if ids.ndim == 2 and ids.shape[1] == 1:
        ids = ids[:, 0]
    if attrs.get('overwrite', True):
        return {'Out': x.at[ids].set(updates)}
    return {'Out': x.at[ids].add(updates)}


@register('gather_nd')
def gather_nd(ctx, ins, attrs):
    x, index = ins['X'], ins['Index']
    return {'Out': x[tuple(jnp.moveaxis(index, -1, 0))]}


@register('pad')
def pad(ctx, ins, attrs):
    x = ins['X']
    p = attrs['paddings']
    pad_width = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {'Out': jnp.pad(x, pad_width,
                           constant_values=attrs.get('pad_value', 0.0))}


@register('pad2d')
def pad2d(ctx, ins, attrs):
    x = ins['X']  # NCHW
    p = attrs['paddings']  # [top, bottom, left, right]
    mode = attrs.get('mode', 'constant')
    pw = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if attrs.get('data_format', 'NCHW') == 'NHWC':
        pw = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == 'constant':
        return {'Out': jnp.pad(x, pw,
                               constant_values=attrs.get('pad_value', 0.0))}
    jmode = {'reflect': 'reflect', 'edge': 'edge'}[mode]
    return {'Out': jnp.pad(x, pw, mode=jmode)}


@register('pad_constant_like')
def pad_constant_like(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']
    pw = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {'Out': jnp.pad(y, pw, constant_values=attrs.get('pad_value', 0.0))}


@register('one_hot')
def one_hot(ctx, ins, attrs):
    x = ins['X']
    depth = attrs['depth']
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return {'Out': jax.nn.one_hot(x, depth, dtype=jnp.float32)}


@register('lookup_table')
def lookup_table(ctx, ins, attrs):
    # reference lookup_table_op.cc: ids [..., 1] int64, W [V, D].
    # Large lookups route through the pallas DMA gather (ops/gather.py,
    # measured 1.7x over XLA's row gather); backward stays scatter-add.
    # Under a mesh the table may be GSPMD-sharded, which the kernel is
    # not partitioned for — multi-chip lowering stays on jnp.take.
    from .gather import embedding_gather
    w, ids = ins['W'], ins['Ids']
    padding_idx = attrs.get('padding_idx', -1)
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    idx = ids[..., 0] if squeeze_last else ids
    if getattr(ctx, 'mesh', None) is not None:
        out = jnp.take(w, idx, axis=0)
    else:
        out = embedding_gather(w, idx)
    if padding_idx is not None and padding_idx >= 0:
        mask = (idx != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return {'Out': out}


def _fill_value(value, dtype):
    """Normalize a fill value before it reaches jnp.full: a 64-bit numpy
    scalar (program serialization hands these back) or an out-of-range
    Python int would hit jax's x32 warn-and-truncate inside the trace.
    Narrow HERE with explicit C-style wraparound so the truncation is
    ours — same numerics, silent under warnings-as-error.  numpy >= 1.24
    raises its own RuntimeWarning on an overflowing astype, so the
    wraparound cast runs under errstate suppression."""
    try:
        with np.errstate(over='ignore', invalid='ignore'):
            return np.asarray(value).astype(dtype)
    except (OverflowError, TypeError, ValueError):
        return value


@register('fill_constant')
def fill_constant(ctx, ins, attrs):
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    shape = [int(d) for d in attrs['shape']]
    return {'Out': jnp.full(shape, _fill_value(attrs['value'], dtype),
                            dtype=dtype)}


@register('fill_constant_batch_size_like')
def fill_constant_batch_size_like(ctx, ins, attrs):
    ref = ins['Input']
    shape = list(attrs['shape'])
    in_idx = attrs.get('input_dim_idx', 0)
    out_idx = attrs.get('output_dim_idx', 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    return {'Out': jnp.full(shape, _fill_value(attrs['value'], dtype),
                            dtype=dtype)}


@register('fill_zeros_like')
def fill_zeros_like(ctx, ins, attrs):
    return {'Out': jnp.zeros_like(ins['X'])}


@register('assign')
def assign(ctx, ins, attrs):
    return {'Out': ins['X']}


@register('assign_value')
def assign_value(ctx, ins, attrs):
    dtype = convert_dtype(attrs.get('dtype', 'float32'))
    vals = np.array(attrs['values'], dtype=dtype).reshape(attrs['shape'])
    return {'Out': jnp.asarray(vals)}


@register('shape')
def shape_op(ctx, ins, attrs):
    return {'Out': jnp.array(ins['Input'].shape, dtype=jnp.int32)}


@register('top_k')
def top_k(ctx, ins, attrs):
    x = ins['X']
    k = attrs['k']
    vals, idx = lax.top_k(x, k)
    return {'Out': vals, 'Indices': idx.astype(jax_dtype('int64'))}


@register('arg_max')
def arg_max(ctx, ins, attrs):
    return {'Out': jnp.argmax(ins['X'], axis=attrs.get('axis', -1))
            .astype(jax_dtype('int64'))}


@register('arg_min')
def arg_min(ctx, ins, attrs):
    return {'Out': jnp.argmin(ins['X'], axis=attrs.get('axis', -1))
            .astype(jax_dtype('int64'))}


@register('argsort')
def argsort(ctx, ins, attrs):
    x = ins['X']
    axis = attrs.get('axis', -1)
    idx = jnp.argsort(x, axis=axis)
    return {'Out': jnp.sort(x, axis=axis), 'Indices': idx.astype(jax_dtype('int64'))}


@register('reverse')
def reverse(ctx, ins, attrs):
    x = ins['X']
    return {'Out': jnp.flip(x, axis=tuple(a % x.ndim for a in attrs['axis']))}


@register('multiplex')
def multiplex(ctx, ins, attrs):
    ids = ins['Ids']  # [B, 1] int
    xs = jnp.stack(ins['X'], axis=0)  # [n, B, D]
    idx = ids[:, 0]
    return {'Out': xs[idx, jnp.arange(xs.shape[1])]}


@register('expand_as')
def expand_as(ctx, ins, attrs):
    x, y = ins['X'], ins['target_tensor']
    reps = [t // s for s, t in zip(x.shape, y.shape)]
    return {'Out': jnp.tile(x, reps)}


@register('label_smooth')
def label_smooth(ctx, ins, attrs):
    x = ins['X']
    eps = attrs.get('epsilon', 0.0)
    if 'PriorDist' in ins:
        prior = ins['PriorDist']
        return {'Out': (1 - eps) * x + eps * prior}
    return {'Out': (1 - eps) * x + eps / x.shape[-1]}


@register('space_to_depth')
def space_to_depth(ctx, ins, attrs):
    x = ins['X']  # NCHW
    bs = attrs['blocksize']
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return {'Out': x.reshape(n, c * bs * bs, h // bs, w // bs)}


@register('shuffle_channel')
def shuffle_channel(ctx, ins, attrs):
    x = ins['X']
    g = attrs['group']
    n, c, h, w = x.shape
    return {'Out': x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
            .reshape(n, c, h, w)}


@register('where_index')
def where_index(ctx, ins, attrs):
    """Coordinates of nonzero elements (parity: reference
    where_index_op — its output shape is data-dependent, which XLA
    cannot compile).  TPU-native fixed-K contract (the multiclass_nms
    pattern): attr `max_count` (default: condition size, always exact)
    bounds the result; outputs are Out int64 [K, rank] with valid rows
    FIRST in row-major scan order and -1 padding after, plus Count
    int64 [1] with the true number of nonzeros.  Count > max_count
    means truncation: callers picking a smaller K own that bound."""
    cond = ins['Condition']
    rank = max(cond.ndim, 1)
    flat = (cond != 0).reshape(-1)
    n = flat.shape[0]
    K = int(attrs.get('max_count') or n)
    pos = jnp.arange(n)
    # stable compaction: valid positions first, in scan order
    order = jnp.argsort(jnp.where(flat, pos, pos + n))[:K]
    valid = jnp.arange(K) < flat.sum()
    coords = []
    rem = order
    for d in range(rank - 1, -1, -1):
        dim = cond.shape[d] if cond.ndim else 1
        coords.append(rem % dim)
        rem = rem // dim
    out = jnp.stack(coords[::-1], axis=1).astype(jax_dtype('int64'))
    out = jnp.where(valid[:, None], out, -1)
    return {'Out': out, 'Count': flat.sum().reshape(1).astype(jax_dtype('int64'))}


@register('py_func')
def py_func_op(ctx, ins, attrs):
    """Host-callback op (parity: reference py_func_op.cc).  The Python
    callable runs on the host inside the jitted step via
    jax.pure_callback; backward_func becomes a custom VJP that also runs
    as a host callback.  Callables must be pure (XLA may re-run them)."""
    xs = ins['X']
    xs = list(xs) if isinstance(xs, (list, tuple)) else [xs]
    func = attrs['func']
    bwd = attrs.get('backward_func')
    # canonicalize (int64 -> int32 etc. without x64), like jnp ops do
    dtypes = [jax.dtypes.canonicalize_dtype(np.dtype(convert_dtype(d)))
              for d in attrs['out_dtypes']]
    batch = xs[0].shape[0] if xs and getattr(xs[0], 'ndim', 0) else 1

    def _static_shape(shp):
        # -1 means "the batch dim" and is only meaningful at axis 0;
        # pure_callback needs every other dim static at trace time.
        out = []
        for ax, s in enumerate(shp):
            if s == -1:
                if ax != 0:
                    raise ValueError(
                        'py_func out_shape %r: -1 is only supported at '
                        'axis 0 (the batch dim); XLA needs static shapes '
                        'for every other dim' % (list(shp),))
                out.append(batch)
            else:
                out.append(s)
        return tuple(out)

    result = tuple(
        jax.ShapeDtypeStruct(_static_shape(shp), d)
        for shp, d in zip(attrs['out_shapes'], dtypes))

    def host_fwd(*arrays):
        r = func(*[np.asarray(a) for a in arrays])
        r = list(r) if isinstance(r, (list, tuple)) else [r]
        return tuple(np.asarray(v).astype(d) for v, d in zip(r, dtypes))

    if bwd is None:
        # reference semantics without backward_func: no grad propagates
        outs = jax.pure_callback(
            host_fwd, result, *[lax.stop_gradient(x) for x in xs])
        return {'Out': list(outs)}

    skip = set(attrs.get('skip_bwd_idx', ()))

    float_pos = [i for i, x in enumerate(xs)
                 if jnp.issubdtype(x.dtype, jnp.floating)]
    float_xs = [xs[i] for i in float_pos]

    def host_bwd(*arrays):
        # backward_func returns one grad per input (reference contract);
        # only the float ones are consumed
        r = bwd(*[np.asarray(a) for a in arrays])
        r = list(r) if isinstance(r, (list, tuple)) else [r]
        return tuple(np.asarray(r[i]).astype(xs[i].dtype)
                     for i in float_pos)

    @jax.custom_vjp
    def call(*args):
        return jax.pure_callback(host_fwd, result, *args)

    def call_fwd(*args):
        outs = jax.pure_callback(host_fwd, result, *args)
        return outs, (args, outs)

    def call_bwd(res, g):
        args, outs = res
        bwd_in = [a for i, a in enumerate(list(args) + list(outs))
                  if i not in skip] + list(g)
        dx_shape = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype)
                         for x in float_xs)
        dxs = list(jax.pure_callback(host_bwd, dx_shape, *bwd_in))
        full = []
        for x in args:
            if jnp.issubdtype(x.dtype, jnp.floating):
                full.append(dxs.pop(0))
            else:  # integer inputs get symbolic-zero cotangents
                full.append(np.zeros(x.shape, jax.dtypes.float0))
        return tuple(full)

    call.defvjp(call_fwd, call_bwd)
    return {'Out': list(call(*xs))}


@register('hash')
def hash_op(ctx, ins, attrs):
    x = ins['X'].astype(jax_dtype('int64'))
    num_hash = attrs.get('num_hash', 1)
    mod_by = attrs.get('mod_by', 100000007)
    outs = []
    for i in range(num_hash):
        h = jnp.sum(x * jnp.asarray(1000003 ** (i + 1) &
                    0x7fffffff, jax_dtype('int64')), axis=-1,
                    keepdims=True)
        outs.append(jnp.abs(h) % mod_by)
    return {'Out': jnp.concatenate(outs, axis=-1)}


@register('uniform_random_batch_size_like')
def uniform_random_batch_size_like(ctx, ins, attrs):
    ref = ins['Input']
    shape = list(attrs['shape'])
    shape[attrs.get('output_dim_idx', 0)] = \
        ref.shape[attrs.get('input_dim_idx', 0)]
    # jax_dtype, not convert_dtype: the astype happens INSIDE the trace,
    # and asking for a 64-bit dtype there warn-and-truncates per trace
    dtype = jax_dtype(attrs.get('dtype', 'float32'))
    key = ctx.rng()
    return {'Out': jax.random.uniform(
        key, shape, dtype=jnp.float32,
        minval=attrs.get('min', -1.0),
        maxval=attrs.get('max', 1.0)).astype(dtype)}


@register('gaussian_random_batch_size_like')
def gaussian_random_batch_size_like(ctx, ins, attrs):
    ref = ins['Input']
    shape = list(attrs['shape'])
    shape[attrs.get('output_dim_idx', 0)] = \
        ref.shape[attrs.get('input_dim_idx', 0)]
    dtype = jax_dtype(attrs.get('dtype', 'float32'))  # in-trace astype
    key = ctx.rng()
    out = attrs.get('mean', 0.0) + attrs.get('std', 1.0) * \
        jax.random.normal(key, shape, dtype=jnp.float32)
    return {'Out': out.astype(dtype)}


@register('print')
def print_op(ctx, ins, attrs):
    import jax
    x = ins['X']
    jax.debug.print(attrs.get('message', '') + ' {}', x)
    return {'Out': x}


@register('is_empty')
def is_empty_op(ctx, ins, attrs):
    return {'Out': jnp.asarray(ins['X'].size == 0)}


@register('split_lod_tensor')
def split_lod_tensor(ctx, ins, attrs):
    """IfElse row split (ref operators/split_lod_tensor_op.cc).  The
    reference compacts rows into two shorter batches; under static-shape
    XLA both branch bodies run the full batch and merge_lod_tensor picks
    rows, so the 'split' is a passthrough."""
    x = ins['X']
    return {'OutTrue': x, 'OutFalse': x}


@register('merge_lod_tensor')
def merge_lod_tensor(ctx, ins, attrs):
    """IfElse row merge (ref operators/merge_lod_tensor_op.cc): row i of
    the output comes from InTrue where Mask[i] else InFalse — one fused
    select."""
    t, f, m = ins['InTrue'], ins['InFalse'], ins['Mask']
    m = m.reshape((-1,) + (1,) * (t.ndim - 1)).astype(bool)
    return {'Out': jnp.where(m, t, f)}


@register('batched_gather')
def batched_gather(ctx, ins, attrs):
    """Per-row gather: X [N, M, ...], Index [N, K] -> [N, K, ...]
    (rows of Index select rows of the matching batch element)."""
    x, idx = ins['X'], ins['Index']
    return {'Out': jnp.take_along_axis(
        x, idx.astype(jnp.int32).reshape(idx.shape[0], idx.shape[1],
                                         *([1] * (x.ndim - 2))), axis=1)}
