"""Optimizer update ops — pure functional updates fused into the train step.

Parity: reference paddle/fluid/operators/optimizers/ (sgd_op, momentum_op,
adam_op, adagrad_op, adamax_op, adadelta_op, rmsprop_op, ftrl_op,
decayed_adagrad_op, lars_momentum_op).  The whole update runs inside the one
jitted train-step executable with parameter buffers donated, so updates are
in-place on device.
"""
import jax.numpy as jnp

from ..core.registry import register


def _lr(ins):
    lr = ins['LearningRate']
    return lr.reshape(()) if hasattr(lr, 'reshape') else lr


@register('sgd')
def sgd(ctx, ins, attrs):
    return {'ParamOut': ins['Param'] - _lr(ins) * ins['Grad']}


@register('momentum')
def momentum(ctx, ins, attrs):
    p, g, v = ins['Param'], ins['Grad'], ins['Velocity']
    mu = attrs.get('mu', 0.9)
    lr = _lr(ins)
    v_new = mu * v + g
    if attrs.get('use_nesterov', False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {'ParamOut': p_new, 'VelocityOut': v_new}


@register('lars_momentum')
def lars_momentum(ctx, ins, attrs):
    p, g, v = ins['Param'], ins['Grad'], ins['Velocity']
    mu = attrs.get('mu', 0.9)
    coeff = attrs.get('lars_coeff', 0.001)
    decay = attrs.get('lars_weight_decay', 0.0005)
    lr = _lr(ins)
    pn = jnp.sqrt(jnp.sum(jnp.square(p)))
    gn = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * coeff * pn / (gn + decay * pn + 1e-12)
    v_new = mu * v + local_lr * (g + decay * p)
    return {'ParamOut': p - v_new, 'VelocityOut': v_new}


@register('adam')
def adam(ctx, ins, attrs):
    p, g = ins['Param'], ins['Grad']
    m1, m2 = ins['Moment1'], ins['Moment2']
    b1p, b2p = ins['Beta1Pow'], ins['Beta2Pow']
    b1 = attrs.get('beta1', 0.9)
    b2 = attrs.get('beta2', 0.999)
    eps = attrs.get('epsilon', 1e-8)
    lr = _lr(ins)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    pn = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    return {'ParamOut': pn, 'Moment1Out': m1n, 'Moment2Out': m2n,
            'Beta1PowOut': b1p * b1, 'Beta2PowOut': b2p * b2}


@register('adamax')
def adamax(ctx, ins, attrs):
    p, g = ins['Param'], ins['Grad']
    m, u = ins['Moment'], ins['InfNorm']
    b1p = ins['Beta1Pow']
    b1 = attrs.get('beta1', 0.9)
    b2 = attrs.get('beta2', 0.999)
    eps = attrs.get('epsilon', 1e-8)
    lr = _lr(ins)
    mn = b1 * m + (1 - b1) * g
    un = jnp.maximum(b2 * u, jnp.abs(g))
    pn = p - (lr / (1 - b1p.reshape(()))) * mn / (un + eps)
    return {'ParamOut': pn, 'MomentOut': mn, 'InfNormOut': un}


@register('adagrad')
def adagrad(ctx, ins, attrs):
    p, g, mom = ins['Param'], ins['Grad'], ins['Moment']
    eps = attrs.get('epsilon', 1e-6)
    mn = mom + jnp.square(g)
    return {'ParamOut': p - _lr(ins) * g / (jnp.sqrt(mn) + eps),
            'MomentOut': mn}


@register('decayed_adagrad')
def decayed_adagrad(ctx, ins, attrs):
    p, g, mom = ins['Param'], ins['Grad'], ins['Moment']
    decay = attrs.get('decay', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    mn = decay * mom + (1 - decay) * jnp.square(g)
    return {'ParamOut': p - _lr(ins) * g / (jnp.sqrt(mn) + eps),
            'MomentOut': mn}


@register('adadelta')
def adadelta(ctx, ins, attrs):
    p, g = ins['Param'], ins['Grad']
    avg_sq_g, avg_sq_u = ins['AvgSquaredGrad'], ins['AvgSquaredUpdate']
    rho = attrs.get('rho', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    gn = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (gn + eps)) * g
    un = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {'ParamOut': p + update, 'AvgSquaredGradOut': gn,
            'AvgSquaredUpdateOut': un}


@register('rmsprop')
def rmsprop(ctx, ins, attrs):
    p, g = ins['Param'], ins['Grad']
    ms, mom = ins['MeanSquare'], ins['Moment']
    rho = attrs.get('decay', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    mu = attrs.get('momentum', 0.0)
    lr = _lr(ins)
    msn = rho * ms + (1 - rho) * jnp.square(g)
    if attrs.get('centered', False):
        mg = ins['MeanGrad']
        mgn = rho * mg + (1 - rho) * g
        momn = mu * mom + lr * g / jnp.sqrt(msn - jnp.square(mgn) + eps)
        return {'ParamOut': p - momn, 'MeanSquareOut': msn,
                'MomentOut': momn, 'MeanGradOut': mgn}
    momn = mu * mom + lr * g / jnp.sqrt(msn + eps)
    return {'ParamOut': p - momn, 'MeanSquareOut': msn, 'MomentOut': momn}


@register('ftrl')
def ftrl(ctx, ins, attrs):
    p, g = ins['Param'], ins['Grad']
    sq, lin = ins['SquaredAccumulator'], ins['LinearAccumulator']
    l1 = attrs.get('l1', 0.0) + 1e-10
    l2 = attrs.get('l2', 0.0) + 1e-10
    power = attrs.get('lr_power', -0.5)
    lr = _lr(ins)
    new_sq = sq + jnp.square(g)
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    new_lin = lin + g - sigma * p
    if power == -0.5:
        denom = l2 + jnp.sqrt(new_sq) / lr
    else:
        denom = l2 + jnp.power(new_sq, -power) / lr
    pn = jnp.where(jnp.abs(new_lin) > l1,
                   (l1 * jnp.sign(new_lin) - new_lin) / denom,
                   jnp.zeros_like(p))
    return {'ParamOut': pn, 'SquaredAccumOut': new_sq,
            'LinearAccumOut': new_lin}
