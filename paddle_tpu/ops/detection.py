"""Detection ops (SSD / YOLO / RCNN family).

Parity: reference paddle/fluid/operators/detection/.  Batched, fixed-shape
formulations (XLA-friendly): variable-count outputs (NMS survivors, proposal
lists) are returned fixed-size with validity masks/scores rather than ragged
LoD outputs.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register


@register('iou_similarity')
def iou_similarity(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']  # [N,4], [M,4] xyxy

    def area(b):
        return jnp.maximum(b[..., 2] - b[..., 0], 0) * \
            jnp.maximum(b[..., 3] - b[..., 1], 0)
    xi = jnp.maximum(x[:, None, 0], y[None, :, 0])
    yi = jnp.maximum(x[:, None, 1], y[None, :, 1])
    xa = jnp.minimum(x[:, None, 2], y[None, :, 2])
    ya = jnp.minimum(x[:, None, 3], y[None, :, 3])
    inter = jnp.maximum(xa - xi, 0) * jnp.maximum(ya - yi, 0)
    union = area(x)[:, None] + area(y)[None, :] - inter
    return {'Out': inter / jnp.maximum(union, 1e-10)}


@register('box_coder')
def box_coder(ctx, ins, attrs):
    prior, tb = ins['PriorBox'], ins['TargetBox']
    pvar = ins.get('PriorBoxVar')
    code_type = attrs.get('code_type', 'encode_center_size')
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    if pvar is None:
        pvar = jnp.ones((prior.shape[0], 4), prior.dtype)
    if code_type.startswith('encode'):
        tw = tb[:, None, 2] - tb[:, None, 0]
        th = tb[:, None, 3] - tb[:, None, 1]
        tcx = tb[:, None, 0] + 0.5 * tw
        tcy = tb[:, None, 1] + 0.5 * th
        out = jnp.stack([
            (tcx - pcx[None]) / pw[None] / pvar[None, :, 0],
            (tcy - pcy[None]) / ph[None] / pvar[None, :, 1],
            jnp.log(jnp.maximum(tw / pw[None], 1e-10)) / pvar[None, :, 2],
            jnp.log(jnp.maximum(th / ph[None], 1e-10)) / pvar[None, :, 3],
        ], axis=-1)
    else:
        # decode: tb [N, M, 4] deltas
        dcx = tb[..., 0] * pvar[None, :, 0] * pw[None] + pcx[None]
        dcy = tb[..., 1] * pvar[None, :, 1] * ph[None] + pcy[None]
        dw = jnp.exp(tb[..., 2] * pvar[None, :, 2]) * pw[None]
        dh = jnp.exp(tb[..., 3] * pvar[None, :, 3]) * ph[None]
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2, dcy + dh / 2], axis=-1)
    return {'OutputBox': out}


@register('prior_box')
def prior_box(ctx, ins, attrs):
    feat, image = ins['Input'], ins['Image']  # NCHW
    min_sizes = attrs['min_sizes']
    max_sizes = attrs.get('max_sizes', [])
    ars_attr = attrs.get('aspect_ratios', [1.0])
    flip = attrs.get('flip', False)
    step_w = attrs.get('step_w', 0.0)
    step_h = attrs.get('step_h', 0.0)
    offset = attrs.get('offset', 0.5)
    clip = attrs.get('clip', False)
    variances = attrs.get('variances', [0.1, 0.1, 0.2, 0.2])
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw = step_w or img_w / W
    sh = step_h or img_h / H
    ars = [1.0]
    for a in ars_attr:
        if abs(a - 1.0) > 1e-6:
            ars.append(a)
            if flip:
                ars.append(1.0 / a)
    boxes = []
    for ms in min_sizes:
        for a in ars:
            boxes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
    for ms, mxs in zip(min_sizes, max_sizes or []):
        boxes.append((np.sqrt(ms * mxs), np.sqrt(ms * mxs)))
    cx = (jnp.arange(W) + offset) * sw
    cy = (jnp.arange(H) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing='ij')
    whs = jnp.asarray(boxes)  # [P, 2]
    out = jnp.stack([
        (gx[..., None] - whs[None, None, :, 0] / 2) / img_w,
        (gy[..., None] - whs[None, None, :, 1] / 2) / img_h,
        (gx[..., None] + whs[None, None, :, 0] / 2) / img_w,
        (gy[..., None] + whs[None, None, :, 1] / 2) / img_h,
    ], axis=-1)  # [H, W, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {'Boxes': out, 'Variances': var}


@register('density_prior_box')
def density_prior_box(ctx, ins, attrs):
    feat, image = ins['Input'], ins['Image']
    fixed_sizes = attrs.get('fixed_sizes', [])
    fixed_ratios = attrs.get('fixed_ratios', [])
    densities = attrs.get('densities', [])
    offset = attrs.get('offset', 0.5)
    variances = attrs.get('variances', [0.1, 0.1, 0.2, 0.2])
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw, sh = img_w / W, img_h / H
    boxes = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    boxes.append((bw, bh,
                                  -size / 2 + step / 2 + dj * step,
                                  -size / 2 + step / 2 + di * step))
    cx = (jnp.arange(W) + offset) * sw
    cy = (jnp.arange(H) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing='ij')
    arr = jnp.asarray(boxes)  # [P, 4] = bw, bh, ox, oy
    ctrx = gx[..., None] + arr[None, None, :, 2]
    ctry = gy[..., None] + arr[None, None, :, 3]
    out = jnp.stack([
        (ctrx - arr[None, None, :, 0] / 2) / img_w,
        (ctry - arr[None, None, :, 1] / 2) / img_h,
        (ctrx + arr[None, None, :, 0] / 2) / img_w,
        (ctry + arr[None, None, :, 1] / 2) / img_h,
    ], axis=-1)
    out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {'Boxes': out, 'Variances': var}


@register('anchor_generator')
def anchor_generator(ctx, ins, attrs):
    feat = ins['Input']
    anchor_sizes = attrs['anchor_sizes']
    ars = attrs['aspect_ratios']
    stride = attrs['stride']
    offset = attrs.get('offset', 0.5)
    variances = attrs.get('variances', [0.1, 0.1, 0.2, 0.2])
    H, W = feat.shape[2], feat.shape[3]
    whs = []
    for s in anchor_sizes:
        for a in ars:
            whs.append((s * np.sqrt(a), s / np.sqrt(a)))
    cx = (jnp.arange(W) + offset) * stride[0]
    cy = (jnp.arange(H) + offset) * stride[1]
    gy, gx = jnp.meshgrid(cy, cx, indexing='ij')
    arr = jnp.asarray(whs)
    out = jnp.stack([
        gx[..., None] - arr[None, None, :, 0] / 2,
        gy[..., None] - arr[None, None, :, 1] / 2,
        gx[..., None] + arr[None, None, :, 0] / 2,
        gy[..., None] + arr[None, None, :, 1] / 2,
    ], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {'Anchors': out, 'Variances': var}


@register('yolov3_loss')
def yolov3_loss(ctx, ins, attrs):
    x = ins['X']  # [N, C, H, W]
    gt_box = ins['GTBox']  # [N, B, 4] cx cy w h (normalized)
    gt_label = ins['GTLabel']  # [N, B]
    anchors = attrs['anchors']
    anchor_mask = attrs.get('anchor_mask', list(range(len(anchors) // 2)))
    class_num = attrs['class_num']
    downsample = attrs.get('downsample_ratio', 32)
    N, C, H, W = x.shape
    na = len(anchor_mask)
    an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    amask = jnp.asarray(anchor_mask)
    pred = x.reshape(N, na, 5 + class_num, H, W)
    px = jax.nn.sigmoid(pred[:, :, 0])
    py = jax.nn.sigmoid(pred[:, :, 1])
    pw, ph = pred[:, :, 2], pred[:, :, 3]
    pobj = pred[:, :, 4]
    pcls = pred[:, :, 5:]
    input_size = downsample * H
    # build targets: for each gt, responsible cell + best anchor
    gtx, gty = gt_box[..., 0], gt_box[..., 1]
    gtw, gth = gt_box[..., 2], gt_box[..., 3]
    gi = jnp.clip((gtx * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gty * H).astype(jnp.int32), 0, H - 1)
    valid = (gtw > 0)
    # best anchor by IoU of (w, h)
    aw = an[:, 0] / input_size
    ah = an[:, 1] / input_size
    inter = jnp.minimum(gtw[..., None], aw) * jnp.minimum(gth[..., None], ah)
    union = gtw[..., None] * gth[..., None] + aw * ah - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N, B]
    in_mask = jnp.any(best[..., None] == amask, axis=-1) & valid
    tx = gtx * W - gi
    ty = gty * H - gj
    local_a = jnp.argmax(best[..., None] == amask, axis=-1)
    sel_aw = jnp.take(aw, best)
    sel_ah = jnp.take(ah, best)
    tw = jnp.log(jnp.maximum(gtw / jnp.maximum(sel_aw, 1e-10), 1e-10))
    th = jnp.log(jnp.maximum(gth / jnp.maximum(sel_ah, 1e-10), 1e-10))
    scale = 2.0 - gtw * gth
    bidx = jnp.arange(N)[:, None]

    def gather_pred(p):
        return p[bidx, local_a, gj, gi]
    mf = in_mask.astype(x.dtype)
    loss_xy = jnp.sum(mf * scale * (
        jnp.square(gather_pred(px) - tx) + jnp.square(gather_pred(py) - ty)),
        axis=1)
    loss_wh = jnp.sum(mf * scale * (
        jnp.square(gather_pred(pw) - tw) + jnp.square(gather_pred(ph) - th)),
        axis=1)
    obj_target = jnp.zeros((N, na, H, W)).at[bidx, local_a, gj, gi].max(mf)
    bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(
        jnp.exp(-jnp.abs(z)))
    loss_obj = jnp.sum(bce(pobj, obj_target), axis=(1, 2, 3))
    cls_t = jax.nn.one_hot(gt_label, class_num, dtype=x.dtype)
    pc = pcls[bidx, local_a, :, gj, gi]
    loss_cls = jnp.sum(mf[..., None] * bce(pc, cls_t), axis=(1, 2))
    return {'Loss': loss_xy + loss_wh + loss_obj + loss_cls}


@register('polygon_box_transform')
def polygon_box_transform(ctx, ins, attrs):
    x = ins['Input']  # [N, geo, H, W]
    n, g, h, w = x.shape
    gy, gx = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing='ij')
    out = x.at[:, 0::2].set(gx[None, None] * 4.0 - x[:, 0::2])
    out = out.at[:, 1::2].set(gy[None, None] * 4.0 - out[:, 1::2])
    return {'Output': out}


def _nms_fixed(boxes, scores, iou_thresh, max_out):
    """Fixed-size NMS via iterative suppression (lax.fori-friendly).
    Returns (keep, valid): once candidates are exhausted, argmax over
    the all -inf scores would re-emit index 0 — `valid` marks the slots
    that selected a real (still-unsuppressed) box, so callers never
    duplicate the top box into the padding slots."""
    def body(i, state):
        sc, keep, valid = state
        best = jnp.argmax(sc)
        ok = sc[best] > -jnp.inf
        keep = keep.at[i].set(best)
        valid = valid.at[i].set(ok)
        bb = boxes[best]
        xi = jnp.maximum(boxes[:, 0], bb[0])
        yi = jnp.maximum(boxes[:, 1], bb[1])
        xa = jnp.minimum(boxes[:, 2], bb[2])
        ya = jnp.minimum(boxes[:, 3], bb[3])
        inter = jnp.maximum(xa - xi, 0) * jnp.maximum(ya - yi, 0)
        area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
            jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
        ab = jnp.maximum(bb[2] - bb[0], 0) * jnp.maximum(bb[3] - bb[1], 0)
        iou = inter / jnp.maximum(area + ab - inter, 1e-10)
        sc = jnp.where(iou > iou_thresh, -jnp.inf, sc)
        sc = sc.at[best].set(-jnp.inf)
        return sc, keep, valid
    keep0 = jnp.zeros((max_out,), jnp.int32)
    valid0 = jnp.zeros((max_out,), jnp.bool_)
    _, keep, valid = jax.lax.fori_loop(0, max_out, body,
                                       (scores, keep0, valid0))
    return keep, valid


@register('multiclass_nms')
def multiclass_nms(ctx, ins, attrs):
    """Detection output with per-class NMS; fixed-size [N, keep, 6] output
    (label, score, x1, y1, x2, y2), invalid rows get label -1."""
    bboxes, scores = ins['BBoxes'], ins['Scores']
    # bboxes [N, M, 4]; scores [N, C, M]
    score_thresh = attrs.get('score_threshold', 0.01)
    nms_thresh = attrs.get('nms_threshold', 0.3)
    keep_top_k = attrs.get('keep_top_k', 100)
    background = attrs.get('background_label', 0)
    if keep_top_k <= 0:
        keep_top_k = 100
    N, C, M = scores.shape

    def per_image(box, sc):
        outs = []
        for c in range(C):
            if c == background:  # reference skips the background class
                continue
            s = jnp.where(sc[c] >= score_thresh, sc[c], -jnp.inf)
            k = min(keep_top_k, M)
            keep, ok = _nms_fixed(box, s, nms_thresh, k)
            kept_s = jnp.take(s, keep)
            kept_b = jnp.take(box, keep, axis=0)
            lab = jnp.where(ok, float(c), -1.0)
            outs.append(jnp.concatenate(
                [lab[:, None], jnp.where(ok, kept_s, 0.0)[:, None],
                 jnp.where(ok[:, None], kept_b, 0.0)], axis=1))
        if not outs:  # only the background class exists
            return jnp.zeros((keep_top_k, 6)).at[:, 0].set(-1.0)
        allc = jnp.concatenate(outs, axis=0)
        if allc.shape[0] < keep_top_k:  # honor the fixed [keep, 6] shape
            pad = jnp.zeros((keep_top_k - allc.shape[0], 6), allc.dtype)
            allc = jnp.concatenate([allc, pad.at[:, 0].set(-1.0)], axis=0)
        # invalid rows sort last regardless of their (zeroed) score
        order = jnp.argsort(jnp.where(allc[:, 0] >= 0, -allc[:, 1],
                                      jnp.inf))
        return jnp.take(allc, order[:keep_top_k], axis=0)

    out = jax.vmap(per_image)(bboxes, scores)
    return {'Out': out}


@register('bipartite_match')
def bipartite_match(ctx, ins, attrs):
    dist = ins['DistMat']  # [N, M] (rows: gt? cols: priors)
    # greedy bipartite matching like the reference's default
    n, m = dist.shape

    def body(i, state):
        d, row_to_col, col_matched = state
        flat = jnp.argmax(d)
        r, c = flat // m, flat % m
        ok = d[r, c] > -jnp.inf
        row_to_col = jnp.where(ok, row_to_col.at[r].set(c), row_to_col)
        col_matched = jnp.where(ok, col_matched.at[c].set(r), col_matched)
        d = d.at[r, :].set(-jnp.inf)
        d = d.at[:, c].set(-jnp.inf)
        return d, row_to_col, col_matched

    init = (dist, -jnp.ones((n,), jnp.int32), -jnp.ones((m,), jnp.int32))
    _, row_to_col, col_match = jax.lax.fori_loop(0, min(n, m), body, init)
    dist_out = jnp.where(col_match >= 0,
                         dist[jnp.maximum(col_match, 0),
                              jnp.arange(m)], 0.0)
    return {'ColToRowMatchIndices': col_match[None, :],
            'ColToRowMatchDist': dist_out[None, :]}


@register('target_assign')
def target_assign(ctx, ins, attrs):
    x, match = ins['X'], ins['MatchIndices']  # x [M', K], match [N, P]
    mismatch_value = attrs.get('mismatch_value', 0)
    idx = jnp.maximum(match, 0)
    out = jnp.take(x, idx, axis=0)  # [N, P, K]
    w = (match >= 0).astype(jnp.float32)
    out = jnp.where(match[..., None] >= 0, out, mismatch_value)
    return {'Out': out, 'OutWeight': w[..., None]}


@register('roi_align')
def roi_align(ctx, ins, attrs):
    x, rois = ins['X'], ins['ROIs']  # x NCHW, rois [R, 4] + RoisBatch
    ph = attrs.get('pooled_height', 1)
    pw = attrs.get('pooled_width', 1)
    scale = attrs.get('spatial_scale', 1.0)
    ratio = attrs.get('sampling_ratio', -1)
    ratio = 2 if ratio <= 0 else ratio
    batch_idx = ins.get('RoisBatch')
    R = rois.shape[0]
    if batch_idx is None:
        batch_idx = jnp.zeros((R,), jnp.int32)
    n, c, h, w = x.shape

    def one_roi(roi, bi):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample ratio x ratio points per bin, bilinear
        py = y1 + (jnp.arange(ph * ratio) + 0.5) * bin_h / ratio
        px = x1 + (jnp.arange(pw * ratio) + 0.5) * bin_w / ratio
        py = jnp.clip(py, 0, h - 1)
        px = jnp.clip(px, 0, w - 1)
        y0 = jnp.floor(py).astype(jnp.int32)
        x0 = jnp.floor(px).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        wy = py - y0
        wx = px - x0
        img = x[bi]  # [C, H, W]
        v = (img[:, y0][:, :, x0] * ((1 - wy)[:, None] * (1 - wx)[None, :])[None] +
             img[:, y1i][:, :, x0] * (wy[:, None] * (1 - wx)[None, :])[None] +
             img[:, y0][:, :, x1i] * ((1 - wy)[:, None] * wx[None, :])[None] +
             img[:, y1i][:, :, x1i] * (wy[:, None] * wx[None, :])[None])
        v = v.reshape(c, ph, ratio, pw, ratio).mean(axis=(2, 4))
        return v

    out = jax.vmap(one_roi)(rois, batch_idx)
    return {'Out': out}


@register('roi_pool')
def roi_pool(ctx, ins, attrs):
    x, rois = ins['X'], ins['ROIs']
    ph = attrs.get('pooled_height', 1)
    pw = attrs.get('pooled_width', 1)
    scale = attrs.get('spatial_scale', 1.0)
    batch_idx = ins.get('RoisBatch')
    R = rois.shape[0]
    if batch_idx is None:
        batch_idx = jnp.zeros((R,), jnp.int32)
    n, c, h, w = x.shape

    def one_roi(roi, bi):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        img = x[bi]
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        out = jnp.full((c, ph, pw), -jnp.inf, x.dtype)
        for i in range(ph):
            for j in range(pw):
                ys_lo = y1 + (i * rh) // ph
                ys_hi = y1 + ((i + 1) * rh + ph - 1) // ph
                xs_lo = x1 + (j * rw) // pw
                xs_hi = x1 + ((j + 1) * rw + pw - 1) // pw
                m = ((ys >= ys_lo) & (ys < jnp.maximum(ys_hi, ys_lo + 1)))[:, None] & \
                    ((xs >= xs_lo) & (xs < jnp.maximum(xs_hi, xs_lo + 1)))[None, :]
                out = out.at[:, i, j].set(
                    jnp.max(jnp.where(m[None], img, -jnp.inf), axis=(1, 2)))
        return out

    out = jax.vmap(one_roi)(rois, batch_idx)
    return {'Out': jnp.where(jnp.isfinite(out), out, 0.0), 'Argmax': None}


@register('psroi_pool')
def psroi_pool(ctx, ins, attrs):
    """Position-sensitive ROI average pooling (R-FCN).

    Ref: paddle/fluid/operators/psroi_pool_op.h.  Input channels are laid out
    as (output_channels, pooled_h, pooled_w); bin (i, j) of output channel c
    average-pools input channel (c*ph + i)*pw + j over the bin region.
    """
    x, rois = ins['X'], ins['ROIs']
    oc = attrs['output_channels']
    scale = attrs.get('spatial_scale', 1.0)
    ph = attrs.get('pooled_height', 1)
    pw = attrs.get('pooled_width', 1)
    batch_idx = ins.get('RoisBatch')
    if batch_idx is None:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
    n, c, h, w = x.shape

    def one_roi(roi, bi):
        # std::round semantics (half away from zero), not jnp.round's
        # half-to-even; end coords are round(v)+1 per the reference kernel
        rnd = lambda v: jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)
        x1 = rnd(roi[0]) * scale
        y1 = rnd(roi[1]) * scale
        x2 = (rnd(roi[2]) + 1.0) * scale
        y2 = (rnd(roi[3]) + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / pw
        bin_h = rh / ph
        img = x[bi]
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        out = jnp.zeros((oc, ph, pw), x.dtype)
        for i in range(ph):
            for j in range(pw):
                hs = jnp.clip(jnp.floor(y1 + i * bin_h), 0, h).astype(jnp.int32)
                he = jnp.clip(jnp.ceil(y1 + (i + 1) * bin_h), 0, h).astype(jnp.int32)
                ws = jnp.clip(jnp.floor(x1 + j * bin_w), 0, w).astype(jnp.int32)
                we = jnp.clip(jnp.ceil(x1 + (j + 1) * bin_w), 0, w).astype(jnp.int32)
                m = (((ys >= hs) & (ys < he))[:, None] &
                     ((xs >= ws) & (xs < we))[None, :]).astype(x.dtype)
                area = jnp.maximum(m.sum(), 1.0)
                ch = (jnp.arange(oc) * ph + i) * pw + j
                out = out.at[:, i, j].set(
                    (img[ch] * m[None]).sum(axis=(1, 2)) / area)
        return out

    return {'Out': jax.vmap(one_roi)(rois, batch_idx)}


@register('roi_perspective_transform')
def roi_perspective_transform(ctx, ins, attrs):
    """Perspective-warp quadrilateral ROIs to a fixed rectangle.

    Ref: paddle/fluid/operators/detection/roi_perspective_transform_op.cc.
    ROIs are (R, 8) corner quads (x1 y1 ... x4 y4, clockwise from top-left).
    The 3x3 homography rect->quad is solved per ROI as an 8x8 linear system
    (batched jnp.linalg.solve lowers to XLA LU, fine on TPU), then the output
    grid is bilinearly sampled from the input.
    """
    x, rois = ins['X'], ins['ROIs']
    th = attrs['transformed_height']
    tw = attrs['transformed_width']
    scale = attrs.get('spatial_scale', 1.0)
    batch_idx = ins.get('RoisBatch')
    if batch_idx is None:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
    n, c, h, w = x.shape

    def one_roi(quad, bi):
        pts = quad.reshape(4, 2) * scale  # (x, y) corners
        # aspect-preserving normalized width (ref op .cc:121-134): the quad
        # is mapped onto the first nw columns, the rest stay zero
        side = jnp.sqrt(jnp.sum(
            (pts - jnp.roll(pts, -1, axis=0)) ** 2, axis=1))
        est_w = (side[0] + side[2]) / 2.0
        est_h = (side[1] + side[3]) / 2.0
        nw = jnp.minimum(
            jnp.round(est_w * (th - 1) / jnp.maximum(est_h, 1e-6)) + 1.0,
            float(tw))
        # destination rect corners in output coords
        dst = jnp.stack([
            jnp.array([0., 0.], x.dtype),
            jnp.stack([nw - 1.0, jnp.asarray(0.0, x.dtype)]),
            jnp.stack([nw - 1.0, jnp.asarray(th - 1.0, x.dtype)]),
            jnp.array([0., th - 1.], x.dtype)]).astype(x.dtype)
        # solve a*8 homography coeffs mapping dst -> src
        def row_pair(d, s):
            dx, dy = d[0], d[1]
            sx, sy = s[0], s[1]
            r1 = jnp.array([dx, dy, 1., 0., 0., 0., -dx * sx, -dy * sx], x.dtype)
            r2 = jnp.array([0., 0., 0., dx, dy, 1., -dx * sy, -dy * sy], x.dtype)
            return jnp.stack([r1, r2]), jnp.array([sx, sy], x.dtype)
        rows, rhs = jax.vmap(row_pair)(dst, pts)
        A = rows.reshape(8, 8)
        b = rhs.reshape(8)
        coef = jnp.linalg.solve(A + 1e-8 * jnp.eye(8, dtype=x.dtype), b)
        Hm = jnp.append(coef, 1.0).reshape(3, 3)
        gy, gx = jnp.meshgrid(jnp.arange(th, dtype=x.dtype),
                              jnp.arange(tw, dtype=x.dtype), indexing='ij')
        ones = jnp.ones_like(gx)
        src = jnp.einsum('ij,jhw->ihw', Hm, jnp.stack([gx, gy, ones]))
        sx = src[0] / src[2]
        sy = src[1] / src[2]
        inb = ((sx >= -0.5) & (sx <= w - 0.5) & (sy >= -0.5) &
               (sy <= h - 0.5) & (gx <= nw - 1.0 + 1e-4))
        sxc = jnp.clip(sx, 0, w - 1)
        syc = jnp.clip(sy, 0, h - 1)
        x0 = jnp.floor(sxc).astype(jnp.int32)
        y0 = jnp.floor(syc).astype(jnp.int32)
        x1i = jnp.minimum(x0 + 1, w - 1)
        y1i = jnp.minimum(y0 + 1, h - 1)
        wx = sxc - x0
        wy = syc - y0
        img = x[bi]
        v = (img[:, y0, x0] * ((1 - wy) * (1 - wx))[None] +
             img[:, y1i, x0] * (wy * (1 - wx))[None] +
             img[:, y0, x1i] * ((1 - wy) * wx)[None] +
             img[:, y1i, x1i] * (wy * wx)[None])
        return jnp.where(inb[None], v, 0.0)

    return {'Out': jax.vmap(one_roi)(rois, batch_idx)}


@register('detection_map')
def detection_map(ctx, ins, attrs):
    """Batch mAP for detection outputs.

    Parity: reference operators/detection/detection_map_op.h (integral and
    11point AP).  TPU-native reformulation: fixed-shape batched inputs —
    DetectRes [B, Nd, 6] (label, score, x1, y1, x2, y2) with DetectCount
    [B], Label [B, Ng, 6] (label, x1, y1, x2, y2, difficult) with
    LabelCount [B] — greedy score-order TP assignment runs as a lax.scan
    per image (carrying the matched-gt mask), then per-class AP via a
    global sort.  Stateless: returns this batch's mAP (the accumulating
    pos_count/true_pos/false_pos state of the reference op lives in
    evaluator.DetectionMAP on the host side).
    """
    det = ins['DetectRes']
    gt = ins['Label']
    B, Nd = det.shape[0], det.shape[1]
    Ng = gt.shape[1]
    n_cls = attrs['class_num']
    bg = attrs.get('background_label', 0)
    thresh = attrs.get('overlap_threshold', 0.3)
    eval_difficult = attrs.get('evaluate_difficult', True)
    ap_version = attrs.get('ap_version', 'integral')
    dcount = ins.get('DetectCount')
    gcount = ins.get('LabelCount')
    dvalid = (jnp.arange(Nd)[None, :] <
              (dcount.reshape(B, 1) if dcount is not None
               else jnp.full((B, 1), Nd)))
    gvalid = (jnp.arange(Ng)[None, :] <
              (gcount.reshape(B, 1) if gcount is not None
               else jnp.full((B, 1), Ng)))

    d_lbl = det[..., 0].astype(jnp.int32)
    d_scr = jnp.where(dvalid, det[..., 1], -1e9)
    d_box = det[..., 2:6]
    g_lbl = gt[..., 0].astype(jnp.int32)
    g_box = gt[..., 1:5]
    g_dif = (gt[..., 5] > 0.5) if gt.shape[-1] > 5 else \
        jnp.zeros((B, Ng), bool)
    g_dif = g_dif & gvalid
    if eval_difficult:
        g_dif = jnp.zeros_like(g_dif)

    def iou(a, b):  # [Nd,4] x [Ng,4] -> [Nd,Ng]
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.maximum(rb - lt, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
            jnp.maximum(a[:, 3] - a[:, 1], 0)
        area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
            jnp.maximum(b[:, 3] - b[:, 1], 0)
        return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter,
                                   1e-10)

    def per_image(dl, ds, db, gl, gb, gdif, gv, dv):
        ious = iou(db, gb)                                  # [Nd, Ng]
        order = jnp.argsort(-ds)                            # score desc

        def step(matched, di):
            ok_cls = (gl == dl[di]) & gv
            cand = jnp.where(ok_cls & ~matched, ious[di], -1.0)
            j = jnp.argmax(cand)
            hit = (cand[j] >= thresh) & dv[di]
            is_dif = jnp.where(hit, gdif[j], False)
            matched = matched.at[j].set(matched[j] | hit)
            tp = hit & ~is_dif
            # difficult-matched detections are ignored (neither tp nor fp)
            fp = dv[di] & ~hit
            return matched, (di, tp, fp)

        _, (idx, tp, fp) = jax.lax.scan(
            step, jnp.zeros((Ng,), bool), order)
        # unsort back to detection order
        tp_o = jnp.zeros((Nd,), bool).at[idx].set(tp)
        fp_o = jnp.zeros((Nd,), bool).at[idx].set(fp)
        return tp_o, fp_o

    tp, fp = jax.vmap(per_image)(d_lbl, d_scr, d_box, g_lbl, g_box, g_dif,
                                 gvalid, dvalid)

    flat_scr = d_scr.reshape(-1)
    flat_lbl = d_lbl.reshape(-1)
    flat_tp = tp.reshape(-1)
    flat_fp = fp.reshape(-1)
    flat_valid = dvalid.reshape(-1)
    order = jnp.argsort(-flat_scr)
    s_lbl = flat_lbl[order]
    s_tp = flat_tp[order].astype(jnp.float32)
    s_fp = flat_fp[order].astype(jnp.float32)
    s_valid = flat_valid[order]

    def class_ap(c):
        mask = (s_lbl == c) & s_valid
        tp_c = jnp.cumsum(jnp.where(mask, s_tp, 0.0))
        fp_c = jnp.cumsum(jnp.where(mask, s_fp, 0.0))
        npos = ((g_lbl == c) & gvalid & ~g_dif).sum().astype(jnp.float32)
        recall = tp_c / jnp.maximum(npos, 1.0)
        precision = tp_c / jnp.maximum(tp_c + fp_c, 1e-10)
        if ap_version == '11point':
            pts = jnp.linspace(0.0, 1.0, 11)
            pmax = jax.vmap(lambda t: jnp.max(
                jnp.where(mask & (recall >= t), precision, 0.0)))(pts)
            ap = pmax.sum() / 11.0
        else:
            prev_r = jnp.concatenate([jnp.zeros(1), recall[:-1]])
            ap = jnp.where(mask, (recall - prev_r) * precision, 0.0).sum()
        return jnp.where(npos > 0, ap, -1.0)

    classes = jnp.arange(n_cls)
    aps = jax.vmap(class_ap)(classes)
    aps = jnp.where(classes == bg, -1.0, aps)
    have = aps >= 0
    m_ap = jnp.where(have.sum() > 0,
                     jnp.where(have, aps, 0.0).sum() /
                     jnp.maximum(have.sum(), 1), 0.0)
    return {'MAP': m_ap.reshape(1).astype(jnp.float32)}


# ------------------------------------------------------ RCNN family
# Parity: reference operators/detection/{rpn_target_assign_op.cc,
# generate_proposals_op.cc, generate_proposal_labels_op.cc,
# generate_mask_labels_op.cc}.  The reference emits variable-count LoD
# outputs and samples rows with host RNG; here every output is FIXED-K
# per image with validity weights (invalid rows carry zero weight), and
# "sampling" is deterministic top-K by overlap — same training losses
# once the weights mask the padding, and the whole pipeline stays in one
# XLA executable.

def _iou_matrix(a, b):
    """a [M,4], b [G,4] xyxy -> [M,G]."""
    def area(x):
        return jnp.maximum(x[..., 2] - x[..., 0], 0) * \
            jnp.maximum(x[..., 3] - x[..., 1], 0)
    xi = jnp.maximum(a[:, None, 0], b[None, :, 0])
    yi = jnp.maximum(a[:, None, 1], b[None, :, 1])
    xa = jnp.minimum(a[:, None, 2], b[None, :, 2])
    ya = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(xa - xi, 0) * jnp.maximum(ya - yi, 0)
    union = area(a)[:, None] + area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def _encode_deltas(anchors, gt, weights=(1.0, 1.0, 1.0, 1.0)):
    """Standard RCNN box-delta encoding of gt wrt anchors [K,4]->[K,4]."""
    aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-6)
    ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-6)
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-6)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-6)
    gcx = gt[:, 0] + 0.5 * gw
    gcy = gt[:, 1] + 0.5 * gh
    wx, wy, ww, wh = weights
    return jnp.stack([(gcx - acx) / aw / wx, (gcy - acy) / ah / wy,
                      jnp.log(gw / aw) / ww, jnp.log(gh / ah) / wh],
                     axis=1)


@register('generate_proposals')
def generate_proposals(ctx, ins, attrs):
    """Decode RPN deltas at anchors, clip, min-size filter, NMS.
    Outputs are fixed [N, post_nms_topN, 4] rois + [N, post_nms_topN, 1]
    probs (invalid rows prob 0) instead of the reference's ragged LoD."""
    scores = ins['Scores']            # [N, A, H, W]
    deltas = ins['BboxDeltas']        # [N, 4A, H, W]
    im_info = ins['ImInfo']           # [N, 3] (h, w, scale)
    anchors = ins['Anchors'].reshape(-1, 4)     # [H*W*A, 4]
    variances = ins['Variances'].reshape(-1, 4)
    pre_n = int(attrs.get('pre_nms_topN', 6000))
    post_n = int(attrs.get('post_nms_topN', 1000))
    nms_thresh = float(attrs.get('nms_thresh', 0.5))
    min_size = float(attrs.get('min_size', 0.1))
    N, A, H, W = scores.shape

    def per_image(sc, dl, info):
        # -> anchor-major [H, W, A(,4)] to line up with the anchor layout
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)          # [HWA]
        d = jnp.transpose(dl.reshape(A, 4, H, W),
                          (2, 3, 0, 1)).reshape(-1, 4)        # [HWA, 4]
        k1 = min(pre_n, s.shape[0])
        top_s, top_i = jax.lax.top_k(s, k1)
        top_d = jnp.take(d, top_i, axis=0)
        top_a = jnp.take(anchors, top_i, axis=0)
        top_v = jnp.take(variances, top_i, axis=0)
        # decode (center-size with per-anchor variances)
        aw = top_a[:, 2] - top_a[:, 0]
        ah = top_a[:, 3] - top_a[:, 1]
        acx = top_a[:, 0] + 0.5 * aw
        acy = top_a[:, 1] + 0.5 * ah
        cx = top_d[:, 0] * top_v[:, 0] * aw + acx
        cy = top_d[:, 1] * top_v[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(top_d[:, 2] * top_v[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(top_d[:, 3] * top_v[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], axis=1)
        # clip to image
        ih, iw = info[0], info[1]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, iw - 1),
                           jnp.clip(boxes[:, 1], 0, ih - 1),
                           jnp.clip(boxes[:, 2], 0, iw - 1),
                           jnp.clip(boxes[:, 3], 0, ih - 1)], axis=1)
        # drop tiny boxes (min_size scaled to the input image)
        ms = min_size * info[2]
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1 >= ms) &
                   (boxes[:, 3] - boxes[:, 1] + 1 >= ms))
        top_s = jnp.where(keep_sz, top_s, -jnp.inf)
        k2 = min(post_n, k1)
        keep, valid = _nms_fixed(boxes, top_s, nms_thresh, k2)
        rois = jnp.take(boxes, keep, axis=0)
        probs = jnp.take(top_s, keep)
        rois = jnp.where(valid[:, None], rois, 0.0)
        probs = jnp.where(valid, probs, 0.0)
        if k2 < post_n:
            rois = jnp.pad(rois, ((0, post_n - k2), (0, 0)))
            probs = jnp.pad(probs, (0, post_n - k2))
        return rois, probs[:, None]

    rois, probs = jax.vmap(per_image)(scores, deltas, im_info)
    return {'RpnRois': rois, 'RpnRoiProbs': probs}


@register('rpn_target_assign')
def rpn_target_assign(ctx, ins, attrs):
    """Anchor-side RPN targets.  Fixed-size per image: K sampled score
    rows (fg+bg) and Kf location rows; deterministic top-K-by-IoU
    subsampling stands in for the reference's host RNG sampling."""
    anchor = ins['Anchor']            # [M, 4]
    gt = ins['GtBoxes']               # [N, G, 4] padded
    gt_len = ins.get('GtLength')      # [N] valid gt counts
    is_crowd = ins.get('IsCrowd')     # [N, G] (1 = crowd, excluded)
    K = int(attrs.get('rpn_batch_size_per_im', 256))
    fg_frac = float(attrs.get('rpn_fg_fraction', 0.5))
    pos_th = float(attrs.get('rpn_positive_overlap', 0.7))
    neg_th = float(attrs.get('rpn_negative_overlap', 0.3))
    Kf = max(1, int(K * fg_frac))
    N, G = gt.shape[0], gt.shape[1]
    M = anchor.shape[0]
    if gt_len is None:
        gt_len = jnp.full((N,), G, jnp.int32)
    gt_len = gt_len.reshape(-1).astype(jnp.int32)

    def per_image(g, glen, crowd):
        valid_g = jnp.arange(G) < glen
        if crowd is not None:
            valid_g = valid_g & (crowd.reshape(-1) == 0)
        iou = _iou_matrix(anchor, g)                  # [M, G]
        iou = jnp.where(valid_g[None, :], iou, -1.0)
        best_g = jnp.argmax(iou, axis=1)              # [M]
        best_iou = jnp.max(iou, axis=1)
        # (i) the best anchor for each gt is fg.  scatter-MAX: padded gt
        # columns all argmax to anchor 0, and a duplicate-index set()
        # applies in undefined order — a pad's False must never erase a
        # valid gt's True
        best_a_per_g = jnp.argmax(iou, axis=0)        # [G]
        forced = jnp.zeros((M,), jnp.int32).at[best_a_per_g].max(
            valid_g.astype(jnp.int32)) > 0
        fg = forced | (best_iou >= pos_th)
        bg = (~fg) & (best_iou < neg_th) & (best_iou >= 0)
        # deterministic subsample: fg by IoU desc, bg by IoU asc
        fg_rank = jnp.where(fg, best_iou + forced, -jnp.inf)
        _, fg_idx = jax.lax.top_k(fg_rank, Kf)
        fg_ok = jnp.take(fg, fg_idx)
        bg_rank = jnp.where(bg, -best_iou, -jnp.inf)
        _, bg_idx = jax.lax.top_k(bg_rank, K - Kf)
        bg_ok = jnp.take(bg, bg_idx)
        score_idx = jnp.concatenate([fg_idx, bg_idx])
        score_w = jnp.concatenate([fg_ok, bg_ok]).astype(jnp.float32)
        labels = jnp.concatenate([jnp.ones((Kf,), jnp.int32),
                                  jnp.zeros((K - Kf,), jnp.int32)])
        # rows that are padding / ignore-zone anchors get label -1 so a
        # loss with ignore_index=-1 skips them (score_w carries the same
        # mask as a float weight)
        labels = jnp.where(score_w > 0, labels, -1)
        # location targets for the fg rows
        tgt_g = jnp.take(best_g, fg_idx)              # [Kf]
        tgt_boxes = jnp.take(g, tgt_g, axis=0)
        loc_anchor = jnp.take(anchor, fg_idx, axis=0)
        tgt = _encode_deltas(loc_anchor, tgt_boxes)
        inside_w = jnp.where(fg_ok[:, None], 1.0, 0.0) * \
            jnp.ones((Kf, 4), jnp.float32)
        tgt = tgt * inside_w
        return (fg_idx.astype(jnp.int32), score_idx.astype(jnp.int32),
                labels[:, None], tgt, inside_w, score_w[:, None])

    (loc_i, score_i, labels, tgt_bbox, inside_w, score_w) = jax.vmap(
        per_image)(gt, gt_len,
                   is_crowd if is_crowd is not None else
                   jnp.zeros((N, G), jnp.int32))
    return {'LocationIndex': loc_i, 'ScoreIndex': score_i,
            'TargetLabel': labels, 'TargetBBox': tgt_bbox,
            'BBoxInsideWeight': inside_w, 'ScoreWeight': score_w}


@register('generate_proposal_labels')
def generate_proposal_labels(ctx, ins, attrs):
    """RoI-side Fast-RCNN targets: label each proposal by best-IoU gt,
    fixed B = batch_size_per_im sampled rows per image."""
    rois = ins['RpnRois']             # [N, R, 4]
    gt_cls = ins['GtClasses']         # [N, G, 1] int
    gt = ins['GtBoxes']               # [N, G, 4]
    gt_len = ins.get('GtLength')
    is_crowd = ins.get('IsCrowd')
    B = int(attrs.get('batch_size_per_im', 256))
    fg_frac = float(attrs.get('fg_fraction', 0.25))
    fg_th = float(attrs.get('fg_thresh', 0.5))
    bg_hi = float(attrs.get('bg_thresh_hi', 0.5))
    bg_lo = float(attrs.get('bg_thresh_lo', 0.0))
    bbox_w = attrs.get('bbox_reg_weights', [0.1, 0.1, 0.2, 0.2])
    n_cls = int(attrs.get('class_nums', 81))
    Bf = max(1, int(B * fg_frac))
    N, R = rois.shape[0], rois.shape[1]
    G = gt.shape[1]
    if gt_len is None:
        gt_len = jnp.full((N,), G, jnp.int32)
    gt_len = gt_len.reshape(-1).astype(jnp.int32)

    def per_image(r, g, gc, glen, crowd):
        valid_g = jnp.arange(G) < glen
        if crowd is not None:
            valid_g = valid_g & (crowd.reshape(-1) == 0)
        # gt boxes join the roi pool (reference appends them): each valid
        # gt matches itself at IoU 1, so fg rows exist even when every
        # RPN proposal is poor (early training bootstrap)
        r = jnp.concatenate([r, jnp.where(valid_g[:, None], g, 0.0)])
        iou = _iou_matrix(r, g)
        iou = jnp.where(valid_g[None, :], iou, -1.0)
        best_g = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        fg = best_iou >= fg_th
        bg = (best_iou < bg_hi) & (best_iou >= bg_lo)
        fg_rank = jnp.where(fg, best_iou, -jnp.inf)
        _, fg_idx = jax.lax.top_k(fg_rank, Bf)
        fg_ok = jnp.take(fg, fg_idx)
        bg_rank = jnp.where(bg & ~fg, best_iou, -jnp.inf)
        _, bg_idx = jax.lax.top_k(bg_rank, B - Bf)
        bg_ok = jnp.take(bg, bg_idx)
        sel = jnp.concatenate([fg_idx, bg_idx])
        ok = jnp.concatenate([fg_ok, bg_ok])
        out_rois = jnp.take(r, sel, axis=0) * ok[:, None]
        sel_g = jnp.take(best_g, sel)
        cls = jnp.take(gc.reshape(-1), sel_g)
        is_fg = jnp.concatenate([fg_ok, jnp.zeros((B - Bf,), bool)])
        labels = jnp.where(is_fg, cls, 0).astype(jnp.int32)
        labels = jnp.where(ok, labels, -1)
        # class-slotted bbox targets (4*n_cls, filled at the label slot)
        deltas = _encode_deltas(jnp.take(r, sel, axis=0),
                                jnp.take(g, sel_g, axis=0),
                                weights=tuple(bbox_w))
        onehot = (jnp.arange(n_cls)[None, :] ==
                  jnp.maximum(labels, 0)[:, None]) & is_fg[:, None]
        tgt = (onehot[:, :, None] * deltas[:, None, :]).reshape(B,
                                                                4 * n_cls)
        in_w = (onehot[:, :, None] *
                jnp.ones((1, 1, 4))).reshape(B, 4 * n_cls)
        return (out_rois, labels[:, None], tgt, in_w, in_w)

    (rois_o, labels, tgt, in_w, out_w) = jax.vmap(per_image)(
        rois, gt, gt_cls, gt_len,
        is_crowd if is_crowd is not None else
        jnp.zeros((N, G), jnp.int32))
    return {'Rois': rois_o, 'LabelsInt32': labels, 'BboxTargets': tgt,
            'BboxInsideWeights': in_w, 'BboxOutsideWeights': out_w}


@register('generate_mask_labels')
def generate_mask_labels(ctx, ins, attrs):
    """Mask-RCNN mask targets by polygon rasterization.  gt_segms here is
    ONE padded polygon per gt instance [N, G, P, 2] (the reference takes
    multi-polygon LoD); rasterization is an even-odd crossing test over
    the resolution grid — fully vectorized, no host loop."""
    rois = ins['Rois']                # [N, B, 4]
    labels = ins['LabelsInt32']       # [N, B, 1]
    segms = ins['GtSegms']            # [N, G, P, 2] polygon vertices
    roi_gt = ins['RoiGtIndex']        # [N, B, 1] matched gt per roi
    num_cls = int(attrs.get('num_classes', 81))
    R = int(attrs.get('resolution', 14))
    N, B = rois.shape[0], rois.shape[1]

    def rasterize(poly, box):
        # sample centers of an RxR grid over the roi box
        x0, y0, x1, y1 = box[0], box[1], box[2], box[3]
        xs = x0 + (jnp.arange(R) + 0.5) / R * jnp.maximum(x1 - x0, 1e-6)
        ys = y0 + (jnp.arange(R) + 0.5) / R * jnp.maximum(y1 - y0, 1e-6)
        gx, gy = jnp.meshgrid(xs, ys, indexing='xy')      # [R, R]
        px, py = poly[:, 0], poly[:, 1]
        qx, qy = jnp.roll(px, -1), jnp.roll(py, -1)
        # even-odd rule: count edges crossing the upward ray from (gx,gy)
        gxe = gx[..., None]
        gye = gy[..., None]
        cond = (py[None, None, :] > gye) != (qy[None, None, :] > gye)
        t = (gye - py) / jnp.where(qy - py == 0, 1e-12, qy - py)
        xint = px + t * (qx - px)
        crossings = jnp.sum(cond & (gxe < xint), axis=-1)
        return (crossings % 2).astype(jnp.int32)          # [R, R]

    def per_image(r, lab, sg, rg):
        def per_roi(box, l, gi):
            poly = sg[jnp.maximum(gi, 0)]
            m = rasterize(poly, box)
            has = (l > 0) & (gi >= 0)
            m = jnp.where(has, m, -1)                     # ignore rows
            slot = (jnp.arange(num_cls)[:, None, None] ==
                    jnp.maximum(l, 0))
            full = jnp.where(slot, m[None], -1)
            return full.reshape(num_cls * R * R), has.astype(jnp.int32)
        masks, has = jax.vmap(per_roi)(r, lab.reshape(-1),
                                       rg.reshape(-1))
        return r * (has > 0)[:, None].astype(r.dtype), has[:, None], masks

    mask_rois, has_mask, masks = jax.vmap(per_image)(
        rois, labels, segms, roi_gt)
    return {'MaskRois': mask_rois, 'RoiHasMaskInt32': has_mask,
            'MaskInt32': masks}
