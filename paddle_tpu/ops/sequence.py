"""Sequence ops over padded-batch (+lengths) representation, and RNNs.

Parity: reference sequence_pool_op, sequence_softmax_op, sequence_expand_op,
sequence_conv_op, sequence_pad/unpad, sequence_mask, sequence_reverse,
sequence_slice, sequence_concat, sequence_enumerate, lstm_op, gru_op.

TPU-native redesign: the reference walks CPU-side LoD offset tables per
sequence; here every op is a masked dense computation over [B, T, ...] with
an int32 `Length` [B] input — static shapes, vectorized over the batch, and
RNN recurrences are `lax.scan` (single compiled loop, no Python unrolling).
Ragged inputs are converted once at feed time (core/lod.py).
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register
from ..core.dtypes import jax_dtype


def _mask(x, length):
    """[B, T] validity mask broadcastable to x [B, T, ...]."""
    B, T = x.shape[0], x.shape[1]
    m = jnp.arange(T)[None, :] < length[:, None]
    return m.reshape((B, T) + (1,) * (x.ndim - 2))


def _length_or_full(ins, x, key='Length'):
    if key in ins and ins[key] is not None:
        return ins[key]
    return jnp.full((x.shape[0],), x.shape[1], dtype=jnp.int32)


@register('sequence_pool')
def sequence_pool(ctx, ins, attrs):
    x = ins['X']  # [B, T, ...]
    length = _length_or_full(ins, x)
    ptype = attrs.get('pooltype', 'AVERAGE').upper()
    m = _mask(x, length)
    mf = m.astype(x.dtype)
    cnt = jnp.maximum(length.astype(x.dtype), 1).reshape(
        (-1,) + (1,) * (x.ndim - 2))
    if ptype == 'SUM':
        out = jnp.sum(x * mf, axis=1)
    elif ptype == 'AVERAGE':
        out = jnp.sum(x * mf, axis=1) / cnt
    elif ptype == 'SQRT':
        out = jnp.sum(x * mf, axis=1) / jnp.sqrt(cnt)
    elif ptype == 'MAX':
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(m, x, neg), axis=1)
    elif ptype == 'LAST':
        idx = jnp.maximum(length - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    elif ptype == 'FIRST':
        out = x[:, 0]
    else:
        raise ValueError('bad pooltype %s' % ptype)
    return {'Out': out, 'MaxIndex': None}


@register('sequence_softmax')
def sequence_softmax(ctx, ins, attrs):
    x = ins['X']  # [B, T] or [B, T, 1]
    length = _length_or_full(ins, x)
    m = _mask(x, length)
    neg = jnp.finfo(x.dtype).min
    out = jax.nn.softmax(jnp.where(m, x, neg), axis=1)
    return {'Out': out * m.astype(x.dtype)}


@register('sequence_expand')
def sequence_expand(ctx, ins, attrs):
    # x: [B, ...] (one row per sequence), y gives target lengths ->
    # out: [B, T, ...] rows repeated along new time dim, masked by y length
    x, y = ins['X'], ins['Y']
    T = y.shape[1]
    if x.ndim == y.ndim:  # x already [B, T, ...]: tile row-wise not needed
        return {'Out': x}
    out = jnp.repeat(x[:, None], T, axis=1)
    return {'Out': out}


@register('sequence_expand_as')
def sequence_expand_as(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']
    T = y.shape[1]
    out = jnp.repeat(x[:, None], T, axis=1)
    return {'Out': out}


@register('sequence_reverse')
def sequence_reverse(ctx, ins, attrs):
    x = ins['X']
    length = _length_or_full(ins, x)
    T = x.shape[1]
    # reverse only the valid prefix: index (len-1-t) mod T for t < len
    t = jnp.arange(T)[None, :]
    idx = jnp.where(t < length[:, None], length[:, None] - 1 - t, t)
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return {'Y': out}


@register('sequence_conv')
def sequence_conv(ctx, ins, attrs):
    x, w = ins['X'], ins['Filter']  # x [B, T, D], w [ctx_len*D, out]
    length = _length_or_full(ins, x)
    ctx_len = attrs.get('contextLength', 3)
    ctx_start = attrs.get('contextStart', -(ctx_len // 2))
    B, T, D = x.shape
    xm = x * _mask(x, length).astype(x.dtype)
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        shifted = jnp.roll(xm, -off, axis=1)
        t = jnp.arange(T)
        valid = (t + off >= 0) & (t + off < T)
        cols.append(shifted * valid[None, :, None].astype(x.dtype))
    col = jnp.concatenate(cols, axis=-1)  # [B, T, ctx_len*D]
    out = col @ w
    return {'Out': out * _mask(out, length).astype(out.dtype)}


@register('sequence_pad')
def sequence_pad(ctx, ins, attrs):
    x = ins['X']
    length = _length_or_full(ins, x)
    # already padded in our representation
    return {'Out': x, 'Length': length.astype(jax_dtype('int64'))}


@register('sequence_unpad')
def sequence_unpad(ctx, ins, attrs):
    x, length = ins['X'], ins['Length']
    return {'Out': x, 'OutLength': length.astype(jnp.int32)}


@register('sequence_mask')
def sequence_mask(ctx, ins, attrs):
    x = ins['X']  # lengths tensor
    maxlen = attrs.get('maxlen', -1)
    from ..core.dtypes import jax_dtype
    dtype = jax_dtype(attrs.get('out_dtype', 'int64'))
    if maxlen is None or maxlen < 0:
        raise ValueError('sequence_mask on TPU requires static maxlen attr')
    m = jnp.arange(maxlen)[None, :] < x.reshape(-1, 1)
    m = m.reshape(tuple(x.shape) + (maxlen,))
    return {'Y': m.astype(dtype)}


@register('sequence_slice')
def sequence_slice(ctx, ins, attrs):
    x, offset, length = ins['X'], ins['Offset'], ins['Length']
    T = x.shape[1]
    off = offset.reshape(-1).astype(jnp.int32)
    t = jnp.arange(T)[None, :]
    idx = jnp.minimum(off[:, None] + t, T - 1)
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    # the reference enforces offset + length <= seq_len
    # (sequence_slice_op.h); with static shapes we clamp instead so a
    # request past the row's valid end can never report padding (or the
    # clamp-duplicated last frame) as valid tokens.
    row_len = _length_or_full(ins, x, key='XLength').astype(jnp.int32)
    new_len = jnp.clip(length.reshape(-1).astype(jnp.int32),
                       0, jnp.maximum(row_len - off, 0))
    m = (t < new_len[:, None]).reshape(
        (x.shape[0], T) + (1,) * (x.ndim - 2))
    return {'Out': out * m.astype(x.dtype), 'OutLength': new_len}


@register('sequence_concat')
def sequence_concat(ctx, ins, attrs):
    """Concatenate sequences ROW-WISE (parity: reference
    sequence_concat_op): row i of the output is input0's valid tokens
    then input1's valid tokens, contiguous, with length = sum of the
    per-input lengths.  In the padded layout that means compacting the
    concatenated padded blocks left (stable argsort on validity), not
    just stacking them — stacking would leave pad holes between rows'
    valid segments."""
    xs = ins['X']
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    lens = ins.get('Length')
    combined = jnp.concatenate(xs, axis=1)           # [B, sum T, ...]
    B, T = combined.shape[:2]
    if lens is None:
        return {'Out': combined,
                'OutLength': jnp.full((B,), T, jnp.int32)}
    lens = lens if isinstance(lens, (list, tuple)) else [lens]
    masks = [jnp.arange(x.shape[1])[None, :] <
             l.reshape(-1).astype(jnp.int32)[:, None]
             for x, l in zip(xs, lens)]
    valid = jnp.concatenate(masks, axis=1)           # [B, sum T]
    t = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    order = jnp.argsort(jnp.where(valid, t, t + T), axis=1)
    out = jnp.take_along_axis(
        combined, order.reshape(order.shape + (1,) * (combined.ndim - 2)),
        axis=1)
    new_len = valid.sum(axis=1).astype(jnp.int32)
    tail = (t < new_len[:, None]).reshape(
        (B, T) + (1,) * (combined.ndim - 2))
    return {'Out': jnp.where(tail, out, jnp.zeros_like(out)),
            'OutLength': new_len}


@register('sequence_enumerate')
def sequence_enumerate(ctx, ins, attrs):
    x = ins['X']  # [B, T] or [B, T, 1] int
    win = attrs['win_size']
    pad_value = attrs.get('pad_value', 0)
    squeeze = x.ndim == 3
    ids = x[..., 0] if squeeze else x
    B, T = ids.shape
    outs = []
    for i in range(win):
        shifted = jnp.roll(ids, -i, axis=1)
        valid = (jnp.arange(T) + i < T)[None, :]
        outs.append(jnp.where(valid, shifted, pad_value))
    out = jnp.stack(outs, axis=-1)  # [B, T, win]
    return {'Out': out}


@register('sequence_reshape')
def sequence_reshape(ctx, ins, attrs):
    x = ins['X']  # [B, T, D]
    new_dim = attrs['new_dim']
    B, T, D = x.shape
    # suffix padding keeps each row's valid data contiguous through the
    # flatten, so only the LENGTHS rescale: l tokens of width D become
    # l*D/new_dim tokens of width new_dim (reference sequence_reshape_op)
    length = _length_or_full(ins, x)
    new_len = (length.astype(jnp.int32) * D) // new_dim
    return {'Out': x.reshape(B, T * D // new_dim, new_dim),
            'OutLength': new_len}


@register('sequence_scatter')
def sequence_scatter(ctx, ins, attrs):
    x, ids, updates = ins['X'], ins['Ids'], ins['Updates']
    # ids/updates: [B, T(,1)] — scatter-add along dim 1 of x
    idx = ids[..., 0] if ids.ndim == 3 else ids
    upd = updates[..., 0] if updates.ndim == 3 else updates
    b = jnp.arange(x.shape[0])[:, None]
    return {'Out': x.at[b, idx].add(upd.astype(x.dtype))}


@register('sequence_erase')
def sequence_erase(ctx, ins, attrs):
    """Remove the attr `tokens` from each sequence (parity: reference
    sequence_erase_op.cc).  Data-dependent lengths are handled with
    static shapes: kept tokens compact left via a stable argsort on
    (erased?, position), the tail zero-fills, and the new per-row
    lengths come back in the Length slot — the padded+lengths analog of
    the reference's shrinking LoD."""
    x = ins['X']  # [B, T] or [B, T, 1] int tokens
    tokens = attrs.get('tokens', [])
    length = _length_or_full(ins, x)
    squeeze = x.ndim == 3
    ids = x[..., 0] if squeeze else x
    B, T = ids.shape
    t = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    valid = t < length[:, None]
    erased = jnp.zeros_like(valid)
    for tok in tokens:
        erased = erased | (ids == tok)
    keep = valid & ~erased
    # kept tokens sort before dropped ones, original order preserved
    order = jnp.argsort(jnp.where(keep, t, t + T), axis=1)
    compacted = jnp.take_along_axis(ids, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(t < new_len[:, None], compacted,
                    jnp.zeros_like(compacted))
    if squeeze:
        out = out[..., None]
    return {'Out': out, 'OutLength': new_len}


# --------------------------------------------------------------- RNNs

def _lstm_scan(xproj, h0, c0, w, bias, length, gate_act, cell_act, cand_act,
               use_peepholes, is_reverse):
    """xproj: [B, T, 4D] already input-projected; w: [D, 4D] recurrent.
    Gate layout: [i, f, g(candidate), o] (internal convention; reference
    lstm_op.h uses its own fixed order — self-consistent end-to-end here)."""
    B, T, D4 = xproj.shape
    D = D4 // 4
    if is_reverse:
        xproj = jnp.flip(xproj, axis=1)
    tmask = (jnp.arange(T)[None, :] < length[:, None]).astype(xproj.dtype)
    if is_reverse:
        tmask = jnp.flip(tmask, axis=1)
    xs = jnp.swapaxes(xproj, 0, 1)  # [T, B, 4D]
    ms = jnp.swapaxes(tmask, 0, 1)  # [T, B]
    if use_peepholes:
        b_g, w_ic, w_fc, w_oc = (bias[:, :4 * D], bias[:, 4 * D:5 * D],
                                 bias[:, 5 * D:6 * D], bias[:, 6 * D:7 * D])
    else:
        b_g = bias
        w_ic = w_fc = w_oc = None

    def step(carry, inp):
        h, c = carry
        xt, mt = inp
        gates = xt + h @ w + b_g
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = i + c * w_ic
            f = f + c * w_fc
        i, f = gate_act(i), gate_act(f)
        g = cand_act(g)
        c_new = f * c + i * g
        if use_peepholes:
            o = o + c_new * w_oc
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        m = mt[:, None]
        h = m * h_new + (1 - m) * h
        c = m * c_new + (1 - m) * c
        return (h, c), (h, c)

    (hT, cT), (hs, cs) = lax.scan(step, (h0, c0), (xs, ms))
    hs = jnp.swapaxes(hs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, axis=1)
        cs = jnp.flip(cs, axis=1)
    return hs, cs, hT, cT


_ACTS = {'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh, 'relu': jax.nn.relu,
         'identity': lambda x: x, 'hard_sigmoid': lambda x: jnp.clip(
             0.2 * x + 0.5, 0., 1.)}


@register('lstm')
def lstm(ctx, ins, attrs):
    """dynamic_lstm (ref lstm_op.cc): Input [B, T, 4D] (pre-projected),
    Weight [D, 4D], Bias [1, 4D or 7D]."""
    x = ins['Input']
    w = ins['Weight']
    bias = ins['Bias']
    length = _length_or_full(ins, x)
    D = w.shape[0]
    B = x.shape[0]
    h0 = ins.get('H0', None) if isinstance(ins, dict) else None
    c0 = ins.get('C0', None)
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, D), x.dtype)
    hs, cs, _, _ = _lstm_scan(
        x, h0, c0, w, bias, length,
        _ACTS[attrs.get('gate_activation', 'sigmoid')],
        _ACTS[attrs.get('cell_activation', 'tanh')],
        _ACTS[attrs.get('candidate_activation', 'tanh')],
        attrs.get('use_peepholes', True),
        attrs.get('is_reverse', False))
    return {'Hidden': hs, 'Cell': cs}


@register('cudnn_lstm')
def cudnn_lstm(ctx, ins, attrs):
    """Multi-layer LSTM (ref cudnn_lstm_op): here just stacked scans."""
    raise NotImplementedError('use layers.lstm / dynamic_lstm')


@register('gru')
def gru(ctx, ins, attrs):
    """dynamic_gru (ref gru_op.cc): Input [B, T, 3D] pre-projected,
    Weight [D, 3D] laid out as [W_update|W_reset|W_candidate], Bias [1,3D]."""
    x = ins['Input']
    w = ins['Weight']
    bias = ins.get('Bias')
    length = _length_or_full(ins, x)
    D = w.shape[0]
    B, T, _ = x.shape
    h0 = ins.get('H0')
    if h0 is None:
        h0 = jnp.zeros((B, D), x.dtype)
    if bias is None:
        bias = jnp.zeros((1, 3 * D), x.dtype)
    gact = _ACTS[attrs.get('gate_activation', 'sigmoid')]
    cact = _ACTS[attrs.get('activation', 'tanh')]
    is_reverse = attrs.get('is_reverse', False)
    w_ur = w[:, :2 * D]
    w_c = w[:, 2 * D:]
    if is_reverse:
        x = jnp.flip(x, axis=1)
    tmask = (jnp.arange(T)[None, :] < length[:, None]).astype(x.dtype)
    if is_reverse:
        tmask = jnp.flip(tmask, axis=1)
    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(tmask, 0, 1)

    def step(h, inp):
        xt, mt = inp
        xu, xr, xc = jnp.split(xt + bias, 3, axis=-1)
        ur = gact(jnp.concatenate([xu, xr], -1) + h @ w_ur)
        u, r = jnp.split(ur, 2, axis=-1)
        c = cact(xc + (r * h) @ w_c)
        h_new = u * h + (1 - u) * c
        m = mt[:, None]
        h = m * h_new + (1 - m) * h
        return h, h

    hT, hs = lax.scan(step, h0, (xs, ms))
    hs = jnp.swapaxes(hs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, axis=1)
    return {'Hidden': hs}


@register('gru_unit')
def gru_unit(ctx, ins, attrs):
    x, h_prev, w = ins['Input'], ins['HiddenPrev'], ins['Weight']
    D = h_prev.shape[-1]
    bias = ins.get('Bias')
    if bias is None:
        bias = jnp.zeros((1, 3 * D), x.dtype)
    gact = _ACTS.get(
        {1: 'sigmoid', 2: 'tanh', 0: 'identity', 3: 'relu'}.get(
            attrs.get('gate_activation', 1), 'sigmoid'))
    cact = _ACTS.get(
        {1: 'sigmoid', 2: 'tanh', 0: 'identity', 3: 'relu'}.get(
            attrs.get('activation', 2), 'tanh'))
    xu, xr, xc = jnp.split(x + bias, 3, axis=-1)
    w_ur, w_c = w[:, :2 * D], w[:, 2 * D:]
    ur = gact(jnp.concatenate([xu, xr], -1) + h_prev @ w_ur)
    u, r = jnp.split(ur, 2, axis=-1)
    c = cact(xc + (r * h_prev) @ w_c)
    h = u * h_prev + (1 - u) * c
    return {'Hidden': h, 'Gate': jnp.concatenate([u, r, c], -1),
            'ResetHiddenPrev': r * h_prev}


@register('lstm_unit')
def lstm_unit(ctx, ins, attrs):
    x, c_prev = ins['X'], ins['C_prev']
    forget_bias = attrs.get('forget_bias', 0.0)
    i, f, g, o = jnp.split(x, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + \
        jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {'C': c, 'H': h}


@register('beam_search')
def beam_search(ctx, ins, attrs):
    """One step of beam search, dense formulation.

    Ref: paddle/fluid/operators/beam_search_op.cc + math/beam_search.cc.  The
    reference shrinks/grows beams via LoD levels; on TPU the beam width stays
    static: every source keeps exactly `beam_size` rows, finished rows keep
    re-selecting (end_id, pre_score) as their only candidate (exactly the
    reference's finished-branch rule, math/beam_search.cc:241-246).  At the
    first step the caller makes only beam 0 live by feeding pre_scores of
    [0, -inf, -inf, ...] per source (the LoD equivalent in the reference).
    """
    pre_ids = ins['pre_ids']          # (R, 1) int
    pre_scores = ins['pre_scores']    # (R, 1) float
    scores = ins['scores']            # (R, K) float
    ids = ins.get('ids')              # (R, K) int or None -> arange
    beam = int(attrs['beam_size'])
    end_id = int(attrs['end_id'])
    acc = bool(attrs.get('is_accumulated', True))
    R, K = scores.shape
    batch = -(-R // beam)  # rows are padded up so any R builds (the batch
    # dim is a -1 placeholder during shape inference; real runs have
    # R % beam == 0 and the pad is empty)
    pad = batch * beam - R
    if ids is None:
        ids = jnp.broadcast_to(jnp.arange(K, dtype=pre_ids.dtype), (R, K))
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    cand = scores if acc else pre_scores + jnp.log(
        jnp.maximum(scores, jnp.finfo(scores.dtype).tiny))
    finished = (pre_ids[:, 0] == end_id)[:, None]              # (R, 1)
    only_slot0 = jnp.arange(K)[None, :] == 0
    cand = jnp.where(finished, jnp.where(only_slot0, pre_scores, neg_inf),
                     cand)
    cand_ids = jnp.where(finished, end_id, ids)
    if pad:
        cand = jnp.pad(cand, [(0, pad), (0, 0)], constant_values=-jnp.inf)
        cand_ids = jnp.pad(cand_ids, [(0, pad), (0, 0)],
                           constant_values=end_id)
    flat_scores = cand.reshape(batch, beam * K)
    flat_ids = cand_ids.reshape(batch, beam * K)
    top_v, top_i = jax.lax.top_k(flat_scores, beam)            # (batch, beam)
    parent_in_src = top_i // K                                 # beam index
    sel_ids = jnp.take_along_axis(flat_ids, top_i, axis=1)
    parent_idx = (jnp.arange(batch)[:, None] * beam + parent_in_src)
    return {'selected_ids': sel_ids.reshape(-1, 1)[:R],
            'selected_scores': top_v.reshape(-1, 1)[:R].astype(scores.dtype),
            'parent_idx': jnp.minimum(parent_idx.reshape(-1)[:R],
                                      R - 1).astype(jnp.int32)}


@register('beam_search_decode')
def beam_search_decode(ctx, ins, attrs):
    """Backtrace beam-search steps into full hypotheses.

    Ref: paddle/fluid/operators/beam_search_decode_op.cc.  The reference
    walks LoD back-pointers on the CPU and emits a 2-LEVEL LoDTensor
    (level 0: source -> its beam_size hypotheses; level 1: hypothesis ->
    its tokens).  Here the per-step parent indices are an explicit dense
    input and the walk is a lax.scan from the last step — one compiled
    gather chain, shapes static — and the same two levels come back as
    the padded+lengths companions: OutLength[R] (tokens per hypothesis,
    INCLUDING its end token, reference convention) and OutOuterLength
    [R/beam_size] (constant beam_size fan-out per source).

    Inputs: Ids (T, R, 1), Scores (T, R, 1), Parents (T, R) int32.
    Outputs: SentenceIds (R, T), SentenceScores (R, T); positions after a
    hypothesis' end token hold end_id / its final score.
    """
    ids = ins['Ids'][:, :, 0]        # (T, R)
    scores = ins['Scores'][:, :, 0]  # (T, R)
    T, R = ids.shape
    parents = ins.get('Parents')     # (T, R); identity when omitted
    if parents is None:
        parents = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32), (T, R))

    def step(src, t):
        tok = ids[t, src]
        sc = scores[t, src]
        nxt = parents[t, src]
        return nxt, (tok, sc)

    _, (toks, scs) = jax.lax.scan(step, jnp.arange(R), jnp.arange(T),
                                  reverse=True)
    toks, scs = toks.T, scs.T        # (R, T)
    end_id = attrs.get('end_id', 0)
    beam = int(attrs.get('beam_size', 1))
    is_end = toks == end_id
    # tokens per hypothesis including its first end token (reference
    # keeps the end token in the emitted sentence)
    first_end = jnp.argmax(is_end, axis=1)
    length = jnp.where(is_end.any(axis=1), first_end + 1, T).astype(
        jnp.int32)
    n_src = max(R // max(beam, 1), 1)
    outer = jnp.full((n_src,), R // n_src, jnp.int32)
    return {'SentenceIds': toks, 'SentenceScores': scs,
            'OutLength': length, 'OutOuterLength': outer}
