"""NN ops: conv / pool / norm / softmax / dropout / resize.

Parity: reference conv_op, pool_op, batch_norm_op, layer_norm_op,
group_norm_op, softmax_op, dropout_op, lrn_op, interpolate_op, etc.
Convs/pools use lax.conv_general_dilated / lax.reduce_window in NCHW — XLA
lays them out for the MXU; no cuDNN-style algo selection needed.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


@register('conv2d')
def conv2d(ctx, ins, attrs):
    x, w = ins['Input'], ins['Filter']
    strides = _pair(attrs.get('strides', [1, 1]))
    pads = _pair(attrs.get('paddings', [0, 0]))
    dil = _pair(attrs.get('dilations', [1, 1]))
    groups = attrs.get('groups', 1) or 1
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    if 'Bias' in ins:
        out = out + ins['Bias'].reshape(1, -1, 1, 1)
    return {'Output': out}


@register('conv3d')
def conv3d(ctx, ins, attrs):
    x, w = ins['Input'], ins['Filter']
    strides = _pair(attrs.get('strides', [1, 1, 1]), 3)
    pads = _pair(attrs.get('paddings', [0, 0, 0]), 3)
    dil = _pair(attrs.get('dilations', [1, 1, 1]), 3)
    groups = attrs.get('groups', 1) or 1
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'))
    return {'Output': out}


def _transpose_filter(w, groups, spatial_axes):
    """[in_c, out_c/g, *k] -> flipped [out_c, in_c/g, *k] for the
    gradient-of-conv formulation (grouped: per-group O/I swap)."""
    w = jnp.flip(w, spatial_axes)
    if groups == 1:
        return w.swapaxes(0, 1)
    in_c, ocg = w.shape[0], w.shape[1]
    k = w.shape[2:]
    wg = w.reshape((groups, in_c // groups, ocg) + k)
    wg = wg.swapaxes(1, 2)  # [g, out_c/g, in_c/g, *k]
    return wg.reshape((groups * ocg, in_c // groups) + k)


@register('conv2d_transpose')
def conv2d_transpose(ctx, ins, attrs):
    x, w = ins['Input'], ins['Filter']  # w: [in_c, out_c/groups, kh, kw]
    strides = _pair(attrs.get('strides', [1, 1]))
    pads = _pair(attrs.get('paddings', [0, 0]))
    dil = _pair(attrs.get('dilations', [1, 1]))
    groups = attrs.get('groups', 1) or 1
    kh, kw = w.shape[2], w.shape[3]
    # gradient-of-conv formulation: lhs_dilation = stride
    out = lax.conv_general_dilated(
        x, _transpose_filter(w, groups, (2, 3)),
        window_strides=(1, 1),
        padding=[(dil[0] * (kh - 1) - pads[0], dil[0] * (kh - 1) - pads[0]),
                 (dil[1] * (kw - 1) - pads[1], dil[1] * (kw - 1) - pads[1])],
        lhs_dilation=strides, rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    return {'Output': out}


@register('conv3d_transpose')
def conv3d_transpose(ctx, ins, attrs):
    x, w = ins['Input'], ins['Filter']
    strides = _pair(attrs.get('strides', [1, 1, 1]), 3)
    pads = _pair(attrs.get('paddings', [0, 0, 0]), 3)
    dil = _pair(attrs.get('dilations', [1, 1, 1]), 3)
    groups = attrs.get('groups', 1) or 1
    ks = w.shape[2:]
    out = lax.conv_general_dilated(
        x, _transpose_filter(w, groups, (2, 3, 4)),
        window_strides=(1, 1, 1),
        padding=[(dil[i] * (ks[i] - 1) - pads[i],) * 2 for i in range(3)],
        lhs_dilation=strides, rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'))
    return {'Output': out}


def _pool(x, ksize, strides, pads, ptype, exclusive, ceil_mode,
          global_pool, adaptive=False, nd=2):
    if global_pool:
        axes = tuple(range(2, 2 + nd))
        if ptype == 'max':
            return jnp.max(x, axis=axes, keepdims=True)
        return jnp.mean(x, axis=axes, keepdims=True)
    ksize = _pair(ksize, nd)
    strides = _pair(strides, nd)
    pads = _pair(pads, nd)
    window = (1, 1) + ksize
    wstrides = (1, 1) + strides
    padding = [(0, 0), (0, 0)]
    for i in range(nd):
        hi = pads[i]
        if ceil_mode:
            size = x.shape[2 + i]
            out = -(-(size + 2 * pads[i] - ksize[i]) // strides[i]) + 1
            needed = (out - 1) * strides[i] + ksize[i] - size - pads[i]
            hi = max(pads[i], needed)
        padding.append((pads[i], hi))
    if ptype == 'max':
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, wstrides, padding)
    s = lax.reduce_window(x, 0.0, lax.add, window, wstrides, padding)
    if exclusive:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, wstrides, padding)
        return s / cnt
    return s / float(np.prod(ksize))


@register('pool2d')
def pool2d(ctx, ins, attrs):
    return {'Out': _pool(ins['X'], attrs.get('ksize', [2, 2]),
                         attrs.get('strides', [1, 1]),
                         attrs.get('paddings', [0, 0]),
                         attrs.get('pooling_type', 'max'),
                         attrs.get('exclusive', True),
                         attrs.get('ceil_mode', False),
                         attrs.get('global_pooling', False), nd=2)}


@register('pool3d')
def pool3d(ctx, ins, attrs):
    return {'Out': _pool(ins['X'], attrs.get('ksize', [2, 2, 2]),
                         attrs.get('strides', [1, 1, 1]),
                         attrs.get('paddings', [0, 0, 0]),
                         attrs.get('pooling_type', 'max'),
                         attrs.get('exclusive', True),
                         attrs.get('ceil_mode', False),
                         attrs.get('global_pooling', False), nd=3)}


def _adaptive_pool(x, out_size, ptype, nd=2):
    axes_sizes = x.shape[2:2 + nd]
    out_size = _pair(out_size, nd)
    # decompose into even windows when divisible (common case), else resize
    ks = []
    for s, o in zip(axes_sizes, out_size):
        assert s % o == 0, 'adaptive pool needs divisible sizes on TPU'
        ks.append(s // o)
    return _pool(x, ks, ks, [0] * nd, ptype, True, False, False, nd=nd)


@register('adaptive_pool2d')
def adaptive_pool2d(ctx, ins, attrs):
    return {'Out': _adaptive_pool(ins['X'], attrs['ksize'],
                                  attrs.get('pooling_type', 'max'), 2)}


@register('adaptive_pool3d')
def adaptive_pool3d(ctx, ins, attrs):
    return {'Out': _adaptive_pool(ins['X'], attrs['ksize'],
                                  attrs.get('pooling_type', 'max'), 3)}


@register('batch_norm')
def batch_norm(ctx, ins, attrs):
    x = ins['X']
    scale, bias = ins['Scale'], ins['Bias']
    mean, var = ins['Mean'], ins['Variance']
    eps = attrs.get('epsilon', 1e-5)
    momentum = attrs.get('momentum', 0.9)
    is_test = attrs.get('is_test', False)
    layout = attrs.get('data_layout', 'NCHW')
    ch_axis = 1 if layout == 'NCHW' else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    # statistics always accumulate in f32 (bf16 mean/var over B*H*W
    # elements would lose ~5 bits); y returns in the input dtype so AMP
    # activations stay half-width in HBM
    xf = x.astype(jnp.float32)

    if is_test or attrs.get('use_global_stats', False):
        m, v = mean, var
        y = (xf - m.reshape(bshape)) * (
            scale.reshape(bshape) * lax.rsqrt(v.reshape(bshape) + eps)) + \
            bias.reshape(bshape)
        return {'Y': y.astype(x.dtype), 'MeanOut': mean, 'VarianceOut': var,
                'SavedMean': m, 'SavedVariance': v}
    # one-pass statistics (f32 accumulation): the two-pass
    # mean(square(x - m)) form reads the conv-sized activation TWICE
    # per BN — at ResNet bench shapes the BN statistic fusions were
    # ~20% of the step (per-HLO ledger, PERF.md r5).  The sums are
    # SHIFTED by a per-channel pilot value c (the first element) so the
    # E[d^2] - E[d]^2 subtraction never catastrophically cancels when
    # |mean| >> std; the shift is analytically a no-op (stop_gradient'd)
    # and fuses into the same single read.  Residual risk: a pilot
    # element ~4000 sigma away from its group mean can still cancel —
    # PT_TWO_PASS_NORM=1 restores the exact two-pass form.
    if os.environ.get('PT_TWO_PASS_NORM', '0') == '1':
        m = jnp.mean(xf, axis=axes)
        v = jnp.mean(jnp.square(xf - m.reshape(bshape)), axis=axes)
        y = (xf - m.reshape(bshape)) * (
            scale.reshape(bshape) * lax.rsqrt(v.reshape(bshape) + eps)) + \
            bias.reshape(bshape)
        new_mean = lax.stop_gradient(momentum * mean + (1 - momentum) * m)
        new_var = lax.stop_gradient(momentum * var + (1 - momentum) * v)
        return {'Y': y.astype(x.dtype), 'MeanOut': new_mean,
                'VarianceOut': new_var, 'SavedMean': m,
                'SavedVariance': v}
    c = lax.stop_gradient(xf[tuple(
        slice(None) if i == ch_axis else slice(0, 1)
        for i in range(x.ndim))])
    d = xf - c
    md = jnp.mean(d, axis=axes, keepdims=True)
    v = jnp.maximum(
        jnp.mean(jnp.square(d), axis=axes, keepdims=True)
        - jnp.square(md), 0.0)
    m = (md + c).reshape(x.shape[ch_axis])
    v = v.reshape(x.shape[ch_axis])
    y = (d - md) * (
        scale.reshape(bshape) * lax.rsqrt(v.reshape(bshape) + eps)) + \
        bias.reshape(bshape)
    new_mean = lax.stop_gradient(momentum * mean + (1 - momentum) * m)
    new_var = lax.stop_gradient(momentum * var + (1 - momentum) * v)
    return {'Y': y.astype(x.dtype), 'MeanOut': new_mean,
            'VarianceOut': new_var, 'SavedMean': m, 'SavedVariance': v}


@register('layer_norm')
def layer_norm(ctx, ins, attrs):
    x = ins['X']
    begin = attrs.get('begin_norm_axis', 1)
    eps = attrs.get('epsilon', 1e-5)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)  # f32 statistics; output in input dtype
    # shifted one-pass statistics like batch_norm above: one read, and
    # the per-row pilot shift bounds the E[d^2]-E[d]^2 cancellation
    # (PT_TWO_PASS_NORM=1 restores the exact two-pass form)
    if os.environ.get('PT_TWO_PASS_NORM', '0') == '1':
        m = jnp.mean(xf, axis=axes, keepdims=True)
        v = jnp.mean(jnp.square(xf - m), axis=axes, keepdims=True)
        y = (xf - m) * lax.rsqrt(v + eps)
    else:
        c = lax.stop_gradient(xf[tuple(
            slice(None) if i < begin else slice(0, 1)
            for i in range(x.ndim))])
        d = xf - c
        md = jnp.mean(d, axis=axes, keepdims=True)
        v = jnp.maximum(
            jnp.mean(jnp.square(d), axis=axes, keepdims=True)
            - jnp.square(md), 0.0)
        m = md + c
        y = (d - md) * lax.rsqrt(v + eps)
    norm_shape = x.shape[begin:]
    if 'Scale' in ins:
        y = y * ins['Scale'].reshape(norm_shape)
    if 'Bias' in ins:
        y = y + ins['Bias'].reshape(norm_shape)
    return {'Y': y.astype(x.dtype), 'Mean': m.reshape(x.shape[:begin]),
            'Variance': v.reshape(x.shape[:begin])}


@register('group_norm')
def group_norm(ctx, ins, attrs):
    x = ins['X']  # NCHW
    g = attrs.get('groups', 1)
    eps = attrs.get('epsilon', 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:]).astype(jnp.float32)
    axes = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=axes, keepdims=True)
    v = jnp.mean(jnp.square(xg - m), axis=axes, keepdims=True)
    y = ((xg - m) * lax.rsqrt(v + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if 'Scale' in ins:
        y = y * ins['Scale'].reshape(bshape)
    if 'Bias' in ins:
        y = y + ins['Bias'].reshape(bshape)
    return {'Y': y.astype(x.dtype), 'Mean': m.reshape(n, g),
            'Variance': v.reshape(n, g)}


@register('data_norm')
def data_norm(ctx, ins, attrs):
    x = ins['X']
    sizes, sums, sqsums = ins['BatchSize'], ins['BatchSum'], ins['BatchSquareSum']
    means = sums / sizes
    scales = lax.rsqrt(sqsums / sizes - jnp.square(means) + 1e-4)
    return {'Y': (x - means) * scales, 'Means': means, 'Scales': scales}


@register('softmax')
def softmax(ctx, ins, attrs):
    x = ins['X']  # exp/sum in f32; result back in input dtype
    out = jax.nn.softmax(x.astype(jnp.float32), axis=attrs.get('axis', -1))
    return {'Out': out.astype(x.dtype)}


@register('log_softmax')
def log_softmax(ctx, ins, attrs):
    x = ins['X']
    out = jax.nn.log_softmax(x.astype(jnp.float32),
                             axis=attrs.get('axis', -1))
    return {'Out': out.astype(x.dtype)}


@register('dropout')
def dropout(ctx, ins, attrs):
    x = ins['X']
    p = attrs.get('dropout_prob', 0.5)
    is_test = attrs.get('is_test', False)
    impl = attrs.get('dropout_implementation', 'downgrade_in_infer')
    if is_test:
        out = x * (1.0 - p) if impl == 'downgrade_in_infer' else x
        return {'Out': out, 'Mask': jnp.ones_like(x)}
    seed = attrs.get('seed', 0)
    key = jax.random.key(seed) if seed else ctx.rng()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    out = x * mask
    if impl == 'upscale_in_train' and p < 1.0:
        out = out / (1.0 - p)
    return {'Out': out, 'Mask': mask}


@register('lrn')
def lrn(ctx, ins, attrs):
    x = ins['X']  # NCHW
    n = attrs.get('n', 5)
    k = attrs.get('k', 2.0)
    alpha = attrs.get('alpha', 1e-4)
    beta = attrs.get('beta', 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {'Out': x / jnp.power(mid, beta), 'MidOut': mid}


@register('l2_norm_layer')
def l2_norm_layer(ctx, ins, attrs):
    x = ins['X']
    return {'Out': x / jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))}


def _resize(x, out_h, out_w, method, align_corners):
    n, c, h, w = x.shape
    if not align_corners:
        xt = x.transpose(0, 2, 3, 1)
        out = jax.image.resize(xt, (n, out_h, out_w, c), method=method)
        return out.transpose(0, 3, 1, 2)

    # align_corners=True (the reference default): src = i*(in-1)/(out-1)
    def coords(out_size, in_size):
        if out_size == 1:
            return jnp.zeros((1,))
        return jnp.arange(out_size) * ((in_size - 1) / (out_size - 1))

    ys = coords(out_h, h)
    xs = coords(out_w, w)
    if method == 'nearest':
        yi = jnp.round(ys).astype(jnp.int32)
        xi = jnp.round(xs).astype(jnp.int32)
        return x[:, :, yi][:, :, :, xi]
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0).reshape(1, 1, -1, 1).astype(x.dtype)
    wx = (xs - x0).reshape(1, 1, 1, -1).astype(x.dtype)
    tl = x[:, :, y0][:, :, :, x0]
    tr = x[:, :, y0][:, :, :, x1]
    bl = x[:, :, y1][:, :, :, x0]
    br = x[:, :, y1][:, :, :, x1]
    top = tl * (1 - wx) + tr * wx
    bot = bl * (1 - wx) + br * wx
    return top * (1 - wy) + bot * wy


@register('bilinear_interp')
def bilinear_interp(ctx, ins, attrs):
    x = ins['X']
    out_h, out_w = attrs['out_h'], attrs['out_w']
    if 'OutSize' in ins:
        pass  # dynamic size unsupported under XLA; use attrs
    return {'Out': _resize(x, out_h, out_w, 'bilinear',
                           attrs.get('align_corners', True))}


@register('nearest_interp')
def nearest_interp(ctx, ins, attrs):
    x = ins['X']
    return {'Out': _resize(x, attrs['out_h'], attrs['out_w'], 'nearest',
                           attrs.get('align_corners', True))}


@register('affine_channel')
def affine_channel(ctx, ins, attrs):
    x, scale, bias = ins['X'], ins['Scale'], ins['Bias']
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    return {'Out': x * scale.reshape(bshape) + bias.reshape(bshape)}


@register('row_conv')
def row_conv(ctx, ins, attrs):
    # lookahead row convolution over time (ref row_conv_op.cc); x: [B, T, D]
    x, w = ins['X'], ins['Filter']  # w: [future_ctx, D]
    k = w.shape[0]
    pad = jnp.pad(x, [(0, 0), (0, k - 1), (0, 0)])
    out = sum(pad[:, i:i + x.shape[1]] * w[i] for i in range(k))
    return {'Out': out}


@register('conv_shift')
def conv_shift(ctx, ins, attrs):
    x, y = ins['X'], ins['Y']  # [B, M], [B, N] N odd
    m, n = x.shape[1], y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, half + 1)[None, :]) % m
    return {'Out': jnp.einsum('bmn,bn->bm', x[:, idx], y)}


@register('im2sequence')
def im2sequence(ctx, ins, attrs):
    x = ins['X']  # NCHW
    kh, kw = attrs['kernels']
    sh, sw = attrs.get('strides', [1, 1])
    n, c, h, w = x.shape
    patches = []
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    for i in range(oh):
        for j in range(ow):
            patches.append(x[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
                           .reshape(n, -1))
    out = jnp.stack(patches, axis=1)  # [N, oh*ow, c*kh*kw]
    return {'Out': out}


@register('grid_sampler')
def grid_sampler(ctx, ins, attrs):
    x, grid = ins['X'], ins['Grid']  # x NCHW, grid [N, H, W, 2] in [-1,1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1

    def sample(yi, xi):
        yi = jnp.clip(yi, 0, h - 1)
        xi = jnp.clip(xi, 0, w - 1)
        bidx = jnp.arange(n)[:, None, None]
        return x[bidx, :, yi, xi]  # [N, H, W, C]

    wa = ((x1 - gx) * (y1 - gy))[..., None]
    wb = ((x1 - gx) * (gy - y0))[..., None]
    wc = ((gx - x0) * (y1 - gy))[..., None]
    wd = ((gx - x0) * (gy - y0))[..., None]
    out = wa * sample(y0, x0) + wb * sample(y1, x0) + \
        wc * sample(y0, x1) + wd * sample(y1, x1)
    return {'Output': out.transpose(0, 3, 1, 2)}


@register('affine_grid')
def affine_grid(ctx, ins, attrs):
    theta = ins['Theta']  # [N, 2, 3]
    _, _, h, w = attrs['output_shape'] if 'output_shape' in attrs else \
        (0, 0, 0, 0)
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [H, W, 3]
    out = jnp.einsum('hwk,nik->nhwi', base, theta)
    return {'Output': out}


@register('add_position_encoding')
def add_position_encoding(ctx, ins, attrs):
    x = ins['X']  # [B, T, D]
    alpha = attrs.get('alpha', 1.0)
    beta = attrs.get('beta', 1.0)
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=x.dtype)[:, None]
    half = d // 2
    div = jnp.power(10000.0, jnp.arange(half, dtype=x.dtype) / half)
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    return {'Out': alpha * x + beta * pe[None, :, :]}


@register('similarity_focus')
def similarity_focus(ctx, ins, attrs):
    x = ins['X']
    axis = attrs['axis']
    indexes = attrs['indexes']
    sel = jnp.take(x, jnp.array(indexes), axis=axis)
    mx = jnp.max(sel, axis=axis, keepdims=True)
    mask = (x == jnp.max(mx, axis=tuple(range(2, x.ndim)), keepdims=True))
    return {'Out': jnp.where(mask, jnp.ones_like(x), jnp.zeros_like(x))}


@register('tree_conv')
def tree_conv(ctx, ins, attrs):
    """Tree-based convolution (TBCNN).

    Ref: paddle/fluid/operators/tree_conv_op.h + math/tree2col.cc.  The
    reference builds per-root "patches" by depth-limited DFS on the host and
    runs a gemm per sample.  TPU-native formulation: depth-d reachability is
    A^d (boolean matmul chain, d < max_depth), and the eta_t/eta_l/eta_r
    coefficient matrices are built densely so the whole op is a few (N+1)^2
    matmuls + one (N, 3F) x (3F, out*nf) gemm per sample — all MXU work, no
    host graph traversal.

    Inputs: NodesVector (B, N, F); EdgeSet (B, E, 2) int, 1-based (parent,
    child) pairs, zero-terminated; Filter (F, 3, out_size, num_filters).
    Output: (B, N, out_size, num_filters).
    """
    nodes, edges, filt = ins['NodesVector'], ins['EdgeSet'], ins['Filter']
    max_depth = int(attrs.get('max_depth', 2))
    B, N, F = nodes.shape
    fdim, three, out_size, nf = filt.shape
    w2d = filt.reshape(3 * F, out_size * nf)
    fd = float(max_depth)

    def one(sample_nodes, sample_edges):
        u = sample_edges[:, 0].astype(jnp.int32)
        v = sample_edges[:, 1].astype(jnp.int32)
        ok = (u != 0) & (v != 0)
        # reference construct_tree breaks at the first invalid edge
        valid = (jnp.cumprod(ok.astype(jnp.int32)) > 0)
        node_count = valid.sum() + 1
        A = jnp.zeros((N + 1, N + 1), nodes.dtype)
        A = A.at[jnp.where(valid, u, 0), jnp.where(valid, v, 0)].add(
            valid.astype(nodes.dtype))
        A = A.at[0, 0].set(0.0).clip(0.0, 1.0)
        # sibling order (1-based) and sibling count per child edge
        same_parent = (u[:, None] == u[None, :]) & valid[None, :]
        E = u.shape[0]
        earlier = jnp.tril(jnp.ones((E, E), jnp.int32), -1)
        order = (same_parent.astype(jnp.int32) * earlier).sum(-1) + 1
        pclen = same_parent.astype(jnp.int32).sum(-1)
        temp_e = jnp.where(pclen == 1, 0.5,
                           (order - 1.0) / jnp.maximum(pclen - 1.0, 1e-6))
        node_temp = jnp.zeros((N + 1,), nodes.dtype)
        node_temp = node_temp.at[jnp.where(valid, v, 0)].set(
            jnp.where(valid, temp_e.astype(nodes.dtype), 0.0))
        # reachability at each depth d = A^d restricted to d < max_depth
        M_t = jnp.eye(N + 1, dtype=nodes.dtype)  # root: eta_t=1, eta_l=eta_r=0
        M_l = jnp.zeros((N + 1, N + 1), nodes.dtype)
        M_r = jnp.zeros((N + 1, N + 1), nodes.dtype)
        Rd = jnp.eye(N + 1, dtype=nodes.dtype)
        for d in range(1, max_depth):
            Rd = (Rd @ A > 0).astype(nodes.dtype)
            et = (fd - d) / fd
            el = (1.0 - et) * node_temp[None, :]
            er = (1.0 - et) * (1.0 - el)
            M_t = M_t + Rd * et
            M_l = M_l + Rd * el
            M_r = M_r + Rd * er
        feat = jnp.concatenate(
            [jnp.zeros((1, F), nodes.dtype), sample_nodes], axis=0)
        p_t = (M_t @ feat)[1:]
        p_l = (M_l @ feat)[1:]
        p_r = (M_r @ feat)[1:]
        patch = jnp.stack([p_l, p_r, p_t], axis=-1).reshape(N, 3 * F)
        active = (jnp.arange(1, N + 1) <= node_count)[:, None]
        out = jnp.where(active, patch, 0.0) @ w2d
        return out.reshape(N, out_size, nf)

    return {'Out': jax.vmap(one)(nodes, edges)}


@register('rms_norm')
def rms_norm(ctx, ins, attrs):
    """Root-mean-square LayerNorm (no mean-centering, no bias) — the LLaMA
    norm.  New vs reference (it predates RMSNorm); fused by XLA into the
    surrounding matmuls."""
    x = ins['X']
    w = ins.get('Scale')
    eps = attrs.get('epsilon', 1e-6)
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w.astype(jnp.float32)
    return {'Y': out.astype(dt)}


@register('rope')
def rope(ctx, ins, attrs):
    """Rotary position embedding on [B, H, T, D] (D even): rotate feature
    pairs by position-dependent angles.  theta: base frequency (LLaMA-3
    uses 500000).  `Positions` (optional int [B, T]) overrides 0..T-1."""
    x = ins['X']
    theta = attrs.get('theta', 10000.0)
    B, H, T, D = x.shape
    pos = ins.get('Positions')
    if pos is None:
        pos = jnp.arange(T)[None, :]                       # [1, T]
    freqs = theta ** (-jnp.arange(0, D // 2) * 2.0 / D)    # [D/2]
    ang = pos[:, None, :, None].astype(jnp.float32) * freqs  # [B,1,T,D/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(B, H, T, D)
    return {'Out': out.astype(x.dtype)}


@register('chunk_eval')
def chunk_eval(ctx, ins, attrs):
    """Chunk detection eval (NER-style): counts inferred/label/correct
    chunks under IOB/IOE/IOBES/plain tag schemes.

    Parity: reference paddle/fluid/operators/chunk_eval_op.h semantics
    (ChunkBegin/ChunkEnd rule tables), re-expressed as a vectorized
    position-parallel computation: a chunk is identified by its (start,
    end, type) triple; starts come from a running max over begin markers,
    and a correct chunk is an aligned (end, start, type) match — no
    sequential segment walk, so the whole batch evals in one fused XLA op.
    """
    scheme = attrs.get('chunk_scheme', 'IOB')
    num_chunk_types = attrs['num_chunk_types']
    excluded = attrs.get('excluded_chunk_types') or []
    n_tag = {'IOB': 2, 'IOE': 2, 'IOBES': 4, 'plain': 1}[scheme]
    # tag-type codes per scheme; -1 = not present
    tb, ti, te, ts = {'IOB': (0, 1, -1, -1), 'IOE': (-1, 0, 1, -1),
                      'IOBES': (0, 1, 2, 3), 'plain': (-1, -1, -1, -1)}[
                          scheme]
    other = num_chunk_types

    inf = ins['Inference']
    lab = ins['Label']
    if inf.ndim == 3:
        inf = inf[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    B, T = inf.shape
    lens = ins.get('SeqLength')
    if lens is None:
        lens = jnp.full((B,), T, jnp.int32)
    lens = lens.reshape(B).astype(jnp.int32)
    valid = jnp.arange(T)[None, :] < lens[:, None]          # [B, T]

    def marks(tags):
        ctype = jnp.where(valid, tags // n_tag, other)
        ttype = tags % n_tag
        # shift: position 0 sees prev_type = other
        pt = jnp.concatenate([jnp.full((B, 1), other), ctype[:, :-1]], 1)
        ptag = jnp.concatenate([jnp.full((B, 1), -1), ttype[:, :-1]], 1)
        is_other = ctype == other
        prev_other = pt == other
        # ChunkBegin(prev, cur) rule table (see reference chunk_eval_op.h)
        begin = jnp.where(
            prev_other, ~is_other,
            jnp.where(is_other, False,
                      jnp.where(ctype != pt, True,
                                (ttype == tb) | (ttype == ts) |
                                (((ttype == ti) | (ttype == te)) &
                                 ((ptag == te) | (ptag == ts))))))
        # ChunkEnd(cur, next): close at i when the i+1 transition says so
        nt = jnp.concatenate([ctype[:, 1:], jnp.full((B, 1), other)], 1)
        ntag = jnp.concatenate([ttype[:, 1:], jnp.full((B, 1), -1)], 1)
        end = jnp.where(
            is_other, False,
            jnp.where(nt == other, True,
                      jnp.where(nt != ctype, True,
                                (ttype == te) | (ttype == ts) |
                                (((ttype == tb) | (ttype == ti)) &
                                 ((ntag == tb) | (ntag == ts))))))
        begin = begin & valid
        end = end & valid
        # chunk start position aligned to each index: running max of
        # begin-marked indices
        idx = jnp.arange(T)[None, :]
        start_of = jax.lax.cummax(jnp.where(begin, idx, -1), axis=1)
        keep = jnp.ones((B, T), bool)
        for ex in excluded:
            keep = keep & (ctype != ex)
        return begin & keep, end & keep, ctype, start_of

    ib, ie, it, istart = marks(inf.astype(jnp.int32))
    lb, le, lt, lstart = marks(lab.astype(jnp.int32))
    num_inf = ib.sum()
    num_lab = lb.sum()
    correct = (ie & le & (istart == lstart) & (it == lt)).sum()

    num_inf_f = num_inf.astype(jnp.float32)
    num_lab_f = num_lab.astype(jnp.float32)
    cor_f = correct.astype(jnp.float32)
    precision = jnp.where(num_inf_f > 0, cor_f / num_inf_f, 0.0)
    recall = jnp.where(num_lab_f > 0, cor_f / num_lab_f, 0.0)
    f1 = jnp.where(precision + recall > 0,
                   2 * precision * recall / (precision + recall), 0.0)
    i64 = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return {'Precision': precision.reshape(1),
            'Recall': recall.reshape(1),
            'F1-Score': f1.reshape(1),
            'NumInferChunks': num_inf.astype(i64).reshape(1),
            'NumLabelChunks': num_lab.astype(i64).reshape(1),
            'NumCorrectChunks': correct.astype(i64).reshape(1)}


@register('edit_distance')
def edit_distance(ctx, ins, attrs):
    """Levenshtein distance between hypothesis and reference id sequences.

    Parity: reference operators/edit_distance_op (CPU/GPU DP kernels).
    TPU-native: one lax.scan over hypothesis rows; within a row the
    d[i][j-1] dependency is folded into a prefix-min —
    row[j] = j + cummin_j(f[j] - j) with f = min(prev+1, shift(prev)+cost)
    — so each row is a fused vector op instead of a scalar inner loop.
    """
    hyps = ins['Hyps']
    refs = ins['Refs']
    if hyps.ndim == 3:
        hyps = hyps[..., 0]
    if refs.ndim == 3:
        refs = refs[..., 0]
    B, Th = hyps.shape
    Tr = refs.shape[1]
    hl = ins.get('HypsLength')
    rl = ins.get('RefsLength')
    hl = (jnp.full((B,), Th, jnp.int32) if hl is None
          else hl.reshape(B).astype(jnp.int32))
    rl = (jnp.full((B,), Tr, jnp.int32) if rl is None
          else rl.reshape(B).astype(jnp.int32))
    normalized = attrs.get('normalized', True)
    ignored = attrs.get('ignored_tokens') or []

    def squeeze_ignored(seq, length):
        if not ignored:
            return seq, length
        keep = jnp.ones(seq.shape, bool)
        for t in ignored:
            keep = keep & (seq != t)
        keep = keep & (jnp.arange(seq.shape[0]) < length)
        idx = jnp.argsort(~keep, stable=True)  # kept tokens first, in order
        return seq[idx], keep.sum().astype(jnp.int32)

    def one(h, r, hlen, rlen):
        h, hlen = squeeze_ignored(h, hlen)
        r, rlen = squeeze_ignored(r, rlen)
        j = jnp.arange(Tr + 1)
        row0 = j.astype(jnp.int32)

        def step(prev, hi):
            cost = jnp.where(hi == r, 0, 1).astype(jnp.int32)  # [Tr]
            diag = prev[:-1] + cost
            up = prev[1:] + 1
            f = jnp.concatenate([(prev[:1] + 1), jnp.minimum(diag, up)])
            row = jax.lax.cummin(f - row0) + row0
            return row, row

        _, rows = jax.lax.scan(step, row0, h)
        all_rows = jnp.concatenate([row0[None], rows])     # [Th+1, Tr+1]
        d = all_rows[hlen, rlen].astype(jnp.float32)
        if normalized:
            d = d / jnp.maximum(rlen.astype(jnp.float32), 1.0)
        return d

    out = jax.vmap(one)(hyps.astype(jnp.int32), refs.astype(jnp.int32),
                        hl, rl)
    i64 = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return {'Out': out.reshape(B, 1),
            'SequenceNum': jnp.asarray([B], i64)}
