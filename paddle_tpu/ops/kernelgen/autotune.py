"""Persistent tile/block autotuner for the kernelgen tier.

On first compile of a (kernel kind, signature) pair the builder asks
``choose()`` for a block config.  The search is bounded — each call site
hands in a pre-filtered candidate list (a handful of block bases or row
counts, deduped by *effective* config) — and runs under real timing:
one warmup + best-of-N wall-clock executions per candidate, with inputs
synthesized FRESH for every run so kernels that donate their buffers
(``input_output_aliases``) never time against an already-consumed arg.

The winner persists in the PR-3 AOT disk cache directory
(``compile_cache.cache_dir()/autotune/<sha256>.json``) keyed by the
signature plus ``kernelgen.fingerprint_extra()``, so a fleet tunes once
and every later process starts warm.  Lookup order per signature:

  in-process memo  ->  disk (counts ``kernelgen.autotune_cache_hits``)
  ->  timed search (counts ``kernelgen.autotune_searches``)

Knobs (docs/kernels.md):

``PT_AUTOTUNE``
    ``1`` (default) search on miss; ``cached`` use memo/disk only and
    fall back to the static default on miss (never search — fleet
    followers); ``0`` tier runs entirely on the static
    ``PT_KERNELGEN_BLOCK`` default.
``PT_AUTOTUNE_SIZE_CAP``
    Max flat lane count a segment may have before the *interpret-mode*
    (CPU emulation) search is skipped — the interpreter pays per grid
    step, so timing (and even compiling) a megabyte-scale group costs
    minutes, far more than any block choice could save.  Default
    ``1 << 16``.  Real-TPU searches ignore the cap.

Failures are loud-but-soft: a candidate that raises is warned about and
dropped; if every candidate fails, ``choose()`` warns and returns the
static default (the tier keeps running untuned rather than falling back
to the replay path).
"""
import json
import os
import time

__all__ = ['mode', 'choose', 'clear_memory', 'interpret_size_cap',
           'synth_value', 'time_thunk']

_MEM = {}


def mode():
    v = os.environ.get('PT_AUTOTUNE', '1')
    return v if v in ('0', '1', 'cached') else '1'


def interpret_size_cap():
    return int(os.environ.get('PT_AUTOTUNE_SIZE_CAP', str(1 << 16)))


def clear_memory():
    """Drop the in-process memo (tests: force disk/search re-resolution)."""
    _MEM.clear()


def _warn(msg):
    import warnings
    warnings.warn('kernelgen autotune: %s' % msg, stacklevel=3)


def _counter(name):
    from ...observability import metrics
    return metrics.counter(name)


def _sig_key(kind, signature):
    """Stable digest: the signature plus the tier fingerprint, so a rule
    table / version change invalidates every persisted choice exactly
    like it invalidates the AOT executables."""
    import hashlib
    from . import fingerprint_extra
    blob = repr((kind, signature, fingerprint_extra()))
    return hashlib.sha256(blob.encode('utf-8')).hexdigest()[:32]


def _autotune_dir():
    from ...core import compile_cache
    return os.path.join(compile_cache.cache_dir(), 'autotune')


def _disk_load(path):
    from ...core import compile_cache
    if not compile_cache.disk_enabled():
        return None
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    choice = rec.get('choice')
    return choice if isinstance(choice, dict) else None


def _disk_store(path, kind, signature, choice, timings):
    from ...core import compile_cache
    if not compile_cache.disk_enabled():
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = '%s.tmp.%d' % (path, os.getpid())
        with open(tmp, 'w') as f:
            json.dump({'kind': kind, 'signature': repr(signature),
                       'choice': choice, 'timings_ms': timings}, f,
                      sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        _warn('could not persist %s choice (%s)' % (kind, e))


def time_thunk(thunk, warmup=1, runs=2):
    """Best-of-``runs`` wall seconds of ``thunk()`` (blocked to ready).
    The thunk must synthesize its own inputs per call — donated buffers
    are consumed by each execution."""
    import jax
    best = None
    for i in range(warmup + runs):
        t0 = time.perf_counter()
        out = thunk()
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if i >= warmup and (best is None or dt < best):
            best = dt
    return best


def synth_value(shape, dtype):
    """A benign concrete array for timing runs: mid-range floats (no
    overflow through exp/log chains), ones for int/bool (valid masks and
    lengths)."""
    import numpy as np
    import jax.numpy as jnp
    dt = np.dtype(dtype)
    if dt.kind in 'iub':
        return jnp.asarray(np.ones(shape, dt))
    return jnp.asarray(np.full(shape, 0.5, dt))


def choose(kind, signature, candidates, timer, default, allow_search):
    """Resolve the block config for one (kind, signature) pair.

    ``candidates`` is a non-empty list of JSON-plain dicts; ``timer`` is
    ``cand -> seconds`` (may raise — the candidate is dropped);
    ``default`` is returned whenever no search happens and nothing is
    cached.  ``allow_search=False`` callers (the lint abstract
    interpreter, which reaches plan building under ``eval_shape``) never
    time anything.
    """
    m = mode()
    if m == '0' or not candidates:
        return default
    key = _sig_key(kind, signature)
    hit = _MEM.get(key)
    if hit is not None:
        return hit
    path = os.path.join(_autotune_dir(), key + '.json')
    disk = _disk_load(path)
    if disk is not None:
        _MEM[key] = disk
        _counter('kernelgen.autotune_cache_hits').inc()
        return disk
    if len(candidates) == 1:
        # nothing to search; memoize (skip the disk stat next time) but
        # don't count a search that never ran, don't persist
        _MEM[key] = candidates[0]
        return candidates[0]
    if m == 'cached' or not allow_search:
        return default
    _counter('kernelgen.autotune_searches').inc()
    best, best_t, timings = None, None, {}
    for cand in candidates:
        try:
            t = timer(cand)
        except Exception as e:     # noqa: BLE001 — drop, loudly
            _warn('%s candidate %r failed (%s: %s)'
                  % (kind, cand, type(e).__name__, e))
            continue
        timings[repr(sorted(cand.items()))] = round(t * 1e3, 4)
        if best_t is None or t < best_t:
            best, best_t = cand, t
    if best is None:
        _warn('every %s candidate failed — using the static '
              'PT_KERNELGEN_BLOCK default' % kind)
        return default
    _MEM[key] = best
    _disk_store(path, kind, signature, best, timings)
    return best
